"""sheepscope receipts (ISSUE 17): span emission + kill switch, trace
context riding PUSH/WEIGHTS frame meta, NTP-style clock sync, the
sender-monotonic heartbeat age, role telemetry shards, and the PROFILE
frame answered by a live ReplayService."""

import json
import os
import struct
import time

import numpy as np
import pytest

from sheeprl_tpu.flock import wire
from sheeprl_tpu.flock.service import (
    PROTO_VERSION,
    ReplayService,
    _ActorState,
    pack_push,
    unpack_push,
)
from sheeprl_tpu.telemetry import Telemetry
from sheeprl_tpu.telemetry.trace import ClockSync, Tracer


class _Recorder:
    """Telemetry stand-in that records events and exposes a live tracer."""

    enabled = True

    def __init__(self):
        self.events = []

    def event(self, name, /, **data):
        self.events.append((name, data))

    @property
    def tracer(self):
        return Tracer(self)

    def of(self, name):
        return [d for n, d in self.events if n == name]


# ---------------------------------------------------------------------------
# tracer + kill switch
# ---------------------------------------------------------------------------


def test_tracer_spans_and_points():
    rec = _Recorder()
    tracer = Tracer(rec)
    span = tracer.begin("collect", actor=1)
    assert span is not None and len(span.id) == 8
    cid = tracer.end(span, rows=4)
    assert cid == span.id
    pid = tracer.point("ingest", parent=cid, actor=1)
    spans = rec.of("span")
    assert [s["name"] for s in spans] == ["collect", "ingest"]
    collect, ingest = spans
    assert collect["parent"] is None and collect["actor"] == 1
    assert collect["rows"] == 4 and collect["t1"] >= collect["t0"]
    assert ingest["parent"] == cid and ingest["span"] == pid
    # a point with t0 covers [t0, now]
    t0 = time.time() - 0.5
    tracer.point("drain", t0=t0)
    drain = rec.of("span")[-1]
    assert drain["dur_ms"] >= 400.0


def test_trace_kill_switch(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_TRACE", "0")
    rec = _Recorder()
    tracer = Tracer(rec)
    assert not tracer.enabled
    span = tracer.begin("collect")
    assert span is None
    assert tracer.end(span) is None          # None-tolerant end
    assert tracer.point("ingest") is None
    assert rec.events == []
    # clock events are suppressed too
    clock = ClockSync(rec)
    clock.add(0.0, 10.0, 0.1)
    assert rec.events == []


def test_tracer_disabled_telemetry_is_noop():
    tracer = Telemetry(None, enabled=False).tracer
    assert not tracer.enabled
    assert tracer.begin("x") is None and tracer.point("y") is None


# ---------------------------------------------------------------------------
# trace context on the wire
# ---------------------------------------------------------------------------


def test_pack_push_trace_meta_roundtrip():
    tree = {"obs": np.zeros((2, 1, 3), np.float32)}
    trace = {"span": "deadbeef", "actor": 1, "mono_ts": 12.5}
    ops, meta = unpack_push(
        pack_push([(tree, None)], rows=2, env_steps=2, weight_version=3, trace=trace)
    )
    assert meta["trace"] == trace
    assert len(ops) == 1
    # old peers: no trace argument -> the key is absent entirely
    _, meta2 = unpack_push(
        pack_push([(tree, None)], rows=2, env_steps=2, weight_version=3)
    )
    assert "trace" not in meta2


def test_publish_span_rides_weights_meta():
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=8, telem=None,
    ) as svc:
        addr = svc.start()
        svc.publish([np.zeros(1, np.float32)], span="feedc0de")
        sock = wire.connect(addr, timeout=5.0)
        wire.send_json(
            sock, wire.HELLO,
            {"actor_id": 0, "role": "weights", "proto": PROTO_VERSION},
        )
        wire.send_json(sock, wire.GET_WEIGHTS, {"have_version": -1})
        kind, payload = wire.recv_frame(sock)
        assert kind == wire.WEIGHTS
        (meta_len,) = struct.unpack_from("<I", payload)
        meta = json.loads(payload[4 : 4 + meta_len].decode())
        assert meta == {"version": 1, "span": "feedc0de"}
        # span-less publish (tracing off / old learner): no key
        svc.publish([np.zeros(1, np.float32)])
        wire.send_json(sock, wire.GET_WEIGHTS, {"have_version": 1})
        kind, payload = wire.recv_frame(sock)
        (meta_len,) = struct.unpack_from("<I", payload)
        assert json.loads(payload[4 : 4 + meta_len].decode()) == {"version": 2}
        sock.close()


@pytest.mark.timeout(60)
def test_push_trace_emits_ingest_and_drain_provenance(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="ppo", run_id="r1")
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=8, telem=telem,
    ) as svc:
        addr = svc.start()
        sock = wire.connect(addr, timeout=5.0)
        wire.send_json(
            sock, wire.HELLO,
            {"actor_id": 0, "pid": 1, "role": "data", "proto": PROTO_VERSION},
        )
        wire.recv_json(sock, wire.WELCOME)
        tree = {"obs": np.zeros((5, 1, 3), np.float32)}
        payload = pack_push(
            [(tree, None)], rows=4, env_steps=4, weight_version=2,
            trace={"span": "abcd1234", "actor": 0, "mono_ts": time.monotonic()},
        )
        wire.send_frame(sock, wire.PUSH, payload)
        wire.recv_json(sock, wire.PUSH_OK)
        assert svc.next_chunk(timeout=5.0) is not None
        prov = svc.last_drain
        assert prov is not None and prov["actor"] == 0
        assert prov["weight_version"] == 2
        assert prov["wait_s"] >= 0.0 and prov["queued_s"] >= 0.0
        # the ingest span landed in the learner shard, parented on the
        # actor's push span, and its id is the drain's parent
        telem.close()
        events = [
            json.loads(line)
            for line in (tmp_path / "telemetry.jsonl").read_text().splitlines()
        ]
        ingest = [e for e in events if e.get("event") == "span"]
        assert len(ingest) == 1 and ingest[0]["name"] == "ingest"
        assert ingest[0]["parent"] == "abcd1234"
        assert prov["span"] == ingest[0]["span"]
        # a timed-out drain clears the provenance
        assert svc.next_chunk(timeout=0.05) is None
        assert svc.last_drain is None
        sock.close()


# ---------------------------------------------------------------------------
# clock sync + sender-monotonic heartbeat age
# ---------------------------------------------------------------------------


def test_clock_sync_min_rtt_wins():
    rec = _Recorder()
    clock = ClockSync(rec)
    # server 10s ahead, symmetric 0.2s RTT
    assert clock.add(100.0, 110.1, 100.2)
    assert clock.offset_s == pytest.approx(10.0)
    assert clock.rtt_s == pytest.approx(0.2)
    # worse RTT: ignored
    assert not clock.add(200.0, 210.8, 201.0)
    assert clock.offset_s == pytest.approx(10.0)
    # better RTT: adopted + re-emitted
    assert clock.add(300.0, 310.04, 300.08)
    assert clock.offset_s == pytest.approx(10.0)
    assert clock.rtt_s == pytest.approx(0.08)
    emitted = rec.of("trace.clock")
    assert len(emitted) == 2
    assert emitted[-1]["samples"] == 3


def test_heartbeat_age_uses_sender_monotonic_clock():
    st = _ActorState(0)
    st.last_heartbeat = time.monotonic()
    st.note_sender_mono(1000.0)
    # sender advanced 5s, receiver advanced 5s -> silent for ~0
    st.note_sender_mono(1005.0)
    st.recv_mono0 -= 5.0  # receiver saw 5s pass since the baseline
    now = time.monotonic()
    assert st.heartbeat_age(now) == pytest.approx(0.0, abs=0.1)
    # receiver saw 9 MORE seconds pass with no newer stamp: silent ~9s
    st.recv_mono0 -= 9.0
    assert st.heartbeat_age(now) == pytest.approx(9.0, abs=0.1)
    # a monotonic REGRESSION (actor restarted) re-baselines instead of
    # producing a bogus negative age
    st.note_sender_mono(3.0)
    assert st.sender_mono0 == 3.0
    assert st.heartbeat_age(time.monotonic()) == pytest.approx(0.0, abs=0.1)


def test_heartbeat_age_falls_back_for_old_peers():
    st = _ActorState(0)
    st.last_heartbeat = 100.0
    assert st.heartbeat_age(103.5) == pytest.approx(3.5)
    st.note_sender_mono(None)  # old peer: no stamp, still the fallback
    assert st.heartbeat_age(103.5) == pytest.approx(3.5)


@pytest.mark.timeout(60)
def test_heartbeat_reply_carries_server_wall_ts():
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=8, telem=None,
    ) as svc:
        addr = svc.start()
        sock = wire.connect(addr, timeout=5.0)
        wire.send_json(
            sock, wire.HELLO,
            {"actor_id": 0, "pid": 1, "role": "data", "proto": PROTO_VERSION},
        )
        wire.recv_json(sock, wire.WELCOME)
        before = time.time()
        wire.send_json(
            sock, wire.HEARTBEAT,
            {"env_steps": 8, "weight_version": 0, "sps": 1.0,
             "mono_ts": time.monotonic(), "wall_ts": before},
        )
        reply = wire.recv_json(sock, wire.HEARTBEAT_OK)
        assert before <= reply["server_wall_ts"] <= time.time()
        sock.close()


# ---------------------------------------------------------------------------
# role shards + run id
# ---------------------------------------------------------------------------


def test_role_shard_filenames(tmp_path):
    learner = Telemetry(str(tmp_path), rank=0, algo="ppo", run_id="r1")
    actor = Telemetry(str(tmp_path), rank=0, algo="ppo", role="actor3", run_id="r1")
    serve = Telemetry(str(tmp_path), rank=0, algo="serve", role="serve", run_id="r1")
    learner.event("ping")
    actor.event("ping")
    serve.event("ping")
    for t in (learner, actor, serve):
        t.close()
    assert (tmp_path / "telemetry.jsonl").exists()
    assert (tmp_path / "telemetry.actor3.jsonl").exists()
    assert (tmp_path / "telemetry.serve.jsonl").exists()


def test_ensure_run_id_exports_to_environment(monkeypatch):
    from sheeprl_tpu.telemetry.trace import RUN_ENV, ensure_run_id

    monkeypatch.delenv(RUN_ENV, raising=False)
    rid = ensure_run_id()
    assert rid and len(rid) == 8
    assert os.environ[RUN_ENV] == rid
    assert ensure_run_id() == rid  # idempotent: subprocesses inherit ONE id
    monkeypatch.setenv(RUN_ENV, "fixed123")
    assert ensure_run_id() == "fixed123"


# ---------------------------------------------------------------------------
# PROFILE frame against a live service
# ---------------------------------------------------------------------------


@pytest.mark.timeout(240)
def test_profile_frame_opens_bounded_window(tmp_path):
    from sheeprl_tpu.telemetry.trace import profile_window

    telem = Telemetry(str(tmp_path), rank=0, algo="ppo", run_id="r1")
    try:
        with ReplayService(
            algo="ppo", n_actors=1, mode="chunks", capacity_rows=8, telem=telem,
        ) as svc:
            addr = svc.start()
            # generous socket timeout: jax.profiler's first-ever trace
            # start cold-initializes its infra, which can take >5s on a
            # loaded CI box
            sock = wire.connect(addr, timeout=60.0)
            wire.send_json(sock, wire.PROFILE, {"seconds": 0.05})
            reply = wire.recv_json(sock, wire.PROFILE)
            sock.close()
            assert reply["ok"] is True, reply
            assert reply["dir"].startswith(str(tmp_path)), reply
            assert reply["seconds"] == pytest.approx(0.05)
            # a second request while the window is open is refused, not
            # stacked — the running trace stays intact
            sock = wire.connect(addr, timeout=60.0)
            wire.send_json(sock, wire.PROFILE, {"seconds": 5})
            second = wire.recv_json(sock, wire.PROFILE)
            sock.close()
            # on a fast box the first window is still open -> refused; on
            # a slow one it may already have closed and this opened a
            # real (bounded) second window — both are correct behavior
            if second["ok"] is False:
                assert "already open" in second["error"]
            deadline = time.monotonic() + 30.0
            while profile_window().active and time.monotonic() < deadline:
                time.sleep(0.05)
            profile_window().close()  # idempotent on a closed window
            assert not profile_window().active
            # `active` flips False the moment close() starts, but the
            # timer thread emits profile.window.stop only AFTER
            # jax.profiler.stop_trace finishes dumping the artifact —
            # slow in a hot process. Wait for the event to land before
            # closing the shard.
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if '"profile.window.stop"' in (
                    tmp_path / "telemetry.jsonl"
                ).read_text():
                    break
                time.sleep(0.1)
    finally:
        telem.close()
    events = [
        json.loads(line)
        for line in (tmp_path / "telemetry.jsonl").read_text().splitlines()
    ]
    names = [e.get("event") for e in events]
    assert "profile.window.start" in names, names
    assert "profile.window.stop" in names, names
    start = next(e for e in events if e["event"] == "profile.window.start")
    assert os.path.isdir(start["dir"])


# ---------------------------------------------------------------------------
# overhead bound (ISSUE 17 acceptance: trace overhead <= 2% sps)
# ---------------------------------------------------------------------------


@pytest.mark.timeout(120)
def test_trace_overhead_within_two_percent(tmp_path):
    """The per-update span pattern the flock learner runs (drain point +
    train span + publish point: 3 JSONL lines) must cost <2% of a
    realistically sized update. The pattern costs ~40us on this box
    (fast-path JSON + cached kill switch + lazy span flush), so the bound
    is checked against a ~5ms workload — well under the smallest real
    flock update; the tiny CPU bench configs sit below that floor, which
    is why `bench.py --telemetry ab`'s trace arm reports a larger (noise-
    dominated) percentage there. Interleaved pairs + min-of-ratios, same
    methodology as the telemetry overhead bound."""
    a = np.random.default_rng(0).normal(size=(450, 450))

    def workload():
        return float(np.linalg.norm(a @ a))

    iters = 40
    telem = Telemetry(str(tmp_path), rank=0, algo="overhead")
    tracer = telem.tracer

    def run_plain():
        t0 = time.perf_counter()
        for _ in range(iters):
            workload()
        return time.perf_counter() - t0

    def run_traced():
        t0 = time.perf_counter()
        for u in range(iters):
            drain = tracer.point("drain", update=u)
            span = tracer.begin("train", parent=drain, update=u)
            workload()
            tracer.point("publish", parent=tracer.end(span), version=u)
        return time.perf_counter() - t0

    run_plain(), run_traced()  # warmup both paths
    ratios = [run_traced() / run_plain() for _ in range(6)]
    telem.close()
    overhead = min(ratios) - 1.0
    assert overhead < 0.02, f"trace overhead {overhead:.2%} exceeds 2%"
