"""Flock frame protocol: pickle-free length-prefixed frames (ISSUE 14)."""

import socket
import threading

import numpy as np
import pytest

from sheeprl_tpu.flock import service as service_mod
from sheeprl_tpu.flock import wire


def _pair():
    a, b = socket.socketpair()
    return a, b


def test_frame_roundtrip():
    a, b = _pair()
    try:
        wire.send_frame(a, wire.PUSH, b"payload-bytes")
        kind, payload = wire.recv_frame(b)
        assert kind == wire.PUSH
        assert payload == b"payload-bytes"
        # empty payload is legal (length 0)
        wire.send_frame(a, wire.BYE)
        assert wire.recv_frame(b) == (wire.BYE, b"")
    finally:
        a.close()
        b.close()


def test_json_roundtrip_and_expected_kind():
    a, b = _pair()
    try:
        wire.send_json(a, wire.HELLO, {"actor_id": 3, "proto": 1})
        msg = wire.recv_json(b, wire.HELLO)
        assert msg == {"actor_id": 3, "proto": 1}
        wire.send_json(a, wire.HEARTBEAT, {})
        with pytest.raises(wire.FrameError, match="expected push"):
            wire.recv_json(b, wire.PUSH)
    finally:
        a.close()
        b.close()


def test_error_frame_raises():
    a, b = _pair()
    try:
        wire.send_json(a, wire.ERROR, {"error": "boom"})
        with pytest.raises(wire.FrameError, match="boom"):
            wire.recv_json(b, wire.WELCOME)
    finally:
        a.close()
        b.close()


def test_bad_magic_and_oversize_length():
    a, b = _pair()
    try:
        a.sendall(b"NOPE" + bytes(12))
        with pytest.raises(wire.FrameError, match="magic") as exc:
            wire.recv_frame(b)
        # the message names the offending bytes AND the expected magic —
        # the difference between "corrupt frame" and "wrong port" in a log
        assert "b'NOPE'" in str(exc.value)
        assert repr(wire.MAGIC) in str(exc.value)
    finally:
        a.close()
        b.close()
    a, b = _pair()
    try:
        a.sendall(
            wire._HEADER.pack(wire.MAGIC, wire.PUSH, 0, 0, wire.MAX_FRAME_BYTES + 1)
        )
        with pytest.raises(wire.FrameError, match="exceeds cap"):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_clean_eof_is_none_mid_frame_raises():
    a, b = _pair()
    a.close()
    try:
        assert wire.recv_frame(b) is None  # EOF at a frame boundary
    finally:
        b.close()
    a, b = _pair()
    try:
        a.sendall(wire._HEADER.pack(wire.MAGIC, wire.PUSH, 0, 0, 100) + b"short")
        a.close()
        with pytest.raises(wire.FrameError, match="closed"):
            wire.recv_frame(b)
    finally:
        b.close()


def test_address_roundtrip():
    assert wire.parse_address(wire.format_address("tcp", "127.0.0.1", 4242)) == (
        "tcp",
        "127.0.0.1",
        4242,
    )
    assert wire.parse_address(wire.format_address("unix", "/tmp/x.sock")) == (
        "unix",
        "/tmp/x.sock",
    )
    with pytest.raises(ValueError):
        wire.parse_address("carrier-pigeon:coop7")


def test_connect_tcp_and_unix(tmp_path):
    # tcp
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    addr = wire.format_address("tcp", "127.0.0.1", srv.getsockname()[1])
    got = {}

    def _accept():
        conn, _ = srv.accept()
        got["frame"] = wire.recv_frame(conn)
        conn.close()

    t = threading.Thread(target=_accept, name="test-accept-tcp", daemon=True)
    t.start()
    c = wire.connect(addr, timeout=5.0)
    wire.send_frame(c, wire.HELLO, b"hi")
    c.close()
    t.join(timeout=5.0)
    srv.close()
    assert got["frame"] == (wire.HELLO, b"hi")

    # unix
    path = str(tmp_path / "svc.sock")
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(path)
    srv.listen(1)
    t = threading.Thread(target=_accept, name="test-accept-unix", daemon=True)
    t.start()
    c = wire.connect(wire.format_address("unix", path), timeout=5.0)
    wire.send_frame(c, wire.HELLO, b"hi")
    c.close()
    t.join(timeout=5.0)
    srv.close()
    assert got["frame"] == (wire.HELLO, b"hi")


def test_push_payload_roundtrip_bit_exact():
    """pack_push/unpack_push carry trees through data/wire.py packing:
    bit-exact floats (NaN payloads included) and exact indices metadata."""
    rng = np.random.default_rng(7)
    tree_a = {
        "rgb": rng.integers(0, 255, (4, 2, 3), dtype=np.uint8),
        "rewards": np.array([[np.nan], [1.5]], np.float32),
    }
    tree_b = {"dones": np.ones((1, 2, 1), np.float32)}
    payload = service_mod.pack_push(
        [(tree_a, None), (tree_b, [0, 1])],
        rows=4,
        env_steps=123,
        weight_version=9,
    )
    ops, meta = service_mod.unpack_push(payload)
    assert meta == {"rows": 4, "env_steps": 123, "weight_version": 9}
    assert len(ops) == 2
    out_a, idx_a = ops[0]
    assert idx_a is None
    np.testing.assert_array_equal(out_a["rgb"], tree_a["rgb"])
    assert out_a["rewards"].tobytes() == tree_a["rewards"].tobytes()  # NaN-safe
    out_b, idx_b = ops[1]
    assert idx_b == [0, 1]
    np.testing.assert_array_equal(out_b["dones"], tree_b["dones"])


# ---------------------------------------------------------------------------
# frame-kind registry (ISSUE 15 satellite): the kind byte is a wire-format
# contract — committed values may NEVER be renumbered, and new kinds must
# register without touching old ones
# ---------------------------------------------------------------------------

PINNED_KINDS = {
    # flock (PR 14)
    "hello": 1,
    "welcome": 2,
    "push": 3,
    "push_ok": 4,
    "heartbeat": 5,
    "heartbeat_ok": 6,
    "get_weights": 7,
    "weights": 8,
    "weights_unchanged": 9,
    "bye": 10,
    "error": 11,
    # serving tier (PR 15)
    "request": 12,
    "response": 13,
    "shed": 14,
    "reload": 15,
    # 16 = "health" is registered by serve/server.py at import time
    # sheepscope (ISSUE 17)
    "profile": 17,
    # flock scale-out (ISSUE 19)
    "shm_attach": 18,
    "relay_hello": 19,
    "push_batch": 20,
    "relay_fwd": 21,
}


def test_frame_kind_values_are_pinned():
    """Regression pin: adding a frame kind must not renumber existing
    ones. If this fails, a wire-format break shipped — fix the numbers,
    not this test."""
    for name, value in PINNED_KINDS.items():
        assert getattr(wire, name.upper()) == value, name
        assert wire.KIND_NAMES[value] == name


def test_register_kind_rejects_collisions():
    with pytest.raises(ValueError):
        wire.register_kind(wire.HELLO, "not-hello")  # value taken
    with pytest.raises(ValueError):
        wire.register_kind(200, "hello")  # name taken by another value
    with pytest.raises(ValueError):
        wire.register_kind(0, "zero")  # out of u8 range
    with pytest.raises(ValueError):
        wire.register_kind(256, "too-big")
    # re-registering the same (value, name) pair is idempotent
    assert wire.register_kind(wire.HELLO, "hello") == wire.HELLO


def test_serve_frames_travel_like_flock_frames():
    a, b = _pair()
    try:
        wire.send_json(a, wire.SHED, {"id": 4, "retry_after_ms": 12.5})
        kind, payload = wire.recv_frame(b)
        assert kind == wire.SHED
        wire.send_frame(a, wire.REQUEST, b"\x01\x02")
        assert wire.recv_frame(b) == (wire.REQUEST, b"\x01\x02")
    finally:
        a.close()
        b.close()


def test_corrupt_magic_constant_is_not_magic():
    """The fault injector's corruption pattern is a named constant, and it
    must stay distinguishable from a real frame."""
    assert wire.CORRUPT_MAGIC == b"XXXX"
    assert len(wire.CORRUPT_MAGIC) == len(wire.MAGIC)
    assert wire.CORRUPT_MAGIC != wire.MAGIC


def test_profile_frame_roundtrip():
    """The sheepscope PROFILE kind (17) travels like any JSON frame."""
    a, b = _pair()
    try:
        wire.send_json(a, wire.PROFILE, {"seconds": 1.5})
        assert wire.recv_json(b, wire.PROFILE) == {"seconds": 1.5}
        wire.send_json(a, wire.PROFILE, {"ok": True, "dir": "/tmp/x"})
        assert wire.recv_json(b, wire.PROFILE)["ok"] is True
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# relay codecs (ISSUE 19): PUSH payloads must survive the aggregation hop
# verbatim — shard bytes and sheepscope trace context are bit-equal after
# pack/unpack, whatever binary junk they contain
# ---------------------------------------------------------------------------


def test_relay_fwd_roundtrip():
    inner = b"\x00\xffhello\x00" * 7
    blob = wire.pack_relay_fwd(42, wire.HEARTBEAT, inner)
    aid, kind, payload = wire.unpack_relay_fwd(blob)
    assert (aid, kind) == (42, wire.HEARTBEAT)
    assert payload == inner  # verbatim, not re-encoded


def test_push_batch_roundtrip_preserves_payloads_verbatim():
    items = [
        (0, b""),
        (3, bytes(range(256))),
        (7, b"\x00" * 1024),
    ]
    blob = wire.pack_push_batch(items)
    assert wire.unpack_push_batch(blob) == items


def test_push_batch_rejects_truncation():
    blob = wire.pack_push_batch([(1, b"abc"), (2, b"defg")])
    with pytest.raises(wire.FrameError):
        wire.unpack_push_batch(blob[:-1])
    with pytest.raises(wire.FrameError):
        wire.unpack_push_batch(blob + b"\x00")
