"""Hierarchical actor aggregation (ISSUE 19 tentpole c): the relay hop
must be invisible to actors (same protocol) and to the learner
(membership, rejoin receipts and weight versions unchanged), while
collapsing N actor connections into one batched upstream. Chaos: a
partitioned or killed relay heals without losing the learner."""

import os
import time

import numpy as np
import pytest

from sheeprl_tpu.flock import relay as relay_mod
from sheeprl_tpu.flock import wire
from sheeprl_tpu.flock.actor import ResilientLink, _ServiceLink
from sheeprl_tpu.flock.relay import Relay
from sheeprl_tpu.flock.service import PROTO_VERSION, ReplayService, pack_push
from sheeprl_tpu.resilience import inject

from .test_service import _FakeActor, _Recorder, _chunk, _wait_events


@pytest.fixture(autouse=True)
def _fresh_plan(monkeypatch):
    monkeypatch.delenv(inject.ENV_VAR, raising=False)
    monkeypatch.delenv(inject.SEED_VAR, raising=False)
    inject.reset_plan()
    wire._partition_until = 0.0
    yield
    inject.reset_plan()
    wire._partition_until = 0.0


def _arm(monkeypatch, text):
    monkeypatch.setenv(inject.ENV_VAR, text)
    inject.reset_plan()
    return inject.get_plan()


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.01)


def _service(rec, n_actors=4):
    return ReplayService(
        algo="ppo", n_actors=n_actors, mode="chunks", capacity_rows=64,
        telem=rec,
    )


@pytest.mark.timeout(60)
def test_relay_batches_pushes_and_forwards_membership(monkeypatch):
    # widen the coalescing dwell so a loaded box can't spread 6 pushes
    # into 6 singleton flushes — the batching assertion stays exact
    monkeypatch.setattr(relay_mod, "FLUSH_S", 0.3)
    rec = _Recorder()
    with _service(rec) as svc:
        addr = svc.start()
        with Relay(upstream=addr, relay_id=0) as relay:
            raddr = relay.start()
            # actors speak the UNMODIFIED protocol to the relay
            a0, a1 = _FakeActor(raddr, 0), _FakeActor(raddr, 1)
            assert a0.welcome["shard_capacity"] == 64
            _wait_events(rec, "flock.relay_joined")
            _wait_events(rec, "flock.actor_joined", n=2)
            for _ in range(3):
                a0.push(_chunk(1.0), rows=4)
                a1.push(_chunk(2.0), rows=4)
            _wait(lambda: svc.rows_total() == 24, msg="forwarded rows")
            # batched: 6 pushes crossed upstream in < 6 PUSH_BATCH frames
            gauges = relay.gauges()
            assert gauges["Flock/relay/forwarded"] == 6.0
            assert gauges["Flock/relay/batches"] < 6.0
            assert gauges["Flock/relay/members"] == 2.0
            assert svc.gauges()["Flock/transport/relay_batches"] >= 1.0
            # learner-side liveness comes from forwarded heartbeats
            hb = a0.heartbeat(
                actor_id=0, env_steps=12, weight_version=0, sps=1.0,
                mono_ts=time.monotonic(), wall_ts=time.time(),
            )
            assert "random_phase" in hb
            assert svc.actors_alive() == 2
            a0.bye()
            a1.bye()
            _wait(lambda: svc.actors_alive() == 0, msg="BYE forwarding")


@pytest.mark.timeout(60)
def test_relay_weight_cache_serves_the_learners_exact_frame():
    rec = _Recorder()
    with _service(rec) as svc:
        addr = svc.start()
        svc.publish([np.arange(6, dtype=np.float32)])
        with Relay(upstream=addr, relay_id=0) as relay:
            raddr = relay.start()
            ws = wire.connect(raddr, timeout=5.0)
            try:
                wire.send_json(ws, wire.HELLO, {
                    "actor_id": 0, "pid": 1, "role": "weights",
                    "proto": PROTO_VERSION,
                })
                got = None
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    wire.send_json(ws, wire.GET_WEIGHTS, {"have_version": -1})
                    kind, payload = wire.recv_frame(ws)
                    if kind == wire.WEIGHTS:
                        got = payload
                        break
                    time.sleep(0.05)
                # ONE cached snapshot per version, byte-identical to the
                # learner's frame — N actors cost one upstream transfer
                assert got == svc._weight_payload
                wire.send_json(ws, wire.GET_WEIGHTS, {"have_version": 1})
                kind, _ = wire.recv_frame(ws)
                assert kind == wire.WEIGHTS_UNCHANGED
            finally:
                ws.close()


@pytest.mark.timeout(60)
def test_shm_attach_through_relay_reaches_the_learner():
    """A colocated actor rides a shared-memory ring INTO its relay; the
    payload then crosses upstream in a PUSH_BATCH — both scale-out
    transports compose."""
    rec = _Recorder()
    with _service(rec) as svc:
        addr = svc.start()
        with Relay(upstream=addr, relay_id=0, telem=rec) as relay:
            raddr = relay.start()
            link = _ServiceLink(raddr, 0, timeout=5.0, use_shm=True)
            reply = link.push(
                [(_chunk(1.0), None)], rows=4, env_steps=4, weight_version=0
            )
            assert reply.get("shm") is True
            _wait_events(rec, "flock.shm_attached")
            _wait(lambda: svc.rows_total() == 4, msg="shm->relay->learner")
            assert svc.gauges()["Flock/transport/relay_frames"] == 1.0
            link.close()


@pytest.mark.timeout(60)
def test_net_partition_on_relay_upstream_heals_and_rehellos(monkeypatch):
    """Chaos satellite: net.partition fired on the relay's upstream send.
    The relay redials through the partition window, re-HELLOs its
    members (the learner sees the rejoin), and the batch that hit the
    partition is retried on the fresh connection — rows land."""
    rec = _Recorder()
    with _service(rec) as svc:
        addr = svc.start()
        with Relay(upstream=addr, relay_id=0) as relay:
            raddr = relay.start()
            a0 = _FakeActor(raddr, 0)
            a0.push(_chunk(1.0), rows=4)
            _wait(lambda: svc.rows_total() == 4, msg="pre-partition push")
            # armed now: the next frame send is the forwarder's PUSH_BATCH
            # (enqueued directly so no downstream reply races the counter)
            _arm(monkeypatch, "net.partition@1:0.5")
            payload = pack_push(
                [(_chunk(2.0), None)], rows=4, env_steps=8, weight_version=0
            )
            relay._enqueue(0, payload)
            _wait(
                lambda: svc.rows_total() == 8,
                timeout=20.0,
                msg="post-partition batch retry",
            )
            assert inject.counters().get("Fault/net.partition") == 1.0
            # the redial re-registered the member: learner-side rejoin
            _wait_events(rec, "flock.actor_rejoined")
            _wait_events(rec, "flock.relay_disconnected")
            assert rec.names().count("flock.relay_joined") == 2
            # the actor's own connection never noticed
            assert a0.push(_chunk(3.0), rows=4)["rows_total"] >= 8
            _wait(lambda: svc.rows_total() == 12, msg="post-heal push")
            a0.bye()


@pytest.mark.timeout(90)
def test_relay_death_and_respawn_at_same_address_preserves_rejoin(tmp_path):
    """The peer-crash shape on a relay: the process dies, a replacement
    binds the SAME address (launcher contract), and the actors'
    ResilientLink backoff carries their next push through the new hop —
    learner keeps serving throughout, rejoin receipts fire."""
    rec = _Recorder()
    bind = f"unix:{tmp_path}/r0.sock"
    with _service(rec) as svc:
        addr = svc.start()
        relay1 = Relay(upstream=addr, relay_id=0, bind=bind)
        relay1.start()
        link = ResilientLink(bind, 0, timeout=5.0)
        link.push(
            [(_chunk(1.0), None)], rows=4, env_steps=4, weight_version=0
        )
        _wait(lambda: svc.rows_total() == 4, msg="push via relay1")
        relay1.close()  # the "SIGKILL": downstream conns die with it
        # learner is UNHARMED: a directly-connected actor still lands
        direct = _FakeActor(addr, 1)
        assert direct.push(_chunk(9.0), rows=4)["rows_total"] == 8
        # replacement binds the same path (what ActorFleet's respawn does)
        relay2 = Relay(upstream=addr, relay_id=0, bind=bind)
        relay2.start()
        # the actor's next push reconnects through the new relay
        link.push(
            [(_chunk(2.0), None)], rows=4, env_steps=8, weight_version=0
        )
        _wait(lambda: svc.rows_total() == 12, msg="push via relay2")
        _wait_events(rec, "flock.actor_rejoined")
        link.close()
        direct.bye()
        relay2.close()


def test_launcher_topology_maps_actors_to_relays(tmp_path):
    """`--relays R`: actor i dials relay i % R; R is clamped to the actor
    count; R=0 keeps the direct topology."""
    from sheeprl_tpu.algos.args import StandardArgs
    from sheeprl_tpu.flock.launcher import ActorFleet

    args = StandardArgs(flock="4", relays=2)
    fleet = ActorFleet(
        algo="ppo", args=args, address="unix:/tmp/svc.sock",
        log_dir=str(tmp_path / "run"),
    )
    assert fleet.n_relays == 2
    assert fleet._actor_address(0) == fleet._relay_addrs[0]
    assert fleet._actor_address(1) == fleet._relay_addrs[1]
    assert fleet._actor_address(2) == fleet._relay_addrs[0]
    assert fleet._actor_address(3) == fleet._relay_addrs[1]
    # every bind is a unix path under the AF_UNIX length cap
    for a in fleet._relay_addrs.values():
        assert a.startswith("unix:") and len(a) - 5 < 100
    fleet.close()

    direct = ActorFleet(
        algo="ppo", args=StandardArgs(flock="2"),
        address="unix:/tmp/svc.sock", log_dir=str(tmp_path / "d"),
    )
    assert direct.n_relays == 0
    assert direct._actor_address(1) == "unix:/tmp/svc.sock"
    direct.close()

    clamped = ActorFleet(
        algo="ppo",
        args=StandardArgs(flock="2", relays=8),
        address="unix:/tmp/svc.sock", log_dir=str(tmp_path / "c"),
    )
    assert clamped.n_relays == 2  # never more relays than actors
    clamped.close()


def test_relays_arg_validation():
    from sheeprl_tpu.algos.args import StandardArgs

    with pytest.raises(ValueError, match="relays"):
        StandardArgs(relays=-1)
    with pytest.raises(ValueError, match="relays"):
        StandardArgs(relays="two")
    assert StandardArgs(relays="3").relays == 3
