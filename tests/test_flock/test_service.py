"""ReplayService behavior over real sockets: membership, elastic rejoin,
chunk draining, buffer sampling, weight distribution, gauges (ISSUE 14)."""

import json
import struct

import numpy as np
import pytest

from sheeprl_tpu.data.wire import unpack_leaves
from sheeprl_tpu.flock import wire
from sheeprl_tpu.flock.service import PROTO_VERSION, ReplayService, pack_push


class _Recorder:
    """Stands in for the learner Telemetry: records service events."""

    def __init__(self):
        self.events = []

    def event(self, name, **data):
        self.events.append((name, data))

    def names(self):
        return [n for n, _ in self.events]


class _FakeActor:
    """Speaks the data-connection protocol from the test thread."""

    def __init__(self, address, actor_id):
        self.sock = wire.connect(address, timeout=5.0)
        wire.send_json(
            self.sock,
            wire.HELLO,
            {"actor_id": actor_id, "pid": 123, "role": "data", "proto": PROTO_VERSION},
        )
        self.welcome = wire.recv_json(self.sock, wire.WELCOME)

    def push(self, tree, *, rows, env_steps=0, weight_version=0, indices=None):
        payload = pack_push(
            [(tree, indices)],
            rows=rows,
            env_steps=env_steps,
            weight_version=weight_version,
        )
        wire.send_frame(self.sock, wire.PUSH, payload)
        return wire.recv_json(self.sock, wire.PUSH_OK)

    def heartbeat(self, **hb):
        wire.send_json(self.sock, wire.HEARTBEAT, hb)
        return wire.recv_json(self.sock, wire.HEARTBEAT_OK)

    def bye(self):
        wire.send_json(self.sock, wire.BYE, {})
        self.sock.close()


def _chunk(v=0.0, rows=4):
    return {
        "obs": np.full((rows + 1, 1, 3), v, np.float32),
        "dones": np.zeros((rows + 1, 1, 1), np.float32),
    }


def _wait_events(rec, name, n=1, timeout=5.0):
    import time

    deadline = time.monotonic() + timeout
    while rec.names().count(name) < n:
        if time.monotonic() > deadline:
            raise AssertionError(f"never saw {n}x {name}: {rec.names()}")
        time.sleep(0.01)


@pytest.mark.timeout(60)
@pytest.mark.parametrize("transport", ["unix", "tcp"])
def test_membership_join_heartbeat_bye(transport):
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=64,
        transport=transport, telem=rec,
    ) as svc:
        addr = svc.start()
        assert addr.startswith(transport + ":")
        a0 = _FakeActor(addr, 0)
        assert a0.welcome["generation"] == 0
        assert a0.welcome["shard_capacity"] == 64
        assert svc.wait_for_actors(n=1, timeout=5.0)
        assert not svc.wait_for_actors(n=2, timeout=0.2)  # a1 not here yet
        a1 = _FakeActor(addr, 1)
        assert svc.wait_for_actors(timeout=5.0)
        assert svc.actors_alive() == 2
        hb = a1.heartbeat(env_steps=40, weight_version=0, sps=10.0)
        assert hb["weight_version"] == 0
        a0.bye()
        a1.bye()
        _wait_events(rec, "flock.actor_disconnected", n=2)
        assert svc.actors_alive() == 0
    assert rec.names().count("flock.actor_joined") == 2


@pytest.mark.timeout(60)
def test_rejoin_bumps_generation_and_emits_receipt():
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        a = _FakeActor(addr, 0)
        a.push(_chunk(1.0), rows=4)
        a.sock.close()  # simulate SIGKILL: no BYE, just a dead connection
        _wait_events(rec, "flock.actor_disconnected")
        assert svc.actors_alive() == 0
        # respawned process reconnects under the same id
        b = _FakeActor(addr, 0)
        assert b.welcome["generation"] == 1
        assert svc.actors_alive() == 1
        b.bye()
    joined = [n for n in rec.names() if n.startswith("flock.actor_")]
    assert "flock.actor_rejoined" in joined
    assert joined.index("flock.actor_joined") < joined.index("flock.actor_rejoined")


@pytest.mark.timeout(60)
def test_bad_hello_rejected():
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=8, telem=_Recorder(),
    ) as svc:
        addr = svc.start()
        sock = wire.connect(addr, timeout=5.0)
        wire.send_json(
            sock, wire.HELLO, {"actor_id": 7, "role": "data", "proto": PROTO_VERSION}
        )
        with pytest.raises(wire.FrameError, match="bad hello"):
            wire.recv_json(sock, wire.WELCOME)
        sock.close()


@pytest.mark.timeout(60)
def test_chunks_round_robin_and_oldest_dropped():
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=8, telem=rec,
    ) as svc:
        addr = svc.start()
        a0, a1 = _FakeActor(addr, 0), _FakeActor(addr, 1)
        a0.push(_chunk(0.0), rows=4)
        a0.push(_chunk(1.0), rows=4)
        a1.push(_chunk(10.0), rows=4)
        assert svc.rows_total() == 12
        # round-robin drain alternates actors while both have chunks
        vals = [float(svc.next_chunk(timeout=5.0)["obs"][0, 0, 0]) for _ in range(3)]
        assert set(vals) == {0.0, 1.0, 10.0}
        assert vals[:2] in ([0.0, 10.0], [10.0, 0.0])  # one from each first
        assert svc.next_chunk(timeout=0.1) is None
        # queue cap = capacity_rows // rows = 2: a third undrained chunk
        # evicts the OLDEST (on-policy data ages out)
        a0.push(_chunk(2.0), rows=4)
        a0.push(_chunk(3.0), rows=4)
        a0.push(_chunk(4.0), rows=4)
        assert svc.gauges()["Flock/chunks_dropped"] == 1.0
        assert float(svc.next_chunk(timeout=5.0)["obs"][0, 0, 0]) == 3.0
        a0.bye()
        a1.bye()


class _ListShard:
    """Minimal stand-in for a replay buffer shard."""

    def __init__(self, cap):
        self.cap = cap
        self.rows = []

    def add(self, tree, indices=None):
        self.rows.append((tree, indices))

    def sample(self, n, **kw):
        if not self.rows:
            raise ValueError("empty shard")
        return {"x": np.full((n, 1), float(len(self.rows)), np.float32)}

    def to_bytes(self):
        return b""

    @classmethod
    def from_bytes(cls, blob, **kw):
        return cls(0)


@pytest.mark.timeout(60)
def test_buffer_mode_applies_ops_and_partitions_sample():
    rec = _Recorder()
    with ReplayService(
        algo="dreamer_v3", n_actors=2, mode="buffer", capacity_rows=16,
        make_shard=_ListShard, telem=rec,
    ) as svc:
        addr = svc.start()
        a0, a1 = _FakeActor(addr, 0), _FakeActor(addr, 1)
        row = {"x": np.zeros((1, 1, 1), np.float32)}
        a0.push(row, rows=1)
        a0.push(row, rows=1, indices=[0])
        a1.push(row, rows=1)
        # ordered ops landed on the right shards, indices preserved
        assert [idx for _, idx in svc.shard(0).rows] == [None, [0]]
        assert len(svc.shard(1).rows) == 1
        out = svc.sample(4)
        assert out["x"].shape == (4, 1)
        a0.bye()
        a1.bye()


@pytest.mark.timeout(60)
def test_buffer_sample_tops_up_from_serving_shard():
    """A warming-up (empty) shard must not shrink the batch — its slice is
    re-served from a shard that has data; only all-empty raises."""
    with ReplayService(
        algo="dreamer_v3", n_actors=2, mode="buffer", capacity_rows=16,
        make_shard=_ListShard, telem=_Recorder(),
    ) as svc:
        svc.start()
        with pytest.raises(RuntimeError, match="no flock shard"):
            svc.sample(4)
        svc.shard(1).add({"x": np.zeros((1,), np.float32)})
        # shard 0 empty: batch_size=1 would partition [1, 0] — the fallback
        # must find shard 1; batch_size=4 tops shard 0's slice up from 1
        assert svc.sample(1)["x"].shape == (1, 1)
        assert svc.sample(4)["x"].shape == (4, 1)


@pytest.mark.timeout(60)
def test_weights_channel_versioned_pull():
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=8, telem=_Recorder(),
    ) as svc:
        addr = svc.start()
        leaves = [np.arange(6, dtype=np.float32).reshape(2, 3), np.zeros(2, np.int32)]
        assert svc.publish(leaves) == 1
        sock = wire.connect(addr, timeout=5.0)
        wire.send_json(
            sock, wire.HELLO,
            {"actor_id": 0, "role": "weights", "proto": PROTO_VERSION},
        )
        wire.send_json(sock, wire.GET_WEIGHTS, {"have_version": -1})
        kind, payload = wire.recv_frame(sock)
        assert kind == wire.WEIGHTS
        (meta_len,) = struct.unpack_from("<I", payload)
        meta = json.loads(payload[4 : 4 + meta_len].decode())
        assert meta == {"version": 1}
        out = unpack_leaves(payload[4 + meta_len :])
        np.testing.assert_array_equal(out[0], leaves[0])
        np.testing.assert_array_equal(out[1], leaves[1])
        # holding the current version -> no bulk transfer
        wire.send_json(sock, wire.GET_WEIGHTS, {"have_version": 1})
        assert wire.recv_json(sock, wire.WEIGHTS_UNCHANGED) == {"version": 1}
        svc.publish(leaves)
        wire.send_json(sock, wire.GET_WEIGHTS, {"have_version": 1})
        kind, _ = wire.recv_frame(sock)
        assert kind == wire.WEIGHTS
        sock.close()


@pytest.mark.timeout(60)
def test_gauges_track_staleness_and_fill():
    with ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=8, telem=_Recorder(),
    ) as svc:
        addr = svc.start()
        svc.publish([np.zeros(1, np.float32)])  # v1
        a0 = _FakeActor(addr, 0)
        a0.push(_chunk(0.0), rows=4, env_steps=4, weight_version=1)
        g = svc.gauges()
        assert g["Flock/actors_alive"] == 1.0
        assert g["Flock/weight_version"] == 1.0
        assert g["Flock/rows_total"] == 4.0
        assert g["Flock/actor0/version_lag"] == 0.0
        assert g["Flock/actor0/staleness_s"] == 0.0
        assert g["Flock/actor0/shard_fill"] == 0.5  # 1 chunk of cap 2
        assert "Flock/actor1/connected" not in g  # never joined: no row
        svc.publish([np.zeros(1, np.float32)])  # v2: actor 0 now stale
        g = svc.gauges()
        assert g["Flock/actor0/version_lag"] == 1.0
        assert g["Flock/actor0/staleness_s"] >= 0.0
        a0.bye()


@pytest.mark.timeout(60)
def test_sample_remainder_rotates_round_robin_fairly():
    """ISSUE 19 satellite: `plan_partition`'s remainder must rotate across
    shards instead of always topping up the lowest ids — deterministic,
    and over many draws every shard serves the same count to within the
    per-call remainder."""
    with ReplayService(
        algo="dreamer_v3", n_actors=3, mode="buffer", capacity_rows=16,
        make_shard=_ListShard, telem=_Recorder(),
    ) as svc:
        # batch 4 over 3 shards: each call is [2,1,1] in rotation
        draws = {0: 0, 1: 0, 2: 0}
        plans = []
        for _ in range(9):
            plan = svc.plan_partition(4)
            plans.append(plan)
            for aid, n in plan:
                draws[aid] += n
        # deterministic: the same call sequence replans identically
        svc.set_sample_state({"rr": 0, "shards": {}})
        assert [svc.plan_partition(4) for _ in range(9)] == plans
        # fair: 36 rows over 3 shards -> exactly 12 each after 9 calls
        # (remainder 1 rotated 0,1,2,0,1,2,...)
        assert draws == {0: 12, 1: 12, 2: 12}
        # every plan serves the full batch
        assert all(sum(n for _, n in plan) == 4 for plan in plans)


@pytest.mark.timeout(60)
def test_sample_rr_survives_sidecar_roundtrip(tmp_path):
    """The remainder rotation is part of the sample state: crash-resume
    must not reset it (or a restored learner would re-favor shard 0)."""
    ck = str(tmp_path / "ck")
    with ReplayService(
        algo="dreamer_v3", n_actors=3, mode="buffer", capacity_rows=16,
        make_shard=_ListShard, telem=_Recorder(),
    ) as svc:
        svc.start()
        svc.plan_partition(4)  # rr 0 -> 1
        svc.plan_partition(4)  # rr 1 -> 2
        svc.save_sidecar(ck)
    with ReplayService(
        algo="dreamer_v3", n_actors=3, mode="buffer", capacity_rows=16,
        make_shard=_ListShard, telem=_Recorder(),
    ) as svc2:
        assert svc2.restore_sidecar(ck)
        assert svc2.get_sample_state()["rr"] == 2
        # next remainder lands on shard 2, continuing the rotation
        assert svc2.plan_partition(4) == [(0, 1), (1, 1), (2, 2)]
