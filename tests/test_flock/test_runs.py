"""End-to-end flock runs: `--flock off` bit-exactness and short `--flock 2`
CPU runs for both supported algorithms (ISSUE 14 acceptance receipts)."""

import os

import numpy as np
import pytest

from sheeprl_tpu.utils.checkpoint import load_checkpoint
from sheeprl_tpu.utils.registry import tasks
import sheeprl_tpu.algos  # noqa: F401 - fire registrations


def _ppo_argv(tmp_path, run_name, extra=()):
    return [
        "--env_id", "CartPole-v1",
        "--dry_run",
        "--num_envs", "1",
        "--rollout_steps", "8",
        "--per_rank_batch_size", "4",
        "--update_epochs", "1",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--cnn_features_dim", "16",
        "--mlp_features_dim", "8",
        "--root_dir", str(tmp_path),
        "--run_name", run_name,
        *extra,
    ]


def test_flock_flag_validation():
    from sheeprl_tpu.algos.ppo.args import PPOArgs

    with pytest.raises(ValueError, match="flock"):
        PPOArgs(flock="many")
    with pytest.raises(ValueError, match="flock"):
        PPOArgs(flock="0")
    assert PPOArgs(flock="2").flock == "2"
    # actors run host envs: the Anakin backend has no actor processes
    with pytest.raises(ValueError, match="flock"):
        tasks["ppo"](["--flock", "2", "--env_backend", "jax", "--dry_run"])


@pytest.mark.timeout(300)
def test_ppo_flock_off_is_bit_exact_vs_default(tmp_path):
    """The acceptance parity receipt: an explicit --flock off run is
    bitwise-identical to a run with no flag at all — the flock wiring must
    not perturb the in-process path."""
    import jax

    tasks["ppo"](_ppo_argv(tmp_path, "default"))
    tasks["ppo"](_ppo_argv(tmp_path, "flock_off", extra=("--flock", "off")))
    a = load_checkpoint(str(tmp_path / "default" / "checkpoints" / "ckpt_1"))
    b = load_checkpoint(str(tmp_path / "flock_off" / "checkpoints" / "ckpt_1"))
    leaves_a = jax.tree_util.tree_leaves(a["agent"])
    leaves_b = jax.tree_util.tree_leaves(b["agent"])
    assert len(leaves_a) == len(leaves_b)
    for la, lb in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


@pytest.mark.timeout(600)
def test_ppo_flock_two_actors_dry_run(tmp_path):
    tasks["ppo"](_ppo_argv(tmp_path, "flock2", extra=("--flock", "2")))
    ckpt_dir = tmp_path / "flock2" / "checkpoints"
    state = load_checkpoint(str(ckpt_dir / "ckpt_1"))
    assert set(state.keys()) == {"agent", "optimizer", "update_step"}
    telemetry = (tmp_path / "flock2" / "telemetry.jsonl").read_text()
    assert '"flock.started"' in telemetry
    assert telemetry.count('"flock.actor_joined"') == 2
    assert '"Flock/actors_alive"' in telemetry
    # both actor log files exist (spawned subprocess receipts)
    logs = sorted(os.listdir(tmp_path / "flock2" / "flock"))
    assert logs == ["actor0.log", "actor1.log"]
    # sheepscope (ISSUE 17): each actor wrote its own telemetry shard into
    # the shared run dir, keyed by the learner's run id
    run_dir = tmp_path / "flock2"
    shards = sorted(p for p in os.listdir(run_dir) if p.startswith("telemetry"))
    assert "telemetry.actor0.jsonl" in shards, shards
    assert "telemetry.actor1.jsonl" in shards, shards
    import json as _json
    import sys

    run_ids = set()
    for shard in shards:
        for line in (run_dir / shard).read_text().splitlines():
            ev = _json.loads(line)
            if ev.get("event") == "start":
                run_ids.add(ev.get("run"))
    assert len(run_ids) == 1 and None not in run_ids, run_ids
    # the span chains cross the process boundary: sheeptrace reconstructs
    # at least one complete collect->push->ingest->drain->train->publish
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.path.insert(0, os.path.join(repo, "tools"))
    import sheeptrace

    summary = sheeptrace.summarize(sheeptrace.load_shards(str(run_dir)))
    assert summary["complete"], (
        summary["partial"],
        [s.get("name") for s in summary["spans"]],
    )
    names = [s["name"] for s in summary["complete"][0]]
    assert names == list(reversed(sheeptrace.CHAIN))


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_dreamer_v3_flock_two_actors_dry_run(tmp_path):
    tasks["dreamer_v3"](
        [
            "--dry_run", "--num_devices=1", "--num_envs=1", "--sync_env",
            "--per_rank_batch_size=1", "--per_rank_sequence_length=1",
            "--buffer_size=4", "--learning_starts=0", "--gradient_steps=1",
            "--horizon=4", "--dense_units=8", "--cnn_channels_multiplier=2",
            "--recurrent_state_size=8", "--hidden_size=8",
            "--stochastic_size=4", "--discrete_size=4", "--mlp_layers=1",
            "--train_every=1", "--checkpoint_every=1",
            "--env_id=discrete_dummy", f"--root_dir={tmp_path}",
            "--run_name=flock2", "--cnn_keys", "rgb", "--flock", "2",
        ]
    )
    ckpt_dir = tmp_path / "flock2" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in sorted(os.listdir(ckpt_dir)))
    telemetry = (tmp_path / "flock2" / "telemetry.jsonl").read_text()
    assert telemetry.count('"flock.actor_joined"') == 2
