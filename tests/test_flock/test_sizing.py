"""Shard capacities come from the committed sheepmem ledger (ISSUE 14)."""

import pytest

from sheeprl_tpu.flock.sizing import ledger_peak_bytes, shard_capacity


def test_ledger_peak_bytes_reads_committed_budget():
    # the repo commits analysis/budget/ppo.json (PR 10); peak must be real
    peak = ledger_peak_bytes("ppo")
    assert peak is not None and peak > 0


def test_shard_capacity_scales_ledger_and_splits_actors(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_FLOCK_SHARD_BYTES", raising=False)
    monkeypatch.setenv("SHEEPRL_TPU_FLOCK_HOST_FACTOR", "64")
    one = shard_capacity("ppo", 1, 1000)
    two = shard_capacity("ppo", 2, 1000)
    assert one == 64 * ledger_peak_bytes("ppo") // 1000
    assert two == one // 2  # fixed host budget split across the fleet


def test_shard_capacity_env_override_and_clamps(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_FLOCK_SHARD_BYTES", "1000000")
    assert shard_capacity("ppo", 2, 1000) == 500
    # floor wins over a tiny budget; ceiling over a huge one
    assert shard_capacity("ppo", 2, 1000, floor_rows=600) == 600
    monkeypatch.setenv("SHEEPRL_TPU_FLOCK_SHARD_BYTES", str(10**15))
    assert shard_capacity("ppo", 2, 1000, ceil_rows=2048) == 2048


def test_unknown_spec_uses_fallback_budget(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_FLOCK_SHARD_BYTES", raising=False)
    assert ledger_peak_bytes("no_such_algo") is None
    cap = shard_capacity(
        "no_such_algo", 4, 1000, fallback_budget_bytes=4_000_000
    )
    assert cap == 1000
