"""Zero-copy shared-memory ring transport (ISSUE 19 tentpole b): ring
seqlock semantics, the service-side attach/drain/teardown lifecycle, and
the chaos contract — `net.*` sites fire on shm frames exactly like
socket frames, and every ring failure falls back to the socket path
without losing the learner."""

import os
import time

import numpy as np
import pytest

from sheeprl_tpu.flock import shm as shm_mod
from sheeprl_tpu.flock import wire
from sheeprl_tpu.flock.actor import ResilientLink, _ServiceLink
from sheeprl_tpu.flock.service import ReplayService
from sheeprl_tpu.flock.shm import ShmReceiver, ShmRing, ring_geometry, shm_enabled_for
from sheeprl_tpu.resilience import inject

from .test_service import _Recorder, _chunk, _wait_events


@pytest.fixture(autouse=True)
def _fresh_plan(monkeypatch):
    monkeypatch.delenv(inject.ENV_VAR, raising=False)
    monkeypatch.delenv(inject.SEED_VAR, raising=False)
    inject.reset_plan()
    wire._partition_until = 0.0
    yield
    inject.reset_plan()
    wire._partition_until = 0.0


def _arm(monkeypatch, text):
    monkeypatch.setenv(inject.ENV_VAR, text)
    inject.reset_plan()
    return inject.get_plan()


def _wait(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.01)


# ---------------------------------------------------------------------------
# ring unit semantics
# ---------------------------------------------------------------------------


def test_ring_roundtrip_wraparound_and_ordering():
    ring = ShmRing.create(slots=4, slot_bytes=shm_mod.SLOT_HEADER_BYTES + 256)
    try:
        # three full revolutions: seqlock targets keep advancing, FIFO holds
        for round_ in range(3):
            for i in range(4):
                assert ring.try_push(b"%d:%d" % (round_, i))
            for i in range(4):
                payload, crc_ok = ring.try_pop()
                assert crc_ok and payload == b"%d:%d" % (round_, i)
        assert ring.try_pop() is None  # empty again
    finally:
        ring.close()


def test_ring_full_and_oversize_refuse():
    ring = ShmRing.create(slots=2, slot_bytes=shm_mod.SLOT_HEADER_BYTES + 64)
    try:
        assert ring.try_push(b"a") and ring.try_push(b"b")
        assert not ring.try_push(b"c")  # full: caller falls back to socket
        assert not ring.push(b"c", timeout=0.05)  # bounded wait, then False
        assert not ring.try_push(b"x" * 65)  # oversize payload
        payload, _ = ring.try_pop()
        assert payload == b"a"
        assert ring.try_push(b"c")  # slot freed
    finally:
        ring.close()


def test_ring_attach_sees_creator_frames_and_unlink_is_idempotent():
    ring = ShmRing.create(slots=4, slot_bytes=shm_mod.SLOT_HEADER_BYTES + 64)
    peer = ShmRing.attach(ring.name)
    ring.try_push(b"hello")
    payload, crc_ok = peer.try_pop()
    assert crc_ok and payload == b"hello"
    peer.close(unlink=True)
    ring.close()  # creator unlink after peer unlink must not raise
    with pytest.raises(FileNotFoundError):
        ShmRing.attach(ring.name)


def test_receiver_drains_commits_on_stop_and_skips_bad_crc():
    ring = ShmRing.create(slots=8, slot_bytes=shm_mod.SLOT_HEADER_BYTES + 64)
    got, bad = [], []
    rx = ShmReceiver(ring, on_payload=got.append, on_corrupt=bad.append)
    rx.start()
    ring.push(b"good-1")
    ring.push(b"garbled", crc=0xDEADBEEF)  # wrong checksum in the slot
    ring.push(b"good-2")
    _wait(lambda: len(got) == 2, msg="drain")
    rx.stop(unlink=True)
    assert got == [b"good-1", b"good-2"]
    assert bad == [b"garbled"] and rx.corrupt == 1
    with pytest.raises(FileNotFoundError):
        ShmRing.attach(ring.name)  # stop() unlinked


def test_ring_geometry_sizing_knobs(monkeypatch):
    slots, slot_bytes = ring_geometry(100)
    assert slots == shm_mod.DEFAULT_SLOTS
    assert slot_bytes == shm_mod.SLOT_HEADER_BYTES + 64 * 1024  # floor
    _, big = ring_geometry(1_000_000)
    assert big == shm_mod.SLOT_HEADER_BYTES + 2_000_000  # 2x headroom
    monkeypatch.setenv(shm_mod.SLOTS_VAR, "16")
    monkeypatch.setenv(shm_mod.SLOT_BYTES_VAR, "4096")
    slots, slot_bytes = ring_geometry(1_000_000)
    assert (slots, slot_bytes) == (16, shm_mod.SLOT_HEADER_BYTES + 4096)


def test_shm_enabled_for_policy(monkeypatch):
    monkeypatch.delenv(shm_mod.ENABLE_VAR, raising=False)
    assert not shm_enabled_for(0)
    for off in ("0", "off", "no"):
        monkeypatch.setenv(shm_mod.ENABLE_VAR, off)
        assert not shm_enabled_for(0)
    for on in ("1", "all", "on"):
        monkeypatch.setenv(shm_mod.ENABLE_VAR, on)
        assert shm_enabled_for(0) and shm_enabled_for(7)
    monkeypatch.setenv(shm_mod.ENABLE_VAR, "0,2")  # mixed topology (CI smoke)
    assert shm_enabled_for(0) and shm_enabled_for(2)
    assert not shm_enabled_for(1) and not shm_enabled_for(3)


# ---------------------------------------------------------------------------
# service integration: attach, ingest, teardown
# ---------------------------------------------------------------------------


def _push(link, v=1.0, rows=4):
    return link.push(
        [(_chunk(v, rows=rows), None)], rows=rows, env_steps=rows, weight_version=0
    )


@pytest.mark.timeout(60)
def test_shm_attach_ingests_pushes_and_counts_transport():
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        link = _ServiceLink(addr, 0, timeout=5.0, use_shm=True)
        sock_link = _ServiceLink(addr, 1, timeout=5.0, use_shm=False)
        # first push lazily creates + attaches the ring, then rides it
        assert _push(link, 1.0).get("shm") is True
        assert _push(link, 2.0).get("shm") is True
        _wait_events(rec, "flock.shm_attached")
        _wait(lambda: svc.rows_total() == 8, msg="shm ingest")
        _push(sock_link, 3.0)
        gauges = svc.gauges()
        assert gauges["Flock/transport/shm_frames"] == 2.0
        assert gauges["Flock/transport/socket_frames"] == 1.0
        assert gauges["Flock/transport/shm_rings"] == 1.0
        assert gauges["Flock/transport/shm_bytes"] > 0.0
        ring_name = link._ring.name
        link.close()  # clean BYE detaches AND unlinks
        sock_link.close()
        _wait_events(rec, "flock.actor_disconnected", n=2)
        with pytest.raises(FileNotFoundError):
            ShmRing.attach(ring_name)


@pytest.mark.timeout(60)
def test_shm_last_pushes_survive_clean_bye():
    """Frames committed to the ring right before BYE are drained, not
    dropped: the receiver's stop() consumes everything committed."""
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        link = _ServiceLink(addr, 0, timeout=5.0, use_shm=True)
        for i in range(5):
            assert _push(link, float(i)).get("shm") is True
        link.close()
        _wait(lambda: svc.rows_total() == 20, msg="final drain")


@pytest.mark.timeout(60)
def test_abrupt_shm_actor_death_unlinks_ring_and_learner_keeps_serving():
    """The peer-crash shape on an shm actor: SIGKILL leaves a ring the
    creator can never unlink — the service must reap it when the data
    connection dies, keep serving other actors, and accept a fresh ring
    from the respawned incarnation."""
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        link = _ServiceLink(addr, 0, timeout=5.0, use_shm=True)
        peer = _ServiceLink(addr, 1, timeout=5.0, use_shm=False)
        assert _push(link, 1.0).get("shm") is True
        ring_name = link._ring.name
        # crash: the socket dies with no BYE, the ring is left behind
        link.sock.close()
        _wait_events(rec, "flock.actor_disconnected")
        _wait(
            lambda: not os.path.exists(f"/dev/shm/{ring_name}"),
            msg="service-side ring unlink",
        )
        # the learner keeps serving the surviving actor...
        assert _push(peer, 2.0)["rows_total"] >= 4
        # ...and the respawned actor re-attaches a FRESH ring (new name)
        link._ring = None  # the old mapping died with the process
        relink = _ServiceLink(addr, 0, timeout=5.0, use_shm=True)
        assert _push(relink, 3.0).get("shm") is True
        assert relink._ring.name != ring_name
        _wait(lambda: svc.rows_total() == 12, msg="rejoined shm ingest")
        _wait_events(rec, "flock.actor_rejoined")
        relink.close()
        peer.close()


# ---------------------------------------------------------------------------
# chaos: net.* sites firing on the shm transport
# ---------------------------------------------------------------------------


@pytest.mark.timeout(60)
def test_net_corrupt_on_shm_frame_is_skipped_with_receipt(monkeypatch):
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        link = _ServiceLink(addr, 0, timeout=5.0, use_shm=True)
        assert _push(link, 1.0).get("shm") is True
        _wait(lambda: svc.rows_total() == 4, msg="clean ingest")
        # armed AFTER the handshake: the next net frame is the shm push
        _arm(monkeypatch, "net.corrupt@1")
        assert _push(link, 2.0).get("shm") is True  # committed, but garbled
        _wait_events(rec, "flock.shm_corrupt")
        assert inject.counters().get("Fault/net.corrupt") == 1.0
        # the corrupt frame was consumed (not re-read forever), the next
        # clean push lands, and the learner never saw poisoned bytes
        assert _push(link, 3.0).get("shm") is True
        _wait(lambda: svc.rows_total() == 8, msg="post-corrupt ingest")
        assert svc.gauges()["Flock/transport/shm_corrupt"] == 1.0
        link.close()


@pytest.mark.timeout(60)
def test_net_partition_on_shm_falls_back_to_socket(monkeypatch):
    """The chaos contract end to end: an injected partition on the ring
    path detaches the ring, the reconnect waits the window out on the
    SOCKET path, and the in-flight chunk is replayed — zero rows lost,
    shm disabled for the link's lifetime (the degraded path is real)."""
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        link = ResilientLink(addr, 0, timeout=5.0, use_shm=True)
        assert _push(link, 1.0).get("shm") is True
        ring_name = link._link._ring.name
        _arm(monkeypatch, "net.partition@1:0.5")
        t0 = time.monotonic()
        reply = _push(link, 2.0)  # partition fires on the ring path
        waited = time.monotonic() - t0
        # the replayed push went over the SOCKET (per-push reply, no shm)
        assert "shm" not in reply
        assert reply["rows_total"] == 8  # nothing lost
        assert waited >= 0.4  # the reconnect genuinely waited the window
        assert inject.counters().get("Fault/net.partition") == 1.0
        assert not link._use_shm  # sticky fallback
        _wait_events(rec, "flock.actor_rejoined")
        with pytest.raises(FileNotFoundError):
            ShmRing.attach(ring_name)  # the partitioned ring was torn down
        assert _push(link, 3.0)["rows_total"] == 12  # still on socket
        link.close()
