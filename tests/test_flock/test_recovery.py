"""Flock recovery paths over real sockets (ISSUE 16): FrameError
isolation (a poisoned connection dies alone), heartbeat-staleness
eviction, and the crash-resume sidecar (snapshot -> restore -> rehost at
the same address with zero committed rows lost)."""

import os
import time

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import AsyncReplayBuffer
from sheeprl_tpu.flock import wire
from sheeprl_tpu.flock.service import ReplayService

from .test_service import _chunk, _FakeActor, _Recorder, _wait_events


@pytest.mark.timeout(60)
def test_frame_error_kills_only_that_connection():
    """Garbage magic on actor 0's connection: only actor 0 dies — the
    service emits flock.conn_error and keeps serving actor 1."""
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        a0 = _FakeActor(addr, 0)
        a1 = _FakeActor(addr, 1)
        # poison the stream: bad magic, then half a header (mid-frame EOF)
        a0.sock.sendall(b"XXXX" + b"\x00" * 12)
        a0.sock.close()
        _wait_events(rec, "flock.conn_error")
        _wait_events(rec, "flock.actor_disconnected")
        # the OTHER actor's connection is untouched
        reply = a1.push(_chunk(2.0), rows=4)
        assert reply["rows_total"] == 4
        assert svc.actors_alive() == 1
        a1.bye()
    err = dict(rec.events)["flock.conn_error"]
    assert err["actor_id"] == 0 and "FrameError" in err["error"]


@pytest.mark.timeout(60)
def test_oversize_frame_kills_only_that_connection():
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        a0 = _FakeActor(addr, 0)
        a1 = _FakeActor(addr, 1)
        # a length field past MAX_FRAME_BYTES must not allocate the moon
        a0.sock.sendall(
            wire._HEADER.pack(
                wire.MAGIC, wire.PUSH, 0, 0, wire.MAX_FRAME_BYTES + 1
            )
        )
        _wait_events(rec, "flock.conn_error")
        assert a1.push(_chunk(1.0), rows=4)["rows_total"] == 4
        a0.sock.close()
        a1.bye()


@pytest.mark.timeout(60)
def test_heartbeat_staleness_evicts_but_keeps_shard(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_FLOCK_HEARTBEAT_TIMEOUT_S", "0.5")
    rec = _Recorder()
    evicted = []
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        svc.on_evict = evicted.append
        addr = svc.start()
        a = _FakeActor(addr, 0)
        a.push(_chunk(3.0), rows=4)
        # go silent: no heartbeat, no push — past the 0.5 s timeout the
        # monitor frees the connection but KEEPS the shard
        _wait_events(rec, "flock.actor_stale", timeout=10.0)
        assert evicted == [0]
        _wait_events(rec, "flock.actor_disconnected")
        assert svc.rows_total() == 4
        assert svc.next_chunk(timeout=1.0) is not None  # shard kept
        # rejoin under the same id still works (generation bumps)
        b = _FakeActor(addr, 0)
        assert b.welcome["generation"] == 1
        b.bye()
    stale = dict(rec.events)["flock.actor_stale"]
    assert stale["actor_id"] == 0 and stale["timeout_s"] == 0.5


@pytest.mark.timeout(60)
def test_heartbeat_timeout_zero_disables_monitor(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_FLOCK_HEARTBEAT_TIMEOUT_S", "0")
    rec = _Recorder()
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=64, telem=rec,
    ) as svc:
        addr = svc.start()
        a = _FakeActor(addr, 0)
        time.sleep(0.8)  # far past any would-be timeout
        assert svc.actors_alive() == 1
        assert "flock.actor_stale" not in rec.names()
        a.bye()


@pytest.mark.timeout(60)
def test_sidecar_roundtrip_chunks_mode(tmp_path):
    """SIGKILL-shaped crash: snapshot, rebuild a FRESH service from the
    sidecar, rehost at the same address, and verify zero committed rows
    lost, monotonic weight versions, and actor rejoin."""
    rec = _Recorder()
    ckpt = str(tmp_path / "ckpt_3")
    svc = ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=64, telem=rec,
    )
    addr = svc.start()
    svc.publish([np.arange(4, dtype=np.float32)])  # version 1
    a0 = _FakeActor(addr, 0)
    a0.push(_chunk(1.0), rows=4, env_steps=4, weight_version=1)
    a0.push(_chunk(2.0), rows=4, env_steps=8, weight_version=1)
    path = svc.save_sidecar(ckpt)
    assert os.path.exists(path)
    a0.sock.close()
    svc.close()  # the crash (the real one never even closes)

    rec2 = _Recorder()
    svc2 = ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=64, telem=rec2,
    )
    assert svc2.restore_sidecar(ckpt)
    addr2 = svc2.start()
    try:
        assert addr2 == addr  # rehosted at the pre-crash address
        assert svc2.rows_total() == 8  # zero committed rows lost
        assert "flock.resumed" in rec2.names()
        # publish AFTER restore bumps PAST the restored version: monotonic
        assert svc2.publish([np.arange(4, dtype=np.float32)]) == 2
        chunk = svc2.next_chunk(timeout=1.0)
        np.testing.assert_array_equal(
            chunk["obs"], _chunk(1.0)["obs"]
        )
        # a surviving actor re-dials the SAME address and re-HELLOs
        b = _FakeActor(addr2, 0)
        assert b.welcome["generation"] == 1  # ever_connected survived
        assert "flock.actor_rejoined" in rec2.names()
        b.bye()
    finally:
        svc2.close()


@pytest.mark.timeout(60)
def test_sidecar_roundtrip_buffer_mode(tmp_path):
    def make_shard(cap):
        return AsyncReplayBuffer(
            cap, 2, storage="host", sequential=True,
            obs_keys=("obs",), seed=7,
        )

    ckpt = str(tmp_path / "ckpt_9")
    svc = ReplayService(
        algo="dreamer_v3", n_actors=1, mode="buffer", capacity_rows=32,
        make_shard=make_shard, telem=_Recorder(),
    )
    addr = svc.start()
    a = _FakeActor(addr, 0)
    tree = {
        "obs": np.random.default_rng(0).standard_normal(
            (8, 2, 3)
        ).astype(np.float32),
        "rewards": np.zeros((8, 2, 1), np.float32),
    }
    a.push(tree, rows=8, env_steps=16, weight_version=0)
    before = svc.shard(0).to_bytes()
    svc.save_sidecar(ckpt)
    a.sock.close()
    svc.close()

    svc2 = ReplayService(
        algo="dreamer_v3", n_actors=1, mode="buffer", capacity_rows=32,
        make_shard=make_shard, telem=_Recorder(),
    )
    assert svc2.restore_sidecar(ckpt)
    svc2.start()
    try:
        # bit-exact shard restore: ring contents + sampler PRNG state
        assert svc2.shard(0).to_bytes() == before
        assert svc2.rows_total() == 8
    finally:
        svc2.close()


@pytest.mark.timeout(60)
def test_sidecar_mismatch_raises(tmp_path):
    ckpt = str(tmp_path / "ckpt_1")
    svc = ReplayService(
        algo="ppo", n_actors=2, mode="chunks", capacity_rows=64,
        telem=_Recorder(),
    )
    svc.start()
    svc.save_sidecar(ckpt)
    svc.close()
    other = ReplayService(
        algo="ppo", n_actors=3, mode="chunks", capacity_rows=64,
        telem=_Recorder(),
    )
    with pytest.raises(ValueError, match="n_actors"):
        other.restore_sidecar(ckpt)
    assert not other.restore_sidecar(str(tmp_path / "no_such_ckpt"))
