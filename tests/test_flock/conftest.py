"""Run the flock suite under the sheepsync runtime thread sanitizer.

Every Lock/RLock/Condition allocated while these tests run is
instrumented: per-thread acquisition order is recorded and asserted
against the committed lock-order ledger
(`analysis/budget/concurrency.json`). Violations never raise — they are
collected and printed at teardown so the suite stays deterministic —
but the instrumentation itself exercising the full flock path IS the
receipt that the static DAG matches the live system (ISSUE 18).

CI additionally exports SHEEPRL_TPU_SANITIZE_THREADS=1 so the actor
*subprocesses* spawned by these tests self-instrument too (the learner
process' sanitizer cannot see their locks).
"""

import pytest

from sheeprl_tpu.analysis import thread_sanitizer


@pytest.fixture(scope="package", autouse=True)
def _sheepsync_sanitizer():
    san = thread_sanitizer.install()
    yield san
    summary = thread_sanitizer.uninstall()
    if summary and summary["violations"]:
        print(
            "\n[sheepsync] lock-order violations observed during the flock "
            f"suite: {summary['violations']}"
        )
