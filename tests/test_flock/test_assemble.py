"""In-network sample pre-assembly (ISSUE 19, tentpole a): the PR-3
`SamplePrefetcher` contract generalized across replay-service shards.
The receipt that matters: assembler ON vs OFF trains on bit-identical
batches — hits serve pre-drawn slices, misses rewind every shard's
sampler PRNG plus the remainder rotation and resample synchronously."""

import numpy as np
import pytest

from sheeprl_tpu.flock.assemble import BatchAssembler
from sheeprl_tpu.flock.service import ReplayService
from sheeprl_tpu.parallel.pipeline import PipelineStats

from .test_service import _Recorder


class _RngShard:
    """Replay-shard stand-in with the full sampling contract: PRNG-driven
    draws, `get/set_sample_state` for the rewind path, and a write `epoch`
    for the consistency guard."""

    def __init__(self, cap, seed=7):
        self.cap = cap
        self.rows = []
        self.epoch = 0
        self._rng = np.random.default_rng(seed)

    def add(self, tree, indices=None):
        self.rows.append(tree)
        self.epoch += 1

    def sample(self, n, **kw):
        if not self.rows:
            raise ValueError("empty shard")
        draw = self._rng.integers(0, len(self.rows), size=n)
        base = float(len(self.rows))
        if "sequence_length" in kw:
            seq = int(kw["sequence_length"])
            out = np.tile(
                np.asarray(draw, np.float32).reshape(1, 1, n, 1), (seq, 1, 1, 1)
            )
            return {"x": out + base}
        return {"x": np.asarray(draw, np.float32).reshape(n, 1) + base}

    def get_sample_state(self):
        return self._rng.bit_generator.state

    def set_sample_state(self, state):
        self._rng.bit_generator.state = state

    def to_bytes(self):
        return b""

    @classmethod
    def from_bytes(cls, blob, **kw):
        return cls(0)


def _service(n_actors=3):
    return ReplayService(
        algo="dreamer_v3", n_actors=n_actors, mode="buffer",
        capacity_rows=16, make_shard=_RngShard, telem=_Recorder(),
    )


def _fill(svc, rows_per_shard=4):
    for aid in range(svc.n_actors):
        for _ in range(rows_per_shard):
            svc.shard(aid).add({"x": np.zeros((1, 1), np.float32)})


def _same(a, b):
    assert set(a) == set(b)
    for k in a:
        assert a[k].shape == b[k].shape
        assert a[k].tobytes() == b[k].tobytes()


@pytest.mark.timeout(60)
def test_quiet_draws_are_bit_exact_and_mostly_hits():
    """No writes between serves: every prefetched batch passes the epoch
    guard, and the served sequence is byte-identical to the unassembled
    service driven by the same call script."""
    with _service() as plain, _service() as svc:
        _fill(plain)
        _fill(svc)
        stats = PipelineStats()
        asm = BatchAssembler(svc, max_staleness=0, stats=stats)
        try:
            for _ in range(6):
                _same(plain.sample(4), asm.sample(4))
        finally:
            asm.close()
        # first call has nothing in flight; the rest serve pre-assembled
        assert stats.sample_hits == 5
        assert stats.sample_misses == 0
        assert stats.sample_prefetches >= 6


@pytest.mark.timeout(60)
def test_write_between_serves_misses_rewinds_and_stays_bit_exact():
    """A write landing in the serve-to-serve gap advances the epoch: the
    prefetched batch is discarded and the PRNG + remainder-rotation rewind
    makes the synchronous resample draw exactly the unassembled answer."""
    with _service() as plain, _service() as svc:
        _fill(plain)
        _fill(svc)
        stats = PipelineStats()
        asm = BatchAssembler(svc, max_staleness=0, stats=stats)
        try:
            row = {"x": np.zeros((1, 1), np.float32)}
            for i in range(5):
                _same(plain.sample(4), asm.sample(4))
                plain.shard(i % 3).add(row)
                svc.shard(i % 3).add(row)
        finally:
            asm.close()
        # every gap had a write: the first in-flight assembly misses, then
        # `predict_quiet` pauses dispatch (strict staleness could never hit
        # there) — later calls are plain synchronous samples, not misses
        assert stats.sample_hits == 0
        assert stats.sample_misses == 1
        assert stats.sample_prefetches == 1


@pytest.mark.timeout(60)
def test_signature_change_discards_and_stays_bit_exact():
    """Changing batch size or sample kwargs between calls invalidates the
    in-flight assembly — the rewind keeps the A/B exact anyway."""
    script = [
        dict(batch_size=4),
        dict(batch_size=6),
        dict(batch_size=6),
        dict(batch_size=4, sequence_length=3, n_samples=1),
        dict(batch_size=4, sequence_length=3, n_samples=1),
    ]
    with _service() as plain, _service() as svc:
        _fill(plain)
        _fill(svc)
        asm = BatchAssembler(svc, max_staleness=0)
        try:
            for kw in script:
                kw = dict(kw)
                bs = kw.pop("batch_size")
                _same(plain.sample(bs, **kw), asm.sample(bs, **kw))
        finally:
            asm.close()


@pytest.mark.timeout(60)
def test_max_staleness_serves_through_writes():
    """Bounded staleness (the PR-3 knob): with max_staleness >= the writes
    per gap, prefetched batches keep serving instead of rewinding."""
    with _service() as svc:
        _fill(svc)
        stats = PipelineStats()
        asm = BatchAssembler(svc, max_staleness=1, stats=stats)
        try:
            asm.sample(4)
            for i in range(4):
                svc.shard(i % 3).add({"x": np.zeros((1, 1), np.float32)})
                out = asm.sample(4)
                assert out["x"].shape == (4, 1)
        finally:
            asm.close()
        assert stats.sample_hits == 4
        assert stats.sample_misses == 0


@pytest.mark.timeout(60)
def test_disabled_paths_delegate_to_the_service():
    """chunks-mode services and `enabled=False` fall through untouched —
    and attribute access proxies to the service either way."""
    with _service() as svc:
        _fill(svc)
        asm = BatchAssembler(svc, enabled=False)
        assert not asm.enabled
        assert asm.sample(4)["x"].shape == (4, 1)
        assert asm.rows_total() == svc.rows_total()  # __getattr__ delegation
        asm.close()
    with ReplayService(
        algo="ppo", n_actors=1, mode="chunks", capacity_rows=8,
        telem=_Recorder(),
    ) as chunks_svc:
        asm = BatchAssembler(chunks_svc)
        assert not asm.enabled  # pre-assembly is a buffer-mode feature
        asm.close()


@pytest.mark.timeout(60)
def test_close_quiesces_workers_and_disables():
    with _service() as svc:
        _fill(svc)
        asm = BatchAssembler(svc)
        asm.sample(4)  # leaves one assembly in flight
        asm.close()
        assert not asm.enabled
        assert not asm._workers
        # post-close sampling still works (synchronous path)
        assert asm.sample(4)["x"].shape == (4, 1)
