"""End-to-end smoke tests for DreamerV1 (reference backbone:
/root/reference/tests/test_algos/test_algos.py:414-463)."""

import os

import pytest

from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import main

TINY = [
    "--dry_run",
    "--num_devices=1",
    "--num_envs=1",
    "--sync_env",
    "--per_rank_batch_size=1",
    "--per_rank_sequence_length=2",
    "--buffer_size=10",
    "--learning_starts=0",
    "--gradient_steps=1",
    "--horizon=8",
    "--dense_units=8",
    "--cnn_channels_multiplier=2",
    "--recurrent_state_size=8",
    "--hidden_size=8",
    "--stochastic_size=4",
    "--mlp_layers=1",
    "--train_every=1",
    "--checkpoint_every=1",
]


@pytest.mark.parametrize(
    "env_id",
    ["discrete_dummy", pytest.param("continuous_dummy", marks=pytest.mark.slow)],
)
def test_dreamer_v1_dry_run(tmp_path, env_id):
    main(
        TINY
        + [
            f"--env_id={env_id}",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    assert os.path.isdir(ckpt_dir)
    assert any(e.startswith("ckpt_") for e in sorted(os.listdir(ckpt_dir)))


def test_dreamer_v1_checkpoint_contract_and_resume(tmp_path):
    main(
        TINY
        + [
            "--env_id=discrete_dummy",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
            "--checkpoint_buffer",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    ckpts = [
        e
        for e in sorted(os.listdir(ckpt_dir))
        if not e.endswith(".json") and not e.endswith(".npz")
    ]
    ckpt = os.path.join(ckpt_dir, ckpts[-1])
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    raw = load_checkpoint(ckpt)
    for k in (
        "world_model",
        "actor",
        "critic",
        "world_optimizer",
        "actor_optimizer",
        "critic_optimizer",
        "expl_decay_steps",
        "global_step",
        "batch_size",
    ):
        assert k in raw, f"missing checkpoint key {k}"
    main([f"--checkpoint_path={ckpt}"])
