"""End-to-end smoke tests for DreamerV2 (mirrors the reference e2e strategy,
/root/reference/tests/test_algos/test_algos.py:466-518: tiny config, dummy
env, dry run, sequential and episode buffers, checkpoint key contract)."""

import os

import pytest

from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import main

TINY = [
    "--dry_run",
    "--num_devices=1",
    "--num_envs=1",
    "--sync_env",
    "--per_rank_batch_size=1",
    "--per_rank_sequence_length=2",
    "--buffer_size=10",
    "--learning_starts=0",
    "--pretrain_steps=1",
    "--gradient_steps=1",
    "--horizon=4",
    "--dense_units=8",
    "--cnn_channels_multiplier=2",
    "--recurrent_state_size=8",
    "--hidden_size=8",
    "--stochastic_size=4",
    "--discrete_size=4",
    "--mlp_layers=1",
    "--train_every=1",
    "--checkpoint_every=1",
]


@pytest.mark.parametrize(
    "env_id",
    ["discrete_dummy", pytest.param("continuous_dummy", marks=pytest.mark.slow)],
)
@pytest.mark.parametrize("buffer_type", ["sequential", "episode"])
def test_dreamer_v2_dry_run(tmp_path, env_id, buffer_type):
    main(
        TINY
        + [
            f"--env_id={env_id}",
            f"--buffer_type={buffer_type}",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    assert os.path.isdir(ckpt_dir)
    assert any(e.startswith("ckpt_") for e in sorted(os.listdir(ckpt_dir)))


def test_dreamer_v2_checkpoint_contract_and_resume(tmp_path):
    main(
        TINY
        + [
            "--env_id=discrete_dummy",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
            "--checkpoint_buffer",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    ckpts = [
        e
        for e in sorted(os.listdir(ckpt_dir))
        if not e.endswith(".json") and not e.endswith(".npz")
    ]
    ckpt = os.path.join(ckpt_dir, ckpts[-1])
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    raw = load_checkpoint(ckpt)
    for k in (
        "world_model",
        "actor",
        "critic",
        "target_critic",
        "world_optimizer",
        "actor_optimizer",
        "critic_optimizer",
        "expl_decay_steps",
        "global_step",
        "batch_size",
    ):
        assert k in raw, f"missing checkpoint key {k}"
    assert os.path.exists(ckpt + "_buffer.npz")
    main([f"--checkpoint_path={ckpt}"])
