"""End-to-end smoke tests for the decoupled player/trainer tasks on the
8-device virtual CPU mesh (1 player device + 7 trainers) — the JAX
equivalent of the reference's torchrun+Gloo multi-process tests
(/root/reference/tests/test_algos/test_algos.py:192-211, 264-283), including
the it-must-fail-on-one-device contract."""

import os

import pytest


def test_ppo_decoupled_dry_run(tmp_path):
    from sheeprl_tpu.algos.ppo.ppo_decoupled import main

    main(
        [
            "--dry_run",
            "--env_id=CartPole-v1",
            "--num_envs=2",
            "--sync_env",
            "--rollout_steps=8",
            "--per_rank_batch_size=2",
            "--update_epochs=1",
            "--dense_units=8",
            "--mlp_layers=1",
            "--checkpoint_every=1",
            f"--root_dir={tmp_path}",
            "--run_name=test",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    assert os.path.isdir(ckpt_dir)
    assert any(e.startswith("ckpt_") for e in sorted(os.listdir(ckpt_dir)))


def test_ppo_decoupled_requires_two_devices(tmp_path):
    from sheeprl_tpu.algos.ppo.ppo_decoupled import main

    # the reference asserts a ChildFailedError with one rank
    # (test_algos.py:192-199); here the mesh construction raises
    with pytest.raises(RuntimeError, match="at least 2 devices"):
        main(
            [
                "--dry_run",
                "--num_devices=1",
                "--env_id=CartPole-v1",
                f"--root_dir={tmp_path}",
                "--run_name=test",
            ]
        )


def test_sac_decoupled_dry_run(tmp_path):
    from sheeprl_tpu.algos.sac.sac_decoupled import main

    main(
        [
            "--dry_run",
            "--env_id=Pendulum-v1",
            "--num_envs=1",
            "--sync_env",
            "--per_rank_batch_size=2",
            "--gradient_steps=1",
            "--learning_starts=0",
            "--buffer_size=16",
            "--actor_hidden_size=8",
            "--critic_hidden_size=8",
            "--checkpoint_every=1",
            f"--root_dir={tmp_path}",
            "--run_name=test",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    assert os.path.isdir(ckpt_dir)


def test_sac_decoupled_requires_two_devices(tmp_path):
    from sheeprl_tpu.algos.sac.sac_decoupled import main

    with pytest.raises(RuntimeError, match="at least 2 devices"):
        main(
            [
                "--dry_run",
                "--num_devices=1",
                "--env_id=Pendulum-v1",
                f"--root_dir={tmp_path}",
                "--run_name=test",
            ]
        )


def test_dreamer_v3_decoupled_dry_run(tmp_path):
    # the flagship task in the decoupled topology (a capability beyond the
    # reference, which decouples only PPO/SAC): player device runs
    # PlayerDV3 + the replay ring, the 7-trainer mesh runs the single-jit
    # DV3 update on the shipped [n_samples, T, B] block, refreshed
    # encoder/RSSM/actor weights stream back asynchronously
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3_decoupled import main

    main(
        [
            "--dry_run",
            "--env_id=discrete_dummy",
            "--num_envs=1",
            "--sync_env",
            "--per_rank_batch_size=2",
            "--per_rank_sequence_length=1",
            "--buffer_size=4",
            "--learning_starts=0",
            "--gradient_steps=1",
            "--horizon=4",
            "--dense_units=8",
            "--cnn_channels_multiplier=2",
            "--recurrent_state_size=8",
            "--hidden_size=8",
            "--stochastic_size=4",
            "--discrete_size=4",
            "--mlp_layers=1",
            "--train_every=1",
            "--checkpoint_every=1",
            "--cnn_keys", "rgb",
            f"--root_dir={tmp_path}",
            "--run_name=test",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    assert os.path.isdir(ckpt_dir)
    assert any(e.startswith("ckpt_") for e in sorted(os.listdir(ckpt_dir)))


def test_dreamer_v3_decoupled_requires_two_devices(tmp_path):
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3_decoupled import main

    with pytest.raises(RuntimeError, match="at least 2 devices"):
        main(
            [
                "--dry_run",
                "--num_devices=1",
                "--env_id=discrete_dummy",
                f"--root_dir={tmp_path}",
                "--run_name=test",
            ]
        )


def test_dreamer_v3_decoupled_rejects_seq_devices(tmp_path):
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3_decoupled import main

    with pytest.raises(ValueError, match="seq_devices"):
        main(
            [
                "--dry_run",
                "--seq_devices=2",
                "--env_id=discrete_dummy",
                f"--root_dir={tmp_path}",
                "--run_name=test",
            ]
        )


def test_dreamer_v3_decoupled_resume(tmp_path):
    # checkpoint contract + resume through the decoupled main (restores
    # args from the checkpoint like the coupled task)
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3_decoupled import main

    tiny = [
        "--dry_run",
        "--env_id=discrete_dummy",
        "--num_envs=1",
        "--sync_env",
        "--per_rank_batch_size=2",
        "--per_rank_sequence_length=1",
        "--buffer_size=4",
        "--learning_starts=0",
        "--gradient_steps=1",
        "--horizon=4",
        "--dense_units=8",
        "--cnn_channels_multiplier=2",
        "--recurrent_state_size=8",
        "--hidden_size=8",
        "--stochastic_size=4",
        "--discrete_size=4",
        "--mlp_layers=1",
        "--train_every=1",
        "--checkpoint_every=1",
        "--checkpoint_buffer",
        "--cnn_keys", "rgb",
        f"--root_dir={tmp_path}",
        "--run_name=test",
    ]
    main(tiny)
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    ckpts = sorted(e for e in os.listdir(ckpt_dir) if e.endswith(".args.json"))
    assert ckpts
    ckpt = os.path.join(ckpt_dir, ckpts[-1].replace(".args.json", ""))
    main([f"--checkpoint_path={ckpt}", f"--root_dir={tmp_path}", "--run_name=resume"])
