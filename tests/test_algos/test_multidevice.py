"""Multi-device end-to-end runs for the coupled algorithms.

The reference parametrizes every e2e test over devices ∈ {1,2}
(/root/reference/tests/test_algos/test_algos.py:16-38, Gloo-on-CPU). Here the
same semantics run on the virtual 8-device CPU mesh: params replicated, batch
sharded, gradient all-reduce implicit in the sharded jit. These tests drive
the `n_dev > 1` shard_batch branches of each coupled main and check that an
indivisible batch/device combination is a hard error, not a silent fallback.
"""

import os

import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.utils.registry import tasks

DV3_TINY = [
    "--dry_run",
    "--env_id=discrete_dummy",
    "--num_envs=1",
    "--sync_env",
    "--per_rank_sequence_length=1",
    "--buffer_size=8",
    "--learning_starts=0",
    "--gradient_steps=1",
    "--horizon=4",
    "--dense_units=8",
    "--cnn_channels_multiplier=2",
    "--recurrent_state_size=8",
    "--hidden_size=8",
    "--stochastic_size=4",
    "--discrete_size=4",
    "--mlp_layers=1",
    "--train_every=1",
    "--checkpoint_every=1",
    "--cnn_keys", "rgb",
]


@pytest.mark.timeout(300)
@pytest.mark.parametrize(
    "num_devices",
    [2, pytest.param(4, marks=pytest.mark.slow)],  # same path, more devices
)
def test_ppo_multidevice(tmp_path, num_devices):
    tasks["ppo"]([
        "--env_id", "discrete_dummy",
        "--dry_run",
        "--num_envs", "1",
        "--rollout_steps", "8",
        "--per_rank_batch_size", "4",
        "--update_epochs", "1",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--cnn_features_dim", "16",
        "--mlp_features_dim", "8",
        "--num_devices", str(num_devices),
        "--root_dir", str(tmp_path),
        "--run_name", f"dev{num_devices}",
    ])
    assert os.path.exists(tmp_path / f"dev{num_devices}" / "checkpoints" / "ckpt_1")


@pytest.mark.timeout(300)
def test_ppo_indivisible_rollout_raises(tmp_path):
    with pytest.raises(ValueError, match="not divisible"):
        tasks["ppo"]([
            "--env_id", "discrete_dummy",
            "--dry_run",
            "--num_envs", "1",
            "--rollout_steps", "7",
            "--per_rank_batch_size", "7",
            "--num_devices", "2",
            "--root_dir", str(tmp_path),
            "--run_name", "bad",
        ])


@pytest.mark.timeout(300)
@pytest.mark.parametrize(
    "num_devices",
    [2, pytest.param(4, marks=pytest.mark.slow)],  # same path, more devices
)
def test_sac_multidevice(tmp_path, num_devices):
    tasks["sac"]([
        "--env_id", "Pendulum-v1",
        "--dry_run",
        "--num_envs", "1",
        "--per_rank_batch_size", "2",
        "--buffer_size", "16",
        "--learning_starts", "0",
        "--gradient_steps", "1",
        "--actor_hidden_size", "8",
        "--critic_hidden_size", "8",
        "--num_devices", str(num_devices),
        "--root_dir", str(tmp_path),
        "--run_name", f"dev{num_devices}",
    ])
    assert os.path.exists(tmp_path / f"dev{num_devices}" / "checkpoints" / "ckpt_1")


@pytest.mark.timeout(600)
@pytest.mark.parametrize(
    "num_devices",
    [2, pytest.param(4, marks=pytest.mark.slow)],  # same path, more devices
)
def test_dreamer_v3_multidevice(tmp_path, num_devices):
    tasks["dreamer_v3"](
        DV3_TINY
        + [
            f"--per_rank_batch_size={num_devices}",
            f"--num_devices={num_devices}",
            f"--root_dir={tmp_path}",
            f"--run_name=dev{num_devices}",
        ]
    )
    ckpt_dir = tmp_path / f"dev{num_devices}" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in os.listdir(ckpt_dir))


@pytest.mark.timeout(300)
def test_dreamer_v3_indivisible_batch_raises(tmp_path):
    with pytest.raises(ValueError, match="not divisible"):
        tasks["dreamer_v3"](
            DV3_TINY
            + [
                "--per_rank_batch_size=3",
                "--num_devices=2",
                f"--root_dir={tmp_path}",
                "--run_name=bad",
            ]
        )


@pytest.mark.timeout(300)
@pytest.mark.parametrize("num_devices", [2])
def test_droq_multidevice(tmp_path, num_devices):
    tasks["droq"]([
        "--env_id", "Pendulum-v1",
        "--dry_run",
        "--num_envs", "1",
        "--per_rank_batch_size", "2",
        "--buffer_size", "8",
        "--learning_starts", "0",
        "--gradient_steps", "2",
        "--actor_hidden_size", "8",
        "--critic_hidden_size", "8",
        "--num_devices", str(num_devices),
        "--root_dir", str(tmp_path),
        "--run_name", f"dev{num_devices}",
    ])
    assert os.path.exists(tmp_path / f"dev{num_devices}" / "checkpoints" / "ckpt_1")


@pytest.mark.timeout(300)
@pytest.mark.parametrize("num_devices", [2])
def test_sac_ae_multidevice(tmp_path, num_devices):
    tasks["sac_ae"]([
        "--env_id", "continuous_dummy",
        "--dry_run",
        "--num_envs", "1",
        "--per_rank_batch_size", "2",
        "--buffer_size", "8",
        "--learning_starts", "0",
        "--gradient_steps", "1",
        "--actor_hidden_size", "16",
        "--critic_hidden_size", "16",
        "--features_dim", "16",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--cnn_channels_multiplier", "1",
        "--num_devices", str(num_devices),
        "--root_dir", str(tmp_path),
        "--run_name", f"dev{num_devices}",
    ])
    assert os.path.exists(tmp_path / f"dev{num_devices}" / "checkpoints" / "ckpt_1")


@pytest.mark.timeout(600)
@pytest.mark.parametrize("num_devices", [2])
def test_dreamer_v2_multidevice(tmp_path, num_devices):
    tasks["dreamer_v2"](
        DV3_TINY
        + [
            f"--per_rank_batch_size={num_devices}",
            f"--num_devices={num_devices}",
            f"--root_dir={tmp_path}",
            f"--run_name=dev{num_devices}",
        ]
    )
    ckpt_dir = tmp_path / f"dev{num_devices}" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in os.listdir(ckpt_dir))


@pytest.mark.timeout(600)
@pytest.mark.parametrize("num_devices", [2])
def test_p2e_dv1_multidevice(tmp_path, num_devices):
    tasks["p2e_dv1"](
        # DreamerV1-family config: Gaussian latent, no --discrete_size
        [a for a in DV3_TINY if not a.startswith("--discrete_size")]
        + [
            f"--per_rank_batch_size={num_devices}",
            f"--num_devices={num_devices}",
            f"--root_dir={tmp_path}",
            f"--run_name=dev{num_devices}",
        ]
    )
    ckpt_dir = tmp_path / f"dev{num_devices}" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in os.listdir(ckpt_dir))


@pytest.mark.timeout(300)
@pytest.mark.parametrize("num_devices", [2])
def test_ppo_recurrent_multidevice(tmp_path, num_devices):
    tasks["ppo_recurrent"]([
        "--env_id=CartPole-v1",
        "--dry_run",
        "--num_devices", str(num_devices),
        "--num_envs=2",
        "--sync_env",
        "--rollout_steps=8",
        "--per_rank_batch_size=4",
        "--per_rank_num_batches=2",
        "--update_epochs=2",
        "--lstm_hidden_size=8",
        "--actor_hidden_size=8",
        "--critic_hidden_size=8",
        "--actor_pre_lstm_hidden_size=8",
        "--critic_pre_lstm_hidden_size=8",
        "--checkpoint_every=1",
        f"--root_dir={tmp_path}",
        f"--run_name=dev{num_devices}",
    ])
    ckpt_dir = tmp_path / f"dev{num_devices}" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in os.listdir(ckpt_dir))
