"""End-to-end smoke tests for recurrent PPO (reference backbone:
/root/reference/tests/test_algos/test_algos.py:214-283)."""

import os

import pytest

from sheeprl_tpu.algos.ppo_recurrent.ppo_recurrent import main

TINY = [
    "--dry_run",
    "--num_devices=1",
    "--num_envs=2",
    "--sync_env",
    "--rollout_steps=8",
    "--per_rank_batch_size=4",
    "--per_rank_num_batches=2",
    "--update_epochs=2",
    "--lstm_hidden_size=8",
    "--actor_hidden_size=8",
    "--critic_hidden_size=8",
    "--actor_pre_lstm_hidden_size=8",
    "--critic_pre_lstm_hidden_size=8",
    "--checkpoint_every=1",
]


@pytest.mark.parametrize("reset_on_done", [False, True])
def test_ppo_recurrent_dry_run(tmp_path, reset_on_done):
    argv = TINY + [
        "--env_id=CartPole-v1",
        f"--root_dir={tmp_path}",
        "--run_name=test",
    ]
    if reset_on_done:
        argv.append("--reset_recurrent_state_on_done")
    main(argv)
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    assert os.path.isdir(ckpt_dir)
    assert any(e.startswith("ckpt_") for e in sorted(os.listdir(ckpt_dir)))


def test_ppo_recurrent_resume(tmp_path):
    main(
        TINY
        + ["--env_id=CartPole-v1", f"--root_dir={tmp_path}", "--run_name=test"]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    ckpts = [e for e in sorted(os.listdir(ckpt_dir)) if not e.endswith(".json")]
    main([f"--checkpoint_path={os.path.join(ckpt_dir, ckpts[-1])}"])


def test_ppo_recurrent_rejects_continuous(tmp_path):
    with pytest.raises(ValueError, match="discrete"):
        main(
            TINY
            + [
                "--env_id=Pendulum-v1",
                f"--root_dir={tmp_path}",
                "--run_name=test",
            ]
        )
