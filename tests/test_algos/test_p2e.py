"""End-to-end smoke tests for Plan2Explore DV1/DV2 (reference backbone:
/root/reference/tests/test_algos/test_algos.py:286-412, incl. the dual
actor-critic checkpoint contract at :395-412)."""

import os

import pytest

TINY_COMMON = [
    "--dry_run",
    "--num_devices=1",
    "--num_envs=1",
    "--sync_env",
    "--per_rank_batch_size=1",
    "--per_rank_sequence_length=2",
    "--buffer_size=10",
    "--learning_starts=0",
    "--gradient_steps=1",
    "--horizon=8",
    "--dense_units=8",
    "--cnn_channels_multiplier=2",
    "--recurrent_state_size=8",
    "--hidden_size=8",
    "--num_ensembles=3",
    "--mlp_layers=1",
    "--train_every=1",
    "--checkpoint_every=1",
]

P2E_DV1_KEYS = {
    "world_model", "actor_task", "critic_task", "ensembles",
    "world_optimizer", "actor_task_optimizer", "critic_task_optimizer",
    "ensemble_optimizer", "expl_decay_steps", "global_step", "batch_size",
    "actor_exploration", "critic_exploration",
    "actor_exploration_optimizer", "critic_exploration_optimizer",
}


def _latest_ckpt(tmp_path):
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    ckpts = [
        e
        for e in sorted(os.listdir(ckpt_dir))
        if not e.endswith(".json") and not e.endswith(".npz")
    ]
    return os.path.join(ckpt_dir, ckpts[-1])


@pytest.mark.parametrize(
    "env_id",
    ["discrete_dummy", pytest.param("continuous_dummy", marks=pytest.mark.slow)],
)
def test_p2e_dv1_dry_run(tmp_path, env_id):
    from sheeprl_tpu.algos.p2e_dv1.p2e_dv1 import main

    main(
        TINY_COMMON
        + [
            "--stochastic_size=4",
            f"--env_id={env_id}",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
        ]
    )
    assert os.path.isdir(os.path.join(tmp_path, "test", "checkpoints"))


def test_p2e_dv1_checkpoint_contract_and_resume(tmp_path):
    from sheeprl_tpu.algos.p2e_dv1.p2e_dv1 import main
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    main(
        TINY_COMMON
        + [
            "--stochastic_size=4",
            "--env_id=discrete_dummy",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
        ]
    )
    ckpt = _latest_ckpt(tmp_path)
    raw = load_checkpoint(ckpt)
    # dual actor-critic contract (reference test_algos.py:395-412)
    assert P2E_DV1_KEYS <= set(raw), P2E_DV1_KEYS - set(raw)
    main([f"--checkpoint_path={ckpt}"])


@pytest.mark.parametrize(
    "env_id",
    ["discrete_dummy", pytest.param("continuous_dummy", marks=pytest.mark.slow)],
)
def test_p2e_dv2_dry_run(tmp_path, env_id):
    from sheeprl_tpu.algos.p2e_dv2.p2e_dv2 import main

    main(
        TINY_COMMON
        + [
            "--stochastic_size=4",
            "--discrete_size=4",
            f"--env_id={env_id}",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
        ]
    )
    assert os.path.isdir(os.path.join(tmp_path, "test", "checkpoints"))


def test_p2e_dv2_checkpoint_contract_and_resume(tmp_path):
    from sheeprl_tpu.algos.p2e_dv2.p2e_dv2 import main
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    main(
        TINY_COMMON
        + [
            "--stochastic_size=4",
            "--discrete_size=4",
            "--env_id=discrete_dummy",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
        ]
    )
    ckpt = _latest_ckpt(tmp_path)
    raw = load_checkpoint(ckpt)
    expected = P2E_DV1_KEYS | {"target_critic_task", "target_critic_exploration"}
    assert expected <= set(raw), expected - set(raw)
    main([f"--checkpoint_path={ckpt}"])
