"""End-to-end smoke tests for DroQ (reference backbone:
/root/reference/tests/test_algos/test_algos.py)."""

import os

import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.utils.checkpoint import load_checkpoint
from sheeprl_tpu.utils.registry import tasks

CKPT_KEYS = {
    "agent", "qf_optimizer", "actor_optimizer", "alpha_optimizer", "global_step"
}


@pytest.mark.timeout(300)
def test_droq_dry_run(tmp_path):
    tasks["droq"](
        [
            "--env_id", "Pendulum-v1",
            "--dry_run",
            "--num_envs", "1",
            "--per_rank_batch_size", "2",
            "--buffer_size", "4",
            "--learning_starts", "0",
            "--gradient_steps", "2",
            "--actor_hidden_size", "8",
            "--critic_hidden_size", "8",
            "--root_dir", str(tmp_path),
            "--run_name", "dry",
        ]
    )
    ckpt = str(tmp_path / "dry" / "checkpoints" / "ckpt_1")
    assert os.path.exists(ckpt)
    assert set(load_checkpoint(ckpt).keys()) == CKPT_KEYS


@pytest.mark.timeout(300)
def test_droq_high_utd_run(tmp_path):
    # several real steps at UTD=4 exercising the scan + fresh actor batch
    tasks["droq"](
        [
            "--env_id", "Pendulum-v1",
            "--num_envs", "2",
            "--total_steps", "12",
            "--per_rank_batch_size", "2",
            "--buffer_size", "32",
            "--learning_starts", "4",
            "--gradient_steps", "4",
            "--actor_hidden_size", "8",
            "--critic_hidden_size", "8",
            "--checkpoint_every", "-1",
            "--sync_env",
            "--root_dir", str(tmp_path),
            "--run_name", "utd",
        ]
    )
    assert (tmp_path / "utd" / "checkpoints" / "ckpt_6").exists()
