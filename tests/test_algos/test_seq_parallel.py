"""Sequence/context parallelism for the DreamerV3 world-model update.

`--seq_devices S` runs the train step over a 2-D (data, seq) mesh: the
[T, B] batch arrives time-sharded over "seq" and batch-sharded over "data";
the per-timestep stages (conv encoder/decoder, reward/continue heads,
imagination) compute in that layout while sharding constraints reshard the
sequential RSSM scan to batch-only. These tests check (a) numerics: the
context-parallel step produces the same metrics as the unsharded step on
identical inputs, and (b) the e2e main runs under a (2, 4) mesh on the
virtual 8-device CPU harness.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu import ops
from sheeprl_tpu.utils.registry import tasks

from .test_multidevice import DV3_TINY


def _tiny_config(args):
    """Shared tiny-model hyperparameters for both Dreamer equivalence tests."""
    args.cnn_keys, args.mlp_keys = ["rgb"], []
    args.dense_units = 16
    args.hidden_size = 16
    args.recurrent_state_size = 16
    args.cnn_channels_multiplier = 4
    args.stochastic_size = 4
    args.discrete_size = 4
    args.horizon = 4
    args.mlp_layers = 1
    args.per_rank_batch_size = 4
    args.per_rank_sequence_length = 8
    return args


_OBS_SPACE = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}


def _tiny_setup(seed=0):
    from sheeprl_tpu.algos.dreamer_v3.agent import build_models
    from sheeprl_tpu.algos.dreamer_v3.args import DreamerV3Args
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import (
        DV3TrainState,
        make_optimizers,
    )

    args = _tiny_config(DreamerV3Args(num_envs=2, env_id="dummy"))
    world_model, actor, critic, target_critic = build_models(
        jax.random.PRNGKey(seed), [3], False, args, _OBS_SPACE, ["rgb"], []
    )
    world_opt, actor_opt, critic_opt = make_optimizers(args)
    state = DV3TrainState(
        world_model=world_model,
        actor=actor,
        critic=critic,
        target_critic=target_critic,
        world_opt=world_opt.init(world_model),
        actor_opt=actor_opt.init(actor),
        critic_opt=critic_opt.init(critic),
        moments=ops.Moments.init(args.moments_decay, args.moment_max),
    )
    return args, state, (world_opt, actor_opt, critic_opt)


def _assert_metrics_match(metrics_ref, metrics_sp, what):
    for name in metrics_ref:
        np.testing.assert_allclose(
            np.asarray(metrics_ref[name]),
            np.asarray(metrics_sp[name]),
            rtol=2e-3,
            atol=2e-3,
            err_msg=f"{what} metric {name} diverged under seq parallelism",
        )


def _tiny_batch(args):
    T, B = args.per_rank_sequence_length, args.per_rank_batch_size
    rng = np.random.default_rng(0)
    return {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 64, 64, 3), dtype=np.uint8)),
        "actions": jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, (T, B))]),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "dones": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }


@pytest.mark.timeout(900)
@pytest.mark.parametrize(
    "batch_size",
    [4, pytest.param(8, marks=pytest.mark.slow)],  # same layout regime now
)
def test_seq_parallel_matches_single_device(batch_size):
    """Both sizes run the replicated-scan layout (scan batch over "data",
    seq groups replicating the scan — see scan_batch_spec for why the
    fully-sharded alternative is off); batch_size=4 keeps B < devices, the
    long-context regime context parallelism exists for, batch_size=8 the
    B-divides-grid case that previously took the fully-sharded path."""
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.parallel import make_mesh, replicate, shard_time_batch

    args, state, (world_opt, actor_opt, critic_opt) = _tiny_setup()
    args.per_rank_batch_size = batch_size
    data = _tiny_batch(args)
    key = jax.random.PRNGKey(7)

    # single-device reference
    step_ref = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], [3], False
    )
    state_ref = jax.tree_util.tree_map(jnp.copy, state)
    _, metrics_ref = step_ref(state_ref, dict(data), key, jnp.float32(1.0))

    # (data=2, seq=4) context-parallel run on the same inputs
    mesh = make_mesh(8, seq_devices=4)
    assert mesh.shape == {"data": 2, "seq": 4}
    step_sp = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], [3], False, mesh=mesh
    )
    state_sp = replicate(jax.tree_util.tree_map(jnp.copy, state), mesh)
    sharded = shard_time_batch(dict(data), mesh, time_axis=0, batch_axis=1)
    _, metrics_sp = step_sp(state_sp, sharded, key, jnp.float32(1.0))

    _assert_metrics_match(metrics_ref, metrics_sp, "DV3")




def _run_seq_parallel_e2e(task_name, tmp_path, extra=()):
    """Shared e2e: a short real loop under a (2, 4) mesh (a dry run adds a
    single transition — too few for T=4 sequences), asserting a checkpoint."""
    drop = ["--per_rank_sequence_length", "--dry_run"]
    if task_name in ("dreamer_v1", "p2e_dv1"):
        drop.append("--discrete_size")  # Gaussian latent: no discrete size
    tasks[task_name](
        [a for a in DV3_TINY if not a.startswith(tuple(drop))]
        + [
            "--per_rank_sequence_length=4",
            "--per_rank_batch_size=2",
            "--num_devices=8",
            "--seq_devices=4",
            "--total_steps=8",
            "--learning_starts=6",
            "--buffer_size=16",
            "--checkpoint_every=8",
            *extra,
            f"--root_dir={tmp_path}",
            "--run_name=sp",
        ]
    )
    ckpt_dir = tmp_path / "sp" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in os.listdir(ckpt_dir))


@pytest.mark.timeout(600)
def test_dreamer_v3_seq_parallel_e2e(tmp_path):
    _run_seq_parallel_e2e("dreamer_v3", tmp_path)


@pytest.mark.timeout(300)
def test_seq_devices_must_divide_sequence_length(tmp_path):
    with pytest.raises(ValueError, match="not divisible"):
        tasks["dreamer_v3"](
            [a for a in DV3_TINY if not a.startswith("--per_rank_sequence_length")]
            + [
                "--per_rank_sequence_length=3",
                "--per_rank_batch_size=2",
                "--num_devices=8",
                "--seq_devices=4",
                f"--root_dir={tmp_path}",
                "--run_name=bad",
            ]
        )


def test_seq_devices_must_divide_device_count():
    from sheeprl_tpu.parallel import make_mesh

    with pytest.raises(ValueError, match="must divide"):
        make_mesh(8, seq_devices=3)


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_dreamer_v2_seq_parallel_matches_single_device():
    """The DreamerV2 context-parallel step must be metric-equivalent too."""
    from sheeprl_tpu.algos.dreamer_v2.agent import build_models
    from sheeprl_tpu.algos.dreamer_v2.args import DreamerV2Args
    from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import (
        DV2TrainState,
        make_optimizers,
        make_train_step,
    )
    from sheeprl_tpu.parallel import make_mesh, replicate, shard_time_batch

    args = _tiny_config(DreamerV2Args(num_envs=2, env_id="dummy"))
    world_model, actor, critic, target_critic = build_models(
        jax.random.PRNGKey(0), [3], False, args, _OBS_SPACE, ["rgb"], []
    )
    world_opt, actor_opt, critic_opt = make_optimizers(args)
    state = DV2TrainState(
        world_model=world_model,
        actor=actor,
        critic=critic,
        target_critic=target_critic,
        world_opt=world_opt.init(world_model),
        actor_opt=actor_opt.init(actor),
        critic_opt=critic_opt.init(critic),
    )
    data = _tiny_batch(args)
    key = jax.random.PRNGKey(7)

    step_ref = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], [3], False
    )
    state_ref = jax.tree_util.tree_map(jnp.copy, state)
    _, metrics_ref = step_ref(state_ref, dict(data), key, jnp.float32(1.0))

    mesh = make_mesh(8, seq_devices=4)
    step_sp = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], [3], False, mesh=mesh
    )
    state_sp = replicate(jax.tree_util.tree_map(jnp.copy, state), mesh)
    sharded = shard_time_batch(dict(data), mesh, time_axis=0, batch_axis=1)
    _, metrics_sp = step_sp(state_sp, sharded, key, jnp.float32(1.0))

    _assert_metrics_match(metrics_ref, metrics_sp, "DV2")


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_p2e_dv2_seq_parallel_e2e(tmp_path):
    """P2E-DV2 dual-AC + ensemble under the mesh (whole Dreamer family)."""
    _run_seq_parallel_e2e(
        "p2e_dv2", tmp_path,
        extra=("--exploration_steps=8", "--num_ensembles=2"),
    )


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_dreamer_v2_seq_parallel_e2e(tmp_path):
    """The DV2 main-loop wiring (shard_time_batch + divisibility asserts)."""
    _run_seq_parallel_e2e("dreamer_v2", tmp_path)


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_p2e_dv2_seq_parallel_matches_single_device():
    """The exploring-phase P2E-DV2 step (ensemble loss over time-shifted
    posteriors + disagreement reward + dual AC) must be metric-equivalent
    under the (2, 4) mesh."""
    from sheeprl_tpu.algos.p2e_dv2.agent import build_models
    from sheeprl_tpu.algos.p2e_dv2.args import P2EDV2Args
    from sheeprl_tpu.algos.p2e_dv2.p2e_dv2 import (
        P2EDV2TrainState,
        make_optimizers,
        make_train_step,
    )
    from sheeprl_tpu.parallel import make_mesh, replicate, shard_time_batch

    args = _tiny_config(P2EDV2Args(num_envs=2, env_id="dummy"))
    args.num_ensembles = 2
    (
        world_model, actor_task, critic_task, target_critic_task,
        actor_expl, critic_expl, target_critic_expl, ensembles,
    ) = build_models(jax.random.PRNGKey(0), [3], False, args, _OBS_SPACE, ["rgb"], [])
    optimizers = make_optimizers(args)
    (world_opt, actor_task_opt, critic_task_opt,
     actor_expl_opt, critic_expl_opt, ensemble_opt) = optimizers
    state = P2EDV2TrainState(
        world_model=world_model,
        actor_task=actor_task,
        critic_task=critic_task,
        target_critic_task=target_critic_task,
        actor_exploration=actor_expl,
        critic_exploration=critic_expl,
        target_critic_exploration=target_critic_expl,
        ensembles=ensembles,
        world_opt=world_opt.init(world_model),
        actor_task_opt=actor_task_opt.init(actor_task),
        critic_task_opt=critic_task_opt.init(critic_task),
        actor_exploration_opt=actor_expl_opt.init(actor_expl),
        critic_exploration_opt=critic_expl_opt.init(critic_expl),
        ensemble_opt=ensemble_opt.init(ensembles),
    )
    data = _tiny_batch(args)
    key = jax.random.PRNGKey(7)

    step_ref = make_train_step(
        args, optimizers, ["rgb"], [], [3], False, exploring=True
    )
    state_ref = jax.tree_util.tree_map(jnp.copy, state)
    _, metrics_ref = step_ref(state_ref, dict(data), key, jnp.float32(1.0))

    mesh = make_mesh(8, seq_devices=4)
    step_sp = make_train_step(
        args, optimizers, ["rgb"], [], [3], False, exploring=True, mesh=mesh
    )
    state_sp = replicate(jax.tree_util.tree_map(jnp.copy, state), mesh)
    sharded = shard_time_batch(dict(data), mesh, time_axis=0, batch_axis=1)
    _, metrics_sp = step_sp(state_sp, sharded, key, jnp.float32(1.0))

    _assert_metrics_match(metrics_ref, metrics_sp, "P2E-DV2")


@pytest.mark.timeout(600)
def test_dreamer_v1_seq_parallel_matches_single_device():
    """The Gaussian-RSSM (DV1) context-parallel step must be metric-equivalent."""
    from sheeprl_tpu.algos.dreamer_v1.agent import build_models
    from sheeprl_tpu.algos.dreamer_v1.args import DreamerV1Args
    from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import (
        DV1TrainState,
        make_optimizers,
        make_train_step,
    )
    from sheeprl_tpu.parallel import make_mesh, replicate, shard_time_batch

    args = _tiny_config(DreamerV1Args(num_envs=2, env_id="dummy"))
    world_model, actor, critic = build_models(
        jax.random.PRNGKey(0), [3], False, args, _OBS_SPACE, ["rgb"], []
    )
    world_opt, actor_opt, critic_opt = make_optimizers(args)
    state = DV1TrainState(
        world_model=world_model,
        actor=actor,
        critic=critic,
        world_opt=world_opt.init(world_model),
        actor_opt=actor_opt.init(actor),
        critic_opt=critic_opt.init(critic),
    )
    data = _tiny_batch(args)
    key = jax.random.PRNGKey(7)

    step_ref = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], []
    )
    state_ref = jax.tree_util.tree_map(jnp.copy, state)
    _, metrics_ref = step_ref(state_ref, dict(data), key)

    mesh = make_mesh(8, seq_devices=4)
    step_sp = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], mesh=mesh
    )
    state_sp = replicate(jax.tree_util.tree_map(jnp.copy, state), mesh)
    sharded = shard_time_batch(dict(data), mesh, time_axis=0, batch_axis=1)
    _, metrics_sp = step_sp(state_sp, sharded, key)

    _assert_metrics_match(metrics_ref, metrics_sp, "DV1")


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_dreamer_v1_seq_parallel_e2e(tmp_path):
    _run_seq_parallel_e2e("dreamer_v1", tmp_path)


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_p2e_dv1_seq_parallel_e2e(tmp_path):
    _run_seq_parallel_e2e(
        "p2e_dv1", tmp_path,
        extra=("--exploration_steps=8", "--num_ensembles=2"),
    )


@pytest.mark.timeout(900)
def test_p2e_dv1_seq_parallel_matches_single_device():
    """P2E-DV1's exploring-phase step (ensemble fit + disagreement reward +
    dual AC on the Gaussian RSSM) must be metric-equivalent under the mesh."""
    from sheeprl_tpu.algos.p2e_dv1.agent import build_models
    from sheeprl_tpu.algos.p2e_dv1.args import P2EDV1Args
    from sheeprl_tpu.algos.p2e_dv1.p2e_dv1 import (
        P2EDV1TrainState,
        make_optimizers,
        make_train_step,
    )
    from sheeprl_tpu.parallel import make_mesh, replicate, shard_time_batch

    args = _tiny_config(P2EDV1Args(num_envs=2, env_id="dummy"))
    args.num_ensembles = 2
    (
        world_model, actor_task, critic_task,
        actor_expl, critic_expl, ensembles,
    ) = build_models(jax.random.PRNGKey(0), [3], False, args, _OBS_SPACE, ["rgb"], [])
    optimizers = make_optimizers(args)
    (world_opt, actor_task_opt, critic_task_opt,
     actor_expl_opt, critic_expl_opt, ensemble_opt) = optimizers
    state = P2EDV1TrainState(
        world_model=world_model,
        actor_task=actor_task,
        critic_task=critic_task,
        actor_exploration=actor_expl,
        critic_exploration=critic_expl,
        ensembles=ensembles,
        world_opt=world_opt.init(world_model),
        actor_task_opt=actor_task_opt.init(actor_task),
        critic_task_opt=critic_task_opt.init(critic_task),
        actor_exploration_opt=actor_expl_opt.init(actor_expl),
        critic_exploration_opt=critic_expl_opt.init(critic_expl),
        ensemble_opt=ensemble_opt.init(ensembles),
    )
    data = _tiny_batch(args)
    key = jax.random.PRNGKey(7)

    step_ref = make_train_step(args, optimizers, ["rgb"], [], exploring=True)
    state_ref = jax.tree_util.tree_map(jnp.copy, state)
    _, metrics_ref = step_ref(state_ref, dict(data), key)

    mesh = make_mesh(8, seq_devices=4)
    step_sp = make_train_step(
        args, optimizers, ["rgb"], [], exploring=True, mesh=mesh
    )
    state_sp = replicate(jax.tree_util.tree_map(jnp.copy, state), mesh)
    sharded = shard_time_batch(dict(data), mesh, time_axis=0, batch_axis=1)
    _, metrics_sp = step_sp(state_sp, sharded, key)

    _assert_metrics_match(metrics_ref, metrics_sp, "P2E-DV1")
