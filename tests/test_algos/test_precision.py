"""--precision bfloat16 on the DreamerV3 train step: model forwards run in
bf16 (params stay f32 master weights, logits/losses/optimizers stay f32 —
the layer system casts weights to the input dtype). The test checks the
bf16 step produces finite metrics and f32 parameter updates, and that its
losses land near the f32 step's on the same batch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu import ops
from sheeprl_tpu.algos.dreamer_v3.agent import build_models
from sheeprl_tpu.algos.dreamer_v3.args import DreamerV3Args
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import (
    DV3TrainState,
    make_optimizers,
    make_train_step,
)


def _tiny_args(precision, remat=False):
    args = DreamerV3Args(num_envs=2, env_id="dummy")
    args.remat = remat
    args.cnn_keys, args.mlp_keys = ["rgb"], []
    args.dense_units = 16
    args.hidden_size = 16
    args.recurrent_state_size = 16
    args.cnn_channels_multiplier = 4
    args.stochastic_size = 4
    args.discrete_size = 4
    args.horizon = 4
    args.mlp_layers = 1
    args.per_rank_batch_size = 3
    args.per_rank_sequence_length = 5
    args.precision = precision
    return args


def _run_one_step(precision, remat=False):
    args = _tiny_args(precision, remat)
    T, B = args.per_rank_sequence_length, args.per_rank_batch_size
    obs_space = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}
    world_model, actor, critic, target_critic = build_models(
        jax.random.PRNGKey(0), [3], False, args, obs_space, ["rgb"], []
    )
    world_opt, actor_opt, critic_opt = make_optimizers(args)
    state = DV3TrainState(
        world_model=world_model,
        actor=actor,
        critic=critic,
        target_critic=target_critic,
        world_opt=world_opt.init(world_model),
        actor_opt=actor_opt.init(actor),
        critic_opt=critic_opt.init(critic),
        moments=ops.Moments.init(args.moments_decay, args.moment_max),
    )
    train_step = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], [3], False
    )
    rng = np.random.default_rng(0)
    data = {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 64, 64, 3), dtype=np.uint8)),
        "actions": jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, (T, B))]),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "dones": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    new_state, metrics = jax.jit(train_step)(
        state, data, jax.random.PRNGKey(7), jnp.float32(1.0)
    )
    return new_state, {k: float(v) for k, v in metrics.items()}


def test_bfloat16_step_finite_and_close_to_f32():
    state_bf, m_bf = _run_one_step("bfloat16")
    state_f32, m_f32 = _run_one_step("float32")

    assert all(np.isfinite(v) for v in m_bf.values()), m_bf
    # params and optimizer state stay f32 master copies
    for leaf in jax.tree_util.tree_leaves((state_bf.world_model, state_bf.actor)):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    # same batch, same seeds: bf16 losses land near the f32 ones (loose —
    # bf16 has ~3 significant digits and the step samples latents)
    for name in ("Loss/reconstruction_loss", "Loss/reward_loss", "State/kl"):
        ref = abs(m_f32[name]) + 1.0
        assert abs(m_bf[name] - m_f32[name]) / ref < 0.15, (
            name, m_bf[name], m_f32[name],
        )


def test_remat_step_matches_plain():
    # rematerialization changes memory usage, not numerics: same seeds, same
    # batch -> identical losses AND identical gradients (the post-update
    # params exercise the checkpointed backward)
    state_remat, m_remat = _run_one_step("float32", remat=True)
    state_plain, m_plain = _run_one_step("float32", remat=False)
    for name in (
        "Loss/reconstruction_loss", "Loss/reward_loss", "State/kl",
        "Loss/policy_loss", "Loss/value_loss",
    ):
        np.testing.assert_allclose(m_remat[name], m_plain[name], rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(state_remat.world_model),
        jax.tree_util.tree_leaves(state_plain.world_model),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
        )


def test_bfloat16_player_step():
    from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3

    args = _tiny_args("bfloat16")
    obs_space = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}
    world_model, actor, *_ = build_models(
        jax.random.PRNGKey(0), [3], False, args, obs_space, ["rgb"], []
    )
    player = PlayerDV3(
        encoder=world_model.encoder,
        rssm=world_model.rssm,
        actor=actor,
        actions_dim=(3,),
        stochastic_size=args.stochastic_size,
        discrete_size=args.discrete_size,
        recurrent_state_size=args.recurrent_state_size,
        is_continuous=False,
        compute_dtype="bfloat16",
    )
    state = player.init_states(2)
    assert state.recurrent_state.dtype == jnp.bfloat16
    obs = {"rgb": jnp.zeros((2, 64, 64, 3), jnp.float32)}
    new_state, actions = jax.jit(
        lambda p, s, o, k: p.step(s, o, k, jnp.float32(0.0))
    )(player, state, obs, jax.random.PRNGKey(1))
    # env-facing actions stay f32 one-hots; the carry stays bf16
    assert actions.dtype == jnp.float32
    assert new_state.recurrent_state.dtype == jnp.bfloat16
    assert np.all(np.isfinite(np.asarray(actions)))
    reset = player.reset_states(new_state, jnp.array([1.0, 0.0]))
    assert reset.recurrent_state.dtype == jnp.bfloat16


def _run_one_dv2_step(precision, continuous=False, remat=False):
    from sheeprl_tpu.algos.dreamer_v2 import agent as dv2_agent
    from sheeprl_tpu.algos.dreamer_v2.args import DreamerV2Args
    from sheeprl_tpu.algos.dreamer_v2 import dreamer_v2 as dv2

    args = DreamerV2Args(num_envs=2, env_id="dummy")
    args.remat = remat
    args.cnn_keys, args.mlp_keys = ["rgb"], []
    args.dense_units = 16
    args.hidden_size = 16
    args.recurrent_state_size = 16
    args.cnn_channels_multiplier = 4
    args.stochastic_size = 4
    args.discrete_size = 4
    args.horizon = 4
    args.mlp_layers = 1
    args.precision = precision
    T, B = 5, 3
    actions_dim = [2] if continuous else [3]
    obs_space = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}
    world_model, actor, critic, target_critic = dv2_agent.build_models(
        jax.random.PRNGKey(0), actions_dim, continuous, args, obs_space, ["rgb"], []
    )
    world_opt, actor_opt, critic_opt = dv2.make_optimizers(args)
    state = dv2.DV2TrainState(
        world_model=world_model,
        actor=actor,
        critic=critic,
        target_critic=target_critic,
        world_opt=world_opt.init(world_model),
        actor_opt=actor_opt.init(actor),
        critic_opt=critic_opt.init(critic),
    )
    train_step = dv2.make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], actions_dim, continuous
    )
    rng = np.random.default_rng(0)
    if continuous:
        actions = np.tanh(rng.normal(size=(T, B, 2)) * 3).astype(np.float32)
    else:
        actions = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (T, B))]
    data = {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 64, 64, 3), dtype=np.uint8)),
        "actions": jnp.asarray(actions),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "dones": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    _, metrics = jax.jit(train_step)(
        state, data, jax.random.PRNGKey(7), jnp.float32(1.0)
    )
    return {k: float(v) for k, v in metrics.items()}


@pytest.mark.slow
def test_dv2_bfloat16_step_finite_and_close_to_f32():
    m_bf = _run_one_dv2_step("bfloat16")
    m_f32 = _run_one_dv2_step("float32")
    assert all(np.isfinite(v) for v in m_bf.values()), m_bf
    for name in ("Loss/reconstruction_loss", "Loss/reward_loss", "State/kl"):
        ref = abs(m_f32[name]) + 1.0
        assert abs(m_bf[name] - m_f32[name]) / ref < 0.15, (
            name, m_bf[name], m_f32[name],
        )


@pytest.mark.slow
def test_dv2_remat_step_matches_plain():
    # remat changes memory usage, not numerics (now covers the DV2 RSSM scan
    # AND the imagination scan)
    m_remat = _run_one_dv2_step("float32", remat=True)
    m_plain = _run_one_dv2_step("float32", remat=False)
    for name in (
        "Loss/reconstruction_loss", "Loss/reward_loss", "State/kl",
        "Loss/policy_loss", "Loss/value_loss",
    ):
        np.testing.assert_allclose(m_remat[name], m_plain[name], rtol=1e-4)


def test_dv2_bfloat16_continuous_actions_finite():
    # saturated tanh actions round to exactly +/-1 in bf16; TanhNormal's
    # log_prob computes in f32 so the actor loss stays finite
    m = _run_one_dv2_step("bfloat16", continuous=True)
    assert all(np.isfinite(v) for v in m.values()), m


@pytest.mark.timeout(600)
@pytest.mark.parametrize("precision,remat", [("bfloat16", False), ("float32", True)])
def test_p2e_dv2_exploring_step_variants(precision, remat):
    """The EXPLORING train step under bf16 and under remat — ensemble fit +
    intrinsic disagreement reward + dual actor-critic (a dry run never
    reaches this branch: exploration flips off before the single training
    call; remat additionally checkpoints the dual imagination scans)."""
    from sheeprl_tpu.algos.p2e_dv2 import p2e_dv2 as p2e
    from sheeprl_tpu.algos.p2e_dv2.agent import build_models as build_p2e
    from sheeprl_tpu.algos.p2e_dv2.args import P2EDV2Args

    args = P2EDV2Args(num_envs=2, env_id="dummy")
    args.remat = remat
    args.cnn_keys, args.mlp_keys = ["rgb"], []
    args.dense_units = 8
    args.hidden_size = 8
    args.recurrent_state_size = 8
    args.cnn_channels_multiplier = 2
    args.stochastic_size = 4
    args.discrete_size = 4
    args.horizon = 4
    args.mlp_layers = 1
    args.num_ensembles = 2
    args.precision = precision
    T, B = 4, 2
    obs_space = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}
    (world_model, actor_task, critic_task, target_critic_task, actor_expl,
     critic_expl, target_critic_expl, ensembles) = build_p2e(
        jax.random.PRNGKey(0), [3], False, args, obs_space, ["rgb"], []
    )
    optimizers = p2e.make_optimizers(args)
    state = p2e.P2EDV2TrainState(
        world_model=world_model,
        actor_task=actor_task,
        critic_task=critic_task,
        target_critic_task=target_critic_task,
        actor_exploration=actor_expl,
        critic_exploration=critic_expl,
        target_critic_exploration=target_critic_expl,
        ensembles=ensembles,
        world_opt=optimizers[0].init(world_model),
        actor_task_opt=optimizers[1].init(actor_task),
        critic_task_opt=optimizers[2].init(critic_task),
        actor_exploration_opt=optimizers[3].init(actor_expl),
        critic_exploration_opt=optimizers[4].init(critic_expl),
        ensemble_opt=optimizers[5].init(ensembles),
    )
    train_step = p2e.make_train_step(
        args, optimizers, ["rgb"], [], [3], False, exploring=True
    )
    rng = np.random.default_rng(0)
    data = {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 64, 64, 3), dtype=np.uint8)),
        "actions": jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, (T, B))]),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "dones": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    _, metrics = jax.jit(train_step)(
        state, data, jax.random.PRNGKey(7), jnp.float32(1.0)
    )
    metrics = {k: float(v) for k, v in metrics.items()}
    assert "Loss/ensemble_loss" in metrics
    assert "Rewards/intrinsic" in metrics
    assert all(np.isfinite(v) for v in metrics.values()), metrics


def test_every_task_accepts_bfloat16_flag():
    """ISSUE 9: the require_float32 guard is lifted — every registered main
    parses --precision bfloat16 (the shared policy in ops/precision.py).
    Full bf16 train-step coverage lives in the per-algo tests; here we only
    prove no main re-grew a reject path, via each task's args dataclass."""
    import sheeprl_tpu.algos  # noqa: F401
    from sheeprl_tpu import algos

    assert not hasattr(algos.args, "require_float32")
    args = algos.args.StandardArgs(precision="bfloat16")
    assert args.precision == "bfloat16"
    with pytest.raises(ValueError, match="precision"):
        algos.args.StandardArgs(precision="float16")


def test_bfloat16_params_actually_update():
    state_bf, _ = _run_one_step("bfloat16")
    args = _tiny_args("bfloat16")
    obs_space = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}
    world_model0, *_ = build_models(
        jax.random.PRNGKey(0), [3], False, args, obs_space, ["rgb"], []
    )
    before = jax.tree_util.tree_leaves(world_model0)
    after = jax.tree_util.tree_leaves(state_bf.world_model)
    changed = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(after, before)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
    )
    assert changed


def test_dv1_remat_step_matches_plain():
    """DV1-family remat (Gaussian RSSM scan + imagination checkpoint) is
    numerics-neutral, incl. the behaviour losses."""
    from sheeprl_tpu.algos.dreamer_v1.agent import build_models as build_dv1
    from sheeprl_tpu.algos.dreamer_v1.args import DreamerV1Args
    from sheeprl_tpu.algos.dreamer_v1 import dreamer_v1 as dv1

    def run(remat):
        args = DreamerV1Args(num_envs=2, env_id="dummy")
        args.remat = remat
        args.cnn_keys, args.mlp_keys = ["rgb"], []
        args.dense_units = 16
        args.hidden_size = 16
        args.recurrent_state_size = 16
        args.cnn_channels_multiplier = 4
        args.stochastic_size = 4
        args.horizon = 4
        args.mlp_layers = 1
        T, B = 5, 3
        obs_space = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}
        world_model, actor, critic = build_dv1(
            jax.random.PRNGKey(0), [3], False, args, obs_space, ["rgb"], []
        )
        world_opt, actor_opt, critic_opt = dv1.make_optimizers(args)
        state = dv1.DV1TrainState(
            world_model=world_model,
            actor=actor,
            critic=critic,
            world_opt=world_opt.init(world_model),
            actor_opt=actor_opt.init(actor),
            critic_opt=critic_opt.init(critic),
        )
        step = dv1.make_train_step(
            args, world_opt, actor_opt, critic_opt, ["rgb"], []
        )
        rng = np.random.default_rng(0)
        data = {
            "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 64, 64, 3), dtype=np.uint8)),
            "actions": jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, (T, B))]),
            "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
            "dones": jnp.zeros((T, B, 1), jnp.float32),
            "is_first": jnp.zeros((T, B, 1), jnp.float32),
        }
        _, metrics = step(state, data, jax.random.PRNGKey(7))
        return {k: float(v) for k, v in metrics.items()}

    m_remat, m_plain = run(True), run(False)
    for name in (
        "Loss/reconstruction_loss", "Loss/reward_loss", "State/kl",
        "Loss/policy_loss", "Loss/value_loss",
        # gradient norms exercise the checkpointed backward, not just the
        # forward losses
        "Grads/world_model", "Grads/actor", "Grads/critic",
    ):
        np.testing.assert_allclose(m_remat[name], m_plain[name], rtol=1e-3)


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_p2e_dv1_exploring_step_remat_matches_plain():
    """P2E-DV1's EXPLORING step under remat (ensemble fit + disagreement
    reward through the checkpointed dual imaginations) is numerics-neutral."""
    from sheeprl_tpu.algos.p2e_dv1.agent import build_models as build_p2e
    from sheeprl_tpu.algos.p2e_dv1.args import P2EDV1Args
    from sheeprl_tpu.algos.p2e_dv1 import p2e_dv1 as p2e

    def run(remat):
        args = P2EDV1Args(num_envs=2, env_id="dummy")
        args.remat = remat
        args.cnn_keys, args.mlp_keys = ["rgb"], []
        args.dense_units = 8
        args.hidden_size = 8
        args.recurrent_state_size = 8
        args.cnn_channels_multiplier = 2
        args.stochastic_size = 4
        args.horizon = 4
        args.mlp_layers = 1
        args.num_ensembles = 2
        T, B = 4, 2
        obs_space = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}
        (world_model, actor_task, critic_task,
         actor_expl, critic_expl, ensembles) = build_p2e(
            jax.random.PRNGKey(0), [3], False, args, obs_space, ["rgb"], []
        )
        optimizers = p2e.make_optimizers(args)
        (world_opt, at_opt, ct_opt, ae_opt, ce_opt, ens_opt) = optimizers
        state = p2e.P2EDV1TrainState(
            world_model=world_model,
            actor_task=actor_task,
            critic_task=critic_task,
            actor_exploration=actor_expl,
            critic_exploration=critic_expl,
            ensembles=ensembles,
            world_opt=world_opt.init(world_model),
            actor_task_opt=at_opt.init(actor_task),
            critic_task_opt=ct_opt.init(critic_task),
            actor_exploration_opt=ae_opt.init(actor_expl),
            critic_exploration_opt=ce_opt.init(critic_expl),
            ensemble_opt=ens_opt.init(ensembles),
        )
        step = p2e.make_train_step(args, optimizers, ["rgb"], [], exploring=True)
        rng = np.random.default_rng(0)
        data = {
            "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 64, 64, 3), dtype=np.uint8)),
            "actions": jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, (T, B))]),
            "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
            "dones": jnp.zeros((T, B, 1), jnp.float32),
            "is_first": jnp.zeros((T, B, 1), jnp.float32),
        }
        _, metrics = step(state, data, jax.random.PRNGKey(7))
        return {k: float(v) for k, v in metrics.items()}

    m_remat, m_plain = run(True), run(False)
    assert all(np.isfinite(v) for v in m_remat.values()), m_remat
    for name in (
        "Loss/reconstruction_loss", "Loss/ensemble_loss",
        "Loss/policy_loss_exploration", "Loss/value_loss_exploration",
        "Grads/actor_exploration", "Grads/world_model",
    ):
        np.testing.assert_allclose(m_remat[name], m_plain[name], rtol=1e-3)


# =============================================================================
# Universal mixed precision (ISSUE 9): model-free parity + checkpoint
# round-trip
# =============================================================================


def _sac_one_step(precision, seed=0):
    """One SAC gradient step at tiny widths under the given precision."""
    from sheeprl_tpu.algos.sac.agent import SACAgent
    from sheeprl_tpu.algos.sac.args import SACArgs
    from sheeprl_tpu.algos.sac.sac import TrainState, make_optimizers, make_train_step

    args = SACArgs()
    args.precision = precision
    agent = SACAgent.init(
        jax.random.PRNGKey(seed), 6, 2,
        actor_hidden_size=16, critic_hidden_size=16,
        precision=precision,
    )
    qf_optim, actor_optim, alpha_optim = make_optimizers(args)
    state = TrainState(
        agent=agent,
        qf_opt=qf_optim.init(agent.critics),
        actor_opt=actor_optim.init(agent.actor),
        alpha_opt=alpha_optim.init(agent.log_alpha),
    )
    train_step = make_train_step(args, qf_optim, actor_optim, alpha_optim)
    rng = np.random.default_rng(seed)
    G, B = 2, 8
    data = {
        "observations": jnp.asarray(rng.normal(size=(G, B, 6)).astype(np.float32)),
        "next_observations": jnp.asarray(rng.normal(size=(G, B, 6)).astype(np.float32)),
        "actions": jnp.asarray(rng.uniform(-1, 1, size=(G, B, 2)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(G, B, 1)).astype(np.float32)),
        "dones": jnp.zeros((G, B, 1), jnp.float32),
    }
    new_state, metrics = train_step(
        state, data, jax.random.PRNGKey(7), jnp.asarray(True)
    )
    return new_state, {k: float(v) for k, v in metrics.items()}


def test_sac_bfloat16_step_finite_and_close_to_f32():
    """Model-free half of the bf16 parity receipt: one SAC update in bf16
    lands near the f32 update on the same batch, with f32 master params."""
    state_bf, m_bf = _sac_one_step("bfloat16")
    state_f32, m_f32 = _sac_one_step("float32")
    assert all(np.isfinite(v) for v in m_bf.values()), m_bf
    for name in m_f32:
        np.testing.assert_allclose(m_bf[name], m_f32[name], rtol=0.15, atol=0.05,
                                   err_msg=name)
    for leaf in jax.tree_util.tree_leaves(state_bf.agent):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32  # master params stay full width


def test_bfloat16_checkpoint_roundtrip_keeps_f32_masters(tmp_path):
    """--precision bfloat16 checkpoint round-trip: the saved state is the
    fp32 master copy and restores EXACTLY (bit-identical), with no bf16
    leaves anywhere in the stored agent."""
    from sheeprl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    state_bf, _ = _sac_one_step("bfloat16")
    path = str(tmp_path / "ckpt")
    save_checkpoint(
        path,
        {"agent": state_bf.agent, "qf_optimizer": state_bf.qf_opt, "global_step": 3},
        block=True,
    )
    restored = load_checkpoint(
        path, {"agent": state_bf.agent, "qf_optimizer": state_bf.qf_opt, "global_step": 0}
    )
    orig = jax.tree_util.tree_leaves((state_bf.agent, state_bf.qf_opt))
    back = jax.tree_util.tree_leaves((restored["agent"], restored["qf_optimizer"]))
    assert len(orig) == len(back)
    for a, b in zip(orig, back):
        if hasattr(a, "dtype"):
            assert a.dtype == b.dtype
            if jnp.issubdtype(a.dtype, jnp.floating):
                assert a.dtype == jnp.float32
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored["global_step"]) == 3
    # the restored agent still runs a bf16 step (compute_dtype static
    # survives the round-trip through the template)
    assert restored["agent"].actor.compute_dtype == "bfloat16"


def test_ppo_recurrent_bfloat16_states_stay_bf16():
    """The LSTM carry contract under bf16: initial states, stepped states
    and reset-masked states all stay in the compute dtype (a silent f32
    promotion would retrace the policy jit every step)."""
    from sheeprl_tpu.algos.ppo_recurrent.agent import RecurrentPPOAgent

    agent = RecurrentPPOAgent.init(
        jax.random.PRNGKey(0), 4, 2, lstm_hidden_size=8,
        actor_hidden_size=8, critic_hidden_size=8, precision="bfloat16",
    )
    state = agent.initial_states(3)
    assert all(
        leaf.dtype == jnp.bfloat16 for leaf in jax.tree_util.tree_leaves(state)
    )
    obs = jnp.zeros((3, 4), jnp.float32)
    action, logprob, value, new_state = agent.step(obs, state, jax.random.PRNGKey(1))
    assert all(
        leaf.dtype == jnp.bfloat16 for leaf in jax.tree_util.tree_leaves(new_state)
    )
    assert logprob.dtype == jnp.float32 and value.dtype == jnp.float32
    d = jnp.ones((3, 1), jnp.float32)
    masked = jax.tree_util.tree_map(lambda s: (1.0 - d).astype(s.dtype) * s, new_state)
    assert all(
        leaf.dtype == jnp.bfloat16 for leaf in jax.tree_util.tree_leaves(masked)
    )
