"""--eval_only: load a checkpoint and run greedy evaluation episodes
without training (a capability the reference v0.2.1 lacks — its users
re-run training mains to get the final test() episode). Train a tiny
checkpoint via --dry_run, then evaluate it with --eval_only."""

import glob
import os

import pytest

TINY_PPO = [
    "--dry_run",
    "--num_devices=1",
    "--num_envs=1",
    "--sync_env",
    "--env_id=discrete_dummy",
    "--rollout_steps=8",
    "--per_rank_batch_size=4",
    "--update_epochs=1",
]

TINY_DV3 = [
    "--num_devices=1",
    "--num_envs=1",
    "--sync_env",
    "--env_id=discrete_dummy",
    "--per_rank_batch_size=1",
    "--per_rank_sequence_length=1",
    "--buffer_size=4",
    "--learning_starts=0",
    "--gradient_steps=1",
    "--horizon=4",
    "--dense_units=8",
    "--cnn_channels_multiplier=2",
    "--recurrent_state_size=8",
    "--hidden_size=8",
    "--stochastic_size=4",
    "--discrete_size=4",
    "--mlp_layers=1",
    "--train_every=1",
    "--checkpoint_every=1",
]


def _latest_ckpt(root):
    # checkpoints are ckpt_<step> DIRECTORIES; skip the args.json and
    # resume.npz (ISSUE 12 deep-state) sidecars that share the prefix
    ckpts = [
        p for p in glob.glob(os.path.join(root, "**", "ckpt_*"), recursive=True)
        if os.path.isdir(p)
    ]
    assert ckpts, f"no checkpoint under {root}"
    return sorted(ckpts, key=lambda p: int(p.rsplit("_", 1)[-1]))[-1]


def test_ppo_eval_only_runs_episodes(tmp_path):
    from sheeprl_tpu.algos.ppo.ppo import main

    train_dir = str(tmp_path / "train")
    main([*TINY_PPO, f"--root_dir={train_dir}", "--run_name=t"])
    ckpt = _latest_ckpt(train_dir)

    eval_dir = str(tmp_path / "eval")
    main([
        "--eval_only",
        f"--checkpoint_path={ckpt}",
        "--test_episodes=2",
        f"--root_dir={eval_dir}",
        "--run_name=e",
    ])
    # TB event files written for the eval run prove the episodes ran
    events = glob.glob(os.path.join(eval_dir, "**", "events.*"), recursive=True)
    assert events


def test_dreamer_v3_eval_only_runs_episodes(tmp_path):
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import main

    train_dir = str(tmp_path / "train")
    main(["--dry_run", *TINY_DV3, f"--root_dir={train_dir}", "--run_name=t"])
    ckpt = _latest_ckpt(train_dir)

    eval_dir = str(tmp_path / "eval")
    main([
        "--eval_only",
        f"--checkpoint_path={ckpt}",
        "--test_episodes=2",
        f"--root_dir={eval_dir}",
        "--run_name=e",
    ])
    events = glob.glob(os.path.join(eval_dir, "**", "events.*"), recursive=True)
    assert events


def test_eval_only_requires_checkpoint():
    from sheeprl_tpu.algos.ppo.ppo import main

    with pytest.raises(ValueError, match="checkpoint_path"):
        main([*TINY_PPO, "--eval_only"])


def test_eval_only_still_requires_checkpoint_for_decoupled():
    from sheeprl_tpu.algos.ppo.ppo_decoupled import main

    with pytest.raises(ValueError, match="checkpoint_path"):
        main(["--eval_only", "--env_id=discrete_dummy"])


@pytest.mark.parametrize("via", ["coupled", "decoupled"])
def test_eval_of_decoupled_checkpoint(tmp_path, via):
    """Decoupled checkpoints share the coupled twin's key contract — prove
    it both ways: train dreamer_v3_decoupled (player + trainer mesh), then
    --eval_only the checkpoint (a) with coupled dreamer_v3 directly and
    (b) through the decoupled task itself, which routes to the coupled
    evaluator natively (VERDICT r3 #7)."""
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import main as coupled_main
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3_decoupled import (
        main as decoupled_main,
    )

    train_dir = str(tmp_path / "train")
    decoupled_main([
        "--dry_run",
        "--env_id=discrete_dummy",
        "--num_envs=1",
        "--sync_env",
        "--per_rank_batch_size=2",
        "--per_rank_sequence_length=1",
        "--buffer_size=4",
        "--learning_starts=0",
        "--gradient_steps=1",
        "--horizon=4",
        "--dense_units=8",
        "--cnn_channels_multiplier=2",
        "--recurrent_state_size=8",
        "--hidden_size=8",
        "--stochastic_size=4",
        "--discrete_size=4",
        "--mlp_layers=1",
        "--train_every=1",
        "--checkpoint_every=1",
        "--cnn_keys", "rgb",
        f"--root_dir={train_dir}",
        "--run_name=t",
    ])
    ckpt = _latest_ckpt(train_dir)

    eval_main = coupled_main if via == "coupled" else decoupled_main
    eval_dir = str(tmp_path / "eval")
    eval_main([
        "--eval_only",
        f"--checkpoint_path={ckpt}",
        "--test_episodes=2",
        f"--root_dir={eval_dir}",
        "--run_name=e",
    ])
    events = glob.glob(os.path.join(eval_dir, "**", "events.*"), recursive=True)
    assert events
