"""End-to-end smoke tests for PPO — the reference's test backbone
(/root/reference/tests/test_algos/test_algos.py): invoke main() in-process
with a tiny config, assert the checkpoint exists and its key set matches."""

import os

import pytest

from sheeprl_tpu.utils.checkpoint import load_checkpoint, load_checkpoint_args
from sheeprl_tpu.utils.registry import tasks
import sheeprl_tpu.algos  # noqa: F401 - fire registrations


def tiny_argv(tmp_path, env_id, run_name, extra=()):
    return [
        "--env_id", env_id,
        "--dry_run",
        "--num_envs", "1",
        "--rollout_steps", "8",
        "--per_rank_batch_size", "4",
        "--update_epochs", "1",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--cnn_features_dim", "16",
        "--mlp_features_dim", "8",
        "--root_dir", str(tmp_path),
        "--run_name", run_name,
        *extra,
    ]


@pytest.mark.timeout(300)
@pytest.mark.parametrize(
    "env_id", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"]
)
def test_ppo_dry_run_dummy_envs(tmp_path, env_id):
    tasks["ppo"](tiny_argv(tmp_path, env_id, env_id))
    ckpt_dir = tmp_path / env_id / "checkpoints"
    ckpts = sorted(os.listdir(ckpt_dir))
    assert any(c.startswith("ckpt_1") for c in ckpts)
    state = load_checkpoint(str(ckpt_dir / "ckpt_1"))
    assert set(state.keys()) == {"agent", "optimizer", "update_step"}
    cfg = load_checkpoint_args(str(ckpt_dir / "ckpt_1"))
    assert cfg["env_id"] == env_id


@pytest.mark.timeout(300)
def test_ppo_cartpole_and_resume(tmp_path):
    tasks["ppo"](tiny_argv(tmp_path, "CartPole-v1", "first"))
    ckpt = str(tmp_path / "first" / "checkpoints" / "ckpt_1")
    assert os.path.exists(ckpt)
    # resume: config restored from the checkpoint's args.json
    tasks["ppo"](["--checkpoint_path", ckpt])
    ckpt2 = tmp_path / "first" / "checkpoints" / "ckpt_2"
    assert ckpt2.exists()


@pytest.mark.timeout(300)
@pytest.mark.parametrize("env_id", ["CartPole-v1", "Pendulum-v1", "pixeltoy"])
def test_ppo_jax_env_backend_dry_run(tmp_path, env_id):
    """ISSUE 6: --env_backend jax runs the whole rollout as one jitted
    Anakin scan; GAE/train/checkpoint/eval are the unchanged host-path jits."""
    run = f"jax_{env_id}"
    # num_envs must divide the 8-device test mesh (the env batch is sharded)
    tasks["ppo"](
        tiny_argv(
            tmp_path, env_id, run,
            extra=("--env_backend", "jax", "--num_envs", "8"),
        )
    )
    ckpt_dir = tmp_path / run / "checkpoints"
    state = load_checkpoint(str(ckpt_dir / "ckpt_1"))
    assert set(state.keys()) == {"agent", "optimizer", "update_step"}


@pytest.mark.timeout(300)
def test_ppo_env_backend_host_is_bit_exact_vs_default(tmp_path):
    """The acceptance parity receipt: an explicit --env_backend host run is
    bitwise-identical to a run with no flag at all (the pre-PR code path) —
    the Anakin wiring must not perturb the default path."""
    import numpy as np
    import jax

    tasks["ppo"](tiny_argv(tmp_path, "CartPole-v1", "default"))
    tasks["ppo"](
        tiny_argv(
            tmp_path, "CartPole-v1", "host", extra=("--env_backend", "host")
        )
    )
    a = load_checkpoint(str(tmp_path / "default" / "checkpoints" / "ckpt_1"))
    b = load_checkpoint(str(tmp_path / "host" / "checkpoints" / "ckpt_1"))
    leaves_a = jax.tree_util.tree_leaves(a["agent"])
    leaves_b = jax.tree_util.tree_leaves(b["agent"])
    assert len(leaves_a) == len(leaves_b)
    for la, lb in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


@pytest.mark.timeout(120)
def test_env_backend_flag_validation():
    from sheeprl_tpu.algos.ppo.args import PPOArgs

    with pytest.raises(ValueError, match="env_backend"):
        PPOArgs(env_backend="gpu")
    assert PPOArgs(env_backend="jax").env_backend == "jax"
