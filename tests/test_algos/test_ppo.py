"""End-to-end smoke tests for PPO — the reference's test backbone
(/root/reference/tests/test_algos/test_algos.py): invoke main() in-process
with a tiny config, assert the checkpoint exists and its key set matches."""

import os

import pytest

from sheeprl_tpu.utils.checkpoint import load_checkpoint, load_checkpoint_args
from sheeprl_tpu.utils.registry import tasks
import sheeprl_tpu.algos  # noqa: F401 - fire registrations


def tiny_argv(tmp_path, env_id, run_name, extra=()):
    return [
        "--env_id", env_id,
        "--dry_run",
        "--num_envs", "1",
        "--rollout_steps", "8",
        "--per_rank_batch_size", "4",
        "--update_epochs", "1",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--cnn_features_dim", "16",
        "--mlp_features_dim", "8",
        "--root_dir", str(tmp_path),
        "--run_name", run_name,
        *extra,
    ]


@pytest.mark.timeout(300)
@pytest.mark.parametrize(
    "env_id", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"]
)
def test_ppo_dry_run_dummy_envs(tmp_path, env_id):
    tasks["ppo"](tiny_argv(tmp_path, env_id, env_id))
    ckpt_dir = tmp_path / env_id / "checkpoints"
    ckpts = sorted(os.listdir(ckpt_dir))
    assert any(c.startswith("ckpt_1") for c in ckpts)
    state = load_checkpoint(str(ckpt_dir / "ckpt_1"))
    assert set(state.keys()) == {"agent", "optimizer", "update_step"}
    cfg = load_checkpoint_args(str(ckpt_dir / "ckpt_1"))
    assert cfg["env_id"] == env_id


@pytest.mark.timeout(300)
def test_ppo_cartpole_and_resume(tmp_path):
    tasks["ppo"](tiny_argv(tmp_path, "CartPole-v1", "first"))
    ckpt = str(tmp_path / "first" / "checkpoints" / "ckpt_1")
    assert os.path.exists(ckpt)
    # resume: config restored from the checkpoint's args.json
    tasks["ppo"](["--checkpoint_path", ckpt])
    ckpt2 = tmp_path / "first" / "checkpoints" / "ckpt_2"
    assert ckpt2.exists()
