"""End-to-end smoke tests for DreamerV3 (mirrors the reference e2e strategy,
/root/reference/tests/test_algos/test_algos.py:520-569: tiny config, dummy
env, dry run, checkpoint key contract)."""

import os

import numpy as np
import pytest

from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import main

TINY = [
    "--dry_run",
    "--num_devices=1",
    "--num_envs=1",
    "--sync_env",
    "--per_rank_batch_size=1",
    "--per_rank_sequence_length=1",
    "--buffer_size=4",
    "--learning_starts=0",
    "--gradient_steps=1",
    "--horizon=4",
    "--dense_units=8",
    "--cnn_channels_multiplier=2",
    "--recurrent_state_size=8",
    "--hidden_size=8",
    "--stochastic_size=4",
    "--discrete_size=4",
    "--mlp_layers=1",
    "--train_every=1",
    "--checkpoint_every=1",
]


@pytest.mark.parametrize(
    "env_id", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"]
)
def test_dreamer_v3_dry_run(tmp_path, env_id):
    main(
        TINY
        + [
            f"--env_id={env_id}",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    assert os.path.isdir(ckpt_dir)
    entries = sorted(os.listdir(ckpt_dir))
    assert any(e.startswith("ckpt_") for e in entries)


def test_dreamer_v3_checkpoint_contract_and_resume(tmp_path):
    args = TINY + [
        "--env_id=discrete_dummy",
        f"--root_dir={tmp_path}",
        "--run_name=test",
        "--cnn_keys", "rgb",
        "--checkpoint_buffer",
    ]
    main(args)
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    ckpts = [e for e in sorted(os.listdir(ckpt_dir)) if not e.endswith(".json")]
    ckpt = os.path.join(ckpt_dir, [e for e in ckpts if not e.endswith(".npz")][-1])
    # key contract (reference test_algos.py:571-584 analog)
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    raw = load_checkpoint(ckpt)
    for k in (
        "world_model",
        "actor",
        "critic",
        "target_critic",
        "world_optimizer",
        "actor_optimizer",
        "critic_optimizer",
        "moments",
        "expl_decay_steps",
        "global_step",
        "batch_size",
    ):
        assert k in raw, f"missing checkpoint key {k}"
    assert os.path.exists(ckpt + "_buffer.npz")
    # resume from the checkpoint
    main([f"--checkpoint_path={ckpt}"])


def test_dreamer_v3_mlp_only(tmp_path):
    # vector-obs env: exercises the MLP encoder/decoder path (no CNN)
    main(
        TINY
        + [
            "--env_id=CartPole-v1",
            "--action_repeat=1",
            "--max_episode_steps=-1",
            f"--root_dir={tmp_path}",
            "--run_name=test",
        ]
    )
    assert os.path.isdir(os.path.join(tmp_path, "test", "checkpoints"))


def test_blob_step_matches_dict_step():
    """The one-transfer blob path (make_blob_step) must produce the same
    player state, env-action indices, and replay row as the separate-puts
    dict path on identical inputs — the blob is transport, not math."""
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3, build_models
    from sheeprl_tpu.algos.dreamer_v3.args import DreamerV3Args
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_blob_step
    from sheeprl_tpu.algos.dreamer_v3.utils import make_device_preprocess
    from sheeprl_tpu.algos.ppo.agent import env_action_indices
    from sheeprl_tpu.data import StepBlobCodec

    args = DreamerV3Args(num_envs=2, env_id="dummy")
    args.dense_units = 8
    args.hidden_size = 8
    args.recurrent_state_size = 8
    args.cnn_channels_multiplier = 2
    args.stochastic_size = 4
    args.discrete_size = 4
    args.mlp_layers = 1
    actions_dim, n_envs = [3], 2
    obs_space = {
        "rgb": type("S", (), {"shape": (64, 64, 3)})(),
        "vec": type("S", (), {"shape": (5,)})(),
    }
    wm, actor, critic, _ = build_models(
        jax.random.PRNGKey(0), actions_dim, False, args, obs_space,
        ["rgb"], ["vec"],
    )
    player = PlayerDV3(
        encoder=wm.encoder, rssm=wm.rssm, actor=actor,
        actions_dim=(3,), stochastic_size=args.stochastic_size,
        discrete_size=args.discrete_size,
        recurrent_state_size=args.recurrent_state_size,
        is_continuous=False, compute_dtype=args.precision,
    )
    prep = make_device_preprocess(("rgb",))
    codec = StepBlobCodec(
        {"rgb": (64, 64, 3)},
        {"vec": (5,), "rewards": (1,), "dones": (1,), "is_first": (1,)},
        idx_len=2 * n_envs, n_envs=n_envs,
    )
    blob_step = make_blob_step(codec, ("rgb", "vec"), prep, actions_dim, False)

    rng = np.random.default_rng(0)
    obs_np = {
        "rgb": rng.integers(0, 256, (n_envs, 64, 64, 3), dtype=np.uint8),
        "vec": rng.normal(size=(n_envs, 5)).astype(np.float32),
    }
    floats = {
        "rewards": rng.normal(size=(n_envs, 1)).astype(np.float32),
        "dones": np.zeros((n_envs, 1), np.float32),
        "is_first": np.ones((n_envs, 1), np.float32),
    }
    idx = np.array([0, 0, 0, 1], np.int32)
    state0 = player.init_states(n_envs)
    key = jax.random.PRNGKey(7)
    expl = jnp.float32(0.0)

    # dict path (the host/memmap route)
    dev_obs = {k: jnp.asarray(v) for k, v in obs_np.items()}
    dict_state, dict_acts = jax.jit(
        lambda p, s, o, k, e: p.step(s, prep(o), k, e, is_training=True, mask=None)
    )(player, state0, dev_obs, key, expl)
    dict_idx = env_action_indices(dict_acts, actions_dim, False)

    # blob path
    blob = codec.pack(
        {"rgb": obs_np["rgb"]}, {"vec": obs_np["vec"], **floats}, idx
    )
    blob_state, blob_env_idx, row, idx_dev = blob_step(
        player, state0, jnp.asarray(blob), key, expl
    )

    np.testing.assert_array_equal(np.asarray(blob_env_idx), np.asarray(dict_idx))
    for a, b in zip(
        jax.tree_util.tree_leaves(dict_state), jax.tree_util.tree_leaves(blob_state)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(row["actions"][0]), np.asarray(dict_acts), atol=1e-6
    )
    for k in obs_np:
        np.testing.assert_array_equal(np.asarray(row[k][0]), obs_np[k])
    for k in floats:
        np.testing.assert_array_equal(np.asarray(row[k][0]), floats[k])
    np.testing.assert_array_equal(np.asarray(idx_dev), idx)


@pytest.mark.timeout(300)
def test_dreamer_v3_jax_env_backend_dry_run(tmp_path):
    """ISSUE 6: --env_backend jax collects via the Anakin scan and writes
    into the device ring with reserve()/add_direct(); the dry run trains and
    checkpoints like the host path."""
    main(
        TINY
        + [
            "--env_id=CartPole-v1",
            "--env_backend=jax",
            "--num_envs=1",
            f"--root_dir={tmp_path}",
            "--run_name=jax_backend",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "jax_backend", "checkpoints")
    entries = sorted(os.listdir(ckpt_dir))
    assert any(e.startswith("ckpt_") for e in entries)


@pytest.mark.timeout(300)
def test_dreamer_v3_jax_env_backend_rejects_memmap(tmp_path):
    with pytest.raises(ValueError, match="device replay"):
        main(
            TINY
            + [
                "--env_id=CartPole-v1",
                "--env_backend=jax",
                "--memmap_buffer",
                f"--root_dir={tmp_path}",
                "--run_name=jax_memmap",
            ]
        )
