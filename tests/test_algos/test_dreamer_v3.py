"""End-to-end smoke tests for DreamerV3 (mirrors the reference e2e strategy,
/root/reference/tests/test_algos/test_algos.py:520-569: tiny config, dummy
env, dry run, checkpoint key contract)."""

import os

import numpy as np
import pytest

from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import main

TINY = [
    "--dry_run",
    "--num_devices=1",
    "--num_envs=1",
    "--sync_env",
    "--per_rank_batch_size=1",
    "--per_rank_sequence_length=1",
    "--buffer_size=4",
    "--learning_starts=0",
    "--gradient_steps=1",
    "--horizon=4",
    "--dense_units=8",
    "--cnn_channels_multiplier=2",
    "--recurrent_state_size=8",
    "--hidden_size=8",
    "--stochastic_size=4",
    "--discrete_size=4",
    "--mlp_layers=1",
    "--train_every=1",
    "--checkpoint_every=1",
]


@pytest.mark.parametrize(
    "env_id", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"]
)
def test_dreamer_v3_dry_run(tmp_path, env_id):
    main(
        TINY
        + [
            f"--env_id={env_id}",
            f"--root_dir={tmp_path}",
            "--run_name=test",
            "--cnn_keys", "rgb",
        ]
    )
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    assert os.path.isdir(ckpt_dir)
    entries = sorted(os.listdir(ckpt_dir))
    assert any(e.startswith("ckpt_") for e in entries)


def test_dreamer_v3_checkpoint_contract_and_resume(tmp_path):
    args = TINY + [
        "--env_id=discrete_dummy",
        f"--root_dir={tmp_path}",
        "--run_name=test",
        "--cnn_keys", "rgb",
        "--checkpoint_buffer",
    ]
    main(args)
    ckpt_dir = os.path.join(tmp_path, "test", "checkpoints")
    ckpts = [e for e in sorted(os.listdir(ckpt_dir)) if not e.endswith(".json")]
    ckpt = os.path.join(ckpt_dir, [e for e in ckpts if not e.endswith(".npz")][-1])
    # key contract (reference test_algos.py:571-584 analog)
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    raw = load_checkpoint(ckpt)
    for k in (
        "world_model",
        "actor",
        "critic",
        "target_critic",
        "world_optimizer",
        "actor_optimizer",
        "critic_optimizer",
        "moments",
        "expl_decay_steps",
        "global_step",
        "batch_size",
    ):
        assert k in raw, f"missing checkpoint key {k}"
    assert os.path.exists(ckpt + "_buffer.npz")
    # resume from the checkpoint
    main([f"--checkpoint_path={ckpt}"])


def test_dreamer_v3_mlp_only(tmp_path):
    # vector-obs env: exercises the MLP encoder/decoder path (no CNN)
    main(
        TINY
        + [
            "--env_id=CartPole-v1",
            "--action_repeat=1",
            "--max_episode_steps=-1",
            f"--root_dir={tmp_path}",
            "--run_name=test",
        ]
    )
    assert os.path.isdir(os.path.join(tmp_path, "test", "checkpoints"))
