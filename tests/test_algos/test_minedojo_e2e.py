"""DreamerV3 + MinedojoActor end-to-end on the mocked MineDojo backend:
drives the full pipeline — make_dict_env minedojo dispatch, the wrapper's
3-head MultiDiscrete actions and mask_* obs, the masked actor at play time —
through one real training update (BASELINE config 5's CI analog)."""

import os

import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
import sheeprl_tpu.envs.minedojo as minedojo_mod
from sheeprl_tpu.envs.minedojo_mock import FakeMineDojoBackend
from sheeprl_tpu.utils.registry import tasks


@pytest.mark.timeout(600)
def test_dreamer_v3_minedojo_mocked(tmp_path, monkeypatch):
    monkeypatch.setattr(minedojo_mod, "MineDojoBackend", FakeMineDojoBackend)
    tasks["dreamer_v3"]([
        "--dry_run",
        "--num_devices=1",
        "--env_id=minedojo_harvest_milk",
        "--num_envs=1",
        "--sync_env",
        "--per_rank_batch_size=1",
        "--per_rank_sequence_length=1",
        "--buffer_size=8",
        "--learning_starts=0",
        "--gradient_steps=1",
        "--horizon=4",
        "--dense_units=8",
        "--cnn_channels_multiplier=2",
        "--recurrent_state_size=8",
        "--hidden_size=8",
        "--stochastic_size=4",
        "--discrete_size=4",
        "--mlp_layers=1",
        "--train_every=1",
        "--checkpoint_every=1",
        f"--root_dir={tmp_path}",
        "--run_name=minedojo",
        "--cnn_keys", "rgb",
        "--mlp_keys",
        "inventory", "equipment", "life_stats",
        "mask_action_type", "mask_equip/place", "mask_destroy",
        "mask_craft_smelt",
    ])
    ckpt_dir = tmp_path / "minedojo" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in os.listdir(ckpt_dir))
