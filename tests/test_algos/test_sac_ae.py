"""End-to-end smoke tests for SAC-AE (reference backbone:
/root/reference/tests/test_algos/test_algos.py:125-171)."""

import os

import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.utils.checkpoint import load_checkpoint
from sheeprl_tpu.utils.registry import tasks

CKPT_KEYS = {
    "agent", "decoder", "qf_optimizer", "actor_optimizer", "alpha_optimizer",
    "encoder_optimizer", "decoder_optimizer", "global_step",
}


def tiny_argv(tmp_path, run_name, extra=()):
    return [
        "--env_id", "continuous_dummy",
        "--dry_run",
        "--num_envs", "1",
        "--per_rank_batch_size", "2",
        "--buffer_size", "4",
        "--learning_starts", "0",
        "--gradient_steps", "1",
        "--actor_hidden_size", "16",
        "--critic_hidden_size", "16",
        "--features_dim", "16",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--cnn_channels_multiplier", "1",
        "--root_dir", str(tmp_path),
        "--run_name", run_name,
        *extra,
    ]


@pytest.mark.timeout(300)
def test_sac_ae_dry_run_pixels(tmp_path):
    tasks["sac_ae"](tiny_argv(tmp_path, "dry"))
    ckpt = str(tmp_path / "dry" / "checkpoints" / "ckpt_1")
    assert os.path.exists(ckpt)
    assert set(load_checkpoint(ckpt).keys()) == CKPT_KEYS


@pytest.mark.timeout(300)
def test_sac_ae_resume(tmp_path):
    tasks["sac_ae"](tiny_argv(tmp_path, "first"))
    ckpt = str(tmp_path / "first" / "checkpoints" / "ckpt_1")
    tasks["sac_ae"](["--checkpoint_path", ckpt])
    assert (tmp_path / "first" / "checkpoints" / "ckpt_2").exists()


@pytest.mark.timeout(300)
def test_sac_ae_rejects_minedojo():
    with pytest.raises(ValueError, match="MineDojo"):
        tasks["sac_ae"](["--env_id", "minedojo_open-ended", "--dry_run"])
