"""End-to-end smoke tests for SAC-AE (reference backbone:
/root/reference/tests/test_algos/test_algos.py:125-171)."""

import os

import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.utils.checkpoint import load_checkpoint
from sheeprl_tpu.utils.registry import tasks

CKPT_KEYS = {
    "agent", "decoder", "qf_optimizer", "actor_optimizer", "alpha_optimizer",
    "encoder_optimizer", "decoder_optimizer", "global_step",
}


def tiny_argv(tmp_path, run_name, extra=()):
    return [
        "--env_id", "continuous_dummy",
        "--dry_run",
        "--num_envs", "1",
        "--per_rank_batch_size", "2",
        "--buffer_size", "4",
        "--learning_starts", "0",
        "--gradient_steps", "1",
        "--actor_hidden_size", "16",
        "--critic_hidden_size", "16",
        "--features_dim", "16",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--cnn_channels_multiplier", "1",
        "--root_dir", str(tmp_path),
        "--run_name", run_name,
        *extra,
    ]


@pytest.mark.timeout(300)
def test_sac_ae_dry_run_pixels(tmp_path):
    tasks["sac_ae"](tiny_argv(tmp_path, "dry"))
    ckpt = str(tmp_path / "dry" / "checkpoints" / "ckpt_1")
    assert os.path.exists(ckpt)
    assert set(load_checkpoint(ckpt).keys()) == CKPT_KEYS


@pytest.mark.timeout(300)
def test_sac_ae_resume(tmp_path):
    tasks["sac_ae"](tiny_argv(tmp_path, "first"))
    ckpt = str(tmp_path / "first" / "checkpoints" / "ckpt_1")
    tasks["sac_ae"](["--checkpoint_path", ckpt])
    assert (tmp_path / "first" / "checkpoints" / "ckpt_2").exists()


@pytest.mark.timeout(300)
def test_sac_ae_rejects_minedojo():
    with pytest.raises(ValueError, match="MineDojo"):
        tasks["sac_ae"](["--env_id", "minedojo_open-ended", "--dry_run"])


@pytest.mark.timeout(300)
def test_sac_ae_split_update_dry_run(tmp_path):
    tasks["sac_ae"](tiny_argv(tmp_path, "split", extra=("--split_update", "on")))
    ckpt = str(tmp_path / "split" / "checkpoints" / "ckpt_1")
    assert set(load_checkpoint(ckpt).keys()) == CKPT_KEYS


@pytest.mark.timeout(300)
def test_sac_ae_chunked_recon_dry_run(tmp_path):
    """The compile-pathology partition end-to-end: split update with the
    reconstruction batch chunked (explicit --recon_chunk 1)."""
    tasks["sac_ae"](
        tiny_argv(
            tmp_path, "chunked",
            extra=("--split_update", "on", "--recon_chunk", "1"),
        )
    )
    ckpt = str(tmp_path / "chunked" / "checkpoints" / "ckpt_1")
    assert set(load_checkpoint(ckpt).keys()) == CKPT_KEYS


@pytest.mark.timeout(600)
def test_split_update_matches_fused():
    """--split_update must be a pure compilation-strategy change: with every
    phase enabled, one split train call produces the same state and losses as
    the fused jit (same update order, same per-step key derivation)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.sac_ae.agent import (
        SACAEAgent,
        SACAECNNDecoder,
        SACAECNNEncoder,
        SACAEDecoder,
        SACAEEncoder,
    )
    from sheeprl_tpu.algos.sac_ae.args import SACAEArgs
    from sheeprl_tpu.algos.sac_ae.sac_ae import (
        TrainState,
        make_optimizers,
        make_split_train_step,
        make_train_step,
    )

    args = SACAEArgs(
        features_dim=8, cnn_channels_multiplier=1,
        actor_hidden_size=16, critic_hidden_size=16,
    )
    act_dim = 2
    key = jax.random.PRNGKey(3)
    k_cnn, k_agent, k_dec, k_data, k_train = jax.random.split(key, 5)
    cnn_encoder = SACAECNNEncoder.init(
        k_cnn, 3, args.features_dim, ("rgb",),
        screen_size=64, cnn_channels_multiplier=args.cnn_channels_multiplier,
    )
    encoder = SACAEEncoder(cnn_encoder=cnn_encoder, mlp_encoder=None)
    cnn_decoder = SACAECNNDecoder.init(
        k_dec, cnn_encoder.conv_output_shape, encoder.output_dim, ("rgb",), [3],
        cnn_channels_multiplier=args.cnn_channels_multiplier,
    )
    decoder = SACAEDecoder(cnn_decoder=cnn_decoder, mlp_decoder=None)
    agent = SACAEAgent.init(
        k_agent, encoder, act_dim,
        num_critics=args.num_critics,
        actor_hidden_size=args.actor_hidden_size,
        critic_hidden_size=args.critic_hidden_size,
        action_low=np.full(act_dim, -1.0), action_high=np.full(act_dim, 1.0),
        alpha=args.alpha, tau=args.tau, encoder_tau=args.encoder_tau,
    )
    optimizers = make_optimizers(args)
    qf_optim, actor_optim, alpha_optim, encoder_optim, decoder_optim = optimizers

    def fresh_state():
        return jax.tree_util.tree_map(
            jnp.array,
            TrainState(
                agent=agent, decoder=decoder,
                qf_opt=qf_optim.init(agent.critic),
                actor_opt=actor_optim.init(agent.actor),
                alpha_opt=alpha_optim.init(agent.log_alpha),
                encoder_opt=encoder_optim.init(agent.critic.encoder),
                decoder_opt=decoder_optim.init(decoder),
            ),
        )

    g, b = 2, 3
    ks = jax.random.split(k_data, 5)
    data = {
        "rgb": jax.random.randint(ks[0], (g, b, 64, 64, 3), 0, 256, jnp.uint8),
        "next_rgb": jax.random.randint(ks[1], (g, b, 64, 64, 3), 0, 256, jnp.uint8),
        "actions": jax.random.uniform(ks[2], (g, b, act_dim), jnp.float32, -1, 1),
        "rewards": jax.random.normal(ks[3], (g, b, 1), jnp.float32),
        "dones": (jax.random.uniform(ks[4], (g, b, 1)) < 0.2).astype(jnp.float32),
    }
    fused = make_train_step(args, optimizers, ("rgb",), ())
    split = make_split_train_step(args, optimizers, ("rgb",), ())
    # the compile-pathology partition: recon batch chunked to 1 — dither
    # noise is drawn at full batch and sliced, so targets are bit-identical
    # and only the chunk-mean reassociation differs
    chunked = make_split_train_step(args, optimizers, ("rgb",), (), recon_chunk=1)
    t = jnp.asarray(True)
    s_fused, m_fused = fused(fresh_state(), data, k_train, t, t, t)

    for variant in (split, chunked):
        s_v, m_v = variant(fresh_state(), data, k_train, t, t, t)
        flat_f, _ = jax.tree_util.tree_flatten(s_fused)
        flat_s, _ = jax.tree_util.tree_flatten(s_v)
        assert len(flat_f) == len(flat_s)
        for a, c in zip(flat_f, flat_s):
            # atol covers reassociation-only drift in near-zero conv-grad
            # elements: split/fused schedule reductions differently, and
            # the worst-case element depends on the drawn data (the
            # partitionable-threefry stream, PR 7, moved a handful of
            # elements past the old 2e-5)
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(c, np.float32),
                rtol=2e-4, atol=5e-4,
            )
        assert set(m_fused) == set(m_v)
        for name in m_fused:
            np.testing.assert_allclose(
                float(m_fused[name]), float(m_v[name]), rtol=2e-4, atol=2e-5
            )
