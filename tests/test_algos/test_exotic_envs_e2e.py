"""End-to-end runs on the mocked MineRL and DIAMBRA backends: drive the full
pipeline — make_dict_env prefix dispatch, wrapper action/obs mapping, the
framework image transform — through one real training update (the CI analogs
of the reference's MineRL/DIAMBRA configurations)."""

import os

import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
import sheeprl_tpu.envs.diambra_wrapper as diambra_mod
import sheeprl_tpu.envs.minerl as minerl_mod
from sheeprl_tpu.envs.diambra_mock import FakeDiambraBackend
from sheeprl_tpu.envs.minerl_mock import FakeMineRLBackend
from sheeprl_tpu.utils.registry import tasks


@pytest.mark.timeout(600)
def test_dreamer_v3_minerl_mocked(tmp_path, monkeypatch):
    monkeypatch.setattr(minerl_mod, "MineRLBackend", FakeMineRLBackend)
    tasks["dreamer_v3"]([
        "--dry_run",
        "--num_devices=1",
        "--env_id=minerl_custom_navigate",
        "--num_envs=1",
        "--sync_env",
        "--per_rank_batch_size=1",
        "--per_rank_sequence_length=1",
        "--buffer_size=8",
        "--learning_starts=0",
        "--gradient_steps=1",
        "--horizon=4",
        "--dense_units=8",
        "--cnn_channels_multiplier=2",
        "--recurrent_state_size=8",
        "--hidden_size=8",
        "--stochastic_size=4",
        "--discrete_size=4",
        "--mlp_layers=1",
        "--train_every=1",
        "--checkpoint_every=1",
        f"--root_dir={tmp_path}",
        "--run_name=minerl",
        "--cnn_keys", "rgb",
        "--mlp_keys", "inventory", "max_inventory", "life_stats", "compass",
    ])
    ckpt_dir = tmp_path / "minerl" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in os.listdir(ckpt_dir))


@pytest.mark.timeout(600)
def test_ppo_diambra_mocked(tmp_path, monkeypatch):
    monkeypatch.setattr(diambra_mod, "DiambraBackend", FakeDiambraBackend)
    tasks["ppo"]([
        "--dry_run",
        "--num_devices=1",
        "--env_id=diambra_doapp",
        "--num_envs=1",
        "--sync_env",
        "--rollout_steps=8",
        "--per_rank_batch_size=4",
        "--update_epochs=1",
        "--dense_units=8",
        "--mlp_layers=1",
        "--checkpoint_every=1",
        f"--root_dir={tmp_path}",
        "--run_name=diambra",
        "--cnn_keys", "frame",
        "--mlp_keys", "ownHealth", "oppHealth", "stage", "ownSide",
    ])
    ckpt_dir = tmp_path / "diambra" / "checkpoints"
    assert any(e.startswith("ckpt_") for e in os.listdir(ckpt_dir))
