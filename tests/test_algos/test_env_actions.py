"""The on-device env-action index path: `env_action_indices` (jit-side
argmax, the tiny per-step d2h payload) must agree with the host-side
`one_hot_to_env_actions` it replaces, and `indices_to_one_hot` must invert
it exactly (host/memmap buffer rows are rebuilt from the index pull)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.algos.ppo.agent import (
    env_action_indices,
    indices_to_env_actions,
    indices_to_one_hot,
    one_hot_to_env_actions,
)


def _random_one_hot(rng, n, actions_dim):
    parts = []
    for d in actions_dim:
        idx = rng.integers(0, d, n)
        parts.append(np.eye(d, dtype=np.float32)[idx])
    return np.concatenate(parts, axis=-1)


@pytest.mark.parametrize("actions_dim", [(4,), (6,), (3, 5, 2)])
def test_indices_match_host_argmax(actions_dim):
    rng = np.random.default_rng(0)
    one_hot = _random_one_hot(rng, 8, actions_dim)
    idx = jax.jit(
        lambda a: env_action_indices(a, actions_dim, False)
    )(jnp.asarray(one_hot))
    env_from_idx = indices_to_env_actions(np.asarray(idx), actions_dim, False)
    env_from_onehot = one_hot_to_env_actions(one_hot, actions_dim, False)
    np.testing.assert_array_equal(env_from_idx, env_from_onehot)
    # single Discrete head: env.step wants a scalar per env
    assert env_from_idx.shape == ((8,) if len(actions_dim) == 1 else (8, len(actions_dim)))


@pytest.mark.parametrize("actions_dim", [(4,), (3, 5, 2)])
def test_one_hot_roundtrip(actions_dim):
    rng = np.random.default_rng(1)
    one_hot = _random_one_hot(rng, 5, actions_dim)
    idx = np.asarray(env_action_indices(jnp.asarray(one_hot), actions_dim, False))
    np.testing.assert_array_equal(indices_to_one_hot(idx, actions_dim), one_hot)


def test_continuous_passthrough():
    acts = np.random.default_rng(2).normal(size=(4, 3)).astype(np.float32)
    out = env_action_indices(jnp.asarray(acts), (3,), True)
    np.testing.assert_allclose(np.asarray(out), acts)
    np.testing.assert_allclose(
        indices_to_env_actions(np.asarray(out), (3,), True), acts
    )
