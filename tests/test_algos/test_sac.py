"""End-to-end smoke tests for SAC (reference backbone:
/root/reference/tests/test_algos/test_algos.py:93-123): run main() in-process
on a tiny config, assert the checkpoint contract."""

import os

import numpy as np
import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.utils.checkpoint import load_checkpoint, load_checkpoint_args
from sheeprl_tpu.utils.registry import tasks

CKPT_KEYS = {
    "agent", "qf_optimizer", "actor_optimizer", "alpha_optimizer", "global_step"
}


def tiny_argv(tmp_path, run_name, extra=()):
    return [
        "--env_id", "Pendulum-v1",
        "--dry_run",
        "--num_envs", "1",
        "--per_rank_batch_size", "2",
        "--buffer_size", "4",
        "--learning_starts", "0",
        "--gradient_steps", "1",
        "--actor_hidden_size", "8",
        "--critic_hidden_size", "8",
        "--root_dir", str(tmp_path),
        "--run_name", run_name,
        *extra,
    ]


@pytest.mark.timeout(300)
@pytest.mark.parametrize("checkpoint_buffer", [True, False])
def test_sac_dry_run(tmp_path, checkpoint_buffer):
    run = f"buf_{checkpoint_buffer}"
    extra = ["--checkpoint_buffer"] if checkpoint_buffer else []
    tasks["sac"](tiny_argv(tmp_path, run, extra))
    ckpt_dir = tmp_path / run / "checkpoints"
    ckpt = str(ckpt_dir / "ckpt_1")
    assert os.path.exists(ckpt)
    state = load_checkpoint(ckpt)
    assert set(state.keys()) == CKPT_KEYS
    assert load_checkpoint_args(ckpt)["env_id"] == "Pendulum-v1"
    assert os.path.exists(ckpt + ".buffer.npz") == checkpoint_buffer


@pytest.mark.timeout(300)
def test_sac_resume(tmp_path):
    tasks["sac"](tiny_argv(tmp_path, "first", ["--checkpoint_buffer"]))
    ckpt = str(tmp_path / "first" / "checkpoints" / "ckpt_1")
    tasks["sac"](["--checkpoint_path", ckpt])
    assert (tmp_path / "first" / "checkpoints" / "ckpt_2").exists()


@pytest.mark.timeout(300)
def test_sac_resume_extends_budget(tmp_path):
    """Training resume honors explicitly-provided CLI flags over the sidecar
    (the budget-extension path): resuming a finished 8-step run with
    --total_steps 16 must train to 16, not silently exit at the restored 8.
    Flags NOT provided on the resume command line still come from the
    sidecar (run_name below)."""
    args = [
        "--env_id", "Pendulum-v1",
        "--num_envs", "1",
        "--sync_env",
        "--total_steps", "8",
        "--learning_starts", "2",
        "--per_rank_batch_size", "2",
        "--buffer_size", "16",
        "--checkpoint_every", "4",
        "--checkpoint_buffer",
        "--actor_hidden_size", "8",
        "--critic_hidden_size", "8",
        "--root_dir", str(tmp_path),
        "--run_name", "ext",
    ]
    tasks["sac"](args)
    ckpt_dir = tmp_path / "ext" / "checkpoints"
    assert (ckpt_dir / "ckpt_8").exists()
    # the resume runs in a SUBPROCESS: this pytest process carries a heavy
    # native import set (torch + scipy + grpc + tensorstore + jaxlib) under
    # which executing a persistent-cache-deserialized donating train step on
    # a resumed state intermittently corrupts the glibc heap (segfault that
    # killed the whole suite at this test). The assertion is unchanged; a
    # crash now fails one test instead of the back half of tier-1.
    import subprocess
    import sys

    proc = subprocess.run(
        [
            sys.executable, "-m", "sheeprl_tpu", "sac",
            "--checkpoint_path", str(ckpt_dir / "ckpt_8"),
            "--total_steps", "16",
        ],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert (ckpt_dir / "ckpt_16").exists(), (
        "resume with --total_steps 16 trained no further steps "
        "(sidecar budget silently won)"
    )


@pytest.mark.timeout(300)
def test_sac_bufferless_resume_burst_is_bounded(tmp_path, monkeypatch):
    """Bufferless resume (no --checkpoint_buffer) shifts the learning
    threshold by start_step so the ring re-fills before updates — but the
    catch-up burst at that threshold must stay the CONFIGURED warmup size
    (ADVICE r4 #1): a threshold-sized burst would re-execute ~start_step
    update iterations in one env step against a near-empty buffer, a
    replay-ratio pathology that effectively hangs large resumes."""
    import sheeprl_tpu.algos.sac.sac as sac_mod

    args = [
        "--env_id", "Pendulum-v1",
        "--num_envs", "1",
        "--sync_env",
        "--total_steps", "8",
        "--learning_starts", "2",
        "--per_rank_batch_size", "2",
        "--buffer_size", "16",
        "--checkpoint_every", "4",
        "--actor_hidden_size", "8",
        "--critic_hidden_size", "8",
        "--root_dir", str(tmp_path),
        "--run_name", "burst",
    ]
    tasks["sac"](args)
    ckpt = str(tmp_path / "burst" / "checkpoints" / "ckpt_8")
    assert os.path.exists(ckpt)

    calls = {"n": 0}
    real_factory = sac_mod.make_train_step

    def counting_factory(*a, **kw):
        step = real_factory(*a, **kw)

        def counted(*sa, **skw):
            calls["n"] += 1
            return step(*sa, **skw)

        return counted

    monkeypatch.setattr(sac_mod, "make_train_step", counting_factory)
    tasks["sac"](["--checkpoint_path", ckpt, "--total_steps", "12"])
    assert (tmp_path / "burst" / "checkpoints" / "ckpt_12").exists()
    # resume runs steps 9..12 with threshold 2+9=11: burst of
    # base_learning_starts(=2) at step 10, then 1 each at 11 and 12. The
    # pre-fix pathology would have burst learning_starts(=11) here.
    assert calls["n"] <= 6, (
        f"{calls['n']} update iterations on a 4-step bufferless resume — "
        "the catch-up burst is using the resume-shifted threshold"
    )


@pytest.mark.timeout(300)
def test_sac_rejects_discrete(tmp_path):
    with pytest.raises(ValueError, match="continuous"):
        tasks["sac"](
            ["--env_id", "CartPole-v1", "--dry_run", "--num_envs", "1",
             "--root_dir", str(tmp_path), "--run_name", "bad"]
        )


@pytest.mark.timeout(300)
def test_sac_dry_run_sample_next_obs(tmp_path):
    # one dry-run step can't produce a valid next-obs sample; the update
    # phase must be skipped gracefully, not crash
    tasks["sac"](tiny_argv(tmp_path, "dry_next", ["--sample_next_obs"]))
    assert (tmp_path / "dry_next" / "checkpoints" / "ckpt_1").exists()


@pytest.mark.timeout(300)
def test_sac_sample_next_obs(tmp_path):
    # needs >1 valid entries: skip dry_run's 1-slot buffer by running 2 steps
    tasks["sac"](
        [
            "--env_id", "Pendulum-v1",
            "--num_envs", "1",
            "--total_steps", "8",
            "--per_rank_batch_size", "2",
            "--buffer_size", "16",
            "--learning_starts", "4",
            "--gradient_steps", "1",
            "--actor_hidden_size", "8",
            "--critic_hidden_size", "8",
            "--checkpoint_every", "-1",
            "--sample_next_obs",
            "--root_dir", str(tmp_path),
            "--run_name", "next_obs",
        ]
    )
    assert (tmp_path / "next_obs" / "checkpoints" / "ckpt_8").exists()
