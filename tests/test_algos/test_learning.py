"""Learning-verification test: PPO must actually solve CartPole, not just be
shape-correct (VERDICT r1 #7 — a capability the reference's smoke-only suite
lacks, SURVEY.md §4.7). Trains with a fixed seed and budgeted steps, then
greedily evaluates the checkpointed policy."""

import os

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.algos.ppo.agent import PPOAgent, one_hot_to_env_actions
from sheeprl_tpu.algos.ppo.args import PPOArgs
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.utils.checkpoint import latest_checkpoint, load_checkpoint
from sheeprl_tpu.utils.registry import tasks


@pytest.mark.slow
@pytest.mark.timeout(1800)
def test_ppo_learns_cartpole(tmp_path):
    tasks["ppo"]([
        "--env_id", "CartPole-v1",
        "--seed", "5",
        "--num_devices", "1",
        "--num_envs", "4",
        "--sync_env",
        "--total_steps", "65536",
        "--rollout_steps", "128",
        "--per_rank_batch_size", "128",
        "--update_epochs", "6",
        "--ent_coef", "0.01",
        "--anneal_lr",
        "--normalize_advantages",
        "--max_grad_norm", "0.5",
        "--checkpoint_every", "1000000",  # only the final checkpoint
        "--root_dir", str(tmp_path),
        "--run_name", "learn",
    ])
    ckpt = latest_checkpoint(str(tmp_path / "learn" / "checkpoints"))
    assert ckpt is not None

    env = gym.make("CartPole-v1")
    template_agent = PPOAgent.init(
        jax.random.PRNGKey(0), [2], {"state": env.observation_space},
        [], ["state"], cnn_features_dim=512, mlp_features_dim=64,
        screen_size=64, mlp_layers=2, dense_units=64, dense_act="tanh",
        layer_norm=False, is_continuous=False,
    )
    opt_template = make_optimizer(PPOArgs(max_grad_norm=0.5)).init(template_agent)
    state = load_checkpoint(
        ckpt, {"agent": template_agent, "optimizer": opt_template, "update_step": 0}
    )
    agent = state["agent"]
    greedy = jax.jit(agent.get_greedy_actions)

    returns = []
    for episode in range(10):
        obs, _ = env.reset(seed=1000 + episode)
        done, ep_return = False, 0.0
        while not done:
            actions = greedy({"state": jnp.asarray(obs, jnp.float32)[None]})
            env_action = one_hot_to_env_actions(
                np.asarray(actions[0]), agent.actions_dim, agent.is_continuous
            )
            obs, reward, terminated, truncated, _ = env.step(env_action.item())
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(ep_return)
    env.close()
    mean_return = float(np.mean(returns))
    assert mean_return >= 400.0, f"PPO failed to learn CartPole: {returns}"




def _eval_pendulum_actor(actor, episodes=10):
    """Greedy Pendulum rollout returns for a restored SAC-family actor."""
    env = gym.make("Pendulum-v1")
    greedy = jax.jit(actor.get_greedy_actions)
    returns = []
    for episode in range(episodes):
        obs, _ = env.reset(seed=1000 + episode)
        done, ep_return = False, 0.0
        while not done:
            action = greedy(jnp.asarray(obs, jnp.float32)[None])
            obs, reward, terminated, truncated, _ = env.step(np.asarray(action[0]))
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(ep_return)
    env.close()
    return returns


def _restore_sac_family_actor(ckpt, AgentCls, make_optimizers, args, **agent_kw):
    """Rebuild the checkpoint template for the shared SAC/DroQ key contract
    and return the restored actor."""
    env = gym.make("Pendulum-v1")
    template_agent = AgentCls.init(
        jax.random.PRNGKey(0),
        int(np.prod(env.observation_space.shape)),
        int(np.prod(env.action_space.shape)),
        actor_hidden_size=256,
        critic_hidden_size=256,
        action_low=env.action_space.low,
        action_high=env.action_space.high,
        **agent_kw,
    )
    env.close()
    qf_opt, actor_opt, alpha_opt = make_optimizers(args)
    state = load_checkpoint(
        ckpt,
        {
            "agent": template_agent,
            "qf_optimizer": qf_opt.init(template_agent.critics),
            "actor_optimizer": actor_opt.init(template_agent.actor),
            "alpha_optimizer": alpha_opt.init(template_agent.log_alpha),
            "global_step": 0,
        },
    )
    return state["agent"].actor


@pytest.mark.slow
@pytest.mark.timeout(1800)
def test_sac_learns_pendulum(tmp_path):
    """SAC must actually swing up Pendulum (random policy: ~-1400 return;
    solved: >= -300), same capability check as the PPO test."""
    from sheeprl_tpu.algos.sac.agent import SACAgent
    from sheeprl_tpu.algos.sac.args import SACArgs
    from sheeprl_tpu.algos.sac.sac import make_optimizers

    tasks["sac"]([
        "--env_id", "Pendulum-v1",
        "--seed", "5",
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        "--total_steps", "15000",
        "--learning_starts", "1000",
        "--per_rank_batch_size", "128",
        "--gradient_steps", "1",
        "--actor_hidden_size", "256",
        "--critic_hidden_size", "256",
        "--checkpoint_every", "1000000",  # only the final checkpoint
        "--root_dir", str(tmp_path),
        "--run_name", "learn",
    ])
    ckpt = latest_checkpoint(str(tmp_path / "learn" / "checkpoints"))
    assert ckpt is not None

    actor = _restore_sac_family_actor(
        ckpt, SACAgent, make_optimizers, SACArgs()
    )
    returns = _eval_pendulum_actor(actor)
    mean_return = float(np.mean(returns))
    assert mean_return >= -300.0, f"SAC failed to learn Pendulum: {returns}"


@pytest.mark.slow
@pytest.mark.timeout(1800)
def test_droq_learns_pendulum(tmp_path):
    """DroQ's high-UTD critic loop must also swing up Pendulum — its
    dropout/LayerNorm ensemble and per-round EMA are the pieces the SAC test
    does not cover."""
    from sheeprl_tpu.algos.droq.agent import DROQAgent
    from sheeprl_tpu.algos.droq.args import DROQArgs
    from sheeprl_tpu.algos.sac.sac import make_optimizers

    tasks["droq"]([
        "--env_id", "Pendulum-v1",
        "--seed", "5",
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        "--total_steps", "10000",
        "--learning_starts", "1000",
        "--per_rank_batch_size", "128",
        "--gradient_steps", "2",
        "--actor_hidden_size", "256",
        "--critic_hidden_size", "256",
        "--checkpoint_every", "1000000",
        "--root_dir", str(tmp_path),
        "--run_name", "learn",
    ])
    ckpt = latest_checkpoint(str(tmp_path / "learn" / "checkpoints"))
    assert ckpt is not None

    actor = _restore_sac_family_actor(
        ckpt, DROQAgent, make_optimizers, DROQArgs()
    )
    returns = _eval_pendulum_actor(actor)
    mean_return = float(np.mean(returns))
    assert mean_return >= -300.0, f"DroQ failed to learn Pendulum: {returns}"


@pytest.mark.slow
@pytest.mark.timeout(3600)
def test_dreamer_v3_learns_cartpole(tmp_path):
    """The flagship claim: a world model + imagination-trained actor must
    actually improve a return (VERDICT r2 #3 — the reference's smoke-only
    suite never checks this, SURVEY.md §4.7). DreamerV3 at small scale on
    vector-obs CartPole; the restored greedy player must beat the
    random-policy baseline (~20 return) by a wide margin. A subtly wrong KL
    balance, lambda-return, or straight-through gradient passes every
    shape/equivalence test but fails this one."""
    from sheeprl_tpu import ops
    from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3, build_models
    from sheeprl_tpu.algos.dreamer_v3.args import DreamerV3Args
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_optimizers

    tasks["dreamer_v3"]([
        "--env_id", "CartPole-v1",
        "--seed", "5",
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        # 6144, not more: at this tiny scale the policy peaks around steps
        # 4.5-6.5k (avg return ~260) and can collapse later (round-3 trial:
        # 8192 steps ended at ~55 after peaking at 381) — the regression
        # pins the budget inside the reliably-learned window
        "--total_steps", "6144",
        "--learning_starts", "512",
        "--train_every", "4",
        "--per_rank_batch_size", "16",
        "--per_rank_sequence_length", "32",
        "--buffer_size", "100000",
        "--dense_units", "256",
        "--hidden_size", "256",
        "--recurrent_state_size", "256",
        "--stochastic_size", "16",
        "--discrete_size", "16",
        "--mlp_layers", "2",
        "--horizon", "15",
        "--action_repeat", "1",
        "--checkpoint_every", "1000000",  # only the final checkpoint
        "--root_dir", str(tmp_path),
        "--run_name", "learn",
        "--mlp_keys", "state",
    ])
    ckpt = latest_checkpoint(str(tmp_path / "learn" / "checkpoints"))
    assert ckpt is not None

    env = gym.make("CartPole-v1")
    args = DreamerV3Args(env_id="CartPole-v1", seed=5)
    args.cnn_keys, args.mlp_keys = [], ["state"]
    args.dense_units = args.hidden_size = args.recurrent_state_size = 256
    args.stochastic_size = args.discrete_size = 16
    args.mlp_layers, args.horizon, args.action_repeat = 2, 15, 1
    wm, actor, critic, tcritic = build_models(
        jax.random.PRNGKey(0), [2], False, args,
        {"state": env.observation_space}, [], ["state"],
    )
    wopt, aopt, copt = make_optimizers(args)
    restored = load_checkpoint(ckpt, {
        "world_model": wm, "actor": actor, "critic": critic,
        "target_critic": tcritic,
        "world_optimizer": wopt.init(wm), "actor_optimizer": aopt.init(actor),
        "critic_optimizer": copt.init(critic),
        "moments": ops.Moments.init(args.moments_decay, args.moment_max),
        "expl_decay_steps": 0, "global_step": 0, "batch_size": 0,
    })
    player = PlayerDV3(
        encoder=restored["world_model"].encoder,
        rssm=restored["world_model"].rssm,
        actor=restored["actor"],
        actions_dim=(2,),
        stochastic_size=16, discrete_size=16, recurrent_state_size=256,
        is_continuous=False,
    )
    step = jax.jit(
        lambda p, s, o, k: p.step(s, o, k, jnp.float32(0.0), is_training=False)
    )
    returns = []
    for episode in range(10):
        obs, _ = env.reset(seed=1000 + episode)
        state = player.init_states(1)
        key = jax.random.PRNGKey(episode)
        done, ep_return = False, 0.0
        while not done:
            dobs = {"state": jnp.asarray(obs, jnp.float32)[None]}
            key, sub = jax.random.split(key)
            state, actions = step(player, state, dobs, sub)
            act = one_hot_to_env_actions(np.asarray(actions), (2,), False)[0]
            obs, reward, terminated, truncated, _ = env.step(act.item())
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(ep_return)
    env.close()
    mean_return = float(np.mean(returns))
    # random policy averages ~20 on CartPole; demand a wide margin over it
    assert mean_return >= 120.0, f"DreamerV3 failed to learn CartPole: {returns}"


@pytest.mark.slow
@pytest.mark.timeout(3600)
def test_dreamer_v2_learns_cartpole(tmp_path):
    """Second Dreamer-family learning receipt: DreamerV2's discrete-latent
    world model + KL-balanced ELBO + reinforce/dynamics-mix actor must also
    improve a return — the same tiny-CartPole recipe as the DV3 regression
    (identical sizes/budget), so a V2-specific defect (KL balancing, the
    V2 row layout, target-critic scheduling) cannot hide behind the V3
    test. Validated run: restored greedy mean 274.5 over 10 episodes
    (random ~20; threshold 120), 2026-08-01."""
    from sheeprl_tpu import ops  # noqa: F401 — parity with the DV3 test
    from sheeprl_tpu.algos.dreamer_v2.agent import PlayerDV2, build_models
    from sheeprl_tpu.algos.dreamer_v2.args import DreamerV2Args
    from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import make_optimizers

    tasks["dreamer_v2"]([
        "--env_id", "CartPole-v1",
        "--seed", "5",
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        "--total_steps", "6144",
        "--learning_starts", "512",
        "--train_every", "4",
        "--per_rank_batch_size", "16",
        "--per_rank_sequence_length", "32",
        "--buffer_size", "100000",
        "--dense_units", "256",
        "--hidden_size", "256",
        "--recurrent_state_size", "256",
        "--stochastic_size", "16",
        "--discrete_size", "16",
        "--mlp_layers", "2",
        "--horizon", "15",
        "--action_repeat", "1",
        "--checkpoint_every", "1000000",  # only the final checkpoint
        "--root_dir", str(tmp_path),
        "--run_name", "learn",
        "--mlp_keys", "state",
    ])
    ckpt = latest_checkpoint(str(tmp_path / "learn" / "checkpoints"))
    assert ckpt is not None

    env = gym.make("CartPole-v1")
    args = DreamerV2Args(env_id="CartPole-v1", seed=5)
    args.cnn_keys, args.mlp_keys = [], ["state"]
    args.dense_units = args.hidden_size = args.recurrent_state_size = 256
    args.stochastic_size = args.discrete_size = 16
    args.mlp_layers, args.horizon, args.action_repeat = 2, 15, 1
    wm, actor, critic, tcritic = build_models(
        jax.random.PRNGKey(0), [2], False, args,
        {"state": env.observation_space}, [], ["state"],
    )
    wopt, aopt, copt = make_optimizers(args)
    restored = load_checkpoint(ckpt, {
        "world_model": wm, "actor": actor, "critic": critic,
        "target_critic": tcritic,
        "world_optimizer": wopt.init(wm), "actor_optimizer": aopt.init(actor),
        "critic_optimizer": copt.init(critic),
        "expl_decay_steps": 0, "global_step": 0, "batch_size": 0,
    })
    player = PlayerDV2(
        encoder=restored["world_model"].encoder,
        rssm=restored["world_model"].rssm,
        actor=restored["actor"],
        actions_dim=(2,),
        stochastic_size=16, discrete_size=16, recurrent_state_size=256,
        is_continuous=False,
    )
    step = jax.jit(
        lambda p, s, o, k: p.step(s, o, k, jnp.float32(0.0), is_training=False)
    )
    returns = []
    for episode in range(10):
        obs, _ = env.reset(seed=1000 + episode)
        state = player.init_states(1)
        key = jax.random.PRNGKey(episode)
        done, ep_return = False, 0.0
        while not done:
            dobs = {"state": jnp.asarray(obs, jnp.float32)[None]}
            key, sub = jax.random.split(key)
            state, actions = step(player, state, dobs, sub)
            act = one_hot_to_env_actions(np.asarray(actions), (2,), False)[0]
            obs, reward, terminated, truncated, _ = env.step(act.item())
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(ep_return)
    env.close()
    mean_return = float(np.mean(returns))
    assert mean_return >= 120.0, f"DreamerV2 failed to learn CartPole: {returns}"


@pytest.mark.slow
@pytest.mark.timeout(7200)
def test_dreamer_v3_decoupled_learns_cartpole(tmp_path):
    """The decoupled topology's learning receipt (VERDICT r3 #6): the
    player collects with ONE-UPDATE-STALE weights (trainer sub-mesh update
    overlaps the next rollout, dreamer_v3_decoupled.py), and that staleness
    tolerance must be proven against returns, not just the 0.999x
    structural parity receipt. Identical recipe to the coupled regression
    above so any gap is attributable to the topology. Validated run:
    restored greedy mean 467.6 over 10 episodes (nine perfect 500s;
    coupled twin 408.5; random ~20; threshold 120), 2026-08-02,
    logs/dv3_decoupled_learn_r4.json."""
    from sheeprl_tpu import ops
    from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3, build_models
    from sheeprl_tpu.algos.dreamer_v3.args import DreamerV3Args
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_optimizers

    tasks["dreamer_v3_decoupled"]([
        "--env_id", "CartPole-v1",
        "--seed", "5",
        "--num_devices", "2",  # 1 player + 1 trainer sub-mesh
        "--num_envs", "1",
        "--sync_env",
        "--total_steps", "6144",
        "--learning_starts", "512",
        "--train_every", "4",
        "--per_rank_batch_size", "16",
        "--per_rank_sequence_length", "32",
        "--buffer_size", "100000",
        "--dense_units", "256",
        "--hidden_size", "256",
        "--recurrent_state_size", "256",
        "--stochastic_size", "16",
        "--discrete_size", "16",
        "--mlp_layers", "2",
        "--horizon", "15",
        "--action_repeat", "1",
        "--checkpoint_every", "2048",
        "--root_dir", str(tmp_path),
        "--run_name", "learn",
        "--mlp_keys", "state",
    ])
    ckpt = latest_checkpoint(str(tmp_path / "learn" / "checkpoints"))
    assert ckpt is not None

    env = gym.make("CartPole-v1")
    args = DreamerV3Args(env_id="CartPole-v1", seed=5)
    args.cnn_keys, args.mlp_keys = [], ["state"]
    args.dense_units = args.hidden_size = args.recurrent_state_size = 256
    args.stochastic_size = args.discrete_size = 16
    args.mlp_layers, args.horizon, args.action_repeat = 2, 15, 1
    wm, actor, critic, tcritic = build_models(
        jax.random.PRNGKey(0), [2], False, args,
        {"state": env.observation_space}, [], ["state"],
    )
    wopt, aopt, copt = make_optimizers(args)
    restored = load_checkpoint(ckpt, {
        "world_model": wm, "actor": actor, "critic": critic,
        "target_critic": tcritic,
        "world_optimizer": wopt.init(wm), "actor_optimizer": aopt.init(actor),
        "critic_optimizer": copt.init(critic),
        "moments": ops.Moments.init(args.moments_decay, args.moment_max),
        "expl_decay_steps": 0, "global_step": 0, "batch_size": 0,
    })
    player = PlayerDV3(
        encoder=restored["world_model"].encoder,
        rssm=restored["world_model"].rssm,
        actor=restored["actor"],
        actions_dim=(2,),
        stochastic_size=16, discrete_size=16, recurrent_state_size=256,
        is_continuous=False,
    )
    step = jax.jit(
        lambda p, s, o, k: p.step(s, o, k, jnp.float32(0.0), is_training=False)
    )
    returns = []
    for episode in range(10):
        obs, _ = env.reset(seed=1000 + episode)
        state = player.init_states(1)
        key = jax.random.PRNGKey(episode)
        done, ep_return = False, 0.0
        while not done:
            dobs = {"state": jnp.asarray(obs, jnp.float32)[None]}
            key, sub = jax.random.split(key)
            state, actions = step(player, state, dobs, sub)
            act = one_hot_to_env_actions(np.asarray(actions), (2,), False)[0]
            obs, reward, terminated, truncated, _ = env.step(act.item())
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(ep_return)
    env.close()
    mean_return = float(np.mean(returns))
    assert mean_return >= 120.0, f"decoupled DV3 failed to learn: {returns}"


@pytest.mark.slow
@pytest.mark.timeout(7200)
def test_dreamer_v1_improves_pendulum(tmp_path):
    """DreamerV1 learning receipt (VERDICT r3 #3), in DV1's native regime:
    continuous control with dense rewards (its tanh_normal actor trains by
    pure dynamics backprop — no reinforce term, no entropy bonus — which
    collapses on discrete tiny-CartPole; see BENCHES.md round-4 DV1
    investigation). At receipt scale the policy plateaus around -950: a
    clear, reproducible improvement over the measured same-protocol random
    baseline (-1287 mean, episodes -865..-1713) without reaching the
    SAC/DroQ receipts' -300 (the reference's own DV1 regime is 5M steps /
    ~500k updates; this budget delivers ~2.8k). Validated runs: greedy
    mean -934.5 at 12288 steps, -982.4 at 24576 (logs/dv1_learn_r4d.json).
    Threshold -1100: both validated runs clear it by >100, a random-policy
    10-episode mean needs a >2-sigma fluke to reach it."""
    from sheeprl_tpu.algos.dreamer_v1.agent import PlayerDV1, build_models
    from sheeprl_tpu.algos.dreamer_v1.args import DreamerV1Args
    from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_optimizers

    tasks["dreamer_v1"]([
        "--env_id", "Pendulum-v1",
        "--seed", "5",
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        "--total_steps", "12288",
        "--learning_starts", "1024",
        "--train_every", "4",
        "--gradient_steps", "1",
        "--per_rank_batch_size", "16",
        "--per_rank_sequence_length", "32",
        "--buffer_size", "100000",
        "--dense_units", "200",
        "--hidden_size", "200",
        "--recurrent_state_size", "200",
        "--stochastic_size", "30",
        "--mlp_layers", "2",
        "--horizon", "15",
        "--action_repeat", "1",
        "--checkpoint_every", "4096",
        "--no_use_continues",
        "--expl_amount", "0.3",
        "--expl_decay",
        "--expl_min", "0.05",
        "--max_step_expl_decay", "2000",
        "--actor_lr", "3e-4",
        "--critic_lr", "3e-4",
        "--root_dir", str(tmp_path),
        "--run_name", "learn",
        "--mlp_keys", "state",
    ])
    ckpt = latest_checkpoint(str(tmp_path / "learn" / "checkpoints"))
    assert ckpt is not None

    env = gym.make("Pendulum-v1")
    args = DreamerV1Args(env_id="Pendulum-v1", seed=5)
    args.cnn_keys, args.mlp_keys = [], ["state"]
    args.dense_units = args.hidden_size = args.recurrent_state_size = 200
    args.stochastic_size = 30
    args.mlp_layers, args.horizon, args.action_repeat = 2, 15, 1
    args.use_continues = False
    wm, actor, critic = build_models(
        jax.random.PRNGKey(0), [1], True, args,
        {"state": env.observation_space}, [], ["state"],
    )
    wopt, aopt, copt = make_optimizers(args)
    restored = load_checkpoint(ckpt, {
        "world_model": wm, "actor": actor, "critic": critic,
        "world_optimizer": wopt.init(wm), "actor_optimizer": aopt.init(actor),
        "critic_optimizer": copt.init(critic),
        "expl_decay_steps": 0, "global_step": 0, "batch_size": 0,
    })
    player = PlayerDV1(
        encoder=restored["world_model"].encoder,
        rssm=restored["world_model"].rssm,
        actor=restored["actor"],
        actions_dim=(1,),
        stochastic_size=30, recurrent_state_size=200,
        is_continuous=True,
    )
    step = jax.jit(
        lambda p, s, o, k: p.step(s, o, k, jnp.float32(0.0), is_training=False)
    )
    returns = []
    for episode in range(10):
        obs, _ = env.reset(seed=1000 + episode)
        state = player.init_states(1)
        key = jax.random.PRNGKey(episode)
        done, ep_return = False, 0.0
        while not done:
            dobs = {"state": jnp.asarray(obs, jnp.float32)[None]}
            key, sub = jax.random.split(key)
            state, actions = step(player, state, dobs, sub)
            obs, reward, terminated, truncated, _ = env.step(np.asarray(actions)[0])
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(ep_return)
    env.close()
    mean_return = float(np.mean(returns))
    assert mean_return >= -1100.0, f"DV1 failed to improve on Pendulum: {returns}"
