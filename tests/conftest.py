"""Test harness: run everything on the CPU backend with 8 virtual devices so
multi-device mesh semantics are exercised without TPU hardware — the JAX
equivalent of the reference's Gloo-on-CPU distributed tests
(/root/reference/tests/test_algos/test_algos.py:16-38).

NOTE on the axon TPU tunnel: this image's sitecustomize registers an `axon`
PJRT plugin and force-sets `jax_platforms="axon,cpu"` at interpreter start,
overriding the JAX_PLATFORMS env var. Tests must run on local CPU (fast,
deterministic, and immune to tunnel flakiness), so we update the jax config
directly — config updates win over the sitecustomize write — and blank the
pool-IPs var so subprocesses spawned by tests skip axon registration.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # children: skip axon registration
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("SHEEPRL_TPU_TEST", "1")

import jax

jax.config.update("jax_platforms", "cpu")

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _assert_cpu_backend() -> None:
    devices = jax.devices()
    assert devices[0].platform == "cpu", devices
    assert len(devices) == 8, devices


_assert_cpu_backend()
