"""Test harness: run everything on the CPU backend with 8 virtual devices so
multi-device mesh semantics are exercised without TPU hardware — the JAX
equivalent of the reference's Gloo-on-CPU distributed tests
(/root/reference/tests/test_algos/test_algos.py:16-38).

NOTE on the axon TPU tunnel: this image's sitecustomize registers an `axon`
PJRT plugin and force-sets `jax_platforms="axon,cpu"` at interpreter start,
overriding the JAX_PLATFORMS env var. Tests must run on local CPU (fast,
deterministic, and immune to tunnel flakiness), so we update the jax config
directly — config updates win over the sitecustomize write — and blank the
pool-IPs var so subprocesses spawned by tests skip axon registration.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # children: skip axon registration
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("SHEEPRL_TPU_TEST", "1")

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# importing the package wires the persistent XLA compilation cache (honoring
# SHEEPRL_TPU_XLA_CACHE=0) and exports JAX_COMPILATION_CACHE_DIR so test
# SUBPROCESSES — bench smoke, CLI dry runs — share one cache with the pytest
# process; identical-HLO graphs compile once per box, not once per process
import sheeprl_tpu  # noqa: F401

import jax

jax.config.update("jax_platforms", "cpu")


def _assert_cpu_backend() -> None:
    devices = jax.devices()
    assert devices[0].platform == "cpu", devices
    assert len(devices) == 8, devices


_assert_cpu_backend()


# ---------------------------------------------------------------------------
# Budget enforcement for the `timeout` marker. pytest-timeout is not in this
# image, so budgets are enforced with SIGALRM: the handler fires between
# Python bytecodes, which catches runaway Python loops, hung subprocess
# waits (EINTR) and stuck env workers. A single long-running C call (one XLA
# compile) defers the alarm until it returns — an accepted limitation, noted
# here so nobody mistakes this for a hard kill.
# ---------------------------------------------------------------------------
import signal

import pytest


class TestBudgetExceeded(BaseException):
    """BaseException so a library's broad `except Exception` cannot swallow
    the budget signal."""


@pytest.fixture(autouse=True)
def _enforce_timeout_marker(request):
    marker = request.node.get_closest_marker("timeout")
    if marker is None or not marker.args or not hasattr(signal, "SIGALRM"):
        yield
        return
    seconds = int(marker.args[0])

    def _expired(signum, frame):
        # re-arm before raising: if anything on the stack still manages to
        # absorb a BaseException, the budget keeps firing
        signal.alarm(30)
        raise TestBudgetExceeded(
            f"test exceeded its {seconds}s timeout budget"
        )

    old = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(seconds)
    try:
        yield
    except TestBudgetExceeded:
        pytest.fail(f"test exceeded its {seconds}s timeout budget", pytrace=False)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
