"""Config parser and registry contracts
(mirrors the reference parser behaviors, utils/parser.py:69-431)."""

import dataclasses
from typing import List, Literal, Optional

import pytest

from sheeprl_tpu.algos.args import StandardArgs
from sheeprl_tpu.utils.parser import Arg, DataclassArgumentParser
from sheeprl_tpu.utils.registry import register_algorithm, tasks


@dataclasses.dataclass
class DemoArgs(StandardArgs):
    lr: float = Arg(default=1e-3, help="learning rate")
    flag: bool = Arg(default=True)
    mode: Literal["a", "b"] = Arg(default="a")
    sizes: List[int] = Arg(default=[1, 2])
    note: Optional[str] = Arg(default=None)


def parse(argv):
    return DataclassArgumentParser(DemoArgs).parse_args_into_dataclasses(argv)[0]


def test_defaults():
    args = parse([])
    assert args.lr == 1e-3 and args.flag is True and args.sizes == [1, 2]
    assert args.env_id == "CartPole-v1"  # inherited


def test_bool_pair():
    assert parse(["--no_flag"]).flag is False
    assert parse(["--flag"]).flag is True


def test_literal_choices():
    assert parse(["--mode", "b"]).mode == "b"
    with pytest.raises(SystemExit):
        parse(["--mode", "c"])


def test_list_nargs():
    assert parse(["--sizes", "3", "4", "5"]).sizes == [3, 4, 5]


def test_unknown_arg_raises():
    with pytest.raises(ValueError):
        parse(["--nope", "1"])


def test_inheritance_overrides():
    args = parse(["--env_id", "dmc_walker_walk", "--lr", "0.01"])
    assert args.env_id == "dmc_walker_walk" and args.lr == 0.01


def test_parse_dict_roundtrip():
    args = parse(["--seed", "7"])
    parser = DataclassArgumentParser(DemoArgs)
    (restored,) = parser.parse_dict(args.as_dict())
    assert restored.seed == 7
    # extra keys tolerated by default (checkpoint resume path)
    (restored2,) = parser.parse_dict({**args.as_dict(), "bogus": 1})
    assert restored2.seed == 7
    with pytest.raises(ValueError):
        parser.parse_dict({"bogus": 1}, allow_extra_keys=False)


def test_log_dir_side_effect(tmp_path):
    args = parse([])
    args.log_dir = str(tmp_path / "run")
    assert (tmp_path / "run" / "args.json").exists()


def test_default_list_not_shared():
    a, b = parse([]), parse([])
    a.sizes.append(99)
    assert b.sizes == [1, 2]


def test_registry_decorator():
    @register_algorithm(name="_test_algo")
    def main(argv):
        return "ran"

    assert "_test_algo" in tasks
    assert tasks["_test_algo"]([]) == "ran"
    with pytest.raises(ValueError):
        register_algorithm(name="_test_algo")(lambda argv: None)
    del tasks["_test_algo"]


def test_cli_provided_tracking():
    """parse_args_into_dataclasses records which fields the user explicitly
    set (vs dataclass defaults) — the eval-time config merge overrides only
    those (utils/evaluation.py, ADVICE r3)."""
    args = parse(["--lr", "0.5", "--no_flag", "--sizes=3"])
    assert {"lr", "flag", "sizes"} <= args._cli_provided
    assert "mode" not in args._cli_provided
    assert "seed" not in args._cli_provided

    # a second parse on the same parser instance must not leak state and
    # defaults must survive the suppressed re-parse
    p = DataclassArgumentParser(DemoArgs)
    a1 = p.parse_args_into_dataclasses(["--seed", "7"])[0]
    a2 = p.parse_args_into_dataclasses([])[0]
    assert "seed" in a1._cli_provided and a1.seed == 7
    assert a2._cli_provided == set() and a2.seed == 42 and a2.flag is True


def test_cli_flag_parity_with_reference():
    """The per-algo dataclass-field set must be a superset of the
    reference's (VERDICT r3 #8) — every flag a reference user passes must
    parse here too. torch_deterministic is documented N/A (no cudnn knob in
    JAX; seeding is explicit PRNG-key threading). Skipped when the
    reference checkout is not present (the suite is standalone)."""
    import ast
    import glob as _glob
    import importlib
    import os as _os

    ref_root = "/root/reference/sheeprl/algos"
    if not _os.path.isdir(ref_root):
        pytest.skip("reference checkout not available")

    ref_classes = {}
    for path in _glob.glob(f"{ref_root}/**/args.py", recursive=True):
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                fields = [
                    s.target.id for s in node.body if isinstance(s, ast.AnnAssign)
                ]
                bases = [
                    b.id if isinstance(b, ast.Name) else getattr(b, "attr", "?")
                    for b in node.bases
                ]
                ref_classes[node.name] = (bases, fields)

    def ref_fields(cls):
        if cls not in ref_classes:
            return set()
        bases, fields = ref_classes[cls]
        out = set(fields)
        for b in bases:
            out |= ref_fields(b)
        return out

    pairs = [
        ("ppo", "PPOArgs"), ("ppo_recurrent", "RecurrentPPOArgs"),
        ("sac", "SACArgs"), ("sac_ae", "SACAEArgs"), ("droq", "DROQArgs"),
        ("dreamer_v1", "DreamerV1Args"), ("dreamer_v2", "DreamerV2Args"),
        ("dreamer_v3", "DreamerV3Args"), ("p2e_dv1", "P2EDV1Args"),
        ("p2e_dv2", "P2EDV2Args"),
    ]
    not_applicable = {"torch_deterministic"}
    missing = {}
    for mod, cls in pairs:
        ours = getattr(importlib.import_module(f"sheeprl_tpu.algos.{mod}.args"), cls)
        of = {f.name for f in dataclasses.fields(ours)}
        m = ref_fields(cls) - of - not_applicable
        if m:
            missing[mod] = sorted(m)
    assert not missing, f"CLI flags present in reference but absent here: {missing}"
