"""Config parser and registry contracts
(mirrors the reference parser behaviors, utils/parser.py:69-431)."""

import dataclasses
from typing import List, Literal, Optional

import pytest

from sheeprl_tpu.algos.args import StandardArgs
from sheeprl_tpu.utils.parser import Arg, DataclassArgumentParser
from sheeprl_tpu.utils.registry import register_algorithm, tasks


@dataclasses.dataclass
class DemoArgs(StandardArgs):
    lr: float = Arg(default=1e-3, help="learning rate")
    flag: bool = Arg(default=True)
    mode: Literal["a", "b"] = Arg(default="a")
    sizes: List[int] = Arg(default=[1, 2])
    note: Optional[str] = Arg(default=None)


def parse(argv):
    return DataclassArgumentParser(DemoArgs).parse_args_into_dataclasses(argv)[0]


def test_defaults():
    args = parse([])
    assert args.lr == 1e-3 and args.flag is True and args.sizes == [1, 2]
    assert args.env_id == "CartPole-v1"  # inherited


def test_bool_pair():
    assert parse(["--no_flag"]).flag is False
    assert parse(["--flag"]).flag is True


def test_literal_choices():
    assert parse(["--mode", "b"]).mode == "b"
    with pytest.raises(SystemExit):
        parse(["--mode", "c"])


def test_list_nargs():
    assert parse(["--sizes", "3", "4", "5"]).sizes == [3, 4, 5]


def test_unknown_arg_raises():
    with pytest.raises(ValueError):
        parse(["--nope", "1"])


def test_inheritance_overrides():
    args = parse(["--env_id", "dmc_walker_walk", "--lr", "0.01"])
    assert args.env_id == "dmc_walker_walk" and args.lr == 0.01


def test_parse_dict_roundtrip():
    args = parse(["--seed", "7"])
    parser = DataclassArgumentParser(DemoArgs)
    (restored,) = parser.parse_dict(args.as_dict())
    assert restored.seed == 7
    # extra keys tolerated by default (checkpoint resume path)
    (restored2,) = parser.parse_dict({**args.as_dict(), "bogus": 1})
    assert restored2.seed == 7
    with pytest.raises(ValueError):
        parser.parse_dict({"bogus": 1}, allow_extra_keys=False)


def test_log_dir_side_effect(tmp_path):
    args = parse([])
    args.log_dir = str(tmp_path / "run")
    assert (tmp_path / "run" / "args.json").exists()


def test_default_list_not_shared():
    a, b = parse([]), parse([])
    a.sizes.append(99)
    assert b.sizes == [1, 2]


def test_registry_decorator():
    @register_algorithm(name="_test_algo")
    def main(argv):
        return "ran"

    assert "_test_algo" in tasks
    assert tasks["_test_algo"]([]) == "ran"
    with pytest.raises(ValueError):
        register_algorithm(name="_test_algo")(lambda argv: None)
    del tasks["_test_algo"]
