"""Meta-test for the SIGALRM budget fixture (tests/conftest.py): the budget
must fire, and a library's broad `except Exception` must not swallow it
(code-review r3 finding: pytest.Failed is an Exception, so a retry loop
could eat the one-shot alarm and run unbounded)."""

import time

import pytest

from tests.conftest import TestBudgetExceeded


@pytest.mark.timeout(2)
def test_budget_fires_through_broad_except():
    with pytest.raises(TestBudgetExceeded):
        try:
            for _ in range(200):
                time.sleep(0.1)
        except Exception:  # the swallow-everything pattern under test
            pytest.fail("budget signal was absorbed by `except Exception`")
