"""The examples/ must stay runnable: architecture_template drives the
player/buffer/trainer sub-mesh topology end-to-end on the virtual CPU mesh."""

import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[2]


@pytest.mark.timeout(300)
def test_architecture_template_runs():
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / "architecture_template.py")],
        capture_output=True,
        text=True,
        cwd=REPO,
        env={
            "PATH": "/usr/bin:/bin",
            "PYTHONPATH": str(REPO),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "PALLAS_AXON_POOL_IPS": "",
            "HOME": "/tmp",
        },
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "template ok" in proc.stdout
    assert "trainers: 7 devices" in proc.stdout
