"""Unit receipts for the ISSUE 3 satellite fixes in tools/ and bench.py:
process matching in the session-end sweep, the bounded --eval-only path,
and the ledger's code fingerprint + fresh-vs-re-emitted partial fields."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "tools"))


# ---------------------------------------------------------------------------
# sweep_runners: only real python processes running runner scripts
# ---------------------------------------------------------------------------


def test_sweep_matches_only_python_runner_processes():
    from sweep_runners import _is_runner_cmd

    # real runners, in the shapes the autobench loop spawns them
    assert _is_runner_cmd("python tools/dv1_learning_run.py --root logs/x")
    assert _is_runner_cmd("python3 -u /root/repo/tools/pixel_chip_run.py")
    assert _is_runner_cmd("/usr/bin/python3.10 tools/sac_ae_pixel_learning_run.py")

    # ADVICE r5: these used to be SIGKILLed by the substring match
    assert not _is_runner_cmd("tail -f logs/dv1_learning_run.py.out")
    assert not _is_runner_cmd("vim tools/dv1_learning_run.py")
    assert not _is_runner_cmd("grep -r pixel_chip_run.py tools/")
    assert not _is_runner_cmd("less pixel_chip_run.py")
    # the sweep itself, and unrelated python work
    assert not _is_runner_cmd("python tools/sweep_runners.py --dry-run")
    assert not _is_runner_cmd("python bench.py --tiny")
    assert not _is_runner_cmd("python -m pytest tests/")
    assert not _is_runner_cmd("")


# ---------------------------------------------------------------------------
# runner_common: --eval-only rides the same bounds as run_bounded
# ---------------------------------------------------------------------------


def test_run_eval_bounded_receipt(tmp_path):
    from runner_common import run_eval_bounded

    out = str(tmp_path / "receipt.json")
    result = run_eval_bounded(
        lambda: {"mean_return": 12.5, "returns": [12.5]},
        out, {"recipe": {"algo": "x"}}, eval_budget_s=60.0,
    )
    assert result["status"] == "eval_receipt"
    assert result["mean_return"] == 12.5
    with open(out) as fh:
        on_disk = json.load(fh)
    assert on_disk["recipe"] == {"algo": "x"}
    assert on_disk["eval_budget_s"] == 60.0
    assert "train_plus_eval_seconds" in on_disk  # legacy consumer key


def test_run_eval_bounded_soft_timeout(tmp_path):
    from runner_common import run_eval_bounded

    out = str(tmp_path / "receipt.json")

    def slow_eval():
        import time

        time.sleep(30)
        return {"mean_return": 0.0}

    result = run_eval_bounded(
        slow_eval, out, {}, eval_budget_s=1.0, hard_grace_s=600.0,
    )
    assert result["status"] == "stub_eval_timeout"
    assert os.path.exists(out)


def test_run_eval_bounded_crash_lands_stub(tmp_path):
    from runner_common import run_eval_bounded

    out = str(tmp_path / "receipt.json")
    result = run_eval_bounded(
        lambda: (_ for _ in ()).throw(RuntimeError("no checkpoint")),
        out, {}, eval_budget_s=30.0,
    )
    assert result["status"] == "stub_no_eval"
    assert "no checkpoint" in result["eval_error"]


# ---------------------------------------------------------------------------
# bench ledger: code fingerprint + fresh/re-emitted partial fields
# ---------------------------------------------------------------------------


def test_ledger_meta_carries_code_fingerprint(tmp_path, monkeypatch):
    sys.path.insert(0, REPO)
    import bench

    fp = bench._code_fingerprint()
    assert fp and fp != "unknown"

    path = str(tmp_path / "ledger.json")
    led = bench.PhaseLedger(path, {"algo": "t"})
    assert led.meta["code"] == fp
    led.complete("A", {"on": [1.0]}, {"value": 1.0})
    assert led.measured_this_run == ["A"]
    assert led.headline["phases_measured_this_run"] == ["A"]
    assert led.headline["resumed_from_sidecar"] is False

    # same code: resume loads the phase, flags the sidecar origin
    led2 = bench.PhaseLedger(path, {"algo": "t"})
    assert led2.done("A")
    assert led2.resumed_from_sidecar is True
    led2.set_headline({"value": 1.0})
    assert led2.headline["resumed_from_sidecar"] is True
    assert led2.headline["phases_measured_this_run"] == []

    # stale code: a sidecar written under a different fingerprint is
    # discarded (ADVICE r5 — no SHEEPRL_TPU_BENCH_FRESH needed)
    with open(path) as fh:
        data = json.load(fh)
    data["meta"]["code"] = "deadbeef0000"
    with open(path, "w") as fh:
        json.dump(data, fh)
    led3 = bench.PhaseLedger(path, {"algo": "t"})
    assert not led3.done("A")
    assert led3.resumed_from_sidecar is False


def test_bench_compile_cache_arming(monkeypatch):
    import bench

    # explicit '' disables; unset + tiny stays hermetic (no env mutation)
    monkeypatch.setenv("SHEEPRL_TPU_COMPILE_CACHE", "")
    bench._arm_compile_cache(tiny=False)
    assert os.environ["SHEEPRL_TPU_COMPILE_CACHE"] == ""

    monkeypatch.delenv("SHEEPRL_TPU_COMPILE_CACHE", raising=False)
    bench._arm_compile_cache(tiny=True)
    assert "SHEEPRL_TPU_COMPILE_CACHE" not in os.environ

    # full bench: defaults to the runners' shared location and applies it
    bench._arm_compile_cache(tiny=False)
    assert os.environ["SHEEPRL_TPU_COMPILE_CACHE"] == "logs/jax_compile_cache"
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == "logs/jax_compile_cache"
    import jax

    assert jax.config.jax_compilation_cache_dir == "logs/jax_compile_cache"


@pytest.fixture(autouse=True)
def _restore_cache_config():
    """test_bench_compile_cache_arming mutates global jax config + env; put
    both back so the suite's shared-cache contract (conftest) holds."""
    import jax

    before_cfg = jax.config.jax_compilation_cache_dir
    before_env = {
        k: os.environ.get(k)
        for k in ("SHEEPRL_TPU_COMPILE_CACHE", "JAX_COMPILATION_CACHE_DIR")
    }
    yield
    jax.config.update("jax_compilation_cache_dir", before_cfg)
    for k, v in before_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
