"""StepProfiler: bounded-window jax.profiler trace capture (the TPU-native
observability upgrade over the reference's wall-clock-only timing,
SURVEY.md §5)."""

import glob
import os

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.profiler import StepProfiler


def test_inactive_without_dir():
    p = StepProfiler(None)
    p.tick()
    p.close()
    assert not p.active


def test_bounded_window_writes_xplane(tmp_path):
    d = str(tmp_path / "profile")
    p = StepProfiler(d, steps=2)
    p.tick()  # starts the trace
    assert p.active
    for _ in range(2):
        jnp.ones((8, 8)).sum().block_until_ready()
        p.tick()
    assert not p.active  # window closed itself
    p.tick()  # further ticks are no-ops
    traces = glob.glob(os.path.join(d, "plugins", "profile", "*", "*.xplane.pb"))
    assert traces, f"no xplane trace written under {d}"


def test_close_flushes_short_runs(tmp_path):
    d = str(tmp_path / "profile")
    p = StepProfiler(d, steps=100)
    p.tick()
    jnp.ones(4).sum().block_until_ready()
    p.close()
    assert not p.active
    assert glob.glob(os.path.join(d, "plugins", "profile", "*", "*.xplane.pb"))
