"""Telemetry subsystem (ISSUE 2): phase-timer nesting/exception safety, the
XLA compile tracker on a forced retrace, JSONL well-formedness + replay
through tools/telemetry_report.py, the NaN watchdog, decoupled-topology
gauges, and the always-on overhead bound (the instrumented path must stay
within 2% of uninstrumented on a CPU-sized workload)."""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from sheeprl_tpu.telemetry import (
    CompileTracker,
    PhaseTimers,
    Telemetry,
    monitoring_supported,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_report_module():
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(REPO, "tools", "telemetry_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# phase timers
# ---------------------------------------------------------------------------


def test_phase_nesting_builds_hierarchical_names():
    t = PhaseTimers()
    with t.phase("train"):
        with t.phase("dispatch"):
            time.sleep(0.002)
    out = t.flush()
    assert set(out) == {"train", "train/dispatch"}
    # the parent's span covers the child
    assert out["train"] >= out["train/dispatch"] > 0.0
    assert t.flush() == {}  # flush clears


def test_phase_exception_safety_records_time_and_reraises():
    t = PhaseTimers()
    with pytest.raises(RuntimeError):
        with t.phase("doomed"):
            time.sleep(0.002)
            raise RuntimeError("boom")
    out = t.flush()
    assert out["doomed"] > 0.0


def test_mark_sections_accumulate_and_flush_restarts_open_phase():
    t = PhaseTimers()
    t.mark("a")
    time.sleep(0.002)
    t.mark("b")  # ends a, starts b
    time.sleep(0.002)
    first = t.flush()  # b is OPEN: contributes elapsed and restarts
    assert first["a"] > 0.0 and first["b"] > 0.0
    time.sleep(0.002)
    t.mark(None)
    second = t.flush()
    # b's post-flush time lands in the second interval — no loss, no double
    # count across the flush boundary
    assert set(second) == {"b"} and second["b"] > 0.0


# ---------------------------------------------------------------------------
# compile tracker
# ---------------------------------------------------------------------------


def test_compile_tracker_counts_forced_retrace():
    if not monitoring_supported():
        pytest.skip("jax.monitoring not available in this jax")
    import jax
    import jax.numpy as jnp

    tracker = CompileTracker().attach()
    try:
        f = jax.jit(lambda x: x * 3.0 + 1.0)
        f(jnp.ones((7,))).block_until_ready()
        first = tracker.flush()
        f(jnp.ones((13,))).block_until_ready()  # new shape -> forced retrace
        second = tracker.flush()
    finally:
        tracker.detach()
    assert first["compiles"] >= 1
    assert second["compiles"] >= 1, "retrace did not increment the counter"
    assert second["total_compiles"] >= first["compiles"] + second["compiles"] - 1
    assert second["total_compile_seconds"] > 0.0
    # detached trackers stop counting
    f2 = jax.jit(lambda x: x - 5.0)
    f2(jnp.ones((3,))).block_until_ready()
    assert tracker.flush()["compiles"] == 0


# ---------------------------------------------------------------------------
# JSONL events + report replay
# ---------------------------------------------------------------------------


def test_jsonl_wellformed_and_replayable_by_report(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit")
    telem.event("start", algo="unit", env_id="dummy", seed=1)
    telem.mark("rollout")
    time.sleep(0.002)
    telem.mark("train/dispatch")
    merged = telem.interval({"Loss/x": 0.25}, step=100, sps=50.0)
    assert merged["Loss/x"] == 0.25
    assert merged["Time/rollout_seconds"] > 0.0
    telem.close()

    path = tmp_path / "telemetry.jsonl"
    lines = path.read_text().strip().splitlines()
    events = [json.loads(l) for l in lines]  # every line parses strictly
    kinds = [e["event"] for e in events]
    assert kinds[0] == "start" and kinds[-1] == "end"
    assert "log" in kinds
    log_ev = events[kinds.index("log")]
    assert log_ev["step"] == 100
    assert log_ev["metrics"]["Time/step_per_second"] == 50.0

    mod = _load_report_module()
    summary = mod.summarize(mod.load_events(str(tmp_path)))
    assert summary["end"] is not None and summary["crash"] is None
    assert summary["last_step"] == 100
    assert "rollout" in summary["phase_seconds"]
    assert mod.render(summary)  # renders without raising


def test_report_tolerates_truncated_tail_and_reports_crash(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit")
    telem.event("start", algo="unit")
    telem.interval({"Loss/x": 1.0}, step=1)
    telem.event("crash", error="KeyboardInterrupt")
    telem.close()
    path = tmp_path / "telemetry.jsonl"
    with open(path, "a") as fh:
        fh.write('{"ts": 1, "event": "log", "metr')  # crash mid-write
    mod = _load_report_module()
    summary = mod.summarize(mod.load_events(str(path)))
    assert summary["crash"] is not None
    assert "CRASHED" in mod.render(summary)


def test_selftest_entrypoint_passes():
    mod = _load_report_module()
    assert mod.main(["--selftest"]) == 0


def test_sheeptrace_selftest_entrypoint_passes():
    """sheeptrace's selftest builds skewed multi-role shards through the
    real Telemetry and asserts clock merge + chain reconstruction — wired
    exactly like telemetry_report's."""
    spec = importlib.util.spec_from_file_location(
        "sheeptrace", os.path.join(REPO, "tools", "sheeptrace.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--selftest"]) == 0


def test_report_reads_role_shard_when_learner_shard_absent(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit", role="actor0")
    telem.event("start", algo="unit")
    telem.interval({"Loss/x": 1.0}, step=3)
    telem.close()
    assert not (tmp_path / "telemetry.jsonl").exists()
    mod = _load_report_module()
    summary = mod.summarize(mod.load_events(str(tmp_path)))
    assert summary["last_step"] == 3


# ---------------------------------------------------------------------------
# NaN watchdog
# ---------------------------------------------------------------------------


def test_nan_watchdog_fires_on_injected_inf(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit")
    merged = telem.interval(
        {"Loss/ok": 1.0, "Loss/exploded": float("inf"), "Loss/gone": float("nan")},
        step=7,
    )
    telem.close()
    assert merged["Health/nonfinite_metrics"] == 2.0
    events = [
        json.loads(l)
        for l in (tmp_path / "telemetry.jsonl").read_text().strip().splitlines()
    ]
    nan_evs = [e for e in events if e["event"] == "health.nan"]
    assert len(nan_evs) == 1
    assert nan_evs[0]["keys"] == ["Loss/exploded", "Loss/gone"]
    assert nan_evs[0]["step"] == 7
    # the log event must still be strict JSON despite the non-finite values
    log_evs = [e for e in events if e["event"] == "log"]
    assert log_evs and isinstance(log_evs[0]["metrics"]["Loss/exploded"], str)


def test_disabled_telemetry_passes_metrics_through(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit", enabled=False)
    metrics = {"Loss/x": 1.0}
    assert telem.interval(metrics, step=1) is metrics
    telem.mark("rollout")  # all no-ops, no file
    telem.close()
    assert not (tmp_path / "telemetry.jsonl").exists()


def test_nonzero_rank_writes_no_jsonl(tmp_path):
    telem = Telemetry(str(tmp_path), rank=1, algo="unit")
    out = telem.interval({"Loss/x": 1.0}, step=1)
    telem.close()
    assert "Loss/x" in out  # timers/merge still work (no-op logger eats it)
    assert not (tmp_path / "telemetry.jsonl").exists()


# ---------------------------------------------------------------------------
# decoupled-topology gauges
# ---------------------------------------------------------------------------


def test_decoupled_gauges_track_transfers_and_staleness():
    import jax.numpy as jnp

    from sheeprl_tpu.parallel.decoupled import make_decoupled_meshes

    meshes = make_decoupled_meshes(2)
    g0 = meshes.telemetry_gauges()
    assert g0["Decoupled/data_transfers"] == 0.0
    assert g0["Decoupled/weight_queue_depth"] == 0.0

    meshes.to_trainers({"x": jnp.ones((4, 3))})
    meshes.to_player({"w": jnp.ones((5,))})
    g1 = meshes.telemetry_gauges()
    assert g1["Decoupled/data_transfers"] == 1.0
    assert g1["Decoupled/data_mb_total"] > 0.0
    assert g1["Decoupled/weight_transfers"] == 1.0
    assert g1["Decoupled/weight_queue_depth"] == 1.0  # shipped, not applied

    meshes.note_weights_applied()
    g2 = meshes.telemetry_gauges()
    assert g2["Decoupled/weight_queue_depth"] == 0.0
    assert g2["Decoupled/weight_staleness_s"] >= 0.0


# ---------------------------------------------------------------------------
# end-to-end: tiny PPO run writes telemetry; the report reads it back
# ---------------------------------------------------------------------------


@pytest.mark.timeout(300)
def test_tiny_ppo_run_emits_telemetry_and_report_renders(tmp_path):
    import sheeprl_tpu.algos  # noqa: F401 - fire registrations
    from sheeprl_tpu.utils.registry import tasks

    tasks["ppo"](
        [
            "--env_id", "CartPole-v1", "--dry_run", "--num_envs", "1",
            "--rollout_steps", "8", "--per_rank_batch_size", "4",
            "--update_epochs", "1", "--dense_units", "8", "--mlp_layers", "1",
            "--cnn_features_dim", "16", "--mlp_features_dim", "8",
            "--root_dir", str(tmp_path), "--run_name", "telem",
        ]
    )
    log_dir = tmp_path / "telem"
    assert (log_dir / "telemetry.jsonl").exists()
    mod = _load_report_module()
    summary = mod.summarize(mod.load_events(str(log_dir)))
    assert summary["start"]["algo"] == "ppo"
    assert summary["end"] is not None and summary["crash"] is None
    # the acceptance phases: rollout + train/dispatch measured, checkpoint
    # lifecycle recorded via save_checkpoint's global emit
    assert summary["phase_seconds"].get("rollout", 0.0) > 0.0
    assert "train/dispatch" in summary["phase_seconds"]
    assert summary["checkpoints"], "checkpoint event missing"
    rendered = mod.render(summary)
    assert "phase breakdown" in rendered and "rollout" in rendered


# ---------------------------------------------------------------------------
# overhead bound
# ---------------------------------------------------------------------------


@pytest.mark.timeout(120)
def test_telemetry_overhead_within_two_percent(tmp_path):
    """The always-on instrumentation pattern every main uses (a few marks +
    one interval() per logging interval) must cost <2% of a realistically
    sized step. Per-mark cost on this box is ~5-10us and interval() ~200us
    (dominated by the JSONL flush), so the bound is checked against a
    ~3-4ms workload — the floor of what one env step + dispatch costs even
    on the tiny CPU configs; real updates are 10-1000x larger."""
    a = np.random.default_rng(0).normal(size=(300, 300))

    def workload():
        return float(np.linalg.norm(a @ a))

    iters, interval_every = 60, 15

    def run_plain():
        t0 = time.perf_counter()
        for _ in range(iters):
            workload()
        return time.perf_counter() - t0

    telem = Telemetry(str(tmp_path), rank=0, algo="overhead")

    def run_instrumented():
        t0 = time.perf_counter()
        for i in range(iters):
            telem.mark("rollout")
            workload()
            telem.mark("train/dispatch")
            telem.mark("log")
            if (i + 1) % interval_every == 0:
                telem.interval({"Loss/x": 1.0}, step=i)
        return time.perf_counter() - t0

    run_plain(), run_instrumented()  # warmup both paths
    # interleaved pairs + min-of-ratios: a box-wide slowdown hits both arms
    # of a pair equally, and one clean pair suffices to prove the bound
    ratios = [run_instrumented() / run_plain() for _ in range(6)]
    telem.close()
    overhead = min(ratios) - 1.0
    assert overhead < 0.02, f"telemetry overhead {overhead:.2%} exceeds 2%"
