"""MetricAggregator / MovingAverageMetric (reference metric.py:12-137):
running means, windowed stats, and the lazy device-scalar pull — updating
with jax scalars in the hot loop must not force a sync, and compute() must
batch-prefetch then convert correctly."""

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.metric import MetricAggregator, MovingAverageMetric


def test_mean_metric_update_compute_reset():
    agg = MetricAggregator()
    agg.update("loss", 1.0)
    agg.update("loss", 3.0)
    out = agg.compute()
    assert out == {"loss": 2.0}
    agg.reset()
    assert agg.compute() == {}  # empty metrics are skipped


def test_device_scalars_pull_at_compute_time():
    agg = MetricAggregator()
    # jax scalars (what train_step metrics are) — update must accept them
    # raw; compute prefetches then converts
    agg.update("a", jnp.float32(1.5))
    agg.update("a", jnp.float32(2.5))
    agg.update("b", jnp.float32(-1.0))
    out = agg.compute()
    assert out["a"] == pytest.approx(2.0)
    assert out["b"] == pytest.approx(-1.0)


def test_moving_average_window_and_dict_flattening():
    agg = MetricAggregator({"rew": MovingAverageMetric(window=3)})
    for v in (1.0, 2.0, jnp.float32(3.0), 4.0):  # first value evicted
        agg.update("rew", v)
    out = agg.compute()
    assert out["rew/mean"] == pytest.approx(3.0)
    assert out["rew/min"] == pytest.approx(2.0)
    assert out["rew/max"] == pytest.approx(4.0)
    assert out["rew/std"] == pytest.approx(np.std([2.0, 3.0, 4.0]))
    # the per-interval reset must NOT wipe the moving-average window — a
    # windowed metric wiped every logging interval degenerates into an
    # interval mean (ISSUE 2 satellite)
    agg.reset()
    out = agg.compute()
    assert out["rew/mean"] == pytest.approx(3.0)
    agg.reset(force=True)
    assert agg.compute() == {}


def test_reset_on_compute_opt_in_and_mean_metric_default():
    agg = MetricAggregator(
        {
            "windowed": MovingAverageMetric(window=4),
            "interval": MovingAverageMetric(window=4, reset_on_compute=True),
        }
    )
    agg.update("windowed", 1.0)
    agg.update("interval", 1.0)
    agg.update("plain", 5.0)  # auto-added MeanMetric: resets every interval
    agg.reset()
    out = agg.compute()
    assert "windowed/mean" in out  # survived
    assert "interval/mean" not in out  # opted into interval resets
    assert "plain" not in out


def test_add_duplicate_raises_and_pop():
    agg = MetricAggregator()
    agg.add("x")
    with pytest.raises(ValueError):
        agg.add("x")
    agg.pop("x")
    agg.add("x")  # fine after pop
