"""Unit tests for the context-parallel mesh helpers."""

import pytest

from sheeprl_tpu.parallel import (
    make_mesh,
    scan_batch_spec,
    seq_axis_size,
    time_batch_sharding,
)


def test_scan_batch_spec_regimes():
    mesh = make_mesh(8, seq_devices=4)  # (data=2, seq=4)
    # B divides the whole grid -> fully sharded scan batch
    assert scan_batch_spec(mesh, 8) == (None, ("data", "seq"))
    assert scan_batch_spec(mesh, 16) == (None, ("data", "seq"))
    # B doesn't divide -> data-only (seq groups replicate the scan)
    assert scan_batch_spec(mesh, 4) == (None, "data")
    assert scan_batch_spec(mesh, 6) == (None, "data")
    # 1-D mesh or no mesh -> data-only spec (constrain is identity anyway)
    assert scan_batch_spec(make_mesh(8), 8) == (None, "data")
    assert scan_batch_spec(None, 8) == (None, "data")


def test_time_batch_sharding_specs():
    mesh2 = make_mesh(8, seq_devices=2)
    spec = time_batch_sharding(mesh2).spec
    assert tuple(spec) == ("seq", "data")
    mesh1 = make_mesh(8)
    spec = time_batch_sharding(mesh1).spec
    assert tuple(spec) == (None, "data")


def test_seq_axis_size():
    assert seq_axis_size(make_mesh(8)) == 1
    assert seq_axis_size(make_mesh(8, seq_devices=2)) == 2
    with pytest.raises(ValueError, match="must divide"):
        make_mesh(8, seq_devices=5)
