"""Unit tests for the context-parallel mesh helpers."""

import pytest

from sheeprl_tpu.parallel import (
    make_mesh,
    scan_batch_spec,
    seq_axis_size,
    time_batch_sharding,
)


def test_scan_batch_spec_regimes():
    # the scan batch shards over "data" only, whatever the mesh/batch: the
    # fully-sharded (None, ("data", "seq")) layout triggers an involuntary
    # full rematerialization in every GSPMD backward (see scan_batch_spec)
    mesh = make_mesh(8, seq_devices=4)  # (data=2, seq=4)
    assert scan_batch_spec(mesh, 8) == (None, "data")
    assert scan_batch_spec(mesh, 16) == (None, "data")
    assert scan_batch_spec(mesh, 4) == (None, "data")
    # 1-D mesh or no mesh -> same spec (constrain is identity anyway)
    assert scan_batch_spec(make_mesh(8), 8) == (None, "data")
    assert scan_batch_spec(None, 8) == (None, "data")


def test_time_batch_sharding_specs():
    mesh2 = make_mesh(8, seq_devices=2)
    spec = time_batch_sharding(mesh2).spec
    assert tuple(spec) == ("seq", "data")
    mesh1 = make_mesh(8)
    spec = time_batch_sharding(mesh1).spec
    assert tuple(spec) == (None, "data")


def test_seq_axis_size():
    assert seq_axis_size(make_mesh(8)) == 1
    assert seq_axis_size(make_mesh(8, seq_devices=2)) == 2
    with pytest.raises(ValueError, match="must divide"):
        make_mesh(8, seq_devices=5)
