"""Unit tests for the shared --eval_only machinery
(sheeprl_tpu/utils/evaluation.py)."""

import pytest

from sheeprl_tpu.algos.args import StandardArgs
from sheeprl_tpu.utils.evaluation import (
    apply_eval_overrides,
    run_test_episodes,
    validate_eval_args,
)


class _StubLogger:
    def __init__(self):
        self.logged = []

    def log(self, name, value, step):
        self.logged.append((name, float(value), step))


def test_validate_requires_checkpoint():
    args = StandardArgs(eval_only=True)
    with pytest.raises(ValueError, match="checkpoint_path"):
        validate_eval_args(args)
    validate_eval_args(StandardArgs(eval_only=False))  # no-op
    validate_eval_args(StandardArgs(eval_only=True, checkpoint_path="x"))


def test_overrides_keep_cli_flags_and_default_one_device():
    args = StandardArgs(
        eval_only=True, checkpoint_path="x", test_episodes=7, seed=123,
        platform="cpu", root_dir="/tmp/out", run_name="e",
    )
    saved = {
        "seed": 42, "platform": None, "num_devices": 4,
        "root_dir": "/train", "run_name": "t", "test_episodes": 1,
    }
    out = apply_eval_overrides(dict(saved), args)
    assert out["eval_only"] is True
    assert out["test_episodes"] == 7
    assert out["seed"] == 123
    assert out["platform"] == "cpu"
    assert out["root_dir"] == "/tmp/out" and out["run_name"] == "e"
    # CLI default -1 ("all local devices") maps to ONE device for eval
    assert out["num_devices"] == 1

    # explicit device counts pass through
    args.num_devices = 2
    assert apply_eval_overrides(dict(saved), args)["num_devices"] == 2

    # without --eval_only the saved config wins untouched
    args2 = StandardArgs(eval_only=False, checkpoint_path="x", seed=9)
    assert apply_eval_overrides(dict(saved), args2) == saved


def test_run_test_episodes_varies_seed_and_logs_mean():
    args = StandardArgs(test_episodes=3, seed=100)
    logger = _StubLogger()
    seen_seeds = []

    def episode():
        seen_seeds.append(args.seed)
        return float(args.seed)  # distinct return per distinct seed

    rets = run_test_episodes(episode, args, logger)
    assert seen_seeds == [100, 101, 102]
    assert args.seed == 100  # restored
    assert rets == [100.0, 101.0, 102.0]
    series = [e for e in logger.logged if e[0] == "Test/episode_reward"]
    assert [s[2] for s in series] == [0, 1, 2]
    (mean,) = [e for e in logger.logged if e[0] == "Test/mean_reward"]
    assert mean[1] == pytest.approx(101.0)


def test_run_test_episodes_single_episode_no_mean():
    args = StandardArgs(test_episodes=1, seed=5)
    logger = _StubLogger()
    run_test_episodes(lambda: 1.0, args, logger)
    assert not any(e[0] == "Test/mean_reward" for e in logger.logged)


def test_seed_restored_on_exception():
    args = StandardArgs(test_episodes=3, seed=50)

    def boom():
        raise RuntimeError("episode crashed")

    with pytest.raises(RuntimeError):
        run_test_episodes(boom, args, _StubLogger())
    assert args.seed == 50


def test_capture_video_persists_unless_explicitly_overridden():
    """ADVICE r3: a run trained with capture_video=True must not silently
    evaluate with the CLI default False — the flag only overrides the
    checkpoint value when the user actually passed it."""
    from sheeprl_tpu.algos.ppo.args import PPOArgs
    from sheeprl_tpu.utils.parser import DataclassArgumentParser

    saved = {"capture_video": True, "seed": 1}

    def parse(argv):
        return DataclassArgumentParser(PPOArgs).parse_args_into_dataclasses(
            argv
        )[0]

    base = ["--eval_only", "--checkpoint_path", "x"]
    # not passed -> checkpoint value survives
    out = apply_eval_overrides(dict(saved), parse(base))
    assert out["capture_video"] is True
    # explicitly disabled -> CLI wins
    out = apply_eval_overrides(dict(saved), parse([*base, "--no_capture_video"]))
    assert out["capture_video"] is False
    # explicitly enabled over a False checkpoint -> CLI wins
    out = apply_eval_overrides(
        {"capture_video": False, "seed": 1}, parse([*base, "--capture_video"])
    )
    assert out["capture_video"] is True
