"""Two-process jax.distributed smoke test on local CPU — the JAX analog of
the reference's torchrun+Gloo multi-node tests
(/root/reference/tests/test_algos/test_algos.py:192-211): spawn two OS
processes, initialize the distributed runtime over localhost, build a global
mesh spanning both processes' devices, and run a sharded computation whose
result proves cross-process reduction happened."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1])
coord = sys.argv[2]

from sheeprl_tpu.parallel import distributed_setup, make_mesh, shard_batch

distributed_setup(coordinator_address=coord, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == pid

mesh = make_mesh()  # spans both processes: 2 local CPU devices each
assert mesh.devices.size == 4, mesh.devices

# each process contributes a distinct local half of the global batch
local = np.full((2, 3), float(pid + 1), dtype=np.float32)
batch = shard_batch({"x": local}, mesh)
assert batch["x"].shape == (4, 3)  # global shape

total = jax.jit(lambda t: t["x"].sum())(batch)
# process 0 contributes 2*3*1, process 1 contributes 2*3*2 -> 18
np.testing.assert_allclose(float(total), 18.0)

# --- context-parallel layout across hosts --------------------------------
from jax.sharding import Mesh
from sheeprl_tpu.parallel import shard_time_batch

# (data=2 over processes, seq=2 within each process): every seq group is
# process-local, so each process contributes full-T, local-B data
mesh2 = make_mesh(seq_devices=2)
assert dict(mesh2.shape) == {"data": 2, "seq": 2}
local_tb = np.full((4, 1, 3), float(pid + 1), dtype=np.float32)  # [T, B_local, F]
seq_batch = shard_time_batch({"x": local_tb}, mesh2)
assert seq_batch["x"].shape == (4, 2, 3)  # global [T, B, F]
total2 = jax.jit(lambda t: t["x"].sum())(seq_batch)
np.testing.assert_allclose(float(total2), 4 * 3 * (1 + 2))

# a seq axis spanning processes must be rejected (it would stitch the two
# hosts' unrelated samples along time)
bad = Mesh(np.asarray(jax.devices()).reshape(2, 2).T, ("data", "seq"))
try:
    shard_time_batch({"x": local_tb}, bad)
except ValueError as e:
    assert "spans processes" in str(e), e
else:
    raise AssertionError("cross-process seq axis was not rejected")
print(f"proc {pid} ok", flush=True)
"""


@pytest.mark.timeout(300)
def test_two_process_distributed_smoke(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=2").strip()
    env["PYTHONPATH"] = "/root/repo"

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(pid), coord],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    if any(
        "Multiprocess computations aren't implemented" in out for out in outs
    ):
        # jaxlib's CPU backend (<=0.4.36) cannot EXECUTE a computation over
        # a cross-process sharded array — a platform limitation, not a code
        # bug: distributed init, the global mesh, and both sharding layouts
        # were already exercised up to the first collective. The full
        # receipt needs a TPU/GPU runner (ROADMAP: multi-host validation);
        # the test stays armed so a jaxlib that grows CPU multiprocess
        # support re-enables it automatically.
        pytest.skip("CPU backend cannot execute multiprocess computations")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} ok" in out
