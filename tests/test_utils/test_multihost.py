"""Two-process jax.distributed smoke test on local CPU — the JAX analog of
the reference's torchrun+Gloo multi-node tests
(/root/reference/tests/test_algos/test_algos.py:192-211): spawn two OS
processes, initialize the distributed runtime over localhost, build a global
mesh spanning both processes' devices, and run a sharded computation whose
result proves cross-process reduction happened."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1])
coord = sys.argv[2]

from sheeprl_tpu.parallel import distributed_setup, make_mesh, shard_batch

distributed_setup(coordinator_address=coord, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == pid

mesh = make_mesh()  # spans both processes: 2 local CPU devices each
assert mesh.devices.size == 4, mesh.devices

# each process contributes a distinct local half of the global batch
local = np.full((2, 3), float(pid + 1), dtype=np.float32)
batch = shard_batch({"x": local}, mesh)
assert batch["x"].shape == (4, 3)  # global shape

total = jax.jit(lambda t: t["x"].sum())(batch)
# process 0 contributes 2*3*1, process 1 contributes 2*3*2 -> 18
np.testing.assert_allclose(float(total), 18.0)
print(f"proc {pid} ok", flush=True)
"""


@pytest.mark.timeout(300)
def test_two_process_distributed_smoke(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=2").strip()
    env["PYTHONPATH"] = "/root/repo"

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(pid), coord],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} ok" in out
