"""Smoke-test the driver-facing bench entry: `python bench.py --tiny` must
print exactly one JSON line with the contract keys whatever the backend —
the round artifact depends on it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.timeout(900)
def test_bench_tiny_prints_contract_json():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = flags
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tiny"],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        timeout=850,
    )
    diag = f"stdout: {proc.stdout!r}\nstderr tail: {proc.stderr[-2000:]!r}"
    assert proc.returncode == 0, diag
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line; {diag}"
    payload = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in payload, f"missing contract key {k}"
    # a 0.0 value means every guarded measurement failed (sentinel) — the
    # guarded tracebacks land on stderr, so surface them
    assert payload["value"] > 0, diag


def test_interleave_keep_rule_helpers():
    """The ABAB keep-decision primitives (VERDICT r3 #1): pooled medians
    ignore dead segments, and a challenger is kept only when its paired
    advantage exceeds both the observed spread and the 2% floor."""
    import bench

    assert bench._pooled([0.0, 0.0]) == 0.0
    assert bench._pooled([100.0, 0.0, 110.0]) == 105.0

    base = [100.0, 100.0, 100.0, 100.0]
    # clear win: +10% with tight spread
    assert bench._beats([110.0, 110.5, 109.5, 110.0], base)
    # sub-noise win: +1% never kept (margin floor)
    assert not bench._beats([101.0, 101.0, 101.0, 101.0], base)
    # big median win but spread wider than the advantage: not kept
    assert not bench._beats([150.0, 80.0, 150.0, 80.0], base)
    # dead challenger / dead baseline: never kept
    assert not bench._beats([0.0, 0.0, 0.0, 0.0], base)
    assert not bench._beats([110.0] * 4, [0.0] * 4)
    # one dead segment is excluded from pairing, not fatal
    assert bench._beats([110.0, 0.0, 110.0, 110.0], base)


def test_interleave_sps_round_robin_and_guards():
    import bench

    calls = []

    def make_run(name, dt):
        def run(n):
            calls.append(name)
            return dt * n
        return run

    samples = bench._interleave_sps(
        {"a": make_run("a", 0.1), "b": make_run("b", 0.2), "dead": None},
        steps_per_cycle=10, segments=3, cycles_per_segment=2,
        discards=[], tiny=True,
    )
    # round-robin order: a,b,a,b,a,b (dead variant never called)
    assert calls == ["a", "b"] * 3
    assert samples["dead"] == [0.0, 0.0, 0.0]
    assert all(abs(s - 100.0) < 1e-6 for s in samples["a"])
    assert all(abs(s - 50.0) < 1e-6 for s in samples["b"])


def test_paired_ratio_ranking_key():
    """Candidates from different interleaved sessions rank by advantage
    over their OWN session's baseline — never by absolute sps."""
    import bench

    # 20% advantage in a slow-weather session
    assert abs(bench._paired_ratio([120.0, 118.0], [100.0, 100.0]) - 1.19) < 0.02
    # bigger advantage in an even slower session still ranks higher
    fast = bench._paired_ratio([120.0, 120.0], [100.0, 100.0])
    slow = bench._paired_ratio([90.0, 90.0], [70.0, 70.0])
    assert slow > fast
    # dead segments excluded; fewer than 2 valid pairs -> 0.0 sentinel
    assert bench._paired_ratio([0.0, 110.0], [100.0, 100.0]) == 0.0
    assert bench._paired_ratio([0.0] * 4, [100.0] * 4) == 0.0
