"""Smoke-test the driver-facing bench entry: `python bench.py --tiny` must
print exactly one JSON line with the contract keys whatever the backend —
the round artifact depends on it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.timeout(900)
def test_bench_tiny_prints_contract_json():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = flags
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tiny"],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        timeout=850,
    )
    diag = f"stdout: {proc.stdout!r}\nstderr tail: {proc.stderr[-2000:]!r}"
    assert proc.returncode == 0, diag
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line; {diag}"
    payload = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in payload, f"missing contract key {k}"
    # a 0.0 value means every guarded measurement failed (sentinel) — the
    # guarded tracebacks land on stderr, so surface them
    assert payload["value"] > 0, diag


@pytest.mark.timeout(900)
def test_bench_ledger_partial_emission_and_resume(tmp_path):
    """VERDICT r4 #1 (the CPU-validated demonstration): a bench session that
    dies mid-run must still emit its completed phases, and a restart must
    skip them. Run 1 is budgeted to ONE phase (the stand-in for a tunnel
    death after phase A) — it must print a partial headline with value > 0
    and persist the phase to the sidecar. Run 2 resumes from the sidecar,
    skips the recorded phase, and completes the remaining phases."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    ledger = str(tmp_path / "ledger.json")
    env["SHEEPRL_TPU_BENCH_LEDGER"] = ledger

    # run 1: die after the first completed phase
    env1 = dict(env, SHEEPRL_TPU_BENCH_MAX_PHASES="1")
    p1 = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tiny"],
        cwd=REPO, env=env1, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, timeout=420,
    )
    diag = f"stdout: {p1.stdout!r}\nstderr tail: {p1.stderr[-2000:]!r}"
    assert p1.returncode == 0, diag
    lines = [l for l in p1.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, diag
    partial = json.loads(lines[0])
    assert partial["value"] > 0, f"partial emission carries no number; {diag}"
    assert partial.get("partial") is True, diag
    assert "phase_budget_exhausted" in partial.get("error", ""), diag
    assert partial["phases_completed"] == ["A_wave_all"], diag
    with open(ledger) as fh:
        side = json.load(fh)
    assert "A_wave_all" in side["phases"], side.get("phases", {}).keys()

    # run 2: resume — phase A must be loaded, not re-measured
    p2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tiny"],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, timeout=420,
    )
    diag2 = f"stdout: {p2.stdout!r}\nstderr tail: {p2.stderr[-2000:]!r}"
    assert p2.returncode == 0, diag2
    lines2 = [l for l in p2.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines2) == 1, diag2
    final = json.loads(lines2[0])
    assert final["value"] > 0, diag2
    assert "A_wave_all" in final["phases_completed"], diag2
    assert "E_e2e" in final["phases_completed"], diag2
    assert "phase A_wave_all loaded" in p2.stderr, (
        "resume did not skip the recorded phase; " + diag2
    )


def test_interleave_keep_rule_helpers():
    """The ABAB keep-decision primitives (VERDICT r3 #1): pooled medians
    ignore dead segments, and a challenger is kept only when its paired
    advantage exceeds both the observed spread and the 2% floor."""
    import bench

    assert bench._pooled([0.0, 0.0]) == 0.0
    assert bench._pooled([100.0, 0.0, 110.0]) == 105.0

    base = [100.0, 100.0, 100.0, 100.0]
    # clear win: +10% with tight spread
    assert bench._beats([110.0, 110.5, 109.5, 110.0], base)
    # sub-noise win: +1% never kept (margin floor)
    assert not bench._beats([101.0, 101.0, 101.0, 101.0], base)
    # big median win but spread wider than the advantage: not kept
    assert not bench._beats([150.0, 80.0, 150.0, 80.0], base)
    # dead challenger / dead baseline: never kept
    assert not bench._beats([0.0, 0.0, 0.0, 0.0], base)
    assert not bench._beats([110.0] * 4, [0.0] * 4)
    # one dead segment is excluded from pairing, not fatal
    assert bench._beats([110.0, 0.0, 110.0, 110.0], base)


def test_interleave_sps_round_robin_and_guards():
    import bench

    calls = []

    def make_run(name, dt):
        def run(n):
            calls.append(name)
            return dt * n
        return run

    samples = bench._interleave_sps(
        {"a": make_run("a", 0.1), "b": make_run("b", 0.2), "dead": None},
        steps_per_cycle=10, segments=3, cycles_per_segment=2,
        discards=[], tiny=True,
    )
    # round-robin order: a,b,a,b,a,b (dead variant never called)
    assert calls == ["a", "b"] * 3
    assert samples["dead"] == [0.0, 0.0, 0.0]
    assert all(abs(s - 100.0) < 1e-6 for s in samples["a"])
    assert all(abs(s - 50.0) < 1e-6 for s in samples["b"])


def test_paired_ratio_ranking_key():
    """Candidates from different interleaved sessions rank by advantage
    over their OWN session's baseline — never by absolute sps."""
    import bench

    # 20% advantage in a slow-weather session
    assert abs(bench._paired_ratio([120.0, 118.0], [100.0, 100.0]) - 1.19) < 0.02
    # bigger advantage in an even slower session still ranks higher
    fast = bench._paired_ratio([120.0, 120.0], [100.0, 100.0])
    slow = bench._paired_ratio([90.0, 90.0], [70.0, 70.0])
    assert slow > fast
    # dead segments excluded; fewer than 2 valid pairs -> 0.0 sentinel
    assert bench._paired_ratio([0.0, 110.0], [100.0, 100.0]) == 0.0
    assert bench._paired_ratio([0.0] * 4, [100.0] * 4) == 0.0


@pytest.mark.timeout(900)
def test_bench_ppo_telemetry_ab_records_overhead():
    """ISSUE 2 satellite: `--algo ppo --telemetry ab` must run both arms of
    the instrumentation A/B and record the overhead in the artifact. The
    strict <2% bound is asserted on a controlled workload in
    tests/test_utils/test_telemetry.py; here the receipt is that the A/B
    ran, both arms produced real numbers, and the instrumented arm is not
    grossly slower (>15% would mean the subsystem is broken, not noisy)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--algo", "ppo",
         "--telemetry", "ab"],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, timeout=850,
    )
    diag = f"stdout: {proc.stdout!r}\nstderr tail: {proc.stderr[-2000:]!r}"
    assert proc.returncode == 0, diag
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, diag
    payload = json.loads(lines[0])
    assert payload["telemetry"] == "ab"
    assert payload["telemetry_on_sps"] > 0 and payload["telemetry_off_sps"] > 0, diag
    assert payload["value"] == payload["telemetry_on_sps"]
    assert payload["telemetry_overhead_pct"] < 15.0, (
        f"instrumented arm {payload['telemetry_overhead_pct']}% slower; {diag}"
    )
