"""Smoke-test the driver-facing bench entry: `python bench.py --tiny` must
print exactly one JSON line with the contract keys whatever the backend —
the round artifact depends on it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.timeout(900)
def test_bench_tiny_prints_contract_json():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = flags
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tiny"],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        timeout=850,
    )
    diag = f"stdout: {proc.stdout!r}\nstderr tail: {proc.stderr[-2000:]!r}"
    assert proc.returncode == 0, diag
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line; {diag}"
    payload = json.loads(lines[0])
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in payload, f"missing contract key {k}"
    # a 0.0 value means every guarded measurement failed (sentinel) — the
    # guarded tracebacks land on stderr, so surface them
    assert payload["value"] > 0, diag
