"""donating_jit: donation must be dropped in the known-corrupting
configuration (CPU backend + persistent compilation cache — the tier-1
environment, where deserialized donating executables corrupted the heap)
and honor the SHEEPRL_TPU_DONATE override in both directions."""

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.jit import donating_jit, donation_safe


def test_donation_disabled_under_cpu_with_persistent_cache(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_DONATE", raising=False)
    # conftest wires the persistent cache; this suite runs on CPU
    assert jax.default_backend() == "cpu"
    if jax.config.jax_compilation_cache_dir:
        assert donation_safe() is False
    x = jnp.ones((4,))
    f = donating_jit(lambda a: a * 2, donate_argnums=(0,))
    y = f(x)
    # without donation the input buffer stays alive and usable
    if not donation_safe():
        assert float(x.sum()) == 4.0
    assert float(y.sum()) == 8.0


def test_donate_override_forces_each_direction(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_DONATE", "1")
    assert donation_safe() is True
    f = donating_jit(lambda a: a + 1, donate_argnums=(0,))
    x = jnp.ones((3,))
    f(x)
    assert x.is_deleted()  # donation actually happened

    monkeypatch.setenv("SHEEPRL_TPU_DONATE", "0")
    assert donation_safe() is False
    g = donating_jit(lambda a: a + 1, donate_argnums=(0,))
    z = jnp.ones((3,))
    g(z)
    assert not z.is_deleted()


def test_decorator_form_matches_jax_jit():
    from functools import partial

    @partial(donating_jit, donate_argnums=(0,))
    def step(s, d):
        return s + d

    assert float(step(jnp.float32(1.0), jnp.float32(2.0))) == 3.0
