"""Run the serve suite under the sheepsync runtime thread sanitizer.

Same contract as tests/test_flock/conftest.py: instrumented
Lock/RLock/Condition wrappers assert per-thread acquisition order
against the committed ledger while the batcher/server/hot-reload tests
run; violations are collected (never raised) and printed at teardown.
"""

import pytest

from sheeprl_tpu.analysis import thread_sanitizer


@pytest.fixture(scope="package", autouse=True)
def _sheepsync_sanitizer():
    san = thread_sanitizer.install()
    yield san
    summary = thread_sanitizer.uninstall()
    if summary and summary["violations"]:
        print(
            "\n[sheepsync] lock-order violations observed during the serve "
            f"suite: {summary['violations']}"
        )
