"""The registered `serve` main: CLI surface, dry-run lifecycle, capture
mode, telemetry record."""

import glob
import json
import os

import pytest

SAC_TINY_MODEL = (
    "--env_id Pendulum-v1 --actor_hidden_size 16 --critic_hidden_size 16"
)


def test_serve_task_registered():
    import sheeprl_tpu.algos  # noqa: F401 — fire registrations
    from sheeprl_tpu.utils.registry import tasks

    assert "serve" in tasks


def test_serve_args_validation():
    from sheeprl_tpu.serve import ServeArgs

    with pytest.raises(ValueError, match="algo"):
        ServeArgs(algo="ppo")
    with pytest.raises(ValueError, match="max_batch"):
        ServeArgs(max_batch=0)
    args = ServeArgs(algo="dreamer_v3", max_batch=4)
    assert args.warm_compile == "on"  # serving default: AOT the ladder


def test_serve_help_mentions_serving_surface(capsys):
    from sheeprl_tpu.utils.parser import DataclassArgumentParser
    from sheeprl_tpu.serve import ServeArgs

    parser = DataclassArgumentParser(ServeArgs)
    with pytest.raises(SystemExit):
        parser.parse_args_into_dataclasses(["--help"])
    help_text = capsys.readouterr().out
    for flag in ("--ckpt", "--batch_window_ms", "--deadline_ms", "--max_batch",
                 "--ladder", "--bind", "--reload_poll_s"):
        assert flag in help_text, flag


@pytest.mark.timeout(60)
def test_capture_mode_records_ladder_jits(tmp_path):
    """The analysis sweep contract: capture unwinds at plan.start() with
    one policy jit per requested rung and nothing executed."""
    from sheeprl_tpu.analysis import jaxpr_check as jc

    algo, extra = jc.resolve_capture("serve")
    plan = jc.capture_plan(algo, str(tmp_path), extra)
    assert [e.name for e in plan._entries] == ["policy_b1", "policy_b2", "policy_b4"]


@pytest.mark.timeout(180)
def test_dry_run_serves_and_writes_telemetry(tmp_path):
    """--dry_run brings the full stack up (policy, ladder, AOT plan,
    socket), writes the address file, emits a parseable Serve/* telemetry
    record, and exits cleanly."""
    import sheeprl_tpu.algos  # noqa: F401
    from sheeprl_tpu.utils.registry import tasks

    tasks["serve"]([
        "--algo", "sac",
        "--model_argv", SAC_TINY_MODEL,
        "--root_dir", str(tmp_path),
        "--run_name", "dry",
        "--platform", "cpu",
        "--max_batch", "2",
        "--dry_run",
    ])
    run_dir = os.path.join(str(tmp_path), "dry")
    addr = open(os.path.join(run_dir, "serve_address")).read().strip()
    assert addr.startswith(("unix:", "tcp:"))
    records = []
    for path in glob.glob(os.path.join(run_dir, "**", "*.jsonl"), recursive=True):
        with open(path) as fh:
            records += [json.loads(line) for line in fh if line.strip()]
    serve_metrics = [
        r for r in records
        if any(str(k).startswith("Serve/") for k in r.get("metrics", {}))
    ]
    assert serve_metrics, f"no Serve/* telemetry record in {run_dir}"
    events = {r.get("event") for r in records}
    assert "serve.start" in events and "serve.stop" in events
