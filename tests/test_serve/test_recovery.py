"""Serve-tier failure modes (ISSUE 16): typed ConnectionLost,
retry/backoff with idempotent request ids (reconnect resends the SAME id,
the server dedupes), SHED retry_after honoring, HEALTH probes, graceful
drain, and FrameError isolation on live sockets."""

import socket
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.flock import wire
from sheeprl_tpu.serve import (
    ConnectionLost,
    MicroBatcher,
    ParamsStore,
    RequestShed,
    ServeClient,
    ServeServer,
)
from sheeprl_tpu.serve.errors import ServeError
from sheeprl_tpu.serve.server import HEALTH, pack_request, unpack_request
from sheeprl_tpu.serve.policies import SACServePolicy

from .test_server import _make_actor, _obs, OBS_DIM


class _Recorder:
    def __init__(self):
        self.events = []

    def event(self, name, **data):
        self.events.append((name, data))

    def names(self):
        return [n for n, _ in self.events]


@pytest.fixture(scope="module")
def sac():
    return SACServePolicy(OBS_DIM, 1), _make_actor(0)


def _serving(policy, params, telem=None, deadline_ms=2000.0):
    store = ParamsStore(lambda path: params, params, source=None)

    def dispatch(stacked, pendings, rung):
        version, live = store.current()
        return policy.run(policy.step, live, version, stacked, pendings, rung), version

    batcher = MicroBatcher(
        dispatch, [1, 2, 4], window_ms=1.0, default_deadline_ms=deadline_ms
    )
    server = ServeServer(policy, store, batcher, telem=telem)
    server.start()
    return server


class _ScriptedServer:
    """A wire-speaking fake that scripts one behavior per connection —
    the knob the real server can't offer: dying mid-request on cue."""

    def __init__(self, scripts):
        self.scripts = list(scripts)
        self.seen_ids = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.address = f"tcp:127.0.0.1:{self._srv.getsockname()[1]}"
        self._thread = threading.Thread(
            target=self._loop, name="test-flaky-service", daemon=True
        )
        self._thread.start()

    def _loop(self):
        for script in self.scripts:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            try:
                frame = wire.recv_frame(conn)
                assert frame is not None and frame[0] == wire.HELLO
                wire.send_json(conn, wire.WELCOME, {"proto": 1, "algo": "fake"})
                frame = wire.recv_frame(conn)
                if frame is None:
                    continue
                meta, obs = unpack_request(frame[1])
                self.seen_ids.append(meta["id"])
                if script == "hangup":
                    conn.close()
                elif script == "shed":
                    wire.send_json(
                        conn, wire.SHED,
                        {"id": meta["id"], "retry_after_ms": 50.0,
                         "reason": "deadline"},
                    )
                    # same connection: the retried request after the hint
                    frame = wire.recv_frame(conn)
                    meta, obs = unpack_request(frame[1])
                    self.seen_ids.append(meta["id"])
                    self._respond(conn, meta, obs)
                else:  # "serve"
                    self._respond(conn, meta, obs)
            except (OSError, wire.FrameError):
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    @staticmethod
    def _respond(conn, meta, obs):
        out = {"actions": np.zeros_like(obs["obs"])}
        out_meta = {"id": meta["id"], "version": 1, "rung": 1,
                    "rows": 1, "queue_ms": 0.0}
        wire.send_frame(conn, wire.RESPONSE, pack_request(out_meta, out))

    def close(self):
        try:
            self._srv.close()
        except OSError:
            pass


def test_connection_lost_is_typed_and_default_not_retried():
    assert issubclass(ConnectionLost, ServeError)
    srv = _ScriptedServer(["hangup"])
    try:
        client = ServeClient(srv.address, timeout=5.0)
        # default retries=0: the dead socket surfaces immediately, typed
        with pytest.raises(ConnectionLost):
            client.request(_obs(1))
        client.close()
    finally:
        srv.close()


@pytest.mark.timeout(60)
def test_reconnect_resends_the_same_request_id():
    srv = _ScriptedServer(["hangup", "serve"])
    try:
        with ServeClient(srv.address, timeout=5.0, backoff_s=0.01) as client:
            result, meta = client.request(_obs(1), retries=2)
            assert result["actions"].shape == (1, OBS_DIM)
        # both attempts carried the SAME idempotent id — the server-side
        # dedupe contract depends on it
        assert len(srv.seen_ids) == 2
        assert srv.seen_ids[0] == srv.seen_ids[1] == meta["id"]
    finally:
        srv.close()


@pytest.mark.timeout(60)
def test_shed_retry_honors_retry_after_hint():
    srv = _ScriptedServer(["shed"])
    try:
        with ServeClient(srv.address, timeout=5.0) as client:
            t0 = time.monotonic()
            result, _meta = client.request(_obs(1), retries=1)
            elapsed = time.monotonic() - t0
        assert result["actions"].shape == (1, OBS_DIM)
        assert elapsed >= 0.04  # slept the server's 50 ms hint
        assert srv.seen_ids[0] == srv.seen_ids[1]
    finally:
        srv.close()


@pytest.mark.timeout(120)
def test_idempotent_string_ids_dedupe_on_the_real_server(sac):
    """Replaying an already-answered string id returns the cached frame
    byte-for-byte and never re-executes; int ids (the legacy protocol)
    are never deduped."""
    policy, params = sac
    server = _serving(policy, params)
    try:
        sock = wire.connect(server.address, timeout=10.0)
        wire.send_json(sock, wire.HELLO, {"proto": 1})
        wire.recv_json(sock, wire.WELCOME)
        payload = pack_request({"id": "abc-1"}, _obs(1))
        wire.send_frame(sock, wire.REQUEST, payload)
        kind1, reply1 = wire.recv_frame(sock)
        # the frame goes out BEFORE the completion counter bumps — settle
        deadline = time.monotonic() + 5.0
        while server.completed < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        executed = server.completed
        assert executed == 1
        wire.send_frame(sock, wire.REQUEST, payload)  # replay the SAME id
        kind2, reply2 = wire.recv_frame(sock)
        assert kind1 == kind2 == wire.RESPONSE
        assert reply1 == reply2  # cached frame, bit-exact
        assert server.completed == executed  # no second execution
        # int ids: full re-execution, replies independent
        legacy = pack_request({"id": 7}, _obs(1))
        wire.send_frame(sock, wire.REQUEST, legacy)
        wire.recv_frame(sock)
        wire.send_frame(sock, wire.REQUEST, legacy)
        wire.recv_frame(sock)
        deadline = time.monotonic() + 5.0
        while server.completed < executed + 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.completed == executed + 2
        sock.close()
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_health_probe_and_drain_shed(sac):
    policy, params = sac
    rec = _Recorder()
    server = _serving(policy, params, telem=rec)
    try:
        assert HEALTH == 16  # pinned on the shared FLK1 registry
        with ServeClient(server.address, timeout=10.0) as client:
            health = client.health()
            assert health["ready"] and not health["draining"]
            assert health["completed"] == 0
            server.drain()
            assert server.draining
            health = client.health()
            assert health["draining"] and not health["ready"]
            # new work is shed with the draining reason + a retry hint
            with pytest.raises(RequestShed) as exc:
                client.request(_obs(1))
            assert exc.value.reason == "draining"
            assert exc.value.retry_after_ms >= 0.0
        assert "serve.draining" in rec.names()
        assert "serve.drained" in rec.names()
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_frame_error_kills_only_that_client(sac):
    """Garbage magic from client A: A's connection dies with a
    serve.conn_error receipt; client B is served as if nothing happened."""
    policy, params = sac
    rec = _Recorder()
    server = _serving(policy, params, telem=rec)
    try:
        rogue = wire.connect(server.address, timeout=10.0)
        wire.send_json(rogue, wire.HELLO, {"proto": 1})
        wire.recv_json(rogue, wire.WELCOME)
        with ServeClient(server.address, timeout=10.0) as client:
            rogue.sendall(b"XXXX" + b"\x00" * 12)  # bad magic + half header
            deadline = time.monotonic() + 5.0
            while "serve.conn_error" not in rec.names():
                assert time.monotonic() < deadline, rec.names()
                time.sleep(0.01)
            result, meta = client.request(_obs(1))
            assert result["actions"].shape == (1, 1)
        err = dict(rec.events)["serve.conn_error"]
        assert "FrameError" in err["error"]
        rogue.close()
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_oversize_frame_kills_only_that_client(sac):
    policy, params = sac
    rec = _Recorder()
    server = _serving(policy, params, telem=rec)
    try:
        rogue = wire.connect(server.address, timeout=10.0)
        wire.send_json(rogue, wire.HELLO, {"proto": 1})
        wire.recv_json(rogue, wire.WELCOME)
        rogue.sendall(
            wire._HEADER.pack(
                wire.MAGIC, wire.REQUEST, 0, 0, wire.MAX_FRAME_BYTES + 1
            )
        )
        with ServeClient(server.address, timeout=10.0) as client:
            deadline = time.monotonic() + 5.0
            while "serve.conn_error" not in rec.names():
                assert time.monotonic() < deadline, rec.names()
                time.sleep(0.01)
            result, _meta = client.request(_obs(1))
            assert result["actions"].shape == (1, 1)
        rogue.close()
    finally:
        server.close()
