"""MicroBatcher edge cases (ISSUE 15 satellite): empty window flush,
oversized rejection, deadline shed before dispatch, pad-slice
bit-exactness. All driven through `flush_once` with an injected clock —
no threads, no sockets, no model."""

import threading

import numpy as np
import pytest

from sheeprl_tpu.serve.batcher import MicroBatcher, _stack_pad
from sheeprl_tpu.serve.errors import OversizedRequest, RequestShed, ServeError


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class RecordingDispatch:
    """Echo dispatch: result rows mirror the stacked obs; records calls."""

    def __init__(self):
        self.calls = []

    def __call__(self, stacked, pendings, rung):
        self.calls.append((stacked, [p.rows for p in pendings], rung))
        return {"actions": stacked["obs"] * 2.0}, 7


def _batcher(rungs=(1, 2, 4), window_ms=5.0, deadline_ms=100.0, clock=None):
    dispatch = RecordingDispatch()
    b = MicroBatcher(
        dispatch, list(rungs), window_ms=window_ms,
        default_deadline_ms=deadline_ms, clock=clock or FakeClock(),
    )
    return b, dispatch


def _obs(rows, dim=3, fill=1.0):
    return {"obs": np.full((rows, dim), fill, dtype=np.float32)}


def test_empty_window_flush_dispatches_nothing():
    b, dispatch = _batcher()
    assert b.flush_once() == 0
    assert dispatch.calls == []
    assert b.gauges()["Serve/dispatches"] == 0.0


def test_oversized_request_rejected_at_submit():
    b, dispatch = _batcher(rungs=(1, 2, 4))
    with pytest.raises(OversizedRequest) as exc:
        b.submit(_obs(5))
    assert exc.value.rows == 5 and exc.value.max_rung == 4
    # rejected before it ever reached the queue: nothing to dispatch
    assert b.flush_once() == 0 and dispatch.calls == []
    assert b.gauges()["Serve/oversized_total"] == 1.0


def test_mismatched_row_axes_rejected():
    b, _ = _batcher()
    with pytest.raises(ServeError, match="rows axis"):
        b.submit({"a": np.zeros((2, 3)), "b": np.zeros((3, 3))})


def test_deadline_expired_request_shed_before_dispatch():
    clock = FakeClock(0.0)
    b, dispatch = _batcher(deadline_ms=50.0, clock=clock)
    pending = b.submit(_obs(1))
    clock.t = 0.2  # 200ms later: way past the 50ms deadline
    assert b.flush_once() == 1
    assert dispatch.calls == []  # shed BEFORE dispatch — no compute spent
    with pytest.raises(RequestShed) as exc:
        pending.wait(timeout=1.0)
    assert exc.value.retry_after_ms >= 0.0
    assert b.gauges()["Serve/shed_total"] == 1.0


def test_expired_and_live_requests_split_in_one_flush():
    clock = FakeClock(0.0)
    b, dispatch = _batcher(deadline_ms=50.0, clock=clock)
    stale = b.submit(_obs(1))
    clock.t = 0.2
    fresh = b.submit(_obs(1, fill=3.0))  # enqueued at t=0.2, not expired
    assert b.flush_once() == 2
    with pytest.raises(RequestShed):
        stale.wait(timeout=1.0)
    out = fresh.wait(timeout=1.0)
    assert np.array_equal(out["actions"], _obs(1, fill=6.0)["obs"])
    assert len(dispatch.calls) == 1


def test_pad_slice_roundtrip_across_requests():
    """3 requests (1+2+1 rows) -> one rung-4 dispatch, slices return in
    submit order and carry exactly each request's rows."""
    b, dispatch = _batcher()
    p1 = b.submit(_obs(1, fill=1.0))
    p2 = b.submit(_obs(2, fill=2.0))
    p3 = b.submit(_obs(1, fill=3.0))
    assert b.flush_once() == 3
    (stacked, rows, rung), = dispatch.calls
    assert rows == [1, 2, 1] and rung == 4
    assert np.array_equal(p1.wait()["actions"], np.full((1, 3), 2.0, np.float32))
    assert np.array_equal(p2.wait()["actions"], np.full((2, 3), 4.0, np.float32))
    assert np.array_equal(p3.wait()["actions"], np.full((1, 3), 6.0, np.float32))
    assert p2.rung == 4 and p2.version == 7
    assert b.gauges()["Serve/batch_occupancy"] == 1.0  # 4 rows / rung 4


def test_padding_goes_to_next_rung_and_is_sliced_off():
    b, dispatch = _batcher(rungs=(1, 2, 4))
    p = b.submit(_obs(3, fill=1.0))
    assert b.flush_once() == 1
    (stacked, _, rung), = dispatch.calls
    assert rung == 4 and stacked["obs"].shape == (4, 3)
    assert np.array_equal(stacked["obs"][3], np.zeros(3, np.float32))  # pad row
    assert p.wait()["actions"].shape == (3, 3)  # pad sliced off


def test_batched_of_one_bit_exact_vs_direct_jit_call():
    """The parity receipt: a single-row request served through rung 1 IS
    the program a direct batch-1 jit call runs — results are bit-exact.
    And a padded dispatch (rung 4) matches the same jit applied to the
    padded batch, row for row."""
    import jax
    import jax.numpy as jnp

    w = np.random.default_rng(0).standard_normal((3, 2)).astype(np.float32)
    step = jax.jit(lambda x: jnp.tanh(x @ w))

    def dispatch(stacked, pendings, rung):
        return {"actions": np.asarray(step(stacked["obs"]))}, 1

    b = MicroBatcher(dispatch, [1, 4], window_ms=0.0, default_deadline_ms=0.0)
    one = np.random.default_rng(1).standard_normal((1, 3)).astype(np.float32)
    p = b.submit({"obs": one})
    b.flush_once()
    assert p.rung == 1
    assert np.array_equal(p.wait()["actions"], np.asarray(step(one)))

    three = np.random.default_rng(2).standard_normal((3, 3)).astype(np.float32)
    p2 = b.submit({"obs": three})
    b.flush_once()
    assert p2.rung == 4
    padded = np.concatenate([three, np.zeros((1, 3), np.float32)])
    assert np.array_equal(p2.wait()["actions"], np.asarray(step(padded))[:3])


def test_dispatch_failure_completes_requests_with_typed_error():
    def bad(stacked, pendings, rung):
        raise RuntimeError("device fell over")

    b = MicroBatcher(bad, [2], window_ms=0.0, default_deadline_ms=0.0)
    p = b.submit(_obs(1))
    b.flush_once()
    with pytest.raises(ServeError, match="device fell over"):
        p.wait(timeout=1.0)
    assert b.gauges()["Serve/failed_total"] == 1.0


def test_greedy_fill_keeps_overflow_for_next_flush():
    b, dispatch = _batcher(rungs=(1, 2, 4))
    for fill in (1.0, 2.0):
        b.submit(_obs(3, fill=fill))  # 3+3 rows > max rung 4
    assert b.flush_once() == 1  # first request only
    assert b.queue_depth() == 3
    assert b.flush_once() == 1
    assert b.queue_depth() == 0
    assert [c[2] for c in dispatch.calls] == [4, 4]


@pytest.mark.timeout(30)
def test_close_drains_queue_zero_drop():
    """Shutdown answers every queued request — the zero-drop guarantee."""
    b, _ = _batcher(deadline_ms=0.0)  # no deadline: nothing may be shed
    pendings = [b.submit(_obs(1, fill=float(i))) for i in range(9)]
    b.start()
    b.close()
    for p in pendings:
        p.wait(timeout=5.0)  # raises if dropped
    assert b.gauges()["Serve/served_total"] == 9.0


@pytest.mark.timeout(30)
def test_threaded_loop_serves_concurrent_submitters():
    b, _ = _batcher(window_ms=1.0, deadline_ms=0.0)
    b.start()
    results = []
    def work(i):
        p = b.submit(_obs(1, fill=float(i)))
        results.append((i, p.wait(timeout=10.0)["actions"][0, 0]))
    threads = [
        threading.Thread(
            target=work, args=(i,), name=f"test-submit-{i}", daemon=True
        )
        for i in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b.close()
    assert sorted(results) == [(i, 2.0 * i) for i in range(16)]


def test_stack_pad_preserves_dtype_and_values():
    trees = [
        {"x": np.arange(6, dtype=np.int32).reshape(2, 3)},
        {"x": np.arange(3, dtype=np.int32).reshape(1, 3) + 100},
    ]
    out = _stack_pad(trees, rows=3, rung=4)
    assert out["x"].dtype == np.int32 and out["x"].shape == (4, 3)
    assert np.array_equal(out["x"][:2], trees[0]["x"])
    assert np.array_equal(out["x"][2:3], trees[1]["x"])
    assert np.array_equal(out["x"][3], np.zeros(3, np.int32))
