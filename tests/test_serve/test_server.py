"""ServeServer integration over real sockets with a real (tiny) SAC
policy: request/response parity, concurrent load, hot reload with zero
dropped in-flight requests, deadline shedding, typed rejections."""

import threading

import jax
import numpy as np
import pytest

from sheeprl_tpu.serve import (
    MicroBatcher,
    OversizedRequest,
    ParamsStore,
    RequestShed,
    ServeClient,
    ServeServer,
)
from sheeprl_tpu.serve.errors import ServeError
from sheeprl_tpu.serve.policies import SACServePolicy

OBS_DIM, ACT_DIM = 3, 1


def _make_actor(seed):
    from sheeprl_tpu.algos.sac.agent import SACAgent

    return SACAgent.init(
        jax.random.PRNGKey(seed), OBS_DIM, ACT_DIM,
        num_critics=2, actor_hidden_size=16, critic_hidden_size=16,
        action_low=np.array([-2.0]), action_high=np.array([2.0]),
        alpha=1.0, tau=0.005, precision="float32",
    ).actor


@pytest.fixture(scope="module")
def sac_policy():
    policy = SACServePolicy(OBS_DIM, ACT_DIM)
    return policy, _make_actor(0), _make_actor(1)


def _serving(policy, params, loaders=None, rungs=(1, 2, 4), window_ms=1.0,
             deadline_ms=2000.0, bind="unix:auto", telem=None):
    loaders = loaders or {}

    def loader(path):
        return loaders[path]  # KeyError -> failed reload, version kept

    store = ParamsStore(loader, params, source=None)

    def dispatch(stacked, pendings, rung):
        version, live = store.current()
        return policy.run(policy.step, live, version, stacked, pendings, rung), version

    batcher = MicroBatcher(
        dispatch, list(rungs), window_ms=window_ms, default_deadline_ms=deadline_ms
    )
    server = ServeServer(policy, store, batcher, bind=bind, telem=telem)
    server.start()
    return server, store


def _obs(rows, seed=0):
    return {
        "obs": np.random.default_rng(seed).standard_normal(
            (rows, OBS_DIM)
        ).astype(np.float32)
    }


@pytest.mark.timeout(120)
def test_request_response_parity_bit_exact(sac_policy):
    policy, params, _ = sac_policy
    server, _store = _serving(policy, params)
    try:
        with ServeClient(server.address) as client:
            assert client.info["algo"] == "sac"
            assert client.info["rungs"] == [1, 2, 4]
            # batched-of-1 through rung 1: the same program as a direct call
            one = _obs(1)
            res, meta = client.request(one)
            assert meta["rung"] == 1 and meta["rows"] == 1
            direct = np.asarray(policy.step(params, one["obs"]))
            assert np.array_equal(res["actions"], direct)
            # 3 rows pad to rung 4; the slice matches the padded direct call
            three = _obs(3, seed=3)
            res3, meta3 = client.request(three)
            assert meta3["rung"] == 4 and res3["actions"].shape == (3, ACT_DIM)
            padded = np.concatenate(
                [three["obs"], np.zeros((1, OBS_DIM), np.float32)]
            )
            assert np.array_equal(
                res3["actions"], np.asarray(policy.step(params, padded))[:3]
            )
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_hot_reload_zero_dropped_requests(sac_policy):
    """Drive concurrent clients, flip the params mid-stream, and require
    every single request to come back served (no drops, no errors) with a
    version from {1, 2} and actions bit-exact for that version."""
    policy, params_v1, params_v2 = sac_policy
    server, store = _serving(
        policy, params_v1, loaders={"v2": params_v2}, deadline_ms=0.0
    )
    n_threads, per_thread = 8, 12
    results = []
    errors = []
    lock = threading.Lock()

    def worker(tid):
        try:
            with ServeClient(server.address) as client:
                for i in range(per_thread):
                    obs = _obs(1, seed=tid * 1000 + i)
                    res, meta = client.request(obs)
                    with lock:
                        results.append((obs["obs"], res["actions"], meta["version"]))
        except Exception as err:  # any failure is a dropped request
            with lock:
                errors.append(err)

    try:
        threads = [
            threading.Thread(
                target=worker, args=(t,), name=f"test-client-{t}", daemon=True
            )
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        # hot reload in the middle of the stream
        with ServeClient(server.address) as admin:
            reply = admin.reload("v2")
        assert reply["ok"] and reply["version"] == 2
        for t in threads:
            t.join(timeout=60.0)
        assert not errors, errors
        assert len(results) == n_threads * per_thread  # zero dropped
        versions = {v for _, _, v in results}
        assert 2 in versions  # some requests really ran on the new params
        by_version = {1: params_v1, 2: params_v2}
        for obs, actions, version in results:
            # concurrent submitters co-batch at unpredictable rungs, and
            # different rungs are different XLA programs — so this check
            # is allclose; the bit-exact receipt (same rung) lives in
            # test_request_response_parity_bit_exact
            np.testing.assert_allclose(
                actions, np.asarray(policy.step(by_version[version], obs)),
                rtol=0.0, atol=1e-6,
            )
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_failed_reload_keeps_serving_old_version(sac_policy):
    policy, params, _ = sac_policy
    server, store = _serving(policy, params)
    try:
        with ServeClient(server.address) as client:
            reply = client.reload("no-such-checkpoint")
            assert not reply["ok"] and reply["version"] == 1
            res, meta = client.request(_obs(1))
            assert meta["version"] == 1  # still serving v1
        assert store.reload_failures == 1
        assert server.gauges()["Serve/reload_failures"] == 1.0
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_deadline_shed_returns_retry_after(sac_policy):
    policy, params, _ = sac_policy
    # window far beyond the deadline: the request expires while queued
    server, _store = _serving(
        policy, params, window_ms=500.0, deadline_ms=10.0, rungs=(4,)
    )
    try:
        with ServeClient(server.address) as client:
            with pytest.raises(RequestShed) as exc:
                client.request(_obs(1))
            assert exc.value.retry_after_ms >= 0.0
            assert exc.value.reason == "deadline"
            # shed is not a connection failure: the stream keeps working
            res, meta = client.request(_obs(1), deadline_ms=10_000.0)
            assert res["actions"].shape == (1, ACT_DIM)
        assert server.gauges()["Serve/shed_total"] >= 1.0
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_oversized_request_typed_error(sac_policy):
    policy, params, _ = sac_policy
    server, _store = _serving(policy, params, rungs=(1, 2))
    try:
        with ServeClient(server.address) as client:
            with pytest.raises(OversizedRequest):
                client.request(_obs(3))
            res, _ = client.request(_obs(2))  # connection survives
            assert res["actions"].shape == (2, ACT_DIM)
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_tcp_transport(sac_policy):
    policy, params, _ = sac_policy
    server, _store = _serving(policy, params, bind="tcp:127.0.0.1:0")
    try:
        assert server.address.startswith("tcp:127.0.0.1:")
        with ServeClient(server.address) as client:
            res, meta = client.request(_obs(1))
            assert res["actions"].shape == (1, ACT_DIM)
    finally:
        server.close()


class _SpanRecorder:
    """Telemetry stand-in: thread-safe event capture + a live tracer."""

    enabled = True

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def event(self, name, /, **data):
        with self._lock:
            self.events.append((name, data))

    @property
    def tracer(self):
        from sheeprl_tpu.telemetry.trace import Tracer

        return Tracer(self)

    def of(self, name):
        with self._lock:
            return [d for n, d in self.events if n == name]


@pytest.mark.timeout(120)
def test_request_span_decomposition_and_echo(sac_policy):
    """sheepscope (ISSUE 17): every served request gets a span parented on
    the client's span id from the REQUEST meta, its own id echoed in the
    RESPONSE meta, and the full queue/pad/dispatch/slice/send breakdown."""
    policy, params, _ = sac_policy
    rec = _SpanRecorder()
    server, _store = _serving(policy, params, telem=rec)
    try:
        with ServeClient(server.address) as client:
            res, meta = client.request(_obs(1))
        assert "span" in meta, meta
        spans = rec.of("span")
        assert len(spans) == 1
        span = spans[0]
        assert span["name"] == "request" and span["outcome"] == "served"
        assert span["span"] == meta["span"] and span["id"] == meta["id"]
        # parented on the CLIENT's span id (a compact 8-hex id the client
        # stamped into the REQUEST meta)
        assert isinstance(span["parent"], str) and len(span["parent"]) == 8
        for phase in ("queue_ms", "pad_ms", "dispatch_ms", "slice_ms", "send_ms"):
            assert span[phase] >= 0.0, (phase, span)
        assert span["version"] == 1 and span["rows"] == 1
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_trace_off_leaves_wire_meta_clean(sac_policy, monkeypatch):
    """Kill switch: no span keys ride the wire in either direction — the
    exact frames an old peer would see."""
    monkeypatch.setenv("SHEEPRL_TPU_TRACE", "0")
    policy, params, _ = sac_policy
    rec = _SpanRecorder()
    server, _store = _serving(policy, params, telem=rec)
    try:
        with ServeClient(server.address) as client:
            _res, meta = client.request(_obs(1))
        assert "span" not in meta, meta
        assert rec.of("span") == []
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_conn_error_attributed_to_last_request(sac_policy):
    """A connection that dies mid-stream is span-tagged: the conn_error
    event names the request id + span it interrupted, so sheeptrace can
    tie the drop back into the chain."""
    import time as _time

    policy, params, _ = sac_policy
    rec = _SpanRecorder()
    server, _store = _serving(policy, params, telem=rec)
    try:
        client = ServeClient(server.address)
        _res, meta = client.request(_obs(1))
        # corrupt bytes on the live connection: the handler's FrameError
        client._sock.sendall(b"XXXX" + bytes(12))
        client._sock.close()
        deadline = _time.monotonic() + 20.0
        while not rec.of("serve.conn_error") and _time.monotonic() < deadline:
            _time.sleep(0.05)
        errors = rec.of("serve.conn_error")
        assert errors, rec.events
        assert errors[0]["request_id"] == meta["id"]
        assert errors[0]["span"] == meta["span"]
    finally:
        server.close()


@pytest.mark.timeout(120)
def test_gauges_expose_serving_telemetry(sac_policy):
    policy, params, _ = sac_policy
    server, _store = _serving(policy, params)
    try:
        with ServeClient(server.address) as client:
            for i in range(5):
                client.request(_obs(1, seed=i))
        g = server.gauges()
        assert g["Serve/served_total"] == 5.0
        assert g["Serve/completed_total"] == 5.0
        assert g["Serve/latency_p50_ms"] > 0.0
        assert g["Serve/latency_p99_ms"] >= g["Serve/latency_p50_ms"]
        assert g["Serve/qps"] > 0.0
        assert g["Serve/params_version"] == 1.0
        assert 0.0 < g["Serve/batch_occupancy"] <= 1.0
    finally:
        server.close()
