"""sheepquant (ISSUE 20): calibration determinism, quality-receipt
acceptance, quantized pad-slice parity, hot-reload scale re-derivation,
and fused-kernel parity in interpret mode."""

import json
import os
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sheeprl_tpu.compile.decisions as dec
import sheeprl_tpu.ops.quant as q
from sheeprl_tpu.algos.sac.agent import SACActor
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.ops import pallas_kernels as pk
from sheeprl_tpu.serve.quant import QuantState, action_divergence

OBS_DIM, ACT_DIM, HIDDEN = 6, 3, 8


def _tiny_actor(seed=0):
    return SACActor.init(
        jax.random.PRNGKey(seed), OBS_DIM, ACT_DIM, hidden_size=HIDDEN
    )


def _actor_call(m, obs):
    return m.get_greedy_actions(jnp.asarray(obs, jnp.float32))


def _quantized(actor, seed=0):
    rng = np.random.default_rng(seed)
    batches = [rng.standard_normal((16, OBS_DIM)).astype(np.float32)
               for _ in range(3)]
    scales = q.calibrate(actor, _actor_call, batches)
    return q.quantize_linears(actor, scales), scales


def _seeded_buffer(seed=11):
    buf = ReplayBuffer(32, n_envs=1, storage="host", obs_keys=("obs",), seed=seed)
    data_rng = np.random.default_rng(99)  # buffer CONTENT is fixed
    buf.add({"obs": data_rng.standard_normal((32, 1, OBS_DIM)).astype(np.float32)})
    return buf


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------


def test_calibration_deterministic_from_seeded_buffer():
    """Two freshly seeded buffers with the same contents must yield
    bit-identical scales through calibrate_from_buffer (the persisted
    quant_scales.npz contract: a restart re-quantizes identically)."""
    actor = _tiny_actor()
    s1 = q.calibrate_from_buffer(
        actor, _actor_call, _seeded_buffer(), obs_key="obs",
        n_batches=2, batch_size=8,
    )
    s2 = q.calibrate_from_buffer(
        actor, _actor_call, _seeded_buffer(), obs_key="obs",
        n_batches=2, batch_size=8,
    )
    assert sorted(s1) == sorted(s2)
    # the greedy forward touches every Linear in the actor
    assert sorted(s1) == sorted(q.linear_paths(actor))
    for k in s1:
        np.testing.assert_array_equal(s1[k], s2[k])
    # a differently seeded buffer draws different batches -> different scales
    s3 = q.calibrate_from_buffer(
        actor, _actor_call, _seeded_buffer(seed=12), obs_key="obs",
        n_batches=2, batch_size=8,
    )
    assert any(not np.array_equal(s1[k], s3[k]) for k in s1)


def test_quantized_actor_close_to_f32():
    actor = _tiny_actor()
    qactor, scales = _quantized(actor)
    assert all(v.dtype == np.float32 for v in scales.values())
    obs = np.random.default_rng(1).standard_normal((4, OBS_DIM)).astype(np.float32)
    a32 = np.asarray(_actor_call(actor, obs))
    a8 = np.asarray(_actor_call(qactor, obs))
    assert action_divergence(a32, a8) < 0.05  # int8 stays near full width
    assert action_divergence(a32, a8) > 0.0  # but is NOT bit-exact


# ---------------------------------------------------------------------------
# quality-receipt acceptance (compile/decisions.py extension)
# ---------------------------------------------------------------------------


def test_decide_quality_receipt_tight_bound_disqualifies(tmp_path):
    store = str(tmp_path / "d.json")
    example = (np.ones((4, 3), np.float32),)

    def build(label):
        if label == "approx":
            return lambda x: x * 2.0 + 0.01
        return lambda x: x * 2.0

    d = dec.decide(
        "toy", "mul@tight", ["base", "approx"], build, example,
        objective="seconds", quality_metric=action_divergence,
        quality_bound=1e-4, store_path=store,
    )
    # the approx candidate diverges by 0.01 > 1e-4: DISQUALIFIED, the
    # baseline wins regardless of timing
    assert d.winner == "base"
    rep = d.candidate("approx")
    assert rep["within_bound"] is False
    assert rep["divergence"] == pytest.approx(0.01, rel=1e-3)
    # the bound is committed next to the record (the sheepopt receipt)
    with open(store) as fh:
        blob = json.load(fh)
    (rec,) = [r for r in blob.values() if r.get("name") == "mul@tight"]
    assert rec["quality_bound"] == pytest.approx(1e-4)


def test_decide_quality_receipt_loose_bound_accepts(tmp_path):
    store = str(tmp_path / "d.json")
    example = (np.ones((4, 3), np.float32),)

    def build(label):
        if label == "approx":
            return lambda x: x * 2.0 + 0.01
        return lambda x: x * 2.0

    d = dec.decide(
        "toy", "mul@loose", ["base", "approx"], build, example,
        objective="seconds", quality_metric=action_divergence,
        quality_bound=0.1, store_path=store,
    )
    rep = d.candidate("approx")
    assert rep["within_bound"] is True  # eligible; winner is whoever timed faster
    assert d.candidate("base")["within_bound"] is True
    assert d.quality_bound == pytest.approx(0.1)


def test_decide_quality_args_come_together(tmp_path):
    with pytest.raises(ValueError, match="come together"):
        dec.decide(
            "toy", "bad", ["a"], lambda label: (lambda x: x),
            (np.ones((2,), np.float32),),
            quality_metric=action_divergence,
            store_path=str(tmp_path / "d.json"),
        )


def _quant_state(tmp_path, actor, bound, ckpt=None, seed=3):
    policy = types.SimpleNamespace(
        algo="sac",
        obs_dim=OBS_DIM,
        step=jax.jit(lambda p, obs: p.get_greedy_actions(obs)),
    )
    args = types.SimpleNamespace(quant_bound=bound, seed=seed, ckpt=ckpt)
    return QuantState(policy, args, str(tmp_path))


def test_accept_rungs_tight_bound_keeps_f32(tmp_path):
    """An impossibly tight bound DISQUALIFIES every int8 rung: the ladder
    keeps serving f32 and the receipt says why."""
    actor = _tiny_actor()
    qs = _quant_state(tmp_path, actor, bound=1e-12)
    won = qs.accept_rungs(1, actor, [1, 2])
    assert won == set() and qs.int8_rungs == set()
    assert qs.available
    for rung in (1, 2):
        d = qs.decisions[rung]
        assert d.winner == "f32"
        rep = d.candidate("int8")
        assert rep["within_bound"] is False and rep["divergence"] > 1e-12
    assert os.path.exists(qs.store_path)


def test_accept_rungs_loose_bound_int8_eligible(tmp_path):
    actor = _tiny_actor()
    qs = _quant_state(tmp_path, actor, bound=10.0)
    qs.accept_rungs(1, actor, [1])
    rep = qs.decisions[1].candidate("int8")
    assert rep["within_bound"] is True
    assert 0.0 < rep["divergence"] <= 10.0
    g = qs.gauges()
    assert g["Serve/quant_enabled"] == 1.0
    assert g["Serve/quant_bound"] == 10.0


# ---------------------------------------------------------------------------
# pad-slice parity of the quantized rung
# ---------------------------------------------------------------------------


def test_quantized_pad_slice_parity():
    """Zero-padding rows up to a rung and slicing back must be bit-exact
    against the direct call — int8 per-row math never mixes rows (the
    batcher's padding contract extends to quantized rungs)."""
    qactor, _ = _quantized(_tiny_actor())
    step = jax.jit(lambda p, obs: p.get_greedy_actions(obs))
    obs = np.random.default_rng(5).standard_normal((3, OBS_DIM)).astype(np.float32)
    padded = np.concatenate([obs, np.zeros((1, OBS_DIM), np.float32)], axis=0)
    direct = np.asarray(step(qactor, jnp.asarray(obs)))
    sliced = np.asarray(step(qactor, jnp.asarray(padded)))[:3]
    np.testing.assert_array_equal(direct, sliced)


# ---------------------------------------------------------------------------
# hot reload re-derives scales
# ---------------------------------------------------------------------------


def test_hot_reload_rederives_scales(tmp_path):
    actor_v1 = _tiny_actor(seed=0)
    actor_v2 = _tiny_actor(seed=1)
    qs = _quant_state(tmp_path, actor_v1, bound=0.05)
    q1 = qs.params_for(1, actor_v1)
    assert qs.params_for(1, actor_v1) is q1  # cached per version
    assert qs.rederives == 0
    q2 = qs.params_for(2, actor_v2)  # the hot-reload path
    assert qs.rederives == 1 and q2 is not q1
    # the new weights were re-calibrated, not served under stale scales
    assert not np.array_equal(
        np.asarray(q2.fc_mean.w_q), np.asarray(q1.fc_mean.w_q)
    )
    assert qs.gauges()["Serve/quant_rederives"] == 1.0


def test_reload_hook_rederives_off_the_dispatch_path(tmp_path):
    """The ParamsStore on_reload hook rebuilds the quantized twin in the
    reload thread, so the first int8 dispatch after a swap finds the
    cache already at the new version."""
    from sheeprl_tpu.serve.params import ParamsStore

    actor_v1 = _tiny_actor(seed=0)
    actor_v2 = _tiny_actor(seed=1)
    qs = _quant_state(tmp_path, actor_v1, bound=0.05)
    qs.params_for(1, actor_v1)  # startup derivation

    store = ParamsStore(lambda path: actor_v2, actor_v1, source="ckpt_1")
    store.on_reload = qs.params_for
    reply = store.reload()
    assert reply["ok"] and reply["version"] == 2
    assert qs.rederives == 1
    # a dispatch at the new version is a pure cache hit — no second derive
    assert qs.params_for(*store.current()) is qs._cache[1]
    assert qs.rederives == 1


def test_reload_hook_failure_keeps_the_swap(tmp_path):
    """A broken derived-state hook must not fail the reload itself."""
    from sheeprl_tpu.serve.params import ParamsStore

    events = []

    class _Telem:
        def event(self, name, **data):
            events.append((name, data))

    store = ParamsStore(lambda path: {"w": 2}, {"w": 1}, source="c", telem=_Telem())

    def boom(version, params):
        raise RuntimeError("hook exploded")

    store.on_reload = boom
    reply = store.reload()
    assert reply["ok"] and reply["version"] == 2
    assert store.current() == (2, {"w": 2})
    hook_errs = [e for e in events if e[0] == "serve.reload_hook_error"]
    assert hook_errs and "hook exploded" in hook_errs[0][1]["error"]


def test_scales_persist_next_to_checkpoint(tmp_path):
    ckpt = str(tmp_path / "ckpt_100")
    os.makedirs(ckpt)
    actor = _tiny_actor()
    qs = _quant_state(tmp_path, actor, bound=0.05, ckpt=ckpt)
    qs.params_for(1, actor)
    path = q.scales_path(ckpt)
    assert os.path.exists(path)
    persisted = q.load_scales(path)
    assert sorted(persisted) == sorted(q.linear_paths(actor))
    # a fresh serve process re-quantizes from the persisted scales:
    # identical quantized weights, no re-calibration drift
    qs2 = _quant_state(tmp_path, actor, bound=0.05, ckpt=ckpt, seed=77)
    qb = qs2.params_for(1, actor)
    qa = qs.params_for(1, actor)
    np.testing.assert_array_equal(
        np.asarray(qa.fc_mean.w_q), np.asarray(qb.fc_mean.w_q)
    )


# ---------------------------------------------------------------------------
# fused Pallas trunk
# ---------------------------------------------------------------------------


@pytest.fixture
def pallas_interpret():
    pk.set_pallas(True, interpret=True)
    yield
    pk.set_pallas(None, interpret=False)


def test_fused_int8_trunk_matches_reference(pallas_interpret):
    rng = np.random.default_rng(7)

    def lin(n_in, n_out):
        w = rng.standard_normal((n_in, n_out)).astype(np.float32) * 0.3
        s_in = jnp.asarray(np.abs(rng.standard_normal(n_in)) + 0.05, jnp.float32)
        w_eff = jnp.asarray(w) * s_in[:, None]
        ws = q.absmax_scale(w_eff, axis=0)
        return (
            s_in, q.quantize(w_eff, ws), ws,
            jnp.asarray(rng.standard_normal(n_out), jnp.float32),
        )

    l0, l1, m = lin(OBS_DIM, HIDDEN), lin(HIDDEN, HIDDEN), lin(HIDDEN, ACT_DIM)
    x = jnp.asarray(rng.standard_normal((5, OBS_DIM)), jnp.float32)
    got = pk.fused_int8_trunk(x, *l0, *l1, *m)
    want = pk.int8_trunk_reference(x, *l0, *l1, *m)
    # the int8 chain is identical math; the dequant multiply-add may fuse
    # differently (FMA) between the interpreter and XLA — f32 ulp noise
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    assert pk.fused_int8_trunk_supported(*l0, *l1, *m)


def test_fused_sac_step_matches_generic_quant_path(pallas_interpret):
    """The fused step and the generic QuantLinear path share int8_linear:
    same quantized actor, same obs, same actions to f32 ulp noise."""
    from sheeprl_tpu.serve.quant import _make_fused_sac_step, _sac_fused_ready

    qactor, _ = _quantized(_tiny_actor())
    policy = types.SimpleNamespace(algo="sac")
    assert _sac_fused_ready(policy, qactor)
    fused = _make_fused_sac_step()
    obs = jnp.asarray(
        np.random.default_rng(9).standard_normal((4, OBS_DIM)), jnp.float32
    )
    got = np.asarray(fused(qactor, obs))
    want = np.asarray(qactor.get_greedy_actions(obs))
    np.testing.assert_allclose(got, want, atol=1e-6)
