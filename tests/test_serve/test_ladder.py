"""Ladder parsing + ledger-first rung sizing (ISSUE 15)."""

import numpy as np
import pytest

import sheeprl_tpu.serve.ladder as lm


def test_parse_rungs_auto_powers_of_two():
    assert lm.parse_rungs("auto", 8) == [1, 2, 4, 8]
    assert lm.parse_rungs("auto", 6) == [1, 2, 4, 6]  # max_batch always kept
    assert lm.parse_rungs("auto", 1) == [1]


def test_parse_rungs_explicit_list():
    assert lm.parse_rungs("4,1,2", 8) == [1, 2, 4]
    with pytest.raises(ValueError, match="exceeds"):
        lm.parse_rungs("16", 8)
    with pytest.raises(ValueError, match=">= 1"):
        lm.parse_rungs("0,2", 8)
    with pytest.raises(ValueError, match="unparseable"):
        lm.parse_rungs("a,b", 8)


def test_ledger_spec_naming():
    assert lm.ledger_spec("sac") == "serve"
    assert lm.ledger_spec("dreamer_v3") == "dreamer_v3@serve"


def test_serve_mem_budget_env_override(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_SERVE_MEM_MB", "64")
    assert lm.serve_mem_budget_bytes() == 64 * 2**20


def _fake_ledger(peaks, jits=None):
    """ledger_entry stand-in: peak scales with the rung suffix. The
    `jits` section feeds the dtype-floor guard (ISSUE 20) — absent by
    default, which keeps the conservative >=1 ratio floor."""

    def entry(key, section="memory"):
        if section == "jits":
            return (jits or {}).get(key)
        assert section == "memory"
        rung = int(key.rsplit("_b", 1)[1])
        if rung not in peaks:
            return None
        return {"peak_bytes": peaks[rung], "argument_bytes": 100 * rung}

    return entry


def _example_of(rung):
    # argument bytes == 100 * rung -> ledger ratio 1.0 exactly
    return (np.zeros((rung, 25), dtype=np.float32),)


def test_size_ladder_ledger_first_accepts_within_budget(monkeypatch):
    monkeypatch.setattr(lm, "ledger_entry", _fake_ledger({1: 50, 2: 90, 4: 200}))
    dec = lm.size_ladder(None, _example_of, [1, 2, 4], "serve", mem_budget_bytes=100)
    assert [(d.rung, d.accepted, d.source) for d in dec] == [
        (1, True, "ledger"), (2, True, "ledger"), (4, False, "ledger"),
    ]
    assert dec[2].peak_bytes == 200


def test_size_ladder_smallest_rung_kept_even_over_budget(monkeypatch):
    monkeypatch.setattr(lm, "ledger_entry", _fake_ledger({2: 500, 4: 900}))
    dec = lm.size_ladder(None, _example_of, [2, 4], "serve", mem_budget_bytes=100)
    assert dec[0].accepted and dec[0].source == "floor"
    assert not dec[1].accepted


def test_size_ladder_scales_ledger_by_argument_ratio(monkeypatch):
    monkeypatch.setattr(lm, "ledger_entry", _fake_ledger({1: 100}))
    # live args are 4x the ledger's argument bytes -> predicted peak 4x
    dec = lm.size_ladder(
        None, lambda r: (np.zeros((r, 100), np.float32),), [1], "serve",
        mem_budget_bytes=10**9,
    )
    assert dec[0].peak_bytes == 400
    assert "x4.00" in dec[0].reason


def test_size_ladder_ratio_floor_only_when_dtypes_match(monkeypatch):
    """The ISSUE 20 dtype-floor fix: a quantized (int8) live example
    against an f32 ledger entry legitimately predicts BELOW the entry —
    the >=1 ratio floor must only apply when the dtypes agree."""
    jits = {"serve/policy_b1": {"in_avals": ["float32[1,100]"]}}
    monkeypatch.setattr(lm, "ledger_entry", _fake_ledger({1: 400}, jits=jits))
    # same dtype, half the argument bytes (ledger has 100): a narrower
    # f32 model -> floored back to the ledger entry
    dec = lm.size_ladder(
        None, lambda r: (np.zeros((r, 12), np.float32),), [1], "serve",
        mem_budget_bytes=10**9,
    )
    assert dec[0].peak_bytes == 400 and "x1.00" in dec[0].reason
    # int8 live example, quarter the bytes: the prediction must NOT be
    # floored back up to the f32 entry
    dec = lm.size_ladder(
        None, lambda r: (np.zeros((r, 25), np.int8),), [1], "serve",
        mem_budget_bytes=10**9,
    )
    assert dec[0].peak_bytes == 100 and "x0.25" in dec[0].reason


def test_derive_rung_occupancy_candidates():
    """Occupancy-driven re-tier (ISSUE 20): degenerate, existing,
    over-max, and too-close candidates are all rejected."""
    assert lm.derive_rung(3.0, [1, 2, 8], 8) == 3
    assert lm.derive_rung(5.2, [1, 2, 8], 8) == 5
    assert lm.derive_rung(0.0, [1, 2, 8], 8) is None  # degenerate
    assert lm.derive_rung(2.2, [1, 2, 8], 8) is None  # already a rung
    assert lm.derive_rung(9.0, [1, 2, 8], 8) is None  # over --max_batch
    assert lm.derive_rung(7.4, [1, 2, 8], 8) is None  # within 1 of rung 8


def test_size_ladder_probe_fallback_uses_real_compile(monkeypatch, tmp_path):
    """No ledger entry -> one trial AOT compile, memoized in the decision
    cache; a second sizing run must hit the cache."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setattr(lm, "ledger_entry", lambda *a, **k: None)
    fn = jax.jit(lambda x: jnp.tanh(x * 2.0))
    store = str(tmp_path / "decisions.json")
    dec = lm.size_ladder(
        fn, lambda r: (np.zeros((r, 8), np.float32),), [2], "nosuchspec",
        mem_budget_bytes=10**9, store_path=store,
    )
    assert dec[0].accepted and dec[0].source == "probe"
    assert "probe cache" not in dec[0].reason
    dec2 = lm.size_ladder(
        fn, lambda r: (np.zeros((r, 8), np.float32),), [2], "nosuchspec",
        mem_budget_bytes=10**9, store_path=store,
    )
    assert "probe cache" in dec2[0].reason


def test_size_ladder_committed_ledger_covers_serve_spec():
    """The committed analysis/budget entries for the capture-spec ladder
    must satisfy the ledger-first path: no probes, no compiles."""
    entry = lm.ledger_entry("serve/policy_b1", "memory")
    assert entry is not None, "analysis/budget/serve.json missing the serving ladder"
    assert entry.get("peak_bytes") and entry.get("argument_bytes")
    entry4 = lm.ledger_entry("dreamer_v3@serve/policy_b4", "memory")
    assert entry4 is not None
