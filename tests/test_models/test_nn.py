"""Shape/gradient contracts for the nn layer (mirrors the reference's
tests/test_models/{test_mlp,test_cnn}.py strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu import nn


KEY = jax.random.PRNGKey(0)


def test_linear_shapes():
    lin = nn.Linear.init(KEY, 5, 3)
    x = jnp.ones((7, 5))
    assert lin(x).shape == (7, 3)
    lin_nb = nn.Linear.init(KEY, 5, 3, use_bias=False)
    assert lin_nb.bias is None
    assert lin_nb(x).shape == (7, 3)


def test_mlp_shapes_and_head():
    mlp = nn.MLP.init(KEY, 4, [8, 8], 2, act="relu", layer_norm=True)
    x = jnp.ones((3, 4))
    assert mlp(x).shape == (3, 2)
    assert mlp.output_dim == 2
    no_head = nn.MLP.init(KEY, 4, [8, 16])
    assert no_head(x).shape == (3, 16)
    assert no_head.output_dim == 16


def test_mlp_is_pytree_and_jits():
    mlp = nn.MLP.init(KEY, 4, [8], 2)
    leaves = jax.tree_util.tree_leaves(mlp)
    assert all(isinstance(leaf, jax.Array) for leaf in leaves)

    @jax.jit
    def f(m, x):
        return m(x).sum()

    g = jax.grad(f)(mlp, jnp.ones((3, 4)))
    assert isinstance(g, nn.MLP)
    assert g.layers[0].weight.shape == mlp.layers[0].weight.shape


def test_mlp_dropout_deterministic_vs_train():
    mlp = nn.MLP.init(KEY, 4, [32, 32], dropout_rate=0.5)
    x = jnp.ones((2, 4))
    eval_out = mlp(x)
    train_out = mlp(x, key=jax.random.PRNGKey(1), training=True)
    assert not np.allclose(eval_out, train_out)


def test_cnn_nhwc():
    cnn = nn.CNN.init(KEY, 3, [16, 32], [3, 3], [2, 2], layer_norm=True)
    x = jnp.ones((2, 16, 16, 3))
    y = cnn(x)
    assert y.shape == (2, 4, 4, 32)
    # leading batch dims folded
    y2 = cnn(jnp.ones((5, 2, 16, 16, 3)))
    assert y2.shape == (5, 2, 4, 4, 32)


def test_decnn_upsamples():
    de = nn.DeCNN.init(KEY, 8, [16, 3], [4, 4], [2, 2])
    x = jnp.ones((2, 4, 4, 8))
    y = de(x)
    assert y.shape == (2, 16, 16, 3)


def test_nature_cnn_output_dim():
    enc = nn.NatureCNN.init(KEY, 4, 512, screen_size=64)
    x = jnp.ones((2, 64, 64, 4))
    assert enc(x).shape == (2, 512)
    assert enc.output_dim == 512


def test_gru_cells():
    for cls in (nn.GRUCell, nn.LayerNormGRUCell):
        cell = cls.init(KEY, 6, 12)
        x = jnp.ones((3, 6))
        h = jnp.zeros((3, 12))
        h2 = cell(x, h)
        assert h2.shape == (3, 12)
        assert not np.allclose(h2, h)


def test_lstm_cell_and_scan():
    cell = nn.LSTMCell.init(KEY, 6, 12)
    xs = jnp.ones((5, 3, 6))
    h0 = cell.initial_state((3,))
    (hT, cT), ys = nn.scan_cell(cell, xs, h0)
    assert ys.shape == (5, 3, 12)
    assert hT.shape == (3, 12) and cT.shape == (3, 12)


def test_scan_cell_reset_mask():
    cell = nn.GRUCell.init(KEY, 4, 8)
    xs = jax.random.normal(KEY, (6, 2, 4))
    h0 = jnp.ones((2, 8))
    # resetting at t=0 must equal starting from zeros
    mask = jnp.zeros((6, 2)).at[0].set(1.0)
    _, ys_reset = nn.scan_cell(cell, xs, h0, reset_mask=mask)
    _, ys_zero = nn.scan_cell(cell, xs, jnp.zeros((2, 8)))
    np.testing.assert_allclose(ys_reset, ys_zero, rtol=1e-5)


def test_multi_encoder_decoder():
    k1, k2, k3 = jax.random.split(KEY, 3)
    cnn_enc = nn.NatureCNN.init(k1, 6, 32, screen_size=64)
    mlp_enc = nn.MLP.init(k2, 5, [16])
    enc = nn.MultiEncoder(
        cnn_encoder=cnn_enc,
        mlp_encoder=mlp_enc,
        cnn_keys=("rgb", "depth"),
        mlp_keys=("state",),
    )
    obs = {
        "rgb": jnp.ones((2, 64, 64, 3)),
        "depth": jnp.ones((2, 64, 64, 3)),
        "state": jnp.ones((2, 5)),
    }
    feat = enc(obs)
    assert feat.shape == (2, 32 + 16)

    mlp_dec = nn.MLP.init(k3, 48, [16])
    heads = {"state": nn.Linear.init(k3, 16, 5)}
    dec = nn.MultiDecoder(
        cnn_decoder=None,
        mlp_decoder=mlp_dec,
        mlp_heads=heads,
        mlp_keys=("state",),
    )
    out = dec(feat)
    assert out["state"].shape == (2, 5)


def test_astype_bf16():
    mlp = nn.MLP.init(KEY, 4, [8], 2)
    bf = mlp.astype(jnp.bfloat16)
    assert bf.layers[0].weight.dtype == jnp.bfloat16


def test_unknown_activation_raises():
    with pytest.raises(ValueError):
        nn.activation("nope")
