

def test_conv_transpose_subpixel_fast_path_matches_lax():
    """The k4/s2/SAME subpixel rewrite must equal lax.conv_transpose exactly
    (it is the same linear map, regrouped by output-pixel parity)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.nn.layers import ConvTranspose2d

    rng = np.random.default_rng(3)
    for cin, cout, h in [(3, 5, 4), (8, 4, 8), (2, 2, 16)]:
        layer = ConvTranspose2d.init(
            jax.random.PRNGKey(0), cin, cout, 4, stride=2, padding="SAME"
        )
        x = jnp.asarray(rng.normal(size=(2, h, h, cin)).astype(np.float32))
        got = layer(x)
        ref = jax.lax.conv_transpose(
            x, layer.kernel, strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + layer.bias
        assert got.shape == (2, 2 * h, 2 * h, cout)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_conv_transpose_other_configs_use_general_path():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.nn.layers import ConvTranspose2d

    # k5/s2 (the DreamerV2-convention decoder stage) stays on the general
    # lax.conv_transpose path and keeps its output contract
    layer = ConvTranspose2d.init(
        jax.random.PRNGKey(1), 3, 4, 5, stride=2, padding="VALID"
    )
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 4, 4, 3)).astype(np.float32))
    assert layer(x).shape == (1, 11, 11, 4)


def test_conv_transpose_subpixel_gradients_match_lax():
    """Input- and kernel-gradients through the subpixel fast path must match
    the lax.conv_transpose lowering (the DV3 decoder trains through it)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.nn.layers import ConvTranspose2d

    layer = ConvTranspose2d.init(
        jax.random.PRNGKey(2), 6, 3, 4, stride=2, padding="SAME"
    )
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(2, 8, 8, 6)).astype(np.float32)
    )

    def loss_fast(kernel, x):
        return jnp.sum(jnp.sin(layer.replace(kernel=kernel)(x)))

    def loss_ref(kernel, x):
        y = jax.lax.conv_transpose(
            x, kernel, strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + layer.bias
        return jnp.sum(jnp.sin(y))

    gk_fast, gx_fast = jax.grad(loss_fast, argnums=(0, 1))(layer.kernel, x)
    gk_ref, gx_ref = jax.grad(loss_ref, argnums=(0, 1))(layer.kernel, x)
    np.testing.assert_allclose(np.asarray(gk_fast), np.asarray(gk_ref), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gx_fast), np.asarray(gx_ref), atol=1e-4)


def test_conv_transpose_subpixel_bf16_dtype_and_numerics():
    """The fast path under bf16 inputs keeps the dtype and stays close to
    the f32 result (the --precision bfloat16 decoder path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.nn.layers import ConvTranspose2d

    layer = ConvTranspose2d.init(
        jax.random.PRNGKey(4), 4, 3, 4, stride=2, padding="SAME"
    )
    x32 = jnp.asarray(
        np.random.default_rng(2).normal(size=(2, 8, 8, 4)).astype(np.float32)
    )
    y32 = layer(x32)
    y16 = layer(x32.astype(jnp.bfloat16))
    assert y16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y16, dtype=np.float32), np.asarray(y32), rtol=0.1, atol=0.05
    )
