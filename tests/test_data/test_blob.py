"""StepBlobCodec: the one-transfer step transport must be a bit-exact
roundtrip (host pack -> device bitcast unpack), and reserve()/add_direct()
must write the ring identically to the packed add() path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data import AsyncReplayBuffer, StepBlobCodec


def test_blob_roundtrip_bit_exact():
    n_envs = 3
    codec = StepBlobCodec(
        u8_shapes={"rgb": (4, 4, 3), "gray": (5,)},
        f32_shapes={"rewards": (1,), "dones": (1,), "vec": (7,)},
        idx_len=2 * n_envs,
        n_envs=n_envs,
    )
    rng = np.random.default_rng(0)
    u8 = {
        "rgb": rng.integers(0, 256, (n_envs, 4, 4, 3), dtype=np.uint8),
        "gray": rng.integers(0, 256, (n_envs, 5), dtype=np.uint8),
    }
    f32 = {
        "rewards": rng.normal(size=(n_envs, 1)).astype(np.float32),
        "dones": np.array([[0.0], [1.0], [0.0]], np.float32),
        # NaN/inf/subnormal bit patterns must survive the bitcasts
        "vec": np.array(
            [[np.nan, np.inf, -np.inf, -0.0, 1e-45, 1.5, -2.5]] * n_envs,
            np.float32,
        ),
    }
    idx = np.array([0, 1, 2, 0, 1, 2], np.int32)

    blob = codec.pack(u8, f32, idx)
    assert blob.dtype == np.int32 and blob.shape == (codec.blob_len,)

    out_u8, out_f32, out_idx = jax.jit(codec.unpack)(jnp.asarray(blob))
    for k in u8:
        np.testing.assert_array_equal(np.asarray(out_u8[k]), u8[k])
    for k in f32:
        np.testing.assert_array_equal(
            np.asarray(out_f32[k]).view(np.int32), f32[k].view(np.int32)
        )
    np.testing.assert_array_equal(np.asarray(out_idx), idx)


def test_reserve_add_direct_matches_packed_add():
    n_envs, cap = 2, 8
    rng = np.random.default_rng(1)
    rows = [
        {
            "rgb": rng.integers(0, 256, (1, n_envs, 3, 3, 1), dtype=np.uint8),
            "rewards": rng.normal(size=(1, n_envs, 1)).astype(np.float32),
            "actions": rng.normal(size=(1, n_envs, 4)).astype(np.float32),
        }
        for _ in range(cap + 3)  # wraps around
    ]
    via_add = AsyncReplayBuffer(cap, n_envs, storage="device", obs_keys=("rgb",))
    via_blob = AsyncReplayBuffer(cap, n_envs, storage="device", obs_keys=("rgb",))
    for row in rows:
        via_add.add(row)
        idx = via_blob.reserve(1)
        via_blob.add_direct(
            {k: jnp.asarray(v) for k, v in row.items()}, jnp.asarray(idx)
        )
    for k in rows[0]:
        np.testing.assert_array_equal(
            np.asarray(via_add._store[k]), np.asarray(via_blob._store[k])
        )
    np.testing.assert_array_equal(via_add._upos, via_blob._upos)
    np.testing.assert_array_equal(via_add._ufull, via_blob._ufull)


def test_reserve_requires_device_unstaged():
    host = AsyncReplayBuffer(4, 1, storage="host")
    with pytest.raises(RuntimeError):
        host.reserve()
    staged = AsyncReplayBuffer(4, 1, storage="device", stage_rows=8)
    with pytest.raises(RuntimeError):
        staged.reserve()


def test_v2_row_blob_matches_dict_add():
    """make_blob_row (the V1/V2-layout one-transfer add) must write the
    ring identically to the dict add path given the same step."""
    from sheeprl_tpu.algos.dreamer_v2.utils import make_blob_row

    n_envs, cap = 2, 8
    rng = np.random.default_rng(2)
    obs_keys = ("rgb", "vec")
    codec = StepBlobCodec(
        {"rgb": (4, 4, 3)},
        {"vec": (5,), "rewards": (1,), "dones": (1,), "is_first": (1,)},
        idx_len=2 * n_envs,
        n_envs=n_envs,
    )
    blob_row = make_blob_row(codec, obs_keys, ("rewards", "dones", "is_first"))

    step = {
        "rgb": rng.integers(0, 256, (n_envs, 4, 4, 3), dtype=np.uint8),
        "vec": rng.normal(size=(n_envs, 5)).astype(np.float32),
        "rewards": rng.normal(size=(n_envs, 1)).astype(np.float32),
        "dones": np.zeros((n_envs, 1), np.float32),
        "is_first": np.ones((n_envs, 1), np.float32),
    }
    actions = rng.normal(size=(n_envs, 4)).astype(np.float32)

    via_dict = AsyncReplayBuffer(
        cap, n_envs, storage="device", sequential=True, obs_keys=obs_keys
    )
    via_dict.add({**{k: v[None] for k, v in step.items()},
                  "actions": actions[None]})

    via_blob = AsyncReplayBuffer(
        cap, n_envs, storage="device", sequential=True, obs_keys=obs_keys
    )
    bidx = via_blob.reserve(1)
    blob = codec.pack(
        {"rgb": step["rgb"]},
        {k: step[k] for k in ("vec", "rewards", "dones", "is_first")},
        bidx,
    )
    row, idx_dev, obs_dev = blob_row(jnp.asarray(blob), jnp.asarray(actions))
    via_blob.add_direct(row, idx_dev)

    for k in (*step, "actions"):
        np.testing.assert_array_equal(
            np.asarray(via_dict._store[k]), np.asarray(via_blob._store[k])
        )
    # the returned obs dict is the next policy step's input
    for k in obs_keys:
        np.testing.assert_array_equal(np.asarray(obs_dev[k]), step[k])


def test_verify_blob_roundtrip_on_backend():
    from sheeprl_tpu.data.blob import verify_blob_roundtrip

    codec, _, _ = StepBlobCodec.for_step(
        {"rgb": np.zeros((2, 4, 4, 3), np.uint8),
         "vec": np.zeros((2, 5), np.float32)},
        ("rgb", "vec"), 2, ("rewards", "dones"),
    )
    assert verify_blob_roundtrip(codec)  # CPU backend must roundtrip

    class _Broken:
        """codec whose unpack corrupts a value: verification must fail"""
        _u8 = codec._u8
        _f32 = codec._f32
        idx_len = codec.idx_len
        pack = codec.pack

        @staticmethod
        def unpack(blob):
            u8, f32, idx = codec.unpack(blob)
            return u8, f32, idx + 1

    assert not verify_blob_roundtrip(_Broken())


def test_reserve_commit_is_deferred_to_add_direct():
    """ADVICE r3: a failure between reserve() and add_direct() must not
    leave a never-written all-zeros row inside the sampler's valid window —
    the head advance commits only when the scatter is dispatched, and a
    retry reserve() reuses the same rows."""
    rb = AsyncReplayBuffer(8, 2, storage="device")
    row = {"observations": np.ones((1, 2, 3), np.float32)}

    idx1 = rb.reserve(1)
    # nothing committed yet: head and fill state untouched
    np.testing.assert_array_equal(rb._upos, np.zeros(2, np.int64))
    # simulate a pack/jit failure -> the retry gets the SAME rows
    idx2 = rb.reserve(1)
    np.testing.assert_array_equal(idx1, idx2)

    rb.add_direct({k: jnp.asarray(v) for k, v in row.items()}, jnp.asarray(idx2))
    np.testing.assert_array_equal(rb._upos, np.ones(2, np.int64))

    # data_len mismatch with the reservation is a loud error
    rb.reserve(1)
    with pytest.raises(ValueError, match="data_len"):
        rb.add_direct(
            {k: jnp.asarray(np.ones((2, 2, 3), np.float32)) for k in row},
            jnp.asarray(rb.reserve(2)),
            data_len=1,
        )


def test_blob_f32_section_rejects_unrepresentable_integers():
    """ADVICE r3: integer values at/above 2**24 would silently lose
    precision in the f32 value-conversion — the codec must refuse those,
    while SMALL integer observations (MineDojo's int32 equipment ids) keep
    converting exactly (the r4 suite caught an over-strict dtype-kind guard
    breaking the MineDojo e2e path)."""
    from sheeprl_tpu.data.blob import StepBlobCodec

    obs = {"state": np.zeros((2, 3), np.float32)}
    codec, u8_keys, f32_keys = StepBlobCodec.for_step(
        obs, obs_keys=("state",), float_keys=("rewards",), n_envs=2
    )
    good = codec.pack(
        {},
        {"state": np.zeros((2, 3), np.float32),
         "rewards": np.zeros((2, 1), np.float64)},
        np.zeros(4, np.int32),
    )
    assert good.dtype == np.int32
    # small ints convert exactly -> allowed (MineDojo equipment path);
    # +/-2**24 are the LAST exactly-representable magnitudes -> allowed
    for ok_val in (361, 2**24, -(2**24)):
        codec.pack(
            {},
            {"state": np.full((2, 3), ok_val, np.int32),
             "rewards": np.zeros((2, 1), np.float64)},
            np.zeros(4, np.int32),
        )
    with pytest.raises(TypeError, match="> 2\\*\\*24"):
        codec.pack(
            {},
            {"state": np.full((2, 3), 2**24 + 1, np.int32),
             "rewards": np.zeros((2, 1), np.float64)},
            np.zeros(4, np.int32),
        )
    # all-negative arrays must be caught by the dedicated min check
    with pytest.raises(TypeError, match="< -\\(2\\*\\*24\\)"):
        codec.pack(
            {},
            {"state": np.full((2, 3), -(2**24) - 1, np.int32),
             "rewards": np.zeros((2, 1), np.float64)},
            np.zeros(4, np.int32),
        )
    # complex silently dropping its imaginary part is the corruption class
    # the guard exists for
    with pytest.raises(TypeError, match="only float/int"):
        codec.pack(
            {},
            {"state": np.zeros((2, 3), np.complex64),
             "rewards": np.zeros((2, 1), np.float64)},
            np.zeros(4, np.int32),
        )
