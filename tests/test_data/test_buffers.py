"""Buffer invariants, mirroring the reference's test strategy
(/root/reference/tests/test_data/): wrap-around add, pos/full invariants,
oversized inserts, sample-validity windows, memmap variants — for both the
HBM (device) and host storage backends."""

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data import (
    AsyncReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
)

STORAGES = ["device", "host"]


def make_rows(t, n_envs, start=0):
    """rows with value = global step index, easy to assert on"""
    vals = (start + np.arange(t))[:, None, None] * np.ones((1, n_envs, 1), np.float32)
    return {"observations": vals, "dones": np.zeros((t, n_envs, 1), np.float32)}


@pytest.mark.parametrize("storage", STORAGES)
def test_add_and_pos_wraparound(storage):
    rb = ReplayBuffer(5, n_envs=2, storage=storage)
    rb.add(make_rows(3, 2))
    assert not rb.full
    rb.add(make_rows(3, 2, start=3))
    assert rb.full
    # pos wrapped to 1; slot 0 holds step 5
    obs = np.asarray(rb["observations"])
    assert obs[0, 0, 0] == 5.0
    assert obs[1, 0, 0] == 1.0  # not yet overwritten


@pytest.mark.parametrize("storage", STORAGES)
def test_oversized_add_keeps_last_rows(storage):
    rb = ReplayBuffer(4, n_envs=1, storage=storage)
    rb.add(make_rows(10, 1))
    assert rb.full
    obs = sorted(np.asarray(rb["observations"]).reshape(-1).tolist())
    assert obs == [6.0, 7.0, 8.0, 9.0]


@pytest.mark.parametrize("storage", STORAGES)
def test_sample_with_next_obs_excludes_last_written(storage):
    # reference semantics (buffers.py:166-186): with sample_next_obs=True the
    # entry at pos-1 is excluded (its successor at pos belongs to another
    # trajectory); without it, every slot is valid once full.
    rb = ReplayBuffer(5, n_envs=1, storage=storage)
    rb.add(make_rows(5, 1))  # full, pos=0
    rb.add(make_rows(1, 1, start=5))  # pos=1, slot0 overwritten with 5
    for _ in range(5):
        s = rb.sample(64, sample_next_obs=True)
        vals = np.asarray(s["observations"]).reshape(-1)
        # step 5 sits at slot pos-1=0 -> never sampled as current obs
        assert 5.0 not in vals
        assert set(np.unique(vals)).issubset({1.0, 2.0, 3.0, 4.0})
    # plain sampling may return every stored step
    s = rb.sample(256)
    assert set(np.unique(np.asarray(s["observations"]).reshape(-1))) == {
        1.0, 2.0, 3.0, 4.0, 5.0,
    }


@pytest.mark.parametrize("storage", STORAGES)
def test_sample_next_obs(storage):
    rb = ReplayBuffer(6, n_envs=1, storage=storage)
    rb.add(make_rows(4, 1))
    s = rb.sample(32, sample_next_obs=True)
    obs = np.asarray(s["observations"]).reshape(-1)
    nxt = np.asarray(s["next_observations"]).reshape(-1)
    np.testing.assert_allclose(nxt, obs + 1.0)


def test_sample_empty_raises():
    rb = ReplayBuffer(4)
    with pytest.raises(RuntimeError):
        rb.sample(1)
    with pytest.raises(ValueError):
        rb.sample(0)


def test_host_memmap_storage(tmp_path):
    rb = ReplayBuffer(8, n_envs=1, storage="host", memmap_dir=tmp_path / "rb")
    rb.add(make_rows(4, 1))
    assert (tmp_path / "rb" / "observations.npy").exists()
    s = rb.sample(8)
    assert s["observations"].shape == (8, 1)


@pytest.mark.parametrize("storage", STORAGES)
def test_sequential_sample_contiguity(storage):
    rb = SequentialReplayBuffer(16, n_envs=2, storage=storage)
    rb.add(make_rows(10, 2))
    s = rb.sample(4, sequence_length=5, n_samples=3)
    obs = np.asarray(s["observations"])
    assert obs.shape == (3, 5, 4, 1)
    # windows are consecutive steps
    diffs = np.diff(obs[..., 0], axis=1)
    np.testing.assert_allclose(diffs, 1.0)


@pytest.mark.parametrize("storage", STORAGES)
def test_sequential_validity_window_when_full(storage):
    rb = SequentialReplayBuffer(8, n_envs=1, storage=storage)
    rb.add(make_rows(8, 1))  # full, pos=0
    rb.add(make_rows(2, 1, start=8))  # pos=2: slots [0,1] = 8,9
    seq_len = 3
    for _ in range(5):
        s = rb.sample(16, sequence_length=seq_len)
        obs = np.asarray(s["observations"])[..., 0]  # [1, T, B]
        starts = obs[0, 0, :]
        # start index cannot fall in (pos - seq_len, pos) = slots {0,1} invalid
        # region in *slot* space; in value space all windows must be contiguous
        diffs = np.diff(obs[0], axis=0)
        np.testing.assert_allclose(diffs, 1.0)
        # windows never span the write head: values 8,9 can only appear at the
        # tail of a window ending at slot pos-1
        assert not np.any(starts == 1.0)


def test_sequential_too_long_sequence_raises():
    rb = SequentialReplayBuffer(8, n_envs=1)
    rb.add(make_rows(3, 1))
    with pytest.raises(ValueError):
        rb.sample(1, sequence_length=4)


def make_episode(length, n_keys=1, start=0):
    ep = {
        "observations": (start + np.arange(length, dtype=np.float32))[:, None],
        "dones": np.zeros((length, 1), np.float32),
    }
    ep["dones"][-1] = 1.0
    return ep


class TestEpisodeBuffer:
    def test_add_validations(self):
        eb = EpisodeBuffer(16, sequence_length=4)
        bad = make_episode(6)
        bad["dones"][2] = 1.0
        with pytest.raises(RuntimeError):
            eb.add(bad)
        no_end = make_episode(6)
        no_end["dones"][-1] = 0.0
        with pytest.raises(RuntimeError):
            eb.add(no_end)
        with pytest.raises(RuntimeError):
            eb.add(make_episode(2))  # too short
        with pytest.raises(RuntimeError):
            eb.add(make_episode(20))  # too long

    def test_eviction_keeps_capacity(self):
        eb = EpisodeBuffer(12, sequence_length=3)
        for i in range(5):
            eb.add(make_episode(5, start=10 * i))
        assert len(eb) <= 12
        # oldest episodes evicted: first remaining episode starts at >= 10
        assert eb[0]["observations"][0, 0] >= 10.0

    def test_sample_shapes_and_windows(self):
        eb = EpisodeBuffer(64, sequence_length=4)
        eb.add(make_episode(10))
        eb.add(make_episode(8, start=100))
        s = eb.sample(6, n_samples=2)
        assert s["observations"].shape == (2, 4, 6, 1)
        diffs = np.diff(s["observations"][..., 0], axis=1)
        np.testing.assert_allclose(diffs, 1.0)

    def test_prioritize_ends_hits_tail(self):
        eb = EpisodeBuffer(64, sequence_length=4, seed=1)
        eb.add(make_episode(32))
        s = eb.sample(256, prioritize_ends=True)
        # with prioritization the final window [28..31] should appear often
        starts = s["observations"][0, 0, :, 0]
        assert (starts == 28.0).mean() > 0.10

    def test_memmap_episode_eviction_cleans_files(self, tmp_path):
        eb = EpisodeBuffer(10, sequence_length=3, memmap_dir=tmp_path / "eb")
        for i in range(4):
            eb.add(make_episode(5, start=10 * i))
        dirs = list((tmp_path / "eb").iterdir())
        # capacity 10 fits two 5-step episodes
        assert len(dirs) == 2


class TestAsyncReplayBuffer:
    @pytest.mark.parametrize("storage", STORAGES)
    def test_per_env_add_with_indices(self, storage):
        arb = AsyncReplayBuffer(8, n_envs=3, storage=storage, sequential=True)
        arb.add(make_rows(4, 3))
        # add one extra row only to env 1
        arb.add(make_rows(1, 1, start=100), indices=[1])
        s = arb.sample(8, sequence_length=2, n_samples=1)
        assert s["observations"].shape == (1, 2, 8, 1)

    @pytest.mark.parametrize("storage", STORAGES)
    def test_sample_partition(self, storage):
        arb = AsyncReplayBuffer(16, n_envs=4, storage=storage, sequential=False)
        arb.add(make_rows(8, 4))
        s = arb.sample(32)
        assert s["observations"].shape == (32, 1)

    def test_even_split_static_shapes(self):
        # the default partition draws B // n_envs from every env (remainder
        # rotating), so per-env gather shapes stay static under jit
        arb = AsyncReplayBuffer(16, n_envs=4, storage="host", sequential=False)
        arb.add(make_rows(8, 4))
        # spy on the per-env sample sizes actually requested
        requested: list[tuple[int, ...]] = []
        originals = [b.sample for b in arb.buffer]
        for b, orig in zip(arb.buffer, originals):
            def spied(n, *a, _orig=orig, **kw):
                requested.append(n)
                return _orig(n, *a, **kw)
            b.sample = spied
        for _ in range(20):
            s = arb.sample(8)
            assert s["observations"].shape == (8, 1)
        # divisible batch: every env contributes exactly B // n_envs
        assert set(requested) == {2}
        # indivisible batch: per-env counts are only floor/floor+1 — at most
        # two distinct shapes ever reach the jitted gather
        requested.clear()
        for _ in range(20):
            arb.sample(5)
        assert set(requested) <= {1, 2}
        assert sum(requested) == 20 * 5

    def test_multinomial_split_still_available(self):
        arb = AsyncReplayBuffer(
            16, n_envs=4, storage="host", sequential=False, split="multinomial"
        )
        arb.add(make_rows(8, 4))
        s = arb.sample(32)
        assert s["observations"].shape == (32, 1)
        with pytest.raises(ValueError, match="split"):
            AsyncReplayBuffer(16, n_envs=4, split="bogus")


@pytest.mark.parametrize("storage", STORAGES)
def test_state_dict_roundtrip(storage):
    rb = ReplayBuffer(6, n_envs=2, storage=storage)
    rb.add(make_rows(4, 2))
    state = rb.to_state_dict()
    rb2 = ReplayBuffer(6, n_envs=2, storage=storage)
    rb2.load_state_dict(state)
    assert rb2.full == rb.full
    np.testing.assert_allclose(
        np.asarray(rb2["observations"]), np.asarray(rb["observations"])
    )
    s = rb2.sample(4)
    assert s["observations"].shape == (4, 1)


class TestAsyncUnifiedDeviceStore:
    """Invariants specific to the unified-HBM AsyncReplayBuffer backend:
    one scatter/gather dispatch for all envs, with per-env independence
    expressed as index arithmetic."""

    def test_env_isolation_and_contiguity(self):
        # env e's stream is e*100 + step: every sampled window must be a
        # contiguous run from a single env
        arb = AsyncReplayBuffer(16, n_envs=4, storage="device", sequential=True)
        t = 10
        obs = (
            np.arange(t)[:, None, None]
            + 100.0 * np.arange(4)[None, :, None]
        ).astype(np.float32)
        arb.add({"observations": obs})
        s = np.asarray(
            arb.sample(12, sequence_length=3, n_samples=2)["observations"]
        )  # [2, 3, 12, 1]
        assert s.shape == (2, 3, 12, 1)
        envs = s // 100.0
        assert (envs == envs[:, :1]).all(), "window crossed env columns"
        steps = s % 100.0
        assert np.allclose(np.diff(steps, axis=1), 1.0), "window not contiguous"

    def test_window_excludes_write_head_after_wrap(self):
        # after wrapping, sequences must never span the write head (stale
        # next to fresh data)
        arb = AsyncReplayBuffer(8, n_envs=2, storage="device", sequential=True)
        t = 13  # wraps: pos=5, live steps 5..12
        obs = np.arange(t, dtype=np.float32)[:, None, None] * np.ones(
            (1, 2, 1), np.float32
        )
        arb.add({"observations": obs})
        for _ in range(20):
            s = np.asarray(
                arb.sample(8, sequence_length=3, n_samples=1)["observations"]
            )
            assert np.allclose(np.diff(s, axis=1), 1.0), (
                "sampled window crossed the write head"
            )

    def test_per_env_heads_advance_independently(self):
        arb = AsyncReplayBuffer(8, n_envs=3, storage="device", sequential=True)
        arb.add({"observations": np.zeros((2, 3, 1), np.float32)})
        arb.add({"observations": np.ones((3, 2, 1), np.float32)}, indices=[0, 2])
        assert [b.pos for b in arb.buffer] == [5, 2, 5]
        assert arb.full == (False, False, False)

    def test_next_obs_synthesis_non_sequential(self):
        arb = AsyncReplayBuffer(16, n_envs=2, storage="device", sequential=False)
        t = 6
        obs = np.arange(t, dtype=np.float32)[:, None, None] * np.ones(
            (1, 2, 1), np.float32
        )
        arb.add({"observations": obs})
        s = arb.sample(8, sample_next_obs=True)
        assert np.allclose(
            np.asarray(s["next_observations"]), np.asarray(s["observations"]) + 1.0
        )

    def test_sequential_insufficient_raises(self):
        arb = AsyncReplayBuffer(8, n_envs=2, storage="device", sequential=True)
        arb.add({"observations": np.zeros((2, 2, 1), np.float32)})
        with pytest.raises(ValueError, match="too long sequence_length"):
            arb.sample(4, sequence_length=4, n_samples=1)

    def test_staged_adds_match_unstaged(self):
        # full-width adds stage host-side and flush as one scatter; the
        # store contents must be identical to per-add scatters across
        # interleaved full/subset adds, wrap-around and row surgery
        def run(stage_cap):
            arb = AsyncReplayBuffer(8, n_envs=3, storage="device",
                                    sequential=True, seed=7,
                                    stage_rows=stage_cap)
            step = 0
            for _ in range(5):  # 15 rows through an 8-ring: wraps twice
                for _ in range(3):
                    row = np.full((1, 3, 1), step, np.float32) + np.arange(
                        3, dtype=np.float32
                    ).reshape(1, 3, 1) * 100.0
                    arb.add({"observations": row})
                    step += 1
                arb.add(
                    {"observations": np.full((1, 1, 1), 999.0, np.float32)},
                    indices=[1],
                )
            arb.buffer[2].set_at("observations", 3, np.float32(-5.0))
            st = arb.to_state_dict()
            return [
                (s["pos"], s["full"], np.asarray(s["buf"]["observations"]))
                for s in st["buffers"]
            ]

        staged, unstaged = run(64), run(0)  # 0 == staging off (direct path)
        for (p_a, f_a, b_a), (p_b, f_b, b_b) in zip(staged, unstaged):
            assert p_a == p_b and f_a == f_b
            np.testing.assert_array_equal(b_a, b_b)

    def test_staging_flush_bounds_and_overflow(self):
        # a single flush holding more rows than the ring must keep only the
        # last buffer_size rows AND land them at the slots sequential
        # per-add scatters would have used (the flush trims + advances its
        # start positions; reachable only when multi-row adds push one
        # staged batch past capacity)
        arb = AsyncReplayBuffer(4, n_envs=2, storage="device", sequential=False,
                                stage_rows=4)
        for base in (0.0, 3.0):  # two 3-row adds: one flush of 6 rows > 4
            rows = (base + np.arange(3, dtype=np.float32)).reshape(3, 1, 1)
            arb.add({"observations": np.broadcast_to(rows, (3, 2, 1))})
        assert arb._staged_rows == 0  # cap (=buffer_size) forced the flush
        assert [b.pos for b in arb.buffer] == [2, 2]
        assert arb.full == (True, True)
        ring = np.asarray(arb.buffer[0].buffer["observations"])[:, 0, 0]
        # rows 2..5 survive; ring slot = step % 4 -> [4, 5, 2, 3]
        assert ring.tolist() == [4.0, 5.0, 2.0, 3.0]

    def test_staged_rows_copy_on_add(self):
        # add() has copy-in semantics: mutating the caller's array after
        # add must not change what a later flush writes
        arb = AsyncReplayBuffer(8, n_envs=1, storage="device", sequential=False,
                                stage_rows=64)
        row = np.full((1, 1, 1), 7.0, np.float32)
        arb.add({"observations": row})
        row[:] = -1.0  # mutate before any flush
        ring = np.asarray(arb.buffer[0].buffer["observations"])
        assert ring[0, 0, 0] == 7.0

    def test_cross_storage_checkpoint_roundtrip(self):
        # host-saved rings restore into a device store and vice versa
        src = AsyncReplayBuffer(8, n_envs=2, storage="host", sequential=True)
        src.add({"observations": np.arange(10, dtype=np.float32)[:, None, None]
                 * np.ones((1, 2, 1), np.float32)})
        src.save("/tmp/arb_cross.npz")
        dst = AsyncReplayBuffer(8, n_envs=2, storage="device", sequential=True)
        dst.load("/tmp/arb_cross.npz")
        assert [b.pos for b in dst.buffer] == [b.pos for b in src.buffer]
        s = dst.sample(4, sequence_length=2, n_samples=1)
        assert np.asarray(s["observations"]).shape == (1, 2, 4, 1)

    def test_partial_env_checkpoint_restores_into_device_store(self):
        # only env 0 ever wrote: host-saved mixed (populated/empty) per-env
        # rings must restore into the unified device store
        src = AsyncReplayBuffer(8, n_envs=3, storage="host", sequential=True)
        src.add(
            {"observations": np.arange(4, dtype=np.float32)[:, None, None]},
            indices=[0],
        )
        src.save("/tmp/arb_partial.npz")
        dst = AsyncReplayBuffer(8, n_envs=3, storage="device", sequential=True)
        dst.load("/tmp/arb_partial.npz")
        assert [b.pos for b in dst.buffer] == [4, 0, 0]
        # the per-env view exposes only its own column
        col = dst.buffer[0].buffer["observations"]
        assert col.shape == (8, 1, 1)
        assert np.asarray(col)[:4, 0, 0].tolist() == [0.0, 1.0, 2.0, 3.0]
        assert np.asarray(dst.buffer[1].buffer["observations"]).max() == 0.0


class TestPackedDeviceAdds:
    """Round-3 transfer packing: the device add ships ONE host->device
    transfer per dtype group (plus packed indices), and values already on
    device (the mains reuse the policy step's obs put) scatter directly."""

    def test_device_resident_values_scatter_directly(self):
        arb = AsyncReplayBuffer(8, n_envs=2, storage="device", sequential=True,
                                obs_keys=("rgb",))
        rgb = np.arange(2 * 4, dtype=np.uint8).reshape(1, 2, 4)
        arb.add({
            "rgb": jnp.asarray(rgb),  # device-resident (direct path)
            "rewards": np.ones((1, 2, 1), np.float32),  # host (packed path)
        })
        ring = np.asarray(arb.buffer[0].buffer["rgb"])
        assert ring.dtype == np.uint8
        assert ring[0, 0].tolist() == rgb[0, 0].tolist()
        assert np.asarray(arb.buffer[1].buffer["rewards"])[0, 0, 0] == 1.0

    def test_mixed_dtype_groups_pack_and_unpack(self):
        arb = AsyncReplayBuffer(8, n_envs=3, storage="device", sequential=True,
                                obs_keys=("rgb",))
        rng = np.random.default_rng(0)
        data = {
            "rgb": rng.integers(0, 255, (2, 3, 5), dtype=np.uint8),
            "vec": rng.normal(size=(2, 3, 4)).astype(np.float32),
            "rewards": rng.normal(size=(2, 3, 1)).astype(np.float32),
        }
        arb.add(data)
        for k, v in data.items():
            ring = np.stack(
                [np.asarray(arb.buffer[e].buffer[k])[:2, 0] for e in range(3)],
                axis=1,
            )
            np.testing.assert_array_equal(ring, v)

    def test_width_class_packing_is_bit_exact(self):
        # the packed transfer bit-views int32 as float32 (one transfer per
        # width class, not per dtype) — the roundtrip must preserve every
        # bit pattern, including ones that alias NaNs/infs/subnormals
        from sheeprl_tpu.data.buffers import _pack_host_values, _unpack_values

        evil_i32 = np.array(
            [0, -1, 2**31 - 1, -(2**31), 0x7F800001, 0x7FC00000],
            np.int32,
        )
        evil_f32 = np.array(
            [0.0, -0.0, np.inf, -np.inf, np.nan, np.float32(1e-45)], np.float32
        )
        data = {
            "i": evil_i32.reshape(1, 6),
            "f": evil_f32.reshape(1, 6),
            "u8": np.arange(256, dtype=np.uint8).reshape(1, 256),
            "b": np.array([[True, False, True]]),
            "i64": np.array([[7, -9]], np.int64),
        }
        direct, packed, layout = _pack_host_values(data)
        assert not direct and len(packed) == 2  # one 4-byte + one 1-byte blob
        out = _unpack_values(direct, packed, layout)
        np.testing.assert_array_equal(np.asarray(out["i"]), data["i"])
        np.testing.assert_array_equal(
            np.asarray(out["f"]).view(np.int32), evil_f32.view(np.int32)[None]
        )
        np.testing.assert_array_equal(np.asarray(out["u8"]), data["u8"])
        np.testing.assert_array_equal(np.asarray(out["b"]), data["b"])
        np.testing.assert_array_equal(
            np.asarray(out["i64"]), data["i64"].astype(np.int32)
        )

    def test_subset_indices_through_packed_path(self):
        arb = AsyncReplayBuffer(8, n_envs=3, storage="device", sequential=True)
        arb.add({"observations": np.zeros((1, 3, 1), np.float32)})
        arb.add({"observations": np.full((1, 2, 1), 9.0, np.float32)},
                indices=[0, 2])
        assert [b.pos for b in arb.buffer] == [2, 1, 2]
        assert np.asarray(arb.buffer[2].buffer["observations"])[1, 0, 0] == 9.0
        assert np.asarray(arb.buffer[1].buffer["observations"])[1, 0, 0] == 0.0

    def test_prefers_host_adds(self):
        dev = AsyncReplayBuffer(8, n_envs=1, storage="device")
        host = AsyncReplayBuffer(8, n_envs=1, storage="host")
        staged = AsyncReplayBuffer(8, n_envs=1, storage="device", stage_rows=16)
        assert not dev.prefers_host_adds
        assert host.prefers_host_adds
        assert staged.prefers_host_adds
