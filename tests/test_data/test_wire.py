"""Bit-exact receipts for the wire layer (ISSUE 14 satellite): the
`pack_tree`/`unpack_tree` framing primitives and every buffer class's
versioned pickle-free `to_bytes()/from_bytes()` round-trip, including the
sampler PRNG state — a restored buffer continues the EXACT sample stream
the source would have drawn."""

import numpy as np
import pytest

from sheeprl_tpu.data import (
    AsyncReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
)
from sheeprl_tpu.data.wire import (
    WireFormatError,
    pack_leaves,
    pack_tree,
    unpack_leaves,
    unpack_tree,
)


def bits_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return (
        a.dtype == b.dtype
        and a.shape == b.shape
        and a.tobytes() == b.tobytes()
    )


# ---------------------------------------------------------------------------
# framing primitives
# ---------------------------------------------------------------------------


def test_pack_tree_roundtrip_mixed_dtypes():
    rng = np.random.default_rng(0)
    tree = {
        "f32": rng.normal(size=(3, 2, 4)).astype(np.float32),
        "u8": rng.integers(0, 255, size=(5, 2), dtype=np.uint8),
        "i32": rng.integers(-(2**30), 2**30, size=(7,), dtype=np.int32),
        "bool": rng.integers(0, 2, size=(4, 3)).astype(np.bool_),
        "f16": rng.normal(size=(2, 2)).astype(np.float16),
        "i64": rng.integers(-(2**60), 2**60, size=(3,), dtype=np.int64),
        "f64": rng.normal(size=(2, 5)),
        "scalarish": np.float32(3.25).reshape(()),
    }
    out = unpack_tree(pack_tree(tree))
    assert set(out) == set(tree)
    for k in tree:
        assert bits_equal(tree[k], out[k]), k
    # restored arrays must be writable (frombuffer views are not)
    out["f32"][0, 0, 0] = 1.0


def test_pack_tree_preserves_nan_payloads():
    # arbitrary NaN bit patterns must survive: the int carrier guarantees
    # no canonicalization anywhere on the wire
    weird = np.array([0x7FC00001, 0xFFC12345, 0x7F800000], np.uint32).view(
        np.float32
    )
    out = unpack_tree(pack_tree({"x": weird}))
    assert out["x"].view(np.uint32).tolist() == weird.view(np.uint32).tolist()


def test_pack_leaves_roundtrip_preserves_order():
    leaves = [
        np.arange(6, dtype=np.float32).reshape(2, 3),
        np.array([True, False]),
        np.arange(4, dtype=np.int64),
    ]
    out = unpack_leaves(pack_leaves(leaves))
    assert len(out) == 3
    for a, b in zip(leaves, out):
        assert bits_equal(a, b)


def test_wire_rejects_garbage():
    with pytest.raises(WireFormatError):
        unpack_tree(b"NOPE" + b"\x00" * 16)
    with pytest.raises(WireFormatError):
        unpack_leaves(b"XXXX")
    with pytest.raises(WireFormatError):
        # valid magic, truncated header
        unpack_tree(pack_tree({"a": np.zeros(4, np.float32)})[:10])


# ---------------------------------------------------------------------------
# buffer round-trips
# ---------------------------------------------------------------------------


def fill_rows(t, n_envs, rng):
    return {
        "observations": rng.normal(size=(t, n_envs, 3)).astype(np.float32),
        "actions": rng.integers(0, 4, size=(t, n_envs, 1)).astype(np.float32),
        "rewards": rng.normal(size=(t, n_envs, 1)).astype(np.float32),
        "dones": (rng.random((t, n_envs, 1)) < 0.1).astype(np.float32),
    }


def assert_same_sample_stream(src, dst, **kw):
    a, b = src.sample(4, **kw), dst.sample(4, **kw)
    assert set(a) == set(b)
    for k in a:
        assert bits_equal(a[k], b[k]), k


@pytest.mark.parametrize("storage", ["device", "host"])
def test_replay_buffer_roundtrip(storage):
    rng = np.random.default_rng(1)
    rb = ReplayBuffer(8, n_envs=2, storage=storage, seed=3)
    rb.add(fill_rows(5, 2, rng))
    rb.sample(2)  # advance the sampler stream past its seed state
    blob = rb.to_bytes()
    out = ReplayBuffer.from_bytes(blob, storage="host")
    assert out.pos == rb.pos and out.full == rb.full
    for k in rb.buffer:
        assert bits_equal(rb[k], out[k]), k
    # stream equality requires the same sampling path (device storage draws
    # from the jax key, host from the numpy rng — both restore, but compare
    # like with like)
    same = ReplayBuffer.from_bytes(blob, storage=storage)
    assert_same_sample_stream(rb, same)


def test_sequential_replay_buffer_roundtrip():
    rng = np.random.default_rng(2)
    rb = SequentialReplayBuffer(16, n_envs=2, storage="host", seed=5)
    rb.add(fill_rows(12, 2, rng))
    blob = rb.to_bytes()
    out = SequentialReplayBuffer.from_bytes(blob, storage="host")
    assert_same_sample_stream(rb, out, sequence_length=4, n_samples=2)


def test_class_name_is_checked():
    rb = ReplayBuffer(4, storage="host")
    rb.add(fill_rows(2, 1, np.random.default_rng(0)))
    with pytest.raises(WireFormatError):
        SequentialReplayBuffer.from_bytes(rb.to_bytes())


def test_empty_buffer_roundtrip():
    rb = ReplayBuffer(4, n_envs=2, storage="host")
    out = ReplayBuffer.from_bytes(rb.to_bytes())
    assert out.buffer is None and out.pos == 0 and not out.full


def test_episode_buffer_roundtrip():
    rng = np.random.default_rng(3)
    eb = EpisodeBuffer(64, sequence_length=4, seed=7)
    for ep_len in (6, 9, 5):
        dones = np.zeros((ep_len, 1), np.float32)
        dones[-1] = 1.0
        eb.add(
            {
                "observations": rng.normal(size=(ep_len, 3)).astype(np.float32),
                "dones": dones,
            }
        )
    eb.sample(2)
    out = EpisodeBuffer.from_bytes(eb.to_bytes())
    assert len(out.buffer) == len(eb.buffer)
    for src_ep, dst_ep in zip(eb.buffer, out.buffer):
        for k in src_ep:
            assert bits_equal(src_ep[k], dst_ep[k]), k
    assert_same_sample_stream(eb, out, n_samples=2)


@pytest.mark.parametrize("storage", ["device", "host"])
def test_async_replay_buffer_roundtrip(storage):
    rng = np.random.default_rng(4)
    rb = AsyncReplayBuffer(
        16, n_envs=3, storage=storage, sequential=True, seed=9
    )
    rb.add(fill_rows(10, 3, rng))
    rb.add(fill_rows(2, 2, rng), indices=[0, 2])
    blob = rb.to_bytes()
    out = AsyncReplayBuffer.from_bytes(blob, storage="host")
    assert out.n_envs == rb.n_envs
    src_st, dst_st = rb.to_state_dict(), out.to_state_dict()
    for s, d in zip(src_st["buffers"], dst_st["buffers"]):
        assert s["pos"] == d["pos"] and s["full"] == d["full"]
        for k in s["buf"] or {}:
            assert bits_equal(s["buf"][k], d["buf"][k]), k
    if storage == "host":
        # full sampler state (incl. per-env sub-states) restores: the next
        # draws from source and restored copies are identical
        assert_same_sample_stream(rb, out, sequence_length=3, n_samples=2)


def test_replay_buffer_roundtrip_preserves_nan_payload_rows():
    rb = ReplayBuffer(4, n_envs=1, storage="host")
    rows = np.array([0x7FC00001, 0x7FC00002], np.uint32).view(np.float32)
    rb.add({"observations": rows.reshape(2, 1, 1)})
    out = ReplayBuffer.from_bytes(rb.to_bytes())
    assert bits_equal(rb["observations"], out["observations"])
