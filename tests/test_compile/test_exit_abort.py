"""Regression receipts for the `--warm_compile on` exit abort (ISSUE 7
satellite): a registered-but-never-called jit used to leave a warm-compile
daemon thread inside an XLA compile at interpreter teardown, which aborts
the process with `terminate called without an active exception` (racy rc
134). `CompilePlan.start()` now wires `close()` to atexit, and `close()`
cancels the untouched queue and joins in-flight workers (bounded by
SHEEPRL_TPU_WARM_JOIN_S)."""

import os
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.compile import CompilePlan, sds

_REPO = Path(__file__).resolve().parents[2]

_NEVER_CALLED_SCRIPT = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    # a persistent-cache hit would make the compile instant and the race
    # moot — force a real in-flight XLA compile at exit
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    os.environ.pop("SHEEPRL_TPU_COMPILE_CACHE", None)
    os.environ.pop("SHEEPRL_TPU_PLAN_MODE", None)
    import jax
    import jax.numpy as jnp
    from sheeprl_tpu.compile import CompilePlan, sds

    class _Args:
        warm_compile = "on"

    plan = CompilePlan.from_args(_Args())

    @jax.jit
    def step(x):  # non-trivial: the worker is still compiling when we exit
        def body(c, _):
            c = jnp.tanh(c @ c.T) @ c
            return c, c.sum()
        c, ys = jax.lax.scan(body, x, None, length=8)
        return c, ys

    warm = plan.register(
        "never_called", step, example=lambda: (sds((64, 64), jnp.float32),)
    )
    plan.start()
    # the bug: return from main without ever calling `warm` and without
    # plan.close() — pre-fix this tears down the interpreter under the
    # worker thread mid-compile and aborts
    sys.exit(0)
    """
)


@pytest.mark.timeout(300)
def test_register_but_never_call_exits_cleanly():
    p = subprocess.run(
        [sys.executable, "-c", _NEVER_CALLED_SCRIPT],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert "terminate called" not in p.stderr, p.stderr[-2000:]
    assert p.returncode == 0, (p.returncode, p.stderr[-2000:])


@pytest.mark.timeout(300)
def test_close_cancels_queued_compiles():
    """close() must drain the queue: entries no worker picked up get a
    cancellation error and a set done-event (so any racing barrier waiter
    falls through to the cold fn instead of hanging)."""
    plan = CompilePlan(enabled=True, threads=1)

    def gate_example():
        return (sds((8, 8), jnp.float32),)

    fns = [jax.jit(lambda x, i=i: x + i) for i in range(4)]
    wrapped = [
        plan.register(f"jit_{i}", fn, example=gate_example)
        for i, fn in enumerate(fns)
    ]
    plan.start()
    plan.close(join_timeout=120.0)
    for entry in plan._entries:
        assert entry.done.is_set()
    cancelled = [e for e in plan._entries if e.error and "cancelled" in e.error]
    compiled = [e for e in plan._entries if e.executable is not None]
    assert len(cancelled) + len(compiled) == len(plan._entries)
    # post-close calls still work (cold path for cancelled entries)
    x = jnp.ones((8, 8), jnp.float32)
    for i, w in enumerate(wrapped):
        assert jnp.allclose(w(x), x + i)


@pytest.mark.timeout(300)
def test_close_idempotent_and_unregisters_atexit():
    plan = CompilePlan(enabled=True)
    plan.register("j", jax.jit(lambda x: x * 2), example=lambda: (sds((4,), jnp.float32),))
    plan.start()
    plan.close()
    plan.close()  # second close is a no-op, not a double-join
    assert plan._closed
