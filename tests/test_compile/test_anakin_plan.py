"""CompilePlan receipt for the Anakin rollout jit (ISSUE 6 satellite): the
registered collector AOT-compiles during the warm-start window and its
executable produces bitwise-identical rollouts to the cold jit path."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.compile import CompilePlan
from sheeprl_tpu.envs.jax import (
    JaxCartPole,
    PPOCollectorCarry,
    VecJaxEnv,
    make_ppo_collector,
)


class _On:
    warm_compile = "on"


def _setup():
    from sheeprl_tpu.algos.ppo.agent import PPOAgent

    venv = VecJaxEnv(env=JaxCartPole(), num_envs=4)
    agent = PPOAgent.init(
        jax.random.PRNGKey(1), [2], venv.single_observation_space.spaces,
        [], ["state"], dense_units=8, mlp_layers=1, mlp_features_dim=8,
    )
    collect = jax.jit(make_ppo_collector(venv, 8, (2,), False))
    state, obs = jax.jit(venv.reset)(jax.random.PRNGKey(0))
    carry = PPOCollectorCarry(
        vec=state, obs=obs, prev_done=jnp.zeros((4, 1), jnp.float32)
    )
    return agent, collect, carry


@pytest.mark.timeout(300)
def test_anakin_rollout_warm_aot_bit_exact():
    agent, collect, carry = _setup()
    key = jax.random.PRNGKey(5)
    carry_cold, traj_cold, ep_cold = collect(agent, carry, key)

    plan = CompilePlan.from_args(_On())
    wrapped = plan.register(
        "anakin_rollout", collect, example=lambda: (agent, carry, key)
    )
    plan.start()
    assert plan.wait(timeout=240), "anakin rollout warm compile did not finish"
    st = plan.stats()["entries"]["anakin_rollout"]
    assert st["compiled"] and st["error"] is None

    carry_aot, traj_aot, ep_aot = wrapped(agent, carry, key)
    st = plan.stats()["entries"]["anakin_rollout"]
    assert st["aot_calls"] == 1 and st["fallbacks"] == 0

    for k in traj_cold:
        np.testing.assert_array_equal(
            np.asarray(traj_cold[k]), np.asarray(traj_aot[k]), err_msg=k
        )
    np.testing.assert_array_equal(
        np.asarray(carry_cold.prev_done), np.asarray(carry_aot.prev_done)
    )
    np.testing.assert_array_equal(
        np.asarray(ep_cold["return_sum"]), np.asarray(ep_aot["return_sum"])
    )
    plan.close()


@pytest.mark.timeout(300)
def test_anakin_rollout_in_ppo_main_plan(tmp_path):
    """End-to-end receipt: a --env_backend jax --warm_compile on PPO dry run
    registers `anakin_rollout` in its CompilePlan and the run's compile
    telemetry records the AOT build (Compile/exe/anakin_rollout_seconds)."""
    import json
    import os

    from sheeprl_tpu.utils.registry import tasks
    import sheeprl_tpu.algos  # noqa: F401

    tasks["ppo"]([
        "--env_id", "CartPole-v1", "--env_backend", "jax", "--dry_run",
        "--warm_compile", "on",
        "--num_envs", "8", "--rollout_steps", "8", "--per_rank_batch_size", "16",
        "--update_epochs", "1", "--dense_units", "8", "--mlp_layers", "1",
        "--mlp_features_dim", "8",
        "--root_dir", str(tmp_path), "--run_name", "anakin_warm",
    ])
    events_path = os.path.join(tmp_path, "anakin_warm", "telemetry.jsonl")
    events = [json.loads(line) for line in open(events_path)]
    compiled = {
        e.get("jit")
        for e in events
        if e.get("event") == "compile" and e.get("mode") in ("warm", "warmup")
        and e.get("error") is None
    }
    assert "anakin_rollout" in compiled, compiled
    summaries = [e for e in events if e.get("event") == "compile.summary"]
    assert summaries, "no compile.summary event"
    entries = summaries[-1]["entries"]
    assert entries["anakin_rollout"]["compiled"], entries["anakin_rollout"]
    assert entries["anakin_rollout"]["aot_calls"] >= 1
