"""sheepopt unified measured-decision framework receipts (ISSUE 11):
cache keying/invalidation, bit-exactness disqualification, the remat
acceptance gate, the scan-unroll legacy-store migration, the batch-chunk
probe cache, and the propose-diff golden."""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.compile import decisions as dec
from sheeprl_tpu.compile.partition import decide_batch_chunk


def _counting_build(calls):
    def build(mult):
        calls.append(mult)

        def fn(x):
            y = x
            for _ in range(int(mult)):
                y = y * 1.0 + 1.0
            return y

        return fn

    return build


def test_decide_seconds_objective_and_cache_hit(tmp_path):
    """The ladder measures every candidate once, persists the decision,
    and a same-key re-run serves from the cache without building or
    compiling anything."""
    store = str(tmp_path / "decisions.json")
    calls = []
    x = jnp.arange(64.0)
    d = dec.decide(
        "toy", "probe", [0, 1], _counting_build(calls), (x,),
        repeats=1, store_path=store,
    )
    assert d.source == "measured"
    assert set(d.candidates) == {"0", "1"}
    assert calls.count(0) >= 1 and calls.count(1) == 1  # 0 also warms up
    n_calls = len(calls)
    again = dec.decide(
        "toy", "probe", [0, 1], _counting_build(calls), (x,),
        repeats=1, store_path=store,
    )
    assert again.source == "cache"
    assert again.winner == d.winner
    assert len(calls) == n_calls  # nothing rebuilt, nothing recompiled
    with open(store) as fh:
        assert d.key in json.load(fh)


def test_cache_invalidated_on_aval_and_version_drift(tmp_path):
    """The key carries avals + jax version + backend: drift in any of
    them is a miss — a decision measured at other shapes or on another
    toolchain never leaks."""
    store = str(tmp_path / "decisions.json")
    calls = []
    x8 = jnp.arange(8.0)
    d = dec.decide(
        "toy", "probe", [0], _counting_build(calls), (x8,),
        repeats=1, store_path=store,
    )
    assert f"jax{jax.__version__}" in d.key and "float32[8]" in d.key
    # aval drift -> fresh measurement
    calls.clear()
    d16 = dec.decide(
        "toy", "probe", [0], _counting_build(calls), (jnp.arange(16.0),),
        repeats=1, store_path=store,
    )
    assert d16.source == "measured" and calls
    # jax-version drift: rewrite the stored key as another version — the
    # current-version lookup must miss it
    with open(store) as fh:
        blob = json.load(fh)
    stale_key = d.key.replace(f"jax{jax.__version__}", "jax0.0.0")
    blob[stale_key] = blob.pop(d.key)
    with open(store, "w") as fh:
        json.dump(blob, fh)
    calls.clear()
    d2 = dec.decide(
        "toy", "probe", [0], _counting_build(calls), (x8,),
        repeats=1, store_path=store,
    )
    assert d2.source == "measured" and calls


def test_bit_exact_disqualification(tmp_path):
    """A candidate whose numerics differ from the baseline is disqualified
    and can never win, even when it is faster."""
    def build(mult):
        return lambda x: x * float(mult)

    d = dec.decide(
        "toy", "tainted", [1, 2], build, (jnp.arange(8.0),),
        repeats=1, store_path=str(tmp_path / "d.json"),
    )
    assert d.candidates["2"]["bit_exact"] is False
    assert d.winner == "1" and not d.accepted


def _scan_grad_build(width=64, steps=24):
    w = jax.random.normal(jax.random.PRNGKey(0), (width, width)) * 0.05
    xs = jax.random.normal(jax.random.PRNGKey(1), (steps, 4, width))
    c0 = jnp.zeros((4, width))

    def build(mode):
        def step(c, x):
            h = jnp.tanh(c @ w + x)
            h2 = jnp.tanh(h @ w)
            return jnp.tanh(h2 @ w + h), h2

        wrapped = dec_checkpoint(step, mode)

        def loss(c0, xs):
            _, ys = jax.lax.scan(wrapped, c0, xs)
            return jnp.sum(ys * ys)

        return jax.value_and_grad(loss, argnums=(0, 1))

    return build, (c0, xs)


def dec_checkpoint(step, mode):
    from sheeprl_tpu.ops.scan import checkpoint_body

    return checkpoint_body(step, mode)


def test_remat_acceptance_gate_accepts_byte_win(tmp_path):
    """A grad-of-scan probe where checkpointing strictly reduces
    `memory_analysis()` peak bytes: the bytes objective accepts a remat
    rung (bit-exact receipt required) under a permissive time budget, and
    the decision records the byte delta."""
    build, example = _scan_grad_build()
    d = dec.decide_remat(
        "test.scan_grad", build, example, repeats=1,
        store_path=str(tmp_path / "d.json"), max_time_cost_frac=10.0,
    )
    assert d.winner in ("on", "policy") and d.accepted
    assert d.candidate(d.winner)["bit_exact"] is True
    assert d.bytes_delta() is not None and d.bytes_delta() < 0


def test_remat_acceptance_gate_time_budget_rejects(tmp_path):
    """The <=X% exec-time gate is enforced: with a budget below the
    baseline's own time, no remat rung can qualify and the baseline is
    kept — bytes never win unboundedly."""
    build, example = _scan_grad_build()
    d = dec.decide_remat(
        "test.scan_grad_tight", build, example, repeats=1,
        store_path=str(tmp_path / "d.json"), max_time_cost_frac=-0.9,
    )
    assert d.winner == "off" and not d.accepted


def test_remat_no_scan_keeps_baseline(tmp_path):
    """With nothing live across a scan, remat cannot strictly reduce peak
    bytes — the baseline survives the bytes objective."""
    def build(mode):
        return lambda x: jnp.sum(x * 2.0)

    d = dec.decide_remat(
        "test.no_scan", build, (jnp.arange(32.0),), repeats=1,
        store_path=str(tmp_path / "d.json"), max_time_cost_frac=10.0,
    )
    assert d.winner == "off" and not d.accepted


def test_scan_unroll_legacy_store_migration(tmp_path, monkeypatch):
    """Satellite: a pre-ISSUE-11 `scan_unroll.json` winner store is
    one-shot migrated into the unified cache under the new key schema —
    the old winner is served as a cache hit (no re-measurement), and the
    legacy file is gone."""
    from sheeprl_tpu.ops import scan as scan_mod

    def fn(xs):
        def step(c, x):
            return c + x, c + x

        _, ys = jax.lax.scan(step, jnp.float32(0.0), xs, unroll=scan_mod.scan_unroll())
        return ys

    xs = jnp.arange(12.0)
    # the legacy key schema: name|avals|jaxX|backend (ops/scan.py @ PR 9)
    legacy_key = (
        f"test.mig|float32[12]|jax{jax.__version__}|{jax.default_backend()}"
    )
    legacy = {
        legacy_key: {
            "probe": "test.mig", "winner": 4,
            "timings_s": {"1": 0.5, "4": 0.125},
            "compile_s": {"1": 0.01, "4": 0.02},
            "bit_exact": {"1": True, "4": True},
        }
    }
    with open(tmp_path / "scan_unroll.json", "w") as fh:
        json.dump(legacy, fh)
    store = str(tmp_path / "decisions.json")
    try:
        d = scan_mod.autotune_unroll(
            "test.mig", fn, (xs,), rungs=(1, 4), repeats=1,
            store_path=store, apply=True,
        )
        # served from the MIGRATED entry: no measurement, old winner kept
        assert d.source == "cache"
        assert d.winner == 4
        assert scan_mod.scan_unroll() == 4
        assert not (tmp_path / "scan_unroll.json").exists()
        with open(store) as fh:
            assert f"scan_unroll|{legacy_key}" in json.load(fh)
    finally:
        scan_mod.set_unroll(None)


def test_batch_chunk_probe_served_from_cache(tmp_path):
    """The decide_batch_chunk measurement (lowering + trial compile) is
    memoized in the unified cache: the second call never lowers or
    compiles, and the decision is re-derived from the cached counts."""
    lowers = []

    class CountingJit:
        def __init__(self, fn):
            self._jit = jax.jit(fn)
            self.__qualname__ = "test.counting_probe"
            self.__module__ = __name__

        def lower(self, *a):
            lowers.append(1)
            return self._jit.lower(*a)

    fn = CountingJit(lambda x: jnp.tanh(x) @ jnp.ones((8, 8)))
    example = (jnp.zeros((4, 8)),)
    store = str(tmp_path / "decisions.json")
    d1 = decide_batch_chunk(
        fn, example, batch=4, backend="cpu", store_path=store
    )
    assert lowers and "[probe cache]" not in d1.reason
    n = len(lowers)
    d2 = decide_batch_chunk(
        fn, example, batch=4, backend="cpu", store_path=store
    )
    assert len(lowers) == n  # zero lowering/trial compiles on the hit
    assert "[probe cache]" in d2.reason
    assert d2.chunk == d1.chunk
    assert d2.counts["convolutions"] == d1.counts["convolutions"]


def test_measured_probe_errors_not_cached(tmp_path):
    store = str(tmp_path / "decisions.json")
    rec, src = dec.measured_probe(
        "toy", "boom", (jnp.zeros(1),), lambda: {"error": "nope"},
        store_path=store,
    )
    assert rec["error"] == "nope" and src == "measured"
    rec2, src2 = dec.measured_probe(
        "toy", "boom", (jnp.zeros(1),), lambda: {"ok": 1}, store_path=store
    )
    assert src2 == "measured" and rec2 == {"ok": 1}  # retried, then cached
    _, src3 = dec.measured_probe(
        "toy", "boom", (jnp.zeros(1),), lambda: {"ok": 2}, store_path=store
    )
    assert src3 == "cache"


def test_remat_mode_and_checkpoint_body():
    assert dec.remat_mode(True) == "on" and dec.remat_mode(False) == "off"
    assert dec.remat_mode("on") == "on"
    assert dec.remat_mode("policy") == "policy"
    assert dec.remat_mode("auto") == "off"  # unresolved auto = baseline
    assert dec.remat_mode("junk") == "off"
    assert dec.remat_enabled("policy") and not dec.remat_enabled("off")
    from sheeprl_tpu.ops.scan import checkpoint_body

    step = lambda c, x: (c, x)  # noqa: E731
    assert checkpoint_body(step, "off") is step
    assert checkpoint_body(step, False) is step
    assert checkpoint_body(step, "auto") is step
    assert checkpoint_body(step, "on") is not step
    assert checkpoint_body(step, True) is not step
    assert checkpoint_body(step, "policy") is not step


# ---------------------------------------------------------------------------
# the remat receipt in the memory budget gate
# ---------------------------------------------------------------------------


def test_memory_budget_remat_receipt():
    """check_memory_budget gates the @remat/@scan twin pair: a remat train
    step whose peak stops undercutting its non-remat twin by the
    tolerance fails CI; a healthy reduction is a note."""
    from sheeprl_tpu.analysis.memory_check import check_memory_budget

    def entry(peak):
        return {"peak_bytes": peak, "aliases": [], "large_constants": []}

    good = {
        "memory": {
            "x@scan/train_step": entry(100),
            "x@remat/train_step": entry(70),
        }
    }
    failures, notes = check_memory_budget({"memory": dict(good["memory"])}, good)
    assert not failures
    assert any("remat peak" in n for n in notes)
    bad = {
        "memory": {
            "x@scan/train_step": entry(100),
            "x@remat/train_step": entry(95),
        }
    }
    failures, _ = check_memory_budget({"memory": dict(bad["memory"])}, bad)
    assert any("stopped buying its bytes" in f for f in failures)
    # only the train step is gated: other jits of the twins don't trip it
    other = {
        "memory": {
            "x@scan/player_step": entry(100),
            "x@remat/player_step": entry(100),
        }
    }
    failures, _ = check_memory_budget({"memory": dict(other["memory"])}, other)
    assert not failures


# ---------------------------------------------------------------------------
# sheepopt --propose golden
# ---------------------------------------------------------------------------


def _load_sheepopt():
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        "sheepopt_under_test", os.path.join(repo, "tools", "sheepopt.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_sheepopt_propose_diff_golden(tmp_path):
    """--propose over a fixture ledger: an undonated player_step emits
    the exact donating_jit diff for its known code site, a replicated
    comms entry emits the sharding proposal, and a scan buffer emits the
    --remat auto pointer."""
    so = _load_sheepopt()
    fixture = {
        "jits": {
            "dreamer_v2/player_step": {
                "donated": 0,
                "in_avals": [
                    "float32[4,256]", "float32[4,64]", "uint8[4,64,64,3]",
                ],
                "out_avals": ["float32[4,256]", "float32[4,64]"],
            },
        },
        "memory": {
            "dreamer_v2/player_step": {"aliases": [], "donated": 0},
            "dreamer_v2/train_step": {
                "scan_buffers": [
                    {"shape": "f32[4,256]", "bytes": 4096, "trip_count": 64}
                ],
            },
        },
        "comms": {
            "fix@mesh/train_step": {
                "replicated_inputs": ["f32[1024,1024]"],
                "replicated_bytes": 4194304,
                "mesh": {"data": 8},
            },
        },
    }
    with open(tmp_path / "dreamer_v2.json", "w") as fh:
        json.dump(fixture, fh)
    ledger = so.load_ledger(str(tmp_path))
    donations = so.propose_donations(ledger)
    assert len(donations) == 1
    p = donations[0]
    assert p["key"] == "dreamer_v2/player_step"
    assert p["open_matches"] == 2
    assert p["file"] == "sheeprl_tpu/algos/dreamer_v2/dreamer_v2.py"
    assert (
        "+    player_step = donating_jit(_player_step, donate_argnums=(1,))"
        in p["diff"]
    )
    shardings = so.propose_shardings(ledger)
    assert len(shardings) == 1
    assert shardings[0]["replicated_bytes"] == 4194304
    remat = so.propose_remat(ledger)
    assert any(
        r["key"] == "dreamer_v2/train_step" and "--remat auto" in r["advice"]
        for r in remat
    )
    # the skip-list honors justified refusals
    fixture["jits"]["ppo_recurrent/policy_step"] = {
        "donated": 0,
        "in_avals": ["float32[2,8]"],
        "out_avals": ["float32[2,8]"],
    }
    with open(tmp_path / "ppo_recurrent.json", "w") as fh:
        json.dump({"jits": {
            "ppo_recurrent/policy_step": fixture["jits"]["ppo_recurrent/policy_step"]
        }}, fh)
    donations = so.propose_donations(so.load_ledger(str(tmp_path)))
    assert not any(p["key"] == "ppo_recurrent/policy_step" for p in donations)


def test_sheepopt_propose_on_committed_ledger():
    """The real committed ledger parses and proposes without error — the
    CI artifact's contract (stdlib-only, advisory exit 0)."""
    so = _load_sheepopt()
    ledger = so.load_ledger(so.budget_dir())
    assert ledger["jits"]
    donations = so.propose_donations(ledger)
    remat = so.propose_remat(ledger)
    assert isinstance(donations, list) and isinstance(remat, list)
    # justified refusals never resurface
    assert not any(
        p["key"].startswith("ppo_recurrent") and p["key"].endswith("policy_step")
        for p in donations
    )
