"""The measured partition heuristic (compile/partition.py)."""

import json

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.compile import (
    chunk_for_budget,
    compiled_memory_stats,
    decide_batch_chunk,
    ledger_entry,
    lowered_op_counts,
    predicted_cpu_compile_seconds,
    sds,
)
from sheeprl_tpu.compile.partition import CPU_SECONDS_PER_CONV_ELEMENT


def test_chunk_for_budget_picks_largest_fitting_divisor():
    # 10 convs at CPU_SECONDS_PER_CONV_ELEMENT each: budget for 4 elements
    budget = predicted_cpu_compile_seconds(10, 4)
    assert chunk_for_budget(32, 10, budget) == 4
    assert chunk_for_budget(32, 10, budget * 8) == 0  # whole batch fits
    # prime batch: only 1 divides
    assert chunk_for_budget(31, 10, budget) == 1
    assert chunk_for_budget(1, 10, 0.0) == 0  # nothing to chunk


def test_predicted_scaling_is_linear_in_batch():
    one = predicted_cpu_compile_seconds(23, 1)
    assert predicted_cpu_compile_seconds(23, 8) == pytest.approx(8 * one)
    assert one == pytest.approx(23 * CPU_SECONDS_PER_CONV_ELEMENT)


@pytest.mark.timeout(120)
def test_lowered_op_counts_sees_convolutions():
    def convnet(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return jnp.mean(jnp.square(y))

    grad = jax.jit(jax.grad(convnet))
    counts = lowered_op_counts(
        grad, sds((3, 3, 4, 4), jnp.float32), sds((2, 8, 8, 4), jnp.float32)
    )
    # forward conv + the two gradient convs
    assert counts["convolutions"] >= 2
    assert counts["ops"] > 0


@pytest.mark.timeout(120)
def test_decide_batch_chunk_cpu_vs_other_backend():
    def convnet(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return jnp.mean(jnp.square(y))

    grad = jax.jit(jax.grad(convnet))
    example = (sds((3, 3, 4, 4), jnp.float32), sds((32, 8, 8, 4), jnp.float32))
    # a non-cpu backend never partitions, whatever the budget
    d = decide_batch_chunk(grad, example, batch=32, budget_s=0.001, backend="tpu")
    assert d.chunk == 0 and "non-cpu" in d.reason
    # cpu with a tiny budget must chunk; the decision records its inputs
    d = decide_batch_chunk(grad, example, batch=32, budget_s=0.001, backend="cpu")
    assert d.chunk == 1
    ev = d.as_event()
    assert ev["count_convolutions"] >= 2 and ev["chunk"] == 1
    # cpu with a huge budget keeps the batch whole
    d = decide_batch_chunk(grad, example, batch=32, budget_s=1e9, backend="cpu")
    assert d.chunk == 0


def test_decide_handles_unlowerable_fn():
    d = decide_batch_chunk(lambda x: x, (jnp.zeros(2),), batch=8, backend="cpu")
    assert d.chunk == 0 and "lowering failed" in d.reason


# ---------------------------------------------------------------------------
# ISSUE 10: the committed sheepmem ledger as the byte-driven decision input
# ---------------------------------------------------------------------------


def _write_ledger(tmp_path, spec, jit, temp_bytes, arg_bytes, convs=0):
    blob = {
        "memory": {
            f"{spec}/{jit}": {
                "temp_bytes": temp_bytes,
                "argument_bytes": arg_bytes,
                "peak_bytes": temp_bytes + arg_bytes,
            }
        },
    }
    if convs:
        blob["jits"] = {
            f"{spec}/{jit}": {
                "primitives": {"conv_general_dilated": convs},
            }
        }
    (tmp_path / f"{spec}.json").write_text(json.dumps(blob))


def test_ledger_entry_reads_committed_sections(tmp_path, monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_BUDGET_DIR", str(tmp_path))
    _write_ledger(tmp_path, "algox", "recon_step", 1000, 2000, convs=5)
    mem = ledger_entry("algox/recon_step")
    assert mem["temp_bytes"] == 1000 and mem["argument_bytes"] == 2000
    jits = ledger_entry("algox/recon_step", "jits")
    assert jits["primitives"]["conv_general_dilated"] == 5
    assert ledger_entry("algox/ghost") is None
    assert ledger_entry("missing_spec/x") is None


def test_decide_from_ledger_memory_scaled_by_argument_ratio(tmp_path, monkeypatch):
    """Ledger temp bytes measured at tiny avals decide the chunk for the
    live config without lowering or trial-compiling anything — the fn may
    even be unlowerable, proving no measurement ran."""
    monkeypatch.setenv("SHEEPRL_TPU_BUDGET_DIR", str(tmp_path))
    # capture avals: 1 KiB of args, 1 MiB of temps
    _write_ledger(tmp_path, "algox", "recon_step", 1 << 20, 1 << 10)
    # live config: 16x the argument bytes -> predicted temp 16 MiB
    example = (sds((4, 1024), jnp.float32),)  # 16 KiB
    d = decide_batch_chunk(
        None, example, batch=32, backend="cpu",
        mem_budget_bytes=4 << 20,  # 4 MiB budget: needs chunk <= batch/4
        ledger_key="algox/recon_step",
    )
    assert d.chunk == 8, d
    assert "ledger algox/recon_step" in d.reason
    assert d.counts["predicted_temp_bytes"] == 16 << 20
    # same ledger, roomy budget: whole batch stays fused, still no lowering
    d = decide_batch_chunk(
        None, example, batch=32, backend="cpu",
        mem_budget_bytes=1 << 30, ledger_key="algox/recon_step",
    )
    assert d.chunk == 0 and "within budget" in d.reason


def test_decide_from_ledger_conv_predictor_cross_validates(tmp_path, monkeypatch):
    """The committed conv histogram still guards superlinear-compile
    toolchains: the tighter of the byte and compile constraints wins."""
    monkeypatch.setenv("SHEEPRL_TPU_BUDGET_DIR", str(tmp_path))
    _write_ledger(tmp_path, "algox", "recon_step", 64, 1 << 10, convs=10)
    example = (sds((256,), jnp.float32),)
    budget = predicted_cpu_compile_seconds(10, 4)  # compile fits 4 elements
    d = decide_batch_chunk(
        None, example, batch=32, backend="cpu", budget_s=budget,
        mem_budget_bytes=1 << 30, ledger_key="algox/recon_step",
    )
    assert d.chunk == 4, d
    assert d.counts["convolutions"] == 10


def test_decide_without_ledger_entry_falls_back_to_measurement(tmp_path, monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_BUDGET_DIR", str(tmp_path))
    d = decide_batch_chunk(
        jax.jit(lambda x: x * 2.0), (sds((8, 4), jnp.float32),), batch=8,
        backend="cpu", ledger_key="nowhere/none",
    )
    # fell through to the trial-compile ladder (reason names the budget)
    assert "ledger" not in d.reason
    assert d.chunk == 0


def test_compiled_memory_stats_reads_executable():
    fn = jax.jit(lambda x: jnp.tanh(x) @ x)
    compiled = fn.lower(jnp.zeros((64, 64), jnp.float32)).compile()
    stats = compiled_memory_stats(compiled)
    assert stats is not None
    assert stats["argument_bytes"] == 64 * 64 * 4
    assert stats["peak_bytes"] >= stats["argument_bytes"] + stats["output_bytes"]
    assert compiled_memory_stats(object()) is None
