"""The measured partition heuristic (compile/partition.py)."""

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.compile import (
    chunk_for_budget,
    decide_batch_chunk,
    lowered_op_counts,
    predicted_cpu_compile_seconds,
    sds,
)
from sheeprl_tpu.compile.partition import CPU_SECONDS_PER_CONV_ELEMENT


def test_chunk_for_budget_picks_largest_fitting_divisor():
    # 10 convs at CPU_SECONDS_PER_CONV_ELEMENT each: budget for 4 elements
    budget = predicted_cpu_compile_seconds(10, 4)
    assert chunk_for_budget(32, 10, budget) == 4
    assert chunk_for_budget(32, 10, budget * 8) == 0  # whole batch fits
    # prime batch: only 1 divides
    assert chunk_for_budget(31, 10, budget) == 1
    assert chunk_for_budget(1, 10, 0.0) == 0  # nothing to chunk


def test_predicted_scaling_is_linear_in_batch():
    one = predicted_cpu_compile_seconds(23, 1)
    assert predicted_cpu_compile_seconds(23, 8) == pytest.approx(8 * one)
    assert one == pytest.approx(23 * CPU_SECONDS_PER_CONV_ELEMENT)


@pytest.mark.timeout(120)
def test_lowered_op_counts_sees_convolutions():
    def convnet(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return jnp.mean(jnp.square(y))

    grad = jax.jit(jax.grad(convnet))
    counts = lowered_op_counts(
        grad, sds((3, 3, 4, 4), jnp.float32), sds((2, 8, 8, 4), jnp.float32)
    )
    # forward conv + the two gradient convs
    assert counts["convolutions"] >= 2
    assert counts["ops"] > 0


@pytest.mark.timeout(120)
def test_decide_batch_chunk_cpu_vs_other_backend():
    def convnet(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return jnp.mean(jnp.square(y))

    grad = jax.jit(jax.grad(convnet))
    example = (sds((3, 3, 4, 4), jnp.float32), sds((32, 8, 8, 4), jnp.float32))
    # a non-cpu backend never partitions, whatever the budget
    d = decide_batch_chunk(grad, example, batch=32, budget_s=0.001, backend="tpu")
    assert d.chunk == 0 and "non-cpu" in d.reason
    # cpu with a tiny budget must chunk; the decision records its inputs
    d = decide_batch_chunk(grad, example, batch=32, budget_s=0.001, backend="cpu")
    assert d.chunk == 1
    ev = d.as_event()
    assert ev["count_convolutions"] >= 2 and ev["chunk"] == 1
    # cpu with a huge budget keeps the batch whole
    d = decide_batch_chunk(grad, example, batch=32, budget_s=1e9, backend="cpu")
    assert d.chunk == 0


def test_decide_handles_unlowerable_fn():
    d = decide_batch_chunk(lambda x: x, (jnp.zeros(2),), batch=8, backend="cpu")
    assert d.chunk == 0 and "lowering failed" in d.reason
