"""`--warm_compile on` vs `off` end-to-end parity: one coupled and one
decoupled algo dry run, checkpoint trees compared BITWISE. The warm path
dispatches AOT executables built from the same lowering as the cold jits,
so not a single parameter bit may differ."""

import glob
import json
import os

import jax
import numpy as np
import pytest

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.utils.checkpoint import load_checkpoint
from sheeprl_tpu.utils.registry import tasks


def _ckpt_tree(run_dir):
    paths = sorted(glob.glob(os.path.join(run_dir, "checkpoints", "ckpt_*")))
    paths = [p for p in paths if os.path.isdir(p)]
    assert paths, f"no checkpoint under {run_dir}"
    return load_checkpoint(paths[-1])


def _assert_bit_exact(tree_a, tree_b):
    la = jax.tree_util.tree_leaves(tree_a)
    lb = jax.tree_util.tree_leaves(tree_b)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _warm_summary(run_dir):
    with open(os.path.join(run_dir, "telemetry.jsonl")) as fh:
        for line in fh:
            ev = json.loads(line)
            if ev.get("event") == "compile.summary":
                return ev["entries"]
    return {}


@pytest.mark.timeout(600)
def test_sac_warm_on_matches_off_bit_exact(tmp_path):
    argv = [
        "--env_id", "Pendulum-v1", "--dry_run", "--num_envs", "1",
        "--num_devices", "1", "--sync_env",
        "--per_rank_batch_size", "4", "--buffer_size", "8",
        "--learning_starts", "0", "--gradient_steps", "1",
        "--actor_hidden_size", "16", "--critic_hidden_size", "16",
        "--root_dir", str(tmp_path),
    ]
    for mode in ("off", "on"):
        tasks["sac"](argv + ["--run_name", mode, "--warm_compile", mode])
    _assert_bit_exact(
        _ckpt_tree(str(tmp_path / "off")), _ckpt_tree(str(tmp_path / "on"))
    )
    # the warm run must actually have gone through the AOT path
    summ = _warm_summary(str(tmp_path / "on"))
    ts = summ.get("train_step", {})
    assert ts.get("compiled") and ts.get("aot_calls", 0) >= 1, summ
    assert ts.get("fallbacks", 0) == 0, summ


@pytest.mark.timeout(600)
def test_ppo_decoupled_warm_on_matches_off_bit_exact(tmp_path):
    argv = [
        "--env_id", "CartPole-v1", "--dry_run", "--num_envs", "1",
        "--sync_env", "--rollout_steps", "8", "--per_rank_batch_size", "4",
        "--root_dir", str(tmp_path),
    ]
    for mode in ("off", "on"):
        tasks["ppo_decoupled"](argv + ["--run_name", mode, "--warm_compile", mode])
    _assert_bit_exact(
        _ckpt_tree(str(tmp_path / "off")), _ckpt_tree(str(tmp_path / "on"))
    )
    summ = _warm_summary(str(tmp_path / "on"))
    ts = summ.get("train_step", {})
    assert ts.get("compiled") and ts.get("aot_calls", 0) >= 1, summ
    assert ts.get("fallbacks", 0) == 0, summ
