"""CompilePlan receipts (ISSUE 5 tentpole): AOT-vs-direct bit-exactness,
warm-start barrier ordering, cache hit/miss counting, and the fallback
safety net."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.compile import CompilePlan, avals_of, sds


class _Args:
    warm_compile = "on"


class _Off:
    warm_compile = "off"


def _sac_step():
    """A real (small) registered train step: SAC's scan-over-gradient-steps
    update — representative math (grads, optimizers, EMA gate)."""
    from sheeprl_tpu.algos.sac.agent import SACAgent
    from sheeprl_tpu.algos.sac.args import SACArgs
    from sheeprl_tpu.algos.sac.sac import TrainState, make_optimizers, make_train_step

    args = SACArgs(actor_hidden_size=16, critic_hidden_size=16)
    key = jax.random.PRNGKey(0)
    agent = SACAgent.init(
        key, 3, 1, num_critics=args.num_critics,
        actor_hidden_size=16, critic_hidden_size=16,
        action_low=np.array([-1.0]), action_high=np.array([1.0]),
        alpha=args.alpha, tau=args.tau,
    )
    qf_optim, actor_optim, alpha_optim = make_optimizers(args)
    state = TrainState(
        agent=agent,
        qf_opt=qf_optim.init(agent.critics),
        actor_opt=actor_optim.init(agent.actor),
        alpha_opt=alpha_optim.init(agent.log_alpha),
    )
    train_step = make_train_step(args, qf_optim, actor_optim, alpha_optim)
    g, b = 2, 4
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    data = {
        "observations": jax.random.normal(ks[0], (g, b, 3), jnp.float32),
        "next_observations": jax.random.normal(ks[1], (g, b, 3), jnp.float32),
        "actions": jax.random.uniform(ks[2], (g, b, 1), jnp.float32, -1, 1),
        "rewards": jax.random.normal(ks[3], (g, b, 1), jnp.float32),
        "dones": jnp.zeros((g, b, 1), jnp.float32),
    }
    return train_step, state, data, jax.random.PRNGKey(2)


@pytest.mark.timeout(300)
def test_aot_vs_direct_bit_exact():
    """The equivalence guarantee: the AOT executable built from captured
    avals produces bitwise-identical outputs to the cold jit path."""
    train_step, state, data, key = _sac_step()
    flag = jnp.asarray(True)
    # cold/direct path first (its own jit cache entry)
    s_direct, m_direct = train_step(state, data, key, flag)

    plan = CompilePlan.from_args(_Args())
    wrapped = plan.register(
        "train_step", train_step,
        example=lambda: (state, data, key, flag), role="update",
    )
    plan.start()
    assert plan.wait(timeout=240), "warm compile did not finish"
    s_aot, m_aot = wrapped(state, data, key, flag)

    st = plan.stats()["entries"]["train_step"]
    assert st["compiled"] and st["error"] is None
    assert st["aot_calls"] == 1 and st["fallbacks"] == 0
    for a, b in zip(
        jax.tree_util.tree_leaves(s_direct), jax.tree_util.tree_leaves(s_aot)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m_direct:
        np.testing.assert_array_equal(
            np.asarray(m_direct[k]), np.asarray(m_aot[k])
        )
    assert plan.time_to_first_update_seconds is not None
    plan.close()


@pytest.mark.timeout(120)
def test_barrier_blocks_update_until_compile_done():
    """Ordering: a call into a registered jit must not execute before its
    background compile completes — the wrapper IS the barrier."""
    order = []

    def slow_fn(x):
        # runs at TRACE time, i.e. inside the background compile worker
        time.sleep(0.8)
        order.append("compiled")
        return x + 1

    fn = jax.jit(slow_fn)
    plan = CompilePlan(enabled=True)
    wrapped = plan.register("slow", fn, example=lambda: (sds((2,), jnp.float32),))
    plan.start()
    t0 = time.perf_counter()
    out = wrapped(jnp.zeros(2, jnp.float32))
    waited = time.perf_counter() - t0
    order.append("executed")
    np.testing.assert_array_equal(np.asarray(out), np.ones(2, np.float32))
    assert order == ["compiled", "executed"]
    e = plan._entries[0]
    assert e.done.is_set() and e.barrier_wait_s > 0.0
    assert waited >= 0.3  # genuinely blocked on the in-flight compile
    plan.close()


@pytest.mark.timeout(120)
def test_aval_mismatch_falls_back_to_cold_path():
    """A registered spec that drifts from the live call must never change
    results — the wrapper falls back to the original jit for good."""
    fn = jax.jit(lambda x: x * 2)
    plan = CompilePlan(enabled=True)
    wrapped = plan.register(
        "wrong", fn, example=lambda: (sds((3,), jnp.float32),)
    )
    plan.start()
    assert plan.wait(timeout=60)
    # live call uses a DIFFERENT shape than the captured spec
    out = wrapped(jnp.ones(5, jnp.float32))
    np.testing.assert_array_equal(np.asarray(out), 2 * np.ones(5, np.float32))
    e = plan._entries[0]
    assert e.fallbacks == 1 and e.executable is None
    # subsequent calls stay on the cold path without re-raising
    wrapped(jnp.ones(5, jnp.float32))
    assert e.fallbacks == 1
    plan.close()


@pytest.mark.timeout(120)
def test_disabled_plan_is_passthrough():
    fn = jax.jit(lambda x: x + 1)
    plan = CompilePlan.from_args(_Off())
    assert plan.register("f", fn, example=lambda: (sds((2,), jnp.float32),)) is fn
    wrapped = plan.register("g", fn, example=None, role="update")
    out = wrapped(jnp.zeros(2, jnp.float32))
    np.testing.assert_array_equal(np.asarray(out), np.ones(2, np.float32))
    # the role wrapper still stamps time_to_first_update on the cold path
    assert plan.time_to_first_update_seconds is not None
    plan.close()


@pytest.mark.timeout(120)
def test_unlowerable_fn_degrades_gracefully():
    """A fn without .lower (e.g. a checkify wrapper or python loop) is
    tracked for timing only; start() must not hang on it."""

    def plain(x):
        return x - 1

    plan = CompilePlan(enabled=True)
    wrapped = plan.register("plain", plain, example=lambda: (jnp.zeros(2),))
    plan.start()
    assert plan.wait(timeout=10)
    out = wrapped(jnp.ones(2, jnp.float32))
    np.testing.assert_array_equal(np.asarray(out), np.zeros(2, np.float32))
    assert plan.stats()["entries"]["plain"]["error"] == "not AOT-lowerable"
    plan.close()


def test_avals_of_commitment_rules():
    """Committed arrays keep sharding; uncommitted arrays and non-arrays
    pass through sharding-free (the decoupled-mesh lowering fix)."""
    dev = jax.devices()[0]
    committed = jax.device_put(jnp.zeros((2, 2)), dev)
    uncommitted = jnp.zeros((3,))
    spec, passthrough = avals_of((committed, 0.5))[0], avals_of((committed, 0.5))[1]
    assert spec.sharding is not None
    assert passthrough == 0.5
    u = avals_of((uncommitted,))[0]
    assert u.sharding is None and u.shape == (3,)


@pytest.mark.timeout(120)
def test_gauges_shape():
    fn = jax.jit(lambda x: x + 1)
    plan = CompilePlan(enabled=True)
    wrapped = plan.register("f", fn, example=lambda: (sds((2,), jnp.float32),))
    plan.start()
    assert plan.wait(timeout=60)
    wrapped(jnp.zeros(2, jnp.float32))
    g = plan.gauges()
    assert g["Compile/warm_enabled"] == 1.0
    assert g["Compile/plan_compiled"] == 1.0
    assert g["Compile/aot_calls"] == 1.0
    assert "Compile/exe/f_seconds" in g
    plan.close()


@pytest.mark.timeout(120)
def test_warmup_mode_populates_dispatch_cache(monkeypatch):
    """SHEEPRL_TPU_WARM_MODE=warmup: the worker calls the jit once on
    synthesized dummies; the executable lands in the jit's own dispatch
    cache and results stay bit-exact (it IS the cold-path executable)."""
    monkeypatch.setenv("SHEEPRL_TPU_WARM_MODE", "warmup")
    calls = []

    def f(x):
        calls.append(x.shape)  # trace-time: once for warmup, never again
        return x * 3

    fn = jax.jit(f)
    plan = CompilePlan(enabled=True)
    wrapped = plan.register("f", fn, example=lambda: (sds((4,), jnp.float32),))
    plan.start()
    assert plan.wait(timeout=60)
    st = plan.stats()["entries"]["f"]
    assert st["warmed"] and st["compiled"] and st["error"] is None
    out = wrapped(jnp.ones(4, jnp.float32))
    np.testing.assert_array_equal(np.asarray(out), 3 * np.ones(4, np.float32))
    # the real call hit the dispatch cache: no second trace
    assert calls == [(4,)]
    plan.close()


@pytest.mark.timeout(60)
def test_wait_timeout_returns_false():
    plan = CompilePlan(enabled=True)
    e_fn = jax.jit(lambda x: x)
    plan.register("never", e_fn, example=lambda: (sds((2,), jnp.float32),))
    # start() NOT called: entries pending forever
    t = threading.Thread(target=lambda: None, name="test-noop", daemon=True)
    t.start(); t.join()
    assert plan.wait(timeout=0.1) is False
    plan.close()
