"""The unified cache-arming path + persistent-cache hit/miss counting."""

import os

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.compile import CacheStats, MIN_COMPILE_SECS, arm_compile_cache


@pytest.fixture
def restore_cache_config():
    """Snapshot/restore the three jax config knobs the helper touches, plus
    the env vars, so tests never leak cache state into the suite."""
    saved = {
        "dir": jax.config.jax_compilation_cache_dir,
        "min_secs": jax.config.jax_persistent_cache_min_compile_time_secs,
        "min_bytes": jax.config.jax_persistent_cache_min_entry_size_bytes,
        "env": {
            k: os.environ.get(k)
            for k in (
                "JAX_COMPILATION_CACHE_DIR",
                "SHEEPRL_TPU_COMPILE_CACHE",
                "SHEEPRL_TPU_XLA_CACHE",
            )
        },
    }
    yield
    jax.config.update("jax_compilation_cache_dir", saved["dir"])
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", saved["min_secs"]
    )
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes", saved["min_bytes"]
    )
    for k, v in saved["env"].items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_one_threshold_for_everyone(tmp_path, restore_cache_config):
    """The satellite fix: every arming path lands the SAME compile-time
    floor (the old distributed_setup re-arm used a silent 10 s)."""
    path = arm_compile_cache(str(tmp_path / "c1"))
    assert path == str(tmp_path / "c1")
    assert jax.config.jax_compilation_cache_dir == path
    assert jax.config.jax_persistent_cache_min_compile_time_secs == MIN_COMPILE_SECS
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == path

    # distributed_setup routes through the same helper with the same floor
    os.environ["SHEEPRL_TPU_COMPILE_CACHE"] = str(tmp_path / "c2")
    from sheeprl_tpu.parallel.mesh import distributed_setup

    distributed_setup()
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "c2")
    assert jax.config.jax_persistent_cache_min_compile_time_secs == MIN_COMPILE_SECS


def test_resolution_order_and_disable(tmp_path, restore_cache_config):
    os.environ["SHEEPRL_TPU_COMPILE_CACHE"] = str(tmp_path / "envvar")
    assert arm_compile_cache() == str(tmp_path / "envvar")
    # explicit path wins over the env var
    assert arm_compile_cache(str(tmp_path / "explicit")) == str(
        tmp_path / "explicit"
    )
    os.environ["SHEEPRL_TPU_XLA_CACHE"] = "0"
    assert arm_compile_cache(str(tmp_path / "off")) is None


@pytest.mark.timeout(120)
def test_cache_hit_miss_counting(tmp_path, restore_cache_config):
    """Compile the same program twice (fresh jit objects, so no in-memory
    dispatch-cache reuse): first is a persistent-cache miss, second a hit.
    min_compile_secs=0 lets the tiny test graph qualify for caching."""
    arm_compile_cache(str(tmp_path / "cache"), min_compile_secs=0.0)
    stats = CacheStats().attach()
    if not stats.supported:
        pytest.skip("jax.monitoring unavailable")

    def build():
        # non-trivial enough that XLA actually compiles a module
        return jax.jit(lambda x: jnp.tanh(x @ x.T).sum())

    x = jnp.ones((16, 16), jnp.float32)
    before = stats.snapshot()
    build()(x).block_until_ready()
    mid = stats.snapshot()
    build()(x).block_until_ready()
    after = stats.snapshot()
    stats.detach()
    assert mid["misses"] - before["misses"] >= 1
    assert mid["hits"] == before["hits"]
    assert after["hits"] - mid["hits"] >= 1
