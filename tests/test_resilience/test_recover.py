"""Recovery actuators (ISSUE 12): batch poisoning, the donation-safe
nonfinite skip select, host-side flag handling, and rollback restore."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu import resilience
from sheeprl_tpu.resilience.recover import SKIP_FLAG
from sheeprl_tpu.telemetry import Telemetry
from sheeprl_tpu.utils.jit import donating_jit


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_FAULTS", raising=False)
    resilience.reset_plan()
    yield
    resilience.reset_plan()


# ---------------------------------------------------------------------------
# poison_batch
# ---------------------------------------------------------------------------


def test_poison_batch_targets_reward_leaf_for_nan_loss():
    resilience.arm_faults("nan.loss@4")
    data = {
        "observations": jnp.ones((8, 3)),
        "rewards": jnp.ones((8, 1)),
    }
    out = resilience.poison_batch(dict(data), 3)
    assert not np.isnan(np.asarray(out["rewards"])).any()  # not yet
    out = resilience.poison_batch(dict(data), 4)
    assert np.isnan(np.asarray(out["rewards"])).sum() == 1
    assert not np.isnan(np.asarray(out["observations"])).any()
    # exactly-once: the next step is clean again
    out = resilience.poison_batch(dict(data), 4)
    assert not np.isnan(np.asarray(out["rewards"])).any()


def test_poison_batch_targets_obs_leaf_for_nan_grad_numpy():
    resilience.arm_faults("nan.grad@1")
    data = {"observations": np.ones((4, 2), np.float32), "rewards": np.ones((4, 1), np.float32)}
    out = resilience.poison_batch(data, 1)
    assert np.isnan(out["observations"]).sum() == 1
    assert not np.isnan(out["rewards"]).any()
    assert not np.isnan(data["observations"]).any()  # input not mutated


# ---------------------------------------------------------------------------
# guard_nonfinite: the donation-safe skip select
# ---------------------------------------------------------------------------


def _toy_step(state, batch, lr):
    """A train-step-shaped body: sgd on a quadratic; metrics carry the loss."""
    params, opt = state

    def loss_fn(p):
        return jnp.mean((p @ batch.T) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new_params = params - lr * grads
    return (new_params, opt + 1), {"Loss/total": loss}


def test_guard_warn_is_identity():
    assert resilience.guard_nonfinite(_toy_step, "warn") is _toy_step


def test_guard_rejects_unknown_policy():
    with pytest.raises(ValueError, match="on_nonfinite"):
        resilience.guard_nonfinite(_toy_step, "explode")


def test_skip_select_keeps_old_state_on_poisoned_batch_under_donation():
    guarded = donating_jit(
        resilience.guard_nonfinite(_toy_step, "skip"), donate_argnums=(0,)
    )
    params0 = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    state = (params0, jnp.int32(0))
    clean = jnp.ones((5, 2))
    poisoned = clean.at[0, 0].set(jnp.nan)

    state1, m1 = guarded(state, clean, jnp.float32(0.1))
    assert float(m1[SKIP_FLAG]) == 0.0
    good_params = np.asarray(state1[0])

    state2, m2 = guarded(state1, poisoned, jnp.float32(0.1))
    assert float(m2[SKIP_FLAG]) == 1.0
    # the poisoned update was dropped: params unchanged THROUGH the donation
    np.testing.assert_array_equal(np.asarray(state2[0]), good_params)
    assert int(state2[1]) == 1  # the in-jit counter select also held

    state3, m3 = guarded(state2, clean, jnp.float32(0.1))
    assert float(m3[SKIP_FLAG]) == 0.0
    assert np.isfinite(np.asarray(state3[0])).all()


def test_skip_is_bit_exact_vs_unguarded_on_clean_batches():
    clean = jnp.arange(10.0).reshape(5, 2)
    state = (jnp.asarray([[0.5, -0.25], [1.0, 2.0]]), jnp.int32(0))
    plain_out, _ = jax.jit(_toy_step)(state, clean, jnp.float32(0.05))
    guarded = jax.jit(resilience.guard_nonfinite(_toy_step, "skip"))
    guard_out, metrics = guarded(state, clean, jnp.float32(0.05))
    np.testing.assert_array_equal(np.asarray(plain_out[0]), np.asarray(guard_out[0]))
    assert float(metrics[SKIP_FLAG]) == 0.0


def test_update_skipped_pops_flag_and_records_one_update_lagged(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit")
    try:
        metrics = {"Loss/total": jnp.float32(jnp.nan), SKIP_FLAG: jnp.float32(1.0)}
        # first call only queues the async pull (no previous flag to read)
        assert resilience.update_skipped(metrics, "skip") is False
        assert SKIP_FLAG not in metrics
        # the next update's call reads the landed flag of the previous one
        clean = {"Loss/total": jnp.float32(1.0), SKIP_FLAG: jnp.float32(0.0)}
        assert resilience.update_skipped(clean, "skip") is True
        # a flag-less metrics dict (policy 'warn') is always a no-op
        assert resilience.update_skipped({"Loss/total": 1.0}, "skip") is False
    finally:
        telem.close()
    events = [
        json.loads(l)
        for l in (tmp_path / "telemetry.jsonl").read_text().strip().splitlines()
    ]
    rec = [e for e in events if e.get("event") == "fault.recovered"]
    assert rec and rec[0]["action"] == "updates_skipped"
    assert resilience.gauges().get("Fault/updates_skipped") == 1.0


# ---------------------------------------------------------------------------
# rollback
# ---------------------------------------------------------------------------


def test_rollback_restores_last_good_checkpoint(tmp_path):
    from sheeprl_tpu.utils.checkpoint import save_checkpoint

    good = {"params": jnp.asarray([1.0, 2.0]), "step": 3}
    path = str(tmp_path / "checkpoints" / "ckpt_3")
    save_checkpoint(path, good, block=True)  # registers via note_checkpoint
    assert resilience.rollback.__module__  # sanity: exported

    restored = resilience.rollback(
        {"params": jnp.zeros(2), "step": 0}, step=5
    )
    assert restored is not None
    np.testing.assert_array_equal(np.asarray(restored["params"]), [1.0, 2.0])
    assert int(restored["step"]) == 3
    assert resilience.gauges().get("Fault/rollbacks") == 1.0


def test_rollback_without_checkpoint_returns_none(tmp_path):
    # fresh process state: clear the registry explicitly
    from sheeprl_tpu.resilience import recover

    recover._LAST_GOOD.clear()
    assert resilience.rollback({"x": jnp.zeros(1)}, step=1) is None
    assert resilience.gauges().get("Fault/rollback_unavailable") == 1.0


def test_optax_state_survives_skip_select():
    """The select must hold for realistic opt states (adam moments, counts)."""
    opt = optax.adam(1e-2)
    params = jnp.ones((3,))
    state = (params, opt.init(params))

    def body(st, batch):
        p, o = st
        grads = jax.grad(lambda q: jnp.sum((q * batch) ** 2))(p)
        updates, o2 = opt.update(grads, o, p)
        return (optax.apply_updates(p, updates), o2), {"Loss/total": jnp.sum(grads)}

    guarded = jax.jit(resilience.guard_nonfinite(body, "skip"))
    st1, m1 = guarded(state, jnp.ones((3,)))
    st2, m2 = guarded(st1, jnp.full((3,), jnp.nan))
    assert float(m2[SKIP_FLAG]) == 1.0
    for a, b in zip(jax.tree_util.tree_leaves(st1), jax.tree_util.tree_leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
