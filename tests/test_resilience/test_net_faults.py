"""Network/peer fault sites injected inside the FLK1 framing layer
(ISSUE 16 tentpole): parse, per-frame counting, each site's blast radius,
and — critically — that the injection layer is INERT with no clauses
armed (the frame path stays byte-identical)."""

import socket
import time

import pytest

from sheeprl_tpu.flock import wire
from sheeprl_tpu.resilience import inject


@pytest.fixture(autouse=True)
def _fresh_plan(monkeypatch):
    monkeypatch.delenv(inject.ENV_VAR, raising=False)
    monkeypatch.delenv(inject.SEED_VAR, raising=False)
    inject.reset_plan()
    wire._partition_until = 0.0
    yield
    inject.reset_plan()
    wire._partition_until = 0.0


def _arm(monkeypatch, text):
    monkeypatch.setenv(inject.ENV_VAR, text)
    inject.reset_plan()
    return inject.get_plan()


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


KIND = wire.HEARTBEAT


def test_new_sites_parse_and_describe():
    plan = inject.FaultPlan.parse(
        "net.drop@3,net.delay@2:250,net.corrupt@1,net.partition@4:1.5,"
        "peer.crash@7"
    )
    sites = {s.site for s in plan.specs}
    assert sites == {
        "net.drop", "net.delay", "net.corrupt", "net.partition", "peer.crash"
    }
    assert {s.site: s.param for s in plan.specs}["net.delay"] == 250.0
    for site in wire.NET_SITES + ("peer.crash",):
        assert site in inject.FAULT_SITES


def test_unarmed_layer_is_inert():
    a, b = _pair()
    try:
        for i in range(5):
            wire.send_frame(a, KIND, b"x" * (i + 1))
        for i in range(5):
            kind, payload = wire.recv_frame(b)
            assert kind == KIND and payload == b"x" * (i + 1)
        # no counters advanced, nothing fired, no partition window opened
        assert inject.counters() == {}
        assert wire.partition_remaining() == 0.0
    finally:
        a.close()
        b.close()


def test_net_drop_loses_exactly_frame_k(monkeypatch):
    _arm(monkeypatch, "net.drop@2")
    a, b = _pair()
    try:
        for tag in (b"one", b"two", b"three"):
            wire.send_frame(a, KIND, tag)
        a.close()
        got = []
        while True:
            frame = wire.recv_frame(b)
            if frame is None:
                break
            got.append(frame[1])
        assert got == [b"one", b"three"]  # frame 2 silently gone
        assert inject.counters().get("Fault/net.drop") == 1.0
        assert inject.counters().get("Fault/injected") == 1.0
    finally:
        b.close()


def test_net_delay_sleeps_param_ms(monkeypatch):
    _arm(monkeypatch, "net.delay@1:200")
    a, b = _pair()
    try:
        t0 = time.monotonic()
        wire.send_frame(a, KIND, b"slow")
        assert time.monotonic() - t0 >= 0.15
        kind, payload = wire.recv_frame(b)
        assert kind == KIND and payload == b"slow"  # delayed, not lost
        # subsequent sends are back to full speed (exactly-once)
        t0 = time.monotonic()
        wire.send_frame(a, KIND, b"fast")
        assert time.monotonic() - t0 < 0.1
    finally:
        a.close()
        b.close()


def test_net_corrupt_garbles_magic_receiver_raises(monkeypatch):
    _arm(monkeypatch, "net.corrupt@1")
    a, b = _pair()
    try:
        wire.send_frame(a, KIND, b"payload")
        with pytest.raises(wire.FrameError, match="bad frame magic"):
            wire.recv_frame(b)
        # the receiver kills that one connection (the stream is desynced
        # past the garbled header); the SENDER's socket stays healthy —
        # its next send does not raise
        wire.send_frame(a, KIND, b"after")
        assert inject.counters().get("Fault/net.corrupt") == 1.0
    finally:
        a.close()
        b.close()


def test_net_partition_kills_connection_and_blocks_reconnect(monkeypatch):
    _arm(monkeypatch, "net.partition@1:0.4")
    a, b = _pair()
    try:
        with pytest.raises(ConnectionError):
            wire.send_frame(a, KIND, b"never lands")
        assert wire.recv_frame(b) is None  # both directions dead
        assert wire.partition_remaining() > 0.0
        # reconnects are refused for the whole window...
        with pytest.raises(ConnectionRefusedError, match="net.partition"):
            wire.connect("tcp:127.0.0.1:1", timeout=0.1)
        time.sleep(0.5)
        # ...then the gate opens (the dial itself may still fail, but for
        # the real reason, not the injected one)
        assert wire.partition_remaining() == 0.0
        assert inject.counters().get("Fault/net.partition") == 1.0
    finally:
        a.close()
        b.close()


def test_net_sites_share_one_per_send_counter(monkeypatch):
    """Every armed net site counts the SAME frame sends: drop@1 and
    corrupt@2 hit the first and second frames of this process."""
    _arm(monkeypatch, "net.drop@1,net.corrupt@2")
    a, b = _pair()
    try:
        wire.send_frame(a, KIND, b"first")   # dropped
        wire.send_frame(a, KIND, b"second")  # corrupted
        with pytest.raises(wire.FrameError):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_peer_crash_fires_at_declared_loop_step(monkeypatch):
    # fire the plan directly — NEVER through guard.tick in-process (the
    # site's action is SIGKILL)
    plan = _arm(monkeypatch, "peer.crash@5")
    assert plan.fire_at("peer.crash", 4) is None
    spec = plan.fire_at("peer.crash", 5)
    assert spec is not None and spec.site == "peer.crash"
    assert plan.fire_at("peer.crash", 5) is None  # exactly-once
    assert inject.counters().get("Fault/injected") == 1.0


def test_partition_window_seeded_range_is_deterministic(monkeypatch):
    monkeypatch.setenv(inject.SEED_VAR, "11")
    p1 = inject.FaultPlan.parse("net.partition@10-50:2", seed=11)
    p2 = inject.FaultPlan.parse("net.partition@10-50:2", seed=11)
    assert p1.specs[0].step == p2.specs[0].step
