"""End-to-end recovery receipts on live mains (ISSUE 12): a poisoned
gradient survived via --on_nonfinite skip and rollback, an injected env.step
crash ridden through by a full SAC run, checkpoint-write retry, and the
decoupled weight-transfer deadline."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu import resilience


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_FAULTS", raising=False)
    resilience.reset_plan()
    yield
    resilience.reset_plan()


def _events(log_dir):
    with open(os.path.join(log_dir, "telemetry.jsonl")) as fh:
        return [json.loads(l) for l in fh if l.strip()]


def _run_sac(tmp_path, run_name, extra):
    from sheeprl_tpu.algos.sac.sac import main

    main(
        [
            "--num_envs", "1", "--sync_env", "--total_steps", "10",
            "--learning_starts", "2", "--per_rank_batch_size", "16",
            "--gradient_steps", "1", "--checkpoint_every", "4",
            "--root_dir", str(tmp_path), "--run_name", run_name,
            "--test_episodes", "0", "--seed", "5",
            *extra,
        ]
    )
    return str(tmp_path / run_name)


@pytest.mark.timeout(300)
def test_sac_survives_poisoned_grad_with_skip(tmp_path):
    log_dir = _run_sac(
        tmp_path, "skip",
        ["--faults", "nan.grad@6", "--on_nonfinite", "skip"],
    )
    ev = _events(log_dir)
    names = [e["event"] for e in ev]
    assert "fault.injected" in names, names
    rec = [e for e in ev if e["event"] == "fault.recovered"]
    assert any(r["action"] == "updates_skipped" for r in rec)
    assert "end" in names  # the run completed despite the poison
    # final params are finite: the poisoned update never reached the tree
    from sheeprl_tpu.utils.checkpoint import latest_checkpoint, load_checkpoint
    import jax

    ckpt = latest_checkpoint(os.path.join(log_dir, "checkpoints"))
    restored = load_checkpoint(ckpt)
    for leaf in jax.tree_util.tree_leaves(restored):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f":
            assert np.isfinite(arr).all()


@pytest.mark.timeout(300)
def test_sac_rollback_restores_last_good_checkpoint(tmp_path):
    log_dir = _run_sac(
        tmp_path, "rollback",
        ["--faults", "nan.grad@6", "--on_nonfinite", "rollback"],
    )
    ev = _events(log_dir)
    rec = [e for e in ev if e["event"] == "fault.recovered"]
    actions = {r["action"] for r in rec}
    assert "updates_skipped" in actions
    assert "rollbacks" in actions, actions
    roll = next(r for r in rec if r["action"] == "rollbacks")
    assert roll["checkpoint"].endswith("ckpt_4")  # the last-good one
    assert any(e["event"] == "end" for e in ev)


@pytest.mark.timeout(300)
def test_sac_rides_through_env_step_crash(tmp_path):
    log_dir = _run_sac(tmp_path, "envcrash", ["--faults", "env.step@4"])
    ev = _events(log_dir)
    assert any(
        e["event"] == "fault.injected" and e["site"] == "env.step" for e in ev
    )
    rec = [e for e in ev if e["event"] == "fault.recovered"]
    assert any(r["action"] == "env_restarts" for r in rec)
    assert any(e["event"] == "end" for e in ev)
    # the Fault gauges rode the metric pipeline into the JSONL log events
    logged = [e for e in ev if e["event"] == "log"]
    assert any(
        e["metrics"].get("Fault/env_restarts", 0) >= 1.0 for e in logged
    )


@pytest.mark.timeout(300)
def test_checkpoint_write_fault_is_retried(tmp_path):
    log_dir = _run_sac(tmp_path, "ckptfault", ["--faults", "ckpt.write@1"])
    ev = _events(log_dir)
    assert any(
        e["event"] == "fault.injected" and e["site"] == "ckpt.write" for e in ev
    )
    assert any(e["event"] == "checkpoint.error" for e in ev)
    rec = [e for e in ev if e["event"] == "fault.recovered"]
    assert any(r["action"] == "ckpt_retried" for r in rec)
    # the retried save committed: checkpoints exist and validate
    from sheeprl_tpu.utils.checkpoint import list_checkpoints

    assert list_checkpoints(os.path.join(log_dir, "checkpoints"))


def test_transfer_deadline_drops_stalled_weights():
    """Decoupled graceful degradation: a stalled weight transfer past the
    deadline returns None (the player keeps stale weights) and counts into
    Fault/transfer_timeouts."""
    from sheeprl_tpu.parallel import make_decoupled_meshes

    resilience.arm_faults("transfer.stall@2:0.2")
    meshes = make_decoupled_meshes(2)
    tree = {"w": jnp.ones((4, 4))}
    out1 = meshes.to_player(tree, deadline_s=0.1)
    assert out1 is not None  # transfer 1: no stall declared
    out2 = meshes.to_player(tree, deadline_s=0.1)  # stalls 0.2s > 0.1s
    assert out2 is None
    assert resilience.gauges().get("Fault/transfer_timeouts") == 1.0
    g = meshes.telemetry_gauges()
    assert g["Decoupled/weight_queue_depth"] == 0.0  # dropped, not pending
    out3 = meshes.to_player(tree, deadline_s=0.1)
    assert out3 is not None  # exactly-once: the link is healthy again
    # no deadline -> a stall can never drop the shipment
    resilience.arm_faults("transfer.stall@1:0.05")
    resilience.reset_plan()
    resilience.arm_faults("transfer.stall@1:0.05")
    assert meshes.to_player(tree, deadline_s=float("inf")) is not None
