"""Crash-safe training receipts (ISSUE 12): the bit-exact SIGTERM resume
twin (jax-env PPO in subprocesses), the resumable rc contract, auto-resume
resolution, corrupt-checkpoint fallback, and the SAC sampler-state restore.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from sheeprl_tpu import resilience
from sheeprl_tpu.resilience.guard import RC_PREEMPTED

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_FAULTS", raising=False)
    resilience.reset_plan()
    yield
    resilience.reset_plan()


def _events(log_dir):
    path = os.path.join(log_dir, "telemetry.jsonl")
    with open(path) as fh:
        return [json.loads(l) for l in fh if l.strip()]


def _run_ppo(extra, timeout=240):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO,
        PALLAS_AXON_POOL_IPS="",
    )
    env.pop("SHEEPRL_TPU_FAULTS", None)
    # single-device children: the pytest process's 8-virtual-device XLA_FLAGS
    # would force num_envs % 8 == 0 on this tiny receipt
    env.pop("XLA_FLAGS", None)
    base = [
        sys.executable, "-m", "sheeprl_tpu", "ppo",
        "--env_backend", "jax", "--num_envs", "2", "--rollout_steps", "8",
        "--total_steps", "96", "--checkpoint_every", "2", "--seed", "3",
        "--test_episodes", "0",
    ]
    return subprocess.run(
        base + extra, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


@pytest.mark.timeout(420)
def test_sigterm_resume_is_bit_exact_vs_uninterrupted_twin(tmp_path):
    """THE resume receipt: a jax-env PPO run killed by an injected SIGTERM
    at update 3 and resumed with --resume auto must land on the SAME final
    checkpoint — params, opt-state, loop PRNG and collector ring state — as
    its uninterrupted twin, bit for bit."""
    twin_a = str(tmp_path / "a")
    twin_b = str(tmp_path / "b")
    a = _run_ppo(["--root_dir", twin_a, "--run_name", "x"])
    assert a.returncode == 0, a.stderr[-2000:]

    b = _run_ppo(["--root_dir", twin_b, "--run_name", "x", "--faults", "sigterm@3"])
    assert b.returncode == RC_PREEMPTED, (b.returncode, b.stderr[-2000:])
    ev = _events(os.path.join(twin_b, "x"))
    names = [e["event"] for e in ev]
    assert "fault.injected" in names and "preempt.signal" in names
    preempt = [e for e in ev if e["event"] == "preempt"]
    assert preempt and preempt[0]["rc"] == RC_PREEMPTED
    assert preempt[0]["step"] == 3
    # the grace checkpoint of the in-flight step committed before exit
    assert os.path.isdir(os.path.join(twin_b, "x", "checkpoints", "ckpt_3"))

    c = _run_ppo(["--root_dir", twin_b, "--run_name", "x", "--resume", "auto"])
    assert c.returncode == 0, c.stderr[-2000:]
    ev = _events(os.path.join(twin_b, "x"))
    resume = [e for e in ev if e["event"] == "resume"]
    assert resume and resume[-1]["checkpoint"].endswith("ckpt_3")

    from sheeprl_tpu.utils.checkpoint import load_checkpoint
    import jax

    final_a = load_checkpoint(os.path.join(twin_a, "x", "checkpoints", "ckpt_6"))
    final_c = load_checkpoint(os.path.join(twin_b, "x", "checkpoints", "ckpt_6"))
    leaves_a = jax.tree_util.tree_leaves(final_a)
    leaves_c = jax.tree_util.tree_leaves(final_c)
    assert len(leaves_a) == len(leaves_c)
    for x, y in zip(leaves_a, leaves_c):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # deep state: loop PRNG + collector carry (env-state "ring head")
    ra = np.load(os.path.join(twin_a, "x", "checkpoints", "ckpt_6.resume.npz"))
    rc = np.load(os.path.join(twin_b, "x", "checkpoints", "ckpt_6.resume.npz"))
    assert sorted(ra.files) == sorted(rc.files)
    for k in ra.files:
        np.testing.assert_array_equal(ra[k], rc[k])


@pytest.mark.timeout(420)
def test_sigkill_has_no_grace_but_auto_resume_recovers(tmp_path):
    """The no-grace site: SIGKILL at step k leaves no grace checkpoint and
    no clean telemetry tail — auto-resume must recover from the last
    PERIODIC checkpoint and run to completion anyway."""
    from sheeprl_tpu.utils.checkpoint import list_checkpoints

    root = str(tmp_path / "k")
    # SIGKILL can land while an ASYNC periodic save is still an
    # orbax-checkpoint-tmp dir (observed: only ckpt_2's tmp dir on disk when
    # killing at step 4 on a busy box) — that save is simply LOST, which is
    # the point of validating commit markers on resume. ckpt_4's save begins
    # by draining ckpt_2's (one outstanding save), so by the kill at step 6
    # at least ckpt_2 is durably committed; ckpt_4 may or may not be.
    b = _run_ppo(["--root_dir", root, "--run_name", "x", "--faults", "sigkill@6"])
    assert b.returncode in (-9, 137), b.returncode
    ckdir = os.path.join(root, "x", "checkpoints")
    valid = list_checkpoints(ckdir)
    assert valid, os.listdir(ckdir)
    assert all(v.endswith(("ckpt_2", "ckpt_4")) for v in valid), valid

    c = _run_ppo(["--root_dir", root, "--run_name", "x", "--resume", "auto"])
    assert c.returncode == 0, c.stderr[-2000:]
    ev = _events(os.path.join(root, "x"))
    resume = [e for e in ev if e["event"] == "resume"]
    assert resume and resume[-1]["checkpoint"] == valid[0]
    assert os.path.isdir(os.path.join(root, "x", "checkpoints", "ckpt_6"))


# ---------------------------------------------------------------------------
# in-process receipts (no subprocess cost)
# ---------------------------------------------------------------------------


def test_resolve_resume_auto_picks_newest_valid_and_explicit_path(tmp_path):
    import jax.numpy as jnp

    from sheeprl_tpu.utils.checkpoint import save_checkpoint

    class _Args:
        resume = "auto"
        eval_only = False
        checkpoint_path = None
        root_dir = str(tmp_path)
        run_name = "r"
        env_id = "CartPole-v1"

    ckdir = tmp_path / "r" / "checkpoints"

    class _A:
        def as_dict(self):
            return {"seed": 0}

    save_checkpoint(str(ckdir / "ckpt_2"), {"x": jnp.ones(1)}, args=_A(), block=True)
    save_checkpoint(str(ckdir / "ckpt_5"), {"x": jnp.ones(1)}, args=_A(), block=True)
    # a partial write: directory without the orbax commit marker
    (ckdir / "ckpt_9").mkdir()
    args = _Args()
    found = resilience.resolve_resume(args, "ppo")
    assert found and found.endswith("ckpt_5")
    assert args.checkpoint_path == found
    # corrupt ckpt_9 was skipped and is NOT in the fallback list
    assert resilience.next_fallback(found).endswith("ckpt_2")
    assert resilience.next_fallback(resilience.next_fallback(found)) is None

    # explicit path mode
    args2 = _Args()
    args2.resume = str(ckdir / "ckpt_2")
    args2.checkpoint_path = None
    assert resilience.resolve_resume(args2, "ppo").endswith("ckpt_2")
    # unknown path rejects loudly
    args3 = _Args()
    args3.resume = str(tmp_path / "nope")
    args3.checkpoint_path = None
    with pytest.raises(ValueError, match="not a checkpoint directory"):
        resilience.resolve_resume(args3, "ppo")


def test_restore_falls_back_past_corrupt_arrays(tmp_path):
    """A checkpoint can pass the marker check yet hold truncated array
    bytes; load_checkpoint must fall back to the previous valid candidate
    of the auto-resume run instead of dying."""
    import jax.numpy as jnp

    from sheeprl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    class _A:
        def as_dict(self):
            return {"seed": 0}

    ckdir = tmp_path / "r" / "checkpoints"
    save_checkpoint(str(ckdir / "ckpt_1"), {"x": jnp.arange(4.0)}, args=_A(), block=True)
    save_checkpoint(str(ckdir / "ckpt_2"), {"x": jnp.arange(4.0) * 2}, args=_A(), block=True)
    # corrupt ckpt_2's array payload (markers intact)
    for root, _dirs, files in os.walk(ckdir / "ckpt_2"):
        for f in files:
            if "METADATA" not in f and "manifest" not in f.lower():
                p = os.path.join(root, f)
                with open(p, "wb") as fh:
                    fh.write(b"garbage")

    class _Args:
        resume = "auto"
        eval_only = False
        checkpoint_path = None
        root_dir = str(tmp_path)
        run_name = "r"
        env_id = "CartPole-v1"

    args = _Args()
    found = resilience.resolve_resume(args, "ppo")
    assert found.endswith("ckpt_2")  # structurally valid, picked first
    restored = load_checkpoint(found)
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.arange(4.0))


def test_sac_resume_restores_sampler_and_buffer_state(tmp_path):
    """The SAC satellite: a resumed run's replay sampler continues the EXACT
    random stream — ring contents, positions, device key and numpy rng all
    round-trip through the checkpoint."""
    import jax.numpy as jnp

    from sheeprl_tpu.data import ReplayBuffer

    rb = ReplayBuffer(16, 2, storage="host", obs_keys=("observations",), seed=9)
    rng = np.random.default_rng(0)
    for _ in range(12):
        rb.add(
            {
                "observations": rng.normal(size=(1, 2, 3)).astype(np.float32),
                "actions": rng.normal(size=(1, 2, 1)).astype(np.float32),
                "rewards": rng.normal(size=(1, 2, 1)).astype(np.float32),
                "dones": np.zeros((1, 2, 1), np.float32),
            }
        )
    rb.sample(4)  # advance the sampler stream before checkpointing
    path = str(tmp_path / "buf.npz")
    rb.save(path)
    expected = [rb.sample(6) for _ in range(3)]  # the stream a live run draws

    rb2 = ReplayBuffer(16, 2, storage="host", obs_keys=("observations",), seed=9)
    rb2.load(path)
    assert rb2.pos == rb.pos and rb2.full == rb.full
    for want in expected:
        got = rb2.sample(6)
        for k in want:
            np.testing.assert_array_equal(np.asarray(want[k]), np.asarray(got[k]))


def test_device_buffer_sampler_state_roundtrips(tmp_path):
    from sheeprl_tpu.data import ReplayBuffer

    rb = ReplayBuffer(8, 1, storage="device", obs_keys=("observations",), seed=4)
    for _ in range(6):
        rb.add({"observations": np.ones((1, 1, 2), np.float32)})
    rb.sample(2)
    path = str(tmp_path / "buf.npz")
    rb.save(path)
    want = np.asarray(rb.sample(3)["observations"])
    rb2 = ReplayBuffer(8, 1, storage="device", obs_keys=("observations",), seed=4)
    rb2.load(path)
    np.testing.assert_array_equal(np.asarray(rb2.sample(3)["observations"]), want)
