"""Deterministic fault injection (ISSUE 12): plan parsing, seeded range
resolution, exactly-once firing per site, telemetry receipts, and the
process-global arming path the mains use."""

import json

import pytest

from sheeprl_tpu import resilience
from sheeprl_tpu.resilience.inject import ENV_VAR, FaultPlan
from sheeprl_tpu.telemetry import Telemetry


@pytest.fixture(autouse=True)
def _fresh_plan(monkeypatch):
    monkeypatch.delenv(ENV_VAR, raising=False)
    resilience.reset_plan()
    yield
    resilience.reset_plan()


def _events(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    if not path.exists():
        return []
    return [json.loads(l) for l in path.read_text().strip().splitlines()]


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------


def test_parse_sites_steps_and_params():
    plan = FaultPlan.parse("env.step@12, nan.grad@3, transfer.stall@2:3.5")
    assert [(s.site, s.step, s.param) for s in plan.specs] == [
        ("env.step", 12, None),
        ("nan.grad", 3, None),
        ("transfer.stall", 2, 3.5),
    ]


def test_parse_rejects_unknown_site_and_bad_clause():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan.parse("warp.core@3")
    with pytest.raises(ValueError, match="site@step"):
        FaultPlan.parse("sigterm")


def test_parse_empty_and_none_are_empty_plans():
    assert FaultPlan.parse(None).specs == []
    assert FaultPlan.parse(" ").specs == []


def test_seeded_range_is_deterministic_and_site_keyed():
    a = FaultPlan.parse("env.step@10-20,sigterm@10-20", seed=7)
    b = FaultPlan.parse("env.step@10-20,sigterm@10-20", seed=7)
    c = FaultPlan.parse("env.step@10-20,sigterm@10-20", seed=8)
    assert [s.step for s in a.specs] == [s.step for s in b.specs]
    assert all(10 <= s.step <= 20 for s in a.specs)
    # site-keyed: the two sites decorrelate under one seed (they could
    # coincide by chance for SOME seed, not for this one — pinned receipt)
    steps_a = {s.site: s.step for s in a.specs}
    steps_c = {s.site: s.step for s in c.specs}
    assert steps_a != steps_c


# ---------------------------------------------------------------------------
# firing semantics
# ---------------------------------------------------------------------------


def test_fire_at_is_exactly_once():
    plan = FaultPlan.parse("sigterm@5")
    assert plan.fire_at("sigterm", 4) is None
    spec = plan.fire_at("sigterm", 5)
    assert spec is not None and spec.step == 5
    assert plan.fire_at("sigterm", 5) is None  # fired specs leave the plan
    assert plan.pending() == []


def test_fire_next_counts_per_site_invocations():
    plan = FaultPlan.parse("ckpt.write@2,env.step@1")
    assert plan.fire_next("ckpt.write") is None  # invocation 1
    assert plan.fire_next("env.step") is not None  # env.step's own counter
    assert plan.fire_next("ckpt.write") is not None  # invocation 2
    assert plan.fire_next("ckpt.write") is None


def test_every_site_has_deterministic_seeded_replay():
    """The acceptance-criteria sweep: EVERY declared fault site resolves a
    seeded range to the same (site, step) on every parse — the deterministic
    half of each site's receipt (recovery halves live in test_envwrap /
    test_recover / test_integration / test_resume)."""
    from sheeprl_tpu.resilience.inject import FAULT_SITES

    text = ",".join(f"{site}@5-50" for site in FAULT_SITES)
    a = FaultPlan.parse(text, seed=13)
    b = FaultPlan.parse(text, seed=13)
    assert [(s.site, s.step) for s in a.specs] == [
        (s.site, s.step) for s in b.specs
    ]
    assert {s.site for s in a.specs} == set(FAULT_SITES)
    assert all(5 <= s.step <= 50 for s in a.specs)
    # and each fires exactly once at its resolved step
    for spec in list(a.specs):
        assert a.fire_at(spec.site, spec.step) is not None
        assert a.fire_at(spec.site, spec.step) is None


def test_deterministic_replay_same_plan_same_firing_sequence():
    """The CI-reproducibility receipt: two identical plans observe identical
    (site, step) firing sequences over the same call trace."""

    def trace(plan):
        fired = []
        for step in range(1, 8):
            for site in ("sigterm", "nan.grad"):
                if plan.fire_at(site, step):
                    fired.append((site, step))
            if plan.fire_next("env.step"):
                fired.append(("env.step", step))
        return fired

    text = "sigterm@6,nan.grad@3,env.step@4"
    assert trace(FaultPlan.parse(text)) == trace(FaultPlan.parse(text))
    assert trace(FaultPlan.parse(text)) == [
        ("nan.grad", 3),
        ("env.step", 4),
        ("sigterm", 6),
    ]


# ---------------------------------------------------------------------------
# telemetry + global plan
# ---------------------------------------------------------------------------


def test_firing_emits_fault_injected_event_and_counts(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit")
    try:
        plan = FaultPlan.parse("nan.loss@2")
        plan.fire_at("nan.loss", 2)
    finally:
        telem.close()
    events = [e for e in _events(tmp_path) if e.get("event") == "fault.injected"]
    assert len(events) == 1
    assert events[0]["site"] == "nan.loss" and events[0]["step"] == 2
    assert resilience.gauges().get("Fault/injected") == 1.0


def test_arm_faults_exports_env_and_installs_global_plan(monkeypatch):
    plan = resilience.arm_faults("sigkill@9")
    import os

    assert os.environ[ENV_VAR] == "sigkill@9"
    assert resilience.get_plan() is plan
    assert [s.site for s in plan.specs] == ["sigkill"]


def test_note_recovery_counts_and_emits(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit")
    try:
        resilience.note_recovery("env.step", "env_restarts", attempt=1)
    finally:
        telem.close()
    events = [e for e in _events(tmp_path) if e.get("event") == "fault.recovered"]
    assert len(events) == 1 and events[0]["action"] == "env_restarts"
    assert resilience.gauges().get("Fault/env_restarts") == 1.0
