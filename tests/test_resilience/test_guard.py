"""Preemption grace + crash scope (ISSUE 12): signal handling, the per-step
fault tick, @crashsafe's distinct resumable rc, and the crashed-run
telemetry guarantee."""

import json
import os
import signal

import pytest

from sheeprl_tpu import resilience
from sheeprl_tpu.resilience.guard import RC_PREEMPTED, Preempted, RunGuard
from sheeprl_tpu.telemetry import Telemetry


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_FAULTS", raising=False)
    resilience.reset_plan()
    yield
    RunGuard.uninstall()
    resilience.reset_plan()


def _events(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    if not path.exists():
        return []
    return [json.loads(l) for l in path.read_text().strip().splitlines()]


def test_sigterm_sets_preempted_flag_and_restores_handlers():
    prev = signal.getsignal(signal.SIGTERM)
    guard = RunGuard.install()
    try:
        assert not guard.preempted
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.preempted
        assert guard.preempt_signal == "SIGTERM"
    finally:
        RunGuard.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_tick_fires_injected_sigterm_at_declared_step():
    resilience.arm_faults("sigterm@3")
    guard = RunGuard.install()
    try:
        assert guard.tick(1) is False
        assert guard.tick(2) is False
        assert guard.tick(3) is True  # injected signal, caught by the guard
        assert guard.preempted
    finally:
        RunGuard.uninstall()


def test_preempt_signal_emits_event_and_counts(tmp_path):
    telem = Telemetry(str(tmp_path), rank=0, algo="unit")
    guard = RunGuard.install(telem)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        os.kill(os.getpid(), signal.SIGTERM)  # duplicate: counted once
    finally:
        RunGuard.uninstall()
        telem.close()
    events = [e for e in _events(tmp_path) if e.get("event") == "preempt.signal"]
    assert len(events) == 1 and events[0]["signal"] == "SIGTERM"
    assert resilience.gauges().get("Fault/preemptions") == 1.0


def test_crashsafe_maps_preempted_to_resumable_rc(tmp_path):
    telem_holder = {}

    @resilience.crashsafe
    def fake_main():
        telem_holder["t"] = Telemetry(str(tmp_path), rank=0, algo="unit")
        RunGuard.install(telem_holder["t"])
        raise Preempted(7, "SIGTERM")

    with pytest.raises(SystemExit) as exc_info:
        fake_main()
    assert exc_info.value.code == RC_PREEMPTED
    events = _events(tmp_path)
    preempt = [e for e in events if e.get("event") == "preempt"]
    assert preempt and preempt[0]["step"] == 7
    assert preempt[0]["rc"] == RC_PREEMPTED
    # telemetry was closed (end event present), handlers restored
    assert any(e.get("event") == "end" for e in events)
    assert RunGuard._current is None


def test_crashsafe_records_crash_event_and_reraises(tmp_path):
    @resilience.crashsafe
    def fake_main():
        Telemetry(str(tmp_path), rank=0, algo="unit")
        raise RuntimeError("boom at step 3")

    with pytest.raises(RuntimeError, match="boom"):
        fake_main()
    events = _events(tmp_path)
    crash = [e for e in events if e.get("event") == "crash"]
    assert crash and "boom at step 3" in crash[0]["error"]
    # the scope tore telemetry down WITHOUT a clean `end` record
    assert not any(e.get("event") == "end" for e in events)


def test_crashsafe_passes_capture_complete_through(tmp_path):
    from sheeprl_tpu.compile.plan import CaptureComplete

    holder = {}

    @resilience.crashsafe
    def fake_main():
        holder["t"] = Telemetry(str(tmp_path), rank=0, algo="unit")
        raise CaptureComplete(None)

    try:
        with pytest.raises(CaptureComplete):
            fake_main()
    finally:
        holder["t"].close()
    # capture aborts are by-design: no crash record
    assert not any(e.get("event") == "crash" for e in _events(tmp_path))


def test_crashsafe_success_path_is_transparent():
    @resilience.crashsafe
    def fake_main(x):
        return x * 2

    assert fake_main(21) == 42
