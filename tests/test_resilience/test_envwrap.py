"""Env restart machinery (ISSUE 12): deterministic env.step injection inside
the retry scope, bounded restarts with truncated-boundary semantics, and the
vector-runner integration every main inherits through utils/env.py."""

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu import resilience
from sheeprl_tpu.resilience.envwrap import RestartingEnv, resilient_thunk


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_FAULTS", raising=False)
    monkeypatch.delenv("SHEEPRL_TPU_ENV_RESTARTS", raising=False)
    resilience.reset_plan()
    yield
    resilience.reset_plan()


class _CountingEnv(gym.Env):
    """Tiny env recording construction and step counts; optionally crashes."""

    observation_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
    action_space = gym.spaces.Discrete(2)
    builds = 0

    def __init__(self, crash_at: int | None = None):
        type(self).builds += 1
        self._crash_at = crash_at
        self._t = 0

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return np.zeros(2, np.float32), {}

    def step(self, action):
        self._t += 1
        if self._crash_at is not None and self._t == self._crash_at:
            raise OSError("simulated emulator crash")
        return np.full(2, self._t, np.float32), 1.0, False, False, {}


def test_injected_env_step_fault_recovers_with_truncated_boundary():
    _CountingEnv.builds = 0
    resilience.arm_faults("env.step@3")
    env = RestartingEnv(lambda: _CountingEnv(), backoff_s=0.0)
    env.reset()
    env.step(0)
    env.step(0)
    obs, reward, term, trunc, info = env.step(0)  # 3rd call: injected fault
    assert _CountingEnv.builds == 2  # restarted once
    assert trunc and not term and reward == 0.0
    assert info.get("env_restarted") is True
    np.testing.assert_array_equal(obs, np.zeros(2, np.float32))  # reset obs
    # the plan fired exactly once: the next steps are clean
    obs, _, _, trunc, info = env.step(0)
    assert not trunc and "env_restarted" not in info
    assert resilience.gauges().get("Fault/env_restarts") == 1.0


def test_real_exception_recovers_and_consecutive_bound_reraises(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_ENV_RESTARTS", "2")
    # every rebuilt env crashes on its FIRST step: failures stay consecutive
    env = RestartingEnv(lambda: _CountingEnv(crash_at=1), backoff_s=0.0)
    env.reset()
    _, _, _, trunc, info = env.step(0)  # failure 1 -> restart
    assert trunc and info["env_restarted"]
    _, _, _, trunc, _ = env.step(0)  # failure 2 -> restart (at the bound)
    assert trunc
    with pytest.raises(RuntimeError, match="consecutive"):
        env.step(0)  # failure 3 exceeds SHEEPRL_TPU_ENV_RESTARTS=2


def test_success_resets_the_consecutive_failure_counter(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_ENV_RESTARTS", "1")
    env = RestartingEnv(lambda: _CountingEnv(crash_at=2), backoff_s=0.0)
    env.reset()
    for _ in range(4):
        # step 1 succeeds (resets the counter), step 2 crashes -> restart;
        # with the bound at 1, only CONSECUTIVE failures would re-raise
        env.step(0)


def test_resilient_thunk_wraps_and_preserves_spaces():
    build = resilient_thunk(lambda: _CountingEnv())
    env = build()
    assert isinstance(env, RestartingEnv)
    assert env.observation_space.shape == (2,)
    assert env.action_space.n == 2
    env.close()


def test_sync_vector_env_rides_through_env_fault():
    """The integration receipt: a SyncVectorEnv over restarting envs keeps
    stepping through an injected fault — the loop above it never notices."""
    from sheeprl_tpu.envs.vector import SyncVectorEnv

    resilience.arm_faults("env.step@2")
    venv = SyncVectorEnv([resilient_thunk(lambda: _CountingEnv()) for _ in range(2)])
    venv.reset(seed=0)
    for _ in range(4):
        obs, rewards, terms, truncs, infos = venv.step([0, 0])
        assert obs.shape == (2, 2)
    assert any("env_restarted" in i for i in infos) or resilience.gauges().get(
        "Fault/env_restarts"
    ) == 1.0
