"""Per-rule positive/negative fixtures for sheeplint (ISSUE 3 satellite):
every rule must fire on its seeded violation, stay silent on the idiomatic
equivalent, and honor the `# sheeplint: disable=` suppression forms."""

import json
import os
import subprocess
import sys
import textwrap

from sheeprl_tpu.analysis.linter import lint_source
from sheeprl_tpu.analysis.rules import RULES, rule_ids

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def ids(src: str, path: str = "fixture.py") -> list:
    return [v.rule.id for v in lint_source(textwrap.dedent(src), path)]


def lines(src: str, path: str = "fixture.py") -> dict:
    return {
        v.line: v.rule.id for v in lint_source(textwrap.dedent(src), path)
    }


# ---------------------------------------------------------------------------
# SL001 — bare donating jit
# ---------------------------------------------------------------------------


def test_sl001_positive_direct_and_partial():
    src = """
    import jax
    from functools import partial

    f = jax.jit(lambda x: x, donate_argnums=(0,))

    @partial(jax.jit, donate_argnums=0)
    def g(x):
        return x
    """
    assert ids(src) == ["SL001", "SL001"]


def test_sl001_negative_donating_jit_and_plain_jit():
    src = """
    import jax
    from functools import partial
    from sheeprl_tpu.utils.jit import donating_jit

    f = donating_jit(lambda x: x, donate_argnums=(0,))

    @partial(donating_jit, donate_argnums=0)
    def g(x):
        return x

    h = jax.jit(lambda x: x)
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# SL002 — host syncs inside traced bodies
# ---------------------------------------------------------------------------


def test_sl002_positive_forms():
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    @jax.jit
    def f(x):
        a = x.item()
        b = float(x * 2)
        c = np.asarray(x)
        return a + b

    def body(carry, t):
        q = int(carry)
        return carry, q

    def outer(xs):
        return lax.scan(body, 0.0, xs)
    """
    assert ids(src) == ["SL002"] * 4


def test_sl002_negative_shapes_literals_host_side():
    src = """
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        n = float(x.shape[0])
        m = int(len(x.shape))
        c = np.array([1, 2, 3])
        return x * n * m + c.sum()

    def host(x):
        return float(x), np.asarray(x), x.item()
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# SL003 — Python control flow on tracers
# ---------------------------------------------------------------------------


def test_sl003_positive_if_while():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        if jnp.any(x > 0):
            x = x + 1
        while (x < 0).all():
            x = x + 1
        return x
    """
    assert ids(src) == ["SL003", "SL003"]


def test_sl003_negative_static_branching_and_lax():
    src = """
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def f(x, flag: bool):
        if flag:
            x = x + 1
        return lax.cond(x.sum() > 0, lambda v: v, lambda v: -v, x)

    def host(x):
        if jnp.any(x > 0):
            return 1
        return 0
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# SL004 — recompile hazards
# ---------------------------------------------------------------------------


def test_sl004_positive_jit_in_loop():
    src = """
    import jax

    def step_loop(x):
        for i in range(10):
            f = jax.jit(lambda y: y + i)
            x = f(x)
        return x
    """
    assert ids(src) == ["SL004"]


def test_sl004_positive_unhashable_static_default():
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnums=(1,))
    def f(x, cfg=[1, 2]):
        return x
    """
    assert ids(src) == ["SL004"]


def test_sl004_negative_hoisted():
    src = """
    import jax
    from functools import partial

    f = jax.jit(lambda y: y + 1)

    @partial(jax.jit, static_argnums=(1,))
    def g(x, cfg=(1, 2)):
        return x

    def step_loop(x):
        for _ in range(10):
            x = f(x)
        return x
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# SL005 — unregistered dataclass pytrees
# ---------------------------------------------------------------------------


def test_sl005_positive_unregistered():
    src = """
    import dataclasses
    import jax

    @dataclasses.dataclass
    class State:
        x: object

    @jax.jit
    def step(s: State):
        return State(s.x + 1)
    """
    assert ids(src) == ["SL005"]


def test_sl005_negatives():
    src = """
    import dataclasses
    import jax
    from jax import tree_util
    from sheeprl_tpu import nn

    @dataclasses.dataclass
    class Registered:
        x: object
    tree_util.register_dataclass(Registered, data_fields=("x",), meta_fields=())

    @tree_util.register_pytree_node_class
    @dataclasses.dataclass
    class Decorated:
        x: object

    class ModuleChild(nn.Module):
        x: object

    @dataclasses.dataclass
    class HostOnlyConfig:
        lr: float

    @jax.jit
    def step(a: Registered, b: Decorated, c: ModuleChild):
        return a, b, c
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# SL006 — unconstrained sharded jits in parallel/
# ---------------------------------------------------------------------------

_SL006_SRC = """
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

@jax.jit
def bad(x, mesh):
    s = NamedSharding(mesh, P("data"))
    return x * 2

@jax.jit
def good(x, mesh):
    s = NamedSharding(mesh, P("data"))
    return jax.lax.with_sharding_constraint(x, s)
"""


def test_sl006_scoped_to_parallel_paths():
    assert ids(_SL006_SRC, "sheeprl_tpu/parallel/topo.py") == ["SL006"]
    # same code outside parallel/ is not in scope for the rule
    assert ids(_SL006_SRC, "sheeprl_tpu/ops/topo.py") == []


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def test_suppression_same_line_and_standalone():
    src = """
    import jax

    @jax.jit
    def f(x):
        a = x.item()  # sheeplint: disable=SL002 — audited sync
        # sheeplint: disable=SL002 — the justification of this one
        # runs over several comment lines before the code line
        b = x.item()
        c = x.item()
        return a + b + c
    """
    assert ids(src) == ["SL002"]  # only the unsuppressed third sync


def test_suppression_file_level_and_all():
    src = """
    # sheeplint: disable-file=SL002
    import jax

    @jax.jit
    def f(x):
        if __import__("jax.numpy").any(x):
            pass
        return x.item()
    """
    assert "SL002" not in ids(src)
    src_all = """
    import jax

    @jax.jit
    def f(x):
        return x.item()  # sheeplint: disable=all
    """
    assert ids(src_all) == []


def test_suppressed_rule_ids_must_match():
    src = """
    import jax

    @jax.jit
    def f(x):
        return x.item()  # sheeplint: disable=SL001
    """
    assert ids(src) == ["SL002"]  # wrong id does not suppress


# ---------------------------------------------------------------------------
# SL007 — blocking host sync inside a hot-loop body
# ---------------------------------------------------------------------------


def test_sl007_positive_named_hot_loop():
    src = """
    import jax
    import numpy as np

    def one_cycle(state, metrics, arr):
        x = float(jax.device_get(metrics["loss"]))
        y = np.asarray(arr)
        z = arr.item()
        jax.block_until_ready(state)
        return x, y, z
    """
    assert ids(src) == ["SL007"] * 5  # device_get + float + asarray + item + block


def test_sl007_positive_marker_comment():
    src = """
    import numpy as np

    # sheeplint: hotloop
    def tight_inner(arr):
        return np.asarray(arr)
    """
    assert ids(src) == ["SL007"]


def test_sl007_negative_cold_function_and_shapes():
    src = """
    import numpy as np

    def setup(arr):
        return np.asarray(arr)  # not a hot-loop body: no finding

    def one_step(batch):
        n = int(batch.shape[0])  # shape access, not a device pull
        return n
    """
    assert ids(src) == []


def test_sl007_defers_to_sl002_inside_jit_bodies():
    src = """
    import jax

    def one_cycle(x):
        @jax.jit
        def inner(v):
            return float(v)  # traced: SL002's jurisdiction

        return inner(x)
    """
    assert ids(src) == ["SL002"]


def test_sl007_suppression_with_justification():
    src = """
    import jax

    def one_cycle(metrics):
        # sheeplint: disable=SL007 — deliberate timing fence
        return float(jax.device_get(metrics))
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# Catalog + CLI contract
# ---------------------------------------------------------------------------


def test_sl008_positive_callback_in_hot_scan_body():
    src = """
    import jax

    @jax.jit
    def rollout(carry):
        def one_cycle(c, _):
            jax.debug.print("c = {}", c)
            return c + 1, c
        return jax.lax.scan(one_cycle, carry, None, length=8)
    """
    assert ids(src) == ["SL008"]


def test_sl008_positive_io_callback_marker():
    src = """
    import jax
    from jax.experimental import io_callback

    @jax.jit
    # sheeplint: hotloop
    def hot_inner(c):
        io_callback(print, None, c)
        return c
    """
    assert ids(src) == ["SL008"]


def test_sl008_negative_cold_jit_and_suppression():
    src = """
    import jax

    @jax.jit
    def diagnostics(c):
        jax.debug.print("c = {}", c)  # cold jit: sheepcheck SC002's turf
        return c

    @jax.jit
    def one_update(c):
        jax.debug.print("c = {}", c)  # sheeplint: disable=SL008 — debug build only
        return c
    """
    assert ids(src) == []


def test_sl009_positive_literal_to_jit_bound_names():
    src = """
    import jax

    train_step = jax.jit(lambda s, lr: s * lr)
    jits = {}
    jits["gae"] = plan.register("gae", train_step)

    def loop(state):
        a = train_step(state, 3e-4)
        b = jits["gae"](state, 0.95)
        return a, b
    """
    assert ids(src) == ["SL009", "SL009"]


def test_sl009_negative_wrapped_scalars_and_plain_calls():
    src = """
    import jax
    import jax.numpy as jnp

    train_step = jax.jit(lambda s, lr: s * lr)

    def loop(state, helper):
        good = train_step(state, jnp.float32(3e-4))
        other = helper(state, 3e-4)  # not jit-bound: no finding
        flag = train_step(state, True)  # bools are static flags
        return good, other, flag
    """
    assert ids(src) == []


def test_sl010_positive_unsharded_batch_puts():
    src = """
    import jax
    import jax.numpy as jnp
    from sheeprl_tpu.parallel import make_mesh

    def main(rb, sampler):
        mesh = make_mesh(8)
        data = {k: jnp.asarray(v) for k, v in sampler(rb).sample(64).items()}
        rows = jax.device_put(rb["observations"])
        return data, rows
    """
    assert ids(src) == ["SL010", "SL010"]


def test_sl010_negative_sharded_idiom_and_no_mesh():
    src = """
    import jax
    import jax.numpy as jnp
    from sheeprl_tpu.parallel import make_mesh, shard_batch

    def main(rb, sampler):
        mesh = make_mesh(8)
        # batch put + explicit shard downstream: the sanctioned idiom
        data = {k: jnp.asarray(v) for k, v in sampler(rb).sample(64).items()}
        data = shard_batch(data, mesh, axis=1)
        # committed placement: device_put WITH a sharding
        rows = jax.device_put(rb["observations"], mesh_sharding)
        # not batch-shaped: per-step obs put
        obs = {k: jnp.asarray(o[k]) for k in keys}
        return data, rows, obs

    def meshless(rb):
        # no mesh in scope: single-device code is out of SL010's scope
        return jnp.asarray(rb["observations"])
    """
    assert ids(src) == []


def test_sl010_suppression_with_justification():
    src = """
    import jax.numpy as jnp
    from sheeprl_tpu.parallel import make_mesh

    def main(rb):
        mesh = make_mesh(8)
        # sheeplint: disable=SL010 — player-side GAE runs on one device by
        # design; the update batch is resharded right after
        data = {k: jnp.asarray(rb[k]) for k in keys}
        return data
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# SL011 — module-level ndarray constants closed over by jit bodies
# ---------------------------------------------------------------------------


def test_sl011_positive_closure_over_module_constant():
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    TABLE = jnp.arange(4096, dtype=jnp.float32)
    GRID: np.ndarray = np.linspace(0.0, 1.0, 1024)

    @jax.jit
    def f(x):
        return x + TABLE

    def body(c, x):
        return c + GRID, ()

    def scanner(xs):
        return jax.lax.scan(body, 0.0, xs)
    """
    assert sorted(ids(src)) == ["SL011", "SL011"]


def test_sl011_negative_args_locals_shadowing_unjitted():
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    TABLE = jnp.arange(4096)
    scale = 3.0  # scalar: not an ndarray constant

    @jax.jit
    def passed(x, TABLE):
        return x + TABLE  # param shadows the module constant

    @jax.jit
    def local(x):
        TABLE = jnp.zeros_like(x)  # local rebind
        return x + TABLE

    @jax.jit
    def scalar_ok(x):
        return x * scale  # SL009's jurisdiction, not SL011's

    def unjitted(x):
        return x + TABLE  # eager: the constant is a plain device array
    """
    assert ids(src) == []


def test_sl011_suppression_with_justification():
    src = """
    import jax
    import jax.numpy as jnp

    TINY_LUT = jnp.asarray([0.0, 1.0, 4.0, 9.0])

    @jax.jit
    def f(x):
        # sheeplint: disable=SL011 — 16-byte lookup table, embedding is fine
        return TINY_LUT[x]
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# SL012 — swallowed-and-unlogged broad exception handlers (ISSUE 12)
# ---------------------------------------------------------------------------


def test_sl012_positive_bare_broad_and_ellipsis():
    src = """
    def f():
        try:
            step()
        except Exception:
            pass

    def g():
        try:
            step()
        except:
            ...

    def h():
        for _ in range(3):
            try:
                step()
            except (ValueError, BaseException):
                continue
    """
    assert ids(src) == ["SL012", "SL012", "SL012"]


def test_sl012_negative_narrow_logged_or_reraised():
    src = """
    def f():
        try:
            step()
        except ValueError:
            pass  # narrow: presumed deliberate

    def g(log):
        try:
            step()
        except Exception as exc:
            log.warning("step failed: %s", exc)

    def h():
        try:
            step()
        except Exception:
            cleanup()
            raise
    """
    assert ids(src) == []


def test_sl012_suppression_with_justification():
    src = """
    def f(env):
        try:
            env.close()
        # sheeplint: disable=SL012 — best-effort close of a crashed env
        except Exception:
            pass
    """
    assert ids(src) == []


# ---------------------------------------------------------------------------
# SL013 — device arrays reaching serialization/socket sinks (ISSUE 14)
# ---------------------------------------------------------------------------


def test_sl013_positive_tobytes_send_and_pickle():
    src = """
    import pickle
    import jax
    import jax.numpy as jnp

    def push(sock, state):
        x = jnp.zeros((4,))
        blob = x.tobytes()
        sock.sendall(x)
        leaves = jax.tree_util.tree_leaves(state)
        pickle.dumps(leaves)
        sock.send(jnp.ones(3))
    """
    assert ids(src) == ["SL013"] * 4


def test_sl013_positive_through_views_and_rebinds():
    src = """
    import jax.numpy as jnp

    def f(sock):
        x = jnp.zeros((4, 2))
        y = x
        sock.sendall(y[0])
        row = x[1]
        sock.send_bytes(row)
    """
    assert ids(src) == ["SL013", "SL013"]


def test_sl013_negative_host_pull_clears_taint():
    src = """
    import pickle
    import numpy as np
    import jax
    import jax.numpy as jnp

    def push(sock, state):
        x = jnp.zeros((4,))
        host = np.asarray(x)
        sock.sendall(host.tobytes())
        x = np.ascontiguousarray(x)
        sock.send(x.tobytes())
        leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(state)]
        pickle.dumps(leaves)
        pulled = jax.device_get(jnp.ones(3))
        sock.sendto(pulled, ("h", 1))
        sock.sendall(np.zeros(3).tobytes())
    """
    assert ids(src) == []


def test_sl013_taint_is_per_scope_and_ordered():
    src = """
    import numpy as np
    import jax.numpy as jnp

    x = jnp.zeros(3)

    def clean(sock):
        x = np.zeros(3)  # shadows: this scope's x is host-side
        sock.sendall(x.tobytes())
    """
    assert ids(src) == []


def test_sl013_suppression_with_justification():
    src = """
    import jax.numpy as jnp

    def f(sock):
        x = jnp.zeros(3)
        sock.sendall(x)  # sheeplint: disable=SL013 — intentional device send
    """
    assert ids(src) == []


def test_rule_catalog_complete():
    assert rule_ids() == [
        "SL001", "SL002", "SL003", "SL004", "SL005", "SL006", "SL007",
        "SL008", "SL009", "SL010", "SL011", "SL012", "SL013", "SL014",
    ]
    for rule in RULES.values():
        assert rule.severity in ("error", "warning")
        assert rule.summary and rule.autofix


def test_cli_exit_codes_and_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\nf = jax.jit(lambda x: x, donate_argnums=(0,))\n"
    )
    clean = tmp_path / "clean.py"
    clean.write_text("import jax\nf = jax.jit(lambda x: x)\n")
    cli = os.path.join(REPO, "tools", "sheeplint.py")

    p = subprocess.run(
        [sys.executable, cli, str(bad), "--format", "json"],
        capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 1, p.stderr
    payload = json.loads(p.stdout)
    assert payload[0]["rule"] == "SL001" and payload[0]["line"] == 2

    p = subprocess.run(
        [sys.executable, cli, str(clean)],
        capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stdout + p.stderr

    p = subprocess.run(
        [sys.executable, cli, "--list-rules"],
        capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0
    for rid in rule_ids():
        assert rid in p.stdout


def test_cli_select_filters_rules(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\nf = jax.jit(lambda x: x, donate_argnums=(0,))\n"
    )
    cli = os.path.join(REPO, "tools", "sheeplint.py")
    p = subprocess.run(
        [sys.executable, cli, str(bad), "--select", "SL002"],
        capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stdout + p.stderr
