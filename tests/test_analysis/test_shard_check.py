"""sheepshard receipts (ISSUE 8 tentpole): each SC006-SC009 rule fires on a
known-bad fixture and stays silent on a clean control; the comms ledger
round-trips and its CI drift gate fails on the injected regressions the
ISSUE names (an extra hot-loop all-gather, a newly replicated large param);
the ppo@anakin producer->consumer data edge resolves as a real cross-jit
sharding contract.

Fixture jits are lowered AND compiled under real NamedShardings on the
conftest 8-virtual-CPU-device mesh — the analyzers read the partitioned HLO
XLA actually emits, not a mock of it."""

import json
import textwrap
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.analysis import jaxpr_check as jc
from sheeprl_tpu.analysis import shard_check as sc
from sheeprl_tpu.compile import DataEdge, sds


def _mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


def _entry(name, fn, example):
    # analyze_entry only reads .name/.fn/.example — a namespace stands in
    # for a CompilePlan._Entry without the capture-mode env dance
    return SimpleNamespace(name=name, fn=fn, example=example)


def _rules_hit(report):
    return {f.rule.id for f in report.findings}


# ---------------------------------------------------------------------------
# clean control
# ---------------------------------------------------------------------------


def test_clean_control_data_parallel_elementwise():
    """Purely data-parallel math over a sharded batch: zero collectives,
    zero findings, and the fingerprint says so."""
    mesh = _mesh8()
    row = NamedSharding(mesh, P("data"))

    @jax.jit
    def step(x):
        return jnp.tanh(x * 2.0) + 1.0

    report, compiled = sc.analyze_entry(
        "fix@clean", _entry("step", step, lambda: (sds((8, 4), jnp.float32, row),))
    )
    assert report.error is None and compiled is not None
    assert report.findings == []
    assert report.comms["num_partitions"] == 8
    assert report.comms["collectives"] == {}
    assert report.comms["wire_bytes"] == 0
    assert report.comms["mesh"] == {"data": 8}
    json.dumps(report.comms)  # the ledger must be committable as-is


def test_not_mesh_bearing_skipped_unless_forced():
    @jax.jit
    def f(x):
        return x + 1.0

    ex = lambda: (sds((4,), jnp.float32),)  # noqa: E731 — no sharding
    report, _ = sc.analyze_entry("fix@clean", _entry("f", f, ex))
    assert report.error is not None and "not mesh-bearing" in report.error
    forced, _ = sc.analyze_entry("fix@clean", _entry("f", f, ex), force=True)
    assert forced.error is None and forced.comms is not None


# ---------------------------------------------------------------------------
# SC006: collective inside a hot (while/scan) loop body
# ---------------------------------------------------------------------------


def _sc006_fixture():
    """Carry [B, H] sharded over H: each scan iteration contracts the
    sharded axis (c @ w), so the partitioner must all-reduce the partial
    products INSIDE the loop body — the textbook hot-loop collective."""
    mesh = _mesh8()
    col = NamedSharding(mesh, P(None, "data"))
    row = NamedSharding(mesh, P("data", None))

    @jax.jit
    def step(c, w):
        def body(c, _):
            return jnp.tanh(c @ w), ()

        c, _ = jax.lax.scan(body, c, None, length=4)
        return c

    example = lambda: (  # noqa: E731
        sds((8, 16), jnp.float32, col), sds((16, 16), jnp.float32, row)
    )
    return _entry("step", step, example)


def test_sc006_collective_in_scan_body():
    report, _ = sc.analyze_entry("fix@hot", _sc006_fixture())
    assert report.error is None
    assert "SC006" in _rules_hit(report)
    assert report.comms["hot_collectives"].get("all-reduce", 0) >= 1
    assert report.comms["wire_bytes_hot"] > 0
    # the trip count multiplies the committed wire bytes
    hot = [c for c in report.findings if c.rule.id == "SC006"]
    assert any("while/scan body" in f.message for f in hot)


def test_sc006_same_math_outside_loop_is_cold():
    """The identical contraction OUTSIDE a loop: the all-reduce is cold —
    recorded in the histogram but no SC006."""
    mesh = _mesh8()
    col = NamedSharding(mesh, P(None, "data"))
    row = NamedSharding(mesh, P("data", None))

    @jax.jit
    def step(c, w):
        return jnp.tanh(c @ w)

    report, _ = sc.analyze_entry(
        "fix@cold",
        _entry(
            "step", step,
            lambda: (sds((8, 16), jnp.float32, col), sds((16, 16), jnp.float32, row)),
        ),
    )
    assert report.error is None
    assert "SC006" not in _rules_hit(report)
    assert report.comms["collectives"].get("all-reduce", 0) >= 1
    assert report.comms["hot_collectives"] == {}


def test_sc006_suppression_carries_justification(monkeypatch):
    monkeypatch.setitem(
        sc.SHARD_SUPPRESSIONS, ("fix@hot", "step", "SC006"), "designed reduce"
    )
    report, _ = sc.analyze_entry("fix@hot", _sc006_fixture())
    hot = [f for f in report.findings if f.rule.id == "SC006"]
    assert hot and all(f.suppressed == "designed reduce" for f in hot)
    assert report.failing == []


# ---------------------------------------------------------------------------
# SC007: silent full replication of an undeclared large input
# ---------------------------------------------------------------------------


def test_sc007_silent_replication(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_SHARD_REPLICATED_FLOOR", "1024")
    mesh = _mesh8()
    row = NamedSharding(mesh, P("data"))

    @jax.jit
    def step(x, w):
        return x @ w

    report, _ = sc.analyze_entry(
        "fix@repl",
        _entry(
            "step", step,
            # w left UNSPECIFIED: the partitioner replicates all 16KiB of it
            lambda: (sds((8, 64), jnp.float32, row), sds((64, 64), jnp.float32)),
        ),
    )
    assert report.error is None
    assert "SC007" in _rules_hit(report)
    assert report.comms["replicated_inputs"], report.comms
    assert report.comms["replicated_bytes"] >= 64 * 64 * 4


def test_sc007_declared_replication_is_chosen_not_silent(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_SHARD_REPLICATED_FLOOR", "1024")
    mesh = _mesh8()
    row = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())

    @jax.jit
    def step(x, w):
        return x @ w

    report, _ = sc.analyze_entry(
        "fix@repl",
        _entry(
            "step", step,
            # same layout, but COMMITTED: P() says "replicate me" out loud
            lambda: (
                sds((8, 64), jnp.float32, row), sds((64, 64), jnp.float32, repl)
            ),
        ),
    )
    assert report.error is None
    assert "SC007" not in _rules_hit(report)
    assert report.comms["replicated_inputs"] == []


def test_sc007_small_replicated_input_below_floor():
    # default floor is 1MiB: a 16KiB weight replicating is normal, not a finding
    mesh = _mesh8()
    row = NamedSharding(mesh, P("data"))

    @jax.jit
    def step(x, w):
        return x @ w

    report, _ = sc.analyze_entry(
        "fix@repl",
        _entry(
            "step", step,
            lambda: (sds((8, 64), jnp.float32, row), sds((64, 64), jnp.float32)),
        ),
    )
    assert "SC007" not in _rules_hit(report)


# ---------------------------------------------------------------------------
# SC008: cross-jit data-edge sharding contracts
# ---------------------------------------------------------------------------


def _edge_plan(consumer_constraint):
    """A two-jit plan with a declared producer->consumer edge. The producer
    emits [8, 32] sharded over 'data'; the consumer's example leaves its
    input UNDECLARED, and `consumer_constraint` decides what layout the
    consumer's compiled executable actually wants."""
    mesh = _mesh8()
    row = NamedSharding(mesh, P("data", None))

    @jax.jit
    def produce(x):
        return x * 2.0

    @jax.jit
    def consume(y):
        y = jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, consumer_constraint)
        )
        return y.sum()

    entries = [
        _entry("produce", produce, lambda: (sds((8, 32), jnp.float32, row),)),
        _entry("consume", consume, lambda: (sds((8, 32), jnp.float32),)),
    ]
    return SimpleNamespace(
        _entries=entries, edges=[DataEdge("produce", "consume", expect="match")]
    )


def test_sc008_matching_contract_ok():
    reports, records, findings = sc.analyze_shard_plan(
        "fix@edge", _edge_plan(P("data", None))
    )
    assert [r.error for r in reports] == [None, None]
    assert records["produce->consume"]["status"] == "ok"
    assert records["produce->consume"]["contract"]  # resolved pairs committed
    assert findings == []


def test_sc008_broken_contract_fires():
    _, records, findings = sc.analyze_shard_plan(
        "fix@edge", _edge_plan(P(None, "data"))  # consumer wants the OTHER axis
    )
    assert records["produce->consume"]["status"] == "mismatch"
    assert [f.rule.id for f in findings] == ["SC008"]
    assert "implicit reshard" in findings[0].message


def test_sc008_reshard_edge_is_documented_contract():
    plan = _edge_plan(P(None, "data"))
    plan.edges = [DataEdge("produce", "consume", expect="reshard", note="on purpose")]
    _, records, findings = sc.analyze_shard_plan("fix@edge", plan)
    rec = records["produce->consume"]
    assert rec["status"] == "ok" and rec["expect"] == "reshard"
    assert rec["note"] == "on purpose"
    assert findings == []  # the reshuffle is declared, not silent


def test_sc008_unresolved_endpoint_recorded():
    plan = _edge_plan(P("data", None))
    plan.edges = [DataEdge("produce", "ghost", expect="match")]
    _, records, findings = sc.analyze_shard_plan("fix@edge", plan)
    assert records["produce->ghost"]["status"] == "unresolved"
    assert findings == []


# ---------------------------------------------------------------------------
# SC009: eager collectives in un-jitted host loops (source pass)
# ---------------------------------------------------------------------------

_SC009_BAD = """
import jax

def sync_loop(xs):
    out = []
    for x in xs:
        out.append(jax.lax.psum(x, "i"))  # one dispatch per iteration
    return out
"""

_SC009_CLEAN = """
import jax

def fused(xs):
    def body(c, x):
        return c + jax.lax.psum(x, "i"), ()
    return jax.lax.scan(body, 0.0, xs)

def hoisted(xs):
    total = jax.lax.psum(xs, "i")
    for x in total:
        print(x)
    return total
"""

_SC009_SUPPRESSED = """
import jax
from jax.experimental import multihost_utils

def barrier_loop(steps):
    for _ in range(steps):
        # sheeplint: disable=SC009 — intentional per-step host barrier
        multihost_utils.sync_global_devices("step")
"""


def test_sc009_eager_collective_in_host_loop(tmp_path):
    path = tmp_path / "bad.py"
    path.write_text(_SC009_BAD)
    findings = sc.check_source_collectives([str(path)])
    assert [f.rule.id for f in findings] == ["SC009"]
    assert "jax.lax.psum" in findings[0].message


def test_sc009_jitted_and_hoisted_are_clean(tmp_path):
    path = tmp_path / "clean.py"
    path.write_text(_SC009_CLEAN)
    assert sc.check_source_collectives([str(path)]) == []


def test_sc009_comment_suppression(tmp_path):
    path = tmp_path / "sup.py"
    path.write_text(_SC009_SUPPRESSED)
    assert sc.check_source_collectives([str(path)]) == []


def test_sc009_repo_is_clean():
    import sheeprl_tpu

    root = str(jc.os.path.dirname(sheeprl_tpu.__file__))
    findings = sc.check_source_collectives([root])
    assert findings == [], [f.format() for f in findings]


# ---------------------------------------------------------------------------
# HLO comms parsing + the wire model (deterministic unit receipts)
# ---------------------------------------------------------------------------

_HLO_FIXTURE = textwrap.dedent("""\
    HloModule fix, num_partitions=8

    %body (p: (f32[4,16], s32[])) -> (f32[4,16], s32[]) {
      %p = parameter(0)
      %ar = f32[4,16] all-reduce(f32[4,16] %x), replica_groups=[1,8]<=[8], to_apply=%sum
      ROOT %t = tuple(%ar)
    }

    %cond (p: (f32[4,16], s32[])) -> pred[] {
      %p = parameter(0)
      ROOT %lt = pred[] compare(%i, %n), direction=LT
    }

    ENTRY %main (x: f32[4,16]) -> f32[4,16] {
      %x = parameter(0)
      %w = f32[4,16] while(%init), condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"5"}}
      %ag = f32[32,16] all-gather(f32[4,16] %x), replica_groups=[1,8]<=[8], dimensions={0}
      ROOT %r = f32[4,16] add(%w, %x)
    }
""")


def test_parse_hlo_comms_hot_and_cold():
    parsed = sc.parse_hlo_comms(_HLO_FIXTURE)
    assert parsed["num_partitions"] == 8
    by_kind = {c.kind: c for c in parsed["collectives"]}
    ar, ag = by_kind["all-reduce"], by_kind["all-gather"]
    assert ar.hot and ar.trip_count == 5
    assert not ag.hot
    assert ar.groups == 1 and ar.group_size == 8
    # ring model: all-reduce 2*(s-1)*B over the 4*16*4-byte payload
    assert ar.wire_bytes == 2 * 7 * 4 * 16 * 4
    # all-gather's full logical payload is its RESULT (32x16)
    assert ag.wire_bytes == 7 * 32 * 16 * 4


def test_estimate_wire_bytes_models():
    b = 1024
    assert sc.estimate_wire_bytes("all-reduce", b, b, 1, 8) == 2 * 7 * b
    assert sc.estimate_wire_bytes("all-gather", 8 * b, b, 1, 8) == 7 * 8 * b
    assert sc.estimate_wire_bytes("reduce-scatter", b, 8 * b, 1, 8) == 7 * 8 * b
    assert sc.estimate_wire_bytes("collective-permute", b, b, 8, 1) == 8 * b
    # two disjoint groups of 4 each
    assert sc.estimate_wire_bytes("all-reduce", b, b, 2, 4) == 2 * 2 * 3 * b


def test_replica_groups_both_syntaxes():
    assert sc._replica_groups("replica_groups=[2,4]<=[8]", 8) == (2, 4)
    assert sc._replica_groups("replica_groups={{0,1},{2,3}}", 8) == (2, 2)
    assert sc._replica_groups("", 8) == (1, 8)


# ---------------------------------------------------------------------------
# the comms ledger: round-trip + drift gate on injected regressions
# ---------------------------------------------------------------------------


def _fixture_ledger():
    report, _ = sc.analyze_entry("fix@hot", _sc006_fixture())
    assert report.comms is not None
    edges = {"fix@hot": {"a->b": {"expect": "match", "status": "ok", "contract": {}}}}
    return sc.build_comms_budget([report], edges)


def test_comms_budget_round_trip_clean():
    ledger = _fixture_ledger()
    failures, notes = sc.check_comms_budget(
        ledger, json.loads(json.dumps(ledger))
    )
    assert failures == [] and notes == []


def test_comms_gate_fails_on_injected_hot_all_gather():
    """ISSUE acceptance: an extra all-gather appearing in a hot loop must
    fail the gate — both as a new collective kind and as hot-loop growth."""
    ledger = _fixture_ledger()
    drifted = json.loads(json.dumps(ledger))
    fp = drifted["comms"]["fix@hot/step"]
    fp["collectives"]["all-gather"] = 1
    fp["hot_collectives"]["all-gather"] = 1
    failures, _ = sc.check_comms_budget(ledger, drifted)
    assert any("new collective kind" in f and "all-gather" in f for f in failures)
    assert any("hot-loop all-gather count grew" in f for f in failures)


def test_comms_gate_fails_on_injected_replicated_param():
    ledger = _fixture_ledger()
    drifted = json.loads(json.dumps(ledger))
    fp = drifted["comms"]["fix@hot/step"]
    fp["replicated_inputs"] = ["1:float32[4096,4096]"]
    failures, _ = sc.check_comms_budget(ledger, drifted)
    assert any("newly replicated large tensor" in f for f in failures)


def test_comms_gate_wire_bytes_tolerance():
    ledger = _fixture_ledger()
    grown = json.loads(json.dumps(ledger))
    fp = grown["comms"]["fix@hot/step"]
    fp["wire_bytes"] = int(ledger["comms"]["fix@hot/step"]["wire_bytes"] * 1.5) + 4096
    failures, _ = sc.check_comms_budget(ledger, grown)
    assert any("comms bytes grew" in f for f in failures)

    shrunk = json.loads(json.dumps(ledger))
    shrunk["comms"]["fix@hot/step"]["wire_bytes"] = 0
    failures, notes = sc.check_comms_budget(ledger, shrunk)
    assert failures == []
    assert any("shrank" in n for n in notes)


def test_comms_gate_fails_on_broken_edge_and_new_jit():
    ledger = _fixture_ledger()
    drifted = json.loads(json.dumps(ledger))
    drifted["edges"]["fix@hot/a->b"]["status"] = "mismatch"
    drifted["comms"]["fix@hot/new_jit"] = drifted["comms"]["fix@hot/step"]
    failures, _ = sc.check_comms_budget(ledger, drifted)
    assert any("contract broke" in f for f in failures)
    assert any("new mesh-bearing jit" in f for f in failures)
    gone = json.loads(json.dumps(ledger))
    del gone["comms"]["fix@hot/step"]
    failures, _ = sc.check_comms_budget(ledger, gone)
    assert any("disappeared" in f for f in failures)


def test_comms_reductions_are_notes():
    ledger = _fixture_ledger()
    improved = json.loads(json.dumps(ledger))
    fp = improved["comms"]["fix@hot/step"]
    fp["hot_collectives"] = {}
    fp["collectives"] = {}
    fp["wire_bytes"] = 0
    failures, notes = sc.check_comms_budget(ledger, improved)
    assert failures == []
    assert any("eliminated" in n for n in notes)
    assert any("hot-loop all-reduce count shrank" in n for n in notes)


# ---------------------------------------------------------------------------
# ledger persistence: per-algo dir layout <-> legacy blob
# ---------------------------------------------------------------------------


def test_budget_dir_layout_sections_coexist(tmp_path):
    """sheepcheck owns `jits`, sheepshard owns `comms`+`edges` — each
    saver rewrites only its sections and the other's survive."""
    path = str(tmp_path / "budget.json")
    jits = {
        "version": 1, "jax_version": jax.__version__,
        "tolerance": {"op_count_frac": 0.25},
        "jits": {"algoX/train_step": {"op_count": 3, "dtypes": ["float32"]}},
    }
    jc.save_budget(jits, path, sections=("jits",))
    comms = _fixture_ledger()
    jc.save_budget(comms, path, sections=("comms", "edges"))
    merged = jc.load_budget(path)
    assert merged["jits"] == jits["jits"]
    assert merged["comms"] == comms["comms"]
    assert merged["edges"] == comms["edges"]
    # tolerances merge rather than clobber
    assert merged["tolerance"]["op_count_frac"] == 0.25
    assert merged["tolerance"]["wire_bytes_frac"] == 0.25
    # one file per spec, deterministic key order
    assert sorted(p.name for p in tmp_path.glob("budget/*.json")) == [
        "_meta.json", "algoX.json", "fix@hot.json",
    ]
    first = (tmp_path / "budget" / "algoX.json").read_text()
    jc.save_budget(jits, path, sections=("jits",))
    assert (tmp_path / "budget" / "algoX.json").read_text() == first


def test_budget_legacy_blob_rejected_with_pointer(tmp_path):
    """The PR-8 'readable for one release' grace period is over (ISSUE
    11): a pre-split single-blob ledger raises a clear error naming the
    dir layout and the rebuild commands, instead of silently gating
    against stale data. Once the dir exists, it wins as before."""
    import pytest

    path = str(tmp_path / "budget.json")
    blob = {"version": 1, "jits": {"a/b": {"op_count": 1}}}
    with open(path, "w") as fh:
        json.dump(blob, fh)
    assert jc.budget_exists(path)  # exists -> tools route into the error
    with pytest.raises(RuntimeError, match="legacy single-blob"):
        jc.load_budget(path)
    with pytest.raises(RuntimeError, match="--update-budget"):
        jc.load_budget(path)
    # a missing ledger is still a plain FileNotFoundError, not the hint
    with pytest.raises(FileNotFoundError):
        jc.load_budget(str(tmp_path / "absent.json"))
    # the dir layout wins once it exists
    jc.save_budget(blob, path, sections=("jits",))
    assert jc.load_budget(path)["jits"] == blob["jits"]


def test_committed_ledger_loads_in_dir_layout():
    import sheeprl_tpu

    repo = jc.os.path.dirname(jc.os.path.dirname(sheeprl_tpu.__file__))
    ledger = jc.load_budget(jc.os.path.join(repo, "analysis", "budget.json"))
    assert len(ledger["jits"]) >= 39
    assert len(ledger["comms"]) >= 16
    assert len(ledger["edges"]) >= 8
    # every edge record resolved to a non-mismatch status at HEAD
    for key, rec in ledger["edges"].items():
        assert rec["status"] in ("ok", "unresolved"), (key, rec)


# ---------------------------------------------------------------------------
# the ppo@anakin cross-jit contract, end-to-end (the ROADMAP-4 slice)
# ---------------------------------------------------------------------------


@pytest.mark.timeout(600)
def test_ppo_anakin_edge_contract_end_to_end(tmp_path):
    """Capture the real ppo@anakin main under the 8-mesh and resolve its
    declared data edges: the rollout->gae handoff is device-to-device and
    must MATCH; gae->train reshuffles on purpose (expect='reshard')."""
    algo, extra_argv = sc.resolve_capture("ppo@anakin")
    plan = jc.capture_plan(algo, str(tmp_path), extra_argv=extra_argv)
    assert plan.edges, "ppo main declared no data edges"
    reports, records, findings = sc.analyze_shard_plan("ppo@anakin", plan)
    by_name = {r.name: r for r in reports}
    assert by_name["anakin_rollout"].comms is not None
    assert by_name["anakin_rollout"].comms["mesh"] == {"data": 8}
    match_edge = records["anakin_rollout->gae"]
    assert match_edge["expect"] == "match"
    assert match_edge["status"] == "ok", match_edge
    assert match_edge["contract"], "no aval groups resolved on the edge"
    reshard_edge = records["gae->train_step"]
    assert reshard_edge["expect"] == "reshard"
    assert [f.format() for f in findings] == []
    # and the whole spec is finding-free modulo justified suppressions
    for r in reports:
        assert r.failing == [], [f.format() for f in r.failing]
