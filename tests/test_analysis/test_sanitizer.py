"""Runtime sanitizer (`--sanitize`) receipts: the transfer guard records
real implicit transfers without crashing the run, checkify findings reach
telemetry, and a full algo main runs end-to-end in sanitize mode with the
events visible in telemetry.jsonl (ISSUE 3 acceptance)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.analysis import Sanitizer


class FakeTelemetry:
    def __init__(self):
        self.events = []

    def event(self, name, **data):
        self.events.append({"event": name, **data})


def test_disabled_sanitizer_is_transparent():
    s = Sanitizer(enabled=False)
    assert s.checked("x", lambda a: a + 1, 1) == 2
    assert s.gauges() == {}
    with pytest.raises(RuntimeError):
        s.checkified(lambda x: x)
    s.close()  # no-op


def test_checked_records_transfer_and_reruns():
    telem = FakeTelemetry()
    s = Sanitizer(enabled=True, telemetry=telem)
    f = jax.jit(lambda x: x * 2)
    f(jnp.ones(4))  # warm outside the guard

    # implicit h2d (numpy arg into a jitted fn) must be recorded, and the
    # call must still produce the right answer via the unguarded rerun
    out = s.checked("train", f, np.ones(4, np.float32))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    trips = [e for e in telem.events if e["event"] == "sanitizer.transfer"]
    assert len(trips) == 1 and trips[0]["phase"] == "train"
    assert "transfer" in trips[0]["message"].lower()

    # second trip in the same phase: counted, not re-emitted
    s.checked("train", f, np.ones(4, np.float32))
    assert len([e for e in telem.events if e["event"] == "sanitizer.transfer"]) == 1
    assert s.gauges()["Sanitizer/transfer_train"] == 2.0

    # clean call (device-resident arg) records nothing new
    s.checked("clean", f, jnp.ones(4))
    assert s.gauges().get("Sanitizer/transfer_clean") is None

    s.close()
    summary = telem.events[-1]
    assert summary["event"] == "sanitizer.summary" and not summary["clean"]


def test_checked_propagates_real_errors():
    s = Sanitizer(enabled=True)

    def boom():
        raise ValueError("not a transfer problem")

    with pytest.raises(ValueError):
        s.checked("x", boom)


def test_checkified_reports_nan_div():
    telem = FakeTelemetry()
    s = Sanitizer(enabled=True, telemetry=telem)

    wrapped = s.checkified(lambda x: jnp.log(x) / (x - 1.0), phase="train")
    assert any(e["event"] == "sanitizer.checkify_armed" for e in telem.events)

    np.testing.assert_allclose(float(wrapped(jnp.float32(2.0))), np.log(2.0))
    assert s.gauges().get("Sanitizer/checkify_train") is None

    wrapped(jnp.float32(1.0))  # log(1)/0 -> division by zero
    checks = [e for e in telem.events if e["event"] == "sanitizer.checkify"]
    assert len(checks) == 1 and "divi" in checks[0]["message"]
    assert s.gauges()["Sanitizer/checkify_train"] == 1.0


@pytest.mark.timeout(300)
def test_ppo_dry_run_sanitize_smoke(tmp_path):
    """One algo end-to-end (CPU, dry-run scale) with --sanitize: the run
    completes and telemetry.jsonl carries the sanitizer lifecycle — start,
    checkify instrumentation on the train step, and the end-of-run
    summary."""
    from sheeprl_tpu.algos.ppo.ppo import main

    root = str(tmp_path / "sanitize_smoke")
    main([
        "--dry_run", "--sanitize", "--num_envs", "2", "--rollout_steps", "8",
        "--total_steps", "16", "--checkpoint_every", "-1",
        "--root_dir", root, "--run_name", "r0",
    ])
    telemetry_path = os.path.join(root, "r0", "telemetry.jsonl")
    assert os.path.exists(telemetry_path)
    events = [json.loads(l) for l in open(telemetry_path)]
    names = [e["event"] for e in events]
    assert "sanitizer.start" in names
    assert "sanitizer.checkify_armed" in names
    assert "sanitizer.summary" in names
    # transfer trips, if any, must have been audited (recorded + rerun),
    # never fatal — and the interval metrics carry the enabled gauge
    logged = [e for e in events if e["event"] == "log"]
    assert any(
        e["metrics"].get("Sanitizer/enabled") == 1.0 for e in logged
    ), "sanitizer gauges never reached the metric pipeline"


@pytest.mark.timeout(120)
def test_ppo_dry_run_without_sanitize_has_no_sanitizer_events(tmp_path):
    from sheeprl_tpu.algos.ppo.ppo import main

    root = str(tmp_path / "plain")
    main([
        "--dry_run", "--num_envs", "2", "--rollout_steps", "8",
        "--total_steps", "16", "--checkpoint_every", "-1",
        "--root_dir", root, "--run_name", "r0",
    ])
    telemetry_path = os.path.join(root, "r0", "telemetry.jsonl")
    events = [json.loads(l) for l in open(telemetry_path)]
    assert not [e for e in events if e["event"].startswith("sanitizer.")]
