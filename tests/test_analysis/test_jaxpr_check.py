"""sheepcheck receipts (ISSUE 7 tentpole): each SC rule fires on a
known-bad fixture jit and stays silent on a clean control; fingerprints are
stable and the budget ledger's drift gate fails on an injected regression.

Fixtures trace REAL jaxprs (jit.trace at ShapeDtypeStruct avals — no
execution), so these tests prove the analyzers read the IR jax actually
produces, not a mock of it."""

import json

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.analysis import jaxpr_check as jc
from sheeprl_tpu.compile import avals_of, sds


def _trace(fn, *specs):
    traced = fn.trace(*specs)
    return traced.jaxpr, traced.lower()


def _rules_hit(findings):
    return {f.rule.id for f in findings}


# ---------------------------------------------------------------------------
# clean control
# ---------------------------------------------------------------------------


def test_clean_control_no_findings():
    @jax.jit
    def step(w, x):
        def body(c, _):
            c = jnp.tanh(c @ w)
            return c, c.sum()

        return jax.lax.scan(body, x, None, length=4)

    closed, lowered = _trace(
        step, sds((8, 8), jnp.float32), sds((4, 8), jnp.float32)
    )
    findings = jc.analyze_closed_jaxpr(
        closed, donated=jc._donated_flags(lowered, closed), audit_bf16=True
    )
    assert findings == []


# ---------------------------------------------------------------------------
# SC001 dtype promotion
# ---------------------------------------------------------------------------


def test_sc001_float64_leak():
    @jax.jit
    def f(x):
        return x.astype(jnp.float64) * 2.0

    with jax.experimental.enable_x64():
        closed, _ = _trace(f, sds((4,), jnp.float32))
    findings = jc.analyze_closed_jaxpr(closed)
    assert "SC001" in _rules_hit(findings)
    msgs = " ".join(f.message for f in findings)
    assert "float64" in msgs


def test_sc001_bf16_upcast_only_under_audit():
    @jax.jit
    def f(x):
        h = x.astype(jnp.bfloat16)
        return (h @ h.T).astype(jnp.float32)  # the silent full-width island

    closed, _ = _trace(f, sds((4, 4), jnp.float32))
    assert "SC001" not in _rules_hit(jc.analyze_closed_jaxpr(closed))
    audited = jc.analyze_closed_jaxpr(closed, audit_bf16=True)
    assert "SC001" in _rules_hit(audited)
    assert any("bf16 upcast" in f.message for f in audited)


# ---------------------------------------------------------------------------
# SC002 host callbacks
# ---------------------------------------------------------------------------


def test_sc002_debug_print_in_scan():
    @jax.jit
    def rollout(x):
        def body(c, _):
            jax.debug.print("c = {c}", c=c.sum())
            return c + 1.0, c.sum()

        return jax.lax.scan(body, x, None, length=8)

    closed, _ = _trace(rollout, sds((4,), jnp.float32))
    findings = jc.analyze_closed_jaxpr(closed)
    assert "SC002" in _rules_hit(findings)


def test_sc002_pure_callback():
    import numpy as np

    @jax.jit
    def f(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct((4,), jnp.float32), x
        )

    closed, _ = _trace(f, sds((4,), jnp.float32))
    assert "SC002" in _rules_hit(jc.analyze_closed_jaxpr(closed))


# ---------------------------------------------------------------------------
# SC003 donation hazards
# ---------------------------------------------------------------------------


def test_sc003_dead_donation():
    # arg 0 donated but never read and never returned
    def f(dead, x):
        return x * 2.0

    jf = jax.jit(f, donate_argnums=0)
    closed, lowered = _trace(jf, sds((8,), jnp.float32), sds((8,), jnp.float32))
    findings = jc.analyze_closed_jaxpr(
        closed, donated=jc._donated_flags(lowered, closed)
    )
    assert "SC003" in _rules_hit(findings)
    assert any("dead" in f.message for f in findings)


def test_sc003_double_alias():
    def f(state):
        return state, state  # one donated buffer cannot back two outputs

    jf = jax.jit(f, donate_argnums=0)
    closed, lowered = _trace(jf, sds((8,), jnp.float32))
    findings = jc.analyze_closed_jaxpr(
        closed, donated=jc._donated_flags(lowered, closed)
    )
    assert "SC003" in _rules_hit(findings)


def test_sc003_no_matching_output():
    def f(big, x):
        return (big.sum() + x).astype(jnp.float32)  # no f32[64] output to reuse

    jf = jax.jit(f, donate_argnums=0)
    closed, lowered = _trace(jf, sds((64,), jnp.float32), sds((), jnp.float32))
    findings = jc.analyze_closed_jaxpr(
        closed, donated=jc._donated_flags(lowered, closed)
    )
    assert "SC003" in _rules_hit(findings)
    assert any("no shape/dtype-matching output" in f.message for f in findings)


def test_sc003_good_donation_clean():
    def f(state, g):
        return state - 0.1 * g  # classic state-in state-out reuse

    jf = jax.jit(f, donate_argnums=0)
    closed, lowered = _trace(jf, sds((8, 8), jnp.float32), sds((8, 8), jnp.float32))
    findings = jc.analyze_closed_jaxpr(
        closed, donated=jc._donated_flags(lowered, closed)
    )
    assert "SC003" not in _rules_hit(findings)


# ---------------------------------------------------------------------------
# SC004 scan-carry hazards
# ---------------------------------------------------------------------------


def test_sc004_weak_carry():
    @jax.jit
    def f(xs):
        def body(c, x):
            return c + x, c

        # init 0.0 is a python scalar: the carry aval is weak-typed
        return jax.lax.scan(body, 0.0, xs)

    closed, _ = _trace(f, sds((8,), jnp.float32))
    findings = jc.analyze_closed_jaxpr(closed)
    assert "SC004" in _rules_hit(findings)
    assert any("weak-typed" in f.message for f in findings)


def test_sc004_weak_jit_input():
    """The in-tree catch: a call site passing a raw python float (the
    ppo_decoupled gamma/lambda class) shows up as a weak-typed top-level
    input aval of the traced jit."""

    @jax.jit
    def gae(values, gamma):
        return values * gamma

    # tracing with a live python scalar reproduces the weak-typed aval a
    # raw-float call site creates
    closed = gae.trace(jnp.zeros((4,), jnp.float32), 0.99).jaxpr
    findings = jc.analyze_closed_jaxpr(closed)
    assert "SC004" in _rules_hit(findings)
    assert any("jit input" in f.message and "weak-typed" in f.message
               for f in findings)
    # the fixed call site (committed f32 scalar) is clean
    closed = gae.trace(jnp.zeros((4,), jnp.float32), jnp.float32(0.99)).jaxpr
    assert "SC004" not in _rules_hit(jc.analyze_closed_jaxpr(closed))


def test_sc004_concrete_carry_clean():
    @jax.jit
    def f(xs):
        def body(c, x):
            return c + x, c

        return jax.lax.scan(body, jnp.float32(0.0), xs)

    closed, _ = _trace(f, sds((8,), jnp.float32))
    assert "SC004" not in _rules_hit(jc.analyze_closed_jaxpr(closed))


# ---------------------------------------------------------------------------
# SC005 conv pathology
# ---------------------------------------------------------------------------


def _conv_tower(batch):
    """Forward+backward through a small transposed-conv decoder — the
    gradient convs carry lhs_dilation, the SC005 signature."""

    def loss(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (2, 2), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return (y * y).mean()

    @jax.jit
    def update(w, x):
        return jax.grad(loss)(w, x)

    return update, (
        sds((3, 3, 4, 4), jnp.float32),
        sds((batch, 16, 16, 4), jnp.float32),
    )


def test_sc005_fires_above_threshold(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_COMPILE_BUDGET_S", "0.01")
    update, specs = _conv_tower(batch=64)
    closed, _ = _trace(update, *specs)
    findings = jc.analyze_closed_jaxpr(closed)
    assert "SC005" in _rules_hit(findings)


def test_sc005_silent_below_threshold(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_COMPILE_BUDGET_S", "100000")
    update, specs = _conv_tower(batch=2)
    closed, _ = _trace(update, *specs)
    assert "SC005" not in _rules_hit(jc.analyze_closed_jaxpr(closed))


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_suppression_requires_justification(monkeypatch):
    @jax.jit
    def f(xs):
        return jax.lax.scan(lambda c, x: (c + x, c), 0.0, xs)

    closed, _ = _trace(f, sds((8,), jnp.float32))
    monkeypatch.setitem(
        jc.SUPPRESSIONS, ("algoX", "jitY", "SC004"), "intentional weak carry"
    )
    findings = jc.analyze_closed_jaxpr(closed, algo="algoX", name="jitY")
    hits = [f for f in findings if f.rule.id == "SC004"]
    assert hits and all(f.suppressed == "intentional weak carry" for f in hits)
    # suppressed findings don't fail a report
    report = jc.JitReport(algo="algoX", name="jitY", findings=findings)
    assert not [f for f in report.failing if f.rule.id == "SC004"]


# ---------------------------------------------------------------------------
# fingerprints + budget ledger
# ---------------------------------------------------------------------------


def _fixture_reports():
    def f(state, g):
        return state - 0.1 * g

    jf = jax.jit(f, donate_argnums=0)
    closed, lowered = _trace(jf, sds((8, 8), jnp.float32), sds((8, 8), jnp.float32))
    fp = jc.fingerprint_jaxpr(closed, lowered)
    return [jc.JitReport(algo="algoX", name="train_step", fingerprint=fp)]


def test_fingerprint_contents():
    (report,) = _fixture_reports()
    fp = report.fingerprint
    assert fp["op_count"] >= 1
    assert fp["dtypes"] == ["float32"]
    assert fp["donated"] == 1
    assert sum(fp["primitives"].values()) == fp["op_count"]
    assert fp["in_avals"] == ["float32[8,8]", "float32[8,8]"]
    json.dumps(fp)  # the ledger must be committable as-is


def test_fingerprint_deterministic():
    a = _fixture_reports()[0].fingerprint
    b = _fixture_reports()[0].fingerprint
    assert a == b


def test_budget_round_trip_clean():
    reports = _fixture_reports()
    ledger = jc.build_budget(reports)
    failures, notes = jc.check_budget(ledger, jc.build_budget(reports))
    assert failures == [] and notes == []


def test_budget_drift_gate_fails_on_injected_regression():
    """The ISSUE acceptance receipt: perturb a committed fingerprint and the
    gate must fail — for each gated drift class."""
    reports = _fixture_reports()
    ledger = jc.build_budget(reports)

    bloated = json.loads(json.dumps(ledger))
    fp = bloated["jits"]["algoX/train_step"]
    fp["op_count"] = int(fp["op_count"] * 2 + 10)  # past the 25% tolerance
    failures, _ = jc.check_budget(ledger, bloated)
    assert any("op count grew" in f for f in failures)

    retyped = json.loads(json.dumps(ledger))
    retyped["jits"]["algoX/train_step"]["dtypes"].append("float64")
    failures, _ = jc.check_budget(ledger, retyped)
    assert any("new dtypes" in f and "float64" in f for f in failures)

    undonated = json.loads(json.dumps(ledger))
    undonated["jits"]["algoX/train_step"]["donated"] = 0
    failures, _ = jc.check_budget(ledger, undonated)
    assert any("lost donations" in f for f in failures)

    renamed = json.loads(json.dumps(ledger))
    renamed["jits"]["algoX/other_step"] = renamed["jits"].pop("algoX/train_step")
    failures, _ = jc.check_budget(ledger, renamed)
    assert any("disappeared" in f for f in failures)
    assert any("new jit" in f for f in failures)


def test_budget_improvements_are_notes_not_failures():
    reports = _fixture_reports()
    ledger = jc.build_budget(reports)
    improved = json.loads(json.dumps(ledger))
    fp = improved["jits"]["algoX/train_step"]
    fp["op_count"] = max(1, fp["op_count"] // 4)
    fp["donated"] = fp["donated"] + 1
    failures, notes = jc.check_budget(ledger, improved)
    assert failures == []
    assert any("shrank" in n for n in notes)
    assert any("gained donations" in n for n in notes)


def test_budget_save_load_round_trip(tmp_path):
    ledger = jc.build_budget(_fixture_reports())
    path = str(tmp_path / "budget.json")
    jc.save_budget(ledger, path)
    assert jc.load_budget(path) == ledger


# ---------------------------------------------------------------------------
# plan capture (end-to-end on the cheapest real main)
# ---------------------------------------------------------------------------


@pytest.mark.timeout(600)
def test_capture_plan_sac_end_to_end(tmp_path):
    """The tentpole wiring: run a REAL algo main in capture mode — setup
    proceeds to plan.start(), CaptureComplete unwinds before any training,
    and every registered jit abstract-evals to an analyzable jaxpr with a
    fingerprint. Uses sac (the cheapest main to build)."""
    plan = jc.capture_plan("sac", str(tmp_path))
    assert plan.capture_only and plan._entries
    reports = jc.analyze_plan("sac", plan)
    analyzed = [r for r in reports if r.fingerprint is not None]
    assert analyzed, [r.error for r in reports]
    names = {r.name for r in reports}
    assert "train_step" in names
    for r in analyzed:
        assert r.fingerprint["op_count"] > 0
        assert r.failing == [], [f.format() for f in r.failing]


def test_capture_plan_unknown_algo():
    with pytest.raises(KeyError):
        jc.capture_plan("not_an_algo", "/tmp")


def test_capture_mode_register_returns_raw_fn():
    """In capture mode register() must hand the main back its own callable
    (no WarmJit wrapper) and start() must raise CaptureComplete."""
    import os

    from sheeprl_tpu.compile import CaptureComplete, CompilePlan

    os.environ["SHEEPRL_TPU_PLAN_MODE"] = "capture"
    try:

        class _Args:
            warm_compile = "on"

        plan = CompilePlan.from_args(_Args())
        assert plan.capture_only and not plan.enabled
        fn = jax.jit(lambda x: x + 1)
        out = plan.register("j", fn, example=lambda: (sds((2,), jnp.float32),))
        assert out is fn
        with pytest.raises(CaptureComplete) as exc:
            plan.start()
        assert exc.value.plan is plan
    finally:
        os.environ.pop("SHEEPRL_TPU_PLAN_MODE", None)


# =============================================================================
# bf16 mixed-precision gate (ISSUE 9)
# =============================================================================


def _bf16_ledger():
    """A hand-built ledger with one declared-bf16 jit and one f32-only jit."""
    return {
        "version": 1,
        "tolerance": {"op_count_frac": 0.25},
        "jits": {
            "algo@bf16/train_step": {
                "op_count": 40,
                "dtypes": ["bfloat16", "float32"],
                "bf16_upcasts": 5,
                "donated": 0,
                "primitives": {},
            },
            "algo/train_step": {
                "op_count": 40,
                "dtypes": ["float32"],
                "bf16_upcasts": 0,
                "donated": 0,
                "primitives": {},
            },
        },
    }


def test_bf16_gate_clean_on_identical_budget():
    ledger = _bf16_ledger()
    failures, notes = jc.check_budget(ledger, json.loads(json.dumps(ledger)))
    assert failures == [] and notes == []


def test_bf16_gate_fails_on_new_silent_upcast():
    ledger = _bf16_ledger()
    drifted = json.loads(json.dumps(ledger))
    drifted["jits"]["algo@bf16/train_step"]["bf16_upcasts"] = 7
    failures, _ = jc.check_budget(ledger, drifted)
    assert any("upcasts grew 5 -> 7" in f for f in failures)


def test_bf16_gate_fails_on_lost_bfloat16_compute():
    ledger = _bf16_ledger()
    drifted = json.loads(json.dumps(ledger))
    drifted["jits"]["algo@bf16/train_step"]["dtypes"] = ["float32"]
    drifted["jits"]["algo@bf16/train_step"]["bf16_upcasts"] = 0
    failures, _ = jc.check_budget(ledger, drifted)
    assert any("lost its bfloat16 compute" in f for f in failures)


def test_bf16_gate_shrink_is_a_note_and_f32_jits_exempt():
    ledger = _bf16_ledger()
    drifted = json.loads(json.dumps(ledger))
    # fewer upcasts in the declared jit: improvement, not failure
    drifted["jits"]["algo@bf16/train_step"]["bf16_upcasts"] = 3
    # an f32-only jit growing an upcast count is NOT gated (audit-only)
    drifted["jits"]["algo/train_step"]["bf16_upcasts"] = 2
    failures, notes = jc.check_budget(ledger, drifted)
    assert failures == []
    assert any("bf16 upcasts shrank" in n for n in notes)


def _int8_ledger():
    """One declared-int8 serving rung and its full-width twin (ISSUE 20)."""
    return {
        "version": 1,
        "tolerance": {"op_count_frac": 0.25},
        "jits": {
            "serve@int8/policy_b2": {
                "op_count": 80,
                "dtypes": ["float32", "int32", "int8"],
                "bf16_upcasts": 0,
                "int8_ops": 8,
                "donated": 0,
                "primitives": {},
            },
            "serve/policy_b2": {
                "op_count": 60,
                "dtypes": ["float32"],
                "bf16_upcasts": 0,
                "int8_ops": 0,
                "donated": 0,
                "primitives": {},
            },
        },
    }


def test_int8_gate_clean_on_identical_budget():
    ledger = _int8_ledger()
    failures, notes = jc.check_budget(ledger, json.loads(json.dumps(ledger)))
    assert failures == [] and notes == []


def test_int8_gate_fails_on_lost_int8_compute():
    ledger = _int8_ledger()
    drifted = json.loads(json.dumps(ledger))
    drifted["jits"]["serve@int8/policy_b2"]["dtypes"] = ["float32", "int32"]
    drifted["jits"]["serve@int8/policy_b2"]["int8_ops"] = 0
    failures, _ = jc.check_budget(ledger, drifted)
    assert any("lost its int8 compute" in f for f in failures)


def test_int8_gate_fails_on_shrunk_coverage_notes_growth():
    ledger = _int8_ledger()
    drifted = json.loads(json.dumps(ledger))
    # a dequantized layer: int8 dtype survives but the op coverage shrank
    drifted["jits"]["serve@int8/policy_b2"]["int8_ops"] = 5
    failures, _ = jc.check_budget(ledger, drifted)
    assert any("int8 ops shrank 8 -> 5" in f for f in failures)
    grown = json.loads(json.dumps(ledger))
    grown["jits"]["serve@int8/policy_b2"]["int8_ops"] = 11
    failures, notes = jc.check_budget(ledger, grown)
    assert failures == []
    assert any("int8 ops grew" in n for n in notes)


def test_int8_fingerprint_counts_quantized_eqns():
    """fingerprint_jaxpr's int8_ops: zero on an f32 program, positive on
    the quantized twin of the same math."""
    import numpy as np

    from sheeprl_tpu.ops import quant as q

    w = np.random.default_rng(0).standard_normal((6, 4)).astype(np.float32)
    s = jnp.ones((6,), jnp.float32) * 0.1
    ws = q.absmax_scale(jnp.asarray(w) * s[:, None], axis=0)
    wq = q.quantize(jnp.asarray(w) * s[:, None], ws)

    f32 = jax.jit(lambda x: x @ w).trace(
        jax.ShapeDtypeStruct((2, 6), jnp.float32)
    ).jaxpr
    int8 = jax.jit(lambda x: q.int8_linear(x, s, wq, ws, None)).trace(
        jax.ShapeDtypeStruct((2, 6), jnp.float32)
    ).jaxpr
    fp32 = jc.fingerprint_jaxpr(f32)
    fpq = jc.fingerprint_jaxpr(int8)
    assert fp32["int8_ops"] == 0 and not jc.declares_int8(fp32)
    assert fpq["int8_ops"] > 0 and jc.declares_int8(fpq)
    assert "int8" in fpq["dtypes"]


def test_declares_bf16_predicate():
    ledger = _bf16_ledger()
    assert jc.declares_bf16(ledger["jits"]["algo@bf16/train_step"])
    assert not jc.declares_bf16(ledger["jits"]["algo/train_step"])
    assert not jc.declares_bf16({})
    assert not jc.declares_bf16(None)


def test_bf16_capture_variants_cover_all_mains():
    """The @bf16 sweep is the gate's population: one variant per main."""
    import sheeprl_tpu.algos  # noqa: F401
    from sheeprl_tpu.utils.registry import tasks

    bf16_specs = {s for s in jc.CAPTURE_VARIANTS if s.endswith("@bf16")}
    assert {s.split("@")[0] for s in bf16_specs} == set(tasks)
    for spec in bf16_specs:
        algo, extra = jc.resolve_capture(spec)
        # serve has no top-level --precision; its variant re-specifies the
        # nested --model_argv with the flag appended (last-wins)
        if extra[-2:] == ["--precision", "bfloat16"]:
            continue
        assert extra[-2] == "--model_argv"
        assert extra[-1].split()[-2:] == ["--precision", "bfloat16"]


def test_fingerprint_counts_bf16_upcasts():
    import jax
    import jax.numpy as jnp

    def f(x):
        y = (x.astype(jnp.bfloat16) * 2).astype(jnp.float32)  # one upcast
        z = (x.astype(jnp.bfloat16) + 1).astype(jnp.float32)  # another
        return y + z

    closed = jax.jit(f).trace(jax.ShapeDtypeStruct((4,), jnp.float32)).jaxpr
    fp = jc.fingerprint_jaxpr(closed)
    assert fp["bf16_upcasts"] == 2
    assert "bfloat16" in fp["dtypes"]
