"""The repo lints itself clean (ISSUE 3 acceptance): every pre-existing
violation is either fixed or carries a justified suppression, and any NEW
hazard fails this test (and the CI sheeplint job) immediately."""

import os

from sheeprl_tpu.analysis.linter import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_repo_is_sheeplint_clean():
    targets = [
        os.path.join(REPO, "sheeprl_tpu"),
        os.path.join(REPO, "tools"),
        os.path.join(REPO, "bench.py"),
    ]
    violations = lint_paths(targets)
    assert not violations, "\n" + "\n".join(v.format() for v in violations)
