"""sheepsync runtime half (ISSUE 18): instrumented Lock/RLock/Condition
wrappers, the seeded two-lock deadlock fixture (order violation detected
and reported WITHOUT hanging the suite), gauges, and install/uninstall
lifecycle. Pure stdlib — no jax import."""

import threading
import time

import pytest

from sheeprl_tpu.analysis import thread_sanitizer as ts


@pytest.fixture()
def san():
    """Installed sanitizer with an empty ledger; always uninstalled."""
    assert ts.installed() is None, "sanitizer leaked from another test"
    s = ts.install(ledger={})
    yield s
    ts.uninstall()


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------


def test_install_patches_and_uninstall_restores():
    real_lock = threading.Lock
    s = ts.install(ledger={})
    try:
        assert ts.installed() is s
        assert ts.install(ledger={}) is s  # idempotent
        lk = threading.Lock()
        assert isinstance(lk, ts._InstrumentedLock)
    finally:
        summary = ts.uninstall()
    assert ts.installed() is None
    assert threading.Lock is real_lock
    assert summary is not None and "violations" in summary
    assert ts.uninstall() is None  # second uninstall is a no-op
    # a lock created while instrumented keeps working after uninstall
    with lk:
        assert lk.locked()


def test_maybe_install_from_env(monkeypatch):
    monkeypatch.delenv(ts.ENV_VAR, raising=False)
    assert ts.maybe_install_from_env() is None
    monkeypatch.setenv(ts.ENV_VAR, "1")
    # patch ledger loading cheaply: install with explicit empty ledger via env
    s = ts.maybe_install_from_env()
    try:
        assert s is not None and ts.installed() is s
    finally:
        ts.uninstall()


def test_gauges_empty_when_not_installed():
    assert ts.installed() is None
    assert ts.gauges() == {}


# ---------------------------------------------------------------------------
# wrapper semantics
# ---------------------------------------------------------------------------


def test_lock_wrapper_semantics(san):
    lk = threading.Lock()
    assert not lk.locked()
    with lk:
        assert lk.locked()
        # non-blocking acquire on a held lock fails without deadlocking
        # (same thread, non-reentrant Lock)
        assert lk.acquire(blocking=False) is False
    assert not lk.locked()
    assert san.acquisitions >= 1


def test_rlock_reentrancy(san):
    rl = threading.RLock()
    with rl:
        with rl:
            assert san._held.counts[id(rl)] == 2
        assert san._held.counts[id(rl)] == 1
    assert id(rl) not in san._held.counts


def test_condition_wait_notify_roundtrip(san):
    lk = threading.Lock()
    cond = threading.Condition(lk)
    ready = []

    def waiter():
        with cond:
            while not ready:
                if not cond.wait(timeout=2.0):
                    return
        ready.append("woke")

    t = threading.Thread(target=waiter, name="test-waiter", daemon=True)
    t.start()
    time.sleep(0.05)
    with cond:
        ready.append("go")
        cond.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert "woke" in ready
    # the backing lock was fully released during wait and re-tracked after
    assert id(lk) not in san._held.counts


def test_contention_is_counted(san):
    lk = threading.Lock()
    entered = threading.Event()

    def holder():
        with lk:
            entered.set()
            time.sleep(0.15)

    t = threading.Thread(target=holder, name="test-holder", daemon=True)
    t.start()
    entered.wait(timeout=2.0)
    with lk:
        pass
    t.join(timeout=5.0)
    assert san.contended >= 1
    assert san.gauges()["Sync/wait_ms_max"] > 0


# ---------------------------------------------------------------------------
# the seeded two-lock deadlock fixture
# ---------------------------------------------------------------------------


def test_seeded_two_lock_inversion_detected_without_hanging(san):
    """Two threads take the same two locks in opposite orders — the classic
    deadlock shape. The threads are serialized by an event so the suite can
    never actually deadlock; the sanitizer still sees the inverted order
    and reports it (never raises)."""
    a = threading.Lock()
    b = threading.Lock()
    first_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        first_done.set()

    def t2():
        first_done.wait(timeout=5.0)
        with b:
            with a:
                pass

    threads = [
        threading.Thread(target=t1, name="test-ab", daemon=True),
        threading.Thread(target=t2, name="test-ba", daemon=True),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive(), "fixture hung"
    assert len(san.violations) == 1
    v = san.violations[0]
    assert v["thread"] == "test-ba"
    assert san.gauges()["Sync/order_violations"] == 1.0


def test_violation_against_committed_dag():
    """An inversion of a COMMITTED edge is flagged on first sight — no need
    to observe the forward order in this process."""
    san = ts.ThreadSanitizer(
        {"concurrency": {"lock_order": {"edges": [["X", "Y"]]}}}
    )
    x = ts._InstrumentedLock(threading.Lock(), san, "X", False)
    y = ts._InstrumentedLock(threading.Lock(), san, "Y", False)
    # X -> Y matches the ledger: no violation
    with x:
        with y:
            pass
    assert not san.violations
    # Y -> X inverts it: violation
    with y:
        with x:
            pass
    assert len(san.violations) == 1
    assert san.violations[0]["held"] == "Y"
    assert san.violations[0]["acquiring"] == "X"


def test_committed_closure_catches_transitive_inversion():
    san = ts.ThreadSanitizer(
        {"concurrency": {"lock_order": {"edges": [["A", "B"], ["B", "C"]]}}}
    )
    assert ("A", "C") in san.committed
    a = ts._InstrumentedLock(threading.Lock(), san, "A", False)
    c = ts._InstrumentedLock(threading.Lock(), san, "C", False)
    with c:
        with a:  # inverts the transitive A -> C
            pass
    assert len(san.violations) == 1


def test_undeclared_edges_counted(san):
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    assert len(san.undeclared) == 1
    assert san.gauges()["Sync/undeclared_edges"] == 1.0
    assert san.gauges()["Sync/observed_edges"] == 1.0


def test_site_names_map_through_ledger_lock_sites(san):
    san.sites["sheeprl_tpu/flock/service.py:1"] = "flock.service.Svc._lock"
    assert (
        san.sites.get("sheeprl_tpu/flock/service.py:1")
        == "flock.service.Svc._lock"
    )
    # locks allocated here name by this test file's site (unmatched)
    lk = threading.Lock()
    assert "test_thread_sanitizer.py" in lk.sync_name


def test_hold_time_gauges(san):
    lk = threading.Lock()
    with lk:
        time.sleep(0.02)
    g = san.gauges()
    assert g["Sync/hold_ms_max"] >= 10.0
    assert g["Sync/hold_ms_avg"] > 0.0
