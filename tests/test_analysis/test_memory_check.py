"""sheepmem receipts (ISSUE 10 tentpole): each SC010-SC013 rule fires on a
known-bad fixture and stays silent on a clean control; the memory
fingerprint is deterministic and carries the realized-alias / embedded-
constant / scan-buffer structure the ledger commits; and the CI drift gate
fails on the injected regressions the ISSUE names (peak bloat, a lost
realized alias, a new large constant, a per-shard budget breach, a bf16
variant whose full-width activation bytes stop undercutting its f32 twin).

Fixture jits are lowered AND compiled on the conftest 8-virtual-CPU-device
harness — the analyzers read the optimized HLO and CompiledMemoryStats XLA
actually emits, not a mock of it."""

import functools
import json
import textwrap
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.analysis import jaxpr_check as jc
from sheeprl_tpu.analysis import memory_check as mc
from sheeprl_tpu.compile import sds


def _entry(name, fn, example):
    # analyze_entry only reads .name/.fn/.example — a namespace stands in
    # for a CompilePlan._Entry without the capture-mode env dance
    return SimpleNamespace(name=name, fn=fn, example=example)


def _rules_hit(report):
    return {f.rule.id for f in report.findings}


# ---------------------------------------------------------------------------
# clean control + fingerprint shape
# ---------------------------------------------------------------------------


def test_clean_control_donated_train_state():
    """The canonical state-in/state-out update with donation: the alias is
    realized, no findings, and the fingerprint is committable as-is."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, grads):
        return jax.tree_util.tree_map(lambda s, g: s - 0.1 * g, state, grads)

    ex = lambda: (  # noqa: E731
        sds((256, 4), jnp.float32), sds((256, 4), jnp.float32)
    )
    report = mc.analyze_entry("fix@clean", _entry("step", step, ex))
    assert report.error is None
    assert report.findings == [], [f.format() for f in report.findings]
    m = report.memory
    assert m["donated"] == 1
    assert m["aliases"] == ["out{}<-arg0"] or m["aliases"] == ["out{0}<-arg0"]
    assert m["argument_bytes"] == 2 * 256 * 4 * 4
    assert m["peak_bytes"] > 0
    assert m["declares_bf16"] is False
    json.dumps(m)  # the ledger must be committable as-is


def test_fingerprint_deterministic():
    @jax.jit
    def f(x):
        return jnp.tanh(x) * 2.0

    ex = lambda: (sds((64, 64), jnp.float32),)  # noqa: E731
    a = mc.analyze_entry("fix@det", _entry("f", f, ex)).memory
    b = mc.analyze_entry("fix@det", _entry("f", f, ex)).memory
    assert a == b


def test_entry_without_example_is_skipped():
    report = mc.analyze_entry("fix@skip", _entry("f", lambda x: x, None))
    assert report.error is not None and report.memory is None


# ---------------------------------------------------------------------------
# SC010: missed donation
# ---------------------------------------------------------------------------


def _sc010_fixture(donate: bool):
    jit = (
        functools.partial(jax.jit, donate_argnums=(0,)) if donate else jax.jit
    )

    @jit
    def step(state, lr):
        return jax.tree_util.tree_map(lambda s: s * (1.0 - lr), state)

    ex = lambda: (  # noqa: E731
        sds((512, 8), jnp.float32), sds((), jnp.float32)
    )
    return _entry("step", step, ex)


def test_sc010_undonated_matching_input_fires():
    report = mc.analyze_entry("fix@missed", _sc010_fixture(donate=False))
    assert "SC010" in _rules_hit(report)
    msgs = [f.message for f in report.findings if f.rule.id == "SC010"]
    assert any("not donated but byte-matches an output" in m for m in msgs)


def test_sc010_donated_control_is_clean():
    report = mc.analyze_entry("fix@missed", _sc010_fixture(donate=True))
    assert "SC010" not in _rules_hit(report)


def test_sc010_below_floor_is_silent(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_MEM_DONATION_FLOOR", str(1 << 20))
    report = mc.analyze_entry("fix@missed", _sc010_fixture(donate=False))
    assert "SC010" not in _rules_hit(report)


def test_sc010_suppression_carries_justification(monkeypatch):
    monkeypatch.setitem(
        mc.MEM_SUPPRESSIONS, ("fix@missed", "step", "SC010"), "caller re-reads"
    )
    report = mc.analyze_entry("fix@missed", _sc010_fixture(donate=False))
    hits = [f for f in report.findings if f.rule.id == "SC010"]
    assert hits and all(f.suppressed == "caller re-reads" for f in hits)
    assert report.failing == []


# ---------------------------------------------------------------------------
# SC011: declared donation XLA dropped (realized-alias receipt)
# ---------------------------------------------------------------------------


def test_sc011_dropped_donation_fires():
    """Donate an argument no output can alias (dtype change): the jaxpr
    screen (SC003) flags intent, and SC011 proves from the EXECUTABLE that
    XLA realized no alias."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(x):
        return x.astype(jnp.int32)

    ex = lambda: (sds((1024,), jnp.float32),)  # noqa: E731
    report = mc.analyze_entry("fix@dropped", _entry("step", step, ex))
    assert "SC011" in _rules_hit(report)
    msg = [f for f in report.findings if f.rule.id == "SC011"][0].message
    assert "NO realized input_output_alias" in msg
    assert report.memory["aliases"] == []
    assert report.memory["donated"] == 1


def test_sc011_realized_donation_control_is_clean():
    report = mc.analyze_entry("fix@dropped", _sc010_fixture(donate=True))
    assert "SC011" not in _rules_hit(report)
    assert len(report.memory["aliases"]) == 1


# ---------------------------------------------------------------------------
# SC012: executable-embedded constants
# ---------------------------------------------------------------------------

# random data: an arange would be strength-reduced to an iota by XLA and
# embed nothing — the closure must stay a real 128 KiB literal
_BIG_TABLE = jnp.asarray(
    np.random.RandomState(0).randn(32 * 1024).astype(np.float32)
)


def test_sc012_embedded_constant_fires():
    @jax.jit
    def step(x):
        return x + _BIG_TABLE

    ex = lambda: (sds((32 * 1024,), jnp.float32),)  # noqa: E731
    report = mc.analyze_entry("fix@const", _entry("step", step, ex))
    assert "SC012" in _rules_hit(report)
    assert report.memory["constant_bytes"] >= 128 * 1024
    assert any("f32[32768]" in c for c in report.memory["large_constants"])
    msg = [f for f in report.findings if f.rule.id == "SC012"][0].message
    assert "baked into" in msg


def test_sc012_argument_not_constant_is_clean():
    """The fix the rule prescribes: pass the table as an argument."""

    @jax.jit
    def step(x, table):
        return x + table

    ex = lambda: (  # noqa: E731
        sds((32 * 1024,), jnp.float32), sds((32 * 1024,), jnp.float32)
    )
    report = mc.analyze_entry("fix@const", _entry("step", step, ex))
    assert "SC012" not in _rules_hit(report)
    assert report.memory["large_constants"] == []


# ---------------------------------------------------------------------------
# SC013: per-shard peak over budget (mesh-bearing only)
# ---------------------------------------------------------------------------


def _mesh_fixture():
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    row = NamedSharding(mesh, P("data"))

    @jax.jit
    def step(x):
        return jnp.tanh(x * 2.0)

    ex = lambda: (sds((8, 4096), jnp.float32, row),)  # noqa: E731
    return _entry("step", step, ex)


def test_sc013_budget_breach_fires(monkeypatch):
    monkeypatch.setenv("SHEEPRL_TPU_MEM_PEAK_BUDGET_MB", "0")
    report = mc.analyze_entry("fix@mesh", _mesh_fixture())
    assert report.memory["num_partitions"] == 8
    assert "SC013" in _rules_hit(report)


def test_sc013_within_budget_and_single_device_silent(monkeypatch):
    report = mc.analyze_entry("fix@mesh", _mesh_fixture())
    assert "SC013" not in _rules_hit(report)
    # a single-device jit never trips SC013 even at budget 0
    monkeypatch.setenv("SHEEPRL_TPU_MEM_PEAK_BUDGET_MB", "0")
    report = mc.analyze_entry("fix@single", _sc010_fixture(donate=True))
    assert "SC013" not in _rules_hit(report)


# ---------------------------------------------------------------------------
# HLO parsing (deterministic unit receipts)
# ---------------------------------------------------------------------------

_HLO_FIXTURE = textwrap.dedent("""\
    HloModule jit_f, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, must-alias) }, entry_computation_layout={...}

    ENTRY %main (p0: f32[64,64], p1: f32[], p2: f32[64,64]) -> (f32[64,64], f32[64,64]) {
      %c0 = f32[] constant(2)
      %c1 = f32[64,64]{1,0} constant({...})
      %c2 = s32[128]{0} constant({...})
      %w = (s32[], f32[4,16]{1,0}, bf16[8]{0}) while((s32[], f32[4,16]{1,0}, bf16[8]{0}) %t), condition=%cond, body=%body, backend_config={"known_trip_count":{"n":"12"}}
    }
""")


def test_parse_io_aliases():
    assert mc.parse_io_aliases(_HLO_FIXTURE) == [
        "out{0}<-arg0", "out{1}<-arg2",
    ]
    assert mc.aliased_params(mc.parse_io_aliases(_HLO_FIXTURE)) == {0, 2}
    assert mc.parse_io_aliases("HloModule bare\n") == []


def test_parse_embedded_constants():
    consts = mc.parse_embedded_constants(_HLO_FIXTURE)
    assert (64 * 64 * 4, "f32[64,64]") in consts
    assert (128 * 4, "s32[128]") in consts
    assert consts[0] == (64 * 64 * 4, "f32[64,64]")  # largest first


def test_parse_scan_buffers():
    bufs = mc.parse_scan_buffers(_HLO_FIXTURE)
    assert bufs[0] == {"shape": "f32[4,16]", "bytes": 4 * 16 * 4, "trip_count": 12}
    shapes = {b["shape"] for b in bufs}
    assert "bf16[8]" in shapes and all(b["trip_count"] == 12 for b in bufs)


def test_scan_buffers_from_real_jit():
    @jax.jit
    def rollout(h, w):
        def body(c, _):
            return jnp.tanh(c @ w), c.sum()

        return jax.lax.scan(body, h, None, length=16)

    ex = lambda: (  # noqa: E731
        sds((32, 32), jnp.float32), sds((32, 32), jnp.float32)
    )
    report = mc.analyze_entry("fix@scan", _entry("rollout", rollout, ex))
    bufs = report.memory["scan_buffers"]
    assert bufs, "no while loop found in the optimized HLO"
    assert any(b["trip_count"] == 16 for b in bufs)
    assert max(b["bytes"] for b in bufs) >= 32 * 32 * 4


def test_remat_advice_ranks_by_bytes():
    advice = mc.remat_advice(
        {
            "a/big": {"scan_buffers": [
                {"shape": "f32[1024,1024]", "bytes": 1 << 22, "trip_count": 15}
            ]},
            "a/small": {"scan_buffers": [
                {"shape": "f32[8]", "bytes": 32, "trip_count": None}
            ]},
        }
    )
    assert "a/big" in advice[0] and "x15 known iterations" in advice[0]
    assert "a/small" in advice[1] and "unknown trip count" in advice[1]


# ---------------------------------------------------------------------------
# the memory ledger: round-trip + drift gate on injected regressions
# ---------------------------------------------------------------------------


def _fixture_budget():
    reports = [
        mc.analyze_entry("fix@led", _sc010_fixture(donate=True)),
        mc.analyze_entry("fix@led", _mesh_fixture()),
    ]
    reports[1].name = "mesh_step"
    assert all(r.memory is not None for r in reports)
    return mc.build_memory_budget(reports)


def test_memory_budget_round_trip_clean():
    ledger = _fixture_budget()
    failures, notes = mc.check_memory_budget(
        ledger, json.loads(json.dumps(ledger))
    )
    assert failures == [] and notes == []


def test_memory_gate_fails_on_injected_peak_bloat():
    ledger = _fixture_budget()
    drifted = json.loads(json.dumps(ledger))
    fp = drifted["memory"]["fix@led/step"]
    fp["peak_bytes"] = int(fp["peak_bytes"] * 1.5) + 8192
    failures, _ = mc.check_memory_budget(ledger, drifted)
    assert any("peak bytes grew" in f for f in failures)

    shrunk = json.loads(json.dumps(ledger))
    shrunk["memory"]["fix@led/step"]["peak_bytes"] = 16
    failures, notes = mc.check_memory_budget(ledger, shrunk)
    assert failures == []
    assert any("shrank" in n for n in notes)


def test_memory_gate_fails_on_lost_alias():
    ledger = _fixture_budget()
    drifted = json.loads(json.dumps(ledger))
    drifted["memory"]["fix@led/step"]["aliases"] = []
    failures, _ = mc.check_memory_budget(ledger, drifted)
    assert any("realized alias" in f and "lost" in f for f in failures)
    # the reverse direction (a NEW alias) is an improvement: note only
    failures, notes = mc.check_memory_budget(drifted, ledger)
    assert not any("alias" in f for f in failures)
    assert any("new realized alias" in n for n in notes)


def test_memory_gate_fails_on_new_large_constant():
    ledger = _fixture_budget()
    drifted = json.loads(json.dumps(ledger))
    drifted["memory"]["fix@led/step"]["large_constants"] = [
        "f32[65536]:262144"
    ]
    failures, _ = mc.check_memory_budget(ledger, drifted)
    assert any("new large embedded constant" in f for f in failures)


def test_memory_gate_fails_on_added_and_removed_jits():
    ledger = _fixture_budget()
    drifted = json.loads(json.dumps(ledger))
    drifted["memory"]["fix@led/new_jit"] = drifted["memory"]["fix@led/step"]
    failures, _ = mc.check_memory_budget(ledger, drifted)
    assert any("new jit not in the memory ledger" in f for f in failures)
    gone = json.loads(json.dumps(ledger))
    del gone["memory"]["fix@led/step"]
    failures, _ = mc.check_memory_budget(ledger, gone)
    assert any("disappeared" in f for f in failures)


def test_memory_gate_fails_on_mesh_budget_breach(monkeypatch):
    ledger = _fixture_budget()
    drifted = json.loads(json.dumps(ledger))
    monkeypatch.setenv("SHEEPRL_TPU_MEM_PEAK_BUDGET_MB", "0")
    failures, _ = mc.check_memory_budget(ledger, drifted)
    # only the mesh-bearing jit breaches; the single-device one is exempt
    assert any(
        "fix@led/mesh_step" in f and "exceeds" in f for f in failures
    )
    assert not any("fix@led/step:" in f and "exceeds" in f for f in failures)


def test_memory_gate_bf16_twin_receipt():
    """The ISSUE-9 byte receipt: a declared-bf16 jit whose full-width
    activation bytes do NOT undercut its f32 twin fails the gate."""
    base = {
        "peak_bytes": 1000, "aliases": [], "large_constants": [],
        "num_partitions": 1,
    }
    good = {
        "memory": {
            "a/f": {**base, "wide_activation_bytes": 1000},
            "a@bf16/f": {
                **base, "wide_activation_bytes": 400, "declares_bf16": True,
            },
        }
    }
    failures, notes = mc.check_memory_budget(good, good)
    assert failures == []
    assert any("wide activation bytes 400 vs f32 twin 1000" in n for n in notes)

    bad = json.loads(json.dumps(good))
    bad["memory"]["a@bf16/f"]["wide_activation_bytes"] = 1000
    failures, _ = mc.check_memory_budget(bad, bad)
    assert any("not below the f32 twin" in f for f in failures)

    # a variant jit that never declared bf16 compute is exempt
    undeclared = json.loads(json.dumps(bad))
    undeclared["memory"]["a@bf16/f"]["declares_bf16"] = False
    failures, _ = mc.check_memory_budget(undeclared, undeclared)
    assert failures == []


def test_memory_gate_int8_twin_receipt():
    """The ISSUE-20 byte receipt: a declared-int8 serving rung must carry
    strictly fewer argument bytes than its full-width twin."""
    base = {
        "peak_bytes": 1000, "aliases": [], "large_constants": [],
        "num_partitions": 1,
    }
    good = {
        "memory": {
            "serve/policy_b2": {**base, "argument_bytes": 1432},
            "serve@int8/policy_b2": {
                **base, "argument_bytes": 744, "declares_int8": True,
            },
        }
    }
    failures, notes = mc.check_memory_budget(good, good)
    assert failures == []
    assert any("argument bytes 744 vs full-width twin 1432" in n for n in notes)

    bad = json.loads(json.dumps(good))
    bad["memory"]["serve@int8/policy_b2"]["argument_bytes"] = 1432
    failures, _ = mc.check_memory_budget(bad, bad)
    assert any("not below the full-width twin" in f for f in failures)

    # an @int8 capture that fell back to f32 (calibration unavailable)
    # never declares int8 and is exempt from the receipt
    undeclared = json.loads(json.dumps(bad))
    undeclared["memory"]["serve@int8/policy_b2"]["declares_int8"] = False
    failures, _ = mc.check_memory_budget(undeclared, undeclared)
    assert failures == []


def test_real_bf16_twin_shows_lower_wide_activation_bytes():
    """The receipt on real programs: the same update traced under a
    bf16-compute policy must shrink its full-width intermediate bytes."""

    def update(w, x):
        h = jnp.tanh(x @ w)
        return (h @ w.T).sum()

    def update_bf16(w, x):
        wb, xb = w.astype(jnp.bfloat16), x.astype(jnp.bfloat16)
        h = jnp.tanh(xb @ wb)
        return (h @ wb.T).sum().astype(jnp.float32)

    ex = lambda: (  # noqa: E731
        sds((64, 64), jnp.float32), sds((32, 64), jnp.float32)
    )
    f32 = mc.analyze_entry("twin", _entry("update", jax.jit(update), ex))
    bf16 = mc.analyze_entry(
        "twin@bf16", _entry("update", jax.jit(update_bf16), ex)
    )
    assert bf16.memory["declares_bf16"] and not f32.memory["declares_bf16"]
    assert (
        bf16.memory["wide_activation_bytes"]
        < f32.memory["wide_activation_bytes"]
    )
    derived = {
        "memory": {
            "twin/update": f32.memory,
            "twin@bf16/update": bf16.memory,
        }
    }
    failures, notes = mc.check_memory_budget(derived, derived)
    assert failures == []
    assert any("wide activation bytes" in n for n in notes)


# ---------------------------------------------------------------------------
# ledger persistence + the committed repo ledger
# ---------------------------------------------------------------------------


def test_memory_section_coexists_with_other_sections(tmp_path):
    """sheepmem owns `memory`; the other tools' sections survive its saves
    (and vice versa) in the per-spec dir layout."""
    path = str(tmp_path / "budget.json")
    jits = {
        "version": 1, "jax_version": jax.__version__,
        "tolerance": {"op_count_frac": 0.25},
        "jits": {"fix@led/step": {"op_count": 3, "dtypes": ["float32"]}},
    }
    jc.save_budget(jits, path, sections=("jits",))
    memory = _fixture_budget()
    jc.save_budget(memory, path, sections=("memory",))
    merged = jc.load_budget(path)
    assert merged["jits"] == jits["jits"]
    assert merged["memory"] == memory["memory"]
    assert merged["tolerance"]["op_count_frac"] == 0.25
    assert merged["tolerance"]["peak_bytes_frac"] == 0.25
    # re-saving jits must not clobber memory
    jc.save_budget(jits, path, sections=("jits",))
    assert jc.load_budget(path)["memory"] == memory["memory"]


def test_committed_ledger_carries_memory_for_every_spec():
    """ISSUE acceptance: every capture spec's file carries a `memory`
    section, and the fingerprints have the gated fields."""
    import os

    import sheeprl_tpu

    repo = os.path.dirname(os.path.dirname(sheeprl_tpu.__file__))
    ledger = jc.load_budget(os.path.join(repo, "analysis", "budget.json"))
    memory = ledger.get("memory", {})
    assert len(memory) >= 73, f"only {len(memory)} memory fingerprints"
    specs = {k.split("/", 1)[0] for k in memory}
    for required in (
        "ppo", "sac_ae", "dreamer_v3", "ppo@bf16", "dreamer_v3@bf16",
        "ppo@anakin", "dreamer_v3@anakin", "ppo@mesh8", "dreamer_v3@seq",
        "ppo_decoupled@mesh", "sac_decoupled@mesh", "dreamer_v3_decoupled@mesh",
    ):
        assert required in specs, f"{required} missing from the memory ledger"
    for key, fp in memory.items():
        for field in (
            "peak_bytes", "temp_bytes", "argument_bytes", "aliases",
            "wide_activation_bytes", "num_partitions", "scan_buffers",
        ):
            assert field in fp, (key, field)
    # the committed ledger itself satisfies the bf16 twin receipt
    failures, _ = mc.check_memory_budget(ledger, ledger)
    assert failures == [], failures
    # mesh-bearing specs committed a >1-partition (per-shard) view
    assert memory["ppo@mesh8/train_step"]["num_partitions"] == 8


def test_sheepmem_cli_gate_fails_on_injected_regression(tmp_path):
    """ISSUE acceptance: the CLI exits non-zero on an injected peak-memory
    regression and on a lost realized alias — against a fixture ledger so
    the test stays capture-free (the PR 7/8 gate-verification pattern)."""
    import sys
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "sheepmem_cli",
        jc.os.path.join(
            jc.os.path.dirname(jc.os.path.dirname(jc.os.path.abspath(jc.__file__))),
            jc.os.path.pardir, "tools", "sheepmem.py",
        ),
    )
    # the tool re-execs only when the virtual-device flag is missing; under
    # the test harness it is already set, so import is side-effect-free
    tool = importlib.util.module_from_spec(spec)
    sys.modules["sheepmem_cli"] = tool
    spec.loader.exec_module(tool)

    path = str(tmp_path / "budget.json")
    ledger = _fixture_budget()
    # the committed ledger claims a LOWER peak and an alias the derived
    # sweep will not reproduce -> drift, exit 1
    tampered = json.loads(json.dumps(ledger))
    fp = tampered["memory"]["fix@led/step"]
    fp["peak_bytes"] = max(int(fp["peak_bytes"] * 0.5) - 8192, 1)
    fp["aliases"] = ["out{0}<-arg0", "out{9}<-arg9"]
    failures, _ = mc.check_memory_budget(tampered, ledger)
    assert any("peak bytes grew" in f for f in failures)
    assert any("lost" in f for f in failures)
    jc.save_budget(tampered, path, sections=("memory",))
    # no capture specs resolve from a fixture ledger through the CLI, so
    # drive the gate exactly as main() does: load, filter, check
    loaded = jc.load_budget(path)
    failures2, _ = mc.check_memory_budget(loaded, ledger)
    assert failures2, "gate must fail on the injected regression"


@pytest.mark.timeout(600)
def test_sac_capture_end_to_end(tmp_path):
    """One real capture through the sweep machinery: sac's registered jits
    compile, fingerprint, and come back finding-free (modulo justified
    suppressions) — and the derived entries match the committed ledger
    within the gate's tolerances."""
    algo, extra_argv = mc.resolve_capture("sac")
    plan = jc.capture_plan(algo, str(tmp_path), extra_argv=extra_argv)
    reports = mc.analyze_mem_plan("sac", plan)
    analyzed = [r for r in reports if r.memory is not None]
    assert {r.name for r in analyzed} >= {"train_step", "policy_step"}
    for r in reports:
        assert r.failing == [], [f.format() for f in r.failing]
    derived = mc.build_memory_budget(reports)
    import os

    import sheeprl_tpu

    repo = os.path.dirname(os.path.dirname(sheeprl_tpu.__file__))
    ledger = jc.load_budget(os.path.join(repo, "analysis", "budget.json"))
    committed_sac = {
        k: v for k, v in ledger.get("memory", {}).items()
        if k.startswith("sac/")
    }
    failures, _ = mc.check_memory_budget(
        {**ledger, "memory": committed_sac}, derived
    )
    assert failures == [], failures
