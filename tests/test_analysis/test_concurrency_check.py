"""sheepsync static rules (ISSUE 18): per-rule known-bad fixtures vs clean
controls, suppression honoring, the concurrency ledger round-trip, and the
--check-budget drift gate (injected cycle / unguarded write / new thread).
Pure AST — no jax import, mirrors test_jaxpr_check's fixture style."""

from sheeprl_tpu.analysis import concurrency_check as cc

FLOCK_FIXTURE = "sheeprl_tpu/flock/fixture.py"


def _ids(report):
    return [f.rule.id for f in report.active_findings]


def _analyze(src):
    return cc.analyze_source(src, relpath=FLOCK_FIXTURE)


# ---------------------------------------------------------------------------
# SY001: lock-order cycles
# ---------------------------------------------------------------------------

SY001_BAD = """
import threading
_A = threading.Lock()
_B = threading.Lock()

def f():
    with _A:
        with _B:
            pass

def g():
    with _B:
        with _A:
            pass
"""

SY001_CLEAN = """
import threading
_A = threading.Lock()
_B = threading.Lock()

def f():
    with _A:
        with _B:
            pass

def g():
    with _A:
        with _B:
            pass
"""


def test_sy001_cycle_detected_with_both_chains():
    report = _analyze(SY001_BAD)
    findings = [f for f in report.active_findings if f.rule.id == "SY001"]
    assert findings, _ids(report)
    msg = findings[0].message
    assert "[chain 1]" in msg and "[chain 2]" in msg
    assert report.cycles


def test_sy001_consistent_order_is_clean():
    report = _analyze(SY001_CLEAN)
    assert "SY001" not in _ids(report)
    assert ("flock.fixture._A", "flock.fixture._B") in report.edges


def test_sy001_self_deadlock_through_helper():
    src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        with self._lock:
            self._g()

    def _g(self):
        with self._lock:
            pass
"""
    report = _analyze(src)
    findings = [f for f in report.active_findings if f.rule.id == "SY001"]
    assert findings and "self-deadlock" in findings[0].message


# ---------------------------------------------------------------------------
# SY002: blocking call under a held lock
# ---------------------------------------------------------------------------

SY002_BAD = """
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        with self._lock:
            time.sleep(0.5)
"""

SY002_CLEAN = """
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        time.sleep(0.5)
        with self._lock:
            pass
"""


def test_sy002_sleep_under_lock():
    assert "SY002" in _ids(_analyze(SY002_BAD))


def test_sy002_sleep_outside_lock_is_clean():
    assert "SY002" not in _ids(_analyze(SY002_CLEAN))


def test_sy002_interprocedural_reaches_blocking():
    src = """
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        with self._lock:
            self._g()

    def _g(self):
        time.sleep(0.5)
"""
    report = _analyze(src)
    findings = [f for f in report.active_findings if f.rule.id == "SY002"]
    assert findings, _ids(report)
    assert "reaches" in findings[0].message


# ---------------------------------------------------------------------------
# SY003: unguarded shared writes
# ---------------------------------------------------------------------------

SY003_BAD = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t = threading.Thread(target=self._loop, name="c-loop", daemon=True)

    def _loop(self):
        self.count += 1

    def bump(self):
        self.count += 1
"""

SY003_CLEAN = SY003_BAD.replace(
    "        self.count += 1",
    "        with self._lock:\n            self.count += 1",
)


def test_sy003_unguarded_shared_write():
    report = _analyze(SY003_BAD)
    findings = [f for f in report.active_findings if f.rule.id == "SY003"]
    assert findings, _ids(report)
    assert "thread:_loop" in findings[0].message
    assert report.guards["flock"]["C.count"] is None


def test_sy003_guarded_write_is_clean_and_mapped():
    report = _analyze(SY003_CLEAN)
    assert "SY003" not in _ids(report)
    assert report.guards["flock"]["C.count"] == "flock.fixture.C._lock"


# ---------------------------------------------------------------------------
# SY004: manual acquire without try/finally release
# ---------------------------------------------------------------------------

SY004_BAD = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        self._lock.acquire()
        self.x = 1
        self._lock.release()
"""

SY004_CLEAN = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        self._lock.acquire()
        try:
            self.x = 1
        finally:
            self._lock.release()
"""


def test_sy004_bare_acquire():
    assert "SY004" in _ids(_analyze(SY004_BAD))


def test_sy004_try_finally_is_clean():
    assert "SY004" not in _ids(_analyze(SY004_CLEAN))


# ---------------------------------------------------------------------------
# SY005: Condition.wait outside a predicate loop
# ---------------------------------------------------------------------------

SY005_BAD = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.ready = False

    def f(self):
        with self._cond:
            self._cond.wait(1.0)
"""

SY005_CLEAN = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.ready = False

    def f(self):
        with self._cond:
            while not self.ready:
                self._cond.wait(0.1)
"""


def test_sy005_wait_without_loop():
    assert "SY005" in _ids(_analyze(SY005_BAD))


def test_sy005_predicate_loop_is_clean():
    assert "SY005" not in _ids(_analyze(SY005_CLEAN))


# ---------------------------------------------------------------------------
# SY006: FLK1 protocol sequencing
# ---------------------------------------------------------------------------

SY006_FRESH_BAD = """
from sheeprl_tpu.flock import wire

def push_first(addr):
    sock = wire.connect(addr)
    wire.send_json(sock, wire.PUSH, {})
"""

SY006_FRESH_CLEAN = """
from sheeprl_tpu.flock import wire

def hello_first(addr):
    sock = wire.connect(addr)
    wire.send_json(sock, wire.HELLO, {})
    wire.send_json(sock, wire.PUSH, {})
"""

SY006_REPLY_BAD = """
from sheeprl_tpu.flock import wire

def rogue(sock):
    wire.send_frame(sock, wire.WELCOME, b"")
"""

SY006_REPLY_CLEAN = """
from sheeprl_tpu.flock import wire

def handler(sock):
    kind, payload = wire.recv_frame(sock)
    wire.send_frame(sock, wire.WELCOME, b"")
"""


def test_sy006_fresh_socket_must_open_with_hello():
    report = _analyze(SY006_FRESH_BAD)
    findings = [f for f in report.active_findings if f.rule.id == "SY006"]
    assert findings and "HELLO" in findings[0].message


def test_sy006_hello_first_is_clean():
    assert "SY006" not in _ids(_analyze(SY006_FRESH_CLEAN))


def test_sy006_reply_kind_outside_handler():
    report = _analyze(SY006_REPLY_BAD)
    findings = [f for f in report.active_findings if f.rule.id == "SY006"]
    assert findings and "WELCOME" in findings[0].message


def test_sy006_reply_inside_handler_is_clean():
    assert "SY006" not in _ids(_analyze(SY006_REPLY_CLEAN))


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def test_suppression_with_justification_downgrades_finding(monkeypatch):
    monkeypatch.setitem(
        cc.SYNC_SUPPRESSIONS,
        (FLOCK_FIXTURE, "C.f", "SY002"),
        "test: sleep under lock is the fixture's point",
    )
    report = _analyze(SY002_BAD)
    assert "SY002" not in _ids(report)
    sup = [f for f in report.suppressed_findings if f.rule.id == "SY002"]
    assert sup and sup[0].suppressed.startswith("test:")


# ---------------------------------------------------------------------------
# Ledger + drift gate
# ---------------------------------------------------------------------------


def test_ledger_roundtrip(tmp_path):
    ledger = cc.build_ledger(_analyze(SY003_CLEAN))
    path = cc.save_ledger(ledger, tmp_path / "concurrency.json")
    loaded = cc.load_ledger(path)
    assert loaded == ledger
    assert loaded["concurrency"]["fingerprint"]
    assert "flock" in loaded["concurrency"]["roles"]


def test_check_budget_flags_injected_cycle():
    committed = cc.build_ledger(_analyze(SY001_CLEAN))
    current = cc.build_ledger(_analyze(SY001_BAD))
    regs = cc.check_budget(current, committed)
    assert any("new lock-order edge" in r for r in regs)
    assert any("cycle" in r for r in regs)


def test_check_budget_flags_newly_unguarded_write():
    committed = cc.build_ledger(_analyze(SY003_CLEAN))
    current = cc.build_ledger(_analyze(SY003_BAD))
    regs = cc.check_budget(current, committed)
    assert any("newly unguarded shared write" in r for r in regs)


def test_check_budget_flags_new_undeclared_thread():
    extra = SY003_CLEAN + """
def spawn_extra():
    t = threading.Thread(target=print, name="rogue", daemon=True)
    t.start()
"""
    committed = cc.build_ledger(_analyze(SY003_CLEAN))
    current = cc.build_ledger(_analyze(extra))
    regs = cc.check_budget(current, committed)
    assert any("new undeclared thread" in r for r in regs)


def test_check_budget_identical_is_clean():
    ledger = cc.build_ledger(_analyze(SY003_CLEAN))
    assert cc.check_budget(ledger, ledger) == []


def test_check_budget_requires_committed_ledger():
    regs = cc.check_budget(cc.build_ledger(_analyze(SY001_CLEAN)), None)
    assert regs and "--update-budget" in regs[0]


# ---------------------------------------------------------------------------
# The repo itself (ISSUE 18 acceptance: clean at HEAD, ledger current)
# ---------------------------------------------------------------------------


def test_repo_is_sheepsync_clean_and_ledger_current():
    report = cc.analyze_paths()
    assert not report.active_findings, "\n" + "\n".join(
        f.format() for f in report.active_findings
    )
    # every suppression that fired carries a justification
    for f in report.suppressed_findings:
        assert f.suppressed
    regs = cc.check_budget(cc.build_ledger(report), cc.load_ledger())
    assert not regs, "\n".join(regs)
    # acceptance: the committed ledger covers flock+serve+telemetry
    roles = cc.load_ledger()["concurrency"]["roles"]
    for role in ("flock", "serve", "telemetry"):
        assert role in roles
