"""Equivalence receipts for the critical-path latency-hiding primitives
(ISSUE 4): ActionPipeline ordering, the SamplePrefetcher epoch-consistency
guard under concurrent adds, MetricDrain value equality vs eager compute,
and a DreamerV3 e2e dry run whose ring contents and train math match the
synchronous path bit-exactly with `--pipeline on`."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data import AsyncReplayBuffer, ReplayBuffer
from sheeprl_tpu.parallel.pipeline import (
    ActionPipeline,
    MetricDrain,
    Pipeline,
    PipelineStats,
    SamplePrefetcher,
)
from sheeprl_tpu.utils.metric import MetricAggregator, MovingAverageMetric


# ---------------------------------------------------------------------------
# ActionPipeline
# ---------------------------------------------------------------------------


def test_action_pipeline_fetch_matches_sync_pull():
    pipe = ActionPipeline(enabled=True, lag=0)
    dev = jnp.arange(6, dtype=jnp.int32)
    out = pipe.fetch(dev)
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, np.arange(6, dtype=np.int32))
    # pytrees and host leaves pass through unchanged
    tree = {"a": jnp.ones((2, 3)), "b": np.full(4, 7.0)}
    host = pipe.fetch(tree)
    np.testing.assert_array_equal(host["a"], np.ones((2, 3)))
    np.testing.assert_array_equal(host["b"], np.full(4, 7.0))


def test_action_pipeline_disabled_is_sync():
    pipe = ActionPipeline(enabled=False, lag=0)
    out = pipe.fetch(jnp.arange(3))
    np.testing.assert_array_equal(out, np.arange(3))
    assert pipe._stats.action_fetches == 0  # disabled mode is unaccounted


def test_action_pipeline_ordering_dispatch_then_read():
    """Action t is consumed (read) before obs t+1 would be dispatched: the
    handle returned for step t resolves to step t's values regardless of
    how many later dispatches were issued in between."""
    pipe = ActionPipeline(enabled=True)
    handles = [pipe.dispatch(jnp.full((2,), t, jnp.int32)) for t in range(5)]
    for t, h in enumerate(handles):
        np.testing.assert_array_equal(h.get(), np.full((2,), t, np.int32))
    assert pipe._stats.action_fetches == 5
    assert pipe._stats.action_wait_s >= 0.0


def test_action_pipeline_one_step_lag_fifo():
    """lag=1: the first fetch primes the FIFO (returns None), and fetch t
    then returns the value dispatched at t-1 — the one-step-lagged overlap
    contract (howto/pipelining.md)."""
    pipe = ActionPipeline(enabled=True, lag=1)
    assert pipe.fetch(jnp.int32(0)) is None
    for t in range(1, 5):
        got = pipe.fetch(jnp.int32(t))
        assert int(got) == t - 1
    leftover = pipe.flush()
    assert [int(v) for v in leftover] == [4]
    assert pipe.flush() == []


# ---------------------------------------------------------------------------
# SamplePrefetcher
# ---------------------------------------------------------------------------


def _row(rng, n_envs):
    return {
        "obs": rng.normal(size=(1, n_envs, 3)).astype(np.float32),
        "rewards": rng.normal(size=(1, n_envs, 1)).astype(np.float32),
    }


def _fill(rb, rng, n_rows, n_envs):
    for _ in range(n_rows):
        rb.add(_row(rng, n_envs))


def _assert_batches_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_sample_prefetcher_hits_on_quiet_buffer():
    """With no writes between samples (a pretrain/catch-up burst), the
    prefetched batch is served and is identical to what the synchronous
    path would have drawn."""
    rng = np.random.default_rng(0)
    rb = AsyncReplayBuffer(64, 2, storage="device", obs_keys=("obs",), seed=3)
    rb_sync = AsyncReplayBuffer(64, 2, storage="device", obs_keys=("obs",), seed=3)
    rows = [_row(rng, 2) for _ in range(16)]
    for r in rows:
        rb.add(r)
        rb_sync.add(r)
    stats = PipelineStats()
    pre = SamplePrefetcher(rb, enabled=True, stats=stats)
    for _ in range(6):
        _assert_batches_equal(pre.sample(4), rb_sync.sample(4))
    assert stats.sample_hits >= 4  # first serve is fresh, the rest hit
    assert stats.sample_misses == 0


def test_sample_prefetcher_epoch_guard_under_concurrent_adds():
    """Writes between samples invalidate the prefetch: the guard discards
    it, rewinds the sampler PRNG, and the fresh resample matches the
    synchronous path bit-exactly (same keys, same rows)."""
    rng = np.random.default_rng(1)
    rb = AsyncReplayBuffer(64, 2, storage="device", obs_keys=("obs",), seed=5)
    rb_sync = AsyncReplayBuffer(64, 2, storage="device", obs_keys=("obs",), seed=5)
    rows = [_row(rng, 2) for _ in range(40)]
    for r in rows[:16]:
        rb.add(r)
        rb_sync.add(r)
    pre = SamplePrefetcher(rb, enabled=True)
    for r in rows[16:]:
        _assert_batches_equal(pre.sample(4), rb_sync.sample(4))
        rb.add(r)  # concurrent add: advances the epoch past any prefetch
        rb_sync.add(r)
    # and the final state agrees too: one more quiet pair
    _assert_batches_equal(pre.sample(4), rb_sync.sample(4))


def test_sample_prefetcher_epoch_guard_replay_buffer():
    """Same receipt on the base ReplayBuffer (SAC-family rings), including
    a call-signature change (which must also rewind)."""
    rng = np.random.default_rng(2)
    rb = ReplayBuffer(64, 2, storage="device", obs_keys=("obs",), seed=9)
    rb_sync = ReplayBuffer(64, 2, storage="device", obs_keys=("obs",), seed=9)
    rows = [_row(rng, 2) for _ in range(24)]
    for r in rows[:8]:
        rb.add(r)
        rb_sync.add(r)
    pre = SamplePrefetcher(rb, enabled=True)
    sizes = [4, 4, 6, 4]  # the 6 forces a signature-mismatch discard
    for r, bs in zip(rows[8:], sizes):
        _assert_batches_equal(pre.sample(bs), rb_sync.sample(bs))
        rb.add(r)
        rb_sync.add(r)


def test_sample_prefetcher_staleness_opt_in():
    """max_staleness > 0 serves the prefetched (one-epoch-stale) batch — a
    consistent snapshot of the ring at prefetch time."""
    rng = np.random.default_rng(3)
    rb = AsyncReplayBuffer(64, 2, storage="device", obs_keys=("obs",), seed=11)
    _fill(rb, rng, 16, 2)
    stats = PipelineStats()
    pre = SamplePrefetcher(rb, enabled=True, max_staleness=4, stats=stats)
    pre.sample(4)  # fresh + prefetch
    rb.add(_row(rng, 2))
    pre.sample(4)  # stale by 1 epoch <= 4: served
    assert stats.sample_hits == 1


def test_sample_prefetcher_host_buffer_passthrough():
    """Host-storage rings gather synchronously on host — the wrapper stays
    a passthrough (no prefetch, identical sampling)."""
    rng = np.random.default_rng(4)
    rb = ReplayBuffer(32, 2, storage="host", obs_keys=("obs",), seed=13)
    rb_sync = ReplayBuffer(32, 2, storage="host", obs_keys=("obs",), seed=13)
    rows = [_row(rng, 2) for _ in range(8)]
    for r in rows:
        rb.add(r)
        rb_sync.add(r)
    stats = PipelineStats()
    pre = SamplePrefetcher(rb, enabled=True, stats=stats)
    assert not pre.enabled
    for _ in range(3):
        _assert_batches_equal(pre.sample(4), rb_sync.sample(4))
    assert stats.sample_prefetches == 0


# ---------------------------------------------------------------------------
# MetricDrain
# ---------------------------------------------------------------------------


def _feed(agg):
    agg.update("Loss/a", jnp.float32(1.5))
    agg.update("Loss/a", jnp.float32(2.5))
    agg.update("Loss/b", 3.0)


def test_metric_drain_value_equality_vs_eager():
    """The deferred drain logs exactly the values eager compute would
    have, tagged with the interval they were measured in (one interval
    later in wall-clock)."""
    eager, deferred = MetricAggregator(), MetricAggregator()
    eager.add("win", MovingAverageMetric(window=4))
    deferred.add("win", MovingAverageMetric(window=4))
    drain = MetricDrain(enabled=True)
    logged: list = []
    for step in range(1, 4):
        for agg in (eager, deferred):
            _feed(agg)
            agg.update("win", float(step))
        expected = (eager.compute(), step)
        eager.reset()
        logged.extend(drain.drain(deferred, step))
        # drained output lags one interval; compare when it lands
        if step > 1:
            assert logged[-1][1] == step - 1
        globals().setdefault("_expect", []).append(expected)
    logged.extend(drain.flush())
    expected_all = globals().pop("_expect")
    assert len(logged) == len(expected_all)
    for (got, gstep), (want, wstep) in zip(logged, expected_all):
        assert gstep == wstep
        assert got == want  # exact float equality: same ops on same values


def test_metric_drain_disabled_is_eager():
    agg = MetricAggregator()
    _feed(agg)
    drain = MetricDrain(enabled=False)
    out = drain.drain(agg, 7)
    assert out == [({"Loss/a": 2.0, "Loss/b": 3.0}, 7)]
    assert agg.compute() == {}  # reset happened
    assert drain.flush() == []


def test_pipeline_facade_gauges_and_modes():
    class _Args:
        pipeline = "on"

    pipe = Pipeline.from_args(_Args())
    assert pipe.enabled
    pipe.action.fetch(jnp.arange(2))
    g = pipe.gauges()
    assert "Pipeline/action_wait_ms" in g and g["Pipeline/action_fetches"] == 1.0
    # flush zeroes the window
    assert pipe.gauges()["Pipeline/action_fetches"] == 0.0

    class _Off:
        pipeline = "off"

    assert not Pipeline.from_args(_Off()).enabled


def test_pipeline_sampler_is_cached_per_buffer():
    pipe = Pipeline(enabled=True)
    rb = AsyncReplayBuffer(16, 1, storage="device", obs_keys=("obs",), seed=0)
    assert pipe.sampler(rb) is pipe.sampler(rb)


# ---------------------------------------------------------------------------
# DreamerV3 end-to-end equivalence: --pipeline on == off, bit for bit
# ---------------------------------------------------------------------------

TINY = [
    "--dry_run",
    "--num_devices=1",
    "--num_envs=1",
    "--sync_env",
    "--per_rank_batch_size=1",
    "--per_rank_sequence_length=1",
    "--buffer_size=4",
    "--learning_starts=0",
    "--gradient_steps=1",
    "--horizon=4",
    "--dense_units=8",
    "--cnn_channels_multiplier=2",
    "--recurrent_state_size=8",
    "--hidden_size=8",
    "--stochastic_size=4",
    "--discrete_size=4",
    "--mlp_layers=1",
    "--train_every=1",
    "--checkpoint_every=1",
    "--checkpoint_buffer",
    "--env_id=discrete_dummy",
    "--cnn_keys", "rgb",
    "--seed=7",
]


def _loss_events(log_dir):
    """step -> {Loss/*: value} from the run's telemetry.jsonl."""
    out = {}
    with open(os.path.join(log_dir, "telemetry.jsonl")) as fh:
        for line in fh:
            ev = json.loads(line)
            if ev.get("event") != "log":
                continue
            losses = {
                k: v for k, v in ev.get("metrics", {}).items()
                if k.startswith("Loss/")
            }
            if losses:
                out.setdefault(ev["step"], {}).update(losses)
    return out


@pytest.mark.timeout(600)
def test_dv3_e2e_pipeline_on_matches_sync_bit_exact(tmp_path):
    """The flagship equivalence receipt: one DreamerV3 dry-run cycle with
    `--pipeline on` produces the same replay ring bits and the same logged
    train losses as `--pipeline off` (same seed) — the pipeline hides
    latency without changing a single value."""
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import main

    for mode in ("off", "on"):
        main(TINY + [f"--root_dir={tmp_path}", f"--run_name={mode}", f"--pipeline={mode}"])

    def ring(mode):
        paths = glob.glob(str(tmp_path / mode / "checkpoints" / "ckpt_*_buffer.npz"))
        assert paths, f"no buffer checkpoint for {mode}"
        return dict(np.load(paths[0]))

    off, on = ring("off"), ring("on")
    assert set(off) == set(on)
    for k in off:
        if k == "sampler_state":
            # the checkpointed sampler PRNG (ISSUE 12) is legitimately one
            # draw ahead under the prefetcher at save time; the ring bits
            # and the logged losses below are the equivalence contract
            continue
        np.testing.assert_array_equal(off[k], on[k], err_msg=f"ring key {k}")

    losses_off = _loss_events(str(tmp_path / "off"))
    losses_on = _loss_events(str(tmp_path / "on"))
    assert losses_off and losses_off.keys() == losses_on.keys()
    for step, vals in losses_off.items():
        assert vals == losses_on[step], f"train metrics diverge at step {step}"
