"""MineDojo wrapper unit tests against the scripted mock backend — the
mapping logic the reference leaves untested (its wrapper requires a live
Minecraft): 19-action table, sticky attack/jump, craft/equip argument
compilation, pitch limits, inventory/equipment/mask conversion."""

import numpy as np
import pytest

from sheeprl_tpu.envs.minedojo import (
    ACTION_TABLE,
    ActionTranslator,
    MineDojoWrapper,
    N_HIGH_LEVEL_ACTIONS,
)
from sheeprl_tpu.envs.minedojo_mock import (
    FakeMineDojoBackend,
    MOCK_CRAFT_ITEMS,
    MOCK_ITEMS,
)


def make_env(**kwargs):
    backend = FakeMineDojoBackend(episode_length=kwargs.pop("episode_length", 16))
    env = MineDojoWrapper("harvest_milk", backend=backend, **kwargs)
    return env, backend


# ---- action table ------------------------------------------------------------


def test_action_table_shape_and_noop():
    assert ACTION_TABLE.shape == (N_HIGH_LEVEL_ACTIONS, 8)
    np.testing.assert_array_equal(ACTION_TABLE[0], [0, 0, 0, 12, 12, 0, 0, 0])
    # reference table spot checks (minedojo.py:16-36)
    np.testing.assert_array_equal(ACTION_TABLE[1], [1, 0, 0, 12, 12, 0, 0, 0])
    np.testing.assert_array_equal(ACTION_TABLE[5], [1, 0, 1, 12, 12, 0, 0, 0])
    np.testing.assert_array_equal(ACTION_TABLE[7], [1, 0, 3, 12, 12, 0, 0, 0])
    np.testing.assert_array_equal(ACTION_TABLE[8], [0, 0, 0, 11, 12, 0, 0, 0])
    np.testing.assert_array_equal(ACTION_TABLE[11], [0, 0, 0, 12, 13, 0, 0, 0])
    np.testing.assert_array_equal(ACTION_TABLE[14], [0, 0, 0, 12, 12, 3, 0, 0])
    np.testing.assert_array_equal(ACTION_TABLE[18], [0, 0, 0, 12, 12, 7, 0, 0])


# ---- translator --------------------------------------------------------------


def test_sticky_attack_repeats_on_noop():
    tr = ActionTranslator(sticky_attack=3, sticky_jump=0)
    assert tr.translate([14, 0, 0], {})[5] == 3  # attack
    assert tr.attack_counter == 2
    assert tr.translate([0, 0, 0], {})[5] == 3  # noop -> repeated attack
    assert tr.translate([0, 0, 0], {})[5] == 3
    assert tr.attack_counter == 0
    assert tr.translate([0, 0, 0], {})[5] == 0  # counter exhausted


def test_sticky_attack_cancelled_by_other_functional():
    tr = ActionTranslator(sticky_attack=10, sticky_jump=0)
    tr.translate([14, 0, 0], {})
    assert tr.attack_counter == 9
    assert tr.translate([12, 0, 0], {})[5] == 1  # use cancels the sticky attack
    assert tr.attack_counter == 0
    assert tr.translate([0, 0, 0], {})[5] == 0


def test_sticky_jump_repeats_with_forward_default():
    tr = ActionTranslator(sticky_attack=0, sticky_jump=3)
    native = tr.translate([5, 0, 0], {})  # jump+forward
    assert native[2] == 1 and native[0] == 1
    assert tr.jump_counter == 2
    native = tr.translate([0, 0, 0], {})  # noop -> sticky jump + forward
    assert native[2] == 1 and native[0] == 1
    native = tr.translate([3, 0, 0], {})  # left chosen: jump sticks, no fwd
    assert native[2] == 1 and native[1] == 1 and native[0] == 0
    assert tr.jump_counter == 0


def test_craft_and_item_arguments():
    tr = ActionTranslator(sticky_attack=0, sticky_jump=0)
    native = tr.translate([15, 2, 4], {})  # craft with craft-arg 2
    assert native[5] == 4 and native[6] == 2 and native[7] == 0
    slots = {3: 5}  # item id 3 lives in inventory slot 5
    native = tr.translate([16, 2, 3], slots)  # equip item 3
    assert native[5] == 5 and native[6] == 0 and native[7] == 5
    native = tr.translate([18, 0, 3], slots)  # destroy item 3
    assert native[5] == 7 and native[7] == 5
    # item not in inventory -> slot 0 fallback (reference raises KeyError)
    native = tr.translate([17, 0, 1], slots)
    assert native[5] == 6 and native[7] == 0


# ---- wrapper: spaces + observation conversion --------------------------------


def test_spaces():
    env, _ = make_env()
    n_items, n_craft = len(MOCK_ITEMS), len(MOCK_CRAFT_ITEMS)
    np.testing.assert_array_equal(
        env.action_space.nvec, [N_HIGH_LEVEL_ACTIONS, n_craft, n_items]
    )
    assert set(env.observation_space.spaces) == {
        "rgb", "inventory", "inventory_max", "inventory_delta", "equipment",
        "life_stats", "mask_action_type", "mask_equip/place", "mask_destroy",
        "mask_craft_smelt",
    }
    assert env.observation_space["rgb"].shape == (3, 64, 64)
    assert env.observation_space["inventory"].shape == (n_items,)
    assert env.observation_space["mask_action_type"].shape == (N_HIGH_LEVEL_ACTIONS,)


def test_obs_conversion():
    env, _ = make_env()
    obs, info = env.reset()
    # mock inventory: air x1, stone x3 (slot 1), wooden pickaxe x1, stone x2
    assert obs["inventory"][MOCK_ITEMS.index("stone")] == 5.0
    assert obs["inventory"][MOCK_ITEMS.index("air")] == 1.0
    assert obs["inventory_max"][MOCK_ITEMS.index("stone")] == 5.0
    # delta: +1 stone by craft, -1 apple by other
    assert obs["inventory_delta"][MOCK_ITEMS.index("stone")] == 1.0
    assert obs["inventory_delta"][MOCK_ITEMS.index("apple")] == -1.0
    # equipment one-hot on the canonicalized name
    equipped = np.flatnonzero(obs["equipment"])
    assert list(equipped) == [MOCK_ITEMS.index("wooden pickaxe")]
    np.testing.assert_allclose(obs["life_stats"], [20.0, 20.0, 300.0])
    assert info["location_stats"]["pitch"] == 0.0
    assert info["biomeid"] == 7.0


def test_masks():
    env, _ = make_env()
    obs, _ = env.reset()
    # movement/camera always allowed
    assert obs["mask_action_type"][:12].all()
    # equip/place allowed (pickaxe equippable), destroy allowed (stone)
    assert obs["mask_action_type"][16] and obs["mask_action_type"][17]
    assert obs["mask_action_type"][18]
    pickaxe = MOCK_ITEMS.index("wooden pickaxe")
    assert obs["mask_equip/place"][pickaxe]
    assert not obs["mask_equip/place"][MOCK_ITEMS.index("air")]
    assert obs["mask_destroy"][MOCK_ITEMS.index("stone")]
    # craft mask passed through; last craft item masked out by the mock
    assert obs["mask_craft_smelt"][0] and not obs["mask_craft_smelt"][-1]


def test_equip_uses_first_slot_of_item():
    env, backend = make_env()
    env.reset()
    stone = MOCK_ITEMS.index("stone")
    env.step([18, 0, stone])  # destroy stone
    native = backend.last_sim.received_actions[-1]
    assert native[5] == 7 and native[7] == 1  # first stone slot is 1, not 3


def test_pitch_limit_blocks_rotation():
    env, backend = make_env()
    env.reset()
    for _ in range(4):  # 4 x +15deg = +60: allowed
        env.step([9, 0, 0])
    assert backend.last_sim._pitch == 60.0
    env.step([9, 0, 0])  # would exceed +60 -> camera forced to noop
    assert backend.last_sim._pitch == 60.0
    assert backend.last_sim.received_actions[-1][3] == 12
    env.step([8, 0, 0])  # pitching back down is allowed
    assert backend.last_sim._pitch == 45.0


def test_episode_termination_and_reset_state():
    env, backend = make_env(episode_length=3)
    env.reset()
    env.step([14, 0, 0])  # starts sticky attack
    _, _, done, trunc, _ = env.step([0, 0, 0])
    assert not done
    _, reward, done, trunc, _ = env.step([0, 0, 0])
    assert done and not trunc and reward == 1.0
    obs, _ = env.reset()
    assert env._translator.attack_counter == 0
    # inventory_max reset on reset (reference minedojo.py:268)
    assert obs["inventory_max"][MOCK_ITEMS.index("stone")] == 5.0


def test_start_position_pitch_validation():
    with pytest.raises(ValueError, match="pitch"):
        MineDojoWrapper(
            "x",
            backend=FakeMineDojoBackend(),
            start_position={"x": 0, "y": 0, "z": 0, "pitch": -80, "yaw": 0},
        )


def test_make_kwargs_forwarded():
    backend = FakeMineDojoBackend()
    MineDojoWrapper(
        "harvest_milk", height=32, width=32, seed=7, backend=backend,
        break_speed_multiplier=50,
    )
    kw = backend.last_make_kwargs
    assert kw["task_id"] == "harvest_milk"
    assert kw["image_size"] == (32, 32)
    assert kw["world_seed"] == 7
    assert kw["fast_reset"] is True
    assert kw["break_speed_multiplier"] == 50
