"""Receipts for the Anakin path (ISSUE 6): pure-JAX env dynamics parity vs
Gymnasium, vmap/auto-reset invariants, rollout->`add_direct` ring contents
bit-exact vs a step-by-step reference, transfer-guard purity of the jitted
collector, and mesh-sharded collection equivalence."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data import AsyncReplayBuffer
from sheeprl_tpu.envs.jax import (
    DreamerCollectorCarry,
    JaxCartPole,
    JaxEnvGymWrapper,
    JaxPendulum,
    JaxPixelToy,
    PPOCollectorCarry,
    VecJaxEnv,
    make_dreamer_collector,
    make_jax_env,
    make_ppo_collector,
)
from sheeprl_tpu.envs.jax.cartpole import CartPoleState
from sheeprl_tpu.envs.jax.pendulum import PendulumState
from sheeprl_tpu.parallel import make_mesh, shard_env_batch


def _tiny_agent(env, seed=1):
    from sheeprl_tpu.algos.ppo.agent import PPOAgent

    space = env.observation_space
    cnn_keys = [k for k, s in space.spaces.items() if len(s.shape) == 3]
    mlp_keys = [k for k, s in space.spaces.items() if len(s.shape) == 1]
    act = env.action_space
    if isinstance(act, gym.spaces.Discrete):
        actions_dim, cont = [int(act.n)], False
    else:
        actions_dim, cont = [int(np.prod(act.shape))], True
    agent = PPOAgent.init(
        jax.random.PRNGKey(seed), actions_dim, space.spaces, cnn_keys, mlp_keys,
        dense_units=8, mlp_layers=1, mlp_features_dim=8, cnn_features_dim=16,
        is_continuous=cont,
    )
    return agent, actions_dim, cont


# ---------------------------------------------------------------------------
# dynamics parity vs Gymnasium (teacher-forced: both backends step from the
# SAME state each step over a seeded 200-step action trajectory, so a single
# step's numerics are compared without chaotic drift compounding)
# ---------------------------------------------------------------------------


@pytest.mark.timeout(120)
def test_cartpole_parity_vs_gymnasium():
    genv = gym.make("CartPole-v1")
    genv.reset(seed=3)
    jenv = JaxCartPole()
    jstep = jax.jit(jenv.step)
    rng = np.random.default_rng(0)
    key = jax.random.PRNGKey(0)
    for t in range(200):
        host_state = np.asarray(genv.unwrapped.state, np.float64)
        action = int(rng.integers(0, 2))
        s = CartPoleState(
            state=jnp.asarray(host_state, jnp.float32), t=jnp.zeros((), jnp.int32)
        )
        _, jobs, jr, jterm, _ = jstep(s, jnp.int32(action), key)
        gobs, gr, gterm, _, _ = genv.step(action)
        np.testing.assert_allclose(
            np.asarray(jobs["state"]), gobs, atol=1e-5, err_msg=f"step {t}"
        )
        assert float(jr) == gr
        assert bool(jterm) == gterm, f"step {t}"
        if gterm:
            genv.reset()
    genv.close()


@pytest.mark.timeout(120)
def test_pendulum_parity_vs_gymnasium():
    genv = gym.make("Pendulum-v1")
    genv.reset(seed=5)
    jenv = JaxPendulum()
    jstep = jax.jit(jenv.step)
    rng = np.random.default_rng(1)
    key = jax.random.PRNGKey(0)
    for t in range(200):
        host_state = np.asarray(genv.unwrapped.state, np.float64)
        action = rng.uniform(-2.0, 2.0, size=(1,)).astype(np.float32)
        s = PendulumState(
            state=jnp.asarray(host_state, jnp.float32), t=jnp.zeros((), jnp.int32)
        )
        _, jobs, jr, _, _ = jstep(s, jnp.asarray(action), key)
        gobs, gr, gterm, _, _ = genv.step(action)
        assert not gterm  # pendulum never terminates
        np.testing.assert_allclose(
            np.asarray(jobs["state"]), gobs, atol=1e-4, err_msg=f"step {t}"
        )
        np.testing.assert_allclose(float(jr), gr, atol=1e-4)
    genv.close()


# ---------------------------------------------------------------------------
# vmap / auto-reset shape invariants
# ---------------------------------------------------------------------------


@pytest.mark.timeout(120)
@pytest.mark.parametrize(
    "env_id,obs_key,shape,dtype",
    [
        ("CartPole-v1", "state", (4,), jnp.float32),
        ("Pendulum-v1", "state", (3,), jnp.float32),
        ("pixeltoy", "rgb", (64, 64, 3), jnp.uint8),
    ],
)
def test_vmap_shapes_and_dtypes(env_id, obs_key, shape, dtype):
    n = 5
    venv = VecJaxEnv(env=make_jax_env(env_id), num_envs=n)
    state, obs = jax.jit(venv.reset)(jax.random.PRNGKey(0))
    assert obs[obs_key].shape == (n,) + shape
    assert obs[obs_key].dtype == dtype
    space = venv.single_action_space
    if isinstance(space, gym.spaces.Discrete):
        actions = jnp.zeros((n,), jnp.int32)
    else:
        actions = jnp.zeros((n,) + space.shape, jnp.float32)
    state2, obs2, reward, done, info = jax.jit(venv.step)(
        state, actions, jax.random.PRNGKey(1)
    )
    assert obs2[obs_key].shape == (n,) + shape and obs2[obs_key].dtype == dtype
    assert reward.shape == (n,) and reward.dtype == jnp.float32
    assert done.shape == (n,) and done.dtype == jnp.bool_
    assert info["final_obs"][obs_key].shape == (n,) + shape
    assert state2.ep_length.shape == (n,)
    # observation values match the space the host agent was built for
    assert venv.single_observation_space[obs_key].shape == shape


@pytest.mark.timeout(120)
def test_autoreset_resets_state_and_stats():
    """Drive CartPole to termination with a constant action: the done env's
    state/step-counter/episode stats reset in the same step, and the final
    pre-reset observation is surfaced in info (same-step auto-reset, matching
    envs/vector.py)."""
    n = 4
    venv = VecJaxEnv(env=JaxCartPole(), num_envs=n)
    step = jax.jit(venv.step)
    state, obs = venv.reset(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    saw_done = False
    for t in range(60):
        key, k = jax.random.split(key)
        state, obs, reward, done, info = step(
            state, jnp.ones((n,), jnp.int32), k
        )
        done_np = np.asarray(done)
        if done_np.any():
            saw_done = True
            i = int(np.argmax(done_np))
            # episode stats were zeroed for the finished env...
            assert float(state.ep_return[i]) == 0.0
            assert int(state.ep_length[i]) == 0
            # ...its step counter restarted...
            assert int(state.env_state.t[i]) == 0
            # ...the completed-episode stats are in info...
            assert float(info["ep_return"][i]) == t + 1  # +1 reward per step
            assert int(info["ep_length"][i]) == t + 1
            # ...and the returned obs is the RESET obs (within the reset
            # distribution), while final_obs is the out-of-bounds terminal one
            assert np.all(np.abs(np.asarray(obs["state"])[i]) <= 0.05)
            final = np.asarray(info["final_obs"]["state"])[i]
            assert np.abs(final[2]) > 12 * 2 * np.pi / 360 or np.abs(final[0]) > 2.4
            break
    assert saw_done, "constant-action cartpole never terminated in 60 steps"


@pytest.mark.timeout(120)
def test_truncation_at_max_episode_steps():
    venv = VecJaxEnv(env=JaxPendulum(max_episode_steps=7), num_envs=2)
    step = jax.jit(venv.step)
    state, _ = venv.reset(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)
    for t in range(1, 8):
        key, k = jax.random.split(key)
        state, _, _, done, info = step(
            state, jnp.zeros((2, 1), jnp.float32), k
        )
        if t < 7:
            assert not np.asarray(done).any()
    assert np.asarray(done).all()
    assert np.asarray(info["truncated"]).all()
    assert not np.asarray(info["terminated"]).any()
    assert np.asarray(state.env_state.t == 0).all()  # auto-reset


@pytest.mark.timeout(120)
def test_pixeltoy_reaches_goal_with_scripted_actions():
    env = JaxPixelToy(size=16, grid=4, max_episode_steps=50)
    key = jax.random.PRNGKey(2)
    state, obs = env.reset(key)
    assert obs["rgb"].dtype == jnp.uint8 and obs["rgb"].shape == (16, 16, 3)
    step = jax.jit(env.step)
    # walk the manhattan path: rows first (actions 1=up/2=down), then cols
    for _ in range(12):
        dr = int(state.goal[0] - state.agent[0])
        dc = int(state.goal[1] - state.agent[1])
        if dr != 0:
            a = 2 if dr > 0 else 1
        elif dc != 0:
            a = 4 if dc > 0 else 3
        else:
            break
        state, obs, reward, term, trunc = step(state, jnp.int32(a), key)
        if bool(term):
            assert float(reward) == 1.0
            return
    pytest.fail("scripted manhattan walk never reached the goal")


# ---------------------------------------------------------------------------
# rollout -> add_direct ring contents, bit-exact vs a step-by-step reference
# ---------------------------------------------------------------------------


def _ring_arrays(rb):
    return {k: np.asarray(v) for k, v in rb._store.items()}


@pytest.mark.timeout(300)
def test_dreamer_rollout_ring_bit_exact_vs_step_by_step():
    """One T-length jitted scan writing via reserve()/add_direct() produces
    the SAME device ring as T single-step collects: same scatter layout,
    same PRNG stream (the scan body's split discipline is replayed by
    chaining `split(key, 3)[0]`), bitwise-identical contents."""
    T, n = 6, 3
    venv = VecJaxEnv(env=JaxCartPole(), num_envs=n)
    obs_keys = ("state",)
    kwargs = dict(
        actions_dim=(2,), is_continuous=False,
        dev_preprocess=lambda o: o, random_actions=True,
    )
    collect_T = jax.jit(make_dreamer_collector(venv, T, **kwargs))
    collect_1 = jax.jit(make_dreamer_collector(venv, 1, **kwargs))

    def fresh(seed):
        state, obs = jax.jit(venv.reset)(jax.random.PRNGKey(seed))
        carry = DreamerCollectorCarry(
            vec=state, obs=obs,
            prev_reward=jnp.zeros((n, 1), jnp.float32),
            prev_done=jnp.zeros((n, 1), jnp.float32),
            is_first=jnp.ones((n, 1), jnp.float32),
        )
        rb = AsyncReplayBuffer(
            16, n, storage="device", sequential=True, obs_keys=obs_keys, seed=7
        )
        return carry, rb

    key = jax.random.PRNGKey(11)
    expl = jnp.float32(0.0)

    carry, rb_scan = fresh(0)
    idx = rb_scan.reserve(T)
    _, carry, traj, ep = collect_T(None, None, carry, key, expl)
    rb_scan.add_direct(traj, jnp.asarray(idx), data_len=T)

    carry, rb_ref = fresh(0)
    k = key
    for _ in range(T):
        idx = rb_ref.reserve(1)
        _, carry, row, _ = collect_1(None, None, carry, k, expl)
        rb_ref.add_direct(row, jnp.asarray(idx), data_len=1)
        k = jax.random.split(k, 3)[0]  # the scan body's carried key

    scan_store, ref_store = _ring_arrays(rb_scan), _ring_arrays(rb_ref)
    assert set(scan_store) == set(ref_store)
    for k_ in scan_store:
        np.testing.assert_array_equal(scan_store[k_], ref_store[k_], err_msg=k_)
    np.testing.assert_array_equal(rb_scan._upos, rb_ref._upos)
    np.testing.assert_array_equal(rb_scan._ufull, rb_ref._ufull)
    # row semantics: every row's is_first/dones/rewards are host-shifted
    assert scan_store["is_first"].shape == (16, n, 1)
    assert float(np.asarray(ep["episodes"])) >= 0


@pytest.mark.timeout(300)
def test_ppo_collector_bit_exact_vs_step_by_step():
    venv = VecJaxEnv(env=JaxCartPole(), num_envs=4)
    agent, actions_dim, cont = _tiny_agent(venv.env)
    T = 5
    collect_T = jax.jit(make_ppo_collector(venv, T, actions_dim, cont))
    collect_1 = jax.jit(make_ppo_collector(venv, 1, actions_dim, cont))

    def fresh():
        state, obs = jax.jit(venv.reset)(jax.random.PRNGKey(3))
        return PPOCollectorCarry(
            vec=state, obs=obs, prev_done=jnp.zeros((4, 1), jnp.float32)
        )

    key = jax.random.PRNGKey(9)
    carry_a, traj, ep = collect_T(agent, fresh(), key)

    carry_b = fresh()
    k = key
    rows = []
    for _ in range(T):
        carry_b, row, _ = collect_1(agent, carry_b, k)
        rows.append(row)
        k = jax.random.split(k, 3)[0]
    ref = {
        k_: np.stack([np.asarray(r[k_])[0] for r in rows]) for k_ in rows[0]
    }
    for k_ in ref:
        np.testing.assert_array_equal(np.asarray(traj[k_]), ref[k_], err_msg=k_)
    np.testing.assert_array_equal(
        np.asarray(carry_a.prev_done), np.asarray(carry_b.prev_done)
    )
    np.testing.assert_array_equal(
        np.asarray(carry_a.obs["state"]), np.asarray(carry_b.obs["state"])
    )


# ---------------------------------------------------------------------------
# purity: zero host syncs / transfers inside the compiled collector
# ---------------------------------------------------------------------------


@pytest.mark.timeout(300)
def test_rollout_transfer_guard_purity():
    """The runtime half of the zero-host-transfers guarantee: a compiled
    collector dispatches and retires under `transfer_guard("disallow")` —
    any implicit h2d/d2h inside the scan would raise."""
    venv = VecJaxEnv(env=JaxCartPole(), num_envs=8)
    agent, actions_dim, cont = _tiny_agent(venv.env)
    collect = jax.jit(make_ppo_collector(venv, 16, actions_dim, cont))
    state, obs = jax.jit(venv.reset)(jax.random.PRNGKey(0))
    carry = PPOCollectorCarry(
        vec=state, obs=obs, prev_done=jnp.zeros((8, 1), jnp.float32)
    )
    # compile (and land closure constants + keys on device) outside the guard
    key2 = jax.block_until_ready(jax.random.PRNGKey(2))
    carry, traj, ep = collect(agent, carry, jax.random.PRNGKey(1))
    jax.block_until_ready(traj["dones"])
    with jax.transfer_guard("disallow"):
        carry, traj, ep = collect(agent, carry, key2)
        jax.block_until_ready((traj, ep))


# ---------------------------------------------------------------------------
# mesh sharding: env batch sharded over the virtual 8-device mesh
# ---------------------------------------------------------------------------


@pytest.mark.timeout(300)
def test_sharded_rollout_matches_unsharded():
    mesh = make_mesh()  # all 8 virtual CPU devices
    n_dev = mesh.devices.size
    assert n_dev == 8
    n = 2 * n_dev
    venv = VecJaxEnv(env=JaxCartPole(), num_envs=n)
    agent, actions_dim, cont = _tiny_agent(venv.env)
    collect = jax.jit(make_ppo_collector(venv, 8, actions_dim, cont))
    state, obs = jax.jit(venv.reset)(jax.random.PRNGKey(0))
    carry = PPOCollectorCarry(
        vec=state, obs=obs, prev_done=jnp.zeros((n, 1), jnp.float32)
    )
    key = jax.random.PRNGKey(4)
    _, traj_plain, ep_plain = collect(agent, carry, key)
    sharded = shard_env_batch(carry, mesh)
    # every [N, ...] leaf landed sharded over the data axis
    assert len(sharded.obs["state"].sharding.device_set) == n_dev
    _, traj_shard, ep_shard = collect(agent, sharded, key)
    for k in traj_plain:
        np.testing.assert_array_equal(
            np.asarray(traj_plain[k]), np.asarray(traj_shard[k]), err_msg=k
        )
    np.testing.assert_allclose(
        float(ep_plain["return_sum"]), float(ep_shard["return_sum"]), rtol=1e-6
    )


# ---------------------------------------------------------------------------
# host twin (gym_compat)
# ---------------------------------------------------------------------------


@pytest.mark.timeout(120)
def test_gym_wrapper_pixeltoy():
    env = JaxEnvGymWrapper(make_jax_env("pixeltoy"), seed=0)
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (64, 64, 3) and obs["rgb"].dtype == np.uint8
    obs, reward, term, trunc, _ = env.step(1)
    assert isinstance(reward, float) and isinstance(term, bool)
    assert obs["rgb"].shape == (64, 64, 3)
    frame = env.render()
    assert frame is not None and frame.shape == (64, 64, 3)
