"""DIAMBRA wrapper unit tests against the scripted fake engine: settings
construction (frame shape, sticky-actions step-ratio forcing, disabled
engine-side frame stacking), discrete/multidiscrete action spaces, Discrete
-> Box observation conversion, and per-rank engine instantiation."""

import numpy as np
import pytest

from sheeprl_tpu.envs.diambra_mock import FakeDiambraBackend
from sheeprl_tpu.envs.diambra_wrapper import DiambraWrapper


def make_env(**kwargs):
    backend = FakeDiambraBackend(episode_length=kwargs.pop("episode_length", 8))
    env = DiambraWrapper("doapp", backend=backend, **kwargs)
    return env, backend


def test_settings_and_wrappers_construction():
    env, backend = make_env(
        screen_size=48,
        grayscale=True,
        attack_but_combination=False,
        actions_stack=4,
        noop_max=5,
        seed=3,
        rank=2,
        diambra_settings={"difficulty": 4},
        diambra_wrappers={"normalize_reward": True},
    )
    eng = backend.last_engine
    assert eng.env_id == "doapp"
    assert eng.settings["frame_shape"] == (48, 48, 1)
    assert eng.settings["difficulty"] == 4
    assert eng.settings["attack_but_combination"] is False
    assert eng.wrappers["no_op_max"] == 5
    assert eng.wrappers["actions_stack"] == 4
    assert eng.wrappers["flatten"] is True
    assert eng.wrappers["normalize_reward"] is True
    assert eng.seed == 3 and eng.rank == 2


def test_sticky_actions_force_step_ratio():
    with pytest.warns(UserWarning, match="step_ratio forced to 1"):
        env, backend = make_env(sticky_actions=4)
    assert backend.last_engine.settings["step_ratio"] == 1
    assert backend.last_engine.wrappers["sticky_actions"] == 4
    # explicit step_ratio=1 passes through silently
    env, backend = make_env(
        sticky_actions=4, diambra_settings={"step_ratio": 1}
    )
    assert backend.last_engine.settings["step_ratio"] == 1


def test_engine_frame_wrappers_disabled():
    with pytest.warns(UserWarning, match="frame_stack wrapper is disabled"):
        _, backend = make_env(diambra_wrappers={"frame_stack": 4})
    assert "frame_stack" not in backend.last_engine.wrappers
    with pytest.warns(UserWarning, match="dilation wrapper is disabled"):
        _, backend = make_env(diambra_wrappers={"dilation": 2})
    assert "dilation" not in backend.last_engine.wrappers


def test_action_spaces():
    env, _ = make_env(action_space="discrete")
    assert env.action_space.n == 10
    env, _ = make_env(action_space="multi_discrete")
    np.testing.assert_array_equal(env.action_space.nvec, [9, 8])


def test_observation_space_conversion():
    env, _ = make_env()
    spaces = env.observation_space.spaces
    assert set(spaces) == {"frame", "ownHealth", "oppHealth", "stage", "ownSide"}
    assert spaces["frame"].shape == (64, 64, 3)
    # engine Discrete obs become 1-dim int32 Boxes (reference :79-83)
    assert spaces["stage"].shape == (1,) and spaces["stage"].dtype == np.int32
    assert spaces["stage"].high[0] == 2
    assert spaces["ownSide"].high[0] == 1


def test_step_reset_and_obs_reshape():
    env, backend = make_env(episode_length=3, rank=1)
    obs, info = env.reset()
    assert info["env_domain"] == "DIAMBRA"
    # bare-int Discrete obs reshaped into (1,) arrays
    assert obs["stage"].shape == (1,) and obs["stage"][0] == 1
    assert obs["ownSide"][0] == 1  # rank % 2
    assert obs["frame"].shape == (64, 64, 3)
    done = False
    steps = 0
    while not done:
        obs, reward, done, trunc, info = env.step(env.action_space.sample())
        steps += 1
    assert steps == 3 and reward == 1.0 and not trunc
    assert info["env_domain"] == "DIAMBRA"
    assert len(backend.last_engine.received_actions) == 3
