"""Env pipeline contracts: dict obs, NHWC images, frame stacking, vector
runners, wrappers."""

import dataclasses

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.envs import (
    ActionRepeat,
    ContinuousDummyEnv,
    DiscreteDummyEnv,
    FrameStack,
    MaskVelocityWrapper,
    MultiDiscreteDummyEnv,
    RestartOnException,
    SyncVectorEnv,
    AsyncVectorEnv,
)
from sheeprl_tpu.utils.env import make_dict_env, make_env


@dataclasses.dataclass
class EnvArgs:
    seed: int = 0
    sync_env: bool = True
    screen_size: int = 64
    action_repeat: int = 1
    frame_stack: int = -1
    frame_stack_dilation: int = 1
    max_episode_steps: int = -1
    capture_video: bool = False
    cnn_keys: list = None
    mlp_keys: list = None
    grayscale_obs: bool = False


def test_dummy_envs_channel_last():
    for env in (ContinuousDummyEnv(), DiscreteDummyEnv(), MultiDiscreteDummyEnv()):
        obs, _ = env.reset()
        assert obs.shape == (64, 64, 3) and obs.dtype == np.uint8


def test_make_dict_env_vector_obs():
    args = EnvArgs(mlp_keys=["state"])
    env = make_dict_env("CartPole-v1", seed=0, rank=0, args=args)()
    obs, _ = env.reset(seed=0)
    assert isinstance(obs, dict) and "state" in obs
    assert obs["state"].shape == (4,)


def test_make_dict_env_pixel_obs_nhwc():
    args = EnvArgs(cnn_keys=["rgb"])
    env = make_dict_env("discrete_dummy", seed=0, rank=0, args=args)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (64, 64, 3)
    assert obs["rgb"].dtype == np.uint8
    assert env.observation_space["rgb"].shape == (64, 64, 3)


def test_make_dict_env_grayscale_resize():
    args = EnvArgs(cnn_keys=["rgb"], grayscale_obs=True, screen_size=32)
    env = make_dict_env("discrete_dummy", seed=0, rank=0, args=args)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (32, 32, 1)


def test_make_dict_env_frame_stack_channels():
    args = EnvArgs(cnn_keys=["rgb"], frame_stack=4)
    env = make_dict_env("discrete_dummy", seed=0, rank=0, args=args)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (64, 64, 12)  # 3 channels x 4 frames
    obs, *_ = env.step(env.action_space.sample())
    assert obs["rgb"].shape == (64, 64, 12)


def test_make_dict_env_time_limit():
    args = EnvArgs(mlp_keys=["state"], max_episode_steps=6, action_repeat=2)
    env = make_dict_env("CartPole-v1", seed=0, rank=0, args=args)()
    env.reset(seed=0)
    truncated = False
    for _ in range(4):
        *_, truncated, info = env.step(env.action_space.sample())
        if truncated:
            break
    assert truncated  # 6 // 2 = 3 steps


def test_action_repeat_accumulates_reward():
    env = make_env("CartPole-v1", seed=0, idx=0, action_repeat=3)()
    env.reset(seed=0)
    _, reward, *_ = env.step(env.action_space.sample())
    assert reward >= 1.0  # cartpole gives 1/step; 3 repeats unless early done


def test_mask_velocity():
    env = MaskVelocityWrapper(gym.make("CartPole-v1"))
    obs, _ = env.reset(seed=0)
    assert obs[1] == 0.0 and obs[3] == 0.0


def test_restart_on_exception():
    calls = {"n": 0}

    class Crashy(DiscreteDummyEnv):
        def step(self, action):
            if calls["n"] == 1:
                calls["n"] += 1
                raise RuntimeError("boom")
            calls["n"] += 1
            return super().step(action)

    env = RestartOnException(lambda: Crashy(), wait=0.01)
    env.reset()
    env.step(env.action_space.sample())
    obs, reward, term, trunc, info = env.step(env.action_space.sample())  # crashes -> restart
    assert info.get("restart_on_exception") is True
    assert not term and not trunc


def test_restart_on_exception_gives_up():
    class AlwaysCrash(DiscreteDummyEnv):
        def step(self, action):
            raise RuntimeError("boom")

    env = RestartOnException(lambda: AlwaysCrash(), maxfails=1, wait=0.01, window=1000)
    env.reset()
    with pytest.raises(RuntimeError, match="too many"):
        for _ in range(3):
            env.step(env.action_space.sample())


@pytest.mark.parametrize("cls", [SyncVectorEnv, AsyncVectorEnv])
def test_vector_env_dict_obs_and_autoreset(cls):
    args = EnvArgs(cnn_keys=["rgb"])
    fns = [make_dict_env("discrete_dummy", seed=i, rank=0, args=args) for i in range(2)]
    envs = cls(fns)
    try:
        obs, _ = envs.reset(seed=0)
        assert obs["rgb"].shape == (2, 64, 64, 3)
        saw_final = False
        for _ in range(8):  # dummy env has 4-step episodes
            actions = [envs.single_action_space.sample() for _ in range(2)]
            obs, rewards, terms, truncs, infos = envs.step(actions)
            assert obs["rgb"].shape == (2, 64, 64, 3)
            for i, info in enumerate(infos):
                if terms[i] or truncs[i]:
                    assert "final_observation" in info
                    saw_final = True
        assert saw_final
    finally:
        envs.close()


def test_vector_env_box_obs():
    fns = [make_env("CartPole-v1", seed=i, idx=i) for i in range(3)]
    envs = SyncVectorEnv(fns)
    obs, _ = envs.reset(seed=0)
    assert obs.shape == (3, 4)
    envs.close()


def test_frame_stack_dilation():
    class Counter(DiscreteDummyEnv):
        def __init__(self):
            super().__init__(size=(2, 2, 1), n_steps=100)
            self.t = 0

        def _obs(self):
            self.t += 1
            return np.full((2, 2, 1), self.t % 256, np.uint8)

    from sheeprl_tpu.envs.wrappers import DictObservation

    env = DictObservation(Counter(), "rgb")
    env = FrameStack(env, num_stack=2, cnn_keys=["rgb"], dilation=2)
    obs, _ = env.reset()
    for _ in range(4):
        obs, *_ = env.step(0)
    # after 4 steps: frames deque [1,2,3,4]; dilation 2 -> picks frames 2,4
    assert obs["rgb"].shape == (2, 2, 2)
    np.testing.assert_array_equal(obs["rgb"][0, 0], [2, 4])
