"""MineRL wrapper + task-spec unit tests against the scripted mock backend —
the mapping logic the reference leaves untested (its wrapper requires a live
Minecraft): flat action enumeration from the dict action interface, sticky
attack/jump, pitch limits with yaw wrap, inventory/equipment/compass
conversion, and the declarative task definitions (action vocabularies,
reward schedules, success rules)."""

import numpy as np
import pytest

from sheeprl_tpu.envs.minerl import (
    MineRLWrapper,
    StickyActions,
    build_actions_map,
    make_noop,
)
from sheeprl_tpu.envs.minerl_mock import MOCK_ALL_ITEMS, FakeMineRLBackend
from sheeprl_tpu.envs.minerl_envs.tasks import (
    CUSTOM_TASKS,
    custom_navigate,
    custom_obtain_diamond,
    custom_obtain_iron_pickaxe,
)


def make_env(task_id="custom_navigate", **kwargs):
    backend = FakeMineRLBackend(episode_length=kwargs.pop("episode_length", 16))
    env = MineRLWrapper(task_id, backend=backend, **kwargs)
    return env, backend


# ---- task specs --------------------------------------------------------------


def test_navigate_spec():
    spec = custom_navigate()
    assert spec.name == "CustomMineRLNavigate-v0"
    assert spec.has_compass and not spec.has_equipment
    assert spec.max_episode_steps == 6000
    heads = {h.key: h for h in spec.action_heads}
    assert set(heads) == {
        "forward", "back", "left", "right", "jump", "sneak", "sprint",
        "attack", "camera", "place",
    }
    assert heads["place"].values == ("none", "dirt")
    assert spec.touch_block_rewards == (("diamond_block", 100.0),)
    assert spec.world_generator == "default"
    assert custom_navigate(extreme=True).world_generator == "biome:3"
    assert custom_navigate(dense=True).name == "CustomMineRLNavigateDense-v0"
    assert (
        custom_navigate(dense=True, extreme=True).name
        == "CustomMineRLNavigateExtremeDense-v0"
    )


def test_navigate_success_rule():
    spec = custom_navigate()
    assert spec.determine_success([100.0])
    assert not spec.determine_success([50.0, 49.0])
    dense = custom_navigate(dense=True)
    # threshold raised by 60 in the dense variant (reference navigate.py:90-94)
    assert not dense.determine_success([100.0])
    assert dense.determine_success([100.0, 60.0])


def test_obtain_specs():
    diamond = custom_obtain_diamond()
    iron = custom_obtain_iron_pickaxe()
    assert diamond.name == "CustomMineRLObtainDiamond-v0"
    assert custom_obtain_diamond(dense=True).name == "CustomMineRLObtainDiamondDense-v0"
    assert diamond.max_episode_steps == 18000 and iron.max_episode_steps == 6000
    assert diamond.has_equipment and not diamond.has_compass
    # diamond schedule = iron schedule + the 1024 diamond row
    assert len(diamond.reward_schedule) == 12 and len(iron.reward_schedule) == 11
    assert diamond.reward_schedule[-1].item == "diamond"
    assert diamond.reward_schedule[-1].reward == 1024
    assert [r.reward for r in iron.reward_schedule] == [
        1, 2, 4, 4, 8, 16, 32, 32, 64, 128, 256,
    ]
    assert diamond.quit_on_possess == (("diamond", 1),)
    assert iron.quit_on_craft == (("iron_pickaxe", 1),)
    heads = {h.key: h for h in diamond.extra_heads}
    assert set(heads) == {"place", "equip", "craft", "nearbyCraft", "nearbySmelt"}
    assert len(heads["place"].values) == 7
    assert len(heads["nearbySmelt"].values) == 3


def test_obtain_success_rule():
    iron = custom_obtain_iron_pickaxe()
    rewards = [r.reward for r in iron.reward_schedule]
    assert iron.determine_success(rewards)
    # 10% of 11 rounds to 1 missing value allowed; distinct values are 9
    # (4 and 32 repeat), so dropping one distinct value still succeeds
    assert iron.determine_success([r for r in rewards if r != 256])
    assert not iron.determine_success([r for r in rewards if r not in (128, 256)])


# ---- action enumeration ------------------------------------------------------


def test_actions_map_navigate():
    actions = build_actions_map(custom_navigate())
    # noop + 8 keys + 4 camera + 1 place value
    assert len(actions) == 14
    assert actions[0] == {}
    assert actions[1] == {"forward": 1}
    # jump/sneak/sprint bundle forward (reference minerl.py:90-91)
    assert actions[5] == {"jump": 1, "forward": 1}
    assert actions[6] == {"sneak": 1, "forward": 1}
    assert actions[7] == {"sprint": 1, "forward": 1}
    assert actions[8] == {"attack": 1}
    np.testing.assert_array_equal(actions[9]["camera"], [-15, 0])
    np.testing.assert_array_equal(actions[12]["camera"], [0, 15])
    assert actions[13] == {"place": "dirt"}


def test_actions_map_obtain():
    actions = build_actions_map(custom_obtain_diamond())
    # noop + 8 keys + 4 camera + (6 place + 7 equip + 4 craft + 7 nearbyCraft
    # + 2 nearbySmelt) enum values
    assert len(actions) == 39
    assert {"place": "torch"} in actions
    assert {"equip": "iron_pickaxe"} in actions
    assert {"craft": "planks"} in actions
    assert {"nearbyCraft": "furnace"} in actions
    assert {"nearbySmelt": "coal"} in actions
    # enum no-op values never appear as actions
    assert not any(
        v == "none" for a in actions for v in a.values() if isinstance(v, str)
    )


def test_noop_covers_all_heads():
    spec = custom_obtain_diamond()
    noop = make_noop(spec)
    assert set(noop) == {h.key for h in spec.action_heads}
    assert noop["place"] == "none" and noop["forward"] == 0
    np.testing.assert_array_equal(noop["camera"], [0, 0])


# ---- sticky actions ----------------------------------------------------------


def test_sticky_attack_holds_and_suppresses_jump():
    st = StickyActions(sticky_attack=3, sticky_jump=0)
    out = st.apply({"attack": 1, "jump": 0})
    assert out["attack"] == 1 and st.attack_counter == 2
    out = st.apply({"attack": 0, "jump": 1})
    assert out["attack"] == 1 and out["jump"] == 0  # attack wins over jump
    st.apply({"attack": 0, "jump": 0})
    out = st.apply({"attack": 0, "jump": 0})
    assert out["attack"] == 0  # counter exhausted


def test_sticky_jump_forces_forward():
    st = StickyActions(sticky_attack=0, sticky_jump=2)
    out = st.apply({"attack": 0, "jump": 1, "forward": 0})
    assert out["jump"] == 1 and out["forward"] == 1 and st.jump_counter == 1
    out = st.apply({"attack": 0, "jump": 0, "forward": 0})
    assert out["jump"] == 1 and out["forward"] == 1
    out = st.apply({"attack": 0, "jump": 0, "forward": 0})
    assert out["jump"] == 0


# ---- wrapper -----------------------------------------------------------------


def test_spaces_navigate_vs_obtain():
    env, _ = make_env("custom_navigate")
    assert env.action_space.n == 14
    assert set(env.observation_space.spaces) == {
        "rgb", "life_stats", "inventory", "max_inventory", "compass",
    }
    assert env.observation_space["rgb"].shape == (64, 64, 3)
    assert env.observation_space["inventory"].shape == (len(MOCK_ALL_ITEMS),)

    env2, _ = make_env("custom_obtain_diamond")
    assert env2.action_space.n == 39
    assert set(env2.observation_space.spaces) == {
        "rgb", "life_stats", "inventory", "max_inventory", "equipment",
    }


def test_obs_conversion():
    env, _ = make_env("custom_obtain_diamond")
    obs, _ = env.reset()
    # mock inventory: air x2 (counts 1 per ENTRY, not quantity), dirt x3,
    # wooden_pickaxe x1, "iron ore" x2 (canonicalized to iron_ore)
    assert obs["inventory"][MOCK_ALL_ITEMS.index("air")] == 1.0
    assert obs["inventory"][MOCK_ALL_ITEMS.index("dirt")] == 3.0
    assert obs["inventory"][MOCK_ALL_ITEMS.index("iron ore")] == 2.0
    np.testing.assert_allclose(obs["life_stats"], [20.0, 20.0, 300.0])
    equipped = np.flatnonzero(obs["equipment"])
    assert list(equipped) == [MOCK_ALL_ITEMS.index("wooden_pickaxe")]
    assert obs["rgb"].shape == (64, 64, 3) and obs["rgb"].dtype == np.uint8


def test_compass_and_max_inventory_track():
    env, _ = make_env("custom_navigate")
    obs, _ = env.reset()
    assert obs["compass"].shape == (1,) and obs["compass"][0] == 45.0
    dirt = MOCK_ALL_ITEMS.index("dirt")
    assert obs["max_inventory"][dirt] == 3.0
    obs, *_ = env.step(8)  # attack: mock adds one dirt per attack step
    assert obs["inventory"][dirt] == 4.0 and obs["max_inventory"][dirt] == 4.0
    obs, _ = env.reset()
    assert obs["max_inventory"][dirt] == 3.0  # running max resets


def test_equip_action_reaches_sim():
    env, backend = make_env("custom_obtain_diamond")
    env.reset()
    equip_id = env.actions_map.index({"equip": "iron_pickaxe"})
    obs, *_ = env.step(equip_id)
    assert backend.last_sim.received_actions[-1]["equip"] == "iron_pickaxe"
    assert list(np.flatnonzero(obs["equipment"])) == [
        MOCK_ALL_ITEMS.index("iron_pickaxe")
    ]


def test_pitch_limit_blocks_rotation_yaw_wraps():
    env, backend = make_env("custom_navigate", pitch_limits=(-60, 60))
    env.reset()
    pitch_up = next(
        i for i, a in enumerate(env.actions_map)
        if "camera" in a and a["camera"][0] > 0
    )
    for _ in range(4):  # 4 x +15 = +60: allowed
        env.step(pitch_up)
    assert env._pos["pitch"] == 60.0
    env.step(pitch_up)  # would exceed -> pitch component zeroed
    assert env._pos["pitch"] == 60.0
    np.testing.assert_array_equal(
        backend.last_sim.received_actions[-1]["camera"], [0.0, 0.0]
    )
    yaw_right = next(
        i for i, a in enumerate(env.actions_map)
        if "camera" in a and a["camera"][1] > 0
    )
    for _ in range(13):  # 13 x +15 = 195 -> wraps to -165
        env.step(yaw_right)
    assert env._pos["yaw"] == -165.0


def test_full_episode_actions_valid_and_termination():
    env, backend = make_env("custom_obtain_diamond", episode_length=5)
    env.reset()
    rng = np.random.default_rng(0)
    done = False
    steps = 0
    while not done:
        # the fake sim validates keys/enums/camera of every action
        _, reward, done, trunc, _ = env.step(rng.integers(env.action_space.n))
        steps += 1
    assert steps == 5 and reward == 100.0 and not trunc
    assert len(backend.last_sim.received_actions) == 5


def test_make_kwargs_forwarded_and_unknown_task():
    backend = FakeMineRLBackend()
    MineRLWrapper(
        "custom_navigate", height=32, width=32, seed=7, backend=backend,
        break_speed_multiplier=50, dense=True,
    )
    kw = backend.last_make_kwargs
    assert kw["resolution"] == (32, 32)
    assert kw["break_speed"] == 50
    assert kw["seed"] == 7
    assert kw["spec"].dense
    with pytest.raises(ValueError, match="unknown MineRL task"):
        MineRLWrapper("custom_fly_to_moon", backend=backend)


def test_registry_exposes_all_reference_tasks():
    assert set(CUSTOM_TASKS) == {
        "custom_navigate", "custom_obtain_diamond", "custom_obtain_iron_pickaxe",
    }
