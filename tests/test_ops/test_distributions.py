"""Statistical and gradient contracts for the distribution library."""

import math

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.ops import distributions as D
from sheeprl_tpu.ops import symlog

KEY = jax.random.PRNGKey(0)


def test_normal_log_prob_matches_formula():
    d = D.Normal(loc=jnp.array(1.0), scale=jnp.array(2.0))
    x = jnp.array(0.5)
    expected = -0.5 * ((0.5 - 1.0) / 2.0) ** 2 - math.log(2.0) - 0.5 * math.log(2 * math.pi)
    np.testing.assert_allclose(d.log_prob(x), expected, rtol=1e-6)
    np.testing.assert_allclose(d.entropy(), 0.5 * math.log(2 * math.pi * math.e) + math.log(2.0))


def test_independent_sums_event_dims():
    d = D.Independent(base=D.Normal(loc=jnp.zeros((3, 4)), scale=jnp.ones((3, 4))), event_ndims=1)
    lp = d.log_prob(jnp.zeros((3, 4)))
    assert lp.shape == (3,)
    np.testing.assert_allclose(lp, 4 * (-0.5 * math.log(2 * math.pi)) * np.ones(3), rtol=1e-6)


def test_tanh_normal_log_prob_consistency():
    d = D.TanhNormal(loc=jnp.zeros((5, 3)), scale=jnp.ones((5, 3)) * 0.5)
    a, lp = d.sample_and_log_prob(KEY)
    assert a.shape == (5, 3) and lp.shape == (5,)
    assert np.all(np.abs(a) < 1.0)
    # compare against naive formula
    u = np.arctanh(np.asarray(a))
    base = -0.5 * (u / 0.5) ** 2 - math.log(0.5) - 0.5 * math.log(2 * math.pi)
    corr = np.log(1 - np.tanh(u) ** 2 + 1e-12)
    np.testing.assert_allclose(lp, (base - corr).sum(-1), rtol=1e-3, atol=1e-3)


def test_truncated_normal_bounds_and_moments():
    d = D.TruncatedNormal(
        loc=jnp.zeros(()), scale=jnp.ones(()), low=jnp.array(-1.0), high=jnp.array(1.0)
    )
    s = d.sample(KEY, (20000,))
    assert np.all(np.asarray(s) >= -1.0) and np.all(np.asarray(s) <= 1.0)
    np.testing.assert_allclose(np.mean(np.asarray(s)), 0.0, atol=0.02)
    # known variance of standard normal truncated to [-1, 1] ~ 0.29112
    np.testing.assert_allclose(np.var(np.asarray(s)), 0.29112, atol=0.01)
    # entropy of truncated standard normal on [-1,1]:
    # log sqrt(2*pi*e) + log Z - (b*phi(b) - a*phi(a))/(2Z) = 0.68283
    np.testing.assert_allclose(float(d.entropy()), 0.68283, atol=1e-3)


def test_categorical_sample_and_entropy():
    logits = jnp.log(jnp.array([0.7, 0.2, 0.1]))
    d = D.Categorical.from_logits(jnp.broadcast_to(logits, (5000, 3)))
    s = d.sample(KEY)
    freq = np.bincount(np.asarray(s), minlength=3) / 5000
    np.testing.assert_allclose(freq, [0.7, 0.2, 0.1], atol=0.03)
    expected_h = -(0.7 * math.log(0.7) + 0.2 * math.log(0.2) + 0.1 * math.log(0.1))
    np.testing.assert_allclose(d.entropy()[0], expected_h, rtol=1e-4)
    np.testing.assert_allclose(d.log_prob(jnp.zeros(5000, jnp.int32))[0], math.log(0.7), rtol=1e-4)


def test_one_hot_straight_through_gradients():
    logits = jnp.array([[1.0, 0.0, -1.0]])

    def f(lg):
        d = D.OneHotCategorical.from_logits(lg)
        s = d.rsample(KEY)
        return (s * jnp.arange(3.0)).sum()

    g = jax.grad(f)(logits)
    assert np.any(np.asarray(g) != 0.0)  # gradients flow through probs


def test_unimix_logits():
    logits = jnp.array([100.0, 0.0, 0.0])  # near-deterministic
    mixed = D.unimix_logits(logits, 0.01)
    probs = np.asarray(jax.nn.softmax(mixed))
    assert probs[1] > 0.003  # uniform mass injected (0.01/3)
    np.testing.assert_allclose(probs.sum(), 1.0, rtol=1e-6)


def test_bernoulli_log_prob_and_mode():
    d = D.Bernoulli(logits=jnp.array([2.0, -2.0]))
    np.testing.assert_allclose(np.asarray(d.mode), [1.0, 0.0])
    lp = d.log_prob(jnp.array([1.0, 1.0]))
    p = 1 / (1 + math.exp(-2.0))
    np.testing.assert_allclose(lp, [math.log(p), math.log(1 - p)], rtol=1e-4)


def test_symlog_distribution():
    mode = symlog(jnp.array([[3.0, -5.0]]))
    d = D.SymlogDistribution(_mode=mode, dims=1)
    np.testing.assert_allclose(d.mode, [[3.0, -5.0]], rtol=1e-4)
    np.testing.assert_allclose(d.log_prob(jnp.array([[3.0, -5.0]])), [0.0], atol=1e-5)
    assert float(d.log_prob(jnp.array([[10.0, -5.0]]))[0]) < 0.0


def test_mse_distribution():
    d = D.MSEDistribution(_mode=jnp.array([[1.0, 2.0]]), dims=1)
    np.testing.assert_allclose(d.log_prob(jnp.array([[0.0, 0.0]])), [-(1.0 + 4.0)], rtol=1e-6)


def test_two_hot_distribution_roundtrip():
    # logits that put all mass on the bin closest to symlog(7.0)
    bins = np.linspace(-20, 20, 255)
    target_bin = np.argmin(np.abs(bins - float(symlog(jnp.array(7.0)))))
    logits = jnp.full((1, 255), -1e9).at[0, target_bin].set(0.0)
    d = D.TwoHotEncodingDistribution(logits=logits, dims=1)
    assert abs(float(d.mean[0, 0]) - 7.0) < 1.0
    lp_near = float(d.log_prob(jnp.array([[float(d.mean[0, 0])]]))[0])
    lp_far = float(d.log_prob(jnp.array([[-15.0]]))[0])
    assert lp_near > lp_far


def test_two_hot_log_prob_is_cross_entropy():
    key = jax.random.PRNGKey(3)
    logits = jax.random.normal(key, (4, 255))
    d = D.TwoHotEncodingDistribution(logits=logits, dims=1)
    lp = d.log_prob(jnp.ones((4, 1)) * 2.5)
    assert lp.shape == (4,)
    assert np.all(np.asarray(lp) <= 0.0)


def test_kl_categorical():
    p = jnp.log(jnp.array([[0.5, 0.5]]))
    q = jnp.log(jnp.array([[0.9, 0.1]]))
    kl = D.kl_categorical(p, q, event_ndims=0)
    expected = 0.5 * math.log(0.5 / 0.9) + 0.5 * math.log(0.5 / 0.1)
    np.testing.assert_allclose(kl[0], expected, rtol=1e-4)
    assert float(D.kl_categorical(p, p, event_ndims=0)[0]) == 0.0


def test_kl_normal():
    p = D.Normal(loc=jnp.zeros((1, 2)), scale=jnp.ones((1, 2)))
    q = D.Normal(loc=jnp.ones((1, 2)), scale=jnp.ones((1, 2)) * 2.0)
    kl = D.kl_normal(p, q)
    per_dim = 0.5 * (0.25 + 0.25 - 1 - math.log(0.25))
    np.testing.assert_allclose(kl[0], 2 * per_dim, rtol=1e-4)


def test_distributions_work_under_jit():
    @jax.jit
    def f(key, loc):
        d = D.TanhNormal(loc=loc, scale=jnp.ones_like(loc))
        a, lp = d.sample_and_log_prob(key)
        return a.sum() + lp.sum()

    out = f(KEY, jnp.zeros((2, 3)))
    assert np.isfinite(float(out))
