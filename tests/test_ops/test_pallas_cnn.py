"""CNN-stage Pallas kernel numerics (the fourth north-star family): the
fused conv/deconv + LayerNorm + SiLU stages must match their plain-XLA twins
in value and gradient in interpret mode on CPU, and the CNN/DeCNN blocks
must produce identical outputs with the family toggled (VERDICT r2 #4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.nn.blocks import CNN, DeCNN
from sheeprl_tpu.ops import pallas_cnn
from sheeprl_tpu.ops import pallas_kernels as pk


@pytest.fixture
def pallas_interpret():
    pk.set_pallas(True, interpret=True)
    yield
    pk.set_pallas(None, interpret=False)


def _enc_reference(x, w, scale, offset, eps):
    pre = pallas_cnn._enc_conv(x, w).astype(jnp.float32)
    mean = jnp.mean(pre, axis=-1, keepdims=True)
    var = jnp.var(pre, axis=-1, keepdims=True)
    z = (pre - mean) * jax.lax.rsqrt(var + eps) * scale + offset
    return (z * jax.nn.sigmoid(z)).astype(x.dtype)


def _dec_reference(x, k, scale, offset, eps):
    pre = pallas_cnn._dec_deconv(x, k).astype(jnp.float32)
    mean = jnp.mean(pre, axis=-1, keepdims=True)
    var = jnp.var(pre, axis=-1, keepdims=True)
    z = (pre - mean) * jax.lax.rsqrt(var + eps) * scale + offset
    return (z * jax.nn.sigmoid(z)).astype(x.dtype)


def _stage_args(rng, n, h, w, cin, cout):
    return (
        jnp.asarray(rng.normal(size=(n, h, w, cin)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(4, 4, cin, cout)).astype(np.float32) * 0.2),
        jnp.asarray(rng.normal(size=(cout,)).astype(np.float32) + 1.0),
        jnp.asarray(rng.normal(size=(cout,)).astype(np.float32) * 0.1),
    )


@pytest.mark.parametrize("n,h,cin,cout", [(3, 8, 3, 8), (2, 16, 4, 6)])
def test_conv_ln_silu_matches_reference(pallas_interpret, n, h, cin, cout):
    x, w, scale, offset = _stage_args(np.random.default_rng(0), n, h, h, cin, cout)
    got = pallas_cnn.conv_ln_silu(x, w, scale, offset, 1e-3)
    want = _enc_reference(x, w, scale, offset, 1e-3)
    assert got.shape == (n, h // 2, h // 2, cout)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_conv_ln_silu_gradients(pallas_interpret):
    args = _stage_args(np.random.default_rng(1), 2, 8, 8, 3, 6)
    g_kernel = jax.grad(
        lambda *a: pallas_cnn.conv_ln_silu(*a, 1e-3).sum(), argnums=(0, 1, 2, 3)
    )(*args)
    g_ref = jax.grad(
        lambda *a: _enc_reference(*a, 1e-3).sum(), argnums=(0, 1, 2, 3)
    )(*args)
    for gk, gr in zip(g_kernel, g_ref):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=2e-4)


@pytest.mark.parametrize("n,h,cin,cout", [(3, 4, 8, 4), (2, 8, 6, 3)])
def test_deconv_ln_silu_matches_reference(pallas_interpret, n, h, cin, cout):
    x, k, scale, offset = _stage_args(np.random.default_rng(2), n, h, h, cin, cout)
    got = pallas_cnn.deconv_ln_silu(x, k, scale, offset, 1e-3)
    want = _dec_reference(x, k, scale, offset, 1e-3)
    assert got.shape == (n, 2 * h, 2 * h, cout)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_deconv_ln_silu_gradients(pallas_interpret):
    args = _stage_args(np.random.default_rng(3), 2, 4, 4, 5, 3)
    g_kernel = jax.grad(
        lambda *a: pallas_cnn.deconv_ln_silu(*a, 1e-3).sum(), argnums=(0, 1, 2, 3)
    )(*args)
    g_ref = jax.grad(
        lambda *a: _dec_reference(*a, 1e-3).sum(), argnums=(0, 1, 2, 3)
    )(*args)
    for gk, gr in zip(g_kernel, g_ref):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=2e-4)


def test_cnn_block_pallas_path_matches_plain():
    """The Dreamer encoder stack (k4/s2/SAME + LN + SiLU, no bias) through
    the CNN block: kernels on vs off must agree."""
    cnn = CNN.init(
        jax.random.PRNGKey(0), 3,
        channels=[4, 8], kernel_sizes=[4, 4], strides=[2, 2],
        act="silu", layer_norm=True, use_bias=False, norm_eps=1e-3,
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 3))
    pk.set_pallas(False)
    plain = cnn(x)
    pk.set_pallas(True, interpret=True)
    fused = cnn(x)
    pk.set_pallas(None, interpret=False)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(plain), atol=1e-5)


def test_decnn_block_pallas_path_matches_plain():
    """The Dreamer decoder stack through DeCNN (last layer un-normed and
    un-activated — must stay on the plain path)."""
    dec = DeCNN.init(
        jax.random.PRNGKey(0), 8,
        channels=[4, 3], kernel_sizes=[4, 4], strides=[2, 2],
        act="silu", layer_norm=True, use_bias=False, norm_eps=1e-3,
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 4, 8))
    pk.set_pallas(False)
    plain = dec(x)
    pk.set_pallas(True, interpret=True)
    fused = dec(x)
    pk.set_pallas(None, interpret=False)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(plain), atol=1e-5)


def test_sequence_batch_fold_through_cnn():
    """[T, B, H, W, C] inputs (batch-major fold) agree with per-frame calls."""
    cnn = CNN.init(
        jax.random.PRNGKey(0), 3,
        channels=[4], kernel_sizes=[4], strides=[2],
        act="silu", layer_norm=True, use_bias=False, norm_eps=1e-3,
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 2, 8, 8, 3))
    seq = cnn(x)
    per_frame = jnp.stack([
        jnp.stack([cnn(x[t, b]) for b in range(2)]) for t in range(3)
    ])
    np.testing.assert_allclose(np.asarray(seq), np.asarray(per_frame), atol=1e-5)
