"""Pallas kernel numerics: every kernel must match its plain-XLA twin (value
and gradient) in interpret mode on CPU — the correctness gate before the
on-chip benchmark decides which kernels stay enabled (VERDICT r1 #4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.nn.recurrent import LayerNormGRUCell
from sheeprl_tpu.ops import pallas_kernels as pk
from sheeprl_tpu.ops.distributions import TwoHotEncodingDistribution
from sheeprl_tpu.ops.math import symexp as symexp_ref, symlog as symlog_ref
from sheeprl_tpu.ops.math import two_hot


@pytest.fixture
def pallas_interpret():
    pk.set_pallas(True, interpret=True)
    yield
    pk.set_pallas(None, interpret=False)


def test_gru_kernel_matches_reference(pallas_interpret):
    rng = np.random.default_rng(0)
    B, Dx, H = 4, 6, 8
    x = jnp.asarray(rng.normal(size=(B, Dx)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(Dx + H, 3 * H)).astype(np.float32) * 0.2)
    scale = jnp.asarray(rng.normal(size=(3 * H,)).astype(np.float32) + 1.0)
    offset = jnp.asarray(rng.normal(size=(3 * H,)).astype(np.float32) * 0.1)

    got = pk.layernorm_gru_cell(x, h, w, scale, offset, 1e-5)
    want = pk._gru_reference(x, h, w, scale, offset, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_gru_kernel_gradients(pallas_interpret):
    rng = np.random.default_rng(1)
    B, Dx, H = 3, 5, 4
    args = (
        jnp.asarray(rng.normal(size=(B, Dx)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(B, H)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(Dx + H, 3 * H)).astype(np.float32) * 0.3),
        jnp.asarray(rng.normal(size=(3 * H,)).astype(np.float32) + 1.0),
        jnp.asarray(rng.normal(size=(3 * H,)).astype(np.float32) * 0.1),
    )
    g_kernel = jax.grad(
        lambda *a: pk.layernorm_gru_cell(*a, 1e-5).sum(), argnums=(0, 1, 2, 3, 4)
    )(*args)
    g_ref = jax.grad(
        lambda *a: pk._gru_reference(*a, 1e-5).sum(), argnums=(0, 1, 2, 3, 4)
    )(*args)
    for gk, gr in zip(g_kernel, g_ref):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=1e-5)


def test_gru_cell_module_pallas_path_matches_plain(pallas_interpret):
    cell = LayerNormGRUCell.init(jax.random.PRNGKey(0), 6, 8, use_bias=False)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 6))
    h = jax.random.normal(jax.random.PRNGKey(2), (4, 8))
    with_pallas = cell(x, h)
    pk.set_pallas(False)
    without = cell(x, h)
    np.testing.assert_allclose(np.asarray(with_pallas), np.asarray(without), atol=1e-5)


def test_two_hot_log_prob_matches_dense(pallas_interpret):
    rng = np.random.default_rng(2)
    N, K = 12, 17
    bins = jnp.linspace(-20.0, 20.0, K)
    x = jnp.asarray(rng.uniform(-25, 25, size=(N, 1)).astype(np.float32))
    logits = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))

    got = pk.two_hot_log_prob(x, logits, bins[None])
    target = two_hot(x[:, 0], bins)
    want = (target * jax.nn.log_softmax(logits, axis=-1)).sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_two_hot_log_prob_logits_gradient(pallas_interpret):
    rng = np.random.default_rng(3)
    N, K = 6, 9
    bins = jnp.linspace(-20.0, 20.0, K)
    x = jnp.asarray(rng.uniform(-20, 20, size=(N, 1)).astype(np.float32))
    logits = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))

    g_kernel = jax.grad(lambda l: pk.two_hot_log_prob(x, l, bins[None]).sum())(logits)

    def dense(l):
        target = two_hot(x[:, 0], bins)
        return (target * jax.nn.log_softmax(l, axis=-1)).sum()

    g_ref = jax.grad(dense)(logits)
    np.testing.assert_allclose(np.asarray(g_kernel), np.asarray(g_ref), atol=1e-5)


def test_two_hot_distribution_paths_agree(pallas_interpret):
    rng = np.random.default_rng(4)
    logits = jnp.asarray(rng.normal(size=(5, 3, 255)).astype(np.float32))
    x = jnp.asarray(rng.uniform(-30, 30, size=(5, 3, 1)).astype(np.float32))
    d = TwoHotEncodingDistribution(logits=logits)
    with_pallas = d.log_prob(x)
    pk.set_pallas(False)
    without = d.log_prob(x)
    np.testing.assert_allclose(np.asarray(with_pallas), np.asarray(without), atol=1e-4)


def test_symlog_symexp_kernels(pallas_interpret):
    x = jnp.asarray(np.linspace(-50, 50, 64, dtype=np.float32).reshape(8, 8))
    np.testing.assert_allclose(
        np.asarray(pk.symlog(x)), np.asarray(symlog_ref(x)), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(pk.symexp(x)), np.asarray(symexp_ref(x)), rtol=1e-6
    )
    g = jax.grad(lambda v: pk.symlog(v).sum())(x)
    g_ref = jax.grad(lambda v: symlog_ref(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5)
    g2 = jax.grad(lambda v: pk.symexp(v).sum())(x)
    g2_ref = jax.grad(lambda v: symexp_ref(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g2_ref), rtol=1e-5)


def test_pallas_disabled_on_cpu_by_default():
    # auto mode: CPU backend -> kernels off, the plain paths serve
    pk.set_pallas(None)
    assert not pk.use_pallas()
