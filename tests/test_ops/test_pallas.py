"""Pallas kernel numerics: every kernel must match its plain-XLA twin (value
and gradient) in interpret mode on CPU — the correctness gate before the
on-chip benchmark decides which kernels stay enabled (VERDICT r1 #4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.nn.recurrent import LayerNormGRUCell
from sheeprl_tpu.ops import pallas_kernels as pk
from sheeprl_tpu.ops.distributions import TwoHotEncodingDistribution
from sheeprl_tpu.ops.math import symexp as symexp_ref, symlog as symlog_ref
from sheeprl_tpu.ops.math import two_hot


@pytest.fixture
def pallas_interpret():
    pk.set_pallas(True, interpret=True)
    yield
    pk.set_pallas(None, interpret=False)


def test_gru_kernel_matches_reference(pallas_interpret):
    rng = np.random.default_rng(0)
    B, Dx, H = 4, 6, 8
    x = jnp.asarray(rng.normal(size=(B, Dx)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(Dx + H, 3 * H)).astype(np.float32) * 0.2)
    scale = jnp.asarray(rng.normal(size=(3 * H,)).astype(np.float32) + 1.0)
    offset = jnp.asarray(rng.normal(size=(3 * H,)).astype(np.float32) * 0.1)

    got = pk.layernorm_gru_cell(x, h, w, scale, offset, 1e-5)
    want = pk._gru_reference(x, h, w, scale, offset, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_gru_kernel_gradients(pallas_interpret):
    rng = np.random.default_rng(1)
    B, Dx, H = 3, 5, 4
    args = (
        jnp.asarray(rng.normal(size=(B, Dx)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(B, H)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(Dx + H, 3 * H)).astype(np.float32) * 0.3),
        jnp.asarray(rng.normal(size=(3 * H,)).astype(np.float32) + 1.0),
        jnp.asarray(rng.normal(size=(3 * H,)).astype(np.float32) * 0.1),
    )
    g_kernel = jax.grad(
        lambda *a: pk.layernorm_gru_cell(*a, 1e-5).sum(), argnums=(0, 1, 2, 3, 4)
    )(*args)
    g_ref = jax.grad(
        lambda *a: pk._gru_reference(*a, 1e-5).sum(), argnums=(0, 1, 2, 3, 4)
    )(*args)
    for gk, gr in zip(g_kernel, g_ref):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=1e-5)


def test_gru_cell_module_pallas_path_matches_plain(pallas_interpret):
    cell = LayerNormGRUCell.init(jax.random.PRNGKey(0), 6, 8, use_bias=False)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 6))
    h = jax.random.normal(jax.random.PRNGKey(2), (4, 8))
    with_pallas = cell(x, h)
    pk.set_pallas(False)
    without = cell(x, h)
    np.testing.assert_allclose(np.asarray(with_pallas), np.asarray(without), atol=1e-5)


def test_two_hot_log_prob_matches_dense(pallas_interpret):
    rng = np.random.default_rng(2)
    N, K = 12, 17
    bins = jnp.linspace(-20.0, 20.0, K)
    x = jnp.asarray(rng.uniform(-25, 25, size=(N, 1)).astype(np.float32))
    logits = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))

    got = pk.two_hot_log_prob(x, logits, bins[None])
    target = two_hot(x[:, 0], bins)
    want = (target * jax.nn.log_softmax(logits, axis=-1)).sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_two_hot_log_prob_logits_gradient(pallas_interpret):
    rng = np.random.default_rng(3)
    N, K = 6, 9
    bins = jnp.linspace(-20.0, 20.0, K)
    x = jnp.asarray(rng.uniform(-20, 20, size=(N, 1)).astype(np.float32))
    logits = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))

    g_kernel = jax.grad(lambda l: pk.two_hot_log_prob(x, l, bins[None]).sum())(logits)

    def dense(l):
        target = two_hot(x[:, 0], bins)
        return (target * jax.nn.log_softmax(l, axis=-1)).sum()

    g_ref = jax.grad(dense)(logits)
    np.testing.assert_allclose(np.asarray(g_kernel), np.asarray(g_ref), atol=1e-5)


def test_two_hot_distribution_paths_agree(pallas_interpret):
    rng = np.random.default_rng(4)
    logits = jnp.asarray(rng.normal(size=(5, 3, 255)).astype(np.float32))
    x = jnp.asarray(rng.uniform(-30, 30, size=(5, 3, 1)).astype(np.float32))
    d = TwoHotEncodingDistribution(logits=logits)
    with_pallas = d.log_prob(x)
    pk.set_pallas(False)
    without = d.log_prob(x)
    np.testing.assert_allclose(np.asarray(with_pallas), np.asarray(without), atol=1e-4)


def test_symlog_symexp_kernels(pallas_interpret):
    x = jnp.asarray(np.linspace(-50, 50, 64, dtype=np.float32).reshape(8, 8))
    np.testing.assert_allclose(
        np.asarray(pk.symlog(x)), np.asarray(symlog_ref(x)), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(pk.symexp(x)), np.asarray(symexp_ref(x)), rtol=1e-6
    )
    g = jax.grad(lambda v: pk.symlog(v).sum())(x)
    g_ref = jax.grad(lambda v: symlog_ref(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5)
    g2 = jax.grad(lambda v: pk.symexp(v).sum())(x)
    g2_ref = jax.grad(lambda v: symexp_ref(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g2_ref), rtol=1e-5)


def test_pallas_disabled_on_cpu_by_default():
    # auto mode: CPU backend -> kernels off, the plain paths serve
    pk.set_pallas(None)
    assert not pk.use_pallas()


# =============================================================================
# Fused RSSM dynamic step (ISSUE 9 tentpole b)
# =============================================================================


def _rssm_fixture(dtype=jnp.float32, seed=0):
    """A DV3-shaped RSSM (single-hidden LN MLPs, bias-free LN-GRU) plus a
    random dynamic-step input batch."""
    from sheeprl_tpu import nn
    from sheeprl_tpu.algos.dreamer_v3.agent import RSSM, RecurrentModel

    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 8)
    R, D, Hd, S, Dd, A, E, B = 16, 12, 10, 4, 4, 3, 8, 5
    rm = RecurrentModel.init(ks[0], S * Dd + A, R, D, layer_norm=True, activation="silu")
    tm = nn.MLP.init(ks[1], R, [Hd], S * Dd, act="silu", layer_norm=True,
                     use_bias=False, norm_eps=1e-3)
    pm = nn.MLP.init(ks[2], R + E, [Hd], S * Dd, act="silu", layer_norm=True,
                     use_bias=False, norm_eps=1e-3)
    rssm = RSSM(recurrent_model=rm, representation_model=pm,
                transition_model=tm, discrete=Dd, unimix=0.01)
    batch = dict(
        post=jax.random.normal(ks[3], (B, S, Dd), dtype),
        rec=jax.random.normal(ks[4], (B, R), dtype),
        act=jax.random.normal(ks[5], (B, A), dtype),
        emb=jax.random.normal(ks[6], (B, E), dtype),
        first=jnp.zeros((B, 1), jnp.float32),
        key=ks[7],
    )
    return rssm, batch


def _fused_args(rssm, x, emb):
    weights, act, eps = rssm._fused_step_weights(x, emb)
    return weights, act, eps


def test_fused_rssm_forward_matches_reference(pallas_interpret):
    rssm, b = _rssm_fixture()
    x = jnp.concatenate([b["post"].reshape(b["post"].shape[0], -1), b["act"]], -1)
    weights, act, eps = _fused_args(rssm, x, b["emb"])
    got = pk.fused_rssm_step(x, b["rec"], b["emb"], *weights, act, eps)
    want = pk.rssm_step_reference(x, b["rec"], b["emb"], *weights, act, eps)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)


def test_fused_rssm_vjp_matches_reference(pallas_interpret):
    rssm, b = _rssm_fixture(seed=1)
    x = jnp.concatenate([b["post"].reshape(b["post"].shape[0], -1), b["act"]], -1)
    weights, act, eps = _fused_args(rssm, x, b["emb"])

    def total(fn, *leading):
        out = fn(*leading, *weights, act, eps)
        return sum(jnp.sum(o.astype(jnp.float32) ** 2) for o in out)

    # d/d(x, h, emb) and d/d(every weight)
    argnums = tuple(range(3 + len(weights)))

    def total_all(fn, *args):
        out = fn(*args, act, eps)
        return sum(jnp.sum(o.astype(jnp.float32) ** 2) for o in out)

    g_kernel = jax.grad(lambda *a: total_all(pk.fused_rssm_step, *a), argnums)(
        x, b["rec"], b["emb"], *weights
    )
    g_ref = jax.grad(lambda *a: total_all(pk.rssm_step_reference, *a), argnums)(
        x, b["rec"], b["emb"], *weights
    )
    for gk, gr in zip(g_kernel, g_ref):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=1e-4)


def test_fused_rssm_dynamic_dispatch_matches_xla_path(pallas_interpret):
    """RSSM.dynamic with the fused kernel vs the plain module path: same
    states/logits (value AND gradient) — the swap-in is behavior-preserving."""
    rssm, b = _rssm_fixture(seed=2)
    inputs = (b["post"], b["rec"], b["act"], b["emb"], b["first"], b["key"])

    pk.set_pallas(False)
    ref = rssm.dynamic(*inputs)
    pk.set_pallas(True, interpret=True)
    fused = rssm.dynamic(*inputs)
    for r, f in zip(ref, fused):
        np.testing.assert_allclose(np.asarray(r), np.asarray(f), atol=1e-5)

    def loss(mod, use):
        pk.set_pallas(use, interpret=use)
        out = mod.dynamic(*inputs)
        return sum(jnp.sum(o.astype(jnp.float32) ** 2) for o in out)

    g_ref = jax.grad(lambda m: loss(m, False))(rssm)
    g_fused = jax.grad(lambda m: loss(m, True))(rssm)
    for a, c in zip(
        jax.tree_util.tree_leaves(g_ref), jax.tree_util.tree_leaves(g_fused)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=1e-4)


def test_fused_rssm_bf16_dtypes(pallas_interpret):
    """bf16-aware block contract: compute-dtype state out, f32 raw logits
    out (the fp32 island starts INSIDE the kernel — no extra upcasts)."""
    rssm, b = _rssm_fixture(dtype=jnp.bfloat16, seed=3)
    out = rssm.dynamic(
        b["post"], b["rec"], b["act"], b["emb"], b["first"], b["key"]
    )
    recurrent, posterior, prior, post_logits, prior_logits = out
    assert recurrent.dtype == jnp.bfloat16
    assert posterior.dtype == jnp.bfloat16 and prior.dtype == jnp.bfloat16
    assert post_logits.dtype == jnp.float32 and prior_logits.dtype == jnp.float32


def test_fused_rssm_dispatch_falls_back_on_mismatch(pallas_interpret):
    """A module shape outside the kernel contract (biased GRU projection)
    must return None from the dispatch guard — the XLA path serves."""
    from sheeprl_tpu import nn

    rssm, b = _rssm_fixture(seed=4)
    biased = rssm.recurrent_model.rnn.replace(
        proj=nn.Linear.init(jax.random.PRNGKey(9), 16 + 12, 3 * 16, use_bias=True)
    )
    rssm_biased = rssm.replace(
        recurrent_model=rssm.recurrent_model.replace(rnn=biased)
    )
    x = jnp.concatenate([b["post"].reshape(b["post"].shape[0], -1), b["act"]], -1)
    assert rssm_biased._fused_step_weights(x, b["emb"]) is None
    # and the full step still runs (plain path)
    out = rssm_biased.dynamic(
        b["post"], b["rec"], b["act"], b["emb"], b["first"], b["key"]
    )
    assert all(np.all(np.isfinite(np.asarray(o, dtype=np.float32))) for o in out)
