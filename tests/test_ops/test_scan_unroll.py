"""SHEEPRL_TPU_SCAN_UNROLL changes scheduling, not numerics: the unrolled
RSSM dynamic + imagination scans must produce the SAME losses and updated
parameters as the plain while-loop on the same batch and seeds (the bench
keep-decision relies on the configs being interchangeable,
ops/scan.py)."""

import jax
import numpy as np
import pytest

from sheeprl_tpu.ops.scan import scan_unroll
from tests.test_algos.test_precision import _run_one_step


def test_scan_unroll_env_parsing(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_SCAN_UNROLL", raising=False)
    assert scan_unroll() == 1
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "4")
    assert scan_unroll() == 4
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "0")
    assert scan_unroll() == 1  # floor
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "junk")
    assert scan_unroll() == 1  # unparseable -> plain loop


@pytest.mark.slow  # two full DV3 train-step compiles; runs per round
@pytest.mark.timeout(300)
def test_unrolled_step_matches_plain(monkeypatch):
    # unroll=2 against T=5, horizon=4: exercises both the non-divisible
    # remainder path (5 % 2) and the divisible one (4 % 2)
    monkeypatch.delenv("SHEEPRL_TPU_SCAN_UNROLL", raising=False)
    state_plain, m_plain = _run_one_step("float32")
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "2")
    state_unrolled, m_unrolled = _run_one_step("float32")

    for name in m_plain:
        np.testing.assert_allclose(
            m_unrolled[name], m_plain[name], rtol=1e-4, atol=1e-5, err_msg=name
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(state_plain.world_model),
        jax.tree_util.tree_leaves(state_unrolled.world_model),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
