"""SHEEPRL_TPU_SCAN_UNROLL changes scheduling, not numerics: the unrolled
RSSM dynamic + imagination scans must produce the SAME losses and updated
parameters as the plain while-loop on the same batch and seeds (the bench
keep-decision relies on the configs being interchangeable,
ops/scan.py)."""

import jax
import numpy as np
import pytest

from sheeprl_tpu.ops.scan import scan_unroll
from tests.test_algos.test_precision import _run_one_step


def test_scan_unroll_env_parsing(monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_SCAN_UNROLL", raising=False)
    assert scan_unroll() == 1
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "4")
    assert scan_unroll() == 4
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "0")
    assert scan_unroll() == 1  # floor
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "junk")
    assert scan_unroll() == 1  # unparseable -> plain loop


@pytest.mark.slow  # two full DV3 train-step compiles; runs per round
@pytest.mark.timeout(300)
def test_unrolled_step_matches_plain(monkeypatch):
    # unroll=2 against T=5, horizon=4: exercises both the non-divisible
    # remainder path (5 % 2) and the divisible one (4 % 2)
    monkeypatch.delenv("SHEEPRL_TPU_SCAN_UNROLL", raising=False)
    state_plain, m_plain = _run_one_step("float32")
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "2")
    state_unrolled, m_unrolled = _run_one_step("float32")

    for name in m_plain:
        np.testing.assert_allclose(
            m_unrolled[name], m_plain[name], rtol=1e-4, atol=1e-5, err_msg=name
        )
    for a, b in zip(
        jax.tree_util.tree_leaves(state_plain.world_model),
        jax.tree_util.tree_leaves(state_unrolled.world_model),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


# =============================================================================
# Measured unroll ladder (ISSUE 9 tentpole c)
# =============================================================================


def test_unroll_override_and_mode(monkeypatch):
    from sheeprl_tpu.ops import scan as scan_mod

    monkeypatch.delenv("SHEEPRL_TPU_SCAN_UNROLL", raising=False)
    assert scan_mod.unroll_mode() == "off"
    monkeypatch.setenv("SHEEPRL_TPU_SCAN_UNROLL", "auto")
    assert scan_mod.unroll_mode() == "auto"
    # "auto" is not an integer: the static read stays at 1 until a winner
    # is installed
    assert scan_unroll() == 1
    scan_mod.set_unroll(8)
    try:
        assert scan_unroll() == 8
        with scan_mod.unroll(2):
            assert scan_unroll() == 2
        assert scan_unroll() == 8
    finally:
        scan_mod.set_unroll(None)
    assert scan_unroll() == 1


def test_autotune_ladder_bit_exact_and_persisted(tmp_path, monkeypatch):
    """The measured ladder: every rung's outputs are bit-identical to rung
    1 (the per-rung receipt), the winner is one of the rungs, the decision
    persists next to the compile cache, and a same-key re-run is a cache
    hit that skips measurement."""
    import jax.numpy as jnp

    from sheeprl_tpu.ops import scan as scan_mod

    def fn(xs, c0):
        def step(c, x):
            c = jnp.tanh(c * 1.01 + x)
            return c, c

        _, ys = jax.lax.scan(step, c0, xs, unroll=scan_unroll())
        return ys

    xs = jnp.linspace(-1.0, 1.0, 12 * 3).reshape(12, 3)
    c0 = jnp.zeros((3,))
    store = str(tmp_path / "scan_unroll.json")
    try:
        decision = scan_mod.autotune_unroll(
            "test.scan", fn, (xs, c0), rungs=(1, 4, 8), repeats=2,
            store_path=store, apply=True,
        )
        assert decision.source == "measured"
        assert set(decision.bit_exact) == {1, 4, 8}
        assert all(decision.bit_exact.values())
        assert decision.winner in (1, 4, 8)
        assert scan_unroll() == decision.winner  # installed
        import json as _json

        with open(store) as fh:
            stored = _json.load(fh)
        assert decision.key in stored

        again = scan_mod.autotune_unroll(
            "test.scan", fn, (xs, c0), rungs=(1, 4, 8), repeats=2,
            store_path=store, apply=False,
        )
        assert again.source == "cache"
        assert again.winner == decision.winner
    finally:
        scan_mod.set_unroll(None)


def test_autotune_disqualifies_non_bit_exact_rung(tmp_path):
    """A rung whose outputs differ from rung 1 must never win — receipts
    gate the ladder, not just annotate it. (Forced via a function that
    READS the unroll knob into its numerics — a misuse the receipt is
    exactly there to catch.)"""
    import jax.numpy as jnp

    from sheeprl_tpu.ops import scan as scan_mod

    def fn(xs):
        # numerics depend on the knob: every rung != 1 is disqualified
        return xs * float(scan_unroll())

    xs = jnp.arange(8.0)
    try:
        decision = scan_mod.autotune_unroll(
            "test.tainted", fn, (xs,), rungs=(1, 4), repeats=1,
            store_path=str(tmp_path / "s.json"), apply=False,
        )
        assert decision.bit_exact[4] is False
        assert decision.winner == 1
    finally:
        scan_mod.set_unroll(None)
