"""Numerical contracts for RL math against straightforward NumPy recursions
written from the definitions (GAE: arXiv:1506.02438; lambda-returns: Dreamer)."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu import ops


def _np_gae(rewards, values, dones, next_value, next_done, gamma, lam):
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    lastgaelam = 0.0
    for t in reversed(range(T)):
        if t == T - 1:
            nonterm = 1.0 - next_done
            nxt = next_value
        else:
            nonterm = 1.0 - dones[t + 1]
            nxt = values[t + 1]
        delta = rewards[t] + gamma * nxt * nonterm - values[t]
        lastgaelam = delta + gamma * lam * nonterm * lastgaelam
        adv[t] = lastgaelam
    return adv + values, adv


def test_gae_matches_reference_recursion():
    rng = np.random.default_rng(0)
    T, B = 16, 4
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = (rng.random((T, B)) < 0.2).astype(np.float32)
    next_value = rng.normal(size=(B,)).astype(np.float32)
    next_done = (rng.random((B,)) < 0.2).astype(np.float32)
    ret, adv = ops.gae(
        jnp.array(rewards), jnp.array(values), jnp.array(dones),
        jnp.array(next_value), jnp.array(next_done), 0.99, 0.95,
    )
    ret_np, adv_np = _np_gae(rewards, values, dones, next_value, next_done, 0.99, 0.95)
    np.testing.assert_allclose(adv, adv_np, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ret, ret_np, rtol=1e-4, atol=1e-5)


def test_lambda_values_dv12_recursion():
    rng = np.random.default_rng(1)
    H, B = 15, 3
    rewards = rng.normal(size=(H, B)).astype(np.float32)
    values = rng.normal(size=(H, B)).astype(np.float32)
    mask = np.full((H, B), 0.99, dtype=np.float32)
    last = values[-1]
    lmbda = 0.95
    out = ops.lambda_values(
        jnp.array(rewards), jnp.array(values), jnp.array(mask), jnp.array(last), H, lmbda
    )
    # reference-style recursion (/root/reference/sheeprl/utils/utils.py:51-86)
    lam_vals = np.zeros((H - 1, B), dtype=np.float32)
    carry = np.zeros(B, dtype=np.float32)
    for step in reversed(range(H - 1)):
        nxt = last if step == H - 2 else values[step + 1] * (1 - lmbda)
        delta = rewards[step] + nxt * mask[step]
        carry = delta + lmbda * mask[step] * carry
        lam_vals[step] = carry
    np.testing.assert_allclose(out, lam_vals, rtol=1e-4, atol=1e-5)


def test_lambda_values_dv3_recursion():
    rng = np.random.default_rng(2)
    T, B = 14, 3
    rewards = rng.normal(size=(T, B, 1)).astype(np.float32)
    values = rng.normal(size=(T, B, 1)).astype(np.float32)
    conts = np.full((T, B, 1), 0.997, dtype=np.float32)
    out = ops.lambda_values_dv3(jnp.array(rewards), jnp.array(values), jnp.array(conts), 0.95)
    interm = rewards + conts * values * (1 - 0.95)
    carry = values[-1]
    expected = np.zeros_like(rewards)
    for t in reversed(range(T)):
        carry = interm[t] + conts[t] * 0.95 * carry
        expected[t] = carry
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_symlog_symexp_inverse():
    x = jnp.array([-100.0, -1.0, 0.0, 0.5, 10.0, 1e4])
    np.testing.assert_allclose(ops.symexp(ops.symlog(x)), x, rtol=1e-3)


def test_two_hot_partitions_mass():
    bins = jnp.linspace(-20.0, 20.0, 255)
    x = jnp.array([0.0, 3.3, -7.77, 25.0, -25.0])  # incl. out-of-range
    t = ops.two_hot(x, bins)
    assert t.shape == (5, 255)
    np.testing.assert_allclose(t.sum(-1), np.ones(5), rtol=1e-5)
    # expectation reconstructs in-range values
    recon = (t * bins).sum(-1)
    np.testing.assert_allclose(recon[:3], np.array([0.0, 3.3, -7.77]), atol=1e-3)
    # out-of-range snaps to edge bins
    np.testing.assert_allclose(recon[3:], np.array([20.0, -20.0]), atol=1e-5)


def test_two_hot_exact_bin_is_one_hot():
    bins = jnp.linspace(-2.0, 2.0, 5)  # bins at -2,-1,0,1,2
    t = ops.two_hot(jnp.array([1.0]), bins)
    np.testing.assert_allclose(t[0], np.array([0, 0, 0, 1, 0]), atol=1e-6)


def test_normalize_masked():
    x = jnp.array([1.0, 2.0, 3.0, 100.0])
    mask = jnp.array([True, True, True, False])
    out = ops.normalize(x, mask=mask)
    np.testing.assert_allclose(out[:3].mean(), 0.0, atol=1e-6)


def test_polynomial_decay():
    assert ops.polynomial_decay(0, initial=1.0, final=0.0, max_decay_steps=10) == 1.0
    assert ops.polynomial_decay(10, initial=1.0, final=0.0, max_decay_steps=10) == 0.0
    assert ops.polynomial_decay(11, initial=1.0, final=0.0, max_decay_steps=10) == 0.0
    mid = ops.polynomial_decay(5, initial=1.0, final=0.0, max_decay_steps=10)
    assert 0.0 < mid < 1.0


def test_moments_update():
    m = ops.Moments.init(decay=0.5)
    x = jnp.linspace(0.0, 1.0, 101)
    m2, (offset, invscale) = m.update(x)
    assert m2.low > m.low and m2.high > m.high
    assert invscale > 0
    # jits cleanly with the state as a pytree
    m3, _ = jax.jit(lambda s, v: s.update(v))(m2, x)
    assert float(m3.high) > float(m2.high)
