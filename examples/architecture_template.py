"""Template for a custom distributed RL topology on a device mesh.

The reference ships a 3-tier process template — one buffer process, N player
processes, M trainer processes wired with pickled-object collectives
(/root/reference/examples/architecture_template.py). On TPU the same roles
live in ONE SPMD program over disjoint sub-meshes of the device set:

  - PLAYER tier: device 0 runs jitted policy inference for the host env
    loop (env stepping itself is host Python — it never belongs on device);
  - BUFFER tier: the replay buffer is not a process at all — it is a ring
    of arrays (host numpy here; HBM `jax.Array`s in the real algorithms)
    whose sample batches are `device_put` straight onto the trainer
    sharding, replacing the reference's buffer process + scatter;
  - TRAINER tier: the remaining devices form a `Mesh(("data",))`; the
    jitted update runs with the batch sharded over that axis and XLA
    inserts the gradient all-reduce (replacing the DDP trainer group);
  - WEIGHTS path: updated params are `device_put` back to the player
    device (replacing the flattened-parameter broadcast).

Because it is one program, there are no shutdown sentinels, no uneven-input
Join contexts, and no pickling — control flow is ordinary Python, and every
transfer is a typed pytree over ICI.

Run without hardware on a virtual 8-device CPU mesh:

    PYTHONPATH=. JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/architecture_template.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.nn.blocks import MLP
from sheeprl_tpu.parallel import make_decoupled_meshes

OBS_DIM, ACT_DIM = 8, 4
ROLLOUT, BATCH, UPDATES = 256, 128, 10


def main():
    meshes = make_decoupled_meshes()  # device 0 = player, rest = trainers
    print(
        f"player: {meshes.player_device}, "
        f"trainers: {meshes.num_trainers} devices"
    )

    # --- model + optimizer, replicated across the trainer mesh --------------
    policy = MLP.init(jax.random.PRNGKey(0), OBS_DIM, [64, 64], ACT_DIM)
    optimizer = optax.adam(3e-4)
    opt_state = optimizer.init(policy)
    policy = meshes.replicated_on_trainers(policy)
    opt_state = meshes.replicated_on_trainers(opt_state)

    # --- player tier: jitted inference on the player device -----------------
    player_policy = meshes.to_player(policy)

    @jax.jit
    def act(policy, obs, key):
        logits = policy(obs)
        return jax.random.categorical(key, logits)

    # --- trainer tier: one jitted update over the sharded batch -------------
    @jax.jit
    def train_step(policy, opt_state, batch):
        def loss_fn(p):
            logits = p(batch["obs"])
            logp = jax.nn.log_softmax(logits)
            chosen = jnp.take_along_axis(logp, batch["actions"][:, None], axis=-1)
            return -jnp.mean(chosen[:, 0] * batch["returns"])

        loss, grads = jax.value_and_grad(loss_fn)(policy)
        updates, opt_state = optimizer.update(grads, opt_state)
        return optax.apply_updates(policy, updates), opt_state, loss

    # --- buffer tier: a plain host ring whose samples land sharded ----------
    rng = np.random.default_rng(0)
    obs_ring = np.zeros((ROLLOUT, OBS_DIM), np.float32)
    act_ring = np.zeros((ROLLOUT,), np.int32)
    ret_ring = np.zeros((ROLLOUT,), np.float32)

    key = jax.random.PRNGKey(1)
    for update in range(UPDATES):
        # player: collect a rollout (a scripted "env" here)
        for t in range(ROLLOUT):
            obs = rng.normal(size=(1, OBS_DIM)).astype(np.float32)
            key, sk = jax.random.split(key)
            action = act(player_policy, jnp.asarray(obs), sk)
            obs_ring[t] = obs[0]
            act_ring[t] = int(action[0])
            ret_ring[t] = rng.normal()

        # buffer -> trainers: typed pytree transfer, sharded on the batch axis
        idx = rng.integers(0, ROLLOUT, size=BATCH)
        batch = meshes.to_trainers(
            {
                "obs": jnp.asarray(obs_ring[idx]),
                "actions": jnp.asarray(act_ring[idx]),
                "returns": jnp.asarray(ret_ring[idx]),
            },
            axis=0,
        )

        # trainers: sharded update (XLA all-reduces the grads)
        policy, opt_state, loss = train_step(policy, opt_state, batch)

        # trainers -> player: weight refresh
        player_policy = meshes.to_player(policy)
        print(f"update {update}: loss {float(loss):+.4f}")

    print("template ok")


if __name__ == "__main__":
    main()
