#!/bin/bash
# Tunnel watchdog + auto-bench: probe every 5 min; on a healthy probe, land
# the round-4 chip receipts in priority order, so even a short tunnel window
# makes progress. A receipt only counts landed when its process exits 0 AND
# the artifact carries a real number (value > 0) — a tunnel that dies
# mid-step leaves no final file, so the next healthy window retries.
#
# Priority order (highest value first, resumable work before bounded probes):
#   1. bench_r4_chip.json — full interleaved-ABAB bench (kernel families,
#      bf16, scan-unroll ladder, e2e precision; paired-median keep rule)
#   2. xprof_r4/ — duty-cycle trace naming the next optimization slice
#   3. dreamer_v3 pixel learning run on chip (VERDICT r3 #4 at real scale).
#      RESUMABLE: checkpoints every 2048 steps; each attempt is bounded and
#      auto-resumes, so windows shorter than the full run still accumulate.
#      Lands logs/dreamer_v3_pixel_chip_r4.json on completion.
#   4. phase_probe_r4.json / blob_ab_r4.json — round-3 pending attributions
#   5. sac_ae pixel chip run — only if the CPU split-update receipt
#      (logs/sac_ae_pixel_r4.json) has not landed by then
#
# Concurrent CPU learning runs are recorded in the log. Host-sensitive chip
# steps (the bench's e2e phases, phase/blob probes) SIGSTOP any CPU learning
# runner for their duration and SIGCONT it after — on this 1-core box a
# concurrent trainer would otherwise skew the e2e slice downward.
cd /root/repo

cpu_jobs() { pgrep -f "pixel_learning_run|dv1_learning_run|decoupled_learning_run" | tr '\n' ' '; }
pause_cpu() { J=$(cpu_jobs); [ -n "$J" ] && kill -STOP $J 2>/dev/null; }
resume_cpu() { J=$(cpu_jobs); [ -n "$J" ] && kill -CONT $J 2>/dev/null; }
# EXIT alone doesn't fire on untrapped signals: a `kill` during a paused
# phase must not strand the trainers in state T
trap resume_cpu EXIT INT TERM

while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 45 python -c "import jax; assert any(d.platform!='cpu' for d in jax.devices())" 2>/dev/null; then
    echo "$ts TUNNEL_UP" >> logs/tunnel_watch.log
    if [ ! -f logs/bench_r4_chip.json ]; then
      echo "$ts autobench: starting (python procs: $(ps -e -o comm= | grep -c python))" >> logs/tunnel_watch.log
      pause_cpu
      SHEEPRL_TPU_BENCH_WATCHDOG_S=3000 timeout 3100 python bench.py \
        > logs/bench_r4_chip.tmp 2> logs/bench_r4_chip.err
      rc=$?
      resume_cpu
      if [ $rc -eq 0 ] && python - <<'PY'
import json, sys
try:
    with open("logs/bench_r4_chip.tmp") as fh:
        line = [l for l in fh if l.strip().startswith("{")][-1]
    sys.exit(0 if json.loads(line).get("value", 0) > 0 else 1)
except Exception:
    sys.exit(1)
PY
      then
        mv logs/bench_r4_chip.tmp logs/bench_r4_chip.json
        echo "$ts autobench: LANDED $(tail -c 200 logs/bench_r4_chip.json)" >> logs/tunnel_watch.log
      else
        echo "$ts autobench: FAILED rc=$rc (kept .tmp for forensics, will retry)" >> logs/tunnel_watch.log
      fi
    fi
    if [ -f logs/bench_r4_chip.json ] && [ ! -d logs/xprof_r4 ]; then
      timeout 900 python tools/chip_xprof_trace.py >> logs/tunnel_watch.log 2>&1
      echo "$ts xprof: rc=$?" >> logs/tunnel_watch.log
    fi
    # pixel learning at chip scale: resumable across windows (mid-run
    # checkpoints); a bounded attempt per healthy probe until the receipt
    # JSON lands
    if [ -f logs/bench_r4_chip.json ] && [ ! -f logs/dreamer_v3_pixel_chip_r4.json ]; then
      echo "$ts pixel-chip(dv3): attempt starting" >> logs/tunnel_watch.log
      MUJOCO_GL=egl timeout 2700 python tools/pixel_chip_run.py --algo dreamer_v3 \
        >> logs/dv3_pixel_chip_r4.out 2>&1
      echo "$ts pixel-chip(dv3): rc=$? (json present: $(test -f logs/dreamer_v3_pixel_chip_r4.json && echo yes || echo no))" >> logs/tunnel_watch.log
    fi
    # round-3 closing state named these two receipts PENDING the first
    # healthy tunnel (BENCHES.md): phase attribution V0..V4 and the blob
    # ON/OFF ABAB — run each once after the pixel receipt lands
    if [ -f logs/dreamer_v3_pixel_chip_r4.json ] && [ ! -f logs/phase_probe_r4.json ]; then
      pause_cpu
      timeout 2400 python tools/phase_probe.py > logs/phase_probe_r4.tmp 2>> logs/tunnel_watch.log \
        && mv logs/phase_probe_r4.tmp logs/phase_probe_r4.json
      echo "$ts phase_probe: rc=$?" >> logs/tunnel_watch.log
      resume_cpu
    fi
    if [ -f logs/dreamer_v3_pixel_chip_r4.json ] && [ ! -f logs/blob_ab_r4.json ]; then
      pause_cpu
      timeout 2400 python tools/blob_ab_probe.py > logs/blob_ab_r4.tmp 2>> logs/tunnel_watch.log \
        && mv logs/blob_ab_r4.tmp logs/blob_ab_r4.json
      echo "$ts blob_ab: rc=$?" >> logs/tunnel_watch.log
      resume_cpu
    fi
    # SAC-AE pixels on chip only if the CPU split-update receipt never lands
    if [ -f logs/blob_ab_r4.json ] && [ ! -f logs/sac_ae_pixel_r4.json ] \
       && [ ! -f logs/sac_ae_pixel_chip_r4.json ]; then
      echo "$ts pixel-chip(sac_ae): attempt starting" >> logs/tunnel_watch.log
      MUJOCO_GL=egl timeout 2700 python tools/pixel_chip_run.py --algo sac_ae \
        >> logs/sac_ae_pixel_chip_r4.out 2>&1
      echo "$ts pixel-chip(sac_ae): rc=$? (json present: $(test -f logs/sac_ae_pixel_chip_r4.json && echo yes || echo no))" >> logs/tunnel_watch.log
    fi
  else
    echo "$ts down" >> logs/tunnel_watch.log
  fi
  sleep 300
done
