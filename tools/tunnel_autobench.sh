#!/bin/bash
# Tunnel watchdog + auto-bench: probe every 5 min; on a healthy probe, run
# the full chip bench (interleaved ABAB keep-decisions) and an xprof
# duty-cycle trace, so a short tunnel window still lands the round-4
# receipts. The receipt only counts as landed when the bench exits 0 AND
# the artifact carries a real number (value > 0) — a tunnel that dies
# mid-bench leaves no file, so the next healthy window retries.
# Concurrent CPU learning runs are recorded in the log (they can skew the
# host-side e2e slice; duty-cycle phases are device-bound).
cd /root/repo
while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 45 python -c "import jax; assert any(d.platform!='cpu' for d in jax.devices())" 2>/dev/null; then
    echo "$ts TUNNEL_UP" >> logs/tunnel_watch.log
    if [ ! -f logs/bench_r4_chip.json ]; then
      echo "$ts autobench: starting (python procs: $(ps -e -o comm= | grep -c python))" >> logs/tunnel_watch.log
      SHEEPRL_TPU_BENCH_WATCHDOG_S=3000 timeout 3100 python bench.py \
        > logs/bench_r4_chip.tmp 2> logs/bench_r4_chip.err
      rc=$?
      if [ $rc -eq 0 ] && python - <<'PY'
import json, sys
try:
    with open("logs/bench_r4_chip.tmp") as fh:
        line = [l for l in fh if l.strip().startswith("{")][-1]
    sys.exit(0 if json.loads(line).get("value", 0) > 0 else 1)
except Exception:
    sys.exit(1)
PY
      then
        mv logs/bench_r4_chip.tmp logs/bench_r4_chip.json
        echo "$ts autobench: LANDED $(tail -c 200 logs/bench_r4_chip.json)" >> logs/tunnel_watch.log
      else
        echo "$ts autobench: FAILED rc=$rc (kept .tmp for forensics, will retry)" >> logs/tunnel_watch.log
      fi
    fi
    if [ -f logs/bench_r4_chip.json ] && [ ! -d logs/xprof_r4 ]; then
      timeout 900 python tools/chip_xprof_trace.py >> logs/tunnel_watch.log 2>&1
      echo "$ts xprof: rc=$?" >> logs/tunnel_watch.log
    fi
    # round-3 closing state named these two receipts PENDING the first
    # healthy tunnel (BENCHES.md): phase attribution V0..V4 and the blob
    # ON/OFF ABAB — run each once after the bench lands
    if [ -f logs/bench_r4_chip.json ] && [ ! -f logs/phase_probe_r4.json ]; then
      timeout 2400 python tools/phase_probe.py > logs/phase_probe_r4.tmp 2>> logs/tunnel_watch.log \
        && mv logs/phase_probe_r4.tmp logs/phase_probe_r4.json
      echo "$ts phase_probe: rc=$?" >> logs/tunnel_watch.log
    fi
    if [ -f logs/bench_r4_chip.json ] && [ ! -f logs/blob_ab_r4.json ]; then
      timeout 2400 python tools/blob_ab_probe.py > logs/blob_ab_r4.tmp 2>> logs/tunnel_watch.log \
        && mv logs/blob_ab_r4.tmp logs/blob_ab_r4.json
      echo "$ts blob_ab: rc=$?" >> logs/tunnel_watch.log
    fi
  else
    echo "$ts down" >> logs/tunnel_watch.log
  fi
  sleep 300
done
