#!/usr/bin/env python
"""sheepshard — SPMD partitioning & collective-communication analysis over
the lowered CompilePlan (ISSUE 8), with the CI-gated comms budget.

Usage:
    python tools/sheepshard.py                       # the full mesh sweep
    python tools/sheepshard.py ppo@mesh8 ppo@anakin  # a subset
    python tools/sheepshard.py --list-rules
    python tools/sheepshard.py --update-budget       # refresh comms/edges
    python tools/sheepshard.py --check-budget        # the CI comms gate
    python tools/sheepshard.py --source-only         # just the SC009 pass
    python tools/sheepshard.py --rules SC006,SC008 --json

For every sweep spec (analysis/shard_check.py `SHARD_SWEEP` — the mesh-
bearing configurations: data-parallel ppo on the virtual 8-mesh, both
Anakin variants with `shard_env_batch` placement, the (data,seq) context-
parallel dreamer, and the decoupled player/trainer topologies), the tool
runs the main in SHAPE-CAPTURE mode (zero execution), then lowers AND
compiles every mesh-bearing registered jit under its declared mesh on the
CPU virtual-device harness. The post-SPMD-partitioning HLO is parsed into
a per-jit comms ledger (every collective, its bytes, replica groups,
hot-loop placement, estimated bytes-on-the-wire) and checked (SC006-SC008);
declared CompilePlan data edges are resolved producer-output-sharding vs
consumer-input-sharding (SC008); and an AST pass flags eager collectives
in un-jitted host loops (SC009). Fingerprints live in the committed
`analysis/budget/` ledger (sections `comms` + `edges`, next to
sheepcheck's `jits`); `--check-budget` fails CI on unexplained drift: new
collective kinds, new/multiplied hot-loop collectives, comms-bytes growth
>25%, newly replicated large tensors, or a match-edge turning mismatch.

Exit codes: 0 clean, 1 findings or budget drift, 2 capture/usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent

# Same preamble as tools/sheepcheck.py: the comms ledger is derived on the
# CPU virtual 8-device harness by design (it must not depend on which
# accelerator happens to be attached), so re-exec once with the
# virtual-device flag before anything imports jax.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""  # skip the axon tunnel plugin
    os.execv(sys.executable, [sys.executable, *sys.argv])

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, str(_REPO))

from sheeprl_tpu.analysis import jaxpr_check as jc  # noqa: E402
from sheeprl_tpu.analysis import shard_check as sc  # noqa: E402

DEFAULT_BUDGET = str(_REPO / "analysis" / "budget.json")
SOURCE_PATHS = ("sheeprl_tpu", "tools", "bench.py")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "specs", nargs="*",
        help="sweep specs to capture (default: the full SHARD_SWEEP)",
    )
    ap.add_argument("--rules", default=None, help="comma-separated SC rule ids")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--budget", default=DEFAULT_BUDGET,
        help=f"budget ledger path (default {DEFAULT_BUDGET}; the "
             "analysis/budget/ dir layout is preferred when present)",
    )
    ap.add_argument(
        "--update-budget", action="store_true",
        help="write the derived comms/edges fingerprints to the ledger",
    )
    ap.add_argument(
        "--check-budget", action="store_true",
        help="fail on unexplained comms drift vs the ledger (the CI gate)",
    )
    ap.add_argument(
        "--source-only", action="store_true",
        help="run only the SC009 source pass (no capture, no compile)",
    )
    ap.add_argument(
        "--no-source", action="store_true",
        help="skip the SC009 source pass",
    )
    ap.add_argument(
        "--root-dir", default=None,
        help="where capture runs write their (throwaway) run dirs",
    )
    ap.add_argument("--verbose", action="store_true")
    ns = ap.parse_args(argv)

    if ns.list_rules:
        for rule in sc.SHARD_RULES.values():
            print(f"{rule.id} ({rule.name}) [{rule.severity}]")
            print(f"    {rule.summary}")
            print(f"    fix: {rule.autofix}")
        return 0

    rules = None
    if ns.rules:
        rules = {s.strip().upper() for s in ns.rules.split(",") if s.strip()}
        unknown = rules - set(sc.SHARD_RULES)
        if unknown:
            print(f"unknown rule ids: {sorted(unknown)}", file=sys.stderr)
            return 2

    source_findings = []
    if not ns.no_source and (rules is None or "SC009" in rules):
        source_findings = sc.check_source_collectives(
            [str(_REPO / p) for p in SOURCE_PATHS]
        )

    specs = ns.specs or sorted(sc.SHARD_SWEEP)
    unknown = {
        s for s in specs
        if s not in sc.SHARD_SWEEP and s not in jc.CAPTURE_VARIANTS
    }
    if ns.source_only:
        specs = []
    elif unknown:
        import sheeprl_tpu.algos  # noqa: F401 — fire registrations
        from sheeprl_tpu.utils.registry import tasks

        unknown -= set(tasks)
        if unknown:
            print(f"unknown specs: {sorted(unknown)}", file=sys.stderr)
            return 2

    root = ns.root_dir or tempfile.mkdtemp(prefix="sheepshard_")
    reports: list[sc.ShardReport] = []
    edges_by_spec: dict[str, dict[str, dict]] = {}
    edge_findings: list = []
    capture_errors = 0
    for spec in specs:
        algo, extra_argv = sc.resolve_capture(spec)
        t0 = time.perf_counter()
        try:
            plan = jc.capture_plan(algo, root, extra_argv=extra_argv)
        except BaseException as err:  # CaptureComplete is consumed inside
            if isinstance(err, (KeyboardInterrupt, SystemExit)):
                raise
            print(f"{spec}: CAPTURE FAILED: {type(err).__name__}: {err}",
                  file=sys.stderr)
            capture_errors += 1
            continue
        spec_reports, edge_records, spec_edge_findings = sc.analyze_shard_plan(
            spec, plan, rules=rules
        )
        reports.extend(spec_reports)
        edges_by_spec[spec] = edge_records
        edge_findings.extend(spec_edge_findings)
        analyzed = [r for r in spec_reports if r.comms is not None]
        wire = sum(r.comms["wire_bytes"] for r in analyzed)
        colls = sum(sum(r.comms["collectives"].values()) for r in analyzed)
        print(
            f"{spec}: {len(analyzed)}/{len(spec_reports)} mesh-bearing jits, "
            f"{colls} collective(s), ~{wire} wire bytes/step, "
            f"{len(edge_records)} edge(s), "
            f"{sum(len(r.failing) for r in spec_reports) + sum(1 for f in spec_edge_findings if not f.suppressed)} finding(s) "
            f"[{time.perf_counter() - t0:.1f}s]",
            file=sys.stderr,
        )
        if ns.verbose:
            for r in spec_reports:
                if r.error:
                    print(f"  {r.name}: skipped ({r.error})", file=sys.stderr)
                elif r.comms is not None:
                    print(
                        f"  {r.name}: {r.comms['collectives']} hot="
                        f"{r.comms['hot_collectives']} wire={r.comms['wire_bytes']}",
                        file=sys.stderr,
                    )

    all_findings = [
        *(f for r in reports for f in r.findings),
        *edge_findings,
        *source_findings,
    ]
    failing = [f for f in all_findings if not f.suppressed]
    suppressed = [f for f in all_findings if f.suppressed]

    budget_failures: list[str] = []
    budget_notes: list[str] = []
    derived = sc.build_comms_budget(reports, edges_by_spec)
    if ns.update_budget:
        if (ns.specs or ns.source_only) and jc.budget_exists(ns.budget):
            # partial refresh: replace only the captured specs' comms/edges
            ledger = jc.load_budget(ns.budget)
            prefixes = tuple(f"{s}/" for s in specs)
            for section in ("comms", "edges"):
                merged = {
                    k: v
                    for k, v in ledger.get(section, {}).items()
                    if not k.startswith(prefixes)
                }
                merged.update(derived.get(section, {}))
                derived[section] = merged
        jc.save_budget(derived, ns.budget, sections=("comms", "edges"))
        print(
            f"wrote {len(derived['comms'])} comms fingerprints + "
            f"{len(derived['edges'])} edge contracts to "
            f"{jc.budget_dir_of(ns.budget)}",
            file=sys.stderr,
        )
    elif ns.check_budget:
        if not jc.budget_exists(ns.budget):
            print(f"no ledger at {ns.budget} (run --update-budget first)",
                  file=sys.stderr)
            return 2
        ledger = jc.load_budget(ns.budget)
        if ns.specs:
            # partial capture: gate only the captured specs' entries
            prefixes = tuple(f"{s}/" for s in specs)
            ledger = {
                **ledger,
                "comms": {
                    k: v for k, v in ledger.get("comms", {}).items()
                    if k.startswith(prefixes)
                },
                "edges": {
                    k: v for k, v in ledger.get("edges", {}).items()
                    if k.startswith(prefixes)
                },
            }
        budget_failures, budget_notes = sc.check_comms_budget(ledger, derived)

    if ns.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in failing],
            "suppressed": [f.as_dict() for f in suppressed],
            "budget_failures": budget_failures,
            "budget_notes": budget_notes,
            "capture_errors": capture_errors,
            "comms": derived["comms"],
            "edges": derived["edges"],
        }, indent=2))
    else:
        for f in failing:
            print(f.format())
        if ns.verbose:
            for f in suppressed:
                print(f.format())
        for note in budget_notes:
            print(f"comms note: {note}", file=sys.stderr)
        for failure in budget_failures:
            print(f"COMMS DRIFT: {failure}")

    if capture_errors:
        return 2
    if failing or budget_failures:
        print(
            f"sheepshard: {len(failing)} finding(s), {len(suppressed)} "
            f"suppressed, {len(budget_failures)} comms drift(s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"sheepshard: clean ({len(derived['comms'])} jits fingerprinted, "
        f"{len(derived['edges'])} edge contract(s), "
        f"{len(suppressed)} suppressed finding(s))",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
