"""Chip A/B for the one-transfer blob step transport (data/blob.py).

Interleaves the honest DV3 e2e cycle (bench._dv3_e2e_sps) with the blob
path ON and OFF — ABAB so tunnel-latency drift hits both variants equally.
OFF is the previous best path (separate obs put + single packed add put);
ON merges everything into one int32 blob per step.

Usage: python tools/blob_ab_probe.py [--tiny] [--repeats N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--repeats", type=int, default=4)
    a = p.parse_args()

    import jax

    import bench

    print(f"backend: {jax.devices()}", file=sys.stderr)
    args, state, opts, actions_dim, is_continuous, obs_space = bench._dv3_setup(
        a.tiny
    )
    runs: dict[str, list[float]] = {"blob": [], "dict": []}
    for rep in range(a.repeats):
        for variant in ("blob", "dict"):
            os.environ["SHEEPRL_TPU_STEP_BLOB"] = "1" if variant == "blob" else "0"
            t0 = time.perf_counter()
            sps = bench._measure_guarded(
                bench._dv3_e2e_sps, args, state, opts,
                actions_dim, is_continuous, a.tiny,
            )
            runs[variant].append(round(sps, 1))
            print(
                f"rep {rep} {variant}: e2e_sps={sps:.1f}"
                f" ({time.perf_counter() - t0:.1f}s wall)",
                file=sys.stderr,
            )
    os.environ.pop("SHEEPRL_TPU_STEP_BLOB", None)
    med = {
        k: sorted(v)[len(v) // 2] if v else 0.0 for k, v in runs.items()
    }
    out = {
        "runs": runs,
        "median": med,
        "blob_over_dict": round(med["blob"] / med["dict"], 3) if med["dict"] else None,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
