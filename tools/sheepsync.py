#!/usr/bin/env python
"""sheepsync — static concurrency & wire-protocol analysis CLI (ISSUE 18).

The host-side sibling of sheeplint/sheepcheck/sheepshard/sheepmem: lock
graphs, thread inventories and FLK1 protocol sequencing for the threaded
runtime tiers (flock, serve, telemetry, resilience, parallel, compile).

Usage:

    python tools/sheepsync.py                  # sweep the six packages
    python tools/sheepsync.py --report         # print the lock-order report
    python tools/sheepsync.py --list-rules
    python tools/sheepsync.py --update-budget  # write analysis/budget/concurrency.json
    python tools/sheepsync.py --check-budget   # CI drift gate vs the ledger
    python tools/sheepsync.py --json path/     # machine-readable findings

Exit codes: 0 clean, 1 findings or budget regressions, 2 usage error.
Pure AST + JSON: no jax import, safe for the no-accelerator CI lane.
"""

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from sheeprl_tpu.analysis import concurrency_check as cc  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sheepsync", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: the six runtime packages)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--select", help="comma-separated rule ids (e.g. SY001,SY003)")
    ap.add_argument("--json", action="store_true", help="findings as JSON lines")
    ap.add_argument("--report", action="store_true", help="print the lock-order report")
    ap.add_argument("--update-budget", action="store_true",
                    help="rewrite the committed concurrency ledger")
    ap.add_argument("--check-budget", action="store_true",
                    help="fail on lock-graph drift vs the committed ledger")
    ap.add_argument("--budget", help="ledger path override (default analysis/budget/concurrency.json)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in cc.SY_RULES.values():
            print(f"{rule.id}  {rule.name:28s} [{rule.severity}]")
            print(f"       {rule.summary}")
        return 0

    select = None
    if args.select:
        select = {r.strip().upper() for r in args.select.split(",") if r.strip()}
        unknown = select - set(cc.SY_RULES)
        if unknown:
            print(f"sheepsync: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    for p in args.paths:
        if not os.path.exists(p):
            print(f"sheepsync: no such path: {p}", file=sys.stderr)
            return 2

    report = cc.analyze_paths(args.paths or None)
    budget_path = Path(args.budget) if args.budget else cc.ledger_path()

    if args.report:
        print(cc.render_report(report))

    if args.update_budget:
        path = cc.save_ledger(cc.build_ledger(report), budget_path)
        ledger = cc.load_ledger(path)
        print(f"sheepsync: wrote {path} "
              f"(fingerprint {ledger['concurrency']['fingerprint']}, "
              f"{len(ledger['concurrency']['lock_order']['edges'])} edges)")

    rc = 0
    findings = report.findings
    if select:
        findings = [f for f in findings if f.rule.id in select]
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if args.json:
        for f in findings:
            print(json.dumps(f.as_dict()))
    else:
        for f in active:
            print(f.format())
    if active:
        print(f"sheepsync: {len(active)} finding(s) "
              f"({len(suppressed)} suppressed)", file=sys.stderr)
        rc = 1
    elif not args.json and not args.report and not args.update_budget:
        print(f"sheepsync: clean ({len(suppressed)} suppressed, "
              f"{len(report.edges)} lock-order edges, "
              f"{len(report.threads)} threads)")

    if args.check_budget:
        regressions = cc.check_budget(
            cc.build_ledger(report), cc.load_ledger(budget_path)
        )
        if regressions:
            print("sheepsync: concurrency budget regressions:", file=sys.stderr)
            for r in regressions:
                print(f"  - {r}", file=sys.stderr)
            rc = 1
        else:
            print("sheepsync: budget OK (lock graph matches the committed ledger)")

    return rc


if __name__ == "__main__":
    sys.exit(main())
