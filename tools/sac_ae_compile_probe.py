"""Attribute the SAC-AE XLA:CPU compile stall to a specific split jit.

Round-4 context: the fused SAC-AE update stalls XLA:CPU >25 min at pixel
sizes; `--split_update` (four per-model jits) was built to sidestep it, but
the round-4 receipt runner STILL stalled >2.5 h in its first training step
with split_update=true (batch 32 / 128 units / 64x64x9 frames). This probe
builds the exact receipt-scale state WITHOUT envs and drives the split
train_step with the do-flags enabled one at a time, timing each jit's first
call under a SIGALRM bound — so the stall is attributed to critic / ema /
actor+alpha / recon rather than "somewhere in XLA".

Round-6 extensions (ISSUE 5):

  --recon-chunk N   probe the batch-chunked reconstruction partition
                    (compile/partition.py): the plus_recon phase compiles a
                    chunk-sized executable instead of the full-batch one.
  --sweep           run the (mode x batch x width) attribution matrix, one
                    SUBPROCESS per cell (fresh process: no in-memory jit
                    cache or allocator state leaks between cells; each cell
                    SIGALRM-bounded), and print a markdown table. This is
                    the receipt that resolves the VERDICT r5 951 s-vs->2.5 h
                    discrepancy: compile cost is ~linear in batch at fixed
                    program (23 convs) and superlinear (~x^2.4) in conv
                    channels, so the same nominal config lands anywhere from
                    minutes to hours depending on batch x width x host load.

Usage: python tools/sac_ae_compile_probe.py [--budget-s 900] [--batch 32]
Prints one JSON line per phase: {"phase": ..., "seconds": ... | "TIMEOUT"}.
Every cell disables the persistent compile cache (SHEEPRL_TPU_XLA_CACHE=0)
— cold compiles are the quantity under measurement.
"""

from __future__ import annotations

import os

# the sitecustomize overrides JAX_PLATFORMS at interpreter start, so the env
# var alone is not enough — the config.update below wins over it
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
# cold compiles are the measurement: a warm persistent cache would zero the
# very numbers this probe exists to attribute
os.environ["SHEEPRL_TPU_XLA_CACHE"] = "0"

import argparse
import json
import signal
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np


class PhaseTimeout(Exception):
    pass


def _alarm(_sig, _frm):
    raise PhaseTimeout


def _sweep(ns) -> None:
    """(mode x batch x width) matrix, one bounded subprocess per cell."""
    import subprocess

    # the discrepancy-resolving matrix: batch scaling at two widths (split),
    # the fused reference, and the partitioned (chunked-recon) path. Each
    # phase is timed TWICE (first call, then exec-only) so compile and
    # execution separate.
    cells = [
        ("split", 2, 4, 0),
        ("split", 4, 4, 0),
        ("split", 2, 16, 0),
        ("split", 4, 16, 0),
        ("fused", 2, 16, 0),
        ("split", 4, 16, ns.recon_chunk or 2),
    ]

    rows = []
    for mode, batch, mult, chunk in cells:
        cmd = [
            sys.executable, __file__,
            "--budget-s", str(ns.budget_s), "--batch", str(batch),
            "--hidden", str(ns.hidden), "--mult", str(mult),
        ]
        if mode == "fused":
            cmd.append("--fused")
        if chunk:
            cmd += ["--recon-chunk", str(chunk)]
        t0 = time.perf_counter()
        proc = subprocess.run(
            cmd, capture_output=True, text=True,
            timeout=ns.budget_s * 6 + 120,
        )
        phases = {}
        for line in proc.stdout.splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "phase" in rec:
                phases[rec["phase"]] = rec
        row = {
            "mode": mode, "batch": batch, "mult": mult, "chunk": chunk,
            "phases": phases, "wall_s": round(time.perf_counter() - t0, 1),
            "rc": proc.returncode,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    def cell(rec, field="seconds"):
        if rec is None:
            return "?"
        return rec.get(field, "?")

    print("\n| mode | batch | conv mult | recon chunk | recon first s | recon exec s | recon compile s | total first-call s |")
    print("|---|---|---|---|---|---|---|---|")
    for r in rows:
        p = r["phases"]
        rec = p.get("fused_all") if r["mode"] == "fused" else p.get("plus_recon")
        firsts = [
            v["seconds"] for v in p.values() if isinstance(v.get("seconds"), (int, float))
        ]
        n_expected = 1 if r["mode"] == "fused" else 4
        total = round(sum(firsts), 1) if len(p) == n_expected else "TIMEOUT"
        print(
            f"| {r['mode']} | {r['batch']} | {r['mult']} | {r['chunk'] or '-'} "
            f"| {cell(rec)} | {cell(rec, 'exec_seconds')} "
            f"| {cell(rec, 'compile_seconds_est')} | {total} |"
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget-s", type=int, default=900)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument(
        "--mult", type=int, default=16,
        help="cnn_channels_multiplier — conv width, the superlinear axis of "
        "the XLA:CPU compile cost",
    )
    ap.add_argument("--fused", action="store_true", help="probe the fused path instead")
    ap.add_argument(
        "--recon-chunk", type=int, default=0,
        help="probe the batch-chunked recon partition (0 = unchunked)",
    )
    ap.add_argument(
        "--sweep", action="store_true",
        help="run the (mode x batch x width) matrix in bounded subprocesses "
        "and print the attribution table",
    )
    ap.add_argument(
        "--compile-only", action="store_true",
        help="AOT-compile every jit of the chosen path (lower().compile(), "
        "no execution) and print per-executable compile seconds — the "
        "receipt that 'compiles to first update' is bounded at any batch; "
        "the residual first-call cost is execution",
    )
    ns = ap.parse_args()
    if ns.sweep:
        return _sweep(ns)

    from sheeprl_tpu.algos.sac_ae.args import SACAEArgs
    from sheeprl_tpu.algos.sac_ae.agent import (
        SACAEAgent,
        SACAECNNDecoder,
        SACAECNNEncoder,
        SACAEDecoder,
        SACAEEncoder,
    )
    from sheeprl_tpu.algos.sac_ae.sac_ae import (
        TrainState,
        make_optimizers,
        make_split_train_step,
        make_train_step,
    )
    from sheeprl_tpu.utils.parser import DataclassArgumentParser

    parser = DataclassArgumentParser(SACAEArgs)
    (args,) = parser.parse_args_into_dataclasses([
        "--per_rank_batch_size", str(ns.batch),
        "--actor_hidden_size", str(ns.hidden),
        "--critic_hidden_size", str(ns.hidden),
        "--dense_units", str(ns.hidden),
        "--cnn_channels_multiplier", str(ns.mult),
    ])
    args.screen_size = 64

    key = jax.random.PRNGKey(0)
    key, k_cnn, k_agent, k_dec = jax.random.split(key, 4)
    cnn_keys, mlp_keys = ("rgb",), ()
    in_channels = 9  # 3 stacked rgb frames, the receipt configuration
    cnn_encoder = SACAECNNEncoder.init(
        k_cnn, in_channels, args.features_dim, cnn_keys,
        screen_size=args.screen_size,
        cnn_channels_multiplier=args.cnn_channels_multiplier,
    )
    encoder = SACAEEncoder(cnn_encoder=cnn_encoder, mlp_encoder=None)
    cnn_decoder = SACAECNNDecoder.init(
        k_dec, cnn_encoder.conv_output_shape, encoder.output_dim,
        cnn_keys, [in_channels],
        cnn_channels_multiplier=args.cnn_channels_multiplier,
    )
    decoder = SACAEDecoder(cnn_decoder=cnn_decoder, mlp_decoder=None)
    agent = SACAEAgent.init(
        k_agent, encoder, 1,
        num_critics=args.num_critics,
        actor_hidden_size=args.actor_hidden_size,
        critic_hidden_size=args.critic_hidden_size,
        action_low=np.array([-1.0]), action_high=np.array([1.0]),
        alpha=args.alpha, tau=args.tau, encoder_tau=args.encoder_tau,
    )
    optimizers = make_optimizers(args)
    qf_optim, actor_optim, alpha_optim, encoder_optim, decoder_optim = optimizers
    state = TrainState(
        agent=agent, decoder=decoder,
        qf_opt=qf_optim.init(agent.critic),
        actor_opt=actor_optim.init(agent.actor),
        alpha_opt=alpha_optim.init(agent.log_alpha),
        encoder_opt=encoder_optim.init(agent.critic.encoder),
        decoder_opt=decoder_optim.init(decoder),
    )

    b = ns.batch
    rng = np.random.default_rng(0)
    batch = {
        "rgb": jnp.asarray(rng.integers(0, 255, (1, b, 64, 64, 9), dtype=np.uint8)),
        "next_rgb": jnp.asarray(rng.integers(0, 255, (1, b, 64, 64, 9), dtype=np.uint8)),
        "actions": jnp.asarray(rng.normal(size=(1, b, 1)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(1, b, 1)).astype(np.float32)),
        "dones": jnp.zeros((1, b, 1), jnp.float32),
    }

    if ns.fused:
        train_step = make_train_step(args, optimizers, cnn_keys, mlp_keys)
    else:
        train_step = make_split_train_step(
            args, optimizers, cnn_keys, mlp_keys, recon_chunk=ns.recon_chunk
        )
    signal.signal(signal.SIGALRM, _alarm)

    if ns.compile_only:
        import jax as _jax

        b = ns.batch
        bspec = {
            "rgb": _jax.ShapeDtypeStruct((b, 64, 64, 9), jnp.uint8),
            "next_rgb": _jax.ShapeDtypeStruct((b, 64, 64, 9), jnp.uint8),
            "actions": _jax.ShapeDtypeStruct((b, 1), jnp.float32),
            "rewards": _jax.ShapeDtypeStruct((b, 1), jnp.float32),
            "dones": _jax.ShapeDtypeStruct((b, 1), jnp.float32),
        }
        c = ns.recon_chunk or b
        cspec = {k: _jax.ShapeDtypeStruct((c,) + v.shape[1:], v.dtype)
                 for k, v in bspec.items() if k in ("rgb",)}
        nspec = {"rgb": _jax.ShapeDtypeStruct((c, 64, 64, 9), jnp.float32)}
        if ns.fused:
            jobs = {"fused_train_step": (train_step, (
                state, {k: _jax.ShapeDtypeStruct((1,) + v.shape, v.dtype)
                        for k, v in bspec.items()},
                key, True, True, True))}
        else:
            jits = train_step.jits
            jobs = {
                "critic_step": (jits["critic"], (agent, state.qf_opt, bspec, key)),
                "ema_step": (jits["ema"], (agent,)),
                "actor_alpha_step": (jits["actor_alpha"], (
                    agent, state.actor_opt, state.alpha_opt, bspec, key)),
            }
            if ns.recon_chunk:
                jobs["recon_grads_step"] = (jits["recon_grads"], (
                    agent.critic.encoder, decoder, cspec, nspec))
                jobs["recon_apply_step"] = (jits["recon_apply"], (
                    agent, decoder, state.encoder_opt, state.decoder_opt,
                    agent.critic.encoder, decoder))
            else:
                jobs["recon_step"] = (jits["recon"], (
                    agent, decoder, state.encoder_opt, state.decoder_opt,
                    bspec, key))
        total = 0.0
        for name, (fn, ex) in jobs.items():
            from sheeprl_tpu.compile import avals_of

            t0 = time.perf_counter()
            signal.alarm(ns.budget_s)
            try:
                fn.lower(*avals_of(ex)).compile()
                signal.alarm(0)
                dt = round(time.perf_counter() - t0, 2)
                total += dt
                print(json.dumps({"jit": name, "compile_seconds": dt}), flush=True)
            except PhaseTimeout:
                print(json.dumps({"jit": name, "compile_seconds": "TIMEOUT",
                                  "budget_s": ns.budget_s}), flush=True)
                return
        print(json.dumps({"jit": "TOTAL", "compile_seconds": round(total, 2),
                          "batch": b, "mult": ns.mult,
                          "recon_chunk": ns.recon_chunk}), flush=True)
        return

    if ns.fused:
        phases = [("fused_all", (True, True, True))]
    else:
        phases = [
            ("critic_only", (False, False, False)),
            ("plus_ema", (True, False, False)),
            ("plus_actor_alpha", (True, True, False)),
            ("plus_recon", (True, True, True)),
        ]
    for name, (do_ema, do_actor, do_decoder) in phases:
        key, k = jax.random.split(key)
        t0 = time.perf_counter()
        signal.alarm(ns.budget_s)
        try:
            out_state, metrics = train_step(state, batch, k, do_ema, do_actor, do_decoder)
            jax.block_until_ready(metrics)
            signal.alarm(0)
            dt = round(time.perf_counter() - t0, 1)
            state = out_state
            # SECOND call at identical shapes: pure execution (the dispatch
            # cache serves the executable). first - exec ~= compile. This
            # split is the round-6 extension that resolved the r5 "951 s
            # compile" attribution: the scaling cost is execution.
            key, k2 = jax.random.split(key)
            t1 = time.perf_counter()
            signal.alarm(ns.budget_s)
            out_state, metrics = train_step(
                state, batch, k2, do_ema, do_actor, do_decoder
            )
            jax.block_until_ready(metrics)
            signal.alarm(0)
            exec_s = round(time.perf_counter() - t1, 1)
            print(json.dumps({
                "phase": name, "seconds": dt, "exec_seconds": exec_s,
                "compile_seconds_est": round(max(dt - exec_s, 0.0), 1),
            }), flush=True)
            state = out_state
        except PhaseTimeout:
            print(json.dumps({"phase": name, "seconds": "TIMEOUT",
                              "budget_s": ns.budget_s}), flush=True)
            break


if __name__ == "__main__":
    main()
