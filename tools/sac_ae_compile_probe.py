"""Attribute the SAC-AE XLA:CPU compile stall to a specific split jit.

Round-4 context: the fused SAC-AE update stalls XLA:CPU >25 min at pixel
sizes; `--split_update` (four per-model jits) was built to sidestep it, but
the round-4 receipt runner STILL stalled >2.5 h in its first training step
with split_update=true (batch 32 / 128 units / 64x64x9 frames). This probe
builds the exact receipt-scale state WITHOUT envs and drives the split
train_step with the do-flags enabled one at a time, timing each jit's first
call under a SIGALRM bound — so the stall is attributed to critic / ema /
actor+alpha / recon rather than "somewhere in XLA".

Usage: python tools/sac_ae_compile_probe.py [--budget-s 900] [--batch 32]
Prints one JSON line per phase: {"phase": ..., "seconds": ... | "TIMEOUT"}.
"""

from __future__ import annotations

import os

# the sitecustomize overrides JAX_PLATFORMS at interpreter start, so the env
# var alone is not enough — the config.update below wins over it
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import argparse
import json
import signal
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np


class PhaseTimeout(Exception):
    pass


def _alarm(_sig, _frm):
    raise PhaseTimeout


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget-s", type=int, default=900)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--fused", action="store_true", help="probe the fused path instead")
    ns = ap.parse_args()

    from sheeprl_tpu.algos.sac_ae.args import SACAEArgs
    from sheeprl_tpu.algos.sac_ae.agent import (
        SACAEAgent,
        SACAECNNDecoder,
        SACAECNNEncoder,
        SACAEDecoder,
        SACAEEncoder,
    )
    from sheeprl_tpu.algos.sac_ae.sac_ae import (
        TrainState,
        make_optimizers,
        make_split_train_step,
        make_train_step,
    )
    from sheeprl_tpu.utils.parser import DataclassArgumentParser

    parser = DataclassArgumentParser(SACAEArgs)
    (args,) = parser.parse_args_into_dataclasses([
        "--per_rank_batch_size", str(ns.batch),
        "--actor_hidden_size", str(ns.hidden),
        "--critic_hidden_size", str(ns.hidden),
        "--dense_units", str(ns.hidden),
    ])
    args.screen_size = 64

    key = jax.random.PRNGKey(0)
    key, k_cnn, k_agent, k_dec = jax.random.split(key, 4)
    cnn_keys, mlp_keys = ("rgb",), ()
    in_channels = 9  # 3 stacked rgb frames, the receipt configuration
    cnn_encoder = SACAECNNEncoder.init(
        k_cnn, in_channels, args.features_dim, cnn_keys,
        screen_size=args.screen_size,
        cnn_channels_multiplier=args.cnn_channels_multiplier,
    )
    encoder = SACAEEncoder(cnn_encoder=cnn_encoder, mlp_encoder=None)
    cnn_decoder = SACAECNNDecoder.init(
        k_dec, cnn_encoder.conv_output_shape, encoder.output_dim,
        cnn_keys, [in_channels],
        cnn_channels_multiplier=args.cnn_channels_multiplier,
    )
    decoder = SACAEDecoder(cnn_decoder=cnn_decoder, mlp_decoder=None)
    agent = SACAEAgent.init(
        k_agent, encoder, 1,
        num_critics=args.num_critics,
        actor_hidden_size=args.actor_hidden_size,
        critic_hidden_size=args.critic_hidden_size,
        action_low=np.array([-1.0]), action_high=np.array([1.0]),
        alpha=args.alpha, tau=args.tau, encoder_tau=args.encoder_tau,
    )
    optimizers = make_optimizers(args)
    qf_optim, actor_optim, alpha_optim, encoder_optim, decoder_optim = optimizers
    state = TrainState(
        agent=agent, decoder=decoder,
        qf_opt=qf_optim.init(agent.critic),
        actor_opt=actor_optim.init(agent.actor),
        alpha_opt=alpha_optim.init(agent.log_alpha),
        encoder_opt=encoder_optim.init(agent.critic.encoder),
        decoder_opt=decoder_optim.init(decoder),
    )

    b = ns.batch
    rng = np.random.default_rng(0)
    batch = {
        "rgb": jnp.asarray(rng.integers(0, 255, (1, b, 64, 64, 9), dtype=np.uint8)),
        "next_rgb": jnp.asarray(rng.integers(0, 255, (1, b, 64, 64, 9), dtype=np.uint8)),
        "actions": jnp.asarray(rng.normal(size=(1, b, 1)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(1, b, 1)).astype(np.float32)),
        "dones": jnp.zeros((1, b, 1), jnp.float32),
    }

    make = make_train_step if ns.fused else make_split_train_step
    train_step = make(args, optimizers, cnn_keys, mlp_keys)
    signal.signal(signal.SIGALRM, _alarm)

    if ns.fused:
        phases = [("fused_all", (True, True, True))]
    else:
        phases = [
            ("critic_only", (False, False, False)),
            ("plus_ema", (True, False, False)),
            ("plus_actor_alpha", (True, True, False)),
            ("plus_recon", (True, True, True)),
        ]
    for name, (do_ema, do_actor, do_decoder) in phases:
        key, k = jax.random.split(key)
        t0 = time.perf_counter()
        signal.alarm(ns.budget_s)
        try:
            out_state, metrics = train_step(state, batch, k, do_ema, do_actor, do_decoder)
            jax.block_until_ready(metrics)
            signal.alarm(0)
            dt = round(time.perf_counter() - t0, 1)
            print(json.dumps({"phase": name, "seconds": dt}), flush=True)
            state = out_state
        except PhaseTimeout:
            print(json.dumps({"phase": name, "seconds": "TIMEOUT",
                              "budget_s": ns.budget_s}), flush=True)
            break


if __name__ == "__main__":
    main()
