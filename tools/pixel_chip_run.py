"""Chip-scale pixel learning receipts (closes VERDICT r3 #4 at real scale).

The round-4 CPU campaign proved the DV3 world model learns pixels at tiny
scale but the imagination actor needs real model capacity/updates — "the
model capacity/updates of the real (chip-scale) configs, which this box
cannot fit in a session" (BENCHES.md). This runner trains the REAL
(reference-default) configs on the tunneled TPU chip:

- ``--algo dreamer_v3``: reference-scale DV3 (512 units, 32x32 latent,
  cnn mult 32, B=16 T=64) on dmc_cartpole_swingup pixels — BASELINE
  config 4's shape (DMC pixels + RSSM + conv encoder/decoder). Swingup:
  shaped reward (imagination gradient everywhere), random ~27, so any
  learning is a wide-margin receipt.
- ``--algo sac_ae``: reference-default SAC-AE (batch 128, hidden 1024,
  cnn mult 16) on the same pixels. The CPU attempt learned fast
  ([18, 106, 101] by episode 3) but hit an XLA:CPU compile pathology;
  on TPU the same jit compiles in well under a minute.

Both evaluate through the framework's own ``--eval_only`` path and read
per-episode returns back from the eval run's TB events. Mid-run
checkpoints + auto-resume make a tunnel death resumable.

Reference scope: /root/reference/sheeprl/algos/dreamer_v3/dreamer_v3.py:316-707,
/root/reference/sheeprl/algos/sac_ae/sac_ae.py:50-130.

Usage: MUJOCO_GL=egl python tools/pixel_chip_run.py --algo dreamer_v3
"""

from __future__ import annotations

import os

os.environ.setdefault("MUJOCO_GL", "egl")  # osmesa is broken in this image

import argparse
import glob
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import numpy as np

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.utils.checkpoint import latest_checkpoint
from sheeprl_tpu.utils.registry import tasks

RECIPES = {
    "dreamer_v3": dict(
        env_id="dmc_cartpole_swingup",
        seed=5,
        total_steps=16384,
        learning_starts=1024,
        train_every=2,
        # ring bounded to the run budget (nothing evicts within it) so the
        # per-checkpoint buffer snapshot stays ~200 MB, and checkpointed so
        # a tunnel-death resume keeps its replay data instead of training
        # on a near-empty ring
        buffer_size=16384,
        checkpoint_buffer=True,
        action_repeat=2,
        checkpoint_every=2048,
        # model/batch sizes: reference defaults (512/512, 32x32, cnn 32,
        # B=16 T=64) — deliberately NOT overridden
    ),
    "sac_ae": dict(
        env_id="dmc_cartpole_swingup",
        seed=5,
        total_steps=12288,
        learning_starts=1000,
        buffer_size=12288,
        checkpoint_buffer=True,
        action_repeat=4,  # the reference's DMC SAC-AE convention
        checkpoint_every=2048,
        # batch 128 / hidden 1024 / cnn mult 16: reference defaults
    ),
}

RANDOM_BASELINE = "swingup random 18.5-35.7 over 3 episodes (measured 2026-08-02)"


def _train(algo: str, root: Path, recipe: dict) -> None:
    argv = [
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        "--root_dir", str(root),
        "--run_name", "learn",
        "--cnn_keys", "rgb",
    ]
    for k, v in recipe.items():
        if isinstance(v, bool):
            argv += [f"--{k}" if v else f"--no_{k}"]
        else:
            argv += [f"--{k}", str(v)]
    resume = latest_checkpoint(str(root / "learn" / "checkpoints"))
    if resume is not None:
        print(f"[pixel-chip] resuming from {resume}", flush=True)
        argv += ["--checkpoint_path", resume]
    tasks[algo](argv)


def _evaluate(algo: str, root: Path, episodes: int) -> dict:
    ckpt = latest_checkpoint(str(root / "learn" / "checkpoints"))
    assert ckpt is not None, "no checkpoint to evaluate"
    eval_root = str(root) + "_eval"
    tasks[algo]([
        "--eval_only",
        "--checkpoint_path", ckpt,
        "--test_episodes", str(episodes),
        "--seed", "1000",
        "--root_dir", eval_root,
        "--run_name", "eval",
    ])
    from tensorboard.backend.event_processing.event_accumulator import (
        EventAccumulator,
    )

    events = glob.glob(os.path.join(eval_root, "**", "events.*"), recursive=True)
    assert events, f"no TB events under {eval_root}"
    returns: list[float] = []
    for f in sorted(events, key=os.path.getmtime, reverse=True):
        ea = EventAccumulator(f)
        ea.Reload()
        if "Test/episode_reward" in ea.Tags()["scalars"]:
            returns = [e.value for e in ea.Scalars("Test/episode_reward")]
            break
    assert returns, "eval run logged no Test/episode_reward"
    return {
        "checkpoint": ckpt,
        "returns": [round(r, 1) for r in returns],
        "mean_return": float(np.mean(returns)),
        "random_baseline": RANDOM_BASELINE,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--algo", choices=sorted(RECIPES), default="dreamer_v3")
    ap.add_argument("--root", default=None)
    ap.add_argument("--eval-only", action="store_true")
    ap.add_argument("--episodes", type=int, default=10)
    ap.add_argument("--total-steps", type=int, default=None,
                    help="override the recipe budget; works on resume too — "
                    "explicitly-provided CLI flags override the checkpoint "
                    "sidecar (apply_eval_overrides' training-resume branch)")
    ap.add_argument("--env-id", default=None,
                    help="override the recipe env (e.g. dmc_walker_walk — BASELINE config 4)")
    ns = ap.parse_args()
    platforms = {d.platform for d in jax.devices()}
    assert platforms - {"cpu"}, (
        f"pixel_chip_run needs the tunneled chip; jax.devices() is {platforms}. "
        "Use tools/dv3_pixel_learning_run.py / sac_ae_pixel_learning_run.py for CPU."
    )
    recipe = dict(RECIPES[ns.algo])
    if ns.total_steps is not None:
        recipe["total_steps"] = ns.total_steps
    if ns.env_id is not None:
        recipe["env_id"] = ns.env_id
    root = Path(ns.root or f"logs/{ns.algo}_pixel_chip_r4")
    t0 = time.time()
    if not ns.eval_only:
        _train(ns.algo, root, recipe)
    result = _evaluate(ns.algo, root, ns.episodes)
    result["recipe"] = recipe
    result["backend"] = sorted(platforms)
    result["train_plus_eval_seconds"] = round(time.time() - t0, 1)
    out = Path(str(root) + ".json")
    out.write_text(json.dumps(result, indent=2))
    print(json.dumps({k: result[k] for k in ("mean_return", "returns")}))
    print(f"[pixel-chip] receipt written to {out}", flush=True)


if __name__ == "__main__":
    main()
