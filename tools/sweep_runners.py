"""Session-end straggler sweep (VERDICT r4 #4).

SIGTERMs any `tools/*_learning_run.py` / `pixel_chip_run.py` process still
alive — the bounded harness (tools/runner_common.py) turns SIGTERM into the
graceful checkpoint-then-eval path, so a swept runner lands a
partial/resumable receipt instead of dying silently. After a grace window,
survivors (stuck in native code) get SIGKILL; their mid-run checkpoints
remain resumable and runner_common's hard timer has usually already written
a stub.

Usage: python tools/sweep_runners.py [--grace-s 900] [--dry-run]
Intended callers: the autobench loop's session boundary and any operator
ending a work session.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import time

PATTERNS = ("learning_run.py", "pixel_chip_run.py")


def _is_runner_cmd(cmd: str) -> bool:
    """True only for a python interpreter executing a runner SCRIPT: the
    first token must be a python binary and a later token must be a path
    whose basename matches a runner pattern (ADVICE r5: a plain substring
    match also SIGKILLed `tail -f dv1_learning_run.py`, editors with the
    file open, and greps over the tools tree)."""
    tokens = cmd.split()
    if len(tokens) < 2:
        return False
    interp = os.path.basename(tokens[0])
    if not interp.startswith("python"):
        return False
    if "sweep_runners" in cmd:
        return False
    for tok in tokens[1:]:
        if tok.startswith("-"):
            continue  # interpreter flags (-u, -X, ...)
        # first non-flag token is the script path (a `python -m pkg` runner
        # would not match the .py patterns, correctly)
        base = os.path.basename(tok)
        return any(base.endswith(p) for p in PATTERNS)
    return False


def find_runners() -> dict[int, str]:
    out = subprocess.run(
        ["ps", "-e", "-o", "pid=,args="], capture_output=True, text=True
    ).stdout
    procs = {}
    for line in out.splitlines():
        pid_s, _, cmd = line.strip().partition(" ")
        if _is_runner_cmd(cmd.strip()):
            procs[int(pid_s)] = cmd.strip()
    return procs


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--grace-s", type=float, default=900.0,
                    help="wait this long for graceful receipts before SIGKILL")
    ap.add_argument("--dry-run", action="store_true")
    ns = ap.parse_args()

    procs = find_runners()
    if not procs:
        print("sweep: no runner processes found")
        return
    for pid, cmd in procs.items():
        print(f"sweep: SIGTERM {pid}: {cmd}")
        if not ns.dry_run:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
    if ns.dry_run:
        return
    deadline = time.time() + ns.grace_s
    while time.time() < deadline:
        alive = [pid for pid in procs if _alive(pid)]
        if not alive:
            print("sweep: all runners exited gracefully")
            return
        time.sleep(10)
    for pid in procs:
        if _alive(pid):
            print(f"sweep: SIGKILL {pid} (stuck past grace; checkpoint "
                  "remains resumable)")
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


if __name__ == "__main__":
    main()
