"""Session-end straggler sweep (VERDICT r4 #4).

SIGTERMs any `tools/*_learning_run.py` / `pixel_chip_run.py` process still
alive — the bounded harness (tools/runner_common.py) turns SIGTERM into the
graceful checkpoint-then-eval path, so a swept runner lands a
partial/resumable receipt instead of dying silently. After a grace window,
survivors (stuck in native code) get SIGKILL; their mid-run checkpoints
remain resumable and runner_common's hard timer has usually already written
a stub.

Usage: python tools/sweep_runners.py [--grace-s 900] [--dry-run]
Intended callers: the autobench loop's session boundary and any operator
ending a work session.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import time

PATTERNS = ("learning_run.py", "pixel_chip_run.py")


def find_runners() -> dict[int, str]:
    out = subprocess.run(
        ["ps", "-e", "-o", "pid=,args="], capture_output=True, text=True
    ).stdout
    procs = {}
    for line in out.splitlines():
        pid_s, _, cmd = line.strip().partition(" ")
        if any(p in cmd for p in PATTERNS) and "sweep_runners" not in cmd:
            procs[int(pid_s)] = cmd.strip()
    return procs


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--grace-s", type=float, default=900.0,
                    help="wait this long for graceful receipts before SIGKILL")
    ap.add_argument("--dry-run", action="store_true")
    ns = ap.parse_args()

    procs = find_runners()
    if not procs:
        print("sweep: no runner processes found")
        return
    for pid, cmd in procs.items():
        print(f"sweep: SIGTERM {pid}: {cmd}")
        if not ns.dry_run:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
    if ns.dry_run:
        return
    deadline = time.time() + ns.grace_s
    while time.time() < deadline:
        alive = [pid for pid in procs if _alive(pid)]
        if not alive:
            print("sweep: all runners exited gracefully")
            return
        time.sleep(10)
    for pid in procs:
        if _alive(pid):
            print(f"sweep: SIGKILL {pid} (stuck past grace; checkpoint "
                  "remains resumable)")
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


if __name__ == "__main__":
    main()
