"""Derive the A100 anchor for the north-star ratio (VERDICT r2 #8).

The reference publishes no benchmark numbers (BASELINE.md) and this image has
zero egress, so the A100 side of the "≥1.2× A100 env-steps/sec/chip" goal
cannot be *measured* here. This script derives a defensible engineering
anchor instead:

    1. count the FLOPs of one flagship DreamerV3 duty cycle (train_every
       policy steps + one train step at the published model scale — the same
       computation bench.py times) with XLA's HLO cost analysis;
    2. divide by A100 peak throughput at stated MFU assumptions.

The anchor is DERIVED, NOT MEASURED — its assumptions (MFU, precision mode)
are printed alongside so the ratio stays falsifiable: anyone with an A100
can time the reference's train() at this exact shape and replace the
estimate. Run on CPU; only `lower()` is needed (no execution), so shapes are
full-scale.
"""

from __future__ import annotations

import json

import jax
import numpy as np

# A100-SXM peak dense throughput (NVIDIA A100 datasheet, public):
#   fp32 (CUDA cores)     19.5 TFLOP/s
#   tf32 (tensor cores)  156   TFLOP/s   <- torch matmul default since 1.12 is
#                                            fp32-accumulate tf32 OFF, but
#                                            lightning precision=32 keeps conv
#                                            /matmul on tf32-capable kernels
#   bf16 (tensor cores)  312   TFLOP/s
PEAKS = {"fp32": 19.5e12, "tf32": 156e12, "bf16": 312e12}
# Model FLOP utilization band for a conv+GRU-scan+MLP training mix on A100.
# Published end-to-end MFU for non-transformer RL workloads is well below
# LLM-training MFU; 0.35 is deliberately GENEROUS to the A100 side so the
# resulting ratio understates, not overstates, this framework.
MFU = 0.35


def main() -> None:
    jax.config.update("jax_platforms", "cpu")
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    args, state, opts, actions_dim, is_continuous, obs_space = bench._dv3_setup(
        tiny=False
    )
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step

    world_opt, actor_opt, critic_opt = opts
    train_step = make_train_step(
        args, world_opt, actor_opt, critic_opt,
        args.cnn_keys, args.mlp_keys, actions_dim, is_continuous,
    )
    sample_batch, obs, mask = bench._dv3_synth_data(args, actions_dim, obs_space)
    key = jax.random.PRNGKey(0)

    lowered_train = jax.jit(train_step).lower(
        state, sample_batch, key, jax.numpy.float32(0.02)
    )
    train_flops = float(lowered_train.cost_analysis()["flops"])

    make_player, _ = bench._dv3_player_fns(args, actions_dim, is_continuous)
    player = make_player(state)
    pstate = player.init_states(args.num_envs)
    lowered_policy = jax.jit(
        lambda p, s, o, k: p.step(s, o, k, jax.numpy.float32(0.0))
    ).lower(player, pstate, obs, key)
    policy_flops = float(lowered_policy.cost_analysis()["flops"])

    # one duty cycle = train_every policy steps + one train step,
    # advancing train_every * num_envs env steps (bench.py accounting)
    cycle_flops = args.train_every * policy_flops + train_flops
    env_steps = args.train_every * args.num_envs
    out = {
        "train_step_tflops": round(train_flops / 1e12, 3),
        "policy_step_gflops": round(policy_flops / 1e9, 3),
        "cycle_tflops": round(cycle_flops / 1e12, 3),
        "env_steps_per_cycle": env_steps,
        "mfu_assumed": MFU,
        "a100_anchor_sps": {
            mode: round(env_steps / (cycle_flops / (peak * MFU)), 1)
            for mode, peak in PEAKS.items()
        },
        "note": (
            "derived anchor: env-steps/sec an A100 would sustain on this "
            "exact duty cycle at the stated peak x MFU; not a measurement"
        ),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
