"""Budget-proofed DreamerV1 learning receipt (VERDICT r3 next-round #3).

The round-3 attempt reused the DV2/DV3 CartPole recipe verbatim and died to
the session budget (>75 min on the 1-core box, no checkpoint). This runner
fixes both failure modes:

- **Shrunk recipe**: DV1 needs fewer imagination FLOPs than DV3 (Gaussian
  latent, no discrete head) — 4096 total steps, 200-unit nets, horizon 10
  (vs DV3's 6144 / 256 / 15).
- **Mid-run checkpoints + resume**: `--checkpoint_every 1024` writes a
  checkpoint every ~1k env steps, and on restart the runner auto-resumes
  from the latest one (DV1's `--checkpoint_path` restore path,
  dreamer_v1.py:382-404), so a timeout costs at most 1k steps, not the run.
- **Eval-from-checkpoint**: after training (or on `--eval-only` against a
  partial run) the latest checkpoint is restored and greedily evaluated for
  10 episodes; the result is written to logs/dv1_learn_r4.json.

Reference scope: /root/reference/sheeprl/algos/dreamer_v1/dreamer_v1.py:40-358
(the training loop this receipt certifies our redesign of).

Usage: python tools/dv1_learning_run.py [--eval-only] [--root DIR]
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ.setdefault("SHEEPRL_TPU_COMPILE_CACHE", "logs/jax_compile_cache")  # children: skip axon registration

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax

jax.config.update("jax_platforms", "cpu")

import gymnasium as gym
import jax.numpy as jnp
import numpy as np

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.algos.dreamer_v1.agent import PlayerDV1, build_models
from sheeprl_tpu.algos.dreamer_v1.args import DreamerV1Args
from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_optimizers
from sheeprl_tpu.algos.ppo.agent import one_hot_to_env_actions
from sheeprl_tpu.utils.checkpoint import latest_checkpoint, load_checkpoint
from sheeprl_tpu.utils.registry import tasks

# Attempt 1 (CartPole, 4096 steps, DV1 defaults use_continues=False/expl 0.3)
# trained fine (world losses converged, 897 updates, 7 min) but learned
# nothing (greedy 18.9 ~= random): with no continue predictor the imagined
# rollouts never terminate, and CartPole's ONLY learning signal is
# termination. Attempt 2 (CartPole, continues on, 6144 steps) collapsed
# below random (9.8): DV1's actor trains by PURE dynamics backprop of
# imagined values — no reinforce term, no entropy bonus (the reference
# DV1 loss has neither; DV2 added both) — and the straight-through discrete
# policy saturated into always-left. The reference does support discrete
# DV1 (OneHotCategoricalStraightThrough via the shared Actor); whether its
# torch implementation also collapses on tiny-CartPole is unverified here.
# Attempt 3 moves to DV1's native regime: continuous control with dense
# rewards (Pendulum swing-up, the SAC/DroQ receipt env), tanh_normal actor
# + additive Gaussian exploration noise, no continue head (no termination).
# Attempt 3 (Pendulum, reference lrs, expl 0.3 constant, 12288 then resumed
# to 28672 steps) plateaued at greedy -1066..-1213 across every checkpoint
# vs measured same-protocol random -1287 — within noise, not a receipt.
# Diagnosis: DV1's reference actor/critic lr (8e-5) is calibrated for its
# 100-updates-per-1000-steps x 5M-step regime (~500k updates); our receipt
# budget delivers ~3.5k updates, so the actor barely moves. Attempt 4
# keeps the reference ALGORITHM and scales the receipt recipe: 4x
# actor/critic lr and exploration decay (0.3 -> 0.05) so late collection
# exploits what the world model knows.
RECIPE = dict(
    env_id="Pendulum-v1",
    seed=5,
    total_steps=24576,  # extended once: 12288 still improving (rew_avg -1464 -> -883)
    learning_starts=1024,
    train_every=4,
    gradient_steps=1,  # DV1 default is 100 (train_every=1000 regime)
    per_rank_batch_size=16,
    per_rank_sequence_length=32,
    buffer_size=100000,
    dense_units=200,
    hidden_size=200,
    recurrent_state_size=200,
    stochastic_size=30,
    mlp_layers=2,
    horizon=15,
    action_repeat=1,
    checkpoint_every=2048,
    use_continues=False,
    expl_amount=0.3,
    expl_decay=True,
    expl_min=0.05,
    max_step_expl_decay=2000,
    actor_lr=3e-4,
    critic_lr=3e-4,
)


def _train(root: Path) -> None:
    argv = [
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        "--root_dir", str(root),
        "--run_name", "learn",
        "--mlp_keys", "state",
    ]
    for k, v in RECIPE.items():
        if isinstance(v, bool):
            argv += [f"--{k}" if v else f"--no_{k}"]
        else:
            argv += [f"--{k}", str(v)]
    resume = latest_checkpoint(str(root / "learn" / "checkpoints"))
    if resume is not None:
        print(f"[dv1] resuming from {resume}", flush=True)
        argv += ["--checkpoint_path", resume]
    tasks["dreamer_v1"](argv)


def _evaluate(root: Path) -> dict:
    ckpt = latest_checkpoint(str(root / "learn" / "checkpoints"))
    assert ckpt is not None, "no checkpoint to evaluate"
    env = gym.make(RECIPE["env_id"])
    is_continuous = hasattr(env.action_space, "high")
    act_dim = (
        int(np.prod(env.action_space.shape)) if is_continuous else env.action_space.n
    )
    args = DreamerV1Args(env_id=RECIPE["env_id"], seed=5)
    args.cnn_keys, args.mlp_keys = [], ["state"]
    for k in (
        "dense_units", "hidden_size", "recurrent_state_size",
        "stochastic_size", "mlp_layers", "horizon", "action_repeat",
        "use_continues",
    ):
        setattr(args, k, RECIPE[k])
    wm, actor, critic = build_models(
        jax.random.PRNGKey(0), [act_dim], is_continuous, args,
        {"state": env.observation_space}, [], ["state"],
    )
    wopt, aopt, copt = make_optimizers(args)
    restored = load_checkpoint(ckpt, {
        "world_model": wm, "actor": actor, "critic": critic,
        "world_optimizer": wopt.init(wm), "actor_optimizer": aopt.init(actor),
        "critic_optimizer": copt.init(critic),
        "expl_decay_steps": 0, "global_step": 0, "batch_size": 0,
    })
    player = PlayerDV1(
        encoder=restored["world_model"].encoder,
        rssm=restored["world_model"].rssm,
        actor=restored["actor"],
        actions_dim=(act_dim,),
        stochastic_size=RECIPE["stochastic_size"],
        recurrent_state_size=RECIPE["recurrent_state_size"],
        is_continuous=is_continuous,
    )
    step = jax.jit(
        lambda p, s, o, k: p.step(s, o, k, jnp.float32(0.0), is_training=False)
    )
    returns = []
    for episode in range(10):
        obs, _ = env.reset(seed=1000 + episode)
        state = player.init_states(1)
        key = jax.random.PRNGKey(episode)
        done, ep_return = False, 0.0
        while not done:
            dobs = {"state": jnp.asarray(obs, jnp.float32)[None]}
            key, sub = jax.random.split(key)
            state, actions = step(player, state, dobs, sub)
            if is_continuous:
                obs, reward, terminated, truncated, _ = env.step(
                    np.asarray(actions)[0]
                )
            else:
                act = one_hot_to_env_actions(
                    np.asarray(actions), (act_dim,), False
                )[0]
                obs, reward, terminated, truncated, _ = env.step(act.item())
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(ep_return)
    env.close()
    return {
        "checkpoint": ckpt,
        "returns": returns,
        "mean_return": float(np.mean(returns)),
        "global_step_restored": int(restored["global_step"]),
    }


def main() -> None:
    from runner_common import bounded_runner_main

    bounded_runner_main(
        "logs/dv1_learn_r4d", _train, _evaluate, RECIPE, "dv1"
    )


if __name__ == "__main__":
    main()
