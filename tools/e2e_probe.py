"""One-off probe: time the honest DV3 e2e loop (bench._dv3_e2e_sps) on the
current backend, in isolation from the full bench sweep. Used to A/B the
replay-transfer packing work (round 3) without paying the full artifact run.

Usage: python tools/e2e_probe.py [--tiny] [--repeats N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--repeats", type=int, default=3)
    a = p.parse_args()

    import jax

    import bench

    print(f"backend: {jax.devices()}", file=sys.stderr)
    args, state, opts, actions_dim, is_continuous, obs_space = bench._dv3_setup(
        a.tiny
    )
    results = []
    for i in range(a.repeats):
        t0 = time.perf_counter()
        sps = bench._measure_guarded(
            bench._dv3_e2e_sps, args, state, opts, actions_dim, is_continuous, a.tiny
        )
        results.append(round(sps, 1))
        print(
            f"run {i}: e2e_sps={sps:.1f} ({time.perf_counter() - t0:.1f}s wall)",
            file=sys.stderr,
        )
    print(json.dumps({"e2e_sps_runs": results, "best": max(results)}))


if __name__ == "__main__":
    main()
