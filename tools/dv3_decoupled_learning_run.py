"""Decoupled-DV3 learning receipt (VERDICT r3 next-round #6).

Round 3 proved the decoupled plumbing (0.999x coupled parity on the virtual
mesh, cross-task checkpoint eval) but nothing showed the decoupled loop
itself LEARNS — the player runs one update behind the trainers
(stale-weights overlap, sheeprl_tpu/algos/dreamer_v3/dreamer_v3_decoupled.py),
and that staleness tolerance was untested against returns. This runner
trains the SAME tiny-CartPole recipe as the coupled DV3 learning regression
(tests/test_algos/test_learning.py::test_dreamer_v3_learns_cartpole,
validated greedy mean 408.5) through `dreamer_v3_decoupled` on a 2-device
virtual CPU mesh (1 player + 1 trainer), then greedily evaluates the
checkpoint. A learning result here certifies that the one-update weight lag
does not break imagination training.

Usage: python tools/dv3_decoupled_learning_run.py [--eval-only]
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ.setdefault("SHEEPRL_TPU_COMPILE_CACHE", "logs/jax_compile_cache")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax

jax.config.update("jax_platforms", "cpu")

import gymnasium as gym
import jax.numpy as jnp
import numpy as np

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu import ops
from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3, build_models
from sheeprl_tpu.algos.dreamer_v3.args import DreamerV3Args
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_optimizers
from sheeprl_tpu.algos.ppo.agent import one_hot_to_env_actions
from sheeprl_tpu.utils.checkpoint import latest_checkpoint, load_checkpoint
from sheeprl_tpu.utils.registry import tasks

# identical to the coupled regression's recipe (test_learning.py) so any
# return gap is attributable to the decoupled topology, not the config
RECIPE = dict(
    env_id="CartPole-v1",
    seed=5,
    total_steps=6144,
    learning_starts=512,
    train_every=4,
    per_rank_batch_size=16,
    per_rank_sequence_length=32,
    buffer_size=100000,
    dense_units=256,
    hidden_size=256,
    recurrent_state_size=256,
    stochastic_size=16,
    discrete_size=16,
    mlp_layers=2,
    horizon=15,
    action_repeat=1,
    checkpoint_every=2048,
)


def _train(root: Path) -> None:
    argv = [
        "--num_devices", "2",  # 1 player + 1 trainer sub-mesh
        "--num_envs", "1",
        "--sync_env",
        "--root_dir", str(root),
        "--run_name", "learn",
        "--mlp_keys", "state",
    ]
    for k, v in RECIPE.items():
        if isinstance(v, bool):
            argv += [f"--{k}" if v else f"--no_{k}"]
        else:
            argv += [f"--{k}", str(v)]
    resume = latest_checkpoint(str(root / "learn" / "checkpoints"))
    if resume is not None:
        print(f"[dv3-decoupled] resuming from {resume}", flush=True)
        argv += ["--checkpoint_path", resume]
    tasks["dreamer_v3_decoupled"](argv)


def _evaluate(root: Path) -> dict:
    ckpt = latest_checkpoint(str(root / "learn" / "checkpoints"))
    assert ckpt is not None, "no checkpoint to evaluate"
    env = gym.make("CartPole-v1")
    args = DreamerV3Args(env_id="CartPole-v1", seed=5)
    args.cnn_keys, args.mlp_keys = [], ["state"]
    for k in (
        "dense_units", "hidden_size", "recurrent_state_size",
        "stochastic_size", "discrete_size", "mlp_layers", "horizon",
        "action_repeat",
    ):
        setattr(args, k, RECIPE[k])
    wm, actor, critic, tcritic = build_models(
        jax.random.PRNGKey(0), [2], False, args,
        {"state": env.observation_space}, [], ["state"],
    )
    wopt, aopt, copt = make_optimizers(args)
    restored = load_checkpoint(ckpt, {
        "world_model": wm, "actor": actor, "critic": critic,
        "target_critic": tcritic,
        "world_optimizer": wopt.init(wm), "actor_optimizer": aopt.init(actor),
        "critic_optimizer": copt.init(critic),
        "moments": ops.Moments.init(args.moments_decay, args.moment_max),
        "expl_decay_steps": 0, "global_step": 0, "batch_size": 0,
    })
    player = PlayerDV3(
        encoder=restored["world_model"].encoder,
        rssm=restored["world_model"].rssm,
        actor=restored["actor"],
        actions_dim=(2,),
        stochastic_size=RECIPE["stochastic_size"],
        discrete_size=RECIPE["discrete_size"],
        recurrent_state_size=RECIPE["recurrent_state_size"],
        is_continuous=False,
    )
    step = jax.jit(
        lambda p, s, o, k: p.step(s, o, k, jnp.float32(0.0), is_training=False)
    )
    returns = []
    for episode in range(10):
        obs, _ = env.reset(seed=1000 + episode)
        state = player.init_states(1)
        key = jax.random.PRNGKey(episode)
        done, ep_return = False, 0.0
        while not done:
            dobs = {"state": jnp.asarray(obs, jnp.float32)[None]}
            key, sub = jax.random.split(key)
            state, actions = step(player, state, dobs, sub)
            act = one_hot_to_env_actions(np.asarray(actions), (2,), False)[0]
            obs, reward, terminated, truncated, _ = env.step(act.item())
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(ep_return)
    env.close()
    return {
        "checkpoint": ckpt,
        "returns": returns,
        "mean_return": float(np.mean(returns)),
        "global_step_restored": int(restored["global_step"]),
        "coupled_twin_result": "greedy mean 408.5 (same recipe, BENCHES.md)",
    }


def main() -> None:
    from runner_common import bounded_runner_main

    bounded_runner_main(
        "logs/dv3_decoupled_learn_r5", _train, _evaluate, RECIPE,
        "dv3-decoupled",
    )


if __name__ == "__main__":
    main()
