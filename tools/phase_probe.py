"""Phase attribution for the DV3 duty-vs-e2e gap (round-3/4 hypothesis work).

Times cumulative variants of the honest e2e cycle on the current backend:

  V0 duty    : train_every policy steps (fixed device obs) + train on a
               pre-staged batch  — bench's duty cycle.
  V1 +put    : fresh host obs -> device_put every policy step.
  V2 +add    : + the real AsyncReplayBuffer.add per step (device storage,
               reusing the policy obs put).
  V3 +sample : + rb.sample + stage per cycle, train on the sampled batch —
               the separate-puts honest e2e cycle.
  V4 blob    : the same e2e cycle through the one-transfer blob transport
               (StepBlobCodec + reserve/add_direct) — bench's default
               device-buffer path; V4 vs V3 is the blob's chip receipt.
  V5 pull    : V4 plus the per-step action-index d2h pull the real main
               pays (dreamer_v3.py pulls env indices every step; V4 never
               did) — completes the honest cycle; V5 - V4 prices the pull.
  V6 pipeline: V5 with the ISSUE-4 latency-hiding pipeline on — the pull
               rides ActionPipeline (dispatch before the replay scatter,
               read after) and the sample is double-buffered
               (SamplePrefetcher) — V5 - V6 is the pipeline's recovery.

Adjacent differences attribute the gap to obs transfer, replay add, replay
sample/stage, the action pull, and the pipeline's recovery of it.  Every
variant syncs via a host scalar pull per cycle (readiness can lie on the
tunnel; a value fetch cannot — see BENCHES.md).

Usage: python tools/phase_probe.py [--tiny] [--cycles N] [--repeats N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--cycles", type=int, default=10)
    p.add_argument("--repeats", type=int, default=2)
    a = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.data import stage_batch

    print(f"backend: {jax.devices()}", file=sys.stderr)
    args, state0, opts, actions_dim, is_continuous, obs_space = bench._dv3_setup(
        a.tiny
    )
    T, B, n_envs = (
        args.per_rank_sequence_length,
        args.per_rank_batch_size,
        args.num_envs,
    )
    world_opt, actor_opt, critic_opt = opts
    train_step = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], actions_dim,
        is_continuous,
    )
    make_player, player_step = bench._dv3_player_fns(args, actions_dim, is_continuous)
    sample_batch, fixed_obs, mask = bench._dv3_synth_data(args, actions_dim, obs_space)
    rb, fake_env_obs, add_step = bench._dv3_replay_harness(args)

    def make_cycle(put: bool, add: bool, sample: bool):
        def one_cycle(state, player_state, key):
            player = make_player(state)
            for _ in range(args.train_every):
                if put:
                    obs_u8 = fake_env_obs()
                    dev_u8 = jnp.asarray(obs_u8)
                    obs = {"rgb": dev_u8}
                else:
                    obs = fixed_obs
                key, sk = jax.random.split(key)
                player_state, _ = player_step(player, player_state, obs, sk, None)
                if add:
                    add_step(obs_u8 if rb.prefers_host_adds else dev_u8)
            if sample:
                local = rb.sample(B, sequence_length=T, n_samples=1)
                batch = {k: v[0] for k, v in stage_batch(local).items()}
            else:
                batch = dict(sample_batch)
            key, tk = jax.random.split(key)
            state, metrics = train_step(state, batch, tk, jnp.float32(0.02))
            # sheeplint: disable=SL007 — deliberate per-cycle timing fence
            float(jax.device_get(metrics["Loss/reconstruction_loss"]))
            return state, player_state, key

        return one_cycle

    # V4/V5/V6: blob-transport e2e cycles via bench's OWN harness (the
    # probe must measure exactly the transport bench runs; the harness
    # applies the live roundtrip gate). Each variant gets its own replay
    # buffer so ring state and write heads stay independent.
    from sheeprl_tpu.parallel import Pipeline

    blob_step_fn = bench._dv3_blob_harness(args, actions_dim, is_continuous)

    def make_blob_cycle(pull: bool, pipelined: bool):
        rb_blob, _, _ = bench._dv3_replay_harness(args)
        pipe = Pipeline(enabled=pipelined)

        def blob_cycle(state, player_state, key):
            player = make_player(state)
            for _ in range(args.train_every):
                obs_u8 = fake_env_obs()
                key, sk = jax.random.split(key)
                player_state = blob_step_fn(
                    rb_blob, player, player_state, obs_u8, sk,
                    action=pipe.action if pipelined else None,
                    pull=pull and not pipelined,
                )
            local = pipe.sampler(rb_blob).sample(B, sequence_length=T, n_samples=1)
            batch = {k: v[0] for k, v in stage_batch(local).items()}
            key, tk = jax.random.split(key)
            state, metrics = train_step(state, batch, tk, jnp.float32(0.02))
            # sheeplint: disable=SL007 — deliberate per-cycle timing fence
            float(jax.device_get(metrics["Loss/reconstruction_loss"]))
            return state, player_state, key

        return blob_cycle

    variants = {
        "V0_duty": make_cycle(False, False, False),
        "V1_put": make_cycle(True, False, False),
        "V2_add": make_cycle(True, True, False),
        "V3_sample": make_cycle(True, True, True),
    }
    if blob_step_fn is not None:
        variants["V4_blob"] = make_blob_cycle(pull=False, pipelined=False)
        variants["V5_pull"] = make_blob_cycle(pull=True, pipelined=False)
        variants["V6_pipeline"] = make_blob_cycle(pull=True, pipelined=True)
    else:
        print("V4/V5/V6 skipped: backend failed the blob roundtrip gate",
              file=sys.stderr)
    # Interleaved schedule (V0 V1 V2 V3 V4 | V0 V1 V2 V3 V4 | ...; V4
    # only when the backend passes the blob gate) so tunnel-
    # latency drift over the run hits every variant equally (the sequential
    # layout confounded drift with the later variants). Per-variant state
    # evolves independently; train_step donates, so each gets a fresh copy.
    slots = {}
    for name, cyc in variants.items():
        state = jax.tree_util.tree_map(jnp.copy, state0)
        player_state = make_player(state).init_states(n_envs)
        key = jax.random.PRNGKey(1)
        slots[name] = [cyc, *cyc(state, player_state, key)]  # compile cycle
    times: dict = {name: [] for name in variants}
    total = a.cycles * a.repeats
    for i in range(total):
        for name in variants:
            cyc, state, player_state, key = slots[name]
            t0 = time.perf_counter()
            state, player_state, key = cyc(state, player_state, key)
            times[name].append(time.perf_counter() - t0)
            slots[name] = [cyc, state, player_state, key]
        if (i + 1) % a.cycles == 0:
            snap = {n: round(1e3 * sorted(ts)[len(ts) // 2], 1)
                    for n, ts in times.items()}
            print(f"after {i + 1} cycles, median ms/cycle: {snap}",
                  file=sys.stderr)
    out: dict = {"cycles": total, "interleaved": True}
    # medians are robust to tunnel-latency spikes
    best = {
        n: round(1e3 * sorted(ts)[len(ts) // 2], 1) for n, ts in times.items()
    }
    out["median_ms_per_cycle"] = best
    out["sps"] = {
        n: round(args.train_every * n_envs / (best[n] / 1e3), 1) for n in best
    }
    out["attribution_ms"] = {
        "obs_put": round(best["V1_put"] - best["V0_duty"], 1),
        "replay_add": round(best["V2_add"] - best["V1_put"], 1),
        "replay_sample": round(best["V3_sample"] - best["V2_add"], 1),
    }
    if "V4_blob" in best:
        out["attribution_ms"]["blob_vs_separate_puts"] = round(
            best["V4_blob"] - best["V3_sample"], 1
        )
        out["attribution_ms"]["action_pull"] = round(
            best["V5_pull"] - best["V4_blob"], 1
        )
        out["attribution_ms"]["pipeline_recovery"] = round(
            best["V5_pull"] - best["V6_pipeline"], 1
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
