"""SAC-AE pixel learning receipt (bonus beyond VERDICT r3 #4).

The DV3 swingup run covers the model-based pixel path; this covers the
OTHER pixel family — SAC-AE's autoencoder + detached-encoder actor
(reference sac_ae.py:50-130) — on the same dmc_cartpole_swingup pixels
(random ~27, shaped reward). Evaluation goes through the framework's own
`--eval_only` capability (fresh process path: checkpoint restore + greedy
episodes), and the per-episode returns are read back from the eval run's
TB events — so this receipt also exercises eval_only on a pixel checkpoint.

Usage: MUJOCO_GL=egl python tools/sac_ae_pixel_learning_run.py [--eval-only]
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ.setdefault("MUJOCO_GL", "egl")
# persistent compile cache: the recon jit's pathological XLA:CPU compile
# (~16 min at receipt scale — see tools/sac_ae_compile_probe.py) is paid
# once across bounded sessions, not once per resume
os.environ.setdefault("SHEEPRL_TPU_COMPILE_CACHE", "logs/jax_compile_cache")

import argparse
import glob
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu.utils.checkpoint import latest_checkpoint
from sheeprl_tpu.utils.registry import tasks

RECIPE = dict(
    env_id="dmc_cartpole_swingup",
    seed=5,
    total_steps=8192,  # cut from 16384: collection already hit ~100 by episode 3, and the 1-core box can't fit the full budget in-session
    learning_starts=1000,
    # batch 32 / 128-unit heads, NOT 64/256: the single-jit SAC-AE pixel
    # update (5 optimizers + conv enc/dec fwd+bwd) triggers an XLA:CPU
    # compile blowup at the larger sizes (>25 min observed; fine on TPU,
    # where this jit compiles in tens of seconds) — the receipt must fit
    # the 1-core box's session budget
    per_rank_batch_size=32,
    buffer_size=100000,
    actor_hidden_size=128,
    critic_hidden_size=128,
    dense_units=128,
    action_repeat=4,  # the reference's DMC SAC-AE convention
    # the round-4 fix for the XLA:CPU compile pathology: four per-model jits
    # instead of the fused update (parity unit-tested vs the fused path)
    split_update=True,
)


def _train(root: Path) -> None:
    argv = [
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        "--root_dir", str(root),
        "--run_name", "learn",
        "--cnn_keys", "rgb",
        "--checkpoint_every", "1024",
    ]
    for k, v in RECIPE.items():
        if isinstance(v, bool):
            argv += [f"--{k}" if v else f"--no_{k}"]
        else:
            argv += [f"--{k}", str(v)]
    resume = latest_checkpoint(str(root / "learn" / "checkpoints"))
    if resume is not None:
        print(f"[sac-ae-pixel] resuming from {resume}", flush=True)
        argv += ["--checkpoint_path", resume]
    tasks["sac_ae"](argv)


def _evaluate(root: Path, episodes: int = 10) -> dict:
    """Evaluate through the framework's own --eval_only path and read the
    per-episode returns back from the eval run's TB events."""
    from tensorboard.backend.event_processing.event_accumulator import (
        EventAccumulator,
    )

    ckpt = latest_checkpoint(str(root / "learn" / "checkpoints"))
    assert ckpt is not None, "no checkpoint to evaluate"
    eval_root = str(root) + "_eval"
    tasks["sac_ae"]([
        "--eval_only",
        "--checkpoint_path", ckpt,
        "--test_episodes", str(episodes),
        "--seed", "1000",
        "--root_dir", eval_root,
        "--run_name", "eval",
    ])
    events = glob.glob(os.path.join(eval_root, "**", "events.*"), recursive=True)
    assert events, f"no TB events under {eval_root}"
    returns: list[float] = []
    # newest first: a resumed run's re-evaluation must not pick a stale file
    for f in sorted(events, key=os.path.getmtime, reverse=True):
        ea = EventAccumulator(f)
        ea.Reload()
        if "Test/episode_reward" in ea.Tags()["scalars"]:
            returns = [e.value for e in ea.Scalars("Test/episode_reward")]
            break
    assert returns, "eval run logged no Test/episode_reward"
    return {
        "checkpoint": ckpt,
        "returns": [round(r, 1) for r in returns],
        "mean_return": float(np.mean(returns)),
        "random_baseline": "swingup random 18.5-35.7 over 3 episodes (measured 2026-08-02)",
    }


def main() -> None:
    from runner_common import run_bounded

    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="logs/sac_ae_pixel_r5")
    ap.add_argument("--eval-only", action="store_true")
    ap.add_argument("--budget-s", type=float, default=5400.0,
                    help="wall-clock training budget (VERDICT r4 #4); on "
                    "expiry the latest mid-run checkpoint is evaluated and "
                    "the receipt marked partial/resumable")
    ns = ap.parse_args()
    root = Path(ns.root)
    out = str(root) + ".json"
    if ns.eval_only:
        t0 = time.time()
        result = _evaluate(root)
        result["recipe"] = RECIPE
        result["train_plus_eval_seconds"] = round(time.time() - t0, 1)
        Path(out).write_text(json.dumps(result, indent=2))
        print(json.dumps({k: result[k] for k in ("mean_return", "returns")}))
        print(f"[sac-ae-pixel] receipt written to {out}", flush=True)
        return
    run_bounded(
        ns.budget_s,
        lambda: _train(root),
        lambda: _evaluate(root),
        out,
        {"recipe": RECIPE},
    )


if __name__ == "__main__":
    main()
