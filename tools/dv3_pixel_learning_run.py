"""First pixel-obs learning receipt (VERDICT r3 next-round #4).

Every prior return receipt is vector-obs; the north star is pixel IQM
parity, so this runner trains tiny DreamerV3 on **dmc_cartpole_balance
pixels** (64x64 rgb through the real DMC wrapper + conv encoder/decoder —
BASELINE config 4's shape at CartPole scale) long enough to beat the
random policy by a wide margin, then greedily evaluates the checkpoint.

Env choice (revised after the balance attempts): **swingup**, not balance.
Balance's reward landscape is flat for the actor at tiny scale (random
already collects ~350/1000 because the pole starts upright; the world
model converged, recon 2376->37, but the greedy policy drifted DEGENERATE
— 292 at 8192 steps, 168 at 20480, below random — while stochastic
collection stayed ~300: the trunc-normal mean wandered on a flat imagined
value surface). Swingup's cos-angle shaped reward gives the imagination
gradient signal everywhere and random scores only ~27, so ANY learning is
a wide-margin receipt. Mid-run checkpoints + auto-resume, same
budget-proofing as tools/dv1_learning_run.py.

Reference scope: /root/reference/sheeprl/algos/dreamer_v3/dreamer_v3.py:316-707
(pixel Dreamer training is the reference's flagship use).

Usage: MUJOCO_GL=egl python tools/dv3_pixel_learning_run.py [--eval-only]
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ.setdefault("SHEEPRL_TPU_COMPILE_CACHE", "logs/jax_compile_cache")  # children: skip axon registration
os.environ.setdefault("MUJOCO_GL", "egl")  # osmesa is broken in this image

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import sheeprl_tpu.algos  # noqa: F401 - fire registrations
from sheeprl_tpu import ops
from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3, build_models
from sheeprl_tpu.algos.dreamer_v3.args import DreamerV3Args
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_optimizers
from sheeprl_tpu.utils.checkpoint import latest_checkpoint, load_checkpoint
from sheeprl_tpu.utils.env import make_dict_env
from sheeprl_tpu.utils.registry import tasks

RECIPE = dict(
    env_id="dmc_cartpole_swingup",
    seed=5,
    total_steps=12288,
    learning_starts=1024,
    train_every=4,
    per_rank_batch_size=8,
    per_rank_sequence_length=16,
    buffer_size=100000,
    dense_units=128,
    hidden_size=128,
    recurrent_state_size=128,
    stochastic_size=16,
    discrete_size=16,
    cnn_channels_multiplier=8,
    mlp_layers=2,
    horizon=15,
    action_repeat=2,
    checkpoint_every=2048,
)


def _train(root: Path) -> None:
    argv = [
        "--num_devices", "1",
        "--num_envs", "1",
        "--sync_env",
        "--root_dir", str(root),
        "--run_name", "learn",
        "--cnn_keys", "rgb",
    ]
    for k, v in RECIPE.items():
        if isinstance(v, bool):
            argv += [f"--{k}" if v else f"--no_{k}"]
        else:
            argv += [f"--{k}", str(v)]
    resume = latest_checkpoint(str(root / "learn" / "checkpoints"))
    if resume is not None:
        print(f"[dv3-pixel] resuming from {resume}", flush=True)
        argv += ["--checkpoint_path", resume]
    tasks["dreamer_v3"](argv)


def _evaluate(root: Path, episodes: int = 5) -> dict:
    ckpt = latest_checkpoint(str(root / "learn" / "checkpoints"))
    assert ckpt is not None, "no checkpoint to evaluate"
    args = DreamerV3Args(env_id=RECIPE["env_id"], seed=5, num_envs=1)
    args.cnn_keys, args.mlp_keys = ["rgb"], []
    for k in (
        "dense_units", "hidden_size", "recurrent_state_size",
        "stochastic_size", "discrete_size", "cnn_channels_multiplier",
        "mlp_layers", "horizon", "action_repeat",
    ):
        setattr(args, k, RECIPE[k])
    env = make_dict_env(
        RECIPE["env_id"], 1000, rank=0, args=args, run_name="eval",
        vector_env_idx=0,
    )()
    act_dim = int(np.prod(env.action_space.shape))
    obs_space = {"rgb": env.observation_space["rgb"]}
    wm, actor, critic, tcritic = build_models(
        jax.random.PRNGKey(0), [act_dim], True, args, obs_space, ["rgb"], [],
    )
    wopt, aopt, copt = make_optimizers(args)
    restored = load_checkpoint(ckpt, {
        "world_model": wm, "actor": actor, "critic": critic,
        "target_critic": tcritic,
        "world_optimizer": wopt.init(wm), "actor_optimizer": aopt.init(actor),
        "critic_optimizer": copt.init(critic),
        "moments": ops.Moments.init(args.moments_decay, args.moment_max),
        "expl_decay_steps": 0, "global_step": 0, "batch_size": 0,
    })
    player = PlayerDV3(
        encoder=restored["world_model"].encoder,
        rssm=restored["world_model"].rssm,
        actor=restored["actor"],
        actions_dim=(act_dim,),
        stochastic_size=RECIPE["stochastic_size"],
        discrete_size=RECIPE["discrete_size"],
        recurrent_state_size=RECIPE["recurrent_state_size"],
        is_continuous=True,
    )
    from sheeprl_tpu.algos.dreamer_v3.utils import make_device_preprocess

    _prep = make_device_preprocess(["rgb"])
    step = jax.jit(
        lambda p, s, o, k: p.step(s, _prep(o), k, jnp.float32(0.0), is_training=False)
    )
    returns = []
    for episode in range(episodes):
        obs, _ = env.reset(seed=2000 + episode)
        state = player.init_states(1)
        key = jax.random.PRNGKey(episode)
        done, ep_return = False, 0.0
        while not done:
            dobs = {"rgb": jnp.asarray(obs["rgb"])[None]}
            key, sub = jax.random.split(key)
            state, actions = step(player, state, dobs, sub)
            obs, reward, terminated, truncated, _ = env.step(
                np.asarray(actions)[0]
            )
            ep_return += float(reward)
            done = terminated or truncated
        returns.append(round(ep_return, 1))
        print(f"[dv3-pixel] eval episode {episode}: {ep_return:.1f}", flush=True)
    env.close()
    return {
        "checkpoint": ckpt,
        "returns": returns,
        "mean_return": float(np.mean(returns)),
        "global_step_restored": int(restored["global_step"]),
        "random_baseline": "swingup random 18.5-35.7 over 3 episodes (measured 2026-08-02)",
    }


def main() -> None:
    from runner_common import bounded_runner_main

    bounded_runner_main(
        "logs/dv3_pixel_swingup_r5", _train, _evaluate, RECIPE, "dv3-pixel"
    )


if __name__ == "__main__":
    main()
