#!/usr/bin/env python
"""sheepcheck — jaxpr-level whole-program analysis over the CompilePlan
(ISSUE 7), with the compile-cost budget ledger CI gates on.

Usage:
    python tools/sheepcheck.py                      # all 13 mains, SC rules
    python tools/sheepcheck.py ppo sac_ae           # a subset
    python tools/sheepcheck.py --list-rules
    python tools/sheepcheck.py --update-budget      # refresh analysis/budget/
    python tools/sheepcheck.py --check-budget       # the CI drift gate
    python tools/sheepcheck.py --rules SC001,SC002 --json

For every selected algo main, the tool runs the main in SHAPE-CAPTURE mode
(`SHEEPRL_TPU_PLAN_MODE=capture`): setup proceeds on CPU at tiny avals
until `CompilePlan.start()`, which raises instead of compiling — so every
registered hot jit is captured with its exact example avals and NOTHING of
the algorithm executes. Each jit is then abstract-evaled to a ClosedJaxpr
(`jit.trace`) and analyzed (rules SC001-SC005, catalog:
sheeprl_tpu/analysis/jaxpr_check.py + howto/static_analysis.md), and its
compile-cost fingerprint (primitive histogram, op count, dtype set,
donation map, cost_analysis FLOPs/bytes) is compared against — or written
to — the committed ledger: one file per algo/variant under
`analysis/budget/` (the pre-split single-blob `analysis/budget.json` is
still readable for one release). The SPMD/collective half of the ledger
(`comms`/`edges` sections) belongs to tools/sheepshard.py and is preserved
untouched by `--update-budget` here.

Exit codes: 0 clean, 1 findings or budget drift, 2 capture/usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent

# Capture is CPU-by-design (the ledger must not depend on which accelerator
# happens to be attached) and the decoupled topologies need >=2 devices for
# their player/trainer sub-meshes — re-exec once with the virtual-device
# flag before anything imports jax (the same 8-device harness
# tests/conftest.py and CI pin).
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""  # skip the axon tunnel plugin
    os.execv(sys.executable, [sys.executable, *sys.argv])

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, str(_REPO))

from sheeprl_tpu.analysis import jaxpr_check as jc  # noqa: E402

DEFAULT_BUDGET = str(_REPO / "analysis" / "budget.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "algos", nargs="*",
        help="algo mains to capture (default: all registered)",
    )
    ap.add_argument("--rules", default=None, help="comma-separated SC rule ids")
    ap.add_argument(
        "--audit-bf16", action="store_true",
        help="also flag bf16->f32 upcasts (the ROADMAP-5c mixed-precision audit)",
    )
    ap.add_argument(
        "--gate-bf16", action="store_true",
        help="CI gate for declared-bf16 jits (ISSUE 9): capture the @bf16 "
        "variants, count each jit's bf16->f32 upcasts and FAIL when a jit "
        "whose budget entry declares bf16 compute exceeds its committed "
        "fp32-island count (or loses bfloat16 entirely). f32-only jits "
        "stay audit-only. Implies --audit-bf16; SC findings are reported, "
        "not gated, in this mode (the default run gates them)",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--budget", default=DEFAULT_BUDGET,
        help=f"budget ledger path (default {DEFAULT_BUDGET})",
    )
    ap.add_argument(
        "--update-budget", action="store_true",
        help="write the derived fingerprints to the ledger",
    )
    ap.add_argument(
        "--check-budget", action="store_true",
        help="fail on unexplained fingerprint drift vs the ledger (the CI gate)",
    )
    ap.add_argument(
        "--root-dir", default=None,
        help="where capture runs write their (throwaway) run dirs",
    )
    ap.add_argument("--verbose", action="store_true")
    ns = ap.parse_args(argv)

    if ns.list_rules:
        for rule in jc.SC_RULES.values():
            print(f"{rule.id} ({rule.name}) [{rule.severity}]")
            print(f"    {rule.summary}")
            print(f"    fix: {rule.autofix}")
        return 0

    rules = None
    if ns.rules:
        rules = {s.strip().upper() for s in ns.rules.split(",") if s.strip()}
        unknown = rules - set(jc.SC_RULES)
        if unknown:
            print(f"unknown rule ids: {sorted(unknown)}", file=sys.stderr)
            return 2

    import sheeprl_tpu.algos  # noqa: F401 — fire registrations
    from sheeprl_tpu.utils.registry import tasks

    # default sweep: every registered main at its capture argv, plus the
    # named variants (flag combinations that register extra jits — the
    # Anakin `--env_backend jax` rollout collectors and the ISSUE-9
    # `@bf16` mixed-precision traces). --gate-bf16 narrows the default
    # sweep to the bf16 variants (that's the gated population).
    if ns.gate_bf16:
        ns.audit_bf16 = True
        specs = ns.algos or sorted(
            s for s in jc.CAPTURE_VARIANTS if s.endswith("@bf16")
        )
    else:
        specs = ns.algos or [*sorted(tasks), *sorted(jc.CAPTURE_VARIANTS)]
    unknown = set(specs) - set(tasks) - set(jc.CAPTURE_VARIANTS)
    if unknown:
        print(f"unknown algos: {sorted(unknown)}", file=sys.stderr)
        return 2

    root = ns.root_dir or tempfile.mkdtemp(prefix="sheepcheck_")
    reports: list[jc.JitReport] = []
    capture_errors = 0
    for spec in specs:
        algo, extra_argv = jc.resolve_capture(spec)
        try:
            plan = jc.capture_plan(algo, root, extra_argv=extra_argv)
        except BaseException as err:  # CaptureComplete is consumed inside
            if isinstance(err, (KeyboardInterrupt, SystemExit)):
                raise
            print(f"{spec}: CAPTURE FAILED: {type(err).__name__}: {err}",
                  file=sys.stderr)
            capture_errors += 1
            continue
        algo_reports = jc.analyze_plan(
            spec, plan, rules=rules, audit_bf16=ns.audit_bf16
        )
        reports.extend(algo_reports)
        analyzed = [r for r in algo_reports if r.fingerprint is not None]
        print(
            f"{spec}: captured {len(algo_reports)} jits, "
            f"analyzed {len(analyzed)}, "
            f"{sum(len(r.failing) for r in algo_reports)} finding(s)",
            file=sys.stderr,
        )
        if ns.verbose:
            for r in algo_reports:
                if r.error:
                    print(f"  {r.name}: skipped ({r.error})", file=sys.stderr)

    failing = [f for r in reports for f in r.failing]
    suppressed = [f for r in reports for f in r.findings if f.suppressed]

    budget_failures: list[str] = []
    budget_notes: list[str] = []
    derived = jc.build_budget([r for r in reports if r.fingerprint is not None])

    gate_failures: list[str] = []
    if ns.gate_bf16:
        # findings under the gate are the DECLARED islands — report, don't
        # fail; the gate compares each declared-bf16 jit's upcast count to
        # its committed ledger entry
        failing = []
        if not jc.budget_exists(ns.budget):
            print(f"no ledger at {ns.budget} (run --update-budget first)",
                  file=sys.stderr)
            return 2
        committed = jc.load_budget(ns.budget).get("jits", {})
        for key, fp in sorted(derived["jits"].items()):
            entry = committed.get(key)
            if entry is None:
                gate_failures.append(f"{key}: not in the budget ledger")
                continue
            if not jc.declares_bf16(entry):
                continue  # f32-only jit: audit-only by design
            if not jc.declares_bf16(fp):
                gate_failures.append(
                    f"{key}: lost its declared bfloat16 compute"
                )
            elif int(fp.get("bf16_upcasts", 0)) > int(entry.get("bf16_upcasts", 0)):
                gate_failures.append(
                    f"{key}: bf16->f32 upcasts {entry.get('bf16_upcasts')} "
                    f"-> {fp.get('bf16_upcasts')} — undeclared upcast inside "
                    "a declared-bf16 jit"
                )
    if ns.update_budget:
        if ns.algos and jc.budget_exists(ns.budget):
            # partial refresh: replace only the captured specs' entries —
            # a subset run must not drop the other mains from the ledger
            ledger = jc.load_budget(ns.budget)
            prefixes = tuple(f"{s}/" for s in specs)
            merged = {
                k: v
                for k, v in ledger.get("jits", {}).items()
                if not k.startswith(prefixes)
            }
            merged.update(derived["jits"])
            derived = {**ledger, **derived, "jits": merged}
        jc.save_budget(derived, ns.budget)
        print(f"wrote {len(derived['jits'])} fingerprints to {ns.budget}",
              file=sys.stderr)
    elif ns.check_budget:
        if not jc.budget_exists(ns.budget):
            print(f"no ledger at {ns.budget} (run --update-budget first)",
                  file=sys.stderr)
            return 2
        ledger = jc.load_budget(ns.budget)
        if ns.algos:
            # partial capture: gate only the captured algos' entries
            prefixes = tuple(f"{s}/" for s in specs)
            ledger = {
                **ledger,
                "jits": {
                    k: v for k, v in ledger.get("jits", {}).items()
                    if k.startswith(prefixes)
                },
            }
        budget_failures, budget_notes = jc.check_budget(ledger, derived)

    if ns.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in failing],
            "suppressed": [f.as_dict() for f in suppressed],
            "budget_failures": budget_failures,
            "budget_notes": budget_notes,
            "bf16_gate_failures": gate_failures,
            "bf16_upcasts": (
                {
                    k: fp.get("bf16_upcasts")
                    for k, fp in sorted(derived["jits"].items())
                }
                if ns.gate_bf16
                else None
            ),
            "capture_errors": capture_errors,
            "jits": sorted(derived["jits"]),
        }, indent=2))
    else:
        for f in failing:
            print(f.format())
        if ns.verbose:
            for f in suppressed:
                print(f.format())
        for note in budget_notes:
            print(f"budget note: {note}", file=sys.stderr)
        for failure in budget_failures:
            print(f"BUDGET DRIFT: {failure}")
        for failure in gate_failures:
            print(f"BF16 GATE: {failure}")

    if capture_errors:
        return 2
    if failing or budget_failures or gate_failures:
        n = len(failing)
        print(
            f"sheepcheck: {n} finding(s), {len(suppressed)} suppressed, "
            f"{len(budget_failures)} budget drift(s), "
            f"{len(gate_failures)} bf16 gate failure(s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"sheepcheck: clean ({len(derived['jits'])} jits fingerprinted, "
        f"{len(suppressed)} suppressed finding(s))",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
