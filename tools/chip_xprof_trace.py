"""XProf trace of the DV3 duty cycle on chip (VERDICT r3 next-round #2).

Runs a handful of flagship-scale duty cycles under `jax.profiler.trace` so
the trace names the next bottleneck slice (GRU scan vs conv vs host gaps)
— the evidence the duty-vs-e2e gap analysis needs beyond end-to-end
timings. Writes to logs/xprof_r4/ (open with xprof/tensorboard).

Usage: python tools/chip_xprof_trace.py [--tiny] [--outdir logs/xprof_r4]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--outdir", default="logs/xprof_r4")
    ap.add_argument("--cycles", type=int, default=4)
    ns = ap.parse_args()

    import jax

    import bench

    args, state, opts, actions_dim, is_continuous, _ = bench._dv3_setup(ns.tiny)
    run_cycles = bench._dv3_duty_closure(
        args, state, opts, actions_dim, is_continuous
    )
    # one untraced segment first: compile + cache warm so the trace holds
    # steady-state cycles, not compilation
    run_cycles(1)
    outdir = str(Path(ns.outdir))
    t0 = time.perf_counter()
    with jax.profiler.trace(outdir):
        dt = run_cycles(ns.cycles)
    sps = ns.cycles * args.train_every * args.num_envs / dt
    print(
        f"traced {ns.cycles} duty cycles in {dt:.2f}s "
        f"({sps:.1f} env-steps/sec) -> {outdir} "
        f"(wall incl. trace overhead {time.perf_counter() - t0:.2f}s)"
    )


if __name__ == "__main__":
    main()
