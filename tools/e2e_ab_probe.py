"""Interleaved A/B probe for the replay-transfer packing (round 3).

The tunneled TPU backend's throughput drifts on a scale of minutes, so
sequential before/after runs confound the code change with tunnel weather.
This probe alternates the two variants ABAB... inside ONE process against
the same model state, so each pair of adjacent trials sees the same tunnel:

  legacy   : pre-normalized f32 obs put per policy step + per-key transfers
             in the replay add (the round-2 path, emulated via `_store_add`);
  packed   : raw uint8 obs put normalized inside the jit, the same device
             array reused by the add, and one transfer per dtype group in
             the add (the round-3 path: AsyncReplayBuffer._store_add_packed);
  pipelined: packed plus the ISSUE-4 SamplePrefetcher in its off-policy
             staleness mode (SHEEPRL_TPU_PIPELINE_STALENESS-style): the
             per-cycle replay sample's index put + gather are dispatched
             during the PREVIOUS train step, so the sample pull leaves the
             critical path entirely (howto/pipelining.md).

Usage: python tools/e2e_ab_probe.py [--trials 9] [--cycles 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--trials", type=int, default=9)
    p.add_argument("--cycles", type=int, default=5)
    p.add_argument("--tiny", action="store_true")
    a = p.parse_args()

    import jax
    import jax.numpy as jnp

    import bench
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.data import AsyncReplayBuffer, stage_batch

    print(f"backend: {jax.devices()}", file=sys.stderr)
    args, state, opts, actions_dim, is_continuous, _ = bench._dv3_setup(a.tiny)
    T, B = args.per_rank_sequence_length, args.per_rank_batch_size
    n_envs = args.num_envs
    world_opt, actor_opt, critic_opt = opts
    train_step = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], actions_dim, is_continuous
    )
    make_player, player_step = bench._dv3_player_fns(args, actions_dim, is_continuous)
    # round-2 player contract: obs arrive pre-normalized, no in-jit prep
    legacy_player_step = jax.jit(
        lambda p, s, o, k, mask: p.step(
            s, o, k, jnp.float32(0.0), is_training=True, mask=mask
        )
    )

    rng = np.random.default_rng(0)

    def fake_env_obs():
        return rng.integers(0, 255, (n_envs, 64, 64, 3), dtype=np.uint8)

    def host_step_data(obs_u8):
        return {
            "rgb": obs_u8[None],
            "actions": np.eye(6, dtype=np.float32)[rng.integers(0, 6, (n_envs,))][None],
            "rewards": rng.normal(size=(1, n_envs, 1)).astype(np.float32),
            "dones": np.zeros((1, n_envs, 1), np.float32),
            "is_first": np.zeros((1, n_envs, 1), np.float32),
        }

    from functools import partial

    from sheeprl_tpu.utils.jit import donating_jit

    @partial(donating_jit, donate_argnums=0)
    def _legacy_store_add(store, data, rows, cols):
        # the round-2 scatter (removed from buffers.py when packing landed)
        return {
            k: store[k].at[rows, cols[None, :]].set(data[k].astype(store[k].dtype))
            for k in store
        }

    def legacy_add(rb, data):
        """The round-2 device add: one host->device transfer PER KEY plus
        rows/cols index puts (buffers.py pre-packing)."""
        cols = np.arange(rb._n_envs, dtype=np.int64)
        starts = rb._upos[cols]
        data_len = 1
        rows = (starts[None, :] + np.arange(data_len)[:, None]) % rb._buffer_size
        rb._store = _legacy_store_add(
            rb._store,
            {k: jnp.asarray(v) for k, v in data.items()},
            jnp.asarray(rows),
            jnp.asarray(cols),
        )
        rb._ufull[cols] |= starts + data_len >= rb._buffer_size
        rb._upos[cols] = (starts + data_len) % rb._buffer_size

    from sheeprl_tpu.parallel import SamplePrefetcher

    def make_variant(packed: bool, pipelined: bool = False):
        rb = AsyncReplayBuffer(
            max(4 * T, 64), n_envs, storage="device", sequential=True,
            obs_keys=("rgb",), seed=0,
        )
        for _ in range(2 * T + 8):
            rb.add(host_step_data(fake_env_obs()))
        # the staleness relaxation is what takes the sample pull off the
        # critical path in a write-every-cycle loop (strict mode would
        # discard every prefetch); train_every adds/cycle -> one cycle of
        # staleness, the documented off-policy trade (howto/pipelining.md)
        sampler = (
            SamplePrefetcher(rb, enabled=True, max_staleness=args.train_every + 1)
            if pipelined
            else rb
        )
        st = jax.tree_util.tree_map(jnp.copy, state)
        ps = make_player(st).init_states(n_envs)
        key = jax.random.PRNGKey(1)
        box = {"state": st, "ps": ps, "key": key}

        def one_cycle():
            player = make_player(box["state"])
            for _ in range(args.train_every):
                obs_u8 = fake_env_obs()
                box["key"], sk = jax.random.split(box["key"])
                if packed:
                    dev_u8 = jnp.asarray(obs_u8)
                    box["ps"], _ = player_step(
                        player, box["ps"], {"rgb": dev_u8}, sk, None
                    )
                    step = host_step_data(obs_u8)
                    step["rgb"] = dev_u8[None]
                    rb.add(step)
                else:
                    # faithful round-2 path: HOST-side normalize, f32 put
                    # (4x the bytes), then per-key transfers in the add
                    dev_obs = {
                        "rgb": jnp.asarray(
                            # sheeplint: disable=SL007 — host numpy
                            # normalize IS the legacy arm under measurement
                            np.asarray(obs_u8, dtype=np.float32) / 255.0
                        )
                    }
                    box["ps"], _ = legacy_player_step(player, box["ps"], dev_obs, sk, None)
                    legacy_add(rb, host_step_data(obs_u8))
            local = sampler.sample(B, sequence_length=T, n_samples=1)
            staged = stage_batch(local)
            sample = {k: v[0] for k, v in staged.items()}
            box["key"], tk = jax.random.split(box["key"])
            box["state"], metrics = train_step(
                box["state"], sample, tk, jnp.float32(0.02)
            )
            # sheeplint: disable=SL007 — deliberate per-cycle timing fence
            float(jax.device_get(metrics["Loss/reconstruction_loss"]))

        return one_cycle

    variants = {
        "legacy": make_variant(False),
        "packed": make_variant(True),
        "pipelined": make_variant(True, pipelined=True),
    }
    for name, cyc in variants.items():  # compile all before timing
        cyc()
        print(f"compiled {name}", file=sys.stderr)

    results: dict[str, list[float]] = {name: [] for name in variants}
    order = list(variants)
    for trial in range(a.trials):
        name = order[trial % len(order)]
        t0 = time.perf_counter()
        for _ in range(a.cycles):
            variants[name]()
        dt = time.perf_counter() - t0
        sps = a.cycles * args.train_every * n_envs / dt
        results[name].append(round(sps, 1))
        print(f"trial {trial} {name}: {sps:.1f} sps", file=sys.stderr)

    med = {k: float(np.median(v)) if v else 0.0 for k, v in results.items()}
    print(
        json.dumps(
            {
                "runs": results,
                "median": med,
                "packed_over_legacy": round(med["packed"] / med["legacy"], 3)
                if med["legacy"]
                else None,
                "pipelined_over_packed": round(
                    med["pipelined"] / med["packed"], 3
                )
                if med["packed"] and med.get("pipelined")
                else None,
            }
        )
    )


if __name__ == "__main__":
    main()
