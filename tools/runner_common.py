"""Wall-clock-bounded learning-runner harness (VERDICT r4 #4).

Round 4 left a receipt runner alive 2h18m past the end-of-round snapshot at
10.7 GB RSS on the 1-core box, contending with the next session's work and
producing neither a receipt nor a checkpoint. Every `tools/*_learning_run.py`
now runs its training phase through `run_bounded`, which guarantees the
session ends in one of exactly three states by a known deadline:

1. **receipt** — training finished inside the budget; eval ran; receipt JSON
   written.
2. **partial_receipt_resumable** — the soft deadline (SIGALRM or SIGTERM,
   so the session-end sweep composes with this) interrupted training; the
   latest mid-run checkpoint was evaluated and the receipt says so. A later
   session resumes from that checkpoint.
3. **stub_hard_deadline** — the process was stuck in uninterruptible native
   code (e.g. the XLA:CPU conv-gradient compile pathology, ~16 min for the
   SAC-AE recon jit) past the hard deadline; a daemon timer writes a stub
   sidecar and hard-exits so no orphan survives the session.

The soft handler uses SIGALRM/SIGTERM -> Python exception, which only fires
between bytecodes — a long native call defers it, hence the separate hard
timer with a grace window sized to one pathological compile.

Runners also get the persistent compilation cache
(SHEEPRL_TPU_COMPILE_CACHE -> jax_compilation_cache_dir via
parallel/mesh.py:distributed_setup) so a pathological compile is paid once
across bounded sessions, not once per resume.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time


class BudgetExpired(Exception):
    """Soft deadline (or SIGTERM from the session-end sweep) hit."""


def enable_compile_cache(path: str = "logs/jax_compile_cache") -> None:
    """Arm the persistent compilation cache for this process (read by
    distributed_setup before any jit compiles). Call before importing the
    algo mains' jits."""
    os.environ.setdefault("SHEEPRL_TPU_COMPILE_CACHE", path)


def bounded_runner_main(
    default_root: str,
    train,
    evaluate,
    recipe: dict,
    tag: str,
    default_budget_s: float = 5400.0,
) -> None:
    """Shared CLI entry for the learning-receipt runners: --root / --eval-only
    / --budget-s, training bounded by `run_bounded`, receipt at <root>.json.
    `train(root)` must auto-resume from the latest checkpoint under root;
    `evaluate(root)` must read the latest checkpoint (see run_bounded)."""
    import argparse
    from pathlib import Path

    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=default_root)
    ap.add_argument("--eval-only", action="store_true")
    ap.add_argument(
        "--budget-s", type=float, default=default_budget_s,
        help="wall-clock training budget (VERDICT r4 #4); on expiry the "
        "latest mid-run checkpoint is evaluated and the receipt marked "
        "partial/resumable",
    )
    ap.add_argument(
        "--eval-budget-s", type=float, default=1800.0,
        help="wall-clock bound on the evaluation phase (both --eval-only "
        "and the post-training eval share it; ADVICE r5: a bare eval "
        "session must not outlive the session unbounded either)",
    )
    ns = ap.parse_args()
    root = Path(ns.root)
    out = str(root) + ".json"
    if ns.eval_only:
        result = run_eval_bounded(
            lambda: evaluate(root), out, {"recipe": recipe},
            eval_budget_s=ns.eval_budget_s,
        )
        if "mean_return" in result:
            print(json.dumps(
                {k: result[k] for k in ("mean_return", "returns") if k in result}
            ))
        print(f"[{tag}] receipt written to {out}", flush=True)
        return
    run_bounded(
        ns.budget_s,
        lambda: train(root),
        lambda: evaluate(root),
        out,
        {"recipe": recipe},
        eval_budget_s=ns.eval_budget_s,
    )


def _write_receipt(receipt_path: str, payload: dict, suffix: str = "") -> None:
    path = receipt_path + suffix
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".tmp", "w") as fh:
        json.dump(payload, fh, indent=2)
    os.replace(path + ".tmp", path)


def run_eval_bounded(
    eval_fn,
    receipt_path: str,
    meta: dict,
    *,
    eval_budget_s: float = 1800.0,
    hard_grace_s: float = 1500.0,
) -> dict:
    """`--eval-only` twin of run_bounded's eval half (ADVICE r5): the same
    SIGALRM soft bound plus a daemon hard timer, so a bare evaluation
    session stuck in a pathological XLA compile ends by a known deadline
    with a stub receipt instead of surviving as an orphan."""
    t0 = time.time()

    def _hard_exit() -> None:
        _write_receipt(
            receipt_path,
            {
                **meta,
                "status": "stub_hard_deadline",
                "note": "eval stuck in native code past the hard deadline",
                "eval_budget_s": eval_budget_s,
                "elapsed_s": round(time.time() - t0, 1),
            },
            suffix=".stub",
        )
        print(f"[runner] HARD deadline; stub written to {receipt_path}.stub",
              flush=True)
        os._exit(3)

    hard_timer = threading.Timer(eval_budget_s + hard_grace_s, _hard_exit)
    hard_timer.daemon = True
    hard_timer.start()

    def _raise(_sig, _frm):
        raise BudgetExpired

    signal.signal(signal.SIGALRM, _raise)
    signal.signal(signal.SIGTERM, _raise)  # session-end sweep -> graceful
    signal.alarm(max(1, int(eval_budget_s)))

    result = {**meta, "eval_budget_s": eval_budget_s}
    try:
        result.update(eval_fn())
        result["status"] = "eval_receipt"
    except BudgetExpired:
        result["status"] = "stub_eval_timeout"
    except Exception as exc:
        result["status"] = "stub_no_eval"
        result["eval_error"] = repr(exc)
    finally:
        signal.alarm(0)
        hard_timer.cancel()
    result["elapsed_s"] = round(time.time() - t0, 1)
    result["train_plus_eval_seconds"] = result["elapsed_s"]  # legacy key
    _write_receipt(receipt_path, result)
    print(json.dumps({k: result.get(k) for k in ("status", "mean_return")}),
          flush=True)
    return result


def run_bounded(
    budget_s: float,
    train_fn,
    eval_fn,
    receipt_path: str,
    meta: dict,
    *,
    eval_budget_s: float = 1800.0,
    hard_grace_s: float = 1500.0,
) -> dict:
    """Run `train_fn` under a wall-clock budget, then `eval_fn`; always leave
    a receipt (or stub) at `receipt_path` and return the receipt dict.

    `eval_fn` must evaluate the LATEST CHECKPOINT (not in-memory state): the
    partial path relies on mid-run checkpoints for resumability, so a run
    killed at the soft deadline is evaluated exactly as a resumed session
    would see it.
    """
    t0 = time.time()

    def _write(payload: dict, suffix: str = "") -> None:
        _write_receipt(receipt_path, payload, suffix)

    def _hard_exit() -> None:
        _write(
            {
                **meta,
                "status": "stub_hard_deadline",
                "note": (
                    "stuck in native code past the hard deadline (likely a "
                    "pathological XLA compile); any mid-run checkpoint is "
                    "resumable by the next session"
                ),
                "budget_s": budget_s,
                "elapsed_s": round(time.time() - t0, 1),
            },
            suffix=".stub",
        )
        print(f"[runner] HARD deadline; stub written to {receipt_path}.stub",
              flush=True)
        os._exit(3)

    hard_timer = threading.Timer(budget_s + hard_grace_s, _hard_exit)
    hard_timer.daemon = True
    hard_timer.start()

    def _raise(_sig, _frm):
        raise BudgetExpired

    signal.signal(signal.SIGALRM, _raise)
    signal.signal(signal.SIGTERM, _raise)  # session-end sweep -> graceful
    signal.alarm(max(1, int(budget_s)))

    completed = True
    train_error = None
    try:
        train_fn()
    except BudgetExpired:
        completed = False
        print(f"[runner] soft deadline after {time.time() - t0:.0f}s; "
              "evaluating latest checkpoint", flush=True)
    except Exception as exc:  # training crash still lands a stub
        completed = False
        train_error = repr(exc)
    finally:
        signal.alarm(0)

    # fresh bound for eval: the hard timer above may be nearly spent
    hard_timer.cancel()
    hard_timer = threading.Timer(eval_budget_s + hard_grace_s, _hard_exit)
    hard_timer.daemon = True
    hard_timer.start()
    signal.alarm(int(eval_budget_s))

    result = {
        **meta,
        "completed_training": completed,
        "budget_s": budget_s,
    }
    if train_error:
        result["train_error"] = train_error
    try:
        result.update(eval_fn())
        result["status"] = "receipt" if completed else "partial_receipt_resumable"
    except BudgetExpired:
        result["status"] = "stub_eval_timeout"
    except Exception as exc:
        # e.g. no checkpoint yet: resumable is still the honest outcome
        result["status"] = "stub_no_eval"
        result["eval_error"] = repr(exc)
    finally:
        signal.alarm(0)
        hard_timer.cancel()
    result["elapsed_s"] = round(time.time() - t0, 1)
    _write(result)
    print(json.dumps({k: result.get(k) for k in
                      ("status", "mean_return", "elapsed_s")}), flush=True)
    print(f"[runner] receipt written to {receipt_path}", flush=True)
    return result
