#!/usr/bin/env python
"""Offline run-health report from a `telemetry.jsonl` event log.

    python tools/telemetry_report.py <log_dir-or-telemetry.jsonl>
    python tools/telemetry_report.py --selftest

Reads the structured event log the telemetry subsystem writes
(sheeprl_tpu/telemetry/, schema in howto/observability.md) and prints, for a
finished OR crashed run:

  - run identity + lifecycle (start/end/crash, checkpoints committed,
    profile windows captured);
  - a phase-breakdown table: total seconds and share of accounted time per
    phase (`rollout`, `buffer/sample`, `train/dispatch`, ...), from the
    `Time/<phase>_seconds` series in the `log` events;
  - throughput (mean / last step-per-second) and XLA compile accounting
    (total compiles, compile seconds, recompiles AFTER the first logging
    interval — the retrace-storm signal);
  - health findings: `health.nan` events with the offending metric keys,
    peak device memory;
  - a comms-budget summary (ISSUE 8) sourced from the COMMITTED sheepshard
    ledger (`analysis/budget/`, `comms`/`edges` sections): per mesh-bearing
    jit of the run's algo, its collective histogram, hot-loop collectives,
    and estimated bytes-on-the-wire per dispatch, plus the declared data
    edges' contract status — what the mesh costs per step, next to what the
    run measured;
  - a memory-budget summary (ISSUE 10) sourced from the committed sheepmem
    ledger (`memory` section): per jit of the run's algo, its static
    peak/temp/argument bytes, realized-vs-declared donation aliases,
    embedded-constant bytes and the largest scan-carried buffer — compared
    against the run's `Memory/*` gauges when present;
  - a sheepopt decisions summary (ISSUE 11) sourced from the unified
    decision cache (`decisions.json` next to the compile cache,
    compile/decisions.py): per measured knob decision (scan_unroll, remat,
    batch_chunk, ...) the candidates tried, the winner, its bit-exactness
    receipt status and bytes/seconds deltas vs the baseline.

Pure stdlib + the repo's telemetry package (no jax import), so it runs
anywhere the JSONL can be copied to. `--selftest` synthesizes a small run
via the real Telemetry class, reports on it, and asserts the critical
fields — the CI smoke that the writer and this reader stay in sync.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def load_events(path: str) -> list[dict]:
    """Parse a telemetry.jsonl (or a log_dir containing one). Tolerates a
    truncated final line (crash mid-write). Non-learner processes write
    role shards (`telemetry.<role>.jsonl`, sheepscope ISSUE 17) — a dir
    holding only those (e.g. a serve run) falls back to the first shard;
    merging ALL shards onto one timeline is tools/sheeptrace.py's job."""
    if os.path.isdir(path):
        candidate = os.path.join(path, "telemetry.jsonl")
        if not os.path.exists(candidate):
            import glob as _glob

            shards = sorted(_glob.glob(os.path.join(path, "telemetry.*.jsonl")))
            if shards:
                candidate = shards[0]
        path = candidate
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found — did the run write telemetry? "
            "(rank 0 only; SHEEPRL_TPU_TELEMETRY=0 disables)"
        )
    events = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                # a crash can truncate the last line; everything before it
                # is still a valid record of the run
                break
    return events


def summarize(events: list[dict]) -> dict:
    """Aggregate an event list into the report's data model."""
    summary: dict = {
        "start": None,
        "end": None,
        "crash": None,
        "checkpoints": [],
        "profile_windows": 0,
        "nan_events": [],
        "log_events": 0,
        "first_ts": None,
        "last_ts": None,
        "last_step": None,
        "phase_seconds": {},
        "sps_series": [],
        "total_compiles": 0.0,
        "total_compile_seconds": 0.0,
        "late_recompiles": 0.0,
        "late_compile_seconds": 0.0,
        "peak_memory_bytes": 0.0,
        "gauges_last": {},
        # ISSUE 5 compile-latency subsystem (compile/plan.py)
        "compile_events": [],       # per-executable `compile` events
        "partition_events": [],     # `compile.partition` heuristic decisions
        "first_update": None,       # the `first_update` stamp event
        "compile_gauges": {},       # last Compile/* gauge values
        "anakin_gauges": {},        # last Anakin/* gauge values (jax envs)
        # ISSUE 12 resilience subsystem (resilience/)
        "fault_injected": [],       # fault.injected events (site/step)
        "fault_recovered": [],      # fault.recovered events (site/action)
        "preempt": None,            # the preempt lifecycle event (rc 75 exit)
        "preempt_signal": None,     # when the grace window opened
        "resume": None,             # the resume-resolution event
        "checkpoint_corrupt": [],   # skipped/failed checkpoint candidates
        "checkpoint_errors": [],    # retried checkpoint writes
        "fault_gauges": {},         # last Fault/* gauge values
        # ISSUE 14 flock subsystem (flock/)
        "flock_started": None,      # flock.started (address/mode)
        "flock_events": [],         # flock.* membership lifecycle events
        "flock_gauges": {},         # last Flock/* gauge values
        "flock_staleness": {},      # actor_id -> list of staleness samples
        # ISSUE 15 serving subsystem (serve/)
        "serve_start": None,        # serve.start (address/algo/rungs)
        "serve_stop": None,         # serve.stop (completed/final version)
        "serve_reloads": [],        # serve.reload timeline (ok/version/seconds)
        "serve_ladder": [],         # serve.ladder rung-sizing decisions
        "serve_gauges": {},         # last Serve/* gauge values
        # ISSUE 16 distributed fault tolerance (sheepchaos)
        "serve_events": [],         # serve.* hardening events (conn_error,
                                    # draining/drained, client_close_error)
        # ISSUE 18 sheepsync runtime thread sanitizer
        "sync_events": [],          # sync.* events (order_violation,
                                    # sanitizer_start/stop)
        "sync_gauges": {},          # last Sync/* gauge values
    }
    for ev in events:
        ts = ev.get("ts")
        if ts is not None:
            summary["first_ts"] = ts if summary["first_ts"] is None else summary["first_ts"]
            summary["last_ts"] = ts
        kind = ev.get("event")
        if kind == "start":
            summary["start"] = ev
        elif kind == "end":
            summary["end"] = ev
        elif kind == "crash":
            summary["crash"] = ev
        elif kind == "checkpoint":
            summary["checkpoints"].append(ev.get("path"))
        elif kind == "profile.start":
            summary["profile_windows"] += 1
        elif kind == "health.nan":
            summary["nan_events"].append(ev)
        elif kind == "compile":
            summary["compile_events"].append(ev)
        elif kind == "compile.partition":
            summary["partition_events"].append(ev)
        elif kind == "first_update":
            summary["first_update"] = ev
        elif kind == "fault.injected":
            summary["fault_injected"].append(ev)
        elif kind == "fault.recovered":
            summary["fault_recovered"].append(ev)
        elif kind == "preempt":
            summary["preempt"] = ev
        elif kind == "preempt.signal":
            summary["preempt_signal"] = ev
        elif kind == "resume":
            summary["resume"] = ev
        elif kind in ("checkpoint.corrupt", "checkpoint.fallback"):
            summary["checkpoint_corrupt"].append(ev)
        elif kind == "checkpoint.error":
            summary["checkpoint_errors"].append(ev)
        elif kind == "flock.started":
            summary["flock_started"] = ev
        elif isinstance(kind, str) and kind.startswith("flock."):
            summary["flock_events"].append(ev)
        elif kind == "serve.start":
            summary["serve_start"] = ev
        elif kind == "serve.stop":
            summary["serve_stop"] = ev
        elif kind == "serve.reload":
            summary["serve_reloads"].append(ev)
        elif kind == "serve.ladder":
            summary["serve_ladder"].append(ev)
        elif isinstance(kind, str) and kind.startswith("serve."):
            summary["serve_events"].append(ev)
        elif isinstance(kind, str) and kind.startswith("sync."):
            summary["sync_events"].append(ev)
        elif kind == "log":
            summary["log_events"] += 1
            if ev.get("step") is not None:
                summary["last_step"] = ev["step"]
            metrics = ev.get("metrics", {})
            for k, v in metrics.items():
                if not isinstance(v, (int, float)):
                    continue
                if k.startswith("Time/") and k.endswith("_seconds"):
                    phase = k[len("Time/"):-len("_seconds")]
                    summary["phase_seconds"][phase] = (
                        summary["phase_seconds"].get(phase, 0.0) + v
                    )
                elif k == "Time/step_per_second":
                    summary["sps_series"].append(v)
                elif k == "XLA/total_compiles":
                    summary["total_compiles"] = v
                elif k == "XLA/total_compile_seconds":
                    summary["total_compile_seconds"] = v
                elif k == "XLA/recompiles" and summary["log_events"] > 1:
                    summary["late_recompiles"] += v
                elif k == "XLA/compile_seconds" and summary["log_events"] > 1:
                    summary["late_compile_seconds"] += v
                elif k.startswith("Memory/") and k.endswith("bytes_in_use"):
                    summary["peak_memory_bytes"] = max(summary["peak_memory_bytes"], v)
                elif k.startswith("Decoupled/"):
                    summary["gauges_last"][k] = v
                elif k.startswith("Compile/"):
                    summary["compile_gauges"][k] = v
                elif k.startswith("Anakin/"):
                    summary["anakin_gauges"][k] = v
                elif k.startswith("Fault/"):
                    summary["fault_gauges"][k] = v
                elif k.startswith("Serve/"):
                    summary["serve_gauges"][k] = v
                elif k.startswith("Sync/"):
                    summary["sync_gauges"][k] = v
                elif k.startswith("Flock/"):
                    summary["flock_gauges"][k] = v
                    parts = k.split("/")
                    if len(parts) == 3 and parts[2] == "staleness_s":
                        summary["flock_staleness"].setdefault(
                            parts[1], []
                        ).append(v)
    # the "end" event carries phase time accumulated after the last interval
    if summary["end"]:
        for phase, secs in (summary["end"].get("phases") or {}).items():
            if isinstance(secs, (int, float)):
                summary["phase_seconds"][phase] = (
                    summary["phase_seconds"].get(phase, 0.0) + secs
                )
    return summary


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_ledger_sections(
    sections: tuple[str, ...], path: str | None = None
) -> list[dict]:
    """The requested sections of the committed `analysis/budget/` ledger —
    per-algo dir layout only (the legacy single-blob fallback is gone,
    ISSUE 11). Stdlib-only (this report must run anywhere the JSONL can
    be copied to); missing ledger -> empty dicts."""
    base = path or os.path.join(_REPO, "analysis", "budget")
    out: list[dict] = [dict() for _ in sections]
    try:
        if os.path.isdir(base):
            for name in sorted(os.listdir(base)):
                if not name.endswith(".json") or name == "_meta.json":
                    continue
                with open(os.path.join(base, name), encoding="utf-8") as fh:
                    blob = json.load(fh)
                for i, section in enumerate(sections):
                    out[i].update(blob.get(section, {}))
    except (OSError, json.JSONDecodeError):
        return [dict() for _ in sections]
    return out


def load_comms_ledger(path: str | None = None) -> tuple[dict, dict]:
    """`(comms, edges)` from the committed sheepshard ledger."""
    comms, edges = load_ledger_sections(("comms", "edges"), path)
    return comms, edges


def load_memory_ledger(path: str | None = None) -> dict:
    """The committed sheepmem `memory` section (ISSUE 10)."""
    (memory,) = load_ledger_sections(("memory",), path)
    return memory


def load_concurrency_ledger(path: str | None = None) -> dict:
    """The committed sheepsync `concurrency` section (ISSUE 18)."""
    (concurrency,) = load_ledger_sections(("concurrency",), path)
    return concurrency


def load_decision_cache(path: str | None = None) -> dict:
    """The unified sheepopt decision cache (`decisions.json` next to the
    compile cache, compile/decisions.py) — stdlib-only, empty dict when
    absent. Resolution mirrors the writer: explicit path, then the
    compile-cache env vars, then the tempdir default."""
    if path is None:
        base = (
            os.environ.get("SHEEPRL_TPU_COMPILE_CACHE")
            or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        )
        if not base:
            import tempfile

            uid = getattr(os, "getuid", lambda: "u")()
            base = os.path.join(
                tempfile.gettempdir(), f"sheeprl_tpu_xla_cache_{uid}"
            )
        path = os.path.join(base, "decisions.json")
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return {}


def render_sheepopt_decisions(cache: dict) -> str:
    """The sheepopt decisions section (ISSUE 11): per measured decision in
    the unified cache, the knob family, candidates tried, winner, receipt
    status and the winner's bytes/seconds deltas vs the baseline."""
    lines = ["== sheepopt decisions (unified decision cache) =="]
    ladders = {k: v for k, v in cache.items() if isinstance(v, dict) and "candidates" in v}
    probes = {k: v for k, v in cache.items() if isinstance(v, dict) and "probe" in v}
    if not ladders and not probes:
        lines.append("decision cache empty (no measured decisions this host)")
        return "\n".join(lines)
    for key in sorted(ladders):
        rec = ladders[key]
        cands = rec.get("candidates", {})
        winner = str(rec.get("winner"))
        base = str(rec.get("baseline"))
        wr, br = cands.get(winner, {}), cands.get(base, {})
        disq = sorted(
            lbl for lbl, c in cands.items() if c.get("bit_exact") is False
        )
        receipt = "bit-exact" if wr.get("bit_exact") else "baseline"
        deltas = []
        if wr.get("peak_bytes") is not None and br.get("peak_bytes"):
            d = int(wr["peak_bytes"]) - int(br["peak_bytes"])
            deltas.append(f"bytes {d:+d} ({d / max(br['peak_bytes'], 1):+.0%})")
        if wr.get("exec_seconds") is not None and br.get("exec_seconds"):
            d = float(wr["exec_seconds"]) - float(br["exec_seconds"])
            deltas.append(
                f"seconds {d:+.4f} ({d / max(br['exec_seconds'], 1e-12):+.1%})"
            )
        lines.append(
            f"[{rec.get('family', '?')}] {rec.get('name', '?')}: "
            f"{len(cands)} candidate(s) tried, winner={winner} "
            f"({'ACCEPTED' if rec.get('accepted') else 'baseline kept'}, "
            f"{receipt}"
            + (f", disqualified: {','.join(disq)}" if disq else "")
            + (f"; {' '.join(deltas)}" if deltas else "")
            + ")"
        )
    for key in sorted(probes):
        rec = probes[key]
        lines.append(
            f"[{rec.get('family', '?')}] {rec.get('name', '?')}: measured "
            f"probe cached ({', '.join(sorted(rec['probe']))})"
        )
    return "\n".join(lines)


def _fmt_wire(n: float) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n:.0f}B"


def _hist(h: dict) -> str:
    return (
        ",".join(f"{k}x{v}" for k, v in sorted(h.items())) if h else "-"
    )


def render_comms_budget(comms: dict, edges: dict, algo: str | None = None) -> str:
    """The comms-budget section: the committed per-jit collective ledger
    filtered to `algo`'s mesh specs (a spec key is `<algo>[@variant]/<jit>`)."""

    def of_algo(key: str) -> bool:
        return algo is None or key.split("/", 1)[0].split("@", 1)[0] == algo

    lines = ["== comms budget (committed sheepshard ledger) =="]
    rows = [(k, v) for k, v in sorted(comms.items()) if of_algo(k)]
    if not rows:
        lines.append(
            f"no mesh-bearing specs in the ledger for algo={algo!r} "
            "(see howto/static_analysis.md, sheepshard)"
        )
        return "\n".join(lines)
    widths = (
        max(len("spec/jit"), *(len(k) for k, _ in rows)) + 2, 6, 26, 22, 12,
    )
    lines.append(
        _fmt_row(("spec/jit", "parts", "collectives", "hot(in-loop)", "wire/step"), widths)
    )
    for key, fp in rows:
        lines.append(_fmt_row(
            (
                key,
                fp.get("num_partitions", 1),
                _hist(fp.get("collectives", {})),
                _hist(fp.get("hot_collectives", {})),
                _fmt_wire(fp.get("wire_bytes", 0)),
            ),
            widths,
        ))
        for item in fp.get("replicated_inputs", []):
            lines.append(f"  SILENTLY REPLICATED input {item}")
    for key, rec in sorted(edges.items()):
        if not of_algo(key):
            continue
        status = rec.get("status", "?")
        flag = " <- RESHARD THRASH" if status == "mismatch" else ""
        lines.append(
            f"edge {key}: expect={rec.get('expect', '?')} status={status}{flag}"
        )
    return "\n".join(lines)


def render_memory_budget(
    memory: dict, algo: str | None = None, runtime_peak_bytes: float = 0.0
) -> str:
    """The memory-budget section (ISSUE 10): the committed per-jit sheepmem
    ledger filtered to `algo`'s specs — static peak/temp/alias/constant
    bytes per jit — plus the static-vs-runtime comparison when the run
    recorded `Memory/*` gauges."""

    def of_algo(key: str) -> bool:
        return algo is None or key.split("/", 1)[0].split("@", 1)[0] == algo

    lines = ["== memory budget (committed sheepmem ledger) =="]
    rows = [(k, v) for k, v in sorted(memory.items()) if of_algo(k)]
    if not rows:
        lines.append(
            f"no memory fingerprints in the ledger for algo={algo!r} "
            "(run tools/sheepmem.py --update-budget)"
        )
        return "\n".join(lines)
    widths = (
        max(len("spec/jit"), *(len(k) for k, _ in rows)) + 2,
        10, 10, 10, 12, 10,
    )
    lines.append(_fmt_row(
        ("spec/jit", "peak", "temp", "args", "aliases", "const"), widths
    ))
    static_peak = 0
    for key, fp in rows:
        static_peak = max(static_peak, int(fp.get("peak_bytes", 0)))
        lines.append(_fmt_row(
            (
                key,
                _fmt_wire(fp.get("peak_bytes", 0)),
                _fmt_wire(fp.get("temp_bytes", 0)),
                _fmt_wire(fp.get("argument_bytes", 0)),
                f"{len(fp.get('aliases', []))}/{fp.get('donated', 0)}",
                _fmt_wire(fp.get("constant_bytes", 0)),
            ),
            widths,
        ))
        for item in fp.get("large_constants", []):
            lines.append(f"  LARGE EMBEDDED CONSTANT {item}")
        for buf in fp.get("scan_buffers", [])[:1]:
            trip = buf.get("trip_count")
            lines.append(
                f"  largest scan-carried buffer: {buf.get('shape')} "
                f"({_fmt_wire(buf.get('bytes', 0))}"
                + (f" x{trip} iterations)" if trip else ")")
            )
    if runtime_peak_bytes and static_peak:
        ratio = runtime_peak_bytes / static_peak
        lines.append(
            f"runtime peak (Memory/* gauges) {_fmt_wire(runtime_peak_bytes)} "
            f"vs static max peak {_fmt_wire(static_peak)} "
            f"({ratio:.1f}x — buffers + executables beyond any single jit)"
        )
    return "\n".join(lines)


def render_concurrency(conc: dict, summary: dict) -> str:
    """The sheepsync concurrency section (ISSUE 18): the committed lock
    graph, guard map and thread inventory from the ledger, merged with the
    run's live `Sync/*` sanitizer gauges and any `sync.order_violation`
    timeline. Either side may be empty — ledger-only (no sanitized run) and
    run-only (ledger not committed yet) both render."""
    lines = ["== sheepsync concurrency (lock graph / thread sanitizer) =="]
    if conc:
        lines.append(
            f"ledger fingerprint {conc.get('fingerprint', '?')}  "
            f"(analysis/budget/concurrency.json)"
        )
        roles = conc.get("roles", {})
        for role in sorted(roles):
            locks = roles[role].get("locks", {})
            if not locks:
                continue
            lines.append(f"  [{role}] locks:")
            for ident, ld in sorted(locks.items()):
                backing = f" on {ld['backing']}" if ld.get("backing") else ""
                lines.append(
                    f"    {ident:52s} {ld.get('kind', '?'):9s}{backing} "
                    f"({ld.get('site', '?')})"
                )
        edges = conc.get("lock_order", {}).get("edges", [])
        chains = conc.get("lock_order", {}).get("chains", {})
        lines.append("  lock-order edges (outer -> inner):")
        if not edges:
            lines.append("    (none)")
        for a, b in edges:
            lines.append(f"    {a} -> {b}")
            chain = chains.get(f"{a} -> {b}")
            if chain:
                lines.append(f"        {chain}")
        for cyc in conc.get("lock_order", {}).get("cycles", []):
            lines.append(f"    CYCLE: {cyc[0]} <-> {cyc[1]}")
        guarded = []
        for role in sorted(roles):
            for attr, guard in sorted(
                (roles[role].get("guards") or {}).items()
            ):
                guarded.append(
                    f"    {role}:{attr:40s} "
                    + (guard if guard else "UNGUARDED")
                )
        if guarded:
            lines.append("  shared-write guard map:")
            lines.extend(guarded)
        threads = [
            (role, t)
            for role in sorted(roles)
            for t in roles[role].get("threads", [])
        ]
        if threads:
            lines.append("  declared threads:")
            for role, t in threads:
                d = {True: "daemon", False: "non-daemon"}.get(
                    t.get("daemon"), "daemon?"
                )
                j = "joined" if t.get("joined") else "unjoined"
                lines.append(
                    f"    [{role}] {t.get('name', '?'):26s} "
                    f"target={t.get('target', '?'):34s} {d:11s} {j}"
                )
    gauges = summary.get("sync_gauges", {})
    if gauges:
        lines.append("  runtime sanitizer (last Sync/* gauges):")
        acq = gauges.get("Sync/acquisitions", 0.0)
        lines.append(
            f"    acquisitions {acq:.0f}  contended "
            f"{gauges.get('Sync/contended', 0.0):.0f}  "
            f"hold max {gauges.get('Sync/hold_ms_max', 0.0):.1f}ms "
            f"avg {gauges.get('Sync/hold_ms_avg', 0.0):.3f}ms  "
            f"wait max {gauges.get('Sync/wait_ms_max', 0.0):.1f}ms"
        )
        lines.append(
            f"    observed edges {gauges.get('Sync/observed_edges', 0.0):.0f} "
            f"(undeclared {gauges.get('Sync/undeclared_edges', 0.0):.0f})  "
            f"order violations "
            f"{gauges.get('Sync/order_violations', 0.0):.0f}"
        )
    first_ts = summary.get("first_ts")
    violations = [
        ev
        for ev in summary.get("sync_events", [])
        if ev.get("event") == "sync.order_violation"
    ]
    if violations:
        lines.append("  ORDER VIOLATIONS (runtime inversions of the DAG):")
        for ev in violations:
            rel = ""
            if first_ts is not None and ev.get("ts") is not None:
                rel = f"t+{ev['ts'] - first_ts:7.2f}s  "
            lines.append(
                f"    {rel}[{ev.get('thread', '?')}] acquired "
                f"{ev.get('acquiring', '?')} while holding "
                f"{ev.get('held', '?')}"
            )
    elif gauges or conc:
        lines.append("  no lock-order violations recorded")
    return "\n".join(lines)


def _fmt_row(cols, widths):
    return "  ".join(str(c).ljust(w) for c, w in zip(cols, widths)).rstrip()


# Distributed fault-tolerance lifecycle (ISSUE 16): which events mark a
# failure being NOTICED vs SURVIVED, per tier. The timeline pairs each
# recovery with the nearest preceding detection on the same scope (actor id
# for flock, the whole server for serve) to print recovery latencies.
_DETECT_EVENTS = {
    "flock.conn_error": "flock",
    "flock.actor_stale": "flock",
    "flock.actor_disconnected": "flock",
    "serve.conn_error": "serve",
    "serve.client_close_error": "serve",
    "serve.draining": "serve",
}
_RECOVER_EVENTS = {
    "flock.actor_rejoined": "flock",
    "flock.actor_adopted": "flock",
    "flock.actor_respawned": "flock",
    "flock.resumed": "flock",
    "serve.drained": "serve",
}


def recovery_timeline(summary: dict) -> list[str]:
    """Per-tier fault/recovery timeline: every injection, detection and
    recovery event in one chronological view, recoveries annotated with
    the latency since the matching detection."""
    entries: list[tuple[float, str, str, str]] = []  # ts, tier, verb, detail

    def _detail(ev, skip=("event", "ts", "step")):
        return " ".join(
            f"{k}={v}" for k, v in ev.items() if k not in skip and v is not None
        )

    for ev in summary["fault_injected"]:
        site = str(ev.get("site", "?"))
        tier = (
            "net" if site.startswith("net.")
            else "peer" if site.startswith("peer.")
            else "train"
        )
        param = "" if ev.get("param") is None else f":{ev['param']:g}"
        entries.append(
            (ev.get("ts") or 0.0, tier, "INJECT",
             f"{site}@{ev.get('step')}{param}")
        )

    pool = summary["flock_events"] + summary["serve_events"]
    detections: list[dict] = []
    for ev in sorted(pool, key=lambda e: e.get("ts") or 0.0):
        kind = ev["event"]
        ts = ev.get("ts") or 0.0
        if kind in _DETECT_EVENTS:
            detections.append(ev)
            verb = {
                "flock.actor_stale": "EVICT",
                "serve.draining": "DRAIN",
            }.get(kind, "DETECT")
            entries.append(
                (ts, _DETECT_EVENTS[kind], verb,
                 f"{kind.split('.', 1)[1]} {_detail(ev)}")
            )
        elif kind in _RECOVER_EVENTS:
            # latency: nearest preceding detection on the same scope
            scope = ev.get("actor_id")
            prior = [
                d for d in detections
                if (d.get("ts") or 0.0) <= ts
                and (scope is None or d.get("actor_id") in (None, scope))
                and _DETECT_EVENTS[d["event"]] == _RECOVER_EVENTS[kind]
            ]
            lat = (
                f" (+{ts - (prior[-1].get('ts') or 0.0):.2f}s after "
                f"{prior[-1]['event'].split('.', 1)[1]})"
                if prior else ""
            )
            entries.append(
                (ts, _RECOVER_EVENTS[kind], "RECOVER",
                 f"{kind.split('.', 1)[1]} {_detail(ev)}{lat}")
            )

    if not entries:
        return []
    t0 = summary["first_ts"] or 0.0
    lines = ["distributed recovery timeline (per tier):"]
    for ts, tier, verb, detail in sorted(entries, key=lambda e: e[0]):
        lines.append(f"t+{ts - t0:7.2f}s  [{tier:<5}] {verb:<7} {detail}")
    return lines


def render(summary: dict) -> str:
    """The human-readable report."""
    lines: list[str] = []
    start = summary["start"] or {}
    lines.append("== run ==")
    lines.append(
        f"algo={start.get('algo', '?')} env={start.get('env_id', '?')} "
        f"seed={start.get('seed', '?')} backend={start.get('backend', '?')} "
        f"devices={start.get('local_devices', '?')}"
    )
    if summary["first_ts"] is not None and summary["last_ts"] is not None:
        lines.append(
            f"wall_clock={summary['last_ts'] - summary['first_ts']:.1f}s "
            f"log_events={summary['log_events']} last_step={summary['last_step']}"
        )
    if summary["crash"]:
        lines.append(f"OUTCOME: CRASHED — {summary['crash'].get('error')}")
    elif summary["preempt"]:
        p = summary["preempt"]
        lines.append(
            f"OUTCOME: PREEMPTED at step {p.get('step')} "
            f"({p.get('signal', '?')}, resumable rc {p.get('rc')}) — "
            "restart with --resume auto"
        )
    elif summary["end"]:
        lines.append("OUTCOME: completed (clean end event)")
    else:
        lines.append("OUTCOME: unknown (no end/crash event — log truncated or run live)")
    lines.append(
        f"checkpoints={len(summary['checkpoints'])} "
        f"profile_windows={summary['profile_windows']}"
    )

    lines.append("")
    lines.append("== phase breakdown ==")
    phases = summary["phase_seconds"]
    if phases:
        total = sum(phases.values())
        widths = (max(len("total (accounted)"), *(len(p) for p in phases)) + 2, 12, 8)
        lines.append(_fmt_row(("phase", "seconds", "share"), widths))
        for name, secs in sorted(phases.items(), key=lambda kv: -kv[1]):
            share = f"{100 * secs / total:.1f}%" if total > 0 else "-"
            lines.append(_fmt_row((name, f"{secs:.3f}", share), widths))
        lines.append(_fmt_row(("total (accounted)", f"{total:.3f}", "100%"), widths))
    else:
        lines.append("no phase timings recorded")

    lines.append("")
    lines.append("== throughput / compiles ==")
    if summary["sps_series"]:
        sps = summary["sps_series"]
        lines.append(
            f"step_per_second: mean={sum(sps) / len(sps):.1f} last={sps[-1]:.1f}"
        )
    lines.append(
        f"xla_compiles={summary['total_compiles']:.0f} "
        f"({summary['total_compile_seconds']:.1f}s total)"
    )
    lines.append(
        f"recompiles after first interval: {summary['late_recompiles']:.0f} "
        f"({summary['late_compile_seconds']:.1f}s) "
        + ("<- RETRACE STORM?" if summary["late_recompiles"] > 0 else "(clean)")
    )

    lines.append("")
    lines.append("== compile breakdown (warm-start subsystem) ==")
    g = summary["compile_gauges"]
    fu = summary["first_update"]
    if fu is not None:
        lines.append(
            f"time_to_first_update={fu.get('seconds', 0):.1f}s "
            f"(warm_compile={fu.get('warm_compile', '?')})"
        )
    if summary["compile_events"] or g:
        warm = [e for e in summary["compile_events"] if e.get("mode") == "warm"]
        falls = [
            e for e in summary["compile_events"] if e.get("mode") == "aot_fallback"
        ]
        if warm:
            widths = (max(len("executable"), *(len(str(e.get("jit"))) for e in warm)) + 2, 12, 8, 8)
            lines.append(_fmt_row(("executable", "compile_s", "hits", "misses"), widths))
            for e in warm:
                lines.append(_fmt_row(
                    (e.get("jit"), f"{e.get('seconds', 0):.2f}",
                     e.get("cache_hits", 0), e.get("cache_misses", 0)),
                    widths,
                ))
        if g:
            lines.append(
                f"plan: entries={g.get('Compile/plan_entries', 0):.0f} "
                f"compiled={g.get('Compile/plan_compiled', 0):.0f} "
                f"aot_calls={g.get('Compile/aot_calls', 0):.0f} "
                f"fallbacks={g.get('Compile/aot_fallbacks', 0):.0f} "
                f"cache {g.get('Compile/cache_hits', 0):.0f} hit / "
                f"{g.get('Compile/cache_misses', 0):.0f} miss"
            )
        for e in falls:
            lines.append(f"AOT FALLBACK {e.get('jit')}: {e.get('error', '')}")
        for e in summary["partition_events"]:
            lines.append(
                f"partition {e.get('jit')}: chunk={e.get('chunk')} "
                f"({e.get('reason', '')})"
            )
    else:
        lines.append("no warm-start compile telemetry (cold path or pre-round-6 log)")

    a = summary["anakin_gauges"]
    if a:
        lines.append("")
        lines.append("== anakin collection (on-device jax envs) ==")
        lines.append(
            f"env_steps_per_second: last={a.get('Anakin/env_steps_per_second', 0):,.0f} "
            f"avg={a.get('Anakin/env_steps_per_second_avg', 0):,.0f}"
        )
        lines.append(
            f"scan_span={a.get('Anakin/scan_span', 0):.0f} "
            f"env_batch={a.get('Anakin/env_batch', 0):.0f} "
            f"devices={a.get('Anakin/devices', 0):.0f} "
            f"rollouts={a.get('Anakin/rollouts', 0):.0f} "
            f"env_steps_total={a.get('Anakin/env_steps_total', 0):,.0f}"
        )

    fg = summary["flock_gauges"]
    if fg or summary["flock_started"] or summary["flock_events"]:
        lines.append("")
        lines.append("== flock (actor-learner runtime) ==")
        started = summary["flock_started"] or {}
        lines.append(
            f"service: address={started.get('address', '?')} "
            f"mode={started.get('mode', '?')}"
        )
        lines.append(
            f"fleet: actors_alive={fg.get('Flock/actors_alive', 0):.0f} "
            f"weight_version={fg.get('Flock/weight_version', 0):.0f} "
            f"rows_total={fg.get('Flock/rows_total', 0):,.0f} "
            f"chunks_dropped={fg.get('Flock/chunks_dropped', 0):.0f}"
        )
        # Per-actor table from the Flock/actor{N}/<field> gauge namespace.
        actors = sorted(
            {
                k.split("/")[1]
                for k in fg
                if k.count("/") == 2 and k.split("/")[1].startswith("actor")
            },
            key=lambda a: (len(a), a),
        )
        if actors:
            headers = (
                "actor", "steps/s", "env_steps", "wv", "lag",
                "stale_s", "hb_s", "fill", "gen", "up",
            )
            widths = (8, 10, 12, 5, 5, 9, 7, 7, 5, 4)
            lines.append(_fmt_row(headers, widths))
            for a in actors:
                def g(field, _a=a):
                    return fg.get(f"Flock/{_a}/{field}")

                def num(field, fmt, _g=g):
                    v = _g(field)
                    return format(v, fmt) if isinstance(v, (int, float)) else "-"

                lines.append(_fmt_row(
                    (
                        a,
                        num("env_steps_s", ",.0f"),
                        num("env_steps", ",.0f"),
                        num("weight_version", ".0f"),
                        num("version_lag", ".0f"),
                        num("staleness_s", ".2f"),
                        num("heartbeat_age_s", ".2f"),
                        num("shard_fill", ".2f"),
                        num("generation", ".0f"),
                        "yes" if g("connected") else "no",
                    ),
                    widths,
                ))
        # Staleness distribution across every logged interval, not just the
        # last gauge value — the number the bench round cares about.
        all_stale = [v for vs in summary["flock_staleness"].values() for v in vs]
        if all_stale:
            s = sorted(all_stale)
            lines.append(
                f"weight staleness (all actors, {len(s)} samples): "
                f"min={s[0]:.2f}s p50={s[len(s) // 2]:.2f}s "
                f"p90={s[min(len(s) - 1, int(len(s) * 0.9))]:.2f}s "
                f"max={s[-1]:.2f}s"
            )
        if summary["flock_events"]:
            counts: dict = {}
            for ev in summary["flock_events"]:
                counts[ev["event"]] = counts.get(ev["event"], 0) + 1
            lines.append(
                "membership: "
                + " ".join(f"{k.split('.', 1)[1]}={v}" for k, v in sorted(counts.items()))
            )
            t0 = summary["first_ts"] or 0.0
            for ev in summary["flock_events"]:
                ts = ev.get("ts")
                rel = f"t+{ts - t0:7.2f}s" if isinstance(ts, (int, float)) else "t+      ?"
                what = ev["event"].split(".", 1)[1].upper()
                detail = " ".join(
                    f"{k}={v}"
                    for k, v in ev.items()
                    if k not in ("event", "ts", "step")
                )
                lines.append(f"{rel}  {what:<12} {detail}")

    sg = summary["serve_gauges"]
    if sg or summary["serve_start"] or summary["serve_ladder"]:
        lines.append("")
        lines.append("== serving (batched inference tier) ==")
        started = summary["serve_start"] or {}
        lines.append(
            f"server: algo={started.get('algo', '?')} "
            f"address={started.get('address', '?')} "
            f"rungs={started.get('rungs', '?')} "
            f"ckpt={started.get('ckpt') or '-'}"
        )
        if summary["serve_ladder"]:
            lines.append("batch ladder (ledger-first sizing):")
            for d in summary["serve_ladder"]:
                status = "accepted" if d.get("accepted") else "REJECTED"
                peak = d.get("peak_bytes")
                peak_s = _fmt_wire(peak) if isinstance(peak, (int, float)) else "-"
                lines.append(
                    f"  rung {d.get('rung', '?'):>4}  {status:<9} "
                    f"{str(d.get('source', '?')):<7} peak={peak_s:<10} "
                    f"{d.get('reason', '')}"
                )
        if sg:
            lines.append(
                f"load: qps={sg.get('Serve/qps', 0):.1f} "
                f"latency p50={sg.get('Serve/latency_p50_ms', 0):.2f}ms "
                f"p99={sg.get('Serve/latency_p99_ms', 0):.2f}ms "
                f"batch_occupancy={sg.get('Serve/batch_occupancy', 0):.2f}"
            )
            lines.append(
                f"requests: served={sg.get('Serve/served_total', 0):,.0f} "
                f"shed={sg.get('Serve/shed_total', 0):.0f} "
                f"oversized={sg.get('Serve/oversized_total', 0):.0f} "
                f"failed={sg.get('Serve/failed_total', 0):.0f} "
                f"dispatches={sg.get('Serve/dispatches', 0):,.0f}"
            )
            lines.append(
                f"params: version={sg.get('Serve/params_version', 0):.0f} "
                f"reloads={sg.get('Serve/reloads', 0):.0f} "
                f"reload_failures={sg.get('Serve/reload_failures', 0):.0f}"
            )
        # Hot-reload timeline: every swap (and every refused swap) with the
        # version the server kept serving.
        t0 = summary["first_ts"] or 0.0
        for ev in summary["serve_reloads"]:
            ts = ev.get("ts")
            rel = f"t+{ts - t0:7.2f}s" if isinstance(ts, (int, float)) else "t+      ?"
            if ev.get("ok"):
                lines.append(
                    f"{rel}  RELOAD  -> v{ev.get('version')} "
                    f"({ev.get('seconds', 0):.2f}s) {ev.get('path', '')}"
                )
            else:
                lines.append(
                    f"{rel}  RELOAD-FAILED kept v{ev.get('version')}: "
                    f"{(ev.get('error') or '')[:80]}"
                )
        if summary["serve_stop"]:
            st = summary["serve_stop"]
            lines.append(
                f"stopped: completed={st.get('completed')} "
                f"final_version={st.get('version')}"
            )

    # distributed detections/recoveries (ISSUE 16) open the section too:
    # a partition that only shows up as flock.conn_error + actor_rejoined
    # still belongs in the fault/recovery story
    timeline = recovery_timeline(summary)
    resil_any = (
        summary["fault_injected"]
        or summary["fault_recovered"]
        or summary["preempt"]
        or summary["resume"]
        or summary["checkpoint_corrupt"]
        or summary["checkpoint_errors"]
        or summary["fault_gauges"]
        or timeline
    )
    if resil_any:
        lines.append("")
        lines.append("== resilience (faults / recovery) ==")
        t0 = summary["first_ts"] or 0.0

        def rel(ev):
            ts = ev.get("ts")
            return f"t+{ts - t0:7.2f}s" if isinstance(ts, (int, float)) else "t+      ?"

        if summary["resume"]:
            r = summary["resume"]
            lines.append(
                f"{rel(r)}  RESUME  {r.get('mode')} -> {r.get('checkpoint')}"
                + (
                    f" ({r.get('fallbacks')} fallback candidate(s))"
                    if r.get("fallbacks") is not None
                    else ""
                )
            )
        for ev in summary["fault_injected"]:
            param = "" if ev.get("param") is None else f":{ev['param']:g}"
            lines.append(
                f"{rel(ev)}  INJECT  {ev.get('site')}@{ev.get('step')}{param}"
            )
        for ev in summary["fault_recovered"]:
            lines.append(
                f"{rel(ev)}  RECOVER {ev.get('site')} -> {ev.get('action')}"
            )
        for ev in summary["checkpoint_errors"]:
            lines.append(
                f"{rel(ev)}  CKPT-RETRY attempt {ev.get('attempt')}: "
                f"{ev.get('error', '')[:80]}"
            )
        for ev in summary["checkpoint_corrupt"]:
            what = ev.get("reason") or f"fell back to {ev.get('checkpoint')}"
            lines.append(
                f"{rel(ev)}  CORRUPT {ev.get('path') or ev.get('failed')}: {what}"
            )
        if summary["preempt_signal"]:
            lines.append(
                f"{rel(summary['preempt_signal'])}  PREEMPT "
                f"{summary['preempt_signal'].get('signal')} received "
                "(grace window opened)"
            )
        if summary["preempt"]:
            lines.append(
                f"{rel(summary['preempt'])}  EXIT    grace checkpoint committed, "
                f"rc {summary['preempt'].get('rc')}"
            )
        if summary["fault_gauges"]:
            gauges = " ".join(
                f"{k.split('/', 1)[1]}={v:.0f}"
                for k, v in sorted(summary["fault_gauges"].items())
            )
            lines.append(f"Fault gauges: {gauges}")
        if timeline:
            lines.append("")
            lines.extend(timeline)

    lines.append("")
    lines.append("== health ==")
    if summary["nan_events"]:
        keys: set = set()
        for ev in summary["nan_events"]:
            keys.update(ev.get("keys", []))
        lines.append(
            f"NON-FINITE metrics in {len(summary['nan_events'])} interval(s): "
            f"{sorted(keys)}"
        )
    else:
        lines.append("no non-finite metrics observed")
    if summary["peak_memory_bytes"]:
        lines.append(f"peak_device_memory={summary['peak_memory_bytes'] / 2**30:.2f}GiB")
    for k, v in sorted(summary["gauges_last"].items()):
        lines.append(f"{k}={v:.2f}")
    return "\n".join(lines)


def report(path: str) -> dict:
    """Load + summarize + print; returns the summary (tests use it)."""
    summary = summarize(load_events(path))
    print(render(summary))
    algo = (summary["start"] or {}).get("algo")
    comms, edges = load_comms_ledger()
    if comms or edges:
        print()
        print(render_comms_budget(comms, edges, algo=algo))
    memory = load_memory_ledger()
    if memory:
        print()
        print(render_memory_budget(
            memory, algo=algo,
            runtime_peak_bytes=summary["peak_memory_bytes"],
        ))
    decisions = load_decision_cache()
    if decisions:
        print()
        print(render_sheepopt_decisions(decisions))
    conc = load_concurrency_ledger()
    if conc or summary["sync_gauges"] or summary["sync_events"]:
        print()
        print(render_concurrency(conc, summary))
    return summary


def selftest() -> int:
    """Synthesize a run through the REAL Telemetry writer, then assert this
    reader recovers the critical facts from it."""
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from sheeprl_tpu.telemetry import Telemetry

    d = tempfile.mkdtemp(prefix="telemetry_selftest_")
    telem = Telemetry(d, rank=0, algo="selftest")
    telem.event("start", algo="selftest", env_id="dummy", seed=0)
    for step in (10, 20, 30):
        telem.mark("rollout")
        telem.mark("train/dispatch")
        telem.mark("log")
        metrics = {"Loss/x": 0.5}
        if step == 20:
            metrics["Loss/bad"] = float("inf")
        telem.interval(metrics, step, sps=123.0)
    telem.event("checkpoint", path=os.path.join(d, "ckpt_30"))
    # the warm-start subsystem's events ride the same writer (compile/plan.py)
    telem.event(
        "compile", jit="train_step", mode="warm", seconds=3.25,
        cache_hits=0, cache_misses=1, error=None,
    )
    telem.event("first_update", seconds=7.5, warm_compile="on")
    telem.close()

    summary = report(d)
    assert summary["start"] and summary["start"]["algo"] == "selftest"
    assert summary["end"] is not None and summary["crash"] is None
    assert summary["log_events"] == 3 and summary["last_step"] == 30
    assert "rollout" in summary["phase_seconds"], summary["phase_seconds"]
    assert "train/dispatch" in summary["phase_seconds"]
    assert len(summary["checkpoints"]) == 1
    assert len(summary["nan_events"]) == 1
    assert summary["nan_events"][0]["keys"] == ["Loss/bad"]
    assert summary["first_update"] and summary["first_update"]["seconds"] == 7.5
    assert len(summary["compile_events"]) == 1
    assert summary["compile_events"][0]["jit"] == "train_step"
    assert summary["compile_events"][0]["cache_misses"] == 1

    # comms-budget section: writer (sheepshard ledger schema) and this
    # reader stay in sync — rendered from a synthetic ledger, and the
    # committed repo ledger must load without error wherever it exists
    section = render_comms_budget(
        {
            "selftest@mesh8/train_step": {
                "num_partitions": 8,
                "collectives": {"all-reduce": 5},
                "hot_collectives": {"all-reduce": 5},
                "wire_bytes": 4 << 20,
                "replicated_inputs": ["3:float32[1024,1024]"],
            }
        },
        {"selftest@mesh8/rollout->train_step": {"expect": "match", "status": "mismatch"}},
        algo="selftest",
    )
    assert "all-reducex5" in section and "4.0MiB" in section, section
    assert "SILENTLY REPLICATED" in section and "RESHARD THRASH" in section
    comms, edges = load_comms_ledger()
    if comms:
        assert all("/" in k for k in comms), "comms keys must be spec/jit"
        assert all(r.get("status") for r in edges.values())

    # memory-budget section (ISSUE 10): writer (sheepmem ledger schema) and
    # this reader stay in sync — rendered from a synthetic ledger with a
    # runtime gauge to compare against, and the committed repo ledger must
    # load without error wherever it exists
    mem_section = render_memory_budget(
        {
            "selftest/train_step": {
                "peak_bytes": 8 << 20,
                "temp_bytes": 3 << 20,
                "argument_bytes": 4 << 20,
                "donated": 12,
                "aliases": ["out{0}<-arg0"] * 12,
                "constant_bytes": 2048,
                "large_constants": ["f32[4096,64]:1048576"],
                "scan_buffers": [
                    {"shape": "f32[64,64]", "bytes": 16384, "trip_count": 16}
                ],
            }
        },
        algo="selftest",
        runtime_peak_bytes=float(24 << 20),
    )
    assert "8.0MiB" in mem_section and "12/12" in mem_section, mem_section
    assert "LARGE EMBEDDED CONSTANT f32[4096,64]:1048576" in mem_section
    assert "x16 iterations" in mem_section
    assert "runtime peak" in mem_section and "3.0x" in mem_section
    memory = load_memory_ledger()
    if memory:
        assert all("/" in k for k in memory), "memory keys must be spec/jit"
        assert all("peak_bytes" in fp for fp in memory.values())

    # sheepopt decisions section (ISSUE 11): writer schema
    # (compile/decisions.py Decision.as_dict + measured_probe records) and
    # this renderer stay in sync — a ladder with a disqualified rung, an
    # accepted bytes-objective winner, and a cached probe
    fake_cache = {
        "remat|selftest.step|f32[4]|jax0|cpu": {
            "family": "remat", "name": "selftest.step",
            "winner": "on", "baseline": "off", "objective": "bytes",
            "accepted": True, "source": "measured",
            "candidates": {
                "off": {"exec_seconds": 1.0, "compile_seconds": 0.5,
                        "bit_exact": True, "peak_bytes": 100 << 20,
                        "temp_bytes": 90 << 20},
                "policy": {"exec_seconds": 1.0, "compile_seconds": 0.6,
                           "bit_exact": False, "peak_bytes": 80 << 20,
                           "temp_bytes": 70 << 20},
                "on": {"exec_seconds": 1.04, "compile_seconds": 0.5,
                       "bit_exact": True, "peak_bytes": 70 << 20,
                       "temp_bytes": 60 << 20},
            },
        },
        "batch_chunk|selftest.recon[batch=8]|f32[8]|jax0|cpu": {
            "family": "batch_chunk", "name": "selftest.recon[batch=8]",
            "probe": {"counts": {"convolutions": 23}, "trial": True,
                      "trial_seconds": 2.0, "temp_bytes": 1 << 20},
        },
    }
    opt_section = render_sheepopt_decisions(fake_cache)
    assert "winner=on" in opt_section and "ACCEPTED" in opt_section, opt_section
    assert "disqualified: policy" in opt_section, opt_section
    assert "bytes -31457280" in opt_section, opt_section
    assert "3 candidate(s) tried" in opt_section, opt_section
    assert "measured probe cached" in opt_section, opt_section
    import tempfile as _tf

    opt_dir = _tf.mkdtemp(prefix="telemetry_selftest_dec_")
    with open(os.path.join(opt_dir, "decisions.json"), "w") as fh:
        json.dump(fake_cache, fh)
    loaded = load_decision_cache(os.path.join(opt_dir, "decisions.json"))
    assert loaded == fake_cache
    assert load_decision_cache(os.path.join(opt_dir, "absent.json")) == {}

    # resilience section (ISSUE 12): a preempted run with injected faults,
    # recoveries, a corrupt-checkpoint skip and Fault/* gauges must render
    # as the fault/recovery timeline, and the preempt outcome must win over
    # "unknown" — written through the REAL Telemetry writer like the rest
    d2 = tempfile.mkdtemp(prefix="telemetry_selftest_resil_")
    telem2 = Telemetry(d2, rank=0, algo="resil")
    telem2.event("start", algo="resil", env_id="dummy", seed=0)
    telem2.event("resume", mode="auto", checkpoint="/run/checkpoints/ckpt_4", fallbacks=1)
    telem2.event("fault.injected", site="nan.grad", step=6, param=None)
    telem2.event("fault.recovered", site="nan", action="updates_skipped")
    telem2.event("fault.injected", site="sigterm", step=9, param=None)
    telem2.event("checkpoint.corrupt", path="/run/checkpoints/ckpt_2", reason="missing args.json sidecar")
    telem2.event("checkpoint.error", path="/run/checkpoints/ckpt_8", attempt=1, error="InjectedFault: boom")
    telem2.event("fault.recovered", site="ckpt.write", action="ckpt_retried")
    telem2.event("preempt.signal", signal="SIGTERM")
    telem2.interval({"Loss/x": 1.0, "Fault/injected": 2.0, "Fault/updates_skipped": 1.0}, step=9)
    telem2.event("preempt", step=9, signal="SIGTERM", rc=75)
    telem2.close()
    summary2 = summarize(load_events(d2))
    out2 = render(summary2)
    assert "OUTCOME: PREEMPTED at step 9" in out2 and "rc 75" in out2, out2
    assert "RESUME  auto -> /run/checkpoints/ckpt_4 (1 fallback candidate(s))" in out2
    assert "INJECT  nan.grad@6" in out2 and "INJECT  sigterm@9" in out2
    assert "RECOVER nan -> updates_skipped" in out2
    assert "RECOVER ckpt.write -> ckpt_retried" in out2
    assert "CKPT-RETRY attempt 1" in out2
    assert "CORRUPT /run/checkpoints/ckpt_2: missing args.json sidecar" in out2
    assert "PREEMPT SIGTERM received" in out2
    assert "Fault gauges: injected=2 updates_skipped=1" in out2, out2

    # flock section (ISSUE 14): a 2-actor run with a death + rejoin must
    # render the service line, the per-actor table, the staleness
    # distribution and the membership timeline — written through the REAL
    # Telemetry writer like the rest
    d3 = tempfile.mkdtemp(prefix="telemetry_selftest_flock_")
    telem3 = Telemetry(d3, rank=0, algo="flock")
    telem3.event("start", algo="flock", env_id="dummy", seed=0)
    telem3.event("flock.started", address="unix:/tmp/svc.sock", mode="buffer")
    telem3.event("flock.actor_joined", actor_id=0, pid=111)
    telem3.event("flock.actor_joined", actor_id=1, pid=222)
    telem3.interval(
        {
            "Flock/actors_alive": 2.0, "Flock/weight_version": 3.0,
            "Flock/rows_total": 1024.0, "Flock/chunks_dropped": 0.0,
            "Flock/actor0/env_steps_s": 512.0, "Flock/actor0/env_steps": 600.0,
            "Flock/actor0/weight_version": 3.0, "Flock/actor0/version_lag": 0.0,
            "Flock/actor0/staleness_s": 0.25, "Flock/actor0/heartbeat_age_s": 0.1,
            "Flock/actor0/shard_fill": 0.5, "Flock/actor0/generation": 0.0,
            "Flock/actor0/connected": 1.0,
            "Flock/actor1/env_steps_s": 480.0, "Flock/actor1/env_steps": 424.0,
            "Flock/actor1/weight_version": 2.0, "Flock/actor1/version_lag": 1.0,
            "Flock/actor1/staleness_s": 0.75, "Flock/actor1/heartbeat_age_s": 0.2,
            "Flock/actor1/shard_fill": 0.4, "Flock/actor1/generation": 0.0,
            "Flock/actor1/connected": 1.0,
        },
        step=10,
    )
    telem3.event("flock.actor_disconnected", actor_id=1, rows=424, env_steps=424)
    telem3.event("flock.actor_died", actor_id=1, rc=-9)
    telem3.event("flock.actor_respawned", actor_id=1, attempt=1)
    telem3.event("flock.actor_rejoined", actor_id=1, generation=1, weight_version=4)
    telem3.interval(
        {
            "Flock/actors_alive": 2.0, "Flock/weight_version": 4.0,
            "Flock/rows_total": 2048.0, "Flock/chunks_dropped": 0.0,
            "Flock/actor0/staleness_s": 0.30, "Flock/actor0/connected": 1.0,
            "Flock/actor1/staleness_s": 0.05, "Flock/actor1/connected": 1.0,
            "Flock/actor1/generation": 1.0,
        },
        step=20,
    )
    telem3.close()
    summary3 = summarize(load_events(d3))
    out3 = render(summary3)
    assert "== flock (actor-learner runtime) ==" in out3, out3
    assert "address=unix:/tmp/svc.sock mode=buffer" in out3
    assert "actors_alive=2 weight_version=4 rows_total=2,048" in out3, out3
    assert "actor0" in out3 and "actor1" in out3
    assert "weight staleness (all actors, 4 samples)" in out3, out3
    assert "min=0.05s" in out3 and "max=0.75s" in out3, out3
    assert (
        "membership: actor_died=1 actor_disconnected=1 actor_joined=2 "
        "actor_rejoined=1 actor_respawned=1" in out3
    ), out3
    assert "DIED" in out3 and "rc=-9" in out3
    assert "REJOINED" in out3 and "generation=1" in out3
    assert summary3["flock_staleness"]["actor1"] == [0.75, 0.05]

    # serving section (ISSUE 15): ladder sizing decisions, traffic gauges,
    # and a hot-reload timeline with one success and one refused swap must
    # render — written through the REAL Telemetry writer like the rest
    d4 = tempfile.mkdtemp(prefix="telemetry_selftest_serve_")
    telem4 = Telemetry(d4, rank=0, algo="serve")
    telem4.event("start", algo="serve", env_id="dummy", seed=0)
    telem4.event(
        "serve.ladder", rung=1, accepted=True, source="ledger",
        peak_bytes=2048, reason="ledger serve/policy_b1 x1.05",
    )
    telem4.event(
        "serve.ladder", rung=8, accepted=False, source="ledger",
        peak_bytes=1 << 30, reason="predicted peak exceeds budget",
    )
    telem4.event(
        "serve.start", address="unix:/tmp/serve.sock", algo="sac",
        rungs=[1], version=1, ckpt="/run/checkpoints/ckpt_1",
    )
    telem4.event(
        "serve.reload", ok=True, version=2, path="/run/checkpoints/ckpt_2",
        seconds=0.12, error=None,
    )
    telem4.event(
        "serve.reload", ok=False, version=2, path="/run/checkpoints/ckpt_bad",
        seconds=0.01, error="FileNotFoundError: no such checkpoint",
    )
    telem4.interval(
        {
            "Serve/qps": 180.5, "Serve/latency_p50_ms": 2.4,
            "Serve/latency_p99_ms": 9.8, "Serve/batch_occupancy": 0.81,
            "Serve/served_total": 1200.0, "Serve/shed_total": 3.0,
            "Serve/oversized_total": 1.0, "Serve/failed_total": 0.0,
            "Serve/dispatches": 400.0, "Serve/params_version": 2.0,
            "Serve/reloads": 1.0, "Serve/reload_failures": 1.0,
        },
        step=1200,
    )
    telem4.event("serve.stop", completed=1200, version=2)
    telem4.close()
    summary4 = summarize(load_events(d4))
    out4 = render(summary4)
    assert "== serving (batched inference tier) ==" in out4, out4
    assert "algo=sac address=unix:/tmp/serve.sock rungs=[1]" in out4, out4
    assert "rung    1  accepted  ledger" in out4, out4
    assert "rung    8  REJECTED" in out4, out4
    assert "qps=180.5" in out4 and "p50=2.40ms" in out4 and "p99=9.80ms" in out4
    assert "batch_occupancy=0.81" in out4, out4
    assert "served=1,200 shed=3 oversized=1 failed=0" in out4, out4
    assert "version=2 reloads=1 reload_failures=1" in out4, out4
    assert "RELOAD  -> v2 (0.12s) /run/checkpoints/ckpt_2" in out4, out4
    assert "RELOAD-FAILED kept v2: FileNotFoundError" in out4, out4
    assert "stopped: completed=1200 final_version=2" in out4, out4
    assert len(summary4["serve_ladder"]) == 2
    assert [r["ok"] for r in summary4["serve_reloads"]] == [True, False]

    # distributed recovery timeline (ISSUE 16): a chaos-shaped run — a net
    # partition detected as a flock conn_error + disconnect and survived by
    # a rejoin, a learner resume, and a serve drain — must render one
    # chronological per-tier timeline with recovery latencies
    d5 = tempfile.mkdtemp(prefix="telemetry_selftest_chaos_")
    telem5 = Telemetry(d5, rank=0, algo="chaos")
    telem5.event("start", algo="chaos", env_id="dummy", seed=0)
    telem5.event("fault.injected", site="net.partition", step=30, param=1.0)
    telem5.event(
        "flock.conn_error", actor_id=0, role="data",
        error="FrameError: bad magic b'XXXX'",
    )
    telem5.event("flock.actor_disconnected", actor_id=0, rows=96, env_steps=96)
    telem5.event("flock.actor_rejoined", actor_id=0, generation=1, weight_version=3)
    telem5.event("flock.resumed", rows_total=96, weight_version=3, n_actors=2)
    telem5.event("serve.conn_error", peer="c1", error="FrameError: oversize")
    telem5.event("serve.draining", pending=2)
    telem5.event("serve.drained", completed=60)
    telem5.close()
    summary5 = summarize(load_events(d5))
    assert len(summary5["serve_events"]) == 3, summary5["serve_events"]
    tl = recovery_timeline(summary5)
    assert tl and tl[0] == "distributed recovery timeline (per tier):", tl
    body = "\n".join(tl)
    assert "[net  ] INJECT  net.partition@30:1" in body, body
    assert "[flock] DETECT  conn_error" in body and "FrameError" in body, body
    assert "[flock] DETECT  actor_disconnected" in body, body
    assert "[flock] RECOVER actor_rejoined" in body, body
    assert "[flock] RECOVER resumed" in body, body
    assert "[serve] DETECT  conn_error" in body, body
    assert "[serve] DRAIN   draining" in body, body
    assert "[serve] RECOVER drained" in body, body
    # recoveries carry the latency back to their matching detection
    assert "s after actor_disconnected)" in body or "s after conn_error)" in body, body
    assert "s after draining)" in body, body
    out5 = render(summary5)
    assert "== resilience (faults / recovery) ==" in out5, out5
    assert "distributed recovery timeline (per tier):" in out5, out5
    # the flock selftest's membership churn alone must ALSO open the section
    assert "distributed recovery timeline (per tier):" in out3, out3

    # sheepsync concurrency section (ISSUE 18): writer (the runtime thread
    # sanitizer's sync.* events + Sync/* gauges, and the sheepsync ledger
    # schema) and this reader stay in sync
    d6 = tempfile.mkdtemp(prefix="telemetry_selftest_sync_")
    telem6 = Telemetry(d6, rank=0, algo="selftest")
    telem6.event("start", algo="selftest", env_id="dummy", seed=0)
    telem6.event("sync.sanitizer_start", committed_edges=2, known_sites=16, pid=1)
    telem6.event(
        "sync.order_violation",
        acquiring="flock.service.ReplayService._lock",
        held="flock.service.ReplayService._shard_locks[*]",
        thread="flock-monitor",
    )
    telem6.interval(
        {
            "Sync/acquisitions": 420.0,
            "Sync/contended": 3.0,
            "Sync/order_violations": 1.0,
            "Sync/undeclared_edges": 2.0,
            "Sync/observed_edges": 5.0,
            "Sync/hold_ms_avg": 0.021,
            "Sync/hold_ms_max": 4.5,
            "Sync/wait_ms_max": 1.25,
        },
        10,
    )
    telem6.close()
    summary6 = summarize(load_events(d6))
    assert len(summary6["sync_events"]) == 2, summary6["sync_events"]
    assert summary6["sync_gauges"]["Sync/order_violations"] == 1.0
    fake_conc = {
        "fingerprint": "feedfacecafebeef",
        "lock_order": {
            "edges": [["A._lock", "A._shard[*]"]],
            "chains": {"A._lock -> A._shard[*]": "f holds A._lock, acquires A._shard[*]"},
            "cycles": [],
        },
        "roles": {
            "flock": {
                "locks": {
                    "A._lock": {"kind": "RLock", "site": "a.py:1", "backing": None}
                },
                "threads": [
                    {
                        "role": "flock", "path": "a.py", "line": 9,
                        "target": "A._loop", "name": "flock-monitor",
                        "daemon": True, "joined": True,
                    }
                ],
                "guards": {"A.count": "A._lock", "A.naked": None},
            }
        },
    }
    sync_section = render_concurrency(fake_conc, summary6)
    assert "feedfacecafebeef" in sync_section, sync_section
    assert "A._lock -> A._shard[*]" in sync_section, sync_section
    assert "UNGUARDED" in sync_section and "A.count" in sync_section
    assert "flock-monitor" in sync_section and "joined" in sync_section
    assert "acquisitions 420" in sync_section, sync_section
    assert "ORDER VIOLATIONS" in sync_section, sync_section
    assert "while holding" in sync_section, sync_section
    # ledger-only render (no sanitized run) stays valid + committed ledger
    # loads wherever it exists
    ledger_only = render_concurrency(fake_conc, {"sync_gauges": {}, "sync_events": []})
    assert "no lock-order violations recorded" in ledger_only
    conc = load_concurrency_ledger()
    if conc:
        assert conc.get("fingerprint") and "lock_order" in conc
        assert "roles" in conc and "flock" in conc["roles"]

    print("\nselftest OK", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "path", nargs="?", help="run log_dir or telemetry.jsonl path"
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="synthesize a run and verify writer/reader agreement",
    )
    opts = parser.parse_args(argv)
    if opts.selftest:
        return selftest()
    if not opts.path:
        parser.error("path required (or --selftest)")
    report(opts.path)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `| head` closed the pipe; not an error
        os._exit(0)
