#!/usr/bin/env python
"""sheepopt — ledger-driven auto-optimization over the committed budget
ledgers (ISSUE 11): the advisor-to-actuator step.

The repo carries three committed static ledgers (compute via sheepcheck,
comms via sheepshard, memory via sheepmem) whose findings a human used to
read and hand-fix. This tool closes the loop:

    python tools/sheepopt.py --propose            # actionable proposals
    python tools/sheepopt.py --propose --json     # the CI artifact
    python tools/sheepopt.py --check SPEC         # verify a landed change
    python tools/sheepopt.py --decisions          # the decision cache

`--propose` is STDLIB-ONLY (no jax import — it runs against the committed
`analysis/budget/` files, so the CI job costs seconds) and derives three
proposal classes:

  - **donations** (the SC010 class): per committed jit, undonated inputs
    whose avals byte-match outputs (the `jits` section's in/out avals, the
    `memory` section's donated/alias counts). Known code sites
    (PROPOSAL_SITES) get the EXACT diff to apply; everything else gets the
    donating_jit instruction. Justified refusals (MEM_SUPPRESSIONS
    mirrors) are skipped.
  - **shardings** (the SC007 class): comms entries whose compiled module
    silently replicates large inputs across the mesh — propose declaring
    the sharding in the jit's registered example (the `ppo._gae_example`
    fix shape from PR 8).
  - **remat**: the memory section's live-across-scan buffers ranked by
    bytes x trip count, pointing dreamer-family train steps at
    `--remat auto` (the measured decision, compile/decisions.py) and
    everything else at `jax.checkpoint` on the scan body.

`--check SPEC` re-runs the capture for one spec through all three budget
gates (subprocesses of sheepcheck/sheepshard/sheepmem with the spec
positional) — the receipt that a landed proposal compiles and keeps every
ledger clean. `--decisions` prints the unified decision cache
(`decisions.json` next to the compile cache): per knob family the
candidates tried, the winner, receipt status and bytes/seconds deltas.

Exit codes: 0 ok (proposals are advisory), 1 --check gate failure,
2 usage/ledger error.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from collections import Counter
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "pred": 1,
}

_AVAL_RE = re.compile(r"^([a-z0-9_]+)\[([0-9, ]*)\]$")

# The justified-refusal mirror of analysis/memory_check.MEM_SUPPRESSIONS
# (kept inline so --propose stays stdlib-only): (spec, jit) pairs whose
# donation opportunities are known-unsafe.
DONATION_SKIP = {
    ("ppo_recurrent", "policy_step"),
    ("ppo_recurrent@bf16", "policy_step"),
}

# Known code sites for the donation class, keyed by jit name: the exact
# diff --propose prints. The dreamer-family player_step donation landed in
# ISSUE 11 for dreamer_v1 (its refreshed ledger no longer proposes it);
# the siblings share the identical call shape.
PROPOSAL_SITES = {
    "player_step": {
        "dreamer_v2": "sheeprl_tpu/algos/dreamer_v2/dreamer_v2.py",
        "dreamer_v3": "sheeprl_tpu/algos/dreamer_v3/dreamer_v3.py",
        "dreamer_v3_decoupled": (
            "sheeprl_tpu/algos/dreamer_v3/dreamer_v3_decoupled.py"
        ),
        "p2e_dv1": "sheeprl_tpu/algos/p2e_dv1/p2e_dv1.py",
        "p2e_dv2": "sheeprl_tpu/algos/p2e_dv2/p2e_dv2.py",
        "_diff": (
            "-    player_step = jax.jit(_player_step)\n"
            "+    player_step = donating_jit(_player_step, donate_argnums=(1,))"
        ),
        "_note": (
            "the caller rebinds player_state to the jit's output every "
            "step (dreamer_v1's landed ISSUE-11 donation is the template; "
            "donating_jit keeps the CPU persistent-cache guard)"
        ),
    },
}


def aval_bytes(aval: str) -> int:
    m = _AVAL_RE.match(aval.strip())
    if not m:
        return 0
    n = 1
    for d in m.group(2).split(","):
        if d.strip():
            n *= int(d)
    return n * _DTYPE_BYTES.get(m.group(1), 4)


def budget_dir(explicit: str | None = None) -> str:
    return (
        explicit
        or os.environ.get("SHEEPRL_TPU_BUDGET_DIR")
        or str(_REPO / "analysis" / "budget")
    )


def load_ledger(d: str) -> dict:
    """The committed per-spec ledger files merged by section — a stdlib
    twin of analysis/jaxpr_check.load_budget (which needs the package)."""
    out: dict = {"jits": {}, "comms": {}, "edges": {}, "memory": {}}
    if not os.path.isdir(d):
        raise FileNotFoundError(
            f"no budget ledger dir at {d} (run the sheepcheck/sheepshard/"
            "sheepmem --update-budget sweeps first)"
        )
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json") or name == "_meta.json":
            continue
        with open(os.path.join(d, name), encoding="utf-8") as fh:
            blob = json.load(fh)
        for section in out:
            out[section].update(blob.get(section, {}))
    return out


# ---------------------------------------------------------------------------
# proposers
# ---------------------------------------------------------------------------


def propose_donations(ledger: dict, floor: int = 0) -> list[dict]:
    """SC010's matcher over the committed avals: per jit, the multiset of
    input avals byte-matching output avals, minus the donations already
    declared — every remaining match is a buffer pair one `donate_argnums`
    would collapse. Ranked by candidate bytes."""
    proposals = []
    for key, fp in sorted(ledger.get("jits", {}).items()):
        spec, _, jit = key.partition("/")
        if (spec, jit) in DONATION_SKIP:
            continue
        if int(fp.get("donated", 0)) > 0:
            # already-donated jits are out of scope: the ledger records
            # aval COUNTS, so their residual matches are almost always
            # coincidental shape collisions (a conv kernel aval matching
            # another output of the same shape), not open donations —
            # SC010's var-level greedy matcher owns that precision
            continue
        ins = Counter(fp.get("in_avals", []))
        outs = Counter(fp.get("out_avals", []))
        matched = ins & outs
        open_count = sum(matched.values())
        if open_count <= 0:
            continue
        avals = sorted(matched.elements(), key=aval_bytes, reverse=True)
        candidates = avals[:open_count]
        total = sum(aval_bytes(a) for a in candidates)
        if total < floor:
            continue
        mem = ledger.get("memory", {}).get(key, {})
        site = PROPOSAL_SITES.get(jit, {})
        proposal = {
            "kind": "donation",
            "key": key,
            "open_matches": open_count,
            "candidate_avals": candidates,
            "candidate_bytes": total,
            "realized_aliases": len(mem.get("aliases", [])),
            "advice": (
                f"{open_count} undonated input(s) byte-match outputs "
                f"({total} bytes at the capture avals, scales with the "
                "live batch): donate them if the caller discards its "
                "reference (sheeprl_tpu/utils/jit.py:donating_jit)"
            ),
        }
        if spec in site:
            proposal["file"] = site[spec]
            proposal["diff"] = site["_diff"]
            proposal["note"] = site["_note"]
        proposals.append(proposal)
    proposals.sort(key=lambda p: -p["candidate_bytes"])
    return proposals


def propose_shardings(ledger: dict, floor: int = 1 << 20) -> list[dict]:
    """The SC007 class off the committed comms section: compiled modules
    whose post-SPMD HLO replicates undeclared inputs across a >1-device
    mesh. The fix shape is PR 8's: declare the input's sharding in the
    jit's registered example so the partitioner (and the warm AOT path)
    see the live layout."""
    proposals = []
    for key, fp in sorted(ledger.get("comms", {}).items()):
        replicated = fp.get("replicated_inputs") or []
        if not replicated:
            continue
        rep_bytes = int(fp.get("replicated_bytes", 0))
        if rep_bytes < floor and not replicated:
            continue
        proposals.append({
            "kind": "sharding",
            "key": key,
            "replicated_inputs": replicated,
            "replicated_bytes": rep_bytes,
            "mesh": fp.get("mesh", {}),
            "advice": (
                "declare these inputs' shardings in the jit's registered "
                "example (NamedSharding/PartitionSpec — the "
                "ppo._gae_example fix, PR 8): the partitioner stops "
                "materializing a full copy per device and the warm AOT "
                "executable matches the live layout"
            ),
        })
    proposals.sort(key=lambda p: -p["replicated_bytes"])
    return proposals


def propose_remat(ledger: dict, top: int = 8) -> list[dict]:
    """The memory section's live-across-scan buffers ranked by bytes —
    what `jax.checkpoint` on the scan body would stop keeping live for
    the whole trip count. Dreamer-family train steps point at the
    measured actuator (`--remat auto`); everything else at the manual
    wrap."""
    rows = []
    for key, fp in sorted(ledger.get("memory", {}).items()):
        for buf in fp.get("scan_buffers", []) or []:
            rows.append((int(buf.get("bytes", 0)), key, buf))
    rows.sort(key=lambda r: (-r[0], r[1]))
    proposals = []
    for nbytes, key, buf in rows[:top]:
        spec, _, jit = key.partition("/")
        dreamer = spec.split("@", 1)[0].startswith(("dreamer_", "p2e_"))
        proposals.append({
            "kind": "remat",
            "key": key,
            "buffer": buf.get("shape"),
            "bytes": nbytes,
            "trip_count": buf.get("trip_count"),
            "advice": (
                "run with `--remat auto` — the sheepopt measured decision "
                "trial-compiles the off/policy/on ladder at the run's "
                "exact shapes and accepts on peak-bytes reduction at "
                "<=5% exec-time cost with a bit-exactness receipt"
                if dreamer and jit == "train_step"
                else "wrap the scan body in jax.checkpoint "
                "(ops/scan.py:checkpoint_body) and verify with "
                "compile/decisions.py:decide_remat"
            ),
        })
    return proposals


# ---------------------------------------------------------------------------
# the decision cache (shared with compile/decisions.py, read stdlib-only)
# ---------------------------------------------------------------------------


def decision_cache_path(explicit: str | None = None) -> str:
    if explicit:
        return explicit
    base = (
        os.environ.get("SHEEPRL_TPU_COMPILE_CACHE")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    )
    if not base:
        import tempfile

        uid = getattr(os, "getuid", lambda: "u")()
        base = os.path.join(tempfile.gettempdir(), f"sheeprl_tpu_xla_cache_{uid}")
    return os.path.join(base, "decisions.json")


def load_decisions(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def render_decisions(cache: dict, family: str | None = None) -> list[str]:
    lines = []
    for key, rec in sorted(cache.items()):
        fam = rec.get("family", key.split("|", 1)[0])
        if family and fam != family:
            continue
        if "candidates" in rec:
            winner = rec.get("winner")
            base = rec.get("baseline")
            cands = rec.get("candidates", {})
            wr, br = cands.get(str(winner), {}), cands.get(str(base), {})
            # the quality receipt (ISSUE 20): a non-bit-exact winner that
            # was accepted under a quality bound prints its committed
            # divergence next to the bound it satisfied
            bound = rec.get("quality_bound")
            if wr.get("bit_exact"):
                receipt = "bit-exact"
            elif bound is not None and wr.get("divergence") is not None:
                receipt = (
                    f"divergence {wr['divergence']:.3g} <= bound {bound:g}"
                    if wr.get("within_bound")
                    else f"divergence {wr['divergence']:.3g} > bound {bound:g}"
                )
            elif wr.get("bit_exact") is False:
                receipt = "DISQUALIFIED"
            else:
                receipt = "unmeasured"
            delta = ""
            if wr.get("peak_bytes") is not None and br.get("peak_bytes"):
                delta += f" bytes {wr['peak_bytes'] - br['peak_bytes']:+d}"
            if wr.get("exec_seconds") is not None and br.get("exec_seconds"):
                delta += (
                    f" seconds {wr['exec_seconds'] - br['exec_seconds']:+.4f}"
                )
            lines.append(
                f"[{fam}] {rec.get('name', '?')}: winner={winner} "
                f"(baseline {base}, {len(cands)} candidate(s), {receipt}"
                f"{',' if delta else ''}{delta}) "
                f"{'ACCEPTED' if rec.get('accepted') else 'baseline kept'}"
            )
            if bound is not None:
                for label in sorted(cands):
                    cr = cands[label]
                    if label == str(winner) or cr.get("within_bound") is not False:
                        continue
                    div = cr.get("divergence")
                    lines.append(
                        f"    DISQUALIFIED {label}: divergence "
                        f"{div:.3g} > bound {bound:g}"
                        if div is not None
                        else f"    DISQUALIFIED {label}: "
                        f"{cr.get('error') or 'quality metric failed'}"
                    )
        elif "probe" in rec:
            lines.append(
                f"[{fam}] {rec.get('name', '?')}: measured probe "
                f"({', '.join(sorted(rec['probe']))})"
            )
    return lines


# ---------------------------------------------------------------------------
# --check: one spec through all three budget gates
# ---------------------------------------------------------------------------


def check_spec(spec: str, budget: str | None = None) -> int:
    """Subprocess sheepcheck/sheepshard/sheepmem for `spec` with
    --check-budget. A tool that doesn't know the spec (rc 2 + 'unknown
    specs') is SKIPPED — e.g. sheepshard only sweeps mesh-bearing specs.
    Returns 0 when every applicable gate is clean."""
    rc_total = 0
    for tool in ("sheepcheck", "sheepshard", "sheepmem"):
        cmd = [sys.executable, str(_REPO / "tools" / f"{tool}.py"), spec,
               "--check-budget"]
        if budget:
            cmd += ["--budget", budget]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        out = proc.stdout + proc.stderr
        # sheepcheck says "unknown algos", sheepshard/sheepmem "unknown
        # specs" — either way the spec is outside that tool's population
        if proc.returncode == 2 and ("unknown specs" in out or "unknown algos" in out):
            print(f"{tool}: {spec} not in its sweep population — skipped")
            continue
        tail = [ln for ln in out.strip().splitlines() if ln][-1:]
        print(f"{tool}: rc={proc.returncode} {tail[0] if tail else ''}")
        if proc.returncode != 0:
            sys.stdout.write(out)
            rc_total = 1
    return rc_total


# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--propose", action="store_true",
        help="derive donation/sharding/remat proposals from the committed "
             "ledgers (the default mode; stdlib-only, no jax)",
    )
    ap.add_argument(
        "--check", metavar="SPEC", default=None,
        help="re-run one spec's capture through all three budget gates "
             "(the receipt for a landed proposal)",
    )
    ap.add_argument(
        "--decisions", action="store_true",
        help="print the unified decision cache (winners, receipts, deltas)",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--budget", default=None,
        help="budget ledger dir (default analysis/budget, "
             "SHEEPRL_TPU_BUDGET_DIR honored)",
    )
    ap.add_argument(
        "--family", default=None,
        help="with --decisions: only print records of this knob family "
             "(e.g. serve_quant, serve_ladder, remat)",
    )
    ap.add_argument(
        "--decision-cache", default=None,
        help="decision cache path (default: decisions.json next to the "
             "compile cache)",
    )
    ap.add_argument(
        "--floor", type=int, default=0,
        help="minimum candidate bytes for donation proposals (at the "
             "capture avals; they scale with the live batch)",
    )
    ns = ap.parse_args(argv)

    if ns.check:
        return check_spec(ns.check, ns.budget)

    if ns.decisions:
        cache = load_decisions(decision_cache_path(ns.decision_cache))
        if ns.family:
            cache = {
                k: r for k, r in cache.items()
                if r.get("family", k.split("|", 1)[0]) == ns.family
            }
        if ns.json:
            print(json.dumps(cache, indent=2, sort_keys=True))
        elif not cache:
            print("decision cache empty (no measured decisions yet)")
        else:
            for line in render_decisions(cache, family=ns.family):
                print(line)
        return 0

    # default: --propose
    try:
        ledger = load_ledger(budget_dir(ns.budget))
    except FileNotFoundError as err:
        print(err, file=sys.stderr)
        return 2
    donations = propose_donations(ledger, floor=ns.floor)
    shardings = propose_shardings(ledger)
    remat = propose_remat(ledger)
    if ns.json:
        print(json.dumps({
            "donations": donations,
            "shardings": shardings,
            "remat": remat,
        }, indent=2))
        return 0
    for p in donations:
        print(f"DONATION {p['key']}: {p['advice']}")
        for a in p["candidate_avals"]:
            print(f"    candidate {a} ({aval_bytes(a)} bytes)")
        if "diff" in p:
            print(f"    site: {p['file']}")
            for line in p["diff"].splitlines():
                print(f"    {line}")
            print(f"    note: {p['note']}")
    for p in shardings:
        print(
            f"SHARDING {p['key']}: {p['replicated_bytes']} bytes silently "
            f"replicated across {p.get('mesh')} — {p['advice']}"
        )
        for inp in p["replicated_inputs"]:
            print(f"    replicated {inp}")
    for p in remat:
        trip = f"x{p['trip_count']}" if p.get("trip_count") else "unknown trips"
        print(
            f"REMAT {p['key']}: {p['buffer']} ({p['bytes']} bytes, {trip}) "
            f"live across a scan — {p['advice']}"
        )
    print(
        f"sheepopt: {len(donations)} donation, {len(shardings)} sharding, "
        f"{len(remat)} remat proposal(s)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
