#!/usr/bin/env python
"""sheepmem — static memory & buffer-lifetime analysis over the compiled
plan (ISSUE 10), with the CI-gated HBM budget.

Usage:
    python tools/sheepmem.py                      # the full sweep
    python tools/sheepmem.py sac_ae dreamer_v3    # a subset
    python tools/sheepmem.py --list-rules
    python tools/sheepmem.py --update-budget      # refresh memory sections
    python tools/sheepmem.py --check-budget       # the CI HBM drift gate
    python tools/sheepmem.py --remat              # the remat advisor
    python tools/sheepmem.py --rules SC011,SC012 --json

The sweep re-runs the sheepcheck/sheepshard shape capture over the FULL
population — all 13 mains at their CAPTURE_ARGV, every `@bf16`/Anakin
CAPTURE_VARIANT, and the mesh-bearing SHARD_SWEEP specs (whose mesh argv
wins on name collision: the per-shard peak is the TPU-relevant quantity) —
then `lower().compile()`s every registered jit (CPU virtual mesh, zero
execution) and reads two sources off the executable: XLA's own
`memory_analysis()` (peak/temp/argument/output/generated-code bytes) and
the post-optimization HLO (realized input_output_alias table, embedded
array constants, live-across-scan buffers with known trip counts — the
remat advisor's input). Rules SC010-SC013 (catalog:
sheeprl_tpu/analysis/memory_check.py + howto/static_analysis.md) ride the
sweep; fingerprints live in the committed `analysis/budget/` ledger
(section `memory`, next to `jits`/`comms`/`edges`); `--check-budget`
fails CI on unexplained drift: peak growth >25%, lost realized aliases,
new large embedded constants, per-shard peaks over the HBM budget, or a
`@bf16` variant whose full-width activation bytes do not undercut its f32
twin (the byte-level receipt of the ISSUE-9 mixed-precision contract).

Exit codes: 0 clean, 1 findings or budget drift, 2 capture/usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent

# Same preamble as tools/sheepcheck.py / sheepshard.py: the memory ledger is
# derived on the CPU virtual 8-device harness by design, so re-exec once
# with the virtual-device flag before anything imports jax.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""  # skip the axon tunnel plugin
    os.execv(sys.executable, [sys.executable, *sys.argv])

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, str(_REPO))

from sheeprl_tpu.analysis import jaxpr_check as jc  # noqa: E402
from sheeprl_tpu.analysis import memory_check as mc  # noqa: E402

DEFAULT_BUDGET = str(_REPO / "analysis" / "budget.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "specs", nargs="*",
        help="capture specs to sweep (default: mains + variants + mesh specs)",
    )
    ap.add_argument("--rules", default=None, help="comma-separated SC rule ids")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--budget", default=DEFAULT_BUDGET,
        help=f"budget ledger path (default {DEFAULT_BUDGET}; the "
             "analysis/budget/ dir layout is preferred when present)",
    )
    ap.add_argument(
        "--update-budget", action="store_true",
        help="write the derived memory fingerprints to the ledger",
    )
    ap.add_argument(
        "--check-budget", action="store_true",
        help="fail on unexplained memory drift vs the ledger (the CI gate)",
    )
    ap.add_argument(
        "--remat", action="store_true",
        help="print the remat advisor: the largest live-across-scan buffers",
    )
    ap.add_argument(
        "--root-dir", default=None,
        help="where capture runs write their (throwaway) run dirs",
    )
    ap.add_argument("--verbose", action="store_true")
    ns = ap.parse_args(argv)

    if ns.list_rules:
        for rule in mc.MEM_RULES.values():
            print(f"{rule.id} ({rule.name}) [{rule.severity}]")
            print(f"    {rule.summary}")
            print(f"    fix: {rule.autofix}")
        return 0

    rules = None
    if ns.rules:
        rules = {s.strip().upper() for s in ns.rules.split(",") if s.strip()}
        unknown = rules - set(mc.MEM_RULES)
        if unknown:
            print(f"unknown rule ids: {sorted(unknown)}", file=sys.stderr)
            return 2

    import sheeprl_tpu.algos  # noqa: F401 — fire registrations
    from sheeprl_tpu.utils.registry import tasks
    from sheeprl_tpu.analysis import shard_check as sc

    specs = ns.specs or mc.memory_sweep_specs()
    unknown = {
        s for s in specs
        if s not in tasks
        and s not in jc.CAPTURE_VARIANTS
        and s not in sc.SHARD_SWEEP
        and s not in mc.MEM_VARIANTS
    }
    if unknown:
        print(f"unknown specs: {sorted(unknown)}", file=sys.stderr)
        return 2

    root = ns.root_dir or tempfile.mkdtemp(prefix="sheepmem_")
    reports: list[mc.MemReport] = []
    capture_errors = 0
    for spec in specs:
        algo, extra_argv = mc.resolve_capture(spec)
        t0 = time.perf_counter()
        try:
            plan = jc.capture_plan(algo, root, extra_argv=extra_argv)
        except BaseException as err:  # CaptureComplete is consumed inside
            if isinstance(err, (KeyboardInterrupt, SystemExit)):
                raise
            print(f"{spec}: CAPTURE FAILED: {type(err).__name__}: {err}",
                  file=sys.stderr)
            capture_errors += 1
            continue
        spec_reports = mc.analyze_mem_plan(spec, plan, rules=rules)
        reports.extend(spec_reports)
        analyzed = [r for r in spec_reports if r.memory is not None]
        peak = max((r.memory["peak_bytes"] for r in analyzed), default=0)
        print(
            f"{spec}: {len(analyzed)}/{len(spec_reports)} jits compiled, "
            f"max peak {peak} bytes, "
            f"{sum(len(r.failing) for r in spec_reports)} finding(s) "
            f"[{time.perf_counter() - t0:.1f}s]",
            file=sys.stderr,
        )
        if ns.verbose:
            for r in spec_reports:
                if r.error:
                    print(f"  {r.name}: skipped ({r.error})", file=sys.stderr)
                elif r.memory is not None:
                    m = r.memory
                    print(
                        f"  {r.name}: peak={m['peak_bytes']} "
                        f"temp={m['temp_bytes']} args={m['argument_bytes']} "
                        f"aliases={len(m['aliases'])}/{m['donated']} "
                        f"const={m['constant_bytes']}",
                        file=sys.stderr,
                    )

    all_findings = [f for r in reports for f in r.findings]
    failing = [f for f in all_findings if not f.suppressed]
    suppressed = [f for f in all_findings if f.suppressed]

    budget_failures: list[str] = []
    budget_notes: list[str] = []
    derived = mc.build_memory_budget(reports)
    if ns.update_budget:
        if ns.specs and jc.budget_exists(ns.budget):
            # partial refresh: replace only the captured specs' entries
            ledger = jc.load_budget(ns.budget)
            prefixes = tuple(f"{s}/" for s in specs)
            merged = {
                k: v
                for k, v in ledger.get("memory", {}).items()
                if not k.startswith(prefixes)
            }
            merged.update(derived["memory"])
            derived = {**ledger, **derived, "memory": merged}
        jc.save_budget(derived, ns.budget, sections=("memory",))
        print(
            f"wrote {len(derived['memory'])} memory fingerprints to "
            f"{jc.budget_dir_of(ns.budget)}",
            file=sys.stderr,
        )
    elif ns.check_budget:
        if not jc.budget_exists(ns.budget):
            print(f"no ledger at {ns.budget} (run --update-budget first)",
                  file=sys.stderr)
            return 2
        ledger = jc.load_budget(ns.budget)
        if ns.specs:
            # partial capture: gate only the captured specs' entries
            prefixes = tuple(f"{s}/" for s in specs)
            ledger = {
                **ledger,
                "memory": {
                    k: v for k, v in ledger.get("memory", {}).items()
                    if k.startswith(prefixes)
                },
            }
        budget_failures, budget_notes = mc.check_memory_budget(ledger, derived)

    remat = mc.remat_advice(derived["memory"]) if ns.remat else []

    if ns.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in failing],
            "suppressed": [f.as_dict() for f in suppressed],
            "budget_failures": budget_failures,
            "budget_notes": budget_notes,
            "capture_errors": capture_errors,
            "remat": remat,
            "memory": derived["memory"],
        }, indent=2))
    else:
        for f in failing:
            print(f.format())
        if ns.verbose:
            for f in suppressed:
                print(f.format())
        for line in remat:
            print(f"remat: {line}")
        for note in budget_notes:
            print(f"memory note: {note}", file=sys.stderr)
        for failure in budget_failures:
            print(f"MEMORY DRIFT: {failure}")

    if capture_errors:
        return 2
    if failing or budget_failures:
        print(
            f"sheepmem: {len(failing)} finding(s), {len(suppressed)} "
            f"suppressed, {len(budget_failures)} memory drift(s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"sheepmem: clean ({len(derived['memory'])} jits fingerprinted, "
        f"{len(suppressed)} suppressed finding(s))",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
