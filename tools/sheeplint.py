#!/usr/bin/env python
"""sheeplint — static JAX/TPU hazard linter for this repo (ISSUE 3).

Usage:
    python tools/sheeplint.py sheeprl_tpu/ tools/ bench.py
    python tools/sheeplint.py --list-rules
    python tools/sheeplint.py --select SL001,SL002 sheeprl_tpu/
    python tools/sheeplint.py --format json sheeprl_tpu/ | jq .

Exit codes: 0 clean, 1 violations found, 2 usage/parse error.

The rule catalog, severities, and suppression syntax
(`# sheeplint: disable=SL002 — why`) live in sheeprl_tpu/analysis/rules.py
and howto/static_analysis.md. CI runs this over `sheeprl_tpu/ tools/
bench.py` and fails the build on any new violation.

Pure AST analysis: no jax import, no module execution — safe to run
anywhere, including pre-commit.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from sheeprl_tpu.analysis.linter import lint_file, iter_python_files  # noqa: E402
from sheeprl_tpu.analysis.rules import RULES  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument("--format", choices=["text", "json"], default="text")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--statistics", action="store_true",
        help="print a per-rule violation count summary",
    )
    ns = ap.parse_args(argv)

    if ns.list_rules:
        for rule in RULES.values():
            print(f"{rule.id} ({rule.name}) [{rule.severity}]")
            print(f"    {rule.summary}")
            print(f"    fix: {rule.autofix}")
        return 0
    if not ns.paths:
        ap.error("no paths given (or use --list-rules)")

    select = None
    if ns.select:
        select = {s.strip().upper() for s in ns.select.split(",") if s.strip()}
        unknown = select - set(RULES)
        if unknown:
            print(f"unknown rule ids: {sorted(unknown)}", file=sys.stderr)
            return 2

    violations = []
    parse_errors = 0
    for path in iter_python_files(ns.paths):
        try:
            violations.extend(lint_file(path, select=select))
        except ValueError as exc:
            print(str(exc), file=sys.stderr)
            parse_errors += 1

    if ns.format == "json":
        print(json.dumps([v.as_dict() for v in violations], indent=2))
    else:
        for v in violations:
            print(v.format())
    if ns.statistics:
        counts = Counter(v.rule.id for v in violations)
        for rid in sorted(counts):
            print(f"{rid}: {counts[rid]}", file=sys.stderr)
    if parse_errors:
        return 2
    if violations:
        n_err = sum(1 for v in violations if v.rule.severity == "error")
        print(
            f"sheeplint: {len(violations)} violation(s) "
            f"({n_err} error, {len(violations) - n_err} warning)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
