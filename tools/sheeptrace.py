#!/usr/bin/env python
"""Cross-process trace reconstruction from sheepscope telemetry shards.

    python tools/sheeptrace.py <log_dir-or-shard.jsonl>
    python tools/sheeptrace.py <log_dir> --assert-chain
    python tools/sheeptrace.py --selftest

A flock/serve run writes one telemetry shard per role into the shared run
directory (sheeprl_tpu/telemetry/, ISSUE 17): `telemetry.jsonl` for the
learner, `telemetry.actor{N}.jsonl` per flock actor, `telemetry.serve.jsonl`
for the serving tier — all keyed by one run id. Span events inside each
shard carry compact ids that also rode the FLK1 frame meta, so the chains
cross process boundaries:

    collect -> push -> ingest -> drain -> train -> publish -> (next collect)

This tool merges the shards onto ONE timeline (actor wall clocks are
corrected by the NTP-style offsets the heartbeat round-trips estimated,
recorded as `trace.clock` events; the learner is the reference clock),
reconstructs the span chains by walking parent links, and prints:

  - a shard table: role, events, spans, best clock offset + its RTT bound;
  - the end-to-end chains with a per-hop critical-path decomposition
    (collect / push / wire+queue / train / publish) and per-row weight
    staleness attribution;
  - per-update drain-wait attribution by actor — which actor's chunks the
    learner spent its drain budget waiting on (the straggler table);
  - the serve request decomposition: queue-wait / pad / dispatch(compute) /
    slice / send percentiles per outcome.

Pure stdlib (no jax, no repo imports except the selftest's Telemetry), so
it runs anywhere the JSONL shards can be copied to. `--assert-chain` exits
non-zero unless at least one COMPLETE collect->...->publish chain is
reconstructed — the CI trace-smoke gate. `--selftest` synthesizes skewed
shards through the real Telemetry writer and asserts the merge undoes the
skew and the chains survive — writer and this reader staying in sync.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

# parent-walk order, publish-first; a COMPLETE chain reverses to
# collect -> push -> ingest -> drain -> train -> publish
CHAIN = ("publish", "train", "drain", "ingest", "push", "collect")

SERVE_PHASES = ("queue_ms", "pad_ms", "dispatch_ms", "slice_ms", "send_ms")


# ---------------------------------------------------------------------------
# loading + clock merge
# ---------------------------------------------------------------------------


def _parse_jsonl(path: str) -> list[dict]:
    """One shard's events; tolerates a truncated final line (crash)."""
    events = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                break
    return events


def _shard_role(path: str) -> str:
    """telemetry.jsonl -> learner; telemetry.<role>.jsonl -> role."""
    name = os.path.basename(path)
    parts = name.split(".")
    return parts[1] if len(parts) == 3 else "learner"


def load_shards(path: str) -> dict[str, list[dict]]:
    """role -> event list, from a run dir (all telemetry*.jsonl in it) or a
    single shard file."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "telemetry*.jsonl")))
        if not files:
            raise FileNotFoundError(
                f"no telemetry*.jsonl shards under {path} — did the run "
                "write telemetry? (SHEEPRL_TPU_TELEMETRY=0 disables)"
            )
    elif os.path.exists(path):
        files = [path]
    else:
        raise FileNotFoundError(path)
    return {_shard_role(f): _parse_jsonl(f) for f in files}


def best_offset(events: list[dict]) -> tuple[float, float | None, int]:
    """(offset_s, rtt_s, samples) from the shard's LAST trace.clock event —
    ClockSync only re-emits on a minimum-RTT improvement, so the last one
    is the best estimate. Shards without one (the learner itself, serve)
    are their own reference: offset 0."""
    offset, rtt, samples = 0.0, None, 0
    for ev in events:
        if ev.get("event") == "trace.clock":
            offset = float(ev.get("offset_s") or 0.0)
            rtt = ev.get("rtt_s")
            samples = int(ev.get("samples") or 0)
    return offset, rtt, samples


def collect_spans(shards: dict[str, list[dict]]) -> list[dict]:
    """Every span event across all shards, t0/t1 shifted onto the learner
    clock by the shard's offset, tagged with its role."""
    spans = []
    for role, events in shards.items():
        offset, _, _ = best_offset(events)
        for ev in events:
            if ev.get("event") != "span" or ev.get("span") is None:
                continue
            span = dict(ev)
            span["role"] = role
            for key in ("t0", "t1"):
                if isinstance(span.get(key), (int, float)):
                    span[key] = span[key] + offset
            spans.append(span)
    spans.sort(key=lambda s: s.get("t0") or 0.0)
    return spans


# ---------------------------------------------------------------------------
# chain reconstruction
# ---------------------------------------------------------------------------


def walk_chain(pub: dict, index: dict[str, dict]) -> list[dict]:
    """From a publish span, walk parent links down the expected chain.
    Returns collect-first; complete iff len == len(CHAIN)."""
    path = [pub]
    cur = pub
    for expected in CHAIN[1:]:
        parent = index.get(cur.get("parent") or "")
        if parent is None or parent.get("name") != expected:
            break
        path.append(parent)
        cur = parent
    path.reverse()
    return path


def reconstruct(spans: list[dict]) -> tuple[list[list[dict]], list[list[dict]]]:
    """(complete, partial) chains, one per publish span."""
    index = {s["span"]: s for s in spans}
    complete, partial = [], []
    for s in spans:
        if s.get("name") != "publish":
            continue
        chain = walk_chain(s, index)
        (complete if len(chain) == len(CHAIN) else partial).append(chain)
    return complete, partial


def summarize(shards: dict[str, list[dict]]) -> dict:
    spans = collect_spans(shards)
    complete, partial = reconstruct(spans)
    by_name: dict[str, list[dict]] = {}
    for s in spans:
        by_name.setdefault(str(s.get("name")), []).append(s)
    return {
        "shards": {
            role: {
                "events": len(events),
                "spans": sum(1 for e in events if e.get("event") == "span"),
                "offset": best_offset(events),
                "run": next(
                    (e.get("run") for e in events if e.get("event") == "start"),
                    None,
                ),
            }
            for role, events in shards.items()
        },
        "spans": spans,
        "by_name": by_name,
        "complete": complete,
        "partial": partial,
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_row(cols, widths):
    return "  ".join(str(c).ljust(w) for c, w in zip(cols, widths)).rstrip()


def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(len(sorted_vals) * q))]


def _chain_row(chain: list[dict]) -> dict:
    """Critical-path decomposition of one COMPLETE chain (collect-first,
    already on the merged clock)."""
    named = {s["name"]: s for s in chain}
    collect, push = named["collect"], named["push"]
    ingest, drain = named["ingest"], named["drain"]
    train, publish = named["train"], named["publish"]
    ms = lambda s: float(s.get("dur_ms") or 0.0)  # noqa: E731
    return {
        "actor": drain.get("actor", collect.get("actor")),
        "e2e_ms": round((publish["t1"] - collect["t0"]) * 1e3, 3),
        "collect_ms": ms(collect),
        "push_ms": ms(push),
        # push send -> learner drain pickup: wire transit + shard residency;
        # clock-corrected, so a wildly negative value means a bad offset
        "wire_queue_ms": round((drain["t1"] - push["t0"]) * 1e3, 3),
        "queued_ms": float(drain.get("queued_ms") or 0.0),
        "train_ms": ms(train),
        "publish_ms": ms(publish),
        "staleness": train.get("staleness_versions"),
        "version": publish.get("version"),
        "ingest_id": ingest.get("span"),
    }


def render(summary: dict, max_chains: int = 8) -> str:
    lines: list[str] = []
    lines.append("== telemetry shards ==")
    widths = (10, 8, 7, 12, 10, 8)
    lines.append(
        _fmt_row(("role", "events", "spans", "clock_offset", "rtt_ms", "run"), widths)
    )
    for role in sorted(summary["shards"]):
        info = summary["shards"][role]
        offset, rtt, samples = info["offset"]
        lines.append(_fmt_row(
            (
                role,
                info["events"],
                info["spans"],
                "reference" if samples == 0 and offset == 0.0 else f"{offset:+.3f}s",
                "-" if rtt is None else f"{float(rtt) * 1e3:.1f}",
                (info["run"] or "?")[:8],
            ),
            widths,
        ))

    complete, partial = summary["complete"], summary["partial"]
    lines.append("")
    lines.append("== span chains (collect -> push -> ingest -> drain -> train -> publish) ==")
    lines.append(
        f"complete={len(complete)} partial={len(partial)} "
        f"spans_total={len(summary['spans'])}"
    )
    rows = [_chain_row(c) for c in complete]
    if rows:
        widths = (6, 9, 11, 9, 11, 10, 9, 11, 6)
        lines.append(_fmt_row(
            ("actor", "e2e_ms", "collect_ms", "push_ms", "wire+q_ms",
             "queued_ms", "train_ms", "publish_ms", "stale"),
            widths,
        ))
        for r in rows[:max_chains]:
            lines.append(_fmt_row(
                (
                    r["actor"], f"{r['e2e_ms']:.1f}", f"{r['collect_ms']:.1f}",
                    f"{r['push_ms']:.1f}", f"{r['wire_queue_ms']:.1f}",
                    f"{r['queued_ms']:.1f}", f"{r['train_ms']:.1f}",
                    f"{r['publish_ms']:.1f}",
                    "-" if r["staleness"] is None else r["staleness"],
                ),
                widths,
            ))
        if len(rows) > max_chains:
            lines.append(f"... {len(rows) - max_chains} more chain(s)")
        # critical path: which hop dominates the mean end-to-end latency
        hops = ("collect_ms", "push_ms", "wire_queue_ms", "train_ms", "publish_ms")
        means = {h: sum(r[h] for r in rows) / len(rows) for h in hops}
        top = max(means, key=means.get)
        lines.append(
            "critical path (mean): "
            + " ".join(f"{h[:-3]}={v:.1f}ms" for h, v in means.items())
            + f" <- dominated by {top[:-3]}"
        )
    elif partial:
        # name the break point so a broken chain is debuggable from CI logs
        longest = max(partial, key=len)
        lines.append(
            "NO complete chain; longest partial: "
            + " -> ".join(str(s.get("name")) for s in longest)
        )

    # per-update drain-wait attribution: which actor the learner waited on
    drains = summary["by_name"].get("drain", [])
    if drains:
        lines.append("")
        lines.append("== drain-wait attribution (per actor) ==")
        per: dict = {}
        for d in drains:
            per.setdefault(str(d.get("actor")), []).append(
                (float(d.get("dur_ms") or 0.0), float(d.get("queued_ms") or 0.0))
            )
        widths = (8, 8, 12, 12, 12)
        lines.append(_fmt_row(
            ("actor", "drains", "wait_total", "wait_mean", "queued_mean"), widths
        ))
        for actor in sorted(per):
            waits = [w for w, _ in per[actor]]
            queued = [q for _, q in per[actor]]
            lines.append(_fmt_row(
                (
                    actor, len(waits), f"{sum(waits):.1f}ms",
                    f"{sum(waits) / len(waits):.1f}ms",
                    f"{sum(queued) / len(queued):.1f}ms",
                ),
                widths,
            ))
        slowest = max(per, key=lambda a: sum(w for w, _ in per[a]))
        lines.append(f"straggler: actor {slowest} (largest total drain wait)")

    # per-row weight-version staleness attribution from the train spans
    stale = [
        int(s["staleness_versions"])
        for s in summary["by_name"].get("train", [])
        if s.get("staleness_versions") is not None
    ]
    if stale:
        s = sorted(stale)
        lines.append("")
        lines.append("== trained-row weight staleness (versions behind at train time) ==")
        lines.append(
            f"updates={len(s)} min={s[0]} p50={s[len(s) // 2]} "
            f"p90={_pct(s, 0.9)} max={s[-1]}"
        )

    # serve request decomposition
    requests = summary["by_name"].get("request", [])
    if requests:
        lines.append("")
        lines.append("== serve request decomposition ==")
        outcomes: dict = {}
        for r in requests:
            outcomes[str(r.get("outcome"))] = outcomes.get(str(r.get("outcome")), 0) + 1
        lines.append(
            "outcomes: "
            + " ".join(f"{k}={v}" for k, v in sorted(outcomes.items()))
        )
        served = [r for r in requests if r.get("outcome") == "served"]
        if served:
            widths = (12, 10, 10, 10)
            lines.append(_fmt_row(("phase", "mean_ms", "p50_ms", "p99_ms"), widths))
            for phase in SERVE_PHASES:
                vals = sorted(
                    float(r.get(phase) or 0.0) for r in served
                )
                lines.append(_fmt_row(
                    (
                        phase[:-3], f"{sum(vals) / len(vals):.3f}",
                        f"{_pct(vals, 0.5):.3f}", f"{_pct(vals, 0.99):.3f}",
                    ),
                    widths,
                ))
            total = [
                sum(float(r.get(p) or 0.0) for p in SERVE_PHASES) for r in served
            ]
            lines.append(
                f"served={len(served)} mean_total={sum(total) / len(total):.3f}ms"
            )
    return "\n".join(lines)


def report(path: str, max_chains: int = 8) -> dict:
    """Load + merge + print; returns the summary (tests + CI use it)."""
    summary = summarize(load_shards(path))
    print(render(summary, max_chains=max_chains))
    return summary


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------


def selftest() -> int:
    """Synthesize a skewed 3-shard run through the REAL Telemetry writer,
    then assert the merge undoes the skew and the chain survives."""
    import tempfile

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from sheeprl_tpu.telemetry import Telemetry

    d = tempfile.mkdtemp(prefix="sheeptrace_selftest_")
    run = "selftest-run"

    # learner shard: the reference clock. One full update's learner-side
    # spans, parented across the process boundary by the actor's span ids.
    learner = Telemetry(d, rank=0, algo="selftest", role="learner", run_id=run)
    learner.event("start", algo="selftest", role="learner", run=run)
    learner.event(  # seed publish the actor's collect parents on
        "span", name="publish", span="aaaa0001", parent=None,
        t0=1000.00, t1=1000.01, dur_ms=10.0, version=1,
    )
    learner.event(
        "span", name="ingest", span="cccc0001", parent="bbbb0002",
        t0=1000.280, t1=1000.285, dur_ms=5.0, actor=0, rows=4, weight_version=1,
    )
    learner.event(
        "span", name="drain", span="cccc0002", parent="cccc0001",
        t0=1000.30, t1=1000.31, dur_ms=10.0, actor=0, queued_ms=20.0,
        weight_version=1, update=1,
    )
    learner.event(
        "span", name="train", span="cccc0003", parent="cccc0002",
        t0=1000.31, t1=1000.50, dur_ms=190.0, staleness_versions=1, update=1,
    )
    learner.event(
        "span", name="publish", span="cccc0004", parent="cccc0003",
        t0=1000.50, t1=1000.51, dur_ms=10.0, version=2,
    )
    learner.close()

    # actor shard: wall clock 5s AHEAD of the learner, so its recorded
    # offset (server - local midpoint) is -5s and the raw span times are
    # nonsense until merged.
    actor = Telemetry(d, rank=0, algo="selftest", role="actor0", run_id=run)
    actor.event("start", algo="selftest", role="actor0", run=run)
    actor.event("trace.clock", offset_s=-5.0, rtt_s=0.004, samples=3)
    actor.event(
        "span", name="collect", span="bbbb0001", parent="aaaa0001",
        t0=1005.05, t1=1005.25, dur_ms=200.0, actor=0, rows=4, weight_version=1,
    )
    actor.event(
        "span", name="push", span="bbbb0002", parent="bbbb0001",
        t0=1005.25, t1=1005.27, dur_ms=20.0, actor=0, rows=4,
    )
    actor.close()

    # serve shard: two requests, one served with the full decomposition,
    # one shed — parented on client span ids no shard contains (normal:
    # clients are other processes entirely)
    serve = Telemetry(d, rank=0, algo="serve", role="serve", run_id=run)
    serve.event("start", algo="serve", role="serve", run=run)
    serve.event(
        "span", name="request", span="dddd0001", parent="eeee0001",
        t0=1000.60, t1=1000.61, dur_ms=5.2, id="c1-1", outcome="served",
        version=2, rung=4, rows=2, queue_ms=1.5, pad_ms=0.2,
        dispatch_ms=3.0, slice_ms=0.1, send_ms=0.4,
    )
    serve.event(
        "span", name="request", span="dddd0002", parent="eeee0002",
        t0=1000.62, t1=1000.63, dur_ms=0.3, id="c1-2", outcome="shed",
        reason="deadline",
    )
    serve.close()

    shards = load_shards(d)
    assert set(shards) == {"learner", "actor0", "serve"}, sorted(shards)
    assert best_offset(shards["actor0"]) == (-5.0, 0.004, 3)
    assert best_offset(shards["learner"]) == (0.0, None, 0)

    summary = summarize(shards)
    # the merge puts the actor's collect right after the seed publish
    collect = summary["by_name"]["collect"][0]
    assert abs(collect["t0"] - 1000.05) < 1e-6, collect["t0"]
    # one complete cross-process chain, one partial (the seed publish)
    assert len(summary["complete"]) == 1, summary["partial"]
    assert len(summary["partial"]) == 1
    names = [s["name"] for s in summary["complete"][0]]
    assert names == list(reversed(CHAIN)), names
    roles = [s["role"] for s in summary["complete"][0]]
    assert roles[:2] == ["actor0", "actor0"] and roles[2:] == ["learner"] * 4

    row = _chain_row(summary["complete"][0])
    assert row["actor"] == 0 and row["staleness"] == 1 and row["version"] == 2
    # e2e on the MERGED clock: collect t0 1000.05 -> publish t1 1000.51
    assert abs(row["e2e_ms"] - 460.0) < 1e-6, row["e2e_ms"]
    assert abs(row["wire_queue_ms"] - 60.0) < 1e-6, row["wire_queue_ms"]

    out = render(summary)
    assert "-5.000s" in out and "reference" in out, out
    assert "complete=1 partial=1" in out, out
    assert "straggler: actor 0" in out, out
    assert "updates=1 min=1 p50=1" in out, out
    assert "served=1 shed=1" in out, out
    assert "dispatch" in out and "queue" in out, out
    assert "critical path (mean):" in out, out

    # --assert-chain: the gate passes here, fails on a chain-less dir
    assert main([d, "--assert-chain"]) == 0
    d2 = tempfile.mkdtemp(prefix="sheeptrace_selftest_broken_")
    broken = Telemetry(d2, rank=0, algo="selftest", role="learner", run_id=run)
    broken.event(
        "span", name="publish", span="aaaa0001", parent="gone",
        t0=1.0, t1=1.1, dur_ms=100.0, version=1,
    )
    broken.close()
    assert main([d2, "--assert-chain"]) == 1

    print("\nselftest OK", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "path", nargs="?", help="run log_dir or a single telemetry shard"
    )
    parser.add_argument(
        "--assert-chain", action="store_true",
        help="exit 1 unless >=1 complete collect->...->publish chain",
    )
    parser.add_argument(
        "--max-chains", type=int, default=8,
        help="chains printed in full (default 8)",
    )
    parser.add_argument(
        "--selftest", action="store_true",
        help="synthesize skewed shards and verify writer/reader agreement",
    )
    opts = parser.parse_args(argv)
    if opts.selftest:
        return selftest()
    if not opts.path:
        parser.error("path required (or --selftest)")
    summary = report(opts.path, max_chains=opts.max_chains)
    if opts.assert_chain and not summary["complete"]:
        print(
            "ASSERT-CHAIN FAILED: no complete "
            "collect->push->ingest->drain->train->publish chain",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `| head` closed the pipe; not an error
        os._exit(0)
