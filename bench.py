"""Benchmark entry: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}.

Flagship benchmark (default): **DreamerV3** at its published model scale
(dense 512, cnn multiplier 32, recurrent 512, 32x32 discrete latent,
T=64 x B=16 sequences) on a 64x64 pixel workload — the BASELINE.md
north-star shape (config 4/5) with the host env-step cost removed. Metric is
env-steps/sec/chip, the reference's `Time/step_per_second`
(/root/reference/sheeprl/algos/dreamer_v3/dreamer_v3.py:675).

The one JSON line carries four measurements (VERDICT r1 #4/#5 receipts):
  - value / duty_cycle_sps: the jitted policy-step + single-jit update duty
    cycle at train_every=5, one fixed device-resident batch (device pipeline
    only), with the best of kernels-on/off x f32/bf16;
  - pallas_on_sps / pallas_off_sps: the same cycle with the Pallas kernel
    pass (LayerNorm-GRU cell, two-hot log-prob) enabled / disabled — the
    kernel-keep decision is made from these numbers at runtime;
  - bf16_sps: the same cycle under --precision bfloat16 on the winning
    kernel config; bf16_kept records whether it beat f32 (the e2e run then
    uses the winning precision);
  - e2e_sps: the honest end-to-end loop — AsyncReplayBuffer.add every env
    step, rb.sample -> uint8 preservation/float cast -> host->device
    transfer -> train step — i.e. everything the framework owns including
    the replay pipeline; only gym env stepping is excluded.

Baseline denominator: the reference (torch) is not runnable in this image
(no lightning/tensordict) and publishes no numbers (BASELINE.md), so
vs_baseline is the ratio against THIS framework's round-1 first measurement
(self-improvement, not A100 parity — recorded in baseline_note).

`python bench.py --algo ppo` runs the PPO/CartPole end-to-end bench
(BASELINE.md config 1); `--algo ppo_decoupled` compares coupled vs
overlapped-decoupled PPO on a >=2-device mesh (VERDICT r1 #6 receipt);
`--tiny` shrinks the DreamerV3 model for CPU smoke runs.
"""

from __future__ import annotations

import json
import sys
import time

# round-1 reference points for vs_baseline (see module docstring)
DV3_REFERENCE_SPS = 139.1  # round-1 measurement on the round-1 chip
PPO_CPU_REFERENCE_SPS = 610.0  # round-1 CPU measurement
BASELINE_NOTE = (
    "vs_baseline is vs this framework's round-1 first measurement on the "
    "same benchmark (the torch reference is not runnable here and publishes "
    "no numbers)"
)


def _dv3_setup(tiny: bool):
    import jax
    import numpy as np

    from sheeprl_tpu import ops
    from sheeprl_tpu.algos.dreamer_v3.agent import build_models
    from sheeprl_tpu.algos.dreamer_v3.args import DreamerV3Args
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import (
        DV3TrainState,
        make_optimizers,
    )

    args = DreamerV3Args(num_envs=4, env_id="dummy")
    args.cnn_keys, args.mlp_keys = ["rgb"], []
    if tiny:  # smoke-test mode for CPU runs
        args.dense_units = 16
        args.hidden_size = 16
        args.recurrent_state_size = 16
        args.cnn_channels_multiplier = 4
        args.stochastic_size = 4
        args.discrete_size = 4
        args.per_rank_batch_size = 2
        args.per_rank_sequence_length = 8
        args.horizon = 4
        args.mlp_layers = 1

    actions_dim, is_continuous = [6], False
    obs_space = {"rgb": type("S", (), {"shape": (64, 64, 3)})()}
    key = jax.random.PRNGKey(0)
    world_model, actor, critic, target_critic = build_models(
        key, actions_dim, is_continuous, args, obs_space, ["rgb"], []
    )
    world_opt, actor_opt, critic_opt = make_optimizers(args)
    state = DV3TrainState(
        world_model=world_model,
        actor=actor,
        critic=critic,
        target_critic=target_critic,
        world_opt=world_opt.init(world_model),
        actor_opt=actor_opt.init(actor),
        critic_opt=critic_opt.init(critic),
        moments=ops.Moments.init(args.moments_decay, args.moment_max),
    )
    opts = (world_opt, actor_opt, critic_opt)
    return args, state, opts, actions_dim, is_continuous


def _dv3_player_fns(args, actions_dim, is_continuous):
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3

    def make_player(st):
        return PlayerDV3(
            encoder=st.world_model.encoder,
            rssm=st.world_model.rssm,
            actor=st.actor,
            actions_dim=tuple(actions_dim),
            stochastic_size=args.stochastic_size,
            discrete_size=args.discrete_size,
            recurrent_state_size=args.recurrent_state_size,
            is_continuous=is_continuous,
            compute_dtype=args.precision,
        )

    player_step = jax.jit(lambda p, s, o, k: p.step(s, o, k, jnp.float32(0.0)))
    return make_player, player_step


def _dv3_duty_cycle_sps(args, state, opts, actions_dim, is_continuous, tiny):
    """Device-only duty cycle: train_every jitted policy steps + one update
    on a fixed pre-staged batch (replay pipeline excluded)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step

    T, B = args.per_rank_sequence_length, args.per_rank_batch_size
    world_opt, actor_opt, critic_opt = opts
    train_step = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], actions_dim, is_continuous
    )
    make_player, player_step = _dv3_player_fns(args, actions_dim, is_continuous)
    player_state = make_player(state).init_states(args.num_envs)

    rng = np.random.default_rng(0)
    sample_batch = {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 64, 64, 3), dtype=np.uint8)),
        "actions": jnp.asarray(
            np.eye(6, dtype=np.float32)[rng.integers(0, 6, (T, B))]
        ),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "dones": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    obs = {
        "rgb": jnp.asarray(
            rng.integers(0, 255, (args.num_envs, 64, 64, 3), dtype=np.uint8)
        ).astype(jnp.float32)
        / 255.0
    }

    key = jax.random.PRNGKey(1)

    def one_cycle(state, player_state, key):
        player = make_player(state)
        for _ in range(args.train_every):
            key, sk = jax.random.split(key)
            player_state, _ = player_step(player, player_state, obs, sk)
        key, tk = jax.random.split(key)
        state, metrics = train_step(state, dict(sample_batch), tk, jnp.float32(0.02))
        jax.block_until_ready(metrics)
        return state, player_state, key

    state, player_state, key = one_cycle(state, player_state, key)  # compile
    n_cycles = 3 if tiny else 10
    t0 = time.perf_counter()
    for _ in range(n_cycles):
        state, player_state, key = one_cycle(state, player_state, key)
    dt = time.perf_counter() - t0
    return n_cycles * args.train_every * args.num_envs / dt


def _dv3_e2e_sps(args, state, opts, actions_dim, is_continuous, tiny):
    """Honest end-to-end loop: the real AsyncReplayBuffer in the cycle —
    per-step rb.add, rb.sample, dtype cast, host->device transfer, update
    (only gym env stepping excluded; mirrors dreamer_v3.py:628-660)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.data import AsyncReplayBuffer, stage_batch

    T, B = args.per_rank_sequence_length, args.per_rank_batch_size
    n_envs = args.num_envs
    world_opt, actor_opt, critic_opt = opts
    train_step = make_train_step(
        args, world_opt, actor_opt, critic_opt, ["rgb"], [], actions_dim, is_continuous
    )
    make_player, player_step = _dv3_player_fns(args, actions_dim, is_continuous)
    player_state = make_player(state).init_states(n_envs)

    rb = AsyncReplayBuffer(
        max(4 * T, 64),
        n_envs,
        storage="device",
        sequential=True,
        obs_keys=("rgb",),
        seed=0,
    )
    rng = np.random.default_rng(0)

    def fake_env_obs():
        return rng.integers(0, 255, (n_envs, 64, 64, 3), dtype=np.uint8)

    def add_step(obs_u8):
        rb.add(
            {
                "rgb": obs_u8[None],
                "actions": np.eye(6, dtype=np.float32)[
                    rng.integers(0, 6, (n_envs,))
                ][None],
                "rewards": rng.normal(size=(1, n_envs, 1)).astype(np.float32),
                "dones": np.zeros((1, n_envs, 1), np.float32),
                "is_first": np.zeros((1, n_envs, 1), np.float32),
            }
        )

    for _ in range(2 * T + 8):  # prefill to make T-sequences sampleable
        add_step(fake_env_obs())

    key = jax.random.PRNGKey(1)

    def one_cycle(state, player_state, key):
        player = make_player(state)
        for _ in range(args.train_every):
            obs_u8 = fake_env_obs()
            dev_obs = {"rgb": jnp.asarray(obs_u8).astype(jnp.float32) / 255.0}
            key, sk = jax.random.split(key)
            player_state, _ = player_step(player, player_state, dev_obs, sk)
            add_step(obs_u8)
        local_data = rb.sample(B, sequence_length=T, n_samples=1)
        staged = stage_batch(local_data)
        sample = {k: v[0] for k, v in staged.items()}
        key, tk = jax.random.split(key)
        state, metrics = train_step(state, sample, tk, jnp.float32(0.02))
        jax.block_until_ready(metrics)
        return state, player_state, key

    state, player_state, key = one_cycle(state, player_state, key)  # compile
    n_cycles = 3 if tiny else 10
    t0 = time.perf_counter()
    for _ in range(n_cycles):
        state, player_state, key = one_cycle(state, player_state, key)
    dt = time.perf_counter() - t0
    return n_cycles * args.train_every * n_envs / dt


def bench_dreamer_v3(tiny: bool = False) -> None:
    import traceback

    from sheeprl_tpu.ops import pallas_kernels as pk

    args, state, opts, actions_dim, is_continuous = _dv3_setup(tiny)

    # each measurement individually guarded: an intermittent backend failure
    # (e.g. a flaky TPU tunnel) zeroes that path, not the whole artifact.
    # The train step donates its state buffers, so every measurement gets a
    # fresh copy of the initial state (arg position 1).
    def _measure(fn, args_, state_, *fn_args):
        import jax
        import jax.numpy as jnp

        try:
            state_ = jax.tree_util.tree_map(jnp.copy, state_)
            return fn(args_, state_, *fn_args)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            return 0.0

    pk.set_pallas(False)
    off_sps = _measure(
        _dv3_duty_cycle_sps, args, state, opts, actions_dim, is_continuous, tiny
    )
    pk.set_pallas(True, interpret=not pk._backend_is_tpu())
    on_sps = _measure(
        _dv3_duty_cycle_sps, args, state, opts, actions_dim, is_continuous, tiny
    )

    # keep only winning kernels (VERDICT r1 #4): headline runs the better
    # config; a failed measurement (0.0 sentinel) can never win
    kernels_win = on_sps > 0.0 and on_sps >= off_sps
    pk.set_pallas(
        True if kernels_win and pk._backend_is_tpu() else False,
        interpret=False,
    )
    # bf16 compute (--precision bfloat16) on top of the winning kernel config
    args.precision = "bfloat16"
    bf16_sps = _measure(
        _dv3_duty_cycle_sps, args, state, opts, actions_dim, is_continuous, tiny
    )
    bf16_win = bf16_sps > max(on_sps, off_sps)
    args.precision = "bfloat16" if bf16_win else "float32"
    duty_sps = max(on_sps, off_sps, bf16_sps)
    e2e_sps = _measure(
        _dv3_e2e_sps, args, state, opts, actions_dim, is_continuous, tiny
    )

    print(
        json.dumps(
            {
                "metric": "dreamer_v3_pixel_env_steps_per_sec",
                "value": round(duty_sps, 1),
                "unit": "env-steps/sec/chip",
                "vs_baseline": round(duty_sps / DV3_REFERENCE_SPS, 3),
                "pallas_on_sps": round(on_sps, 1),
                "pallas_off_sps": round(off_sps, 1),
                "pallas_kept": bool(kernels_win),
                "bf16_sps": round(bf16_sps, 1),
                "bf16_kept": bool(bf16_win),
                "e2e_sps": round(e2e_sps, 1),
                "baseline_note": BASELINE_NOTE,
            }
        )
    )


# =============================================================================
# PPO benches
# =============================================================================


def _ppo_run(decoupled: bool, num_devices: int = -1) -> float:
    """One PPO/CartPole throughput run through the real rollout+update loop;
    returns env-steps/sec."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.ppo.agent import PPOAgent, one_hot_to_env_actions
    from sheeprl_tpu.algos.ppo.args import PPOArgs
    from sheeprl_tpu.algos.ppo.ppo import (
        TrainState,
        compute_gae_returns,
        make_optimizer,
        make_train_step,
        policy_step,
        validate_obs_keys,
        actions_dim_of,
    )
    from sheeprl_tpu.envs import make_vector_env
    from sheeprl_tpu.parallel import make_mesh, replicate, shard_batch
    from sheeprl_tpu.parallel.decoupled import make_decoupled_meshes
    from sheeprl_tpu.utils.env import make_dict_env

    args = PPOArgs(
        env_id="CartPole-v1", num_envs=8, rollout_steps=128,
        per_rank_batch_size=64, update_epochs=10, sync_env=True,
    )
    envs = make_vector_env(
        [make_dict_env(args.env_id, i, rank=0, args=args) for i in range(args.num_envs)],
        sync=True,
    )
    cnn_keys, mlp_keys = validate_obs_keys(envs.single_observation_space, args)
    obs_keys = [*cnn_keys, *mlp_keys]
    actions_dim, is_continuous = actions_dim_of(envs.single_action_space)
    agent = PPOAgent.init(
        jax.random.PRNGKey(1), actions_dim, envs.single_observation_space.spaces,
        cnn_keys, mlp_keys, is_continuous=is_continuous,
    )
    optimizer = make_optimizer(args)
    state = TrainState(agent=agent, opt_state=optimizer.init(agent))
    num_minibatches = args.rollout_steps * args.num_envs // args.per_rank_batch_size
    train_step = make_train_step(args, optimizer, num_minibatches)

    meshes = None
    if decoupled:
        meshes = make_decoupled_meshes(num_devices)
        state = meshes.replicated_on_trainers(state)
        player_agent = meshes.to_player(state.agent)
    else:
        mesh = make_mesh(num_devices)
        state = replicate(state, mesh)
        player_agent = state.agent

    obs, _ = envs.reset(seed=0)
    next_done = np.zeros(args.num_envs, np.float32)
    key = jax.random.PRNGKey(0)
    pending_agent = None

    def one_update(state, player_agent, pending_agent, obs, next_done, key):
        if pending_agent is not None:
            leaves = jax.tree_util.tree_leaves(pending_agent)
            if all(l.is_ready() for l in leaves if hasattr(l, "is_ready")):
                player_agent, pending_agent = pending_agent, None
        rows = {k: [] for k in (*obs_keys, "actions", "logprobs", "values", "rewards", "dones")}
        for _ in range(args.rollout_steps):
            key, sk = jax.random.split(key)
            dobs = {k: jnp.asarray(obs[k]) for k in obs_keys}
            if decoupled:
                dobs = {k: jax.device_put(v, meshes.player_device) for k, v in dobs.items()}
            actions, logprob, value = policy_step(player_agent, dobs, sk)
            env_actions = one_hot_to_env_actions(actions, actions_dim, is_continuous)
            nobs, rewards, terms, truncs, _ = envs.step(list(env_actions))
            for k in obs_keys:
                rows[k].append(np.asarray(obs[k]))
            rows["actions"].append(np.asarray(actions))
            rows["logprobs"].append(np.asarray(logprob))
            rows["values"].append(np.asarray(value))
            rows["rewards"].append(rewards[:, None])
            rows["dones"].append(next_done[:, None])
            next_done = (terms | truncs).astype(np.float32)
            obs = nobs
        data = {k: jnp.asarray(np.stack(v)) for k, v in rows.items()}
        dnext = {k: jnp.asarray(obs[k]) for k in obs_keys}
        returns, advantages = compute_gae_returns(
            player_agent, data, dnext, jnp.asarray(next_done)[:, None],
            args.gamma, args.gae_lambda,
        )
        data["returns"], data["advantages"] = returns, advantages
        flat = {
            k: v.reshape((-1,) + v.shape[2:])
            for k, v in data.items() if k not in ("rewards", "dones")
        }
        key, tk = jax.random.split(key)
        if decoupled:
            flat = meshes.to_trainers(flat)
            state, metrics = train_step(
                state, flat, tk, jnp.float32(args.lr), jnp.float32(args.clip_coef),
                jnp.float32(args.ent_coef),
            )
            # overlapped weight return: swap at a later update when ready
            pending_agent = meshes.to_player(state.agent)
        else:
            state, metrics = train_step(
                state, flat, tk, jnp.float32(args.lr), jnp.float32(args.clip_coef),
                jnp.float32(args.ent_coef),
            )
            jax.block_until_ready(metrics)
            player_agent = state.agent
        return state, player_agent, pending_agent, obs, next_done, key

    carry = (state, player_agent, pending_agent, obs, next_done, key)
    carry = one_update(*carry)  # compile
    n_updates = 8
    t0 = time.perf_counter()
    for _ in range(n_updates):
        carry = one_update(*carry)
    import jax as _jax

    _jax.block_until_ready(carry[0])
    dt = time.perf_counter() - t0
    envs.close()
    return n_updates * args.rollout_steps * args.num_envs / dt


def bench_ppo() -> None:
    sps = _ppo_run(decoupled=False)
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_env_steps_per_sec",
                "value": round(sps, 1),
                "unit": "env-steps/sec/chip",
                "vs_baseline": round(sps / PPO_CPU_REFERENCE_SPS, 3),
                "baseline_note": BASELINE_NOTE,
            }
        )
    )


def bench_ppo_decoupled() -> None:
    """Coupled vs overlapped-decoupled PPO on the same >=2-device mesh —
    the VERDICT r1 #6 receipt (decoupled must not be slower)."""
    coupled_sps = _ppo_run(decoupled=False)
    decoupled_sps = _ppo_run(decoupled=True)
    print(
        json.dumps(
            {
                "metric": "ppo_decoupled_vs_coupled_env_steps_per_sec",
                "value": round(decoupled_sps, 1),
                "unit": "env-steps/sec",
                "vs_baseline": round(decoupled_sps / max(coupled_sps, 1e-9), 3),
                "coupled_sps": round(coupled_sps, 1),
                "decoupled_sps": round(decoupled_sps, 1),
                "baseline_note": "vs_baseline here is decoupled/coupled on the same mesh",
            }
        )
    )


def _wait_for_backend(retries: int = 4, delay_s: float = 60.0) -> None:
    """The axon TPU tunnel is intermittently unavailable; a failed backend
    init is retried with backoff so a transient outage at bench time does
    not cost the round its artifact. Exhausted retries re-raise: a partial
    CPU number would be misleading, a missing one is at least honest.

    Two subtleties of jax's backend cache: (a) a failed accelerator init can
    leave a CPU-only `_backends` cache behind, making later `jax.devices()`
    calls 'succeed' on CPU — so when the configured platform list prefers an
    accelerator, a CPU-only device set counts as failure; (b) the cache must
    be cleared between attempts or the retry would just re-read it."""
    import jax

    preferred = (jax.config.jax_platforms or "").split(",")[0]
    want_accelerator = preferred not in ("", "cpu")
    for attempt in range(retries):
        try:
            devices = jax.devices()
            if want_accelerator and all(d.platform == "cpu" for d in devices):
                raise RuntimeError(
                    f"configured platform {preferred!r} unavailable; only CPU "
                    "devices came up"
                )
            return
        except Exception as e:  # backend init surfaces RuntimeError or worse
            if attempt == retries - 1:
                raise
            print(
                f"backend unavailable (attempt {attempt + 1}/{retries}): {e}; "
                f"retrying in {delay_s:.0f}s",
                file=sys.stderr,
            )
            try:
                from jax.extend.backend import clear_backends

                clear_backends()
            except Exception:
                pass
            time.sleep(delay_s)


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--algo", choices=["dreamer_v3", "ppo", "ppo_decoupled"], default="dreamer_v3"
    )
    parser.add_argument("--tiny", action="store_true")
    opts = parser.parse_args()
    _wait_for_backend()
    if opts.algo == "ppo":
        bench_ppo()
    elif opts.algo == "ppo_decoupled":
        bench_ppo_decoupled()
    else:
        bench_dreamer_v3(tiny=opts.tiny)


if __name__ == "__main__":
    main()
