"""Benchmark entry: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Current benchmark: PPO coupled on CartPole-v1 (BASELINE.md config 1) —
end-to-end env-steps/sec including rollout, GAE, and the single-jit update
phase, measured after one warm-up update (compile excluded).

Baseline denominator: the reference (SheepRL, torch) is not runnable in this
image (no lightning/tensordict), and it publishes no numbers (BASELINE.md),
so vs_baseline is measured against this framework's first-round CPU
measurement (610 env-steps/sec on the round-1 host) until a reference run
is available.
"""

from __future__ import annotations

import json
import time

CPU_REFERENCE_SPS = 610.0  # round-1 CPU measurement, see docstring


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.ppo.agent import one_hot_to_env_actions
    from sheeprl_tpu.algos.ppo.args import PPOArgs
    from sheeprl_tpu.algos.ppo.ppo import (
        TrainState,
        compute_gae_returns,
        make_optimizer,
        make_train_step,
        policy_step,
        validate_obs_keys,
        actions_dim_of,
    )
    from sheeprl_tpu.algos.ppo.agent import PPOAgent
    from sheeprl_tpu.envs import make_vector_env
    from sheeprl_tpu.utils.env import make_dict_env

    args = PPOArgs(
        env_id="CartPole-v1", num_envs=8, rollout_steps=128,
        per_rank_batch_size=64, update_epochs=10, sync_env=True,
    )
    envs = make_vector_env(
        [make_dict_env(args.env_id, i, rank=0, args=args) for i in range(args.num_envs)],
        sync=True,
    )
    cnn_keys, mlp_keys = validate_obs_keys(envs.single_observation_space, args)
    obs_keys = [*cnn_keys, *mlp_keys]
    actions_dim, is_continuous = actions_dim_of(envs.single_action_space)
    key = jax.random.PRNGKey(0)
    agent = PPOAgent.init(
        jax.random.PRNGKey(1), actions_dim, envs.single_observation_space.spaces,
        cnn_keys, mlp_keys, is_continuous=is_continuous,
    )
    optimizer = make_optimizer(args)
    state = TrainState(agent=agent, opt_state=optimizer.init(agent))
    num_minibatches = args.rollout_steps * args.num_envs // args.per_rank_batch_size
    train_step = make_train_step(args, optimizer, num_minibatches)

    obs, _ = envs.reset(seed=0)
    next_done = np.zeros(args.num_envs, np.float32)

    def one_update(state, obs, next_done, key):
        rows = {k: [] for k in (*obs_keys, "actions", "logprobs", "values", "rewards", "dones")}
        for _ in range(args.rollout_steps):
            key, sk = jax.random.split(key)
            dobs = {k: jnp.asarray(obs[k]) for k in obs_keys}
            actions, logprob, value = policy_step(state.agent, dobs, sk)
            env_actions = one_hot_to_env_actions(actions, actions_dim, is_continuous)
            nobs, rewards, terms, truncs, _ = envs.step(list(env_actions))
            for k in obs_keys:
                rows[k].append(np.asarray(obs[k]))
            rows["actions"].append(np.asarray(actions))
            rows["logprobs"].append(np.asarray(logprob))
            rows["values"].append(np.asarray(value))
            rows["rewards"].append(rewards[:, None])
            rows["dones"].append(next_done[:, None])
            next_done = (terms | truncs).astype(np.float32)
            obs = nobs
        data = {k: jnp.asarray(np.stack(v)) for k, v in rows.items()}
        dnext = {k: jnp.asarray(obs[k]) for k in obs_keys}
        returns, advantages = compute_gae_returns(
            state.agent, data, dnext, jnp.asarray(next_done)[:, None],
            args.gamma, args.gae_lambda,
        )
        data["returns"], data["advantages"] = returns, advantages
        flat = {
            k: v.reshape((-1,) + v.shape[2:])
            for k, v in data.items() if k not in ("rewards", "dones")
        }
        key, tk = jax.random.split(key)
        state, metrics = train_step(
            state, flat, tk, jnp.float32(args.lr), jnp.float32(args.clip_coef),
            jnp.float32(args.ent_coef),
        )
        jax.block_until_ready(metrics)
        return state, obs, next_done, key

    # warm-up (compile)
    state, obs, next_done, key = one_update(state, obs, next_done, key)
    n_updates = 8
    t0 = time.perf_counter()
    for _ in range(n_updates):
        state, obs, next_done, key = one_update(state, obs, next_done, key)
    dt = time.perf_counter() - t0
    envs.close()
    sps = n_updates * args.rollout_steps * args.num_envs / dt
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_env_steps_per_sec",
                "value": round(sps, 1),
                "unit": "env-steps/sec/chip",
                "vs_baseline": round(sps / CPU_REFERENCE_SPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
